"""exhook: forward broker hookpoints to external gRPC HookProvider servers.

Parity: apps/emqx_exhook — emqx_exhook_server.erl (per-server gRPC channel,
OnProviderLoaded handshake announcing which hooks the provider wants,
request timeout + failed_action policy deny|ignore) and emqx_exhook_handler
(the 20 hookpoint bridges). ValuedResponse semantics: CONTINUE threads the
returned value to the next hook, IGNORE keeps the current one,
STOP_AND_RETURN halts the chain with the returned value — exactly the
run_fold contract of the hooks registry.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Optional

import grpc

from emqx_tpu.apps.protos import exhook_pb2 as pb
from emqx_tpu.broker.message import Message, base62_encode
from emqx_tpu.version import __version__

log = logging.getLogger("emqx_tpu.exhook")

_PKG = "/emqx.exhook.v1.HookProvider"

# hookpoint -> (rpc method, request class, valued?)
HOOK_METHODS = {
    "client.connect": ("OnClientConnect", pb.ClientConnectRequest, False),
    "client.connack": ("OnClientConnack", pb.ClientConnackRequest, False),
    "client.connected": ("OnClientConnected",
                         pb.ClientConnectedRequest, False),
    "client.disconnected": ("OnClientDisconnected",
                            pb.ClientDisconnectedRequest, False),
    "client.authenticate": ("OnClientAuthenticate",
                            pb.ClientAuthenticateRequest, True),
    "client.authorize": ("OnClientAuthorize",
                         pb.ClientAuthorizeRequest, True),
    "client.subscribe": ("OnClientSubscribe",
                         pb.ClientSubscribeRequest, False),
    "client.unsubscribe": ("OnClientUnsubscribe",
                           pb.ClientUnsubscribeRequest, False),
    "session.created": ("OnSessionCreated", pb.SessionCreatedRequest,
                        False),
    "session.subscribed": ("OnSessionSubscribed",
                           pb.SessionSubscribedRequest, False),
    "session.unsubscribed": ("OnSessionUnsubscribed",
                             pb.SessionUnsubscribedRequest, False),
    "session.resumed": ("OnSessionResumed", pb.SessionResumedRequest,
                        False),
    "session.discarded": ("OnSessionDiscarded",
                          pb.SessionDiscardedRequest, False),
    "session.takenover": ("OnSessionTakeovered",
                          pb.SessionTakeoveredRequest, False),
    "session.terminated": ("OnSessionTerminated",
                           pb.SessionTerminatedRequest, False),
    "message.publish": ("OnMessagePublish", pb.MessagePublishRequest,
                        True),
    "message.delivered": ("OnMessageDelivered",
                          pb.MessageDeliveredRequest, False),
    "message.dropped": ("OnMessageDropped", pb.MessageDroppedRequest,
                        False),
    "message.acked": ("OnMessageAcked", pb.MessageAckedRequest, False),
}


def _clientinfo(ci: Any, node: str) -> pb.ClientInfo:
    if isinstance(ci, str):
        ci = {"clientid": ci}
    ci = ci or {}
    peer = ci.get("peername")
    return pb.ClientInfo(
        node=node, clientid=ci.get("clientid") or "",
        username=ci.get("username") or "",
        peerhost=str(peer[0]) if isinstance(peer, tuple) else "",
        protocol=str(ci.get("protocol") or ci.get("proto_name") or "mqtt"),
        mountpoint=ci.get("mountpoint") or "",
        is_superuser=bool(ci.get("is_superuser")))


def _message(m: Message, node: str) -> pb.Message:
    return pb.Message(node=node, id=base62_encode(m.id), qos=m.qos,
                      topic=m.topic, payload=m.payload, timestamp=m.ts,
                      **{"from": m.from_})


class ExhookServer:
    """One configured gRPC provider (emqx_exhook_server.erl)."""

    def __init__(self, node, name: str, url: str, *,
                 timeout: float = 5.0, failed_action: str = "deny",
                 pool_size: int = 8):
        self.node = node
        self.name = name
        self.url = url.replace("http://", "").replace("grpc://", "")
        self.timeout = timeout
        self.failed_action = failed_action   # deny | ignore
        self.channel = grpc.insecure_channel(self.url)
        self.hooks_wanted: dict[str, list[str]] = {}
        self._registered: list[str] = []

    def _call_blocking(self, method: str, req, resp_cls):
        call = self.channel.unary_unary(
            f"{_PKG}/{method}",
            request_serializer=type(req).SerializeToString,
            response_deserializer=resp_cls.FromString)
        return call(req, timeout=self.timeout)

    async def _call(self, method: str, req, resp_cls):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, self._call_blocking, method, req, resp_cls)

    # ---- lifecycle ----
    async def load(self) -> None:
        broker = pb.BrokerInfo(
            version=__version__, sysdescr="EMQX-TPU broker",
            uptime=int(time.time()),
            datetime=time.strftime("%Y-%m-%d %H:%M:%S"))
        resp = await self._call("OnProviderLoaded",
                                pb.ProviderLoadedRequest(broker=broker),
                                pb.LoadedResponse)
        self.hooks_wanted = {h.name: list(h.topics) for h in resp.hooks}
        for hookpoint in self.hooks_wanted:
            if hookpoint not in HOOK_METHODS:
                continue
            handler = self._make_handler(hookpoint)
            self.node.hooks.add(hookpoint, handler,
                                tag=f"exhook:{self.name}", priority=99)
            self._registered.append(hookpoint)

    async def unload(self) -> None:
        for hookpoint in self._registered:
            self.node.hooks.delete(hookpoint, f"exhook:{self.name}")
        self._registered = []
        try:
            await self._call("OnProviderUnloaded",
                             pb.ProviderUnloadedRequest(),
                             pb.EmptySuccess)
        except grpc.RpcError:
            pass
        self.channel.close()

    # ---- hook bridging ----
    def _make_handler(self, hookpoint: str):
        method, req_cls, valued = HOOK_METHODS[hookpoint]
        server = self

        if valued:
            # valued hooks run under awaited folds: authenticate/authorize
            # via the channel's run_fold_async, message.publish via
            # Broker.publish_async (sync Broker.publish skips them)
            async def ahandler(*args):
                req = server._build_request(hookpoint, req_cls, args)
                if req is None:
                    return None
                try:
                    resp = await server._call(method, req,
                                              pb.ValuedResponse)
                except grpc.RpcError as e:
                    log.warning("exhook %s %s failed: %s", server.name,
                                method, e)
                    if server.failed_action == "deny":
                        return server._deny_value(hookpoint, args)
                    return None
                return server._apply_valued(hookpoint, resp, args)
            return ahandler

        # non-valued hooks never block the hot path: fire-and-forget
        async def notify(args):
            req = server._build_request(hookpoint, req_cls, args)
            if req is None:
                return
            try:
                await server._call(method, req, pb.EmptySuccess)
            except grpc.RpcError as e:
                log.debug("exhook %s %s failed: %s", server.name,
                          method, e)

        def fire(*args):
            try:
                asyncio.get_running_loop()
                from emqx_tpu.broker.supervise import spawn
                spawn(notify(args), "exhook-notify")
            except RuntimeError:
                # no loop (sync test context): deliver inline, blocking
                try:
                    req = server._build_request(hookpoint, req_cls, args)
                    if req is not None:
                        server._call_blocking(method, req, pb.EmptySuccess)
                except grpc.RpcError:
                    pass
            return None
        return fire

    def _build_request(self, hookpoint: str, req_cls, args: tuple):
        n = self.node.name
        topics = self.hooks_wanted.get(hookpoint) or []
        try:
            if hookpoint == "client.authenticate":
                (ci, acc) = args
                return pb.ClientAuthenticateRequest(
                    clientinfo=_clientinfo(ci, n),
                    result=bool((acc or {}).get("ok", True)))
            if hookpoint == "client.authorize":
                (ci, action, topic, acc) = args
                return pb.ClientAuthorizeRequest(
                    clientinfo=_clientinfo(ci, n),
                    type=0 if action == "publish" else 1, topic=topic,
                    result=acc != "deny")
            if hookpoint == "message.publish":
                (msg,) = args
                if topics and not any(
                        _topic_match(msg.topic, t) for t in topics):
                    return None
                return pb.MessagePublishRequest(message=_message(msg, n))
            if hookpoint in ("message.delivered", "message.acked"):
                (ci, msg) = args
                return req_cls(clientinfo=_clientinfo(ci, n),
                               message=_message(msg, n))
            if hookpoint == "message.dropped":
                (msg, reason) = args
                return pb.MessageDroppedRequest(
                    message=_message(msg, n), reason=str(reason))
            if hookpoint == "client.connect":
                (conninfo,) = args[:1]
                return pb.ClientConnectRequest(
                    conninfo=_conninfo(conninfo, n))
            if hookpoint == "client.connack":
                (ci, rc) = args[:2]
                return pb.ClientConnackRequest(
                    conninfo=_conninfo(ci, n), result_code=str(rc))
            if hookpoint == "client.disconnected":
                (ci, reason) = args[:2]
                return pb.ClientDisconnectedRequest(
                    clientinfo=_clientinfo(ci, n), reason=str(reason))
            if hookpoint == "session.subscribed":
                (ci, topic, subopts) = args[:3]
                return pb.SessionSubscribedRequest(
                    clientinfo=_clientinfo(ci, n), topic=topic,
                    subopts=pb.SubOpts(qos=(subopts or {}).get("qos", 0)))
            if hookpoint == "session.unsubscribed":
                (ci, topic) = args[:2]
                return pb.SessionUnsubscribedRequest(
                    clientinfo=_clientinfo(ci, n), topic=topic)
            if hookpoint == "session.terminated":
                (ci, reason) = args[:2]
                return pb.SessionTerminatedRequest(
                    clientinfo=_clientinfo(ci, n), reason=str(reason))
            if hookpoint in ("client.subscribe", "client.unsubscribe"):
                (ci, _props, acc) = args
                filters = [pb.TopicFilter(name=f if isinstance(f, str)
                                          else f[0])
                           for f in (acc or [])]
                return req_cls(clientinfo=_clientinfo(ci, n),
                               topic_filters=filters)
            # remaining session.* events carry just the clientinfo
            return req_cls(clientinfo=_clientinfo(args[0], n))
        except Exception:  # noqa: BLE001 — malformed args never break hooks
            log.exception("exhook request build failed for %s", hookpoint)
            return None

    def _apply_valued(self, hookpoint: str, resp, args: tuple):
        rtype = resp.type
        which = resp.WhichOneof("value")
        if rtype == pb.ValuedResponse.IGNORE or which is None:
            return None
        stop = rtype == pb.ValuedResponse.STOP_AND_RETURN
        if hookpoint == "client.authenticate":
            acc = dict(args[-1] or {})
            acc["ok"] = bool(resp.bool_result)
            return ("stop", acc) if stop else ("ok", acc)
        if hookpoint == "client.authorize":
            val = "allow" if resp.bool_result else "deny"
            return ("stop", val) if stop else ("ok", val)
        if hookpoint == "message.publish" and which == "message":
            msg: Message = args[0]
            new = msg.copy()
            new.topic = resp.message.topic or new.topic
            new.payload = bytes(resp.message.payload)
            new.qos = resp.message.qos
            return ("stop", new) if stop else ("ok", new)
        return None

    def _deny_value(self, hookpoint: str, args: tuple):
        if hookpoint == "client.authenticate":
            return ("stop", dict(args[-1] or {}, ok=False))
        if hookpoint == "client.authorize":
            return ("stop", "deny")
        if hookpoint == "message.publish":
            msg: Message = args[0]
            return ("stop", msg.copy().set_header("allow_publish", False))
        return None


def _conninfo(ci: dict, node: str) -> pb.ConnInfo:
    ci = ci or {}
    peer = ci.get("peername")
    return pb.ConnInfo(
        node=node, clientid=ci.get("clientid") or "",
        username=ci.get("username") or "",
        peerhost=str(peer[0]) if isinstance(peer, tuple) else "",
        proto_name=str(ci.get("proto_name") or "MQTT"),
        proto_ver=str(ci.get("proto_ver") or ""),
        keepalive=int(ci.get("keepalive") or 0))


def _topic_match(topic: str, pattern: str) -> bool:
    from emqx_tpu.utils import topic as T
    return T.match(topic, pattern)


class Exhook:
    """The exhook app: manages configured servers (emqx_exhook.erl)."""

    def __init__(self, node, conf: Optional[dict] = None):
        self.node = node
        conf = conf or (node.config.get("exhook") or {})
        self.server_confs = conf.get("servers", [])
        self.servers: dict[str, ExhookServer] = {}

    async def load(self) -> "Exhook":
        for sc in self.server_confs:
            await self.add_server(sc["name"], sc["url"],
                                  timeout=sc.get("timeout", 5.0),
                                  failed_action=sc.get("failed_action",
                                                       "deny"))
        self.node.exhook = self
        return self

    async def add_server(self, name: str, url: str, **kw) -> ExhookServer:
        if name in self.servers:
            raise ValueError(f"exhook server {name} exists")
        server = ExhookServer(self.node, name, url, **kw)
        await server.load()
        self.servers[name] = server
        return server

    async def remove_server(self, name: str) -> bool:
        server = self.servers.pop(name, None)
        if server is None:
            return False
        await server.unload()
        return True

    async def unload(self) -> None:
        for name in list(self.servers):
            await self.remove_server(name)
        if getattr(self.node, "exhook", None) is self:
            self.node.exhook = None

    def list_servers(self) -> list[dict]:
        return [{"name": s.name, "url": s.url,
                 "hooks": sorted(s.hooks_wanted)}
                for s in self.servers.values()]
