"""Config-driven feature-app boot: the application-startup analog.

Parity: the reference's release boots every configured app at node start
(emqx_machine_boot.erl: emqx_retainer, emqx_delayed, emqx_modules,
emqx_authn/authz chains from their config blocks, emqx_rule_engine,
emqx_exhook). Here the same blocks in `etc/emqx.conf` drive
`Node.start_apps()`; each app remains independently usable as a library.

Config surface (all optional; nothing configured = nothing booted):

  retainer { enable = true, storage { type = ram|disc, path = ... } }
  delayed  { enable = true }
  rewrite = [ { action = publish, source = "x/#", re = "...", dest = "y/#" } ]
  rule_engine { rules = [ { id = r1, sql = "SELECT ...", actions = [...] } ] }
  exhook   { servers = [ { name = s1, url = "http://..." } ] }
  event_message { client_connected = true, ... }
  topic_metrics = [ "t/#" ]
  flapping_detect { enable = true, max_count = 15, ... }
  authn {
    enable = true
    chain = [
      { mechanism = password_based, backend = built_in_database,
        user_id_type = username }
      { mechanism = jwt, secret = "..." }
      { mechanism = scram, algorithm = sha256 }
      { mechanism = password_based, backend = http, url = "..." }
      { mechanism = password_based, backend = mysql,
        server = "127.0.0.1:3306", database = mqtt, query = "SELECT ..." }
    ]
  }
  authz {
    no_match = allow | deny
    sources = [
      { type = file, rules = [ { permit=allow, who=all, action=all } ] }
      { type = client_acl }
      { type = http, url = "..." }
      { type = mysql, server = ..., query = "SELECT ..." }
    ]
  }
"""

from __future__ import annotations

from typing import Any


async def _db_resource(node, rid: str, rtype: str, conf: dict):
    """DB-backed authn/authz arms share the typed resource pool."""
    import emqx_tpu.resources.db  # noqa: F401 — registers the DB types
    from emqx_tpu.resources.resource import ResourceManager
    mgr = getattr(node, "resources", None)
    if mgr is None:
        mgr = ResourceManager(node)   # registers itself as node.resources
    return await mgr.create(rid, rtype, conf)


async def _build_authenticator(node, i: int, a: dict) -> Any:
    mech = a.get("mechanism", "password_based")
    if mech == "jwt":
        from emqx_tpu.apps.authn import JWTAuthenticator
        return JWTAuthenticator(
            secret=a["secret"], algorithm=a.get("algorithm", "HS256"),
            verify_claims=a.get("verify_claims"),
            acl_claim_name=a.get("acl_claim_name", "acl"))
    if mech == "scram":
        from emqx_tpu.apps.authn_db import ScramAuthenticator
        return ScramAuthenticator(
            algorithm=a.get("algorithm", "sha256"),
            iteration_count=int(a.get("iteration_count", 4096)))
    if mech != "password_based":
        raise ValueError(f"authn authenticator #{i}: "
                         f"unknown mechanism {mech!r}")
    backend = a.get("backend", "built_in_database")
    if backend == "built_in_database":
        from emqx_tpu.apps.authn import BuiltinDB
        return BuiltinDB(
            user_id_type=a.get("user_id_type", "username"),
            algorithm=a.get("algorithm", "sha256"),
            salt_position=a.get("salt_position", "prefix"))
    if backend == "http":
        from emqx_tpu.apps.authn import HTTPAuthenticator
        return HTTPAuthenticator(
            url=a["url"], method=a.get("method", "post"),
            body=a.get("body"), headers=a.get("headers"),
            timeout=float(a.get("timeout", 5)))
    if backend == "ldap":
        from emqx_tpu.apps.authn_db import LdapAuthenticator
        host, _, port = str(a.get("server", "127.0.0.1:389")).partition(":")
        return LdapAuthenticator(
            host=host, port=int(port or 389),
            base_dn=a.get("base_dn", ""),
            filter_tmpl=a.get("filter", "(uid=${mqtt-username})"),
            bind_dn=a.get("bind_dn"),
            bind_password=a.get("bind_password", ""))
    if backend in ("mysql", "postgresql", "mongodb", "redis"):
        rtype = {"postgresql": "pgsql"}.get(backend, backend)
        res = await _db_resource(node, f"authn_{i}_{backend}", rtype,
                                 dict(a))
        if backend == "mongodb":
            from emqx_tpu.apps.authn_db import MongoAuthenticator
            return MongoAuthenticator(
                res, collection=a.get("collection", "mqtt_user"),
                selector=a.get("selector"),
                algorithm=a.get("algorithm", "sha256"),
                salt_position=a.get("salt_position", "prefix"))
        from emqx_tpu.apps.authn_db import (MysqlAuthenticator,
                                            PgsqlAuthenticator)
        cls = (MysqlAuthenticator if backend == "mysql"
               else PgsqlAuthenticator)
        return cls(res, query=a["query"],
                   algorithm=a.get("algorithm", "sha256"),
                   salt_position=a.get("salt_position", "prefix"))
    raise ValueError(f"authn authenticator #{i}: unknown backend "
                     f"{backend!r}")


async def _build_authz_source(node, i: int, s: dict) -> Any:
    stype = s.get("type", "file")
    if stype == "file":
        from emqx_tpu.apps.authz import FileSource
        rules = s.get("rules")
        if rules is None and s.get("path"):
            import os

            from emqx_tpu.utils.hocon import loads
            with open(s["path"]) as f:
                rules = (loads(f.read(),
                               basedir=os.path.dirname(s["path"]) or ".")
                         or {}).get("rules") or []
        return FileSource(rules or [])
    if stype == "client_acl":
        from emqx_tpu.apps.authz import ClientAclSource
        return ClientAclSource()
    if stype == "http":
        from emqx_tpu.apps.authz import HTTPSource
        return HTTPSource(url=s["url"], method=s.get("method", "post"),
                          body=s.get("body"), headers=s.get("headers"),
                          timeout=float(s.get("timeout", 5)))
    if stype in ("mysql", "postgresql", "redis", "mongodb"):
        rtype = {"postgresql": "pgsql"}.get(stype, stype)
        res = await _db_resource(node, f"authz_{i}_{stype}", rtype, dict(s))
        if stype == "redis":
            from emqx_tpu.apps.authz_db import RedisSource
            return RedisSource(res, cmd=s.get("cmd", "HGETALL mqtt_acl:%u"))
        if stype == "mongodb":
            from emqx_tpu.apps.authz_db import MongoSource
            return MongoSource(res,
                               collection=s.get("collection", "mqtt_acl"),
                               selector=s.get("selector"))
        from emqx_tpu.apps.authz_db import MysqlSource, PgsqlSource
        cls = MysqlSource if stype == "mysql" else PgsqlSource
        return cls(res, query=s["query"])
    raise ValueError(f"authz source #{i}: unknown type {stype!r}")


async def start_apps(node) -> list:
    """Boot every feature app the node's config declares; returns the
    started instances (also registered on the node)."""
    cfg = node.config
    started: list = []

    rc = cfg.get("retainer") or {}
    if rc.get("enable", False):
        from emqx_tpu.apps.retainer import Retainer
        started.append(node.register_app(Retainer(node).load()))

    dc = cfg.get("delayed") or {}
    if dc.get("enable", False):
        from emqx_tpu.apps.delayed import DelayedPublish
        started.append(node.register_app(DelayedPublish(node).load()))

    if cfg.get("rewrite"):       # schema: an ARRAY of rewrite rules
        from emqx_tpu.apps.rewrite import TopicRewrite
        started.append(node.register_app(TopicRewrite(node).load()))

    re_conf = cfg.get("rule_engine") or {}
    if re_conf.get("rules") or re_conf.get("enable"):
        from emqx_tpu.rules import RuleEngine
        eng = RuleEngine(node).load()
        for r in re_conf.get("rules") or []:
            eng.create_rule(r["sql"], list(r.get("actions") or []),
                            rule_id=r.get("id"),
                            enabled=r.get("enable", True),
                            description=r.get("description", ""))
        started.append(node.register_app(eng))

    em = cfg.get("event_message") or {}
    if any(em.values()):
        from emqx_tpu.apps.event_message import EventMessage
        started.append(node.register_app(EventMessage(node).load()))

    tm = cfg.get("topic_metrics") or []
    if tm:
        from emqx_tpu.apps.topic_metrics import TopicMetrics
        started.append(node.register_app(TopicMetrics(node, tm).load()))

    fd = cfg.get("flapping_detect") or {}
    if fd.get("enable", False):
        from emqx_tpu.broker.banned import FlappingDetect
        started.append(node.register_app(FlappingDetect(node).load()))

    ac = cfg.get("authn") or {}
    if ac.get("enable", False):
        from emqx_tpu.apps.authn import AuthnChain
        auths = [await _build_authenticator(node, i, a)
                 for i, a in enumerate(ac.get("chain") or [])]
        started.append(node.register_app(
            AuthnChain(node, auths, enable=True).load()))

    az = cfg.get("authz") or {}
    if az.get("sources") or az.get("no_match") == "deny":
        from emqx_tpu.apps.authz import Authz
        sources = [await _build_authz_source(node, i, s)
                   for i, s in enumerate(az.get("sources") or [])]
        started.append(node.register_app(
            Authz(node, sources,
                  no_match=az.get("no_match", "allow"),
                  cache_enable=az.get("cache", {}).get(
                      "enable", True)).load()))

    ex = cfg.get("exhook") or {}
    if ex.get("servers"):
        from emqx_tpu.apps.exhook import Exhook
        exh = Exhook(node)
        await exh.load()
        started.append(node.register_app(exh))

    return started
