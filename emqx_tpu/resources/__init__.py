"""Resources: generic external-service instances + connectors + bridges.

Parity: apps/emqx_resource (instance lifecycle: create/health-check/
restart, emqx_resource_instance.erl), apps/emqx_connector (http/mqtt
connectors over pools), apps/emqx_data_bridge (named bridges as resources),
apps/emqx_bridge_mqtt (bridge worker FSM with replayq buffering).
"""

from emqx_tpu.resources.bridge_mqtt import MqttBridgeWorker
from emqx_tpu.resources.resource import ResourceManager

__all__ = ["ResourceManager", "MqttBridgeWorker"]
