"""Database resources: pooled connector instances on the Resource behaviour.

Parity: apps/emqx_connector/src/emqx_connector_{redis,mysql,pgsql,mongo}.erl
— each `on_start`s an ecpool of driver connections, answers `on_query`
({cmd,...} / {sql,...} / {find,...}) and `on_health_check`. Here the pool
is connectors.pool.ConnPool over the asyncio wire clients; the query verbs
keep the reference's shapes so authn/authz/rule-actions code is
backend-agnostic.
"""

from __future__ import annotations

from typing import Any, Optional

from emqx_tpu.connectors import (ConnPool, LdapClient, MongoClient,
                                 MysqlClient, PgsqlClient, RedisClient)
from emqx_tpu.resources.resource import Resource, ResourceManager


class _PooledDbResource(Resource):
    """Shared lifecycle: start pool eagerly (status from first connect),
    health-check = client ping on a pooled connection."""

    def _make_client(self):
        raise NotImplementedError

    def __init__(self, rid: str, conf: dict):
        super().__init__(rid, conf)
        self.pool = ConnPool(self._make_client,
                             size=int(conf.get("pool_size", 4)))

    async def start(self) -> None:
        try:
            await self.pool.start()
            self.status = "connected"
        except Exception as e:  # noqa: BLE001
            self.last_error = str(e)
            self.status = "disconnected"

    async def stop(self) -> None:
        await self.pool.stop()
        self.status = "stopped"

    async def health_check(self) -> bool:
        try:
            if not self.pool._started:
                await self.pool.start()
            return bool(await self.pool.run(lambda c: c.ping(), timeout=5))
        except Exception as e:  # noqa: BLE001
            self.last_error = str(e)
            return False


class RedisResource(_PooledDbResource):
    TYPE = "redis"

    def _make_client(self) -> RedisClient:
        c = self.conf
        if c.get("redis_type") == "cluster" or c.get("cluster_nodes"):
            # emqx_connector_redis.erl cluster mode: servers seed the
            # slot-routed cluster client (eredis_cluster).  Seeds come from
            # cluster_nodes, else the reference-style `servers` list, else
            # host/port — an empty seed list is a config error, caught here
            # rather than as a cryptic connect failure later.
            from emqx_tpu.connectors.redis import ClusterRedisClient

            def parse_seeds(raw):
                # accepts a list of (host, port) pairs or "host:port"
                # strings, or the reference-style single comma-separated
                # "h1:6379,h2:6379" string
                if isinstance(raw, str):
                    raw = [s for s in raw.split(",") if s.strip()]
                out = []
                for s in raw or []:
                    if isinstance(s, str):
                        # accepted forms: 'host', 'host:port',
                        # '[v6]' / '[v6]:port', and bare 'v6' (which is
                        # ambiguous with host:port, so any string with
                        # 2+ colons outside brackets is taken as a
                        # port-less IPv6 host)
                        t = s.strip()
                        if t.startswith("["):
                            host, _, port = t.rpartition(":")
                            if host.endswith("]"):
                                host = host[1:-1]
                            else:           # '[v6]' without a port
                                host, port = t.strip("[]"), ""
                        elif t.count(":") > 1:
                            host, port = t, ""
                        else:
                            host, sep, port = t.rpartition(":")
                            if not sep:
                                host, port = t, ""
                        out.append((host, int(port or 6379)))
                    else:
                        out.append((s[0], int(s[1])))
                return out

            seeds = parse_seeds(c.get("cluster_nodes")) \
                or parse_seeds(c.get("servers"))
            if not seeds and c.get("host"):
                seeds.append((c["host"], int(c.get("port", 6379))))
            if not seeds:
                raise ValueError(
                    "redis cluster resource needs seed nodes: set "
                    "cluster_nodes, servers, or host/port")
            return ClusterRedisClient(
                startup_nodes=seeds,
                username=c.get("username"), password=c.get("password"),
                ssl=c.get("ssl"))
        if c.get("redis_type") == "sentinel" or c.get("sentinels"):
            # emqx_connector_redis.erl sentinel mode: servers are the
            # sentinels, `sentinel` names the master set
            from emqx_tpu.connectors.redis import SentinelRedisClient
            return SentinelRedisClient(
                sentinels=[tuple(s) for s in c.get("sentinels", [])],
                master_name=c.get("sentinel", "mymaster"),
                username=c.get("username"), password=c.get("password"),
                sentinel_password=c.get("sentinel_password"),
                database=int(c.get("database", 0)), ssl=c.get("ssl"))
        return RedisClient(
            host=c.get("host", "127.0.0.1"), port=c.get("port", 6379),
            username=c.get("username"), password=c.get("password"),
            database=int(c.get("database", 0)), ssl=c.get("ssl"))

    async def query(self, request: Any) -> Any:
        """request: list command ["HGETALL", key] (the {cmd, CMD} verb)."""
        return await self.pool.run(lambda c: c.cmd(list(request)),
                                   timeout=self.conf.get("timeout", 5))


class MysqlResource(_PooledDbResource):
    TYPE = "mysql"

    def _make_client(self) -> MysqlClient:
        c = self.conf
        return MysqlClient(
            host=c.get("host", "127.0.0.1"), port=c.get("port", 3306),
            username=c.get("username", "root"),
            password=c.get("password", ""),
            database=c.get("database"), ssl=c.get("ssl"))

    async def query(self, request: Any) -> Any:
        """request: ("sql", query, params) or plain SQL string
        -> (columns, rows)."""
        sql, params = _sql_request(request)
        return await self.pool.run(lambda c: c.query(sql, params),
                                   timeout=self.conf.get("timeout", 5))


class PgsqlResource(_PooledDbResource):
    TYPE = "pgsql"

    def _make_client(self) -> PgsqlClient:
        c = self.conf
        return PgsqlClient(
            host=c.get("host", "127.0.0.1"), port=c.get("port", 5432),
            username=c.get("username", "postgres"),
            password=c.get("password", ""),
            database=c.get("database", "postgres"), ssl=c.get("ssl"))

    async def query(self, request: Any) -> Any:
        sql, params = _sql_request(request)
        return await self.pool.run(lambda c: c.query(sql, params),
                                   timeout=self.conf.get("timeout", 5))


class MongoResource(_PooledDbResource):
    TYPE = "mongo"

    def _make_client(self) -> MongoClient:
        c = self.conf
        return MongoClient(
            host=c.get("host", "127.0.0.1"), port=c.get("port", 27017),
            username=c.get("username"), password=c.get("password", ""),
            database=c.get("database", "mqtt"),
            auth_source=c.get("auth_source", "admin"),
            auth_algo=c.get("auth_algo", "sha256"), ssl=c.get("ssl"))

    async def query(self, request: Any) -> Any:
        """request: ("find", collection, filter) -> list of docs,
        ("insert", collection, docs) -> count, or a raw command dict."""
        timeout = self.conf.get("timeout", 5)
        if isinstance(request, dict):
            return await self.pool.run(lambda c: c.command(request),
                                       timeout=timeout)
        verb = request[0]
        if verb == "find":
            return await self.pool.run(
                lambda c: c.find(request[1], request[2]), timeout=timeout)
        if verb == "insert":
            return await self.pool.run(
                lambda c: c.insert(request[1], list(request[2])),
                timeout=timeout)
        raise ValueError(f"unknown mongo verb {verb!r}")


class LdapResource(_PooledDbResource):
    TYPE = "ldap"

    def _make_client(self) -> LdapClient:
        c = self.conf
        return LdapClient(
            host=c.get("host", "127.0.0.1"), port=c.get("port", 389),
            bind_dn=c.get("bind_dn", ""),
            bind_password=c.get("bind_password", ""), ssl=c.get("ssl"))

    async def query(self, request: Any) -> Any:
        """request: ("search", base_dn, scope, filter_bytes, [attrs])."""
        if not (isinstance(request, (tuple, list)) and request
                and request[0] == "search"):
            raise ValueError(f"bad ldap request {request!r}")
        _, base, scope, filt, *rest = request
        attrs = rest[0] if rest else None
        return await self.pool.run(
            lambda c: c.search(base, scope, filt, attributes=attrs),
            timeout=self.conf.get("timeout", 5))


def _sql_request(request: Any) -> tuple[str, Optional[list]]:
    if isinstance(request, str):
        return request, None
    if isinstance(request, (tuple, list)) and request and \
            request[0] == "sql":
        return request[1], list(request[2]) if len(request) > 2 else None
    raise ValueError(f"bad sql request {request!r}")


for _cls in (RedisResource, MysqlResource, PgsqlResource, MongoResource,
             LdapResource):
    ResourceManager.register_type(_cls.TYPE, _cls)
