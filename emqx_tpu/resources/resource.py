"""Resource instance manager.

Parity: emqx_resource_instance.erl — create/remove instances by resource
type, periodic health checks flipping connected/disconnected status,
restart of unhealthy instances; plus the rule-engine action surface the
data-bridge app exposes (actions `data_to_<type>` resolving to an instance,
emqx_rule_actions data_to_* via resources).
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Any, Callable, Optional

log = logging.getLogger("emqx_tpu.resources")


class Resource:
    """Behaviour: subclasses implement start/stop/health_check/query."""

    TYPE = "abstract"

    def __init__(self, rid: str, conf: dict):
        self.id = rid
        self.conf = conf
        self.status = "stopped"       # stopped|connected|disconnected
        self.last_error: Optional[str] = None

    async def start(self) -> None: ...

    async def stop(self) -> None: ...

    async def health_check(self) -> bool:
        return True

    async def query(self, request: Any) -> Any:
        raise NotImplementedError

    def info(self) -> dict:
        return {"id": self.id, "type": self.TYPE, "status": self.status,
                "last_error": self.last_error}


class HttpResource(Resource):
    """HTTP webhook connector (emqx_connector_http over ehttpc)."""

    TYPE = "http"

    async def start(self) -> None:
        self.status = "connected" if await self.health_check() \
            else "disconnected"

    async def health_check(self) -> bool:
        from emqx_tpu.utils.http import request
        try:
            url = self.conf.get("health_url") or self.conf["url"]
            resp = await request("GET", url, timeout=3)
            ok = resp.status < 500
        except Exception as e:  # noqa: BLE001
            self.last_error = str(e)
            ok = False
        return ok

    async def query(self, request_body: Any) -> Any:
        from emqx_tpu.utils.http import request
        body = request_body if isinstance(request_body, (bytes, str)) \
            else json.dumps(request_body)
        if isinstance(body, str):
            body = body.encode()
        return await request(
            self.conf.get("method", "POST"), self.conf["url"],
            headers=dict(self.conf.get("headers")
                         or {"content-type": "application/json"}),
            body=body, timeout=self.conf.get("timeout", 5))


class MqttResource(Resource):
    """Remote MQTT connection (emqx_connector_mqtt via emqtt)."""

    TYPE = "mqtt"

    def __init__(self, rid: str, conf: dict):
        super().__init__(rid, conf)
        self.client = None

    async def start(self) -> None:
        from emqx_tpu.client import Client
        self.client = Client(
            host=self.conf.get("host", "127.0.0.1"),
            port=self.conf.get("port", 1883),
            clientid=self.conf.get("clientid", f"resource-{self.id}"),
            username=self.conf.get("username"),
            password=self.conf.get("password"))
        try:
            await self.client.connect()
            self.status = "connected"
        except Exception as e:  # noqa: BLE001
            self.last_error = str(e)
            self.status = "disconnected"

    async def stop(self) -> None:
        if self.client is not None and self.status == "connected":
            try:
                await self.client.disconnect()
            except Exception:  # noqa: BLE001
                pass
        self.status = "stopped"

    async def health_check(self) -> bool:
        if self.client is None or self.status != "connected":
            return False
        try:
            await self.client.ping()
            return True
        except Exception as e:  # noqa: BLE001
            self.last_error = str(e)
            return False

    async def query(self, request: Any) -> Any:
        """request: {"topic":..., "payload":..., "qos":...}"""
        await self.client.publish(request["topic"],
                                  request.get("payload", b""),
                                  qos=request.get("qos", 0),
                                  retain=request.get("retain", False))
        return True


class ResourceManager:
    RESOURCE_TYPES: dict[str, Callable[..., Resource]] = {
        "http": HttpResource,
        "mqtt": MqttResource,
    }

    def __init__(self, node, health_interval: float = 15.0):
        self.node = node
        self.health_interval = health_interval
        self.instances: dict[str, Resource] = {}
        self._health_task: Optional[asyncio.Task] = None
        node.resources = self

    @classmethod
    def register_type(cls, name: str,
                      factory: Callable[..., Resource]) -> None:
        cls.RESOURCE_TYPES[name] = factory

    async def create(self, rid: str, rtype: str, conf: dict) -> Resource:
        if rid in self.instances:
            raise ValueError(f"resource {rid} exists")
        factory = self.RESOURCE_TYPES.get(rtype)
        if factory is None:
            raise ValueError(f"unknown resource type {rtype}")
        res = factory(rid, conf)
        await res.start()
        self.instances[rid] = res
        return res

    async def remove(self, rid: str) -> bool:
        res = self.instances.pop(rid, None)
        if res is None:
            return False
        await res.stop()
        return True

    def get(self, rid: str) -> Optional[Resource]:
        return self.instances.get(rid)

    def list(self) -> list[dict]:
        return [r.info() for r in self.instances.values()]

    # ---- health loop (emqx_resource_instance periodic health_check) ----
    def start_health_checks(self) -> None:
        if self._health_task is None:
            self._health_task = asyncio.get_running_loop().create_task(
                self._health_loop())

    def stop_health_checks(self) -> None:
        if self._health_task is not None:
            self._health_task.cancel()
            self._health_task = None

    async def _health_loop(self) -> None:
        while True:
            await asyncio.sleep(self.health_interval)
            for res in list(self.instances.values()):
                await self._check_one(res)

    async def _check_one(self, res: Resource) -> None:
        healthy = await res.health_check()
        if healthy:
            res.status = "connected"
            return
        if res.status == "connected":
            res.status = "disconnected"
            log.warning("resource %s became unhealthy: %s", res.id,
                        res.last_error)
        # restart attempt (auto_retry_interval behavior)
        try:
            await res.stop()
            await res.start()
        except Exception as e:  # noqa: BLE001
            res.last_error = str(e)

    # ---- rule-engine action surface (emqx_rule_actions data_to_*) ----
    def has_action(self, name: str) -> bool:
        return name.startswith("data_to_") and \
            name[len("data_to_"):] in self.instances

    def run_action(self, name: str, params: dict, columns: dict,
                   envs: dict) -> Any:
        from emqx_tpu.rules.actions import render_template
        rid = name[len("data_to_"):]
        res = self.instances.get(rid)
        if res is None:
            raise ValueError(f"no resource instance {rid}")
        if res.TYPE == "mqtt":
            req = {"topic": render_template(
                       params.get("target_topic", "${topic}"), columns),
                   "payload": render_template(
                       params.get("payload_tmpl", "${payload}"),
                       columns).encode(),
                   "qos": int(params.get("qos", 0))}
        else:
            tmpl = params.get("payload_tmpl")
            req = render_template(tmpl, columns) if tmpl \
                else json.dumps(columns, default=str)
        task = asyncio.ensure_future(res.query(req))
        task.add_done_callback(_log_query_error)
        return True


def _log_query_error(task: asyncio.Task) -> None:
    if not task.cancelled() and task.exception() is not None:
        log.warning("resource query failed: %s", task.exception())
