"""MQTT bridge: forward local topics to a remote broker and ingress
remote topics into the local one.

Parity: apps/emqx_bridge_mqtt/src/emqx_bridge_worker.erl — gen_statem
idle -> connecting -> connected (:41-49,81-82) with a replayq disk-backed
resend queue (:142-143,211-217): forwards are appended to the queue first
and drained to the remote with acks, so messages survive remote outages and
worker restarts; ingress subscriptions republish into the local broker
under a mountpoint prefix.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional

from emqx_tpu.broker.message import Message, make
from emqx_tpu.utils.replayq import ReplayQ

log = logging.getLogger("emqx_tpu.bridge_mqtt")


class MqttBridgeWorker:
    def __init__(self, node, name: str, conf: dict):
        self.node = node
        self.name = name
        self.conf = conf
        self.state = "idle"                 # idle|connecting|connected
        self.forwards: list[str] = list(conf.get("forwards", []))
        self.subscriptions = list(conf.get("subscriptions", []))
        self.forward_mountpoint = conf.get("forward_mountpoint", "")
        self.receive_mountpoint = conf.get("receive_mountpoint", "")
        self.reconnect_interval = conf.get("reconnect_interval", 2.0)
        self.batch_size = conf.get("batch_size", 32)
        self.queue = ReplayQ(conf.get("queue_dir"),
                             seg_bytes=conf.get("seg_bytes", 10 << 20))
        self.client = None
        self.sid: Optional[int] = None
        self._tasks: list[asyncio.Task] = []
        self._stopping = False
        self._wakeup = asyncio.Event()

    # ---- local subscriber protocol (forward side) ----
    def deliver(self, topic_filter: str, msg: Message) -> bool:
        self.queue.append(json.dumps(msg.to_wire(),
                                     default=_b64).encode())
        self._wakeup.set()
        return True

    # ---- lifecycle ----
    async def start(self) -> None:
        self._stopping = False
        if self.forwards:
            self.sid = self.node.broker.register(
                self, f"bridge:{self.name}")
            for f in self.forwards:
                self.node.broker.subscribe(self.sid, f, {"qos": 1})
        self._tasks.append(asyncio.create_task(self._conn_loop()))
        self._tasks.append(asyncio.create_task(self._drain_loop()))

    async def stop(self) -> None:
        self._stopping = True
        for t in self._tasks:
            t.cancel()
        self._tasks = []
        if self.sid is not None:
            self.node.broker.subscriber_down(self.sid)
            self.sid = None
        await self._disconnect()
        self.state = "idle"

    async def _disconnect(self) -> None:
        if self.client is not None:
            try:
                await self.client.disconnect()
            except Exception:  # noqa: BLE001
                pass
            self.client = None

    # ---- connection FSM ----
    async def _conn_loop(self) -> None:
        while not self._stopping:
            if self.state != "connected":
                await self._try_connect()
            await asyncio.sleep(self.reconnect_interval)

    async def _try_connect(self) -> None:
        from emqx_tpu.client import Client
        self.state = "connecting"
        await self._disconnect()
        try:
            self.client = Client(
                host=self.conf.get("host", "127.0.0.1"),
                port=self.conf.get("port", 1883),
                clientid=self.conf.get("clientid",
                                       f"bridge-{self.name}"),
                username=self.conf.get("username"),
                password=self.conf.get("password"),
                clean_start=False,
                ssl=self.conf.get("ssl"))  # emqx-style client tls opts dict
            await self.client.connect()
            for sub in self.subscriptions:
                topic = sub["topic"] if isinstance(sub, dict) else sub
                qos = sub.get("qos", 1) if isinstance(sub, dict) else 1
                await self.client.subscribe(topic, qos=qos)
            self.state = "connected"
            self._wakeup.set()
            t = asyncio.create_task(self._ingress_loop())
            self._tasks.append(t)
            # prune finished ingress tasks so a flapping remote can't grow
            # the list without bound
            t.add_done_callback(
                lambda t: self._tasks.remove(t)
                if t in self._tasks else None)
            log.info("bridge %s connected to %s:%s", self.name,
                     self.conf.get("host"), self.conf.get("port"))
        except Exception as e:  # noqa: BLE001
            log.info("bridge %s connect failed: %s", self.name, e)
            self.state = "connecting"

    # ---- egress: drain replayq to remote ----
    async def _drain_loop(self) -> None:
        while not self._stopping:
            self._wakeup.clear()
            if self.state != "connected" or self.queue.is_empty():
                try:
                    await asyncio.wait_for(self._wakeup.wait(), 1.0)
                except asyncio.TimeoutError:
                    pass
                continue
            items, ref = self.queue.pop(self.batch_size)
            if not items:
                continue
            try:
                for raw in items:
                    wire = json.loads(raw)
                    await self.client.publish(
                        self.forward_mountpoint + wire["topic"],
                        _unb64(wire["payload"]),
                        qos=min(wire["qos"], 1))
                self.queue.ack(ref)
            except Exception as e:  # noqa: BLE001
                # remote died mid-batch: ref not acked, items replay
                log.info("bridge %s drain failed (%s); will replay",
                         self.name, e)
                self.state = "connecting"
                await asyncio.sleep(self.reconnect_interval)

    # ---- ingress: remote messages -> local broker ----
    async def _ingress_loop(self) -> None:
        client = self.client
        while not self._stopping and self.state == "connected" \
                and self.client is client:
            try:
                pkt = await client.recv(timeout=1.0)
            except asyncio.TimeoutError:
                continue
            except Exception:  # noqa: BLE001
                self.state = "connecting"
                return
            msg = make(f"bridge:{self.name}", pkt.qos,
                       self.receive_mountpoint + pkt.topic, pkt.payload)
            await self.node.broker.publish_async(msg)

    def info(self) -> dict:
        return {"name": self.name, "state": self.state,
                "queue_len": self.queue.count(),
                "forwards": self.forwards,
                "subscriptions": self.subscriptions}


def _b64(o):
    if isinstance(o, (bytes, bytearray)):
        import base64
        return {"$b": base64.b64encode(bytes(o)).decode()}
    return repr(o)


def _unb64(v):
    if isinstance(v, dict) and "$b" in v:
        import base64
        return base64.b64decode(v["$b"])
    return v.encode() if isinstance(v, str) else bytes(v)
