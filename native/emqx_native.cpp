// Native runtime components for the TPU-native broker.
//
// Parity role (SURVEY.md §2.3): where the reference leans on native code
// for hot byte-level work (jiffy's C JSON for payloads, esockd's accept
// path, the BEAM's binary pattern matching that makes emqx_frame.erl fast),
// this library provides the equivalents for the Python host runtime:
//
//   mqtt_frame_scan        batched fixed-header/varint scan that splits a
//                          TCP read buffer into complete MQTT frames — the
//                          {active,N} batching primitive feeding the codec
//   topic_level_hashes     tokenize a topic on '/' and FNV-1a-64 hash each
//                          level for the device intern table
//   topic_hash_batch       the same over a batch of topics in one call
//   topic_match            wildcard filter match (emqx_topic:match/2) for
//                          host-side fast paths
//   replayq_scan           length-prefixed segment scan with torn-tail
//                          detection for the disk replay queue
//
// Build: `make -C native` -> libemqx_native.so, loaded via ctypes
// (emqx_tpu/native.py) with pure-Python fallbacks when absent.

#include <cstdint>
#include <cstddef>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------
// MQTT frame scan
// Returns the number of complete frames found (<= n_out); writes their
// (offset, total_length) into out_off/out_len. *consumed = end of the
// last complete frame. Returns -1 on a malformed varint (>4 bytes) and
// -2 on a frame exceeding max_frame.
// ---------------------------------------------------------------------
int mqtt_frame_scan(const uint8_t* buf, size_t len,
                    uint32_t* out_off, uint32_t* out_len, int n_out,
                    uint32_t max_frame, size_t* consumed) {
    size_t pos = 0;
    int found = 0;
    *consumed = 0;
    while (pos + 2 <= len && found < n_out) {
        // fixed header: type/flags byte + varint remaining length
        size_t p = pos + 1;
        uint32_t rem = 0;
        uint32_t mult = 1;
        int nbytes = 0;
        bool complete_varint = false;
        while (p < len && nbytes < 4) {
            uint8_t b = buf[p++];
            rem += (uint32_t)(b & 0x7F) * mult;
            mult <<= 7;
            ++nbytes;
            if ((b & 0x80) == 0) { complete_varint = true; break; }
        }
        if (!complete_varint) {
            if (nbytes >= 4) return -1;   // varint longer than 4 bytes
            break;                        // need more bytes
        }
        size_t total = (p - pos) + rem;
        if (max_frame && total > max_frame) return -2;
        if (pos + total > len) break;     // incomplete body
        out_off[found] = (uint32_t)pos;
        out_len[found] = (uint32_t)total;
        ++found;
        pos += total;
        *consumed = pos;
    }
    return found;
}

// ---------------------------------------------------------------------
// Topic level hashing (FNV-1a 64) — the intern-table key function.
// ---------------------------------------------------------------------
static inline uint64_t fnv1a(const char* s, size_t n) {
    uint64_t h = 1469598103934665603ULL;
    for (size_t i = 0; i < n; ++i) {
        h ^= (uint8_t)s[i];
        h *= 1099511628211ULL;
    }
    return h;
}

int topic_level_hashes(const char* topic, size_t len,
                       uint64_t* out, int max_levels) {
    int levels = 0;
    size_t start = 0;
    for (size_t i = 0; i <= len; ++i) {
        if (i == len || topic[i] == '/') {
            if (levels >= max_levels) return -1;
            out[levels++] = fnv1a(topic + start, i - start);
            start = i + 1;
        }
    }
    return levels;
}

// Batched: topics concatenated in buf with offsets/lengths. counts[i]
// receives the level count (or 0xFF on overflow); hashes are written to
// out[i*max_levels ...].
int topic_hash_batch(const char* buf, const uint32_t* offs,
                     const uint32_t* lens, int n,
                     uint64_t* out, uint8_t* counts, int max_levels) {
    for (int i = 0; i < n; ++i) {
        int c = topic_level_hashes(buf + offs[i], lens[i],
                                   out + (size_t)i * max_levels,
                                   max_levels);
        counts[i] = c < 0 ? 0xFF : (uint8_t)c;
    }
    return n;
}

// ---------------------------------------------------------------------
// Wildcard topic match (emqx_topic:match/2 semantics):
//   '+' one level, '#' tail (must be last), '$'-topics never match a
//   root-level wildcard. Returns 1 match / 0 no match.
// ---------------------------------------------------------------------
int topic_match(const char* name, size_t name_len,
                const char* filter, size_t filter_len) {
    // $-topics are excluded from root wildcards
    if (name_len > 0 && name[0] == '$' && filter_len > 0 &&
        (filter[0] == '+' || filter[0] == '#'))
        return 0;
    size_t ni = 0, fi = 0;
    while (fi < filter_len) {
        // current filter level [fi, fe)
        size_t fe = fi;
        while (fe < filter_len && filter[fe] != '/') ++fe;
        size_t flen = fe - fi;
        if (flen == 1 && filter[fi] == '#')
            return 1;                      // '#' swallows the rest
        if (ni > name_len) return 0;       // name exhausted, filter not
        // current name level [ni, ne)
        size_t ne = ni;
        while (ne < name_len && name[ne] != '/') ++ne;
        if (!(flen == 1 && filter[fi] == '+')) {
            if (ne - ni != flen ||
                memcmp(name + ni, filter + fi, flen) != 0)
                return 0;
        }
        fi = fe + 1;                       // skip '/'
        ni = ne + 1;
        if (fe == filter_len) {            // filter exhausted
            return ni > name_len ? 1 : 0;  // name must be exhausted too
        }
    }
    return ni > name_len ? 1 : 0;
}

// ---------------------------------------------------------------------
// Replay-queue segment scan: length-prefixed items (>I big-endian).
// Writes item (offset,length) pairs; a torn tail (partial item) is
// ignored, matching ReplayQ._read_seg. Returns item count.
// ---------------------------------------------------------------------
int replayq_scan(const uint8_t* buf, size_t len,
                 uint32_t* out_off, uint32_t* out_len, int n_out) {
    size_t pos = 0;
    int found = 0;
    while (pos + 4 <= len && found < n_out) {
        uint32_t n = ((uint32_t)buf[pos] << 24) |
                     ((uint32_t)buf[pos + 1] << 16) |
                     ((uint32_t)buf[pos + 2] << 8) |
                     (uint32_t)buf[pos + 3];
        if (pos + 4 + n > len) break;      // torn tail
        out_off[found] = (uint32_t)(pos + 4);
        out_len[found] = n;
        ++found;
        pos += 4 + n;
    }
    return found;
}

}  // extern "C"
