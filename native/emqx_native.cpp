// Native runtime components for the TPU-native broker.
//
// Parity role (SURVEY.md §2.3): where the reference leans on native code
// for hot byte-level work (jiffy's C JSON for payloads, esockd's accept
// path, the BEAM's binary pattern matching that makes emqx_frame.erl fast),
// this library provides the equivalents for the Python host runtime:
//
//   mqtt_frame_scan        batched fixed-header/varint scan that splits a
//                          TCP read buffer into complete MQTT frames — the
//                          {active,N} batching primitive feeding the codec
//   topic_level_hashes     tokenize a topic on '/' and FNV-1a-64 hash each
//                          level for the device intern table
//   topic_hash_batch       the same over a batch of topics in one call
//   topic_match            wildcard filter match (emqx_topic:match/2) for
//                          host-side fast paths
//   replayq_scan           length-prefixed segment scan with torn-tail
//                          detection for the disk replay queue
//
// Build: `make -C native` -> libemqx_native.so, loaded via ctypes
// (emqx_tpu/native.py) with pure-Python fallbacks when absent.

#include <cstdint>
#include <cstddef>
#include <cstring>
#include <stdlib.h>
#include <shared_mutex>
#include <mutex>

extern "C" {

// ---------------------------------------------------------------------
// MQTT frame scan
// Returns the number of complete frames found (<= n_out); writes their
// (offset, total_length) into out_off/out_len. *consumed = end of the
// last complete frame. Returns -1 on a malformed varint (>4 bytes) and
// -2 on a frame exceeding max_frame.
// ---------------------------------------------------------------------
int mqtt_frame_scan(const uint8_t* buf, size_t len,
                    uint32_t* out_off, uint32_t* out_len, int n_out,
                    uint32_t max_frame, size_t* consumed) {
    size_t pos = 0;
    int found = 0;
    *consumed = 0;
    while (pos + 2 <= len && found < n_out) {
        // fixed header: type/flags byte + varint remaining length
        size_t p = pos + 1;
        uint32_t rem = 0;
        uint32_t mult = 1;
        int nbytes = 0;
        bool complete_varint = false;
        while (p < len && nbytes < 4) {
            uint8_t b = buf[p++];
            rem += (uint32_t)(b & 0x7F) * mult;
            mult <<= 7;
            ++nbytes;
            if ((b & 0x80) == 0) { complete_varint = true; break; }
        }
        if (!complete_varint) {
            if (nbytes >= 4) return -1;   // varint longer than 4 bytes
            break;                        // need more bytes
        }
        size_t total = (p - pos) + rem;
        if (max_frame && total > max_frame) return -2;
        if (pos + total > len) break;     // incomplete body
        out_off[found] = (uint32_t)pos;
        out_len[found] = (uint32_t)total;
        ++found;
        pos += total;
        *consumed = pos;
    }
    return found;
}

// ---------------------------------------------------------------------
// Columnar PUBLISH decode (ISSUE 11): given the frame boundaries from
// mqtt_frame_scan, decode every PUBLISH frame's wire fields into
// parallel output arrays in one pass. Non-PUBLISH frames — and any
// PUBLISH the strict parser must see for its precise error (qos 3,
// truncated topic/packet-id, packet id 0, malformed property-length
// varint, property span past the body) — stay kind=0 for the
// per-packet parser. UTF-8 topic validation and v5 property CONTENT
// parsing are the python side's job (it owns the string objects).
// All offsets are absolute into buf; flags packs the fixed-header
// nibble (bit0 retain, bits1-2 qos, bit3 dup). Outputs are written
// only for kind=1 rows (kind=0 rows are all-zero), so the pure-python
// fallback can be compared array-for-array. Returns the kind=1 count.
// ---------------------------------------------------------------------
int mqtt_publish_decode_columnar(
        const uint8_t* buf, size_t len,
        const uint32_t* off, const uint32_t* flen, int n, int v5,
        uint8_t* kind, uint8_t* flags,
        uint32_t* topic_off, uint32_t* topic_len, uint32_t* packet_id,
        uint32_t* props_off, uint32_t* props_len,
        uint32_t* payload_off, uint32_t* payload_len) {
    int found = 0;
    for (int i = 0; i < n; ++i) {
        kind[i] = 0; flags[i] = 0;
        topic_off[i] = 0; topic_len[i] = 0; packet_id[i] = 0;
        props_off[i] = 0; props_len[i] = 0;
        payload_off[i] = 0; payload_len[i] = 0;
        size_t s = off[i];
        size_t e = s + flen[i];
        if (e > len || flen[i] < 2) continue;
        uint8_t b0 = buf[s];
        if ((b0 >> 4) != 3) continue;          // not PUBLISH
        uint32_t qos = (b0 >> 1) & 0x3;
        if (qos == 3) continue;                // strict: invalid_qos
        // remaining-length varint (completeness proven by the scan;
        // re-walked only to find the body start)
        size_t p = s + 1;
        int nb = 0;
        while (p < e && nb < 4) {
            uint8_t b = buf[p++];
            ++nb;
            if (!(b & 0x80)) break;
        }
        if (p + 2 > e) continue;               // truncated topic length
        uint32_t tl = ((uint32_t)buf[p] << 8) | buf[p + 1];
        p += 2;
        if (p + tl > e) continue;              // truncated topic
        size_t t_off = p;
        p += tl;
        uint32_t pid = 0;
        if (qos > 0) {
            if (p + 2 > e) continue;           // truncated packet id
            pid = ((uint32_t)buf[p] << 8) | buf[p + 1];
            p += 2;
            if (pid == 0) continue;            // strict: packet id 0
        }
        size_t pr_off = 0, pr_len = 0;
        if (v5) {
            uint32_t pl = 0, mult = 1;
            int k = 0;
            bool done = false;
            while (p < e && k < 4) {
                uint8_t b = buf[p++];
                pl += (uint32_t)(b & 0x7F) * mult;
                mult <<= 7;
                ++k;
                if (!(b & 0x80)) { done = true; break; }
            }
            if (!done) continue;               // malformed props varint
            if (p + pl > e) continue;          // props past body end
            pr_off = p;
            pr_len = pl;
            p += pl;
        }
        topic_off[i] = (uint32_t)t_off;
        topic_len[i] = tl;
        packet_id[i] = pid;
        props_off[i] = (uint32_t)pr_off;
        props_len[i] = (uint32_t)pr_len;
        payload_off[i] = (uint32_t)p;
        payload_len[i] = (uint32_t)(e - p);
        flags[i] = b0 & 0x0F;
        kind[i] = 1;
        ++found;
    }
    return found;
}

// ---------------------------------------------------------------------
// Topic level hashing (FNV-1a 64) — the intern-table key function.
// ---------------------------------------------------------------------
static inline uint64_t fnv1a(const char* s, size_t n) {
    uint64_t h = 1469598103934665603ULL;
    for (size_t i = 0; i < n; ++i) {
        h ^= (uint8_t)s[i];
        h *= 1099511628211ULL;
    }
    return h;
}

int topic_level_hashes(const char* topic, size_t len,
                       uint64_t* out, int max_levels) {
    int levels = 0;
    size_t start = 0;
    for (size_t i = 0; i <= len; ++i) {
        if (i == len || topic[i] == '/') {
            if (levels >= max_levels) return -1;
            out[levels++] = fnv1a(topic + start, i - start);
            start = i + 1;
        }
    }
    return levels;
}

// Batched: topics concatenated in buf with offsets/lengths. counts[i]
// receives the level count (or 0xFF on overflow); hashes are written to
// out[i*max_levels ...].
int topic_hash_batch(const char* buf, const uint32_t* offs,
                     const uint32_t* lens, int n,
                     uint64_t* out, uint8_t* counts, int max_levels) {
    for (int i = 0; i < n; ++i) {
        int c = topic_level_hashes(buf + offs[i], lens[i],
                                   out + (size_t)i * max_levels,
                                   max_levels);
        counts[i] = c < 0 ? 0xFF : (uint8_t)c;
    }
    return n;
}

// ---------------------------------------------------------------------
// Wildcard topic match (emqx_topic:match/2 semantics):
//   '+' one level, '#' tail (must be last), '$'-topics never match a
//   root-level wildcard. Returns 1 match / 0 no match.
// ---------------------------------------------------------------------
int topic_match(const char* name, size_t name_len,
                const char* filter, size_t filter_len) {
    // $-topics are excluded from root wildcards
    if (name_len > 0 && name[0] == '$' && filter_len > 0 &&
        (filter[0] == '+' || filter[0] == '#'))
        return 0;
    size_t ni = 0, fi = 0;
    while (fi < filter_len) {
        // current filter level [fi, fe)
        size_t fe = fi;
        while (fe < filter_len && filter[fe] != '/') ++fe;
        size_t flen = fe - fi;
        if (flen == 1 && filter[fi] == '#')
            return 1;                      // '#' swallows the rest
        if (ni > name_len) return 0;       // name exhausted, filter not
        // current name level [ni, ne)
        size_t ne = ni;
        while (ne < name_len && name[ne] != '/') ++ne;
        if (!(flen == 1 && filter[fi] == '+')) {
            if (ne - ni != flen ||
                memcmp(name + ni, filter + fi, flen) != 0)
                return 0;
        }
        fi = fe + 1;                       // skip '/'
        ni = ne + 1;
        if (fe == filter_len) {            // filter exhausted
            return ni > name_len ? 1 : 0;  // name must be exhausted too
        }
    }
    return ni > name_len ? 1 : 0;
}

// ---------------------------------------------------------------------
// Interned-word table mirrors + batched topic encoding (SURVEY §7
// hard-part 3, "strings on TPU"): python's InternTable owns word→id
// authoritatively; a mirror here stores hash + THE WORD BYTES (arena)
// so a whole publish batch encodes in one call. Lookups confirm the
// word with memcmp — correctness never touches hash uniqueness; two
// words sharing a 64-bit hash simply occupy different probe slots.
//
// Concurrency: ctypes releases the GIL around these calls and the
// engine's background rebuild thread interns filter words while the
// event loop encodes publish batches — a grow would otherwise free the
// arrays under a concurrent reader. One global shared_mutex guards all
// tables: encode takes it shared (once per BATCH, not per word),
// add/new/free take it exclusive.
// ---------------------------------------------------------------------

struct WTab {
    uint64_t* keys;   // 0 = empty slot (a real hash of 0 is remapped)
    uint32_t* woff;   // word bytes in arena
    uint32_t* wlen;
    int32_t*  ids;
    char*  arena;
    size_t arena_used, arena_cap;
    size_t cap;       // power of two
    size_t used;
};

#define MAX_WTABS 64
static WTab g_wtabs[MAX_WTABS];
static uint8_t g_wtab_live[MAX_WTABS];
static std::shared_mutex g_wtab_mu;

static inline uint64_t nz(uint64_t h) { return h ? h : 1; }

static inline bool word_eq(const WTab* t, size_t i, const char* w,
                           size_t n) {
    return t->wlen[i] == n && memcmp(t->arena + t->woff[i], w, n) == 0;
}

// probe for (hash, word); returns slot index (occupied-and-equal or
// first empty)
static size_t wtab_probe(const WTab* t, uint64_t key, const char* w,
                         size_t n) {
    size_t mask = t->cap - 1;
    size_t i = (size_t)key & mask;
    while (t->keys[i]) {
        if (t->keys[i] == key && word_eq(t, i, w, n)) return i;
        i = (i + 1) & mask;
    }
    return i;
}

static int wtab_grow(WTab* t) {
    size_t ncap = t->cap ? t->cap * 2 : 1024;
    uint64_t* nkeys = (uint64_t*)calloc(ncap, sizeof(uint64_t));
    uint32_t* noff = (uint32_t*)malloc(ncap * sizeof(uint32_t));
    uint32_t* nlen = (uint32_t*)malloc(ncap * sizeof(uint32_t));
    int32_t* nids = (int32_t*)malloc(ncap * sizeof(int32_t));
    if (!nkeys || !noff || !nlen || !nids) {
        free(nkeys); free(noff); free(nlen); free(nids);
        return -1;
    }
    size_t mask = ncap - 1;
    for (size_t i = 0; i < t->cap; ++i) {
        if (!t->keys[i]) continue;
        size_t j = (size_t)t->keys[i] & mask;
        while (nkeys[j]) j = (j + 1) & mask;
        nkeys[j] = t->keys[i]; noff[j] = t->woff[i];
        nlen[j] = t->wlen[i]; nids[j] = t->ids[i];
    }
    free(t->keys); free(t->woff); free(t->wlen); free(t->ids);
    t->keys = nkeys; t->woff = noff; t->wlen = nlen; t->ids = nids;
    t->cap = ncap;
    return 0;
}

int intern_table_new(void) {
    std::unique_lock<std::shared_mutex> lk(g_wtab_mu);
    for (int h = 0; h < MAX_WTABS; ++h) {
        if (!g_wtab_live[h]) {
            WTab* t = &g_wtabs[h];
            memset(t, 0, sizeof(*t));
            if (wtab_grow(t) != 0) return -1;
            t->arena_cap = 1 << 16;
            t->arena = (char*)malloc(t->arena_cap);
            if (!t->arena) {
                free(t->keys); free(t->woff); free(t->wlen);
                free(t->ids); memset(t, 0, sizeof(*t));
                return -1;
            }
            g_wtab_live[h] = 1;
            return h;
        }
    }
    return -1;      // out of handles: caller stays on the python path
}

void intern_table_free(int h) {
    std::unique_lock<std::shared_mutex> lk(g_wtab_mu);
    if (h < 0 || h >= MAX_WTABS || !g_wtab_live[h]) return;
    WTab* t = &g_wtabs[h];
    free(t->keys); free(t->woff); free(t->wlen); free(t->ids);
    free(t->arena);
    memset(t, 0, sizeof(*t));
    g_wtab_live[h] = 0;
}

// 0 ok; -1 same word already present with a DIFFERENT id (caller bug:
// intern ids never change); -2 allocation failure / bad handle
int intern_table_add(int h, const char* word, uint32_t len, int32_t id) {
    std::unique_lock<std::shared_mutex> lk(g_wtab_mu);
    if (h < 0 || h >= MAX_WTABS || !g_wtab_live[h]) return -2;
    WTab* t = &g_wtabs[h];
    if ((t->used + 1) * 4 >= t->cap * 3 && wtab_grow(t) != 0) return -2;
    uint64_t key = nz(fnv1a(word, len));
    size_t i = wtab_probe(t, key, word, len);
    if (t->keys[i])
        return t->ids[i] == id ? 0 : -1;
    if (t->arena_used + len > t->arena_cap) {
        size_t ncap = t->arena_cap;
        while (t->arena_used + len > ncap) ncap *= 2;
        char* na = (char*)realloc(t->arena, ncap);
        if (!na) return -2;
        t->arena = na; t->arena_cap = ncap;
    }
    memcpy(t->arena + t->arena_used, word, len);
    t->keys[i] = key;
    t->woff[i] = (uint32_t)t->arena_used;
    t->wlen[i] = len;
    t->ids[i] = id;
    t->arena_used += len;
    t->used++;
    return 0;
}

// Encode a batch of publish topics: buf holds the topics concatenated,
// offs/tlens index them. Writes out_ids[n*max_levels] (pad_id beyond a
// topic's levels), out_lens, out_dollar, out_toolong. Unknown words get
// unknown_id (they can still match +/# on device). Returns n.
int topic_encode_batch(int h, const char* buf, const uint32_t* offs,
                       const uint32_t* tlens, int n, int max_levels,
                       int32_t unknown_id, int32_t pad_id,
                       int32_t* out_ids, int32_t* out_lens,
                       uint8_t* out_dollar, uint8_t* out_toolong) {
    std::shared_lock<std::shared_mutex> lk(g_wtab_mu);
    if (h < 0 || h >= MAX_WTABS || !g_wtab_live[h]) return -2;
    const WTab* t = &g_wtabs[h];
    for (int i = 0; i < n; ++i) {
        const char* s = buf + offs[i];
        size_t len = tlens[i];
        int32_t* row = out_ids + (size_t)i * max_levels;
        int levels = 0, toolong = 0;
        size_t start = 0;
        for (size_t p = 0; p <= len; ++p) {
            if (p == len || s[p] == '/') {
                if (levels >= max_levels) { toolong = 1; break; }
                size_t wl = p - start;
                size_t slot = wtab_probe(t, nz(fnv1a(s + start, wl)),
                                         s + start, wl);
                row[levels++] = t->keys[slot] ? t->ids[slot] : unknown_id;
                start = p + 1;
            }
        }
        for (int k = levels; k < max_levels; ++k) row[k] = pad_id;
        out_lens[i] = levels;
        out_dollar[i] = (len > 0 && s[0] == '$') ? 1 : 0;
        out_toolong[i] = (uint8_t)toolong;
    }
    return n;
}

// ---------------------------------------------------------------------
// Replay-queue segment scan: length-prefixed items (>I big-endian).
// Writes item (offset,length) pairs; a torn tail (partial item) is
// ignored, matching ReplayQ._read_seg. Returns item count.
// ---------------------------------------------------------------------
int replayq_scan(const uint8_t* buf, size_t len,
                 uint32_t* out_off, uint32_t* out_len, int n_out) {
    size_t pos = 0;
    int found = 0;
    while (pos + 4 <= len && found < n_out) {
        uint32_t n = ((uint32_t)buf[pos] << 24) |
                     ((uint32_t)buf[pos + 1] << 16) |
                     ((uint32_t)buf[pos + 2] << 8) |
                     (uint32_t)buf[pos + 3];
        if (pos + 4 + n > len) break;      // torn tail
        out_off[found] = (uint32_t)(pos + 4);
        out_len[found] = n;
        ++found;
        pos += 4 + n;
    }
    return found;
}

}  // extern "C"
