#!/usr/bin/env python
"""North-star benchmark: wildcard topic-match + fan-out throughput on TPU.

Measures the fused shape-hash route step (shape-directed match + subscriber
fan-out + shared-sub selection) against the BASELINE.md target: >=5M
topic-matches/sec at 10M wildcard subscriptions on one v5e-1.

Filter shape mirrors the reference's own bench harness
(emqx_broker_bench.erl:25-34 `device/{{id}}/+/{{num}}/#`), scaled to
BENCH_SUBS subscriptions; BENCH_SHARED_PCT puts that share of subscriptions
into $share groups (BASELINE.md config 4).

Crash policy: one JSON line is ALWAYS printed on stdout. The requested scale
is tried first; on any failure the harness steps down the subscription
ladder (10M -> 1M -> 100k) and reports the scale that succeeded. Uploads are
chunked with retry/backoff because the axon relay's device_put has failed on
single ~100MB+ transfers (round 1 died there with nothing measured).

Measurement notes: the axon relay reports async completions until the first
device->host read, after which dispatches become synchronous; throughput is
therefore measured as a pipelined window of route steps closed by a full
result readback (total wall time / topics routed), which is also how the
broker consumes the device (queue batches, read back deliveries). The
per-batch sync round-trip is reported separately on stderr.

Env knobs: BENCH_SUBS (default 10_000_000), BENCH_BATCH (131072),
BENCH_WINDOW (32), BENCH_SHARED_PCT (50), BENCH_PUT_CHUNK_MB (64),
EMQX_TPU_RELAY_WAIT_S (dead-relay fail-fast window, default
BENCH_INIT_TIMEOUT_S=600 — set it low to stop burning a round's budget
polling a relay that never comes up; a PROVABLY dead port now skips the
poll entirely via the relay_watcher preflight, BENCH_RELAY_PREFLIGHT=0
restores the wait), BENCH_FANOUT (=0 skips the delivery-lane fan-out
row; tools/fanout_bench.py knobs FANOUT_*), BENCH_INGRESS (=0 skips
the columnar-ingress e2e twin row; tools/ingress_bench.py knobs
INGRESS_*), BENCH_OVERLOAD (=0 skips the overload-governor drive row;
tools/overload_bench.py knobs OVERLOAD_*), BENCH_CHECKPOINT /
BENCH_RESUME (resumable phase ladder: each phase's JSON commits to disk
as it completes and a restarted bench resumes from the checkpoint —
BENCH_RESUME=0 starts fresh), BENCH_HBM (=0 skips the HBM capacity
forecast committed right after phase0; tools/hbm_report.py knobs
BENCH_HBM_SIZES / BENCH_HBM_HEADROOM).

Diagnosability: every e2e phase snapshots the node's pipeline telemetry
(stage timings, batch occupancy, compile counts —
broker.telemetry.PipelineTelemetry.snapshot()) into the result row, and
the newest snapshot is embedded in the error JSON too, so a round that
dies mid-flight still reports WHERE the pipeline spent its time. The
memory story rides the same way (ISSUE 8): `hbm_forecast` (the fitted
per-subscription bytes + subscription ceiling per HBM budget),
`phase_memory` (per-phase backend memory_stats, checkpointed/restored
like the wall seconds) and `memory` (the newest HBM-ledger section)
land in the merged AND error JSON.
"""

import json
import os
import sys
import time
import traceback

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# newest pipeline-telemetry snapshot taken this run (set by run_e2e,
# success or failure) — embedded in the error JSON so a round that died
# after real traffic still carries its stage-level diagnosis
_LAST_TELEMETRY = None

# phase-0 incremental headline (committed within the first ~2 minutes
# of a hardware window) — embedded in the success AND error JSON so a
# window that dies mid-plan still records a measured number
_PHASE0 = None

# per-phase wall-clock accounting (ISSUE 7 satellite): rounds 3-5 died
# with `value=0` and NO record of where their minutes went. Every phase
# stamps its wall seconds here — success OR failure — and the dict
# rides the checkpoint, the merged JSON and the error JSON, so a dead
# window's post-mortem starts from "config5 ate 9 of the 12 minutes"
# instead of a blank
_PHASE_WALL: dict = {}
# seconds burned waiting for a relay window (preflight + poll loop) —
# the other place dead rounds' minutes vanished
_RELAY_WAIT_S = 0.0

# per-phase memory accounting (ISSUE 8 satellite): each phase stamps
# the backend's memory_stats() (when the runtime exposes it — TPU yes,
# XLA CPU no) at completion, success or failure; rides the checkpoint,
# the merged JSON and the error JSON like _PHASE_WALL, so a window
# that OOMs mid-plan shows which phase's allocations were live
_PHASE_MEM: dict = {}
# newest node-side memory row (HBM ledger section + device stats, set
# by run_e2e) — the `memory` the error JSON carries
_LAST_MEMORY = None
# the HBM capacity forecast (tools/hbm_report.py), committed right
# after phase0 so even a round whose throughput phases all die still
# reports a measured memory headline
_HBM_FORECAST = None
# the CPU latency probe row (ISSUE 13: bench.py --latency-probe as a
# subprocess), committed right after the hbm forecast and grafted onto
# phase0 — even a round whose relay phases all die commits a measured
# per-message ingress→routed/delivered distribution + SLO verdict
_LAT0 = None


def _mem_row(node=None):
    """One memory accounting row: the HBM ledger's `memory` section
    when `node` carries a ledger (it embeds the device stats), else
    the bare backend memory_stats(); None when neither exists."""
    try:
        from emqx_tpu.broker.hbm_ledger import device_memory_stats
        ledger = getattr(node, "hbm_ledger", None) \
            if node is not None else None
        if ledger is not None:
            return ledger.section()
        dev = device_memory_stats()
        return {"device": dev} if dev else None
    except Exception:  # noqa: BLE001 — accounting must never kill data
        return None


class _phase_clock:
    """Context manager stamping one phase's wall seconds into
    _PHASE_WALL (and its end-of-phase memory row into _PHASE_MEM)
    whether the phase returns or raises."""

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        self.t0 = time.time()
        return self

    def __exit__(self, *exc):
        _PHASE_WALL[self.name] = round(time.time() - self.t0, 1)
        mem = _mem_row()
        if mem:
            _PHASE_MEM[self.name] = mem
        return False


def _last_measured():
    """Latest committed mid-round hardware measurement (written by
    tools/relay_watcher.py at the first live relay window). Embedded in
    every error JSON so a relay that is dead at round end can no longer
    erase data that was really measured (rounds 3+4 both lost their
    entire perf story this way)."""
    try:
        import glob
        here = os.path.dirname(os.path.abspath(__file__))
        paths = sorted(glob.glob(os.path.join(here, "MEASURED_r*.json")))
        if not paths:
            return None
        with open(paths[-1]) as f:   # newest round's measurement
            doc = json.load(f)
        keep = {k: doc.get(k) for k in ("ts", "git_rev")}
        bench = doc.get("bench") or {}
        if bench.get("value"):
            keep["bench"] = bench
        matrix = doc.get("matrix") or {}
        if matrix.get("value"):
            keep["matrix_value"] = matrix["value"]
            keep["matrix"] = matrix
        return keep if len(keep) > 2 else None
    except Exception:  # noqa: BLE001 — never let provenance break a report
        return None


def _error_json(error) -> str:
    doc = {
        "metric": "topic_matches_per_sec",
        "value": 0,
        "unit": "topic-matches/s",
        "vs_baseline": 0.0,
        "error": error,
    }
    # where the dead round's minutes went (ISSUE 7 satellite): phase
    # wall seconds + relay-wait seconds always ride the error JSON
    if _PHASE_WALL:
        doc["phase_wall_s"] = dict(_PHASE_WALL)
    if _RELAY_WAIT_S:
        doc["relay_wait_s"] = round(_RELAY_WAIT_S, 1)
    # the memory story (ISSUE 8 satellite): per-phase device stats,
    # the newest ledger section, and the capacity forecast all ride
    # the error JSON — a dead round still commits a memory headline
    if _PHASE_MEM:
        doc["phase_memory"] = dict(_PHASE_MEM)
    if _LAST_MEMORY:
        doc["memory"] = _LAST_MEMORY
    if _HBM_FORECAST:
        doc["hbm_forecast"] = _HBM_FORECAST
    lm = _last_measured()
    if lm:
        doc["last_measured"] = lm
        doc["note"] = ("this run failed environmentally; last_measured is "
                       "the committed mid-round hardware result "
                       "(MEASURED_r05.json)")
    if _PHASE0:
        # the incremental headline measured BEFORE the failure: value
        # stays 0 (the headline scale was not measured) but the round
        # is no longer numberless
        doc["phase0"] = _PHASE0
    if _LAT0:
        # the per-message latency distribution measured BEFORE the
        # failure (ISSUE 13): a dead round still carries an e2e p99
        doc["latency0"] = _LAT0
    if _LAST_TELEMETRY:
        doc["telemetry"] = _LAST_TELEMETRY
    return json.dumps(doc)


# ---- resumable phase ladder (ISSUE 6 satellite / ROADMAP item 1) -------
# Rounds 3–5 all committed value=0 because ONE fragile relay window had
# to survive the whole phase plan: any late death discarded every phase
# that had already finished. Now each phase's JSON is committed to disk
# the moment it completes (atomic replace), and a restarted bench resumes
# from the checkpoint instead of re-measuring — the phase-0 headline is
# always written first, so a window of MINUTES commits a number.
# Knobs: BENCH_CHECKPOINT (path), BENCH_RESUME=0 (ignore + overwrite).


def _ckpt_path() -> str:
    return os.environ.get(
        "BENCH_CHECKPOINT",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_CHECKPOINT.json"))


def _ckpt_load(sig: dict) -> dict:
    """Completed phases from a previous (dead) run, keyed by phase name
    — only honored when the config signature matches (resuming a 10M
    run's phases into a 100k run would fabricate numbers)."""
    if os.environ.get("BENCH_RESUME", "1") == "0":
        return {}
    try:
        with open(_ckpt_path()) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return {}
    except Exception as e:  # noqa: BLE001 — a corrupt checkpoint (half-
        log(f"bench checkpoint unreadable ({e}); starting fresh")  # writ-
        return {}          # ten pre-atomic-replace crash) is startable
    if doc.get("sig") != sig:
        log("bench checkpoint ignored: config signature changed "
            f"({doc.get('sig')} != {sig})")
        return {}
    phases = doc.get("phases") or {}
    if phases:
        # resumed phases keep their measured wall seconds — the merged
        # JSON's accounting spans the dying run AND its resume
        _PHASE_WALL.update(doc.get("walls") or {})
        # ... and their end-of-phase memory rows (ISSUE 8): the dying
        # run's device memory_stats survive into the merged JSON
        _PHASE_MEM.update(doc.get("mem") or {})
        # likewise the dying run's relay wait (the BENCH_r05 540s):
        # _ckpt_load runs after THIS run's backend probe has already
        # set _RELAY_WAIT_S, so the two accumulate
        global _RELAY_WAIT_S
        _RELAY_WAIT_S += float(doc.get("relay_wait_s") or 0.0)
        log(f"bench resume: phases {sorted(phases)} from "
            f"{_ckpt_path()}")
    return phases


def _ckpt_put(name: str, value, sig: dict, phases: dict) -> None:
    """Commit one completed phase to disk IMMEDIATELY (tmp + atomic
    os.replace — a SIGKILL mid-write can never corrupt the previous
    checkpoint). Errors are never checkpointed: a resumed run retries
    failed phases."""
    phases[name] = value
    path = _ckpt_path()
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"sig": sig, "ts": time.time(),
                       "phases": phases, "walls": _PHASE_WALL,
                       "mem": _PHASE_MEM,
                       "relay_wait_s": round(_RELAY_WAIT_S, 1)}, f)
        os.replace(tmp, path)
    except Exception as e:  # noqa: BLE001 — checkpointing is insurance,
        log(f"bench checkpoint write failed ({e})")  # not a dependency


def _ckpt_clear() -> None:
    """The run completed and printed its merged JSON: the checkpoint has
    served its purpose (leaving it would make the NEXT round resume
    stale phases)."""
    try:
        os.remove(_ckpt_path())
    except OSError:
        pass


def _put_retry(x, tries=4):
    """device_put one array with retry/backoff (relay transfers can flake)."""
    import jax
    last = None
    for t in range(tries):
        try:
            y = jax.device_put(x)
            jax.block_until_ready(y)
            return y
        except Exception as e:  # noqa: BLE001 — relay errors are opaque
            last = e
            log(f"device_put retry {t + 1}/{tries} "
                f"({type(e).__name__}): {str(e)[:200]}")
            time.sleep(1.5 * (t + 1))
    raise last


def device_put_chunked(x, max_bytes=None, tries=4):
    """Upload a large array in row chunks, concatenating on device."""
    import jax
    import jax.numpy as jnp

    if max_bytes is None:
        max_bytes = int(os.environ.get("BENCH_PUT_CHUNK_MB", 64)) << 20
    x = np.asarray(x)
    if x.nbytes <= max_bytes or x.ndim == 0 or x.shape[0] <= 1:
        return _put_retry(x, tries)
    row_bytes = max(1, x.nbytes // x.shape[0])
    rows_per = max(1, max_bytes // row_bytes)
    parts = [_put_retry(x[i:i + rows_per], tries)
             for i in range(0, x.shape[0], rows_per)]
    if len(parts) == 1:
        return parts[0]
    # transiently holds chunks + result (~2x the array) — fine for the
    # <=~200MB tables this path carries on a 16GB chip; the win is that no
    # single relay transfer exceeds the chunk size (round 1 died on one
    # ~800MB device_put)
    out = jnp.concatenate(parts, axis=0)
    jax.block_until_ready(out)
    return out


def put_tree_chunked(tree):
    import jax
    return jax.tree.map(device_put_chunked, tree)


def profile_device_step(run_fn, match_name: str) -> dict:
    """Capture a jax.profiler trace around `run_fn()` and extract the
    on-device execution durations of the jitted step (events named after
    the jitted function on the device tracks) -> device_step_p50/p99_ms.

    This decomposes the relay-inclusive latency into device time vs
    dispatch overhead (round-2 VERDICT item 5: prove or honestly bound
    the p99 criterion). Best-effort: returns {} when the backend has no
    profiler or the trace has no matching device events.
    """
    import glob
    import gzip
    import json
    import shutil
    import tempfile

    import jax

    tmp = tempfile.mkdtemp(prefix="jaxprof-")
    try:
        try:
            jax.profiler.start_trace(tmp)
            run_fn()
        finally:
            try:
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                pass
        durs_by_track: dict = {}
        for path in glob.glob(tmp + "/**/*.trace.json.gz", recursive=True):
            with gzip.open(path, "rt") as f:
                data = json.load(f)
            pids = {}
            for ev in data.get("traceEvents", []):
                if ev.get("ph") == "M" and ev.get("name") == "process_name":
                    pids[ev.get("pid")] = ev.get("args", {}).get("name", "")
            for ev in data.get("traceEvents", []):
                if ev.get("ph") != "X":
                    continue
                name = ev.get("name", "")
                if match_name not in name:
                    continue
                track = pids.get(ev.get("pid"), "")
                durs_by_track.setdefault(track, []).append(
                    ev.get("dur", 0) / 1000.0)        # us -> ms
        if not durs_by_track:
            return {}
        # prefer a device track (TPU/accelerator); fall back to any
        def track_rank(t):
            tl = t.lower()
            if "tpu" in tl or "device" in tl or "xla" in tl and \
                    "host" not in tl:
                return 0
            return 1
        track = sorted(durs_by_track, key=track_rank)[0]
        durs = sorted(durs_by_track[track])
        if not durs:
            return {}
        return {
            "device_step_p50_ms": round(durs[len(durs) // 2], 3),
            "device_step_p99_ms": round(
                durs[min(len(durs) - 1, int(len(durs) * 0.99))], 3),
            "device_step_track": track,
            "device_step_samples": len(durs),
        }
    except Exception as e:  # noqa: BLE001 — never kill the bench
        log(f"device-step profiling unavailable: "
            f"{type(e).__name__}: {str(e)[:120]}")
        return {}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def device_filter_set(subs: int):
    """The reference harness's device/{{id}}/+/{{num}}/# filter set scaled
    to `subs` (emqx_broker_bench.erl:25-34) — the ONE canonical workload
    generator, shared by the main bench and the config-3 suite row so the
    two can never silently measure different workloads."""
    from emqx_tpu.ops import intern as I
    ids = max(64, int(np.sqrt(subs)))
    nums = max(1, subs // ids)
    F = ids * nums
    intern = I.InternTable()
    wd = intern.intern("device")
    id_ids = np.array([intern.intern(f"d{i}") for i in range(ids)], np.int32)
    num_ids = np.array([intern.intern(f"n{n}") for n in range(nums)],
                       np.int32)
    rows = np.zeros((F, 8), np.int32)
    lens = np.full(F, 5, np.int64)
    rows[:, 0] = wd
    rows[:, 1] = np.repeat(id_ids, nums)
    rows[:, 2] = I.PLUS
    rows[:, 3] = np.tile(num_ids, ids)
    rows[:, 4] = I.HASH
    return {"intern": intern, "rows": rows, "lens": lens, "ids": ids,
            "nums": nums, "id_ids": id_ids, "num_ids": num_ids, "wd": wd}


def device_topic_batch(fs: dict, rng, B: int):
    """One Zipf-skewed publish batch; every topic matches exactly one
    filter of device_filter_set (fid = id*nums + num)."""
    intern = fs["intern"]
    x = intern.intern("x")
    tail = intern.intern("t")
    zipf = np.minimum(rng.zipf(1.3, size=B) - 1, fs["ids"] - 1)
    tp = np.zeros((B, 8), np.int32)
    tp[:, 0] = fs["wd"]
    tp[:, 1] = fs["id_ids"][zipf]
    tp[:, 2] = x
    tp[:, 3] = fs["num_ids"][rng.randint(0, fs["nums"], B)]
    tp[:, 4] = tail
    return tp, np.full(B, 5, np.int32)


def make_window_runner(tables, cursors0, strat, stacked,
                       fan_cap: int, slot_cap: int):
    """The ONE fused-window timing kernel, shared by the main bench and
    the config suite (so the two can never measure different work).
    Returns run(n_calls) -> seconds: dispatches the W-fused window
    n_calls times with cursors threaded call-to-call, closed by a single
    scalar readback. Tables/batches ride as jit arguments — closing over
    them would bake the bucket table into the HLO (relay-rejected at
    scale)."""
    import jax
    import jax.numpy as jnp

    from emqx_tpu.models.router_engine import route_window_shapes

    @jax.jit
    def wd(tb, cur, acc, topics, lens_, dollar, hashes):
        new_cur, digests = route_window_shapes(
            tb, cur, topics, lens_, dollar, hashes, strat,
            fanout_cap=fan_cap, slot_cap=slot_cap)
        return new_cur, acc + digests.sum(dtype=jnp.int32)

    def run(n_calls: int) -> float:
        cur = cursors0
        acc = _put_retry(np.int32(0))
        t0 = time.time()
        for _ in range(n_calls):
            cur, acc = wd(tables, cur, acc, *stacked)
        _ = int(np.asarray(acc))  # one scalar D2H closes the window
        return time.time() - t0

    return run


def bench_subtable(F: int, shared_pct: int):
    """The ONE bench subscriber table (one subscriber per filter, the
    first shared_pct%% of filters also in 16-filter/8-member $share
    groups) — shared by run_bench and run_phase0 so the phase-0 number
    is a scaled-down point on the SAME workload curve, never a silently
    different one. Returns (SubTable, n_groups)."""
    from emqx_tpu.ops.fanout import SubTable
    n_shared_filters = F * shared_pct // 100
    sub_start = np.arange(F + 1, dtype=np.int32)
    sub_row = np.arange(F, dtype=np.int32)
    sub_opts = np.ones(F, np.int8)
    group_of = np.arange(n_shared_filters, dtype=np.int32) // 16
    n_groups = max(1, int(group_of.max(initial=0)) + 1)
    fs_start = np.zeros(F + 1, np.int32)
    fs_start[1:n_shared_filters + 1] = 1
    np.cumsum(fs_start, out=fs_start)
    fs_slot = group_of if n_shared_filters else np.full(1, -1, np.int32)
    shared_start = np.arange(n_groups + 1, dtype=np.int32) * 8
    shared_row = F + np.arange(n_groups * 8, dtype=np.int32)
    shared_opts = np.ones(n_groups * 8, np.int8)
    return SubTable(sub_start, sub_row, sub_opts, fs_start, fs_slot,
                    shared_start, shared_row, shared_opts), n_groups


def run_phase0(shared_pct: int = 50) -> dict:
    """Minutes-scale incremental headline (VERDICT r5 top-next): a
    small-but-real fused-window measurement a SHORT relay window can
    commit — table build + upload + one compile + a timed window, no
    tuning sweeps, no profiling, no config suites. The full bench's
    phase plan needs ~2 hours of hardware; three consecutive rounds
    died with `value=0` because the window closed mid-plan. This number
    lands on stdout (and in MEASURED via tools/relay_watcher.py) within
    the first ~2 minutes, so a dying window still records a measured
    rate instead of nothing.

    Same workload generator (device_filter_set) and the same fused
    timing kernel (make_window_runner) as the main bench — a scaled-down
    point on the same curve, labeled with its own metric name so it can
    never be mistaken for the headline scale.
    """
    import jax

    from emqx_tpu.models.router_engine import ShapeRouterTables
    from emqx_tpu.ops.shapes import build_shape_tables
    from emqx_tpu.ops.shared import STRATEGY_ROUND_ROBIN

    t_start = time.time()
    subs = int(os.environ.get("BENCH_PHASE0_SUBS", 100_000))
    B = int(os.environ.get("BENCH_PHASE0_BATCH", 16384))
    window = int(os.environ.get("BENCH_PHASE0_WINDOW", 8))
    fs = device_filter_set(subs)
    rows, lens = fs["rows"], fs["lens"]
    F = fs["ids"] * fs["nums"]
    shapes = build_shape_tables(rows, lens)

    subs_tbl, n_groups = bench_subtable(F, shared_pct)
    tables = put_tree_chunked(
        ShapeRouterTables(shapes=shapes, subs=subs_tbl))
    jax.block_until_ready(tables)
    cursors0 = _put_retry(np.zeros(n_groups, np.int32))
    strat = _put_retry(np.int32(STRATEGY_ROUND_ROBIN))

    import jax.numpy as jnp
    rng = np.random.RandomState(7)
    FUSE = min(4, window)
    staged = []
    for _ in range(FUSE):
        tp, tl = device_topic_batch(fs, rng, B)
        staged.append((_put_retry(tp), _put_retry(tl),
                       _put_retry(np.zeros(B, bool)),
                       _put_retry(rng.randint(0, 1 << 30, B)
                                  .astype(np.int32))))
    stacked = tuple(jnp.stack([staged[k][i] for k in range(FUSE)])
                    for i in range(4))
    runner = make_window_runner(tables, cursors0, strat, stacked,
                                int(os.environ.get("BENCH_FANOUT_CAP", 4)),
                                int(os.environ.get("BENCH_SLOT_CAP", 2)))
    runner(1)                       # compile
    window = max(FUSE, window - window % FUSE)
    dt = runner(window // FUSE)
    mps = B * window / dt
    log(f"phase0: {mps / 1e6:.2f}M topic-matches/s "
        f"({window} batches of {B} at {subs} subs, "
        f"{time.time() - t_start:.0f}s total)")
    return {
        "metric": "topic_matches_per_sec_phase0",
        "value": round(mps),
        "unit": "topic-matches/s",
        "subs": subs,
        "batch": B,
        "window": window,
        "fuse": FUSE,
        "elapsed_s": round(time.time() - t_start, 1),
        "note": ("phase-0 incremental headline at reduced scale; the "
                 "main metric row is the authoritative number when "
                 "present"),
    }


def run_bench(subs: int, B: int, window: int, shared_pct: int) -> dict:
    import jax

    from emqx_tpu.models.router_engine import (ShapeRouterTables,
                                               route_step_shapes)
    from emqx_tpu.ops.shapes import build_shape_tables
    from emqx_tpu.ops.shared import STRATEGY_ROUND_ROBIN

    log(f"bench: subs={subs} batch={B} window={window} shared={shared_pct}% "
        f"device={jax.devices()[0]}")

    # --- filter set: device/{id}/+/{num}/#  ------------------------------
    fs = device_filter_set(subs)
    intern, rows, lens = fs["intern"], fs["rows"], fs["lens"]
    ids, nums = fs["ids"], fs["nums"]
    F = ids * nums

    t0 = time.time()
    shapes = build_shape_tables(rows, lens)
    t_build = time.time() - t0
    table_mb = sum(np.asarray(v).nbytes for v in shapes) / 1e6
    log(f"shape-table build: {t_build:.1f}s, shapes={int(shapes.n_shapes)}, "
        f"buckets={shapes.buckets.shape[0]}, {table_mb:.0f}MB")

    # --- subscriber table ------------------------------------------------
    subs_tbl, n_groups = bench_subtable(F, shared_pct)

    t0 = time.time()
    tables = put_tree_chunked(ShapeRouterTables(shapes=shapes, subs=subs_tbl))
    jax.block_until_ready(tables)
    log(f"upload: {time.time() - t0:.1f}s")
    cursors0 = _put_retry(np.zeros(n_groups, np.int32))
    strat = _put_retry(np.int32(STRATEGY_ROUND_ROBIN))

    # --- pre-staged publish batches (Zipf-skewed device ids) -------------
    rng = np.random.RandomState(7)
    staged = []
    for k in range(8):
        tp, tl = device_topic_batch(fs, rng, B)
        staged.append((_put_retry(tp),
                       _put_retry(tl),
                       _put_retry(np.zeros(B, bool)),
                       _put_retry(rng.randint(0, 1 << 30, B)
                                  .astype(np.int32))))

    # capacity classes sized to the workload (the broker's device_engine
    # quantizes the same way; overflow topics fall back to the host):
    # every bench topic matches exactly one filter -> 1 normal subscriber
    # + at most 1 shared slot. Generic caps of 16/4 paid 4-16x the
    # bandwidth in fan-out/shared lanes for nothing.
    FAN_CAP = int(os.environ.get("BENCH_FANOUT_CAP", 4))
    SLOT_CAP = int(os.environ.get("BENCH_SLOT_CAP", 2))

    # --- rank-block self-tune (accelerators only) ------------------------
    # The sort-free rank kernel's block width is hardware-specific and the
    # driver's round-end bench may be the only hardware window we get, so
    # pick it HERE, before the main step traces (set_rank_block only
    # affects programs traced after it). Explicit EMQX_TPU_RANK_BLOCK or
    # BENCH_TUNE_RANK=0 skips the sweep.
    import functools

    import jax.numpy as jnp

    from emqx_tpu.ops import shared as SH
    rank_tune: dict = {}
    tune_mode = os.environ.get("BENCH_TUNE_RANK", "1")
    if ((jax.default_backend() != "cpu" or tune_mode == "force")
            and "EMQX_TPU_RANK_BLOCK" not in os.environ
            and tune_mode != "0"):
        from emqx_tpu.ops.fanout import shared_slots
        from emqx_tpu.ops.shapes import shape_match

        @jax.jit
        def _mk_sids(tb, t, l, d):
            r = shape_match(tb.shapes, t, l, d)
            s, _ = shared_slots(tb.subs, r.matches, slot_cap=SLOT_CAP)
            return s

        sids_st = [_mk_sids(tables, *staged[i][:3]) for i in range(4)]
        jax.block_until_ready(sids_st)
        best = None
        for blk in (512, 1024, 2048):
            f = jax.jit(functools.partial(
                SH._rank_and_occur_blocked, n_slots=n_groups, block=blk))

            def _run(n):
                acc = _put_retry(np.int32(0))
                t0 = time.time()
                for i in range(n):
                    r_, oc_ = f(sids_st[i % 4])
                    acc = acc + r_.sum(dtype=jnp.int32) \
                        + oc_.sum(dtype=jnp.int32)
                _ = int(np.asarray(acc))
                return time.time() - t0
            try:
                _run(2)
                dt = _run(8) / 8 * 1000
            except Exception as e:  # noqa: BLE001 — a failed width is skipped
                log(f"rank tune block={blk} failed: {type(e).__name__}")
                continue
            rank_tune[str(blk)] = round(dt, 2)
            log(f"rank tune block={blk}: {dt:.2f} ms/batch")
            if best is None or dt < rank_tune[str(best)]:
                best = blk
        if best is not None:
            SH.set_rank_block(best)
            log(f"rank block -> {best}")

    # --- fold backend chosen by DATA, before the main step traces --------
    # (VERDICT r4 item 8). Both folds are oracle-tested bit-identical, so
    # this is purely a measured race: whichever wins the match-only window
    # on THIS hardware becomes the backend the serving step traces with.
    pallas_fields = {}
    try:
        from emqx_tpu.ops import shapes as SHP
        from emqx_tpu.ops.shapes import shape_match, shape_match_pallas

        # bit-identical cross-check ALWAYS runs (an explicitly-forced
        # EMQX_TPU_FOLD=pallas must still be verified in the JSON)
        tb_, lb_, db_, _ = staged[0]
        rx = shape_match(tables.shapes, tb_, lb_, db_)
        rp = shape_match_pallas(tables.shapes, tb_, lb_, db_)
        same = bool((np.asarray(rx.matches)
                     == np.asarray(rp.matches)).all())
        explicit = os.environ.get("EMQX_TPU_FOLD")
        pallas_fields = {"pallas_bit_identical": same,
                         "fold_backend": explicit or "xla"}

        if (jax.default_backend() != "cpu" and not explicit
                and os.environ.get("BENCH_TUNE_FOLD", "1") != "0"):
            def _match_window(fn, n=16):
                acc = _put_retry(np.int32(0))
                t0 = time.time()
                for i in range(n):
                    t_, l_, d_, _ = staged[i % 8]
                    r_ = fn(tables.shapes, t_, l_, d_)
                    acc = acc + r_.matches.sum(dtype=np.int32)
                _ = int(np.asarray(acc))
                return B * n / (time.time() - t0)

            _match_window(shape_match, 2)          # warm
            _match_window(shape_match_pallas, 2)
            xla_ps = _match_window(shape_match)
            pallas_ps = _match_window(shape_match_pallas)
            winner = "pallas" if (same and pallas_ps > xla_ps) else "xla"
            # clears shape_match's jit cache, so the serving step's
            # trace below really picks the winner up; effective=False
            # means the clear failed and already-traced shapes may still
            # run the loser (ISSUE 2 satellite: record it, don't guess)
            SHP.set_fold_backend(winner)
            pallas_fields.update({
                "match_xla_per_s": round(xla_ps),
                "match_pallas_per_s": round(pallas_ps),
                "fold_backend": winner,
                "fold_backend_effective": SHP.fold_backend_effective(),
            })
            log(f"fold backends: xla {xla_ps / 1e6:.1f}M/s, "
                f"pallas {pallas_ps / 1e6:.1f}M/s, bit-identical={same} "
                f"-> serving step uses {winner}")
    except Exception as e:  # noqa: BLE001 — never kills the core run
        log(f"fold tune failed: {type(e).__name__}: {e}")
        pallas_fields = {
            "pallas_error": f"{type(e).__name__}: {str(e)[:160]}"}

    def step(batch, cur):
        return route_step_shapes(tables, cur, *batch, strat,
                                 fanout_cap=FAN_CAP, slot_cap=SLOT_CAP)

    # warmup / compile + correctness sanity (this flips the relay into
    # sync mode — all timing below is honest)
    r = step(staged[0], cursors0)
    jax.block_until_ready(r)
    mc = int(np.asarray(r.match_counts).sum())
    fc = int(np.asarray(r.fan_counts).sum())
    sc = int((np.asarray(r.shared_rows) >= 0).sum())
    ov = int(np.asarray(r.overflow).sum())
    log(f"sanity: matches={mc}/{B}, fan={fc}, shared={sc}, overflow={ov}")
    assert mc == B, "every bench topic must match exactly one filter"

    # sync round-trip latency distribution (single blocked batches) — the
    # BASELINE.md p99 <2ms criterion is judged on this per-batch latency
    sync = []
    for k in range(30):
        t0 = time.time()
        r = step(staged[k % 8], cursors0)
        _ = np.asarray(r.match_counts)
        sync.append(time.time() - t0)
    sync.sort()
    p50_ms = sync[len(sync) // 2] * 1000
    p99_ms = sync[min(len(sync) - 1, int(len(sync) * 0.99))] * 1000
    log(f"sync round-trip: p50 {p50_ms:.1f}ms p99 {p99_ms:.1f}ms/batch "
        f"(includes relay HTTP dispatch overhead)")

    # pipelined window closed by one scalar readback — sustained device
    # throughput. A digest reduction over every output array forces the full
    # routing computation; delivery arrays stay on device because this
    # relay's D2H path (~10 MB/s HTTP) is a dev-harness artifact, not the
    # production consumer (co-located PCIe host).
    import jax.numpy as jnp

    # ONE dispatch per batch: the digest reduction rides inside the same
    # jitted program as the route step (a separate digest dispatch per
    # iteration doubled the relay's per-call overhead in round 2's first
    # measurement)
    from emqx_tpu.models.router_engine import route_digest

    @jax.jit
    def step_digest(tb, cur, acc, topics, lens_, dollar, hashes):
        # tables MUST be an argument: closing over them would bake 200MB
        # of bucket constants into the HLO (the relay rejects the upload)
        r = route_step_shapes(tb, cur, topics, lens_, dollar, hashes,
                              strat, fanout_cap=FAN_CAP,
                              slot_cap=SLOT_CAP)
        return r.new_cursors, acc + route_digest(r)

    # W-fused window: ONE dispatch routes W whole batches (lax.scan inside
    # the jitted program, models/router_engine.route_window_shapes). The
    # per-call dispatch floor — visible in round 2 as the gap between the
    # match fold's arithmetic rate and the match-only call rate — is paid
    # once per W batches. Oracle-tested bit-identical to sequential steps.
    FUSE = max(1, min(int(os.environ.get("BENCH_FUSE", 8)), len(staged),
                      window))
    if window % FUSE:
        log(f"window {window} rounded to {window - window % FUSE} "
            f"(multiple of fuse={FUSE})")
    stacked = tuple(jnp.stack([staged[k][i] for k in range(FUSE)])
                    for i in range(4))

    runner = make_window_runner(tables, cursors0, strat, stacked,
                                FAN_CAP, SLOT_CAP)

    def run_window(n):
        return runner(max(1, n // FUSE))

    window = max(FUSE, window - window % FUSE)
    run_window(FUSE)  # warm
    total = run_window(window)
    per_batch = total / window
    matches_per_sec = B * window / total
    log(f"pipelined: {per_batch * 1000:.2f}ms/batch amortized, "
        f"{matches_per_sec / 1e6:.1f}M topic-matches/s "
        f"({window} batches of {B}, {FUSE} per dispatch)")

    # device-only step time via jax.profiler (VERDICT item 5): decomposes
    # the relay-inclusive sync latency into device execution vs dispatch
    # overhead. Best-effort — {} when the backend can't trace.
    def run_single_steps(n=12):
        cur = cursors0
        acc = _put_retry(np.int32(0))
        for i in range(n):
            cur, acc = step_digest(tables, cur, acc, *staged[i % 8])
        _ = int(np.asarray(acc))

    run_single_steps(2)   # compile outside the trace
    step_profile = profile_device_step(run_single_steps, "step_digest")
    if step_profile:
        log(f"device step: p50 {step_profile['device_step_p50_ms']}ms "
            f"p99 {step_profile['device_step_p99_ms']}ms on "
            f"{step_profile['device_step_track']!r} — relay dispatch adds "
            f"~{p50_ms - step_profile['device_step_p50_ms']:.1f}ms to the "
            f"sync round-trip")

    target = 5_000_000.0
    return {
        **pallas_fields,
        **step_profile,
        "metric": f"topic_matches_per_sec_at_{subs // 1_000_000}M_subs"
                  if subs >= 1_000_000 else
                  f"topic_matches_per_sec_at_{subs // 1000}k_subs",
        "value": round(matches_per_sec),
        "unit": "topic-matches/s",
        "vs_baseline": round(matches_per_sec / target, 2),
        "per_batch_ms": round(per_batch * 1000, 2),
        "sync_p50_ms": round(p50_ms, 1),
        "sync_p99_ms": round(p99_ms, 1),
        # ISSUE 13 satellite: the sync numbers above are WINDOW
        # granularity and include relay HTTP dispatch overhead (the
        # r02 contamination); the per-message, relay-free route tail is
        # the latency observatory's ingress→routed p99, reported by
        # the e2e/latency0 phase rows and summarized in route_latency
        "sync_p99_includes_relay_overhead": True,
        "batch": B,
        "subs": subs,
        "fuse": FUSE,
        "rank_block": SH._RANK_BLOCK,
        **({"rank_tune_ms": rank_tune} if rank_tune else {}),
        "table_build_s": round(t_build, 1),
    }


def run_baseline_configs(B: int, window: int) -> dict:
    """BASELINE.md configs 1-3 at their stated scales, each as a fused
    window over its own compiled tables (config 4 IS the main bench;
    config 5 needs a 2-node cluster and is covered functionally by
    tests/test_cluster.py + the retainer tests, not this chip bench).

    1: 1k exact-match subs, single-level topics
    2: 100k subs with '+' wildcards, 6-level hierarchy
    3: 1M subs mixed '+'/'#', Zipf-skewed publish
    """
    import jax
    import jax.numpy as jnp

    from emqx_tpu.models.router_engine import ShapeRouterTables
    from emqx_tpu.ops import intern as I
    from emqx_tpu.ops.fanout import SubTable
    from emqx_tpu.ops.shapes import build_shape_tables
    from emqx_tpu.ops.shared import STRATEGY_ROUND_ROBIN

    rng = np.random.RandomState(13)
    out = {}

    def one(name, rows, lens, topic_of):
        F = len(lens)
        shapes = build_shape_tables(rows, lens)
        subs_tbl = SubTable(
            sub_start=np.arange(F + 1, dtype=np.int32),
            sub_row=np.arange(F, dtype=np.int32),
            sub_opts=np.ones(F, np.int8),
            fs_start=np.zeros(F + 1, np.int32),
            fs_slot=np.full(1, -1, np.int32),
            shared_start=np.zeros(2, np.int32),
            shared_row=np.full(1, -1, np.int32),
            shared_opts=np.zeros(1, np.int8))
        tables = put_tree_chunked(
            ShapeRouterTables(shapes=shapes, subs=subs_tbl))
        jax.block_until_ready(tables)
        L = rows.shape[1]
        W = 4
        tp = np.zeros((W, B, L), np.int32)
        tl = np.zeros((W, B), np.int32)
        for w in range(W):
            enc, ls = topic_of(rng, B)
            tp[w, :, :enc.shape[1]] = enc
            tl[w] = ls
        t4 = _put_retry(tp)
        l4 = _put_retry(tl)
        d4 = _put_retry(np.zeros((W, B), bool))
        h4 = _put_retry(rng.randint(0, 1 << 30, (W, B)).astype(np.int32))
        cur = _put_retry(np.zeros(1, np.int32))
        strat = _put_retry(np.int32(STRATEGY_ROUND_ROBIN))
        run = make_window_runner(tables, cur, strat, (t4, l4, d4, h4),
                                 fan_cap=4, slot_cap=2)

        # sanity: every generated topic must match exactly one filter
        from emqx_tpu.ops.shapes import shape_match
        mc = int(np.asarray(shape_match(
            tables.shapes, t4[0], l4[0], d4[0]).counts).sum())
        assert mc == B, f"config {name}: {mc}/{B} topics matched"

        run(1)   # compile
        n_calls = max(1, window // W)
        dt = run(n_calls)
        per_s = B * W * n_calls / dt
        out[name] = {"subs": F, "matches_per_s": round(per_s)}
        log(f"config {name}: {per_s / 1e6:.1f}M matches/s at {F} subs")

    # config 1: 1k exact-match, single-level
    intern = I.InternTable()
    F1 = 1000
    w1 = np.array([intern.intern(f"t{i}") for i in range(F1)], np.int32)
    rows = w1[:, None]
    lens = np.ones(F1, np.int64)

    def topics1(rng, B):
        pick = rng.randint(0, F1, B)
        return w1[pick][:, None], np.ones(B, np.int32)

    one("1_exact_1k", rows, lens, topics1)

    # config 2: 100k '+'-wildcard subs, 6-level hierarchy
    # filter: a/{i}/+/b/{j}/+  — two '+' per filter, 6 levels
    intern = I.InternTable()
    n_i, n_j = 400, 250
    F2 = n_i * n_j
    wa = intern.intern("a")
    wb = intern.intern("b")
    wi = np.array([intern.intern(f"i{i}") for i in range(n_i)], np.int32)
    wj = np.array([intern.intern(f"j{j}") for j in range(n_j)], np.int32)
    rows = np.zeros((F2, 6), np.int32)
    rows[:, 0] = wa
    rows[:, 1] = np.repeat(wi, n_j)
    rows[:, 2] = I.PLUS
    rows[:, 3] = wb
    rows[:, 4] = np.tile(wj, n_i)
    rows[:, 5] = I.PLUS
    lens = np.full(F2, 6, np.int64)
    wx = intern.intern("x")

    def topics2(rng, B):
        enc = np.zeros((B, 6), np.int32)
        enc[:, 0] = wa
        enc[:, 1] = wi[rng.randint(0, n_i, B)]
        enc[:, 2] = wx
        enc[:, 3] = wb
        enc[:, 4] = wj[rng.randint(0, n_j, B)]
        enc[:, 5] = wx
        return enc, np.full(B, 6, np.int32)

    one("2_plus_100k", rows, lens, topics2)

    # config 3: 1M mixed '+'/'#', Zipf-skewed publish — the canonical
    # device_filter_set workload at 1M (same generator as the main bench)
    fs3 = device_filter_set(1_000_000)

    def topics3(rng, B):
        return device_topic_batch(fs3, rng, B)

    one("3_mixed_1M_zipf", fs3["rows"], fs3["lens"], topics3)
    return out


def run_config5(n_routes: int, n_retained: int) -> dict:
    """BASELINE config 5: 2-node cluster route-sync + retainer replay
    burst, host-side (no chip involved — this measures the replication
    and retained-message planes the reference implements with replicated
    mnesia, emqx_router.erl:251-303 / emqx_retainer_mnesia.erl:49-55).

    Reported rows:
      route_sync_per_s   bulk route-add convergence rate onto the peer
      route_sync_p50/p99_ms   single route add → visible-on-peer latency
      replay_per_s       retained replay burst rate to a late subscriber
      stated_shape       the BASELINE row-5 10M shape: measured per-route
                         cost × 10M as extrapolated wall time

    Scales via BENCH_C5_ROUTES / BENCH_C5_RETAINED (defaults 1M / 100k).
    The stated shape is 10M: that run is TIME-bound, not memory-bound —
    replication is batched (store.add_many: one RPC frame per 4096
    routes) and scale-linear (no resync storms; anti-entropy only fires
    on real loss), so the 1M default measures the same per-route cost
    the 10M shape pays; set BENCH_C5_ROUTES=10000000 to run it in full
    (≈10-12 min on one core; the section timeout scales with the
    requested count).
    """
    import asyncio

    async def go():
        from emqx_tpu.apps.retainer import Retainer
        from emqx_tpu.broker.connection import Listener
        from emqx_tpu.broker.node import Node
        from emqx_tpu.client import Client
        from emqx_tpu.cluster import ClusterNode
        from emqx_tpu.cluster.cluster import T_ROUTE

        nodes, clusters = [], []
        for i in range(2):
            node = Node(use_device=False, name=f"b{i}@127.0.0.1")
            # 1s beats: on one core a bulk route burst can hold the loop
            # for ~100ms stretches; 0.5s beats with a 2×beat timeout
            # produced false nodedowns mid-bench → purge+resync storms
            cn = ClusterNode(node, port=0, heartbeat_s=1.0)
            await cn.start()
            nodes.append(node)
            clusters.append(cn)
        await clusters[1].join(*clusters[0].address)
        out = {}
        try:
            b0 = nodes[0].broker
            tab1 = clusters[1].store.table(T_ROUTE)

            # --- bulk route-sync: n_routes wildcard filters on node 0,
            # measure convergence onto node 1's replicated table
            class Sink:
                def deliver(self, tf, msg):
                    return True

            sink = Sink()
            sid = b0.register(sink, "c5-sink")
            base = tab1.count()
            t0 = time.perf_counter()
            for i in range(n_routes):
                b0.subscribe(sid, f"c5/d{i}/+/t/#")
                if i % 256 == 255:
                    # frequent yields keep heartbeats + the replication
                    # drain timely on one core
                    await asyncio.sleep(0)
            await clusters[0].flush()
            deadline = time.perf_counter() + max(120, n_routes // 5000)
            while time.perf_counter() < deadline:
                if tab1.count() - base >= n_routes:
                    break
                await asyncio.sleep(0.05)
            dt = time.perf_counter() - t0
            synced = tab1.count() - base
            out["route_sync"] = {
                "routes": int(synced),
                "per_s": round(synced / dt),
                "wall_s": round(dt, 2),
            }
            # BASELINE row 5's stated 10M shape at the measured linear
            # per-route cost (run it in full with BENCH_C5_ROUTES=10000000)
            out["stated_shape"] = {
                "routes": 10_000_000,
                "extrapolated_wall_s": round(10_000_000 * dt / max(1, synced)),
                "measured_at": int(synced),
            }
            log(f"config5 route-sync: {synced} routes -> peer in "
                f"{dt:.2f}s ({synced / dt / 1e3:.1f}k/s; 10M shape "
                f"≈ {out['stated_shape']['extrapolated_wall_s']}s)")

            # --- single-add propagation latency (the visible tail an
            # individual SUBSCRIBE pays before cluster-wide matching)
            lats = []
            lost = 0
            for i in range(100):
                f = f"c5lat/{i}/+"
                t1 = time.perf_counter()
                b0.subscribe(sid, f)
                await clusters[0].flush()
                # bounded per-add: one lost replication event must not
                # spin this loop into the section watchdog and discard
                # the rows already measured
                lim = t1 + 5.0
                while not tab1.lookup(f):
                    if time.perf_counter() > lim:
                        lost += 1
                        break
                    await asyncio.sleep(0)
                else:
                    lats.append(time.perf_counter() - t1)
            lats.sort()
            if lats:
                out["route_sync_p50_ms"] = round(
                    lats[len(lats) // 2] * 1000, 2)
                out["route_sync_p99_ms"] = round(
                    lats[min(len(lats) - 1,
                             int(len(lats) * 0.99))] * 1000, 2)
                log(f"config5 single-add: "
                    f"p50 {out['route_sync_p50_ms']}ms "
                    f"p99 {out['route_sync_p99_ms']}ms")
            if lost:
                out["route_sync_lost"] = lost
                log(f"config5 single-add: {lost} adds never replicated")

            # --- retainer replay burst: n_retained retained messages,
            # then a late wildcard subscriber over a REAL socket replays
            # them all
            ret = nodes[0].register_app(Retainer(nodes[0]).load())
            lst = Listener(nodes[0], bind="127.0.0.1", port=0)
            await lst.start()
            pub = Client(port=lst.port, clientid="c5-pub")
            await pub.connect()
            for i in range(n_retained):
                await pub.publish(f"c5r/{i % 64}/k{i}", b"retained-%d" % i,
                                  qos=0, retain=True)
                if i % 512 == 511:
                    await asyncio.sleep(0)
            # settle: retained table write-behind
            for _ in range(600):
                if len(ret.storage) >= n_retained:
                    break
                await asyncio.sleep(0.05)
            sub = Client(port=lst.port, clientid="c5-sub")
            await sub.connect()
            t2 = time.perf_counter()
            await sub.subscribe("c5r/#", qos=0, timeout=60)
            got = 0
            deadline = time.perf_counter() + 120
            while got < n_retained and time.perf_counter() < deadline:
                try:
                    await sub.recv(timeout=5)
                    got += 1
                except asyncio.TimeoutError:
                    break
            dt2 = time.perf_counter() - t2
            out["retainer_replay"] = {
                "retained": int(got),
                "per_s": round(got / dt2) if dt2 > 0 else 0,
                "wall_s": round(dt2, 2),
            }
            log(f"config5 replay: {got}/{n_retained} retained in "
                f"{dt2:.2f}s ({got / max(dt2, 1e-9) / 1e3:.1f}k/s)")
            await pub.disconnect()
            await sub.disconnect()
            await lst.stop()
        finally:
            for cn in clusters:
                try:
                    await cn.stop()
                except Exception:   # noqa: BLE001 — teardown best-effort
                    pass
        return out

    return asyncio.run(go())


def run_e2e(n_filters: int, n_sub_conns: int, n_pub_conns: int,
            msgs_per_pub: int, use_device: bool) -> dict:
    """End-to-end PUBLISH→deliver over real TCP sockets, at BASELINE
    config 4's workload SHAPE (scaled): `BENCH_E2E_SHARED_PCT` (default
    50) percent of the wildcard filters are owned by 2-member
    $share/bg/... groups (round-robin fan-out across different
    subscriber connections — reference semantics emqx_shared_sub.erl:
    239-283), the rest are plain subscriptions; publishes carry a QoS
    mix (every 4th is QoS1, pipelined PUBACKs). Each publish matches
    exactly one filter, and a shared match delivers to exactly one
    member, so delivered == sent checks exactly-once end to end.
    Throughput = messages delivered to subscriber sockets / wall time.
    Exercises the full serving path: frame parse → channel → publish
    batcher → fused device route step (with on-device shared picks) →
    RouteResult consumption → session → serialize → socket.
    """
    import asyncio

    node_box: dict = {}

    async def go():
        from emqx_tpu.broker.connection import Listener
        from emqx_tpu.broker.node import Node
        from emqx_tpu.client import Client

        # micro-batch window ladder (BASELINE p99 criterion tuning):
        # BENCH_WINDOW_US overrides the 200µs default
        conf = {}
        wus = os.environ.get("BENCH_WINDOW_US")
        if wus:
            conf = {"broker": {"batch_window_us": int(wus)}}
        node = node_box["node"] = Node(conf or None, use_device=use_device)
        lst = Listener(node, bind="127.0.0.1", port=0)
        await lst.start()
        from emqx_tpu.mqtt import packet as P

        shared_pct = int(os.environ.get("BENCH_E2E_SHARED_PCT", 50))
        ids = max(8, int(np.sqrt(n_filters)))
        nums = max(1, n_filters // ids)

        def is_shared(i: int, n: int) -> bool:
            return (i * nums + n) % 100 < shared_pct

        subs = []
        t0 = time.time()
        opts0 = P.SubOpts(qos=0)
        opts1 = P.SubOpts(qos=1)
        n_shared = 0
        for c in range(n_sub_conns):
            cl = Client(port=lst.port, clientid=f"esub{c}")
            await cl.connect()
            batch: list = []
            # plain filters owned by this conn + the SECOND membership of
            # the previous conn's shared groups (2 members per group, on
            # different sockets, so round robin alternates sockets)
            for cc, second in ((c, False),
                               ((c - 1) % n_sub_conns, True)):
                for i in range(cc, ids, n_sub_conns):
                    for n in range(nums):
                        f = f"device/d{i}/+/n{n}/#"
                        if is_shared(i, n):
                            n_shared += not second
                            batch.append((f"$share/bg/{f}", opts1))
                        elif not second:
                            batch.append((f, opts0))
            for k in range(0, len(batch), 512):
                await cl.subscribe(batch[k:k + 512], timeout=30)
            subs.append(cl)
        log(f"e2e: {ids * nums} filters ({n_shared} in 2-member shared "
            f"groups) over {n_sub_conns} sub conns "
            f"in {time.time() - t0:.1f}s (device={use_device})")

        pubs = []
        for c in range(n_pub_conns):
            cl = Client(port=lst.port, clientid=f"epub{c}")
            await cl.connect()
            pubs.append(cl)

        # warmup: compile the route step for this capacity class before
        # the timed window, then drain the warmup deliveries
        for k in range(64):
            await pubs[0].publish(f"device/d0/x/n{k % nums}/t", b"w", qos=0)
        for _ in range(200):
            await asyncio.sleep(0.05)
            if sum(cl.messages.qsize() for cl in subs) >= 64:
                break
        for cl in subs:
            while not cl.messages.empty():
                cl.messages.get_nowait()
        if node.device_engine is not None:
            # compile the full-size batch class before the timed window
            from emqx_tpu.broker.message import make
            warm = [make("w", 0, "warmup/none/t", b"") for _ in range(1024)]
            node.device_engine.route_batch(warm)
            # ... and wait for the background window-class warm: its
            # GIL-holding traces bill to setup here, exactly as a
            # production broker warms before taking peak traffic (only
            # shapes-backend snapshots ever fuse — a trie backend would
            # spin this loop to its timeout for nothing)
            eng = node.device_engine
            if eng._built is not None and eng._built.backend == "shapes":
                for _ in range(1200):
                    if eng.max_fuse() > 1:
                        break
                    await asyncio.sleep(0.05)

        total = n_pub_conns * msgs_per_pub
        t0 = time.time()

        # event-loop responsiveness during routing (round-2 weak #3: the
        # serving path must not stall the loop): sample scheduling jitter
        # while the flood runs
        jitter: list[float] = []

        async def heartbeat():
            while True:
                h0 = time.perf_counter()
                await asyncio.sleep(0.005)
                jitter.append(time.perf_counter() - h0 - 0.005)

        hb = asyncio.get_running_loop().create_task(heartbeat())

        # PUBLISH→deliver latency measured at the CLIENT (BASELINE.md's
        # p99<2ms criterion end to end): every payload carries its send
        # perf_counter; drainers record the delta on arrival
        import struct as _struct
        delivered_n = [0]
        lat: list[float] = []

        async def drain(cl):
            while True:
                m = await cl.messages.get()
                delivered_n[0] += 1
                if len(m.payload) == 8:
                    lat.append(time.perf_counter()
                               - _struct.unpack("d", m.payload)[0])

        drainers = [asyncio.get_running_loop().create_task(drain(cl))
                    for cl in subs]

        async def flood(cl, seed, n_msgs):
            # QoS mix: every 4th publish is QoS1 with a PIPELINED ack
            # (bounded outstanding window) — an awaited round trip per
            # message would serialize the flood on the batcher window
            r = np.random.RandomState(seed)
            acks = []
            for k in range(n_msgs):
                i = int(r.randint(0, ids))
                n = int(r.randint(0, nums))
                fut = cl.publish_start(
                    f"device/d{i}/x/n{n}/t",
                    _struct.pack("d", time.perf_counter()),
                    qos=1 if k % 4 == 0 else 0)
                if fut is not None:
                    acks.append(fut)
                if len(acks) >= 256:
                    await _await_acks(acks)
                if cl.needs_drain:
                    # qos-0 pipeline contract (client.publish_start):
                    # drain every N messages so the transport buffer
                    # stays bounded — the flood's backpressure point
                    await cl.drain()
                if k % 64 == 63:
                    # independent of drain(): below the transport
                    # high-water mark drain() returns without
                    # suspending, so this is the loop's guaranteed
                    # yield (let the batcher drain)
                    await asyncio.sleep(0)
            await _await_acks(acks)

        async def _await_acks(acks):
            # bounded: one lost PUBACK must degrade the number, not hang
            # the whole measurement window (the bench would be SIGKILLed
            # with no JSON — the exact failure mode this round fixes)
            if acks:
                try:
                    await asyncio.wait_for(
                        asyncio.gather(*acks, return_exceptions=True), 30)
                except asyncio.TimeoutError:
                    log("e2e: PUBACK wait timed out; continuing")
                acks.clear()

        try:
            await asyncio.gather(*[flood(cl, 100 + c, msgs_per_pub)
                                   for c, cl in enumerate(pubs)])
            # drain: wait until all deliveries arrive (bounded)
            deadline = time.time() + 60
            while time.time() < deadline:
                if delivered_n[0] >= total:
                    break
                await asyncio.sleep(0.05)
        finally:
            hb.cancel()
        dt = time.time() - t0
        delivered = delivered_n[0]
        main_lat = sorted(lat)
        # snapshot the batcher reservoir BEFORE the ladder mixes windows
        route_lat = (node.publish_batcher.lat_percentiles()
                     if node.publish_batcher else None)

        def pct_of(ls, p):
            return round(ls[min(len(ls) - 1, int(len(ls) * p))]
                         * 1000, 2) if ls else None

        # window ladder (BASELINE p99 criterion): re-run a shorter flood
        # at descending micro-batch windows ON THE SAME node/subscriptions
        # to find the tail-vs-throughput knee without re-paying setup
        ladder_rows = []
        if use_device and node.publish_batcher is not None \
                and os.environ.get("BENCH_E2E_LADDER", "1") != "0":
            for wus_i in (200, 100, 50, 25):
                node.publish_batcher.window_s = wus_i / 1e6
                lat.clear()
                base = delivered_n[0]
                n_l = max(64, msgs_per_pub // 4)
                lt0 = time.time()
                await asyncio.gather(*[flood(cl, 7000 + wus_i + c, n_l)
                                       for c, cl in enumerate(pubs)])
                ldeadline = time.time() + 30
                want_l = base + n_l * len(pubs)
                while time.time() < ldeadline:
                    if delivered_n[0] >= want_l:
                        break
                    await asyncio.sleep(0.05)
                ldt = time.time() - lt0
                lrow = sorted(lat)
                ladder_rows.append({
                    "window_us": wus_i,
                    "per_sec": round((delivered_n[0] - base) / ldt),
                    "lat_p50_ms": pct_of(lrow, 0.50),
                    "lat_p99_ms": pct_of(lrow, 0.99),
                })
                log(f"ladder window={wus_i}us: "
                    f"{ladder_rows[-1]['per_sec']}/s "
                    f"p50={ladder_rows[-1]['lat_p50_ms']}ms "
                    f"p99={ladder_rows[-1]['lat_p99_ms']}ms")

        for d in drainers:
            d.cancel()
        for cl in pubs + subs:
            await cl.disconnect()
        await lst.stop()
        lat = main_lat

        def pct(p):
            return pct_of(lat, p)

        out_extra = {}
        if ladder_rows:
            out_extra["window_ladder"] = ladder_rows
            measured = [r for r in ladder_rows
                        if r["lat_p99_ms"] is not None]
            if measured:
                out_extra["best_window_us"] = min(
                    measured, key=lambda r: r["lat_p99_ms"])["window_us"]
        # per-stage pipeline telemetry: stage p50/p95/p99, batch
        # occupancy per shape class, compile accounting — one schema
        # shared with GET /api/v5/pipeline/stats and profile_step.py
        try:
            snap = node.pipeline_telemetry.snapshot()
            out_extra["telemetry"] = snap
            # flight-recorder overlap summary (ISSUE 7), surfaced at
            # the top of the phase row so the next TPU relay window's
            # post-mortem reads the dispatch↔materialize overlap and
            # the top bubble attributions without digging — the e2e
            # gap diagnosis even if the round dies right after
            tr = snap.get("trace") or {}
            if use_device or tr.get("overlap") or tr.get("bubbles"):
                # ISSUE 9: the overlap row now ALWAYS rides device e2e
                # phases (checkpointed with them), carrying the
                # dispatch depth next to the fraction — the acceptance
                # metric survives even if later phases die, and a
                # depth-1 A/B run is distinguishable in the artifact
                out_extra["overlap"] = {
                    "dispatch_materialize":
                        (tr.get("overlap") or {}).get(
                            "dispatch_materialize"),
                    "dispatch_depth":
                        node.publish_batcher.dispatch_depth
                        if node.publish_batcher is not None else None,
                    "windows": tr.get("windows"),
                    "bubbles_top":
                        (tr.get("bubbles") or {}).get("top"),
                }
            # per-path e2e latency distribution (ISSUE 13): the
            # observatory's ingress→routed / ingress→delivered
            # percentiles + SLO burn verdict, promoted to the top of
            # the phase row (checkpointed with it) so the next TPU
            # relay window commits a real, relay-overhead-free
            # per-message p99 in its first minutes — unlike sync_p99_ms
            # (window-granularity, relay-HTTP-contaminated)
            lat_sec = snap.get("latency")
            if lat_sec:
                out_extra["latency"] = lat_sec
                slo = lat_sec.get("slo") or {}
                log(f"e2e latency: ingress→routed p99 "
                    f"{slo.get('routed_p99_ms')}ms / delivered p99 "
                    f"{slo.get('delivered_p99_ms')}ms vs objective "
                    f"{slo.get('objective_p99_ms')}ms -> "
                    f"{slo.get('verdict')} "
                    f"(burn {slo.get('burn')})")
        except Exception as e:  # noqa: BLE001 — diagnosis must not kill data
            log(f"telemetry snapshot failed: {type(e).__name__}: {e}")
        return {
            "delivered": delivered,
            "sent": total,
            "shared_pct": shared_pct,
            "qos1_pct": 25,
            "per_sec": round(delivered / dt),
            **out_extra,
            # client-observed PUBLISH→deliver latency over the whole
            # flood (includes socket + frame + batcher window + route +
            # session + serialize) — the BASELINE.md p99 criterion's
            # honest end-to-end form
            "lat_p50_ms": pct(0.50),
            "lat_p99_ms": pct(0.99),
            # batcher-internal PUBLISH→route (enqueue → batch complete)
            "route_lat": route_lat,
            "device_routed": node.metrics.val("messages.routed.device"),
            "batches": node.metrics.val("routing.device.batches"),
            # adaptive choice: batches the measured-cost router sent to
            # the host because the device round trip (relay dispatch)
            # would have been slower
            "device_bypassed": node.metrics.val("routing.device.bypassed"),
            # loop scheduling jitter while routing: the pipelined serving
            # path keeps dispatch/readback off the loop, so this stays
            # in the milliseconds even when the device round trip is slow
            "loop_jitter_p99_ms": round(sorted(jitter)[
                min(len(jitter) - 1, int(len(jitter) * 0.99))] * 1000, 1)
            if jitter else None,
        }

    global _LAST_TELEMETRY, _LAST_MEMORY
    try:
        return asyncio.run(go())
    finally:
        # success or crash, keep the newest snapshot for the error JSON:
        # "relay never came up"-class failures stay diagnosable
        node = node_box.get("node")
        if node is not None:
            try:
                _LAST_TELEMETRY = node.pipeline_telemetry.snapshot()
            except Exception:  # noqa: BLE001
                pass
            # the newest HBM-ledger section (ISSUE 8): what was on the
            # device when the run ended, success or crash
            mem = _mem_row(node)
            if mem:
                _LAST_MEMORY = mem


def main():
    if "--phase0" in sys.argv:
        # standalone incremental headline (tools/relay_watcher.py calls
        # this first thing when a window opens; the caller owns the
        # backend probe). A watchdog still bounds a wedged transfer.
        import signal as _sig

        def _p0_kill(signum, frame):
            print(_error_json("phase0 watchdog timeout"), flush=True)
            os._exit(2)

        _sig.signal(_sig.SIGALRM, _p0_kill)
        _sig.alarm(int(os.environ.get("BENCH_PHASE0_TIMEOUT_S", 240)))
        try:
            print(json.dumps(run_phase0(
                int(os.environ.get("BENCH_SHARED_PCT", 50)))),
                flush=True)
        except Exception as e:  # noqa: BLE001 — always emit a JSON line
            traceback.print_exc(file=sys.stderr)
            print(_error_json(
                f"phase0 failed: {type(e).__name__}: {str(e)[:200]}"),
                flush=True)
            sys.exit(2)
        finally:
            _sig.alarm(0)
        return

    if "--latency-probe" in sys.argv:
        # ISSUE 13: a small real-TCP e2e flood whose only product is
        # the latency observatory's per-path ingress→routed/delivered
        # distribution + SLO verdict. main() runs this as a CPU
        # subprocess right after phase0 (axon pool stripped, like the
        # hbm forecast) so even a round whose relay phases all die
        # commits a measured per-message p99 in its first minutes.
        import signal as _sig

        def _lp_kill(signum, frame):
            print(_error_json("latency probe watchdog timeout"),
                  flush=True)
            os._exit(2)

        _sig.signal(_sig.SIGALRM, _lp_kill)
        _sig.alarm(int(os.environ.get("BENCH_LAT_TIMEOUT_S", 420)))
        os.environ.setdefault("BENCH_E2E_LADDER", "0")
        try:
            row = run_e2e(
                int(os.environ.get("BENCH_LAT_FILTERS", 256)), 4, 4,
                int(os.environ.get("BENCH_LAT_MSGS", 1600)) // 4, True)
            print(json.dumps({
                "metric": "latency_probe",
                "latency": row.get("latency"),
                "per_sec": row.get("per_sec"),
                "lat_p99_ms": row.get("lat_p99_ms"),
                "route_lat": row.get("route_lat"),
            }), flush=True)
        except Exception as e:  # noqa: BLE001 — always emit a JSON line
            traceback.print_exc(file=sys.stderr)
            print(_error_json(
                f"latency probe failed: "
                f"{type(e).__name__}: {str(e)[:200]}"), flush=True)
            sys.exit(2)
        finally:
            _sig.alarm(0)
        return

    if "--skew" in sys.argv:
        # skewed-topic microbenchmark for the device-match reuse layers
        # (ISSUE 2 acceptance: cached >= 2x the cache-disabled path);
        # full harness lives in tools/skew_bench.py
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools"))
        import skew_bench
        skew_bench.main()
        return

    if "--churn" in sys.argv:
        # sustained-churn microbenchmark for the delta overlay (ISSUE 4
        # acceptance: overlay >= 2x the rebuild-and-host-fallback
        # baseline, rebuilds reduced >= 5x, host_delta ~= 0);
        # full harness lives in tools/churn_bench.py
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools"))
        import churn_bench
        churn_bench.main()
        return

    if "--cover" in sys.argv:
        # subscription-covering microbenchmark (ISSUE 18 acceptance:
        # covering ON >= 2x OFF on a cover-heavy population, >= 0.95x
        # on a uniform one, delivery counts bit-identical);
        # full harness lives in tools/cover_bench.py
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools"))
        import cover_bench
        cover_bench.main()
        return

    if "--fanout" in sys.argv:
        # high fan-out delivery microbenchmark for the delivery lanes
        # (ISSUE 5 acceptance: deliver_lanes=4 >= 2x the inline
        # baseline at fan-out >= 64, per-session order bit-identical);
        # full harness lives in tools/fanout_bench.py
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools"))
        import fanout_bench
        fanout_bench.main()
        return

    # watchdog: if anything hangs (axon backend init / a stuck transfer),
    # still emit the JSON line before the driver's kill timeout hits
    import signal

    def _alarm(signum, frame):
        print(_error_json("watchdog timeout (backend init or transfer "
                          "hang)"), flush=True)
        os._exit(2)

    # backend-init probe, staged (round-3 post-mortem: the relay was down
    # for the whole round-end window and ONE 600s hang consumed the whole
    # budget — now the budget is spent productively):
    #   1. wait for a relay listener port via `ss` (cheap, can never
    #      hang) — the relay pump may come up at any point in the window;
    #   2. only then probe jax in a CHILD process (a wedged pool hangs
    #      jax.devices() inside C where SIGALRM can't run; the parent
    #      must never touch jax until a disposable child proves the
    #      backend answers), with retries — one killed claimant can leak
    #      its pool claim, and a later attempt may still win.
    # On a CPU/forced backend (JAX_PLATFORMS set, no axon pool) the port
    # wait is skipped.
    import subprocess

    # dead-relay fail-fast: EMQX_TPU_RELAY_WAIT_S bounds how long the
    # round may poll for a relay window before reporting (BENCH_r05 spent
    # ~9 blind minutes on "relay never came up"; now the window is an
    # explicit, tunable budget and the error JSON carries telemetry)
    init_budget = int(os.environ.get(
        "EMQX_TPU_RELAY_WAIT_S",
        os.environ.get("BENCH_INIT_TIMEOUT_S", 600)))
    deadline = time.time() + init_budget
    axon = bool(os.environ.get("PALLAS_AXON_POOL_IPS")) and \
        "cpu" not in os.environ.get("JAX_PLATFORMS", "").lower()

    if axon and os.environ.get("WATCHER_REARM", "1") != "0":
        # watcher re-arm guard (ISSUE 9 satellite): a dead watcher pid
        # means the round has no mid-round window coverage — respawn it
        # before this bench claims the pool (the watcher's .hold/.pid
        # protocol keeps the two from racing a window). Never runs on
        # CPU/CI boxes (no axon pool configured).
        try:
            subprocess.run(
                [sys.executable,
                 os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "tools", "relay_watcher.py"), "--rearm"],
                capture_output=True, timeout=30)
        except Exception as e:  # noqa: BLE001 — guard is best-effort
            log(f"watcher rearm failed: {type(e).__name__}: {e}")

    def relay_listening() -> bool:
        try:
            r = subprocess.run(["ss", "-ltn"], capture_output=True,
                               text=True, timeout=10)
            return any(":808" in ln for ln in r.stdout.splitlines())
        except Exception:  # noqa: BLE001 — treat as unknown, probe anyway
            return True

    if axon and os.environ.get("BENCH_RELAY_PREFLIGHT", "1") != "0":
        # preflight (ISSUE 5 satellite): a PROVABLY dead relay port must
        # fail fast with the phase-0-style error JSON (telemetry/
        # last_measured attached by _error_json) instead of polling out
        # the whole EMQX_TPU_RELAY_WAIT_S window — BENCH_r05 burned 540s
        # doing exactly that to report value=0. One probe, through the
        # watcher's exact-port matcher (tools/relay_watcher.py owns the
        # mid-round windows now; a round-end bench with no listener is
        # a dead round, not a window about to open). ss failing to run
        # reads as "unknown": fall through to the polling loop.
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools"))
        try:
            from relay_watcher import relay_listening as _rw_listening
        except Exception:  # noqa: BLE001 — preflight is best-effort
            _rw_listening = None
        if _rw_listening is not None and not _rw_listening():
            print(_error_json(
                "relay port provably dead at start (no listener on "
                ":8082-:809x); skipped the EMQX_TPU_RELAY_WAIT_S poll "
                f"({init_budget}s) — set BENCH_RELAY_PREFLIGHT=0 to "
                "wait for a window instead"), flush=True)
            os._exit(2)

    ok, detail = False, "relay never came up"
    global _RELAY_WAIT_S
    t_relay = time.time()
    while time.time() < deadline:
        if axon and not relay_listening():
            log("relay not listening; waiting for a window "
                f"({int(deadline - time.time())}s left)")
            time.sleep(15)
            continue
        per_try = min(120, max(30, int(deadline - time.time())))
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(len(jax.devices()))"],
                capture_output=True, timeout=per_try, text=True)
            ok = probe.returncode == 0
            detail = (probe.stdout or probe.stderr or "").strip()[-200:]
        except subprocess.TimeoutExpired:
            ok, detail = False, f"device probe hung > {per_try}s"
        if ok:
            break
        if not axon:
            # a forced/CPU backend fails deterministically — retrying a
            # broken jax for 10 minutes helps nobody
            break
        log(f"backend probe failed ({detail}); "
            f"retrying while budget lasts")
        time.sleep(10)
    # relay/backend-init wait accounting (ISSUE 7 satellite): the other
    # place a dead round's minutes vanished — BENCH_r05 burned 540s
    # here and the JSON never said so
    _RELAY_WAIT_S = time.time() - t_relay
    if not ok:
        print(_error_json(f"backend init failed: {detail}"), flush=True)
        os._exit(2)
    log(f"backend probe ok: {detail} device(s) "
        f"(waited {_RELAY_WAIT_S:.0f}s)")

    requested = int(os.environ.get("BENCH_SUBS", 10_000_000))
    B = int(os.environ.get("BENCH_BATCH", 131072))
    window = int(os.environ.get("BENCH_WINDOW", 32))
    shared_pct = int(os.environ.get("BENCH_SHARED_PCT", 50))
    # the resumable phase ladder (ROADMAP item 1): phases completed by a
    # previous run of the SAME config resume from disk instead of
    # re-measuring — a dying relay window commits what it finished.
    # The signature covers EVERY phase-shaping knob (BENCH_*/FANOUT_*/
    # CHURN_*/SKEW_*/EMQX_TPU_*), not just the headline four — resuming
    # a config5/fanout row measured under different knobs would
    # fabricate numbers. Checkpoint plumbing knobs are excluded (they
    # legitimately differ between the dying run and its resume).
    knob_env = {k: v for k, v in sorted(os.environ.items())
                if k.startswith(("BENCH_", "FANOUT_", "CHURN_",
                                 "SKEW_", "INGRESS_", "OVERLOAD_",
                                 "EXCHANGE_", "COVER_", "EMQX_TPU_"))
                and k not in ("BENCH_CHECKPOINT", "BENCH_RESUME")}
    sig = {"subs": requested, "batch": B, "window": window,
           "shared_pct": shared_pct, "env": knob_env}
    phases = _ckpt_load(sig)

    # phase 0 (VERDICT r5 top-next): commit an incremental headline
    # within the first ~2 minutes of the window, BEFORE the long phase
    # plan — printed immediately (a SIGKILL mid-run leaves this line as
    # the last JSON on stdout), embedded in the final/error JSON, and
    # ALWAYS the first phase written to the checkpoint
    global _PHASE0
    if os.environ.get("BENCH_PHASE0", "1") != "0":
        if "phase0" in phases:
            _PHASE0 = phases["phase0"]
            print(json.dumps(_PHASE0), flush=True)
            log("phase0: resumed from checkpoint")
        else:
            def _p0_alarm(signum, frame):
                raise TimeoutError("phase0 watchdog")

            signal.signal(signal.SIGALRM, _p0_alarm)
            try:
                signal.alarm(int(os.environ.get("BENCH_PHASE0_TIMEOUT_S",
                                                240)))
                with _phase_clock("phase0"):
                    _PHASE0 = run_phase0(
                        int(os.environ.get("BENCH_SHARED_PCT", 50)))
                print(json.dumps(_PHASE0), flush=True)
                _ckpt_put("phase0", _PHASE0, sig, phases)
            except Exception as e:  # noqa: BLE001 — best-effort pre-phase
                signal.alarm(0)
                log(f"phase0 failed: {type(e).__name__}: {e}")
            finally:
                signal.alarm(0)

    # HBM capacity forecast (ISSUE 8): fit per-subscription byte costs
    # from ledgered snapshot-table uploads at several sizes and
    # extrapolate the subscription ceiling per HBM budget (16GB v5e-1
    # headline). Committed RIGHT AFTER phase0 — seconds of CPU, no
    # relay involved (subprocess with the axon pool stripped, like the
    # skew/churn/fanout rows) — so even a round whose throughput phases
    # all die still reports a measured memory headline.
    global _HBM_FORECAST
    if "hbm" in phases:
        _HBM_FORECAST = phases["hbm"]
        log("hbm: resumed from checkpoint")
    elif os.environ.get("BENCH_HBM", "1") != "0":
        try:
            senv = dict(os.environ)
            senv.pop("PALLAS_AXON_POOL_IPS", None)
            senv["JAX_PLATFORMS"] = "cpu"
            with _phase_clock("hbm"):
                sp = subprocess.run(
                    [sys.executable,
                     os.path.join(os.path.dirname(
                         os.path.abspath(__file__)),
                         "tools", "hbm_report.py")],
                    capture_output=True, text=True, env=senv,
                    timeout=int(os.environ.get("BENCH_HBM_TIMEOUT_S",
                                               600)))
            for ln in reversed(sp.stdout.splitlines()):
                if ln.strip().startswith("{"):
                    _HBM_FORECAST = json.loads(ln)
                    break
            if _HBM_FORECAST is not None:
                if sp.returncode != 0:
                    # the report exits 2 when a measure point's arrays
                    # did not release (ledger leak) — the forecast
                    # numbers still print, but they are leak-tainted
                    # and must not read as a clean measurement
                    _HBM_FORECAST["release_proof_failed"] = True
                    log(f"hbm forecast: release proof FAILED "
                        f"(rc={sp.returncode}) — forecast tainted")
                _ckpt_put("hbm", _HBM_FORECAST, sig, phases)
                log(f"hbm forecast: "
                    f"{_HBM_FORECAST['fit']['per_sub_bytes']} B/sub -> "
                    f"{_HBM_FORECAST['headline']['ceiling_subs']} subs "
                    f"ceiling at {_HBM_FORECAST['headline']['budget']}")
            else:
                log(f"hbm forecast produced no JSON "
                    f"(rc={sp.returncode}): {sp.stderr[-200:]}")
        except Exception as e:  # noqa: BLE001 — best-effort pre-phase
            log(f"hbm forecast failed: {type(e).__name__}: {e}")

    # per-message e2e latency probe (ISSUE 13): a small real-TCP flood
    # in a CPU subprocess (axon pool stripped, like the hbm forecast)
    # whose product is the latency observatory's per-path ingress→
    # routed/delivered percentiles + SLO burn verdict. Committed right
    # after the forecast and GRAFTED onto the phase0 row (re-
    # checkpointed), so the round's first minutes carry a measured,
    # relay-overhead-free per-message p99 — the number sync_p99_ms
    # (window-granularity, relay-HTTP-contaminated) never was.
    global _LAT0
    if "latency0" in phases:
        _LAT0 = phases["latency0"]
        log("latency0: resumed from checkpoint")
    elif os.environ.get("BENCH_LATENCY0", "1") != "0":
        try:
            senv = dict(os.environ)
            senv.pop("PALLAS_AXON_POOL_IPS", None)
            senv["JAX_PLATFORMS"] = "cpu"
            senv.setdefault("BENCH_E2E_LADDER", "0")
            with _phase_clock("latency0"):
                sp = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--latency-probe"],
                    capture_output=True, text=True, env=senv,
                    timeout=int(os.environ.get("BENCH_LAT_TIMEOUT_S",
                                               420)))
            for ln in reversed(sp.stdout.splitlines()):
                if ln.strip().startswith("{"):
                    _LAT0 = json.loads(ln)
                    break
            if _LAT0 is not None and _LAT0.get("latency"):
                _ckpt_put("latency0", _LAT0, sig, phases)
                slo = (_LAT0["latency"].get("slo") or {})
                log(f"latency0: ingress→routed p99 "
                    f"{slo.get('routed_p99_ms')}ms vs objective "
                    f"{slo.get('objective_p99_ms')}ms -> "
                    f"{slo.get('verdict')}")
            else:
                log(f"latency0 probe produced no latency row "
                    f"(rc={sp.returncode}): {sp.stderr[-200:]}")
        except Exception as e:  # noqa: BLE001 — best-effort pre-phase
            log(f"latency0 probe failed: {type(e).__name__}: {e}")
    if _LAT0 is not None and _PHASE0 is not None \
            and _LAT0.get("latency") and "latency" not in _PHASE0:
        _PHASE0["latency"] = _LAT0["latency"]
        if "phase0" in phases:
            # keep the checkpointed phase0 in sync with the grafted row
            _ckpt_put("phase0", _PHASE0, sig, phases)

    signal.signal(signal.SIGALRM, _alarm)
    signal.alarm(int(os.environ.get("BENCH_TIMEOUT_S", 2400)))

    ladder = [s for s in (requested, 1_000_000, 100_000) if s <= requested]
    ladder = sorted(set(ladder), reverse=True)
    errors = []
    for subs in ladder:
        try:
            core_key = f"core@{subs}"
            if core_key in phases:
                result = dict(phases[core_key])
                log(f"{core_key}: resumed from checkpoint")
            else:
                with _phase_clock(core_key):
                    result = run_bench(subs, B, window, shared_pct)
                # committed pristine, before the sections below attach
                _ckpt_put(core_key, dict(result), sig, phases)
            if _PHASE0:
                result["phase0"] = _PHASE0
            if subs != requested:
                result["requested_subs"] = requested
                result["stepdown_errors"] = errors
            # core result is in hand: the global watchdog must not be able
            # to discard it over the best-effort config-suite/e2e phases
            signal.alarm(0)
            if "configs" in phases:
                result["configs"] = phases["configs"]
                log("configs: resumed from checkpoint")
            elif os.environ.get("BENCH_CONFIGS", "1") != "0":
                def _cfg_alarm(signum, frame):
                    raise TimeoutError("config suite watchdog")

                signal.signal(signal.SIGALRM, _cfg_alarm)
                try:
                    signal.alarm(int(os.environ.get(
                        "BENCH_CONFIGS_TIMEOUT_S", 600)))
                    with _phase_clock("configs"):
                        result["configs"] = run_baseline_configs(
                            min(B, 32768), max(8, window // 4))
                    _ckpt_put("configs", result["configs"], sig, phases)
                except Exception as e:  # noqa: BLE001 — best-effort
                    signal.alarm(0)   # before anything else: the pending
                    # alarm must not fire inside this handler and escape
                    log(f"config suite failed: {type(e).__name__}: {e}")
                    result["configs_error"] = \
                        f"{type(e).__name__}: {str(e)[:160]}"
                finally:
                    signal.alarm(0)
            if "config5" in phases:
                result["config5"] = phases["config5"]
                log("config5: resumed from checkpoint")
            elif os.environ.get("BENCH_CONFIG5", "1") != "0":
                def _c5_alarm(signum, frame):
                    raise TimeoutError("config5 watchdog")

                signal.signal(signal.SIGALRM, _c5_alarm)
                try:
                    c5_routes = int(os.environ.get("BENCH_C5_ROUTES",
                                                   1_000_000))
                    # the section watchdog scales with the requested
                    # count so BENCH_C5_ROUTES=10000000 (the stated
                    # shape in full) is runnable without extra knobs
                    signal.alarm(int(os.environ.get(
                        "BENCH_C5_TIMEOUT_S",
                        max(600, 300 + c5_routes // 5_000))))
                    with _phase_clock("config5"):
                        result["config5"] = run_config5(
                            c5_routes,
                            int(os.environ.get("BENCH_C5_RETAINED",
                                               100_000)))
                    _ckpt_put("config5", result["config5"], sig, phases)
                except Exception as e:  # noqa: BLE001 — best-effort
                    signal.alarm(0)
                    log(f"config5 failed: {type(e).__name__}: {e}")
                    traceback.print_exc(file=sys.stderr)
                    result["config5_error"] = \
                        f"{type(e).__name__}: {str(e)[:200]}"
                finally:
                    signal.alarm(0)
            if os.environ.get("BENCH_E2E", "1") != "0":
                ef = int(os.environ.get("BENCH_E2E_FILTERS", 100_000))
                em = int(os.environ.get("BENCH_E2E_MSGS", 32_000))

                def _e2e_alarm(signum, frame):
                    raise TimeoutError("e2e watchdog")

                signal.signal(signal.SIGALRM, _e2e_alarm)
                # host first with its own watchdog: it is fast and always
                # works, so a device run that burns its budget on relay
                # compiles can no longer take the host number down with it
                budget = int(os.environ.get("BENCH_E2E_TIMEOUT_S", 600))
                for name, use_device, share in (("e2e_host", False, 1),
                                                ("e2e_device", True, 2)):
                    if name in phases:
                        result[name] = phases[name]
                        log(f"{name}: resumed from checkpoint")
                        continue
                    try:
                        signal.alarm(budget * share // 3)
                        with _phase_clock(name):
                            result[name] = run_e2e(ef, 16, 8, em // 8,
                                                   use_device)
                        _ckpt_put(name, result[name], sig, phases)
                    except Exception as e:  # noqa: BLE001 — best-effort
                        signal.alarm(0)
                        log(f"{name} bench failed: "
                            f"{type(e).__name__}: {e}")
                        traceback.print_exc(file=sys.stderr)
                        result[f"{name}_error"] = \
                            f"{type(e).__name__}: {str(e)[:200]}"
                        if _LAST_TELEMETRY:
                            # the failed phase's pipeline snapshot: the
                            # stage-level diagnosis the round would
                            # otherwise lose
                            result[f"{name}_telemetry"] = _LAST_TELEMETRY
                    finally:
                        signal.alarm(0)
            # ISSUE 13 satellite: the headline route-latency summary —
            # the observatory's per-message ingress→routed p99 placed
            # NEXT TO (and clearly labeled against) the legacy sync
            # round-trip number, so BENCH_r* rows stop conflating relay
            # HTTP dispatch cost with route latency
            if _LAT0 is not None and _LAT0.get("latency"):
                result["latency0"] = _LAT0
            lat_src = ((result.get("e2e_device") or {}).get("latency")
                       or (result.get("e2e_host") or {}).get("latency")
                       or (_LAT0 or {}).get("latency"))
            if lat_src:
                slo = lat_src.get("slo") or {}
                result["route_latency"] = {
                    "ingress_routed_p99_ms": slo.get("routed_p99_ms"),
                    "ingress_delivered_p99_ms":
                        slo.get("delivered_p99_ms"),
                    "objective_p99_ms": slo.get("objective_p99_ms"),
                    "verdict": slo.get("verdict"),
                    "burn": slo.get("burn"),
                    "legacy_sync_p99_ms": result.get("sync_p99_ms"),
                    "note": ("ingress_routed_p99_ms is per-message "
                             "frame-decode→route-result (latency "
                             "observatory, ISSUE 13); legacy_sync_p99_"
                             "ms is per-WINDOW and includes relay HTTP "
                             "dispatch overhead — do not compare them "
                             "as one metric"),
                }
            if "sharded" in phases:
                result["sharded"] = phases["sharded"]
                log("sharded: resumed from checkpoint")
            elif os.environ.get("BENCH_SHARDED", "1") != "0":
                # multichip serving at scale on a VIRTUAL CPU mesh —
                # subprocess with the axon pool stripped so it can never
                # claim (or hang on) the relay; correctness/scale proof,
                # the chip rows above measure raw speed
                try:
                    senv = dict(os.environ)
                    senv.pop("PALLAS_AXON_POOL_IPS", None)
                    senv["JAX_PLATFORMS"] = "cpu"
                    with _phase_clock("sharded"):
                        sp = subprocess.run(
                            [sys.executable,
                             os.path.join(os.path.dirname(
                                 os.path.abspath(__file__)),
                                 "tools", "sharded_bench.py")],
                            capture_output=True, text=True, env=senv,
                            timeout=int(os.environ.get(
                                "BENCH_SHARDED_TIMEOUT_S", 1200)))
                    row = None
                    for ln in reversed(sp.stdout.splitlines()):
                        if ln.strip().startswith("{"):
                            row = json.loads(ln)
                            break
                    if row is not None:
                        result["sharded"] = row
                        _ckpt_put("sharded", row, sig, phases)
                    else:
                        result["sharded_error"] = \
                            f"rc={sp.returncode}: {sp.stderr[-200:]}"
                except Exception as e:  # noqa: BLE001 — best-effort
                    log(f"sharded bench failed: {type(e).__name__}: {e}")
                    result["sharded_error"] = \
                        f"{type(e).__name__}: {str(e)[:200]}"
            if "skew" in phases:
                result["skew"] = phases["skew"]
                log("skew: resumed from checkpoint")
            elif os.environ.get("BENCH_SKEW", "1") != "0":
                # hot-topic reuse microbench (ISSUE 2): cached vs
                # cache-disabled matches/sec + hit-rate/dedup counters,
                # CPU subprocess so it can never claim (or hang on) the
                # relay; rides next to the telemetry the e2e rows embed
                try:
                    senv = dict(os.environ)
                    senv.pop("PALLAS_AXON_POOL_IPS", None)
                    senv["JAX_PLATFORMS"] = "cpu"
                    with _phase_clock("skew"):
                        sp = subprocess.run(
                            [sys.executable,
                             os.path.join(os.path.dirname(
                                 os.path.abspath(__file__)),
                                 "tools", "skew_bench.py")],
                            capture_output=True, text=True, env=senv,
                            timeout=int(os.environ.get(
                                "BENCH_SKEW_TIMEOUT_S", 600)))
                    row = None
                    for ln in reversed(sp.stdout.splitlines()):
                        if ln.strip().startswith("{"):
                            row = json.loads(ln)
                            break
                    if row is not None:
                        # the full telemetry snapshot already rides the
                        # e2e rows; keep the skew row compact
                        tele = row.pop("telemetry", {})
                        row["match_cache"] = tele.get("match_cache")
                        row["dedup"] = tele.get("dedup")
                        row["readback"] = tele.get("readback")
                        result["skew"] = row
                        _ckpt_put("skew", row, sig, phases)
                    else:
                        result["skew_error"] = \
                            f"rc={sp.returncode}: {sp.stderr[-200:]}"
                except Exception as e:  # noqa: BLE001 — best-effort
                    log(f"skew bench failed: {type(e).__name__}: {e}")
                    result["skew_error"] = \
                        f"{type(e).__name__}: {str(e)[:200]}"
            if "churn" in phases:
                result["churn"] = phases["churn"]
                log("churn: resumed from checkpoint")
            elif os.environ.get("BENCH_CHURN", "1") != "0":
                # sustained-churn microbench (ISSUE 4): delta-overlay vs
                # rebuild-and-host-fallback matches/sec + rebuild counts
                # + host_delta, CPU subprocess like the skew row
                try:
                    senv = dict(os.environ)
                    senv.pop("PALLAS_AXON_POOL_IPS", None)
                    senv["JAX_PLATFORMS"] = "cpu"
                    with _phase_clock("churn"):
                        sp = subprocess.run(
                            [sys.executable,
                             os.path.join(os.path.dirname(
                                 os.path.abspath(__file__)),
                                 "tools", "churn_bench.py")],
                            capture_output=True, text=True, env=senv,
                            timeout=int(os.environ.get(
                                "BENCH_CHURN_TIMEOUT_S", 600)))
                    row = None
                    for ln in reversed(sp.stdout.splitlines()):
                        if ln.strip().startswith("{"):
                            row = json.loads(ln)
                            break
                    if row is not None:
                        # keep the row compact: the rebuild section is
                        # the interesting telemetry slice here
                        row.pop("overlay", None)
                        result["churn"] = row
                        _ckpt_put("churn", row, sig, phases)
                    else:
                        result["churn_error"] = \
                            f"rc={sp.returncode}: {sp.stderr[-200:]}"
                except Exception as e:  # noqa: BLE001 — best-effort
                    log(f"churn bench failed: {type(e).__name__}: {e}")
                    result["churn_error"] = \
                        f"{type(e).__name__}: {str(e)[:200]}"
            if "cover" in phases:
                result["cover"] = phases["cover"]
                log("cover: resumed from checkpoint")
            elif os.environ.get("BENCH_COVER", "1") != "0":
                # subscription-covering microbench (ISSUE 18): covering
                # ON vs OFF matches/sec on cover-heavy + uniform
                # populations, with the covering-set reduction factor
                # and the counts cross-check; CPU subprocess like the
                # skew/churn rows
                try:
                    senv = dict(os.environ)
                    senv.pop("PALLAS_AXON_POOL_IPS", None)
                    senv["JAX_PLATFORMS"] = "cpu"
                    with _phase_clock("cover"):
                        sp = subprocess.run(
                            [sys.executable,
                             os.path.join(os.path.dirname(
                                 os.path.abspath(__file__)),
                                 "tools", "cover_bench.py")],
                            capture_output=True, text=True, env=senv,
                            timeout=int(os.environ.get(
                                "BENCH_COVER_TIMEOUT_S", 600)))
                    row = None
                    for ln in reversed(sp.stdout.splitlines()):
                        if ln.strip().startswith("{"):
                            row = json.loads(ln)
                            break
                    if row is not None:
                        result["cover"] = row
                        _ckpt_put("cover", row, sig, phases)
                    else:
                        result["cover_error"] = \
                            f"rc={sp.returncode}: {sp.stderr[-200:]}"
                except Exception as e:  # noqa: BLE001 — best-effort
                    log(f"cover bench failed: {type(e).__name__}: {e}")
                    result["cover_error"] = \
                        f"{type(e).__name__}: {str(e)[:200]}"
            if "fanout" in phases:
                result["fanout"] = phases["fanout"]
                log("fanout: resumed from checkpoint")
            elif os.environ.get("BENCH_FANOUT", "1") != "0":
                # high fan-out delivery microbench (ISSUE 5): lanes
                # 0/1/2/4 deliveries/sec + the ordering oracle, CPU
                # subprocess like the skew/churn rows
                try:
                    senv = dict(os.environ)
                    senv.pop("PALLAS_AXON_POOL_IPS", None)
                    senv["JAX_PLATFORMS"] = "cpu"
                    with _phase_clock("fanout"):
                        sp = subprocess.run(
                            [sys.executable,
                             os.path.join(os.path.dirname(
                                 os.path.abspath(__file__)),
                                 "tools", "fanout_bench.py")],
                            capture_output=True, text=True, env=senv,
                            timeout=int(os.environ.get(
                                "BENCH_FANOUT_TIMEOUT_S", 600)))
                    row = None
                    for ln in reversed(sp.stdout.splitlines()):
                        if ln.strip().startswith("{"):
                            row = json.loads(ln)
                            break
                    if row is not None:
                        # keep the row compact: the deliver section's
                        # counters are the interesting slice
                        row.pop("deliver", None)
                        result["fanout"] = row
                        _ckpt_put("fanout", row, sig, phases)
                    else:
                        result["fanout_error"] = \
                            f"rc={sp.returncode}: {sp.stderr[-200:]}"
                except Exception as e:  # noqa: BLE001 — best-effort
                    log(f"fanout bench failed: {type(e).__name__}: {e}")
                    result["fanout_error"] = \
                        f"{type(e).__name__}: {str(e)[:200]}"
            if "ingress" in phases:
                result["ingress"] = phases["ingress"]
                log("ingress: resumed from checkpoint")
            elif os.environ.get("BENCH_INGRESS", "1") != "0":
                # columnar-ingress e2e microbench (ISSUE 11): real TCP
                # many-connection flood, columnar vs per-packet twin
                # rows + connection-count sweep, CPU subprocess like
                # the skew/churn/fanout rows — checkpointed the moment
                # it completes, so a dying relay window still commits
                # the ingress number
                try:
                    senv = dict(os.environ)
                    senv.pop("PALLAS_AXON_POOL_IPS", None)
                    senv["JAX_PLATFORMS"] = "cpu"
                    with _phase_clock("ingress"):
                        sp = subprocess.run(
                            [sys.executable,
                             os.path.join(os.path.dirname(
                                 os.path.abspath(__file__)),
                                 "tools", "ingress_bench.py")],
                            capture_output=True, text=True, env=senv,
                            timeout=int(os.environ.get(
                                "BENCH_INGRESS_TIMEOUT_S", 1500)))
                    row = None
                    for ln in reversed(sp.stdout.splitlines()):
                        if ln.strip().startswith("{"):
                            row = json.loads(ln)
                            break
                    if row is not None:
                        # keep the row compact: the twin table + the
                        # ingress section are the interesting slices;
                        # the per-stage decompositions stay for the
                        # honest-number requirement
                        result["ingress"] = row
                        _ckpt_put("ingress", row, sig, phases)
                    else:
                        result["ingress_error"] = \
                            f"rc={sp.returncode}: {sp.stderr[-200:]}"
                except Exception as e:  # noqa: BLE001 — best-effort
                    log(f"ingress bench failed: "
                        f"{type(e).__name__}: {e}")
                    result["ingress_error"] = \
                        f"{type(e).__name__}: {str(e)[:200]}"
            if "overload" in phases:
                result["overload"] = phases["overload"]
                log("overload: resumed from checkpoint")
            elif os.environ.get("BENCH_OVERLOAD", "1") != "0":
                # adaptive overload drive (ISSUE 14): sustained
                # real-TCP overdrive flood, governor-on vs governor-off
                # twins — held-SLO / shed-only-QoS0 / recovery legs
                # graded in the row. CPU subprocess like the
                # skew/churn/fanout/ingress rows, checkpointed the
                # moment it completes
                try:
                    senv = dict(os.environ)
                    senv.pop("PALLAS_AXON_POOL_IPS", None)
                    senv["JAX_PLATFORMS"] = "cpu"
                    with _phase_clock("overload"):
                        sp = subprocess.run(
                            [sys.executable,
                             os.path.join(os.path.dirname(
                                 os.path.abspath(__file__)),
                                 "tools", "overload_bench.py")],
                            capture_output=True, text=True, env=senv,
                            timeout=int(os.environ.get(
                                "BENCH_OVERLOAD_TIMEOUT_S", 1200)))
                    row = None
                    for ln in reversed(sp.stdout.splitlines()):
                        if ln.strip().startswith("{"):
                            row = json.loads(ln)
                            break
                    if row is not None:
                        result["overload"] = row
                        _ckpt_put("overload", row, sig, phases)
                    else:
                        result["overload_error"] = \
                            f"rc={sp.returncode}: {sp.stderr[-200:]}"
                except Exception as e:  # noqa: BLE001 — best-effort
                    log(f"overload bench failed: "
                        f"{type(e).__name__}: {e}")
                    result["overload_error"] = \
                        f"{type(e).__name__}: {str(e)[:200]}"
            # where the round's minutes went (ISSUE 7 satellite):
            # per-phase wall seconds + relay/backend-init wait, in the
            # merged JSON whether the phases succeeded or not
            if _PHASE_WALL:
                result["phase_wall_s"] = dict(_PHASE_WALL)
            if _RELAY_WAIT_S:
                result["relay_wait_s"] = round(_RELAY_WAIT_S, 1)
            # the memory story (ISSUE 8): the capacity forecast next to
            # the throughput headline, per-phase device stats, and the
            # newest ledger section — the same fields the error JSON
            # carries, so success and failure rounds compare directly
            if _HBM_FORECAST:
                result["hbm_forecast"] = _HBM_FORECAST
            if _PHASE_MEM:
                result["phase_memory"] = dict(_PHASE_MEM)
            if _LAST_MEMORY:
                result["memory"] = _LAST_MEMORY
            print(json.dumps(result), flush=True)
            # the merged JSON is committed: the checkpoint has served
            # its purpose (a stale one would pollute the next round)
            _ckpt_clear()
            return
        except Exception as e:  # noqa: BLE001 — always emit a JSON line
            log(f"bench at subs={subs} failed: {type(e).__name__}: {e}")
            traceback.print_exc(file=sys.stderr)
            errors.append(f"subs={subs}: {type(e).__name__}: {str(e)[:200]}")
    print(_error_json(errors), flush=True)


if __name__ == "__main__":
    main()
