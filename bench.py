#!/usr/bin/env python
"""North-star benchmark: wildcard topic-match + fan-out throughput on TPU.

Measures the fused route step (NFA match + subscriber fan-out + shared-sub
selection) against the BASELINE.md target: >=5M topic-matches/sec at 10M
wildcard subscriptions on one v5e-1, p99 < 2ms.

Filter shape mirrors the reference's broker_bench
(emqx_broker_bench.erl:25-34 `device/{{id}}/+/{{num}}/#`), scaled to
BENCH_SUBS subscriptions; BENCH_SHARED_PCT puts that share of subscriptions
into $share groups (config 4 of BASELINE.md).

Prints ONE JSON line on stdout; diagnostics go to stderr.

Env knobs: BENCH_SUBS (default 10_000_000), BENCH_BATCH (8192),
BENCH_ITERS (50), BENCH_SHARED_PCT (50).
"""

import json
import os
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    subs = int(os.environ.get("BENCH_SUBS", 10_000_000))
    B = int(os.environ.get("BENCH_BATCH", 8192))
    iters = int(os.environ.get("BENCH_ITERS", 50))
    shared_pct = int(os.environ.get("BENCH_SHARED_PCT", 50))

    import jax

    from emqx_tpu.models.router_engine import RouterTables, route_step
    from emqx_tpu.ops import intern as I
    from emqx_tpu.ops.fanout import SubTable
    from emqx_tpu.ops.shared import STRATEGY_ROUND_ROBIN
    from emqx_tpu.ops.trie import build_tables

    log(f"bench: subs={subs} batch={B} iters={iters} shared={shared_pct}% "
        f"device={jax.devices()[0]}")

    # --- build the filter set: device/{id}/+/{num}/#  -------------------
    ids = max(64, int(np.sqrt(subs)))
    nums = max(1, subs // ids)
    F = ids * nums
    intern = I.InternTable()
    wd = intern.intern("device")
    id_ids = np.array([intern.intern(f"d{i}") for i in range(ids)], np.int32)
    num_ids = np.array([intern.intern(f"n{n}") for n in range(nums)], np.int32)
    rows = np.zeros((F, 8), np.int32)
    lens = np.full(F, 5, np.int64)
    rows[:, 0] = wd
    rows[:, 1] = np.repeat(id_ids, nums)
    rows[:, 2] = I.PLUS
    rows[:, 3] = np.tile(num_ids, ids)
    rows[:, 4] = I.HASH

    t0 = time.time()
    trie = build_tables(rows, lens)
    t_build = time.time() - t0
    log(f"trie build: {t_build:.1f}s, nodes={int(trie.num_nodes)}, "
        f"edges={int(trie.num_edges)}, slots={trie.slot_parent.shape[0]}")

    # --- subscriber table: one subscriber per filter; a slice of filters
    # also belongs to shared groups (one 8-member group per 16 filters) ----
    n_shared_filters = F * shared_pct // 100
    sub_start = np.arange(F + 1, dtype=np.int32)
    sub_row = np.arange(F, dtype=np.int32)
    sub_opts = np.ones(F, np.int32)
    group_of = np.arange(n_shared_filters, dtype=np.int32) // 16
    n_groups = max(1, int(group_of.max(initial=0)) + 1)
    fs_start = np.zeros(F + 1, np.int32)
    fs_start[1:n_shared_filters + 1] = 1
    np.cumsum(fs_start, out=fs_start)
    fs_slot = group_of if n_shared_filters else np.full(1, -1, np.int32)
    shared_start = np.arange(n_groups + 1, dtype=np.int32) * 8
    shared_row = F + np.arange(n_groups * 8, dtype=np.int32)
    shared_opts = np.ones(n_groups * 8, np.int32)
    subs_tbl = SubTable(sub_start, sub_row, sub_opts, fs_start, fs_slot,
                        shared_start, shared_row, shared_opts)

    t0 = time.time()
    tables = jax.device_put(RouterTables(trie=trie, subs=subs_tbl))
    jax.block_until_ready(tables)
    log(f"upload: {time.time() - t0:.1f}s")
    cursors = jax.device_put(np.zeros(n_groups, np.int32))
    strat = jax.device_put(np.int32(STRATEGY_ROUND_ROBIN))
    jax.block_until_ready((cursors, strat))

    # --- pre-staged publish batches (Zipf-ish skew over device ids) ------
    x = intern.intern("x")
    tail = intern.intern("t")
    rng = np.random.RandomState(7)
    zipf = np.minimum(rng.zipf(1.3, size=(8, B)) - 1, ids - 1)
    batches = []
    for k in range(8):
        tp = np.zeros((B, 8), np.int32)
        tp[:, 0] = wd
        tp[:, 1] = id_ids[zipf[k]]
        tp[:, 2] = x
        tp[:, 3] = num_ids[rng.randint(0, nums, B)]
        tp[:, 4] = tail
        b = (jax.device_put(tp), jax.device_put(np.full(B, 5, np.int32)),
             jax.device_put(np.zeros(B, bool)),
             jax.device_put(rng.randint(0, 1 << 30, B).astype(np.int32)))
        batches.append(b)
    jax.block_until_ready(batches)

    def step(batch, cur):
        return route_step(tables, cur, *batch, strat, frontier_cap=8,
                          match_cap=8, fanout_cap=16, slot_cap=4)

    # warmup / compile
    r = step(batches[0], cursors)
    jax.block_until_ready(r)
    log(f"sanity: matches={int(np.asarray(r.match_counts).sum())}/{B}, "
        f"fan={int(np.asarray(r.fan_counts).sum())}, "
        f"shared={int((np.asarray(r.shared_rows) >= 0).sum())}, "
        f"overflow={int(np.asarray(r.overflow).sum())}")

    # timed: blocked per call → latency distribution & honest throughput
    lat = []
    cur = cursors
    for i in range(iters):
        b = batches[i % len(batches)]
        t0 = time.time()
        r = step(b, cur)
        jax.block_until_ready(r)
        lat.append(time.time() - t0)
        cur = r.new_cursors
    lat = np.array(sorted(lat))
    p50 = float(np.percentile(lat, 50))
    p99 = float(np.percentile(lat, 99))
    matches_per_sec = B / p50
    log(f"latency p50={p50 * 1000:.3f}ms p99={p99 * 1000:.3f}ms "
        f"({iters} iters, batch {B})")
    log(f"throughput={matches_per_sec / 1e6:.1f}M topic-matches/s")

    target = 5_000_000.0
    print(json.dumps({
        "metric": f"topic_matches_per_sec_at_{subs // 1_000_000}M_subs",
        "value": round(matches_per_sec),
        "unit": "topic-matches/s",
        "vs_baseline": round(matches_per_sec / target, 2),
        "p50_ms": round(p50 * 1000, 3),
        "p99_ms": round(p99 * 1000, 3),
        "batch": B,
        "subs": subs,
    }))


if __name__ == "__main__":
    main()
