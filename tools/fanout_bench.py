#!/usr/bin/env python
"""High fan-out delivery microbenchmark: the delivery lanes' win.

ISSUE 5 acceptance harness. Measures DELIVERIES/sec through the real
DeviceRouteEngine serving stages (prepare → dispatch → materialize →
finish_sub) at high fan-out — few topics × many subscribers, the regime
where deliveries/s >> matches/s and egress is the ceiling — once per
`deliver_lanes` setting (default 0/1/2/4):

  lanes=0   the inline per-row delivery loop (msg.copy per subscriber,
            per-row metric/hook bookkeeping) — the A/B baseline
  lanes=N   the session-affine egress stage (broker/deliver.py):
            vectorized plan, copy-on-write DeliveryView, coalesced
            same-session drains, per-slice bookkeeping, delivery
            overlapped with the next window's dispatch/materialize
            (which run on executor threads, as in the live pipeline)

The bench carries its own ORDERING ORACLE (not just the tests): every
subscriber records its delivered (topic, payload-seq) sequence, and the
JSON row only reports order_ok=true when every lane configuration's
per-session sequence is bit-identical to the lanes=0 baseline.

Each lane configuration runs in its OWN subprocess (`--one N`): the
lanes=0 baseline must not inherit the lanes=4 run's GC pressure, jit
caches or allocator state (measured: same-process config order moved
the numbers ±2x on a small box). The child reports deliveries/sec plus
a per-session blake2 digest of the delivery log; the parent compares
digests across configurations for the oracle.

Env knobs: FANOUT_TOPICS (16), FANOUT_SUBS (64 subscribers/topic),
FANOUT_BATCH (256), FANOUT_BATCHES (24), FANOUT_LANES ("0,1,2,4").

Run directly or as `python bench.py --fanout`.
"""

import asyncio
import gc
import hashlib
import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


class Sink:
    """Recording subscriber with the coalesced-drain protocol (the
    channel analog): same-session runs land in one deliver_batch."""

    __slots__ = ("got",)

    def __init__(self):
        self.got = []

    def deliver(self, topic_filter, msg):
        self.got.append((msg.topic, bytes(msg.payload)))
        return True

    def deliver_batch(self, items):
        got = self.got
        for _f, m in items:
            got.append((m.topic, bytes(m.payload)))
        return len(items)


def _mk_node(lanes: int):
    from emqx_tpu.broker.node import Node
    return Node({"broker": {"deliver_lanes": lanes,
                            "device_fanout_cap": 128,
                            "device_slot_cap": 2}})


def _subscribe_all(node, n_topics: int, n_subs: int) -> dict:
    """n_topics filters x n_subs subscribers each; returns sid -> Sink.
    Registration order is deterministic, so sids align across nodes
    and the ordering oracle can compare per-session logs directly."""
    b = node.broker
    sinks = {}
    for t in range(n_topics):
        for _s in range(n_subs):
            sink = Sink()
            sid = b.register(sink, f"c{t}-{_s}")
            sinks[sid] = sink
            b.subscribe(sid, f"fan/{t}/+", {"qos": 0})
    return sinks


def _batches(n_topics: int, batch: int, n_batches: int):
    """Deterministic round-robin-ish topic schedule with a global
    sequence number in the payload (the oracle's order key)."""
    rng = np.random.RandomState(17)
    out = []
    seq = 0
    for _ in range(n_batches):
        rows = []
        picks = rng.randint(0, n_topics, batch)
        for k in range(batch):
            rows.append((f"fan/{picks[k]}/x", b"%08d" % seq))
            seq += 1
        out.append(rows)
    return out


async def _run_node(node, batches) -> float:
    """One warm pass (XLA compiles, allocator) + two timed passes (min),
    driving the pipeline the way the batcher does: dispatch/materialize
    on executor threads, consume on the loop, lanes overlapping.
    Returns deliveries/sec of the best timed pass."""
    from emqx_tpu.broker.message import make
    eng = node.device_engine
    eng.rebuild()
    loop = asyncio.get_running_loop()
    pool = node.deliver_lanes
    msg_batches = [[make("p", 0, t, p) for t, p in rows]
                   for rows in batches]

    async def one_pass():
        for msgs in msg_batches:
            h = eng.prepare(msgs, gate_cold=False)
            assert h is not None
            await loop.run_in_executor(None, eng.dispatch, h)
            await loop.run_in_executor(None, eng.materialize, h)
            eng.finish_sub(h, 0)
            if pool is not None:
                await pool.admit()
        if pool is not None:
            await pool.drain()

    await one_pass()                      # warm: compiles + cache seed
    d0 = node.metrics.val("messages.delivered")
    await one_pass()
    per_pass = node.metrics.val("messages.delivered") - d0
    best = float("inf")
    for _ in range(3):
        gc.collect()    # a pending gen-2 sweep must not bill one pass
        t0 = time.perf_counter()
        await one_pass()
        best = min(best, time.perf_counter() - t0)
    return per_pass / best


def run_one(lanes: int) -> dict:
    """One lane configuration in a fresh process: deliveries/sec plus
    the per-session delivery-log digest (the ordering oracle's compact
    cross-process form: blake2 over every (sid, topic, payload) in
    delivery order per session)."""
    n_topics = int(os.environ.get("FANOUT_TOPICS", 16))
    n_subs = int(os.environ.get("FANOUT_SUBS", 64))
    batch = int(os.environ.get("FANOUT_BATCH", 256))
    n_batches = int(os.environ.get("FANOUT_BATCHES", 24))
    batches = _batches(n_topics, batch, n_batches)

    node = _mk_node(lanes)
    sinks = _subscribe_all(node, n_topics, n_subs)
    rate = asyncio.run(_run_node(node, batches))
    digest = hashlib.blake2b(digest_size=16)
    total = 0
    for sid in sorted(sinks):
        digest.update(b"S%d" % sid)
        for topic, payload in sinks[sid].got:
            digest.update(topic.encode())
            digest.update(payload)
            total += 1
    snap = node.pipeline_telemetry.snapshot()
    return {
        "lanes": lanes,
        "per_s": round(rate),
        "order_digest": digest.hexdigest(),
        "deliveries_logged": total,
        "coalesce_ratio": (snap.get("deliver") or {}).get(
            "coalesce_ratio"),
        "deliver": snap.get("deliver"),
        "backend": node.device_engine.stats()["backend"],
    }


def run_fanout() -> dict:
    n_topics = int(os.environ.get("FANOUT_TOPICS", 16))
    n_subs = int(os.environ.get("FANOUT_SUBS", 64))
    batch = int(os.environ.get("FANOUT_BATCH", 256))
    n_batches = int(os.environ.get("FANOUT_BATCHES", 24))
    lane_list = [int(x) for x in os.environ.get(
        "FANOUT_LANES", "0,1,2,4").split(",")]
    log(f"fanout bench: {n_topics} topics x {n_subs} subs "
        f"(fan-out {n_subs}), {n_batches} batches of {batch}, "
        f"lanes {lane_list}, one subprocess per config")

    rows = {}
    for lanes in lane_list:
        sp = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--one",
             str(lanes)],
            capture_output=True, text=True,
            timeout=int(os.environ.get("FANOUT_ONE_TIMEOUT_S", 300)))
        row = None
        for ln in reversed(sp.stdout.splitlines()):
            if ln.strip().startswith("{"):
                row = json.loads(ln)
                break
        if row is None:
            raise RuntimeError(
                f"lanes={lanes} child failed rc={sp.returncode}: "
                f"{sp.stderr[-300:]}")
        rows[lanes] = row
        log(f"lanes={lanes}: {row['per_s'] / 1e3:.1f}k deliveries/s "
            f"digest={row['order_digest'][:12]}")

    base = min(lane_list)
    top = max(lane_list)
    order_ok = all(rows[ln]["order_digest"] == rows[base]["order_digest"]
                   for ln in lane_list)
    top_row = rows[top]
    out = {
        "metric": "fanout_deliveries_per_sec",
        "unit": "deliveries/s",
        "per_lanes": {str(ln): rows[ln]["per_s"] for ln in lane_list},
        "baseline_per_s": rows[base]["per_s"],
        "best_per_s": top_row["per_s"],
        # ISSUE 5 acceptance: lanes=4 >= 2x the inline baseline at
        # fan-out >= 64, with per-session order bit-identical
        "speedup": round(top_row["per_s"] / rows[base]["per_s"], 2),
        "order_ok": order_ok,
        "order_digests": {str(ln): rows[ln]["order_digest"]
                          for ln in lane_list},
        "coalesce_ratio": top_row["coalesce_ratio"],
        "deliver": top_row["deliver"],
        "workload": {
            "topics": n_topics, "subs_per_topic": n_subs,
            "fanout": n_subs, "batch": batch, "batches": n_batches,
        },
        "backend": top_row["backend"],
    }
    return out


def main():
    if "--one" in sys.argv:
        lanes = int(sys.argv[sys.argv.index("--one") + 1])
        print(json.dumps(run_one(lanes)), flush=True)
        return
    print(json.dumps(run_fanout()), flush=True)


if __name__ == "__main__":
    main()
