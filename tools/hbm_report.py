#!/usr/bin/env python
"""HBM capacity forecaster (ISSUE 8): what fits on the chip?

The 10M-subscription north star is ultimately an HBM-budget question:
the snapshot tables the broker `device_put`s grow linearly with the
subscription count, and nothing before the ISSUE-8 ledger measured the
slope. This tool measures it directly — it builds the SAME
ShapeRouterTables the serving path uploads (bench.py's canonical
`device/{id}/+/{num}/#` workload generator, so the fitted bytes are
the bytes a real broker pays) at several table sizes, registers each
upload with a fresh `broker.hbm_ledger.HbmLedger`, and fits

    table_bytes = intercept + per_sub_bytes * subscriptions

by least squares, then inverts the fit per HBM budget:

    ceiling_subs = (budget * (1 - headroom) - intercept) / per_sub_bytes

The 16 GiB v5e-1 budget is the headline row. Each point also carries
the reconciliation the ISSUE-8 acceptance demands: ledger-accounted
bytes vs the summed `.nbytes` of the held pytree (must agree within
1%), and a release check (weakref finalizers return the bytes when the
point's tables are dropped — a leak here is a ledger bug, caught
before it lies in production).

Usage: python tools/hbm_report.py [size ...] [--budget-gb G]...
                                  [--out FILE]

Defaults: sizes 50_000 100_000 200_000 (CPU-friendly; a TPU window can
pass 1_000_000 10_000_000), budgets 16 GiB. The JSON document goes to
stdout (and --out FILE); bench.py embeds the same document as the
`hbm_forecast` phase row, so every round commits a memory headline
even when the throughput phases die. `report()` is importable — the
tier-1 test (tests/test_hbm_ledger.py) runs the full fit at small
sizes and asserts the ceiling forecast.

Env knobs: BENCH_HBM_SIZES (comma-separated, overrides argv sizes),
BENCH_HBM_HEADROOM (fraction of the budget reserved for working
buffers / jit programs / runtime, default 0.25 — the ceiling is a
TABLE budget, not a whole-chip budget).
"""

from __future__ import annotations

import gc
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

SCHEMA = "emqx_tpu.hbm_report/v1"
GIB = 1 << 30


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _tree_nbytes(tree) -> int:
    """Summed `.nbytes` of a pytree's array leaves — the ground truth
    the ledger's accounting is reconciled against."""
    from emqx_tpu.broker.hbm_ledger import _leaves
    return sum(int(x.nbytes) for x in _leaves(tree))


def measure_point(subs: int, shared_pct: int = 50) -> dict:
    """Build + device_put one snapshot-table set at `subs`
    subscriptions through a fresh ledger; return the accounting row.

    The row records the ledger's live bytes, the pytree's summed
    nbytes, their relative error, and whether dropping the tables
    returned the ledger to zero (the weakref-release proof)."""
    import jax

    from bench import bench_subtable, device_filter_set
    from emqx_tpu.broker.hbm_ledger import HbmLedger
    from emqx_tpu.models.router_engine import ShapeRouterTables
    from emqx_tpu.ops.shapes import build_shape_tables

    t0 = time.time()
    fs = device_filter_set(subs)
    F = fs["ids"] * fs["nums"]
    shapes = build_shape_tables(fs["rows"], fs["lens"])
    subs_tbl, n_groups = bench_subtable(F, shared_pct)
    ledger = HbmLedger()
    # hbm: the whole point of this put IS the ledger hold below
    tables = ledger.hold(
        "snapshot_tables",
        jax.device_put(ShapeRouterTables(shapes=shapes, subs=subs_tbl)))
    cursors = ledger.hold(
        "snapshot_cursors",
        jax.device_put(np.zeros(n_groups, np.int32)))
    jax.block_until_ready(jax.tree.leaves(tables))
    ledger_bytes = ledger.live_bytes()
    tree_bytes = _tree_nbytes(tables) + _tree_nbytes(cursors)
    err = abs(ledger_bytes - tree_bytes) / max(1, tree_bytes)
    row = {
        "subs": int(F),
        "requested_subs": int(subs),
        "ledger_bytes": int(ledger_bytes),
        "tree_bytes": int(tree_bytes),
        "reconcile_err": round(err, 6),
        "categories": {k: v["live_bytes"]
                       for k, v in ledger.section()["categories"].items()},
        "build_s": round(time.time() - t0, 2),
    }
    # release proof: dropping the point's tables must return every
    # byte through the weakref finalizers (no explicit release API
    # exists — automatic release is the design)
    del tables, cursors, shapes, subs_tbl, fs
    gc.collect()
    row["released"] = ledger.live_bytes() == 0 \
        and ledger.live_leaves() == 0
    log(f"point subs={row['subs']}: "
        f"{row['ledger_bytes'] / 1e6:.1f}MB ledgered "
        f"(err {err * 100:.3f}%, released={row['released']}, "
        f"{row['build_s']}s)")
    return row


def fit_points(points: list[dict]) -> dict:
    """Least-squares line through (subs, ledger_bytes): the
    per-subscription byte slope + fixed intercept, with r² so a
    non-linear regime (bucket-table quantization steps) is visible."""
    xs = np.array([p["subs"] for p in points], np.float64)
    ys = np.array([p["ledger_bytes"] for p in points], np.float64)
    if len(xs) == 1:
        # one point fixes only the slope-through-origin
        return {"per_sub_bytes": round(float(ys[0] / xs[0]), 3),
                "intercept_bytes": 0, "r2": None, "points": 1}
    slope, intercept = np.polyfit(xs, ys, 1)
    pred = slope * xs + intercept
    ss_res = float(((ys - pred) ** 2).sum())
    ss_tot = float(((ys - ys.mean()) ** 2).sum())
    return {"per_sub_bytes": round(float(slope), 3),
            "intercept_bytes": int(intercept),
            "r2": round(1.0 - ss_res / ss_tot, 6) if ss_tot else 1.0,
            "points": len(points)}


def ceiling(fit: dict, budget_bytes: int, headroom: float) -> dict:
    """Invert the fit for one HBM budget: how many subscriptions fit
    once `headroom` of the budget is reserved for working buffers,
    compiled programs and the runtime."""
    usable = budget_bytes * (1.0 - headroom)
    per_sub = fit["per_sub_bytes"]
    subs = int((usable - fit["intercept_bytes"]) / per_sub) \
        if per_sub > 0 else 0
    return {"budget_bytes": int(budget_bytes),
            "headroom": headroom,
            "table_budget_bytes": int(usable),
            "ceiling_subs": max(0, subs)}


def report(sizes=(50_000, 100_000, 200_000), budgets_gb=(16,),
           shared_pct: int = 50, headroom: float = None) -> dict:
    """The full forecast document (importable: bench.py's hbm phase and
    the tier-1 test both call this)."""
    if headroom is None:
        headroom = float(os.environ.get("BENCH_HBM_HEADROOM", 0.25))
    t0 = time.time()
    points = [measure_point(s, shared_pct) for s in sorted(sizes)]
    fit = fit_points(points)
    budgets = {f"{g:g}GB": ceiling(fit, g * GIB, headroom)
               for g in budgets_gb}
    head_g = f"{budgets_gb[0]:g}"
    doc = {
        "schema": SCHEMA,
        "workload": f"device/{{id}}/+/{{num}}/# {shared_pct}% shared",
        "points": points,
        "fit": fit,
        "budgets": budgets,
        "headline": {
            "budget": f"{head_g}GB",
            "per_sub_bytes": fit["per_sub_bytes"],
            "ceiling_subs": budgets[f"{head_g}GB"]["ceiling_subs"],
            "target_10m_fits":
                budgets[f"{head_g}GB"]["ceiling_subs"] >= 10_000_000,
        },
        "elapsed_s": round(time.time() - t0, 1),
    }
    from emqx_tpu.broker.hbm_ledger import device_memory_stats
    dev = device_memory_stats()
    if dev is not None:
        doc["device"] = dev
    log(f"forecast: {fit['per_sub_bytes']:.1f} B/sub -> "
        f"{doc['headline']['ceiling_subs'] / 1e6:.1f}M subs in "
        f"{head_g}GB (10M fits: {doc['headline']['target_10m_fits']})")
    return doc


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    sizes, budgets, out = [], [], None
    it = iter(argv)
    for a in it:
        if a == "--budget-gb":
            v = next(it, None)
            if v is None:
                print("hbm_report: --budget-gb requires a value",
                      file=sys.stderr)
                return 2
            budgets.append(float(v))
        elif a.startswith("--budget-gb="):
            budgets.append(float(a.split("=", 1)[1]))
        elif a == "--out":
            out = next(it, None)
        elif a.startswith("--out="):
            out = a.split("=", 1)[1]
        else:
            sizes.append(int(a))
    env_sizes = os.environ.get("BENCH_HBM_SIZES")
    if env_sizes:
        sizes = [int(s) for s in env_sizes.split(",") if s.strip()]
    doc = report(sizes or (50_000, 100_000, 200_000),
                 budgets or (16,))
    text = json.dumps(doc)
    print(text, flush=True)
    if out:
        with open(out, "w") as f:
            f.write(text)
    # exit 2 when the release proof failed — CI catches ledger leaks
    return 0 if all(p["released"] for p in doc["points"]) else 2


if __name__ == "__main__":
    sys.exit(main())
