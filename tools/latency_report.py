#!/usr/bin/env python
"""Offline per-path latency percentile / SLO report (ISSUE 13).

Renders the latency observatory's schema (``emqx_tpu.latency/v1``) from
a bench artifact — the merged bench JSON, a single phase row, or a
``BENCH_CHECKPOINT`` file — without importing jax or the broker:

    python tools/latency_report.py BENCH_r06.json
    python tools/latency_report.py /tmp/bench_ckpt.json
    python tools/latency_report.py --require e2e_device BENCH_r06.json

Exit codes (the CI gate a future relay round cannot sneak past):

    0  every required row carries a latency section; report printed
    1  usage / unreadable / unparseable input
    2  a required bench row carries NO latency section — the round is
       about to commit a p99-less headline (exactly the r02..r05
       failure mode: tail numbers that are either missing or
       relay-contaminated). The offending rows are named on stderr.

By default the required rows are every phase row PRESENT in the
artifact from {phase0, latency0, e2e_host, e2e_device} — a row that
ran but lost its latency section fails; a phase that never ran (e.g.
BENCH_E2E=0) is not invented. ``--require a,b`` pins an explicit list
instead (a named row that is absent then also fails: the gate is "this
round MUST carry these measured tails"). The microbench rows
``sharded`` and ``cover`` are also requirable: they carry matches/s +
speedup headlines instead of a latency section, so for them the gate
is row presence and the report prints their scalar summary.
"""

from __future__ import annotations

import json
import sys

# the phase rows that must carry a latency section when present
DEFAULT_ROWS = ("phase0", "latency0", "e2e_host", "e2e_device")
# microbench phase rows --require can pin: they carry their own metric
# (matches/s, speedup, reduction) instead of a latency section, so the
# gate checks PRESENCE and renders the headline numbers
MICRO_ROWS = ("sharded", "cover")


def _rows_of(doc: dict) -> dict:
    """Candidate phase rows from any supported artifact shape."""
    if not isinstance(doc, dict):
        return {}
    # checkpoint file: {"sig": ..., "phases": {name: row}}
    if "phases" in doc and isinstance(doc["phases"], dict):
        return {k: v for k, v in doc["phases"].items()
                if isinstance(v, dict)}
    # a single phase row passed directly
    if "latency" in doc and not any(k in doc for k in DEFAULT_ROWS):
        return {"row": doc}
    # merged bench JSON: phase rows are top-level keys
    return {k: v for k, v in doc.items()
            if k in DEFAULT_ROWS + MICRO_ROWS and isinstance(v, dict)}


def _render_micro(name: str, row: dict) -> str:
    """Headline numbers of a latency-less microbench row (one line per
    nesting level — enough for the round log, not a full report)."""
    def scalars(d):
        return {k: v for k, v in d.items()
                if isinstance(v, (int, float, str, bool))}

    out = [f"== {name} =="]
    top = scalars(row)
    if top:
        out.append("  " + " ".join(f"{k}={v}"
                                   for k, v in sorted(top.items())))
    for k in sorted(row):
        v = row[k]
        if isinstance(v, dict):
            s = scalars(v)
            if s:
                out.append(f"  {k}: " + " ".join(
                    f"{kk}={vv}" for kk, vv in sorted(s.items())))
    return "\n".join(out)


def _latency_of(row: dict):
    """The latency section of one phase row (latency0 nests it)."""
    lat = row.get("latency")
    if isinstance(lat, dict) and (lat.get("routed")
                                  or lat.get("delivered")
                                  or lat.get("slo")):
        return lat
    return None


def _fmt_leg(name: str, series: dict, out: list) -> None:
    if not series:
        return
    out.append(f"  {name} (ms):")
    out.append(f"    {'series':<22}{'count':>9}{'p50':>10}"
               f"{'p99':>10}{'p999':>10}")
    for key in sorted(series):
        row = series[key]
        out.append(f"    {key:<22}{row.get('count', 0):>9}"
                   f"{row.get('p50_ms', 0):>10}"
                   f"{row.get('p99_ms', 0):>10}"
                   f"{row.get('p999_ms', 0):>10}")


def _overload_of(row: dict):
    """The overload section riding a phase row (ISSUE 14): either
    embedded directly (overload_bench rows) or inside the row's full
    telemetry snapshot (e2e phase rows)."""
    if not isinstance(row, dict):
        return None
    ov = row.get("overload")
    if not isinstance(ov, dict):
        ov = (row.get("telemetry") or {}).get("overload") \
            if isinstance(row.get("telemetry"), dict) else None
    return ov if isinstance(ov, dict) else None


def render(name: str, lat: dict, overload=None) -> str:
    out = [f"== {name} =="]
    _fmt_leg("ingress→routed", lat.get("routed") or {}, out)
    _fmt_leg("ingress→delivered", lat.get("delivered") or {}, out)
    slo = lat.get("slo") or {}
    if slo:
        out.append(
            f"  SLO: routed p99 {slo.get('routed_p99_ms')}ms vs "
            f"objective {slo.get('objective_p99_ms')}ms -> "
            f"{str(slo.get('verdict', '?')).upper()}"
            f"  (samples {slo.get('samples')}, breaches "
            f"{slo.get('breaches')}, burn {slo.get('burn')})")
    if overload:
        # the governor's sheds NEXT TO the p99 (ISSUE 14): a tail
        # measured while load was being shed must say so — a p99 with
        # qos0_shed > 0 measures the governed broker, not raw capacity
        state = overload.get("state") or {}
        parts = [f"grade={state.get('grade', '?')}"]
        for k in ("qos0_shed", "connects_rejected", "disconnects",
                  "retained_deferred", "sheds", "grade_changes"):
            v = overload.get(k)
            if v:
                parts.append(f"{k}={v}")
        out.append("  overload: " + " ".join(parts))
    for ex in (lat.get("exemplars") or [])[-3:]:
        out.append(f"  exemplar: {ex.get('latency_ms')}ms "
                   f"path={ex.get('path')} qos={ex.get('qos')} "
                   f"topic={ex.get('topic')} "
                   f"trace={ex.get('trace_id')}")
    return "\n".join(out)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    require = None
    if "--require" in argv:
        i = argv.index("--require")
        if i + 1 >= len(argv):
            print("latency_report: --require needs a comma-separated "
                  "row list", file=sys.stderr)
            return 1
        require = [r for r in argv[i + 1].split(",") if r]
        del argv[i:i + 2]
    if len(argv) != 1:
        print(__doc__, file=sys.stderr)
        return 1
    try:
        with open(argv[0]) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"latency_report: cannot read {argv[0]}: {e}",
              file=sys.stderr)
        return 1
    rows = _rows_of(doc)
    wanted = require if require is not None else \
        [n for n in rows if n in DEFAULT_ROWS or n == "row"]
    missing = []
    printed = 0
    for name in wanted:
        row = rows.get(name)
        if name in MICRO_ROWS:
            # microbench rows (sharded/cover) carry their own metric,
            # not a latency section: the gate is row PRESENCE
            if row is None:
                missing.append(name)
                continue
            print(_render_micro(name, row))
            printed += 1
            continue
        lat = _latency_of(row) if row else None
        if lat is None:
            missing.append(name)
            continue
        print(render(name, lat, overload=_overload_of(row)))
        printed += 1
    if missing:
        print(f"latency_report: required bench rows missing or carry "
              f"NO latency section: {missing} — this round would "
              f"commit a p99-less headline (run with "
              f"EMQX_TPU_LATENCY=1 / BENCH_LATENCY0=1)",
              file=sys.stderr)
        return 2
    if not printed:
        print("latency_report: artifact contains no latency-bearing "
              "phase rows at all", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
