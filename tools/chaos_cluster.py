#!/usr/bin/env python
"""Cluster chaos drive: node kills AND freezes under continuous QoS1
traffic.

The reference's failure story is tested with docker-compose node kills
(scripts/ + emqx_takeover_SUITE.erl); this is the sharper analog: a
3-OS-process cluster where, each cycle, a random non-seed node is either
SIGKILLed (crash) or SIGSTOPped (gray failure: TCP open, nothing
answers) mid-flood. Its clients re-home to a survivor (cross-node
takeover of the same clientid against the corpse/frozen owner), the
victim is restarted/thawed, and the invariants asserted every cycle:

  1. CONNECT to any survivor completes fast — a dead peer must never
     park the clientid lock; a FROZEN peer costs at most the bounded
     RPC timeouts (connect/handshake, lock, takeover).
  2. QoS1 publishes keep earning PUBACKs throughout the outage.
  3. The anchor subscriber (on the seed) resumes receiving within the
     bound — routes survive peer death.
  4. After heal/thaw, membership converges back to 3 running nodes.
  5. A node restarted at NEW dynamic ports is deliverable-to again
     (peer re-addressing + replication incarnation).

CHAOS_MODE=kill|freeze|mixed (default mixed), CHAOS_SEED, CHAOS_LAX,
CHAOS_QOS=2 (drive at QoS 2 and assert exactly-once), CHAOS_DEVICE=1.
Usage: python tools/chaos_cluster.py [cycles]    (default 6)

Exit 0 with "CHAOS OK" on success; assertion failure otherwise.
"""

import asyncio
import os
import random
import signal
import subprocess
import sys
import time

REPO = os.path.join(os.path.dirname(__file__), "..")
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

# latency-bound scale: the bounds separate "healthy" (<2s) from the
# 35s-stall bug class; under heavy CPU contention (full pytest suite +
# 5 broker processes on a small box) honest 2s bounds flake, so the
# in-suite wrapper runs with CHAOS_LAX=3
LAX = float(os.environ.get("CHAOS_LAX", "1"))
# CHAOS_QOS=2 runs the whole drive at QoS 2: the anchor then also
# asserts EXACTLY-once (a duplicate delivery fails the run)
QOS = int(os.environ.get("CHAOS_QOS", "1"))


def spawn(name, join=None):
    from test_two_process_cluster import _readline_deadline
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    cmd = [sys.executable, os.path.join(REPO, "tools", "run_node.py"),
           "--name", name]
    if os.environ.get("CHAOS_DEVICE", "0") != "1":
        cmd.append("--no-device")   # CHAOS_DEVICE=1: serve through the
        # batcher + device engine (CPU backend) so kills/freezes also
        # exercise the fused serving path
    if join:
        cmd += ["--join", join]
    p = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL, env=env)
    line = _readline_deadline(p, 60).strip()
    assert line.startswith("READY "), f"{name}: {line}"
    _, mqtt, rpc = line.split()
    rec = {"p": p, "mqtt": int(mqtt), "rpc": int(rpc), "name": name}
    _ALL_PROCS.append(rec)
    return rec


async def connect_fast(port, clientid, bound_s=None):
    """Invariant 1: CONNECT to a live node must complete inside bound_s
    even right after a peer died (pre-nodedown-detection window)."""
    bound_s = (bound_s or 2.0) * LAX
    from emqx_tpu.client import Client
    c = Client(port=port, clientid=clientid)
    t0 = time.monotonic()
    await c.connect(timeout=bound_s + 3)
    dt = time.monotonic() - t0
    assert dt < bound_s, f"CONNECT took {dt:.1f}s (> {bound_s}s) on :{port}"
    return c


async def main(cycles: int) -> None:
    from emqx_tpu.mqtt import packet as P

    seed = spawn("a@127.0.0.1")
    b = spawn("b@127.0.0.1", join=f"127.0.0.1:{seed['rpc']}")
    c = spawn("c@127.0.0.1", join=f"127.0.0.1:{seed['rpc']}")
    others = {"b@127.0.0.1": b, "c@127.0.0.1": c}
    procs = [seed, b, c]
    rng = random.Random(int(os.environ.get("CHAOS_SEED", 42)))
    clients: list = []

    anchor = await connect_fast(seed["mqtt"], "anchor")
    await anchor.subscribe([("chaos/#", P.SubOpts(qos=QOS))])

    # shared-group invariant members: one on the seed, one on a node the
    # chaos will kill/freeze — group dispatch (device picks under
    # CHAOS_DEVICE=1, incl. remote-member forwards) must stay
    # exactly-once-per-group at every steady state
    share1 = await connect_fast(seed["mqtt"], "share-1")
    await share1.subscribe([("$share/grp/shgrp/t", P.SubOpts(qos=QOS))])
    share2 = await connect_fast(b["mqtt"], "share-2")
    await share2.subscribe([("$share/grp/shgrp/t", P.SubOpts(qos=QOS))])
    shared_epoch = 0

    def drain_shared():
        got = []
        for s in (share1, share2):
            while not s.messages.empty():
                got.append(s.messages.get_nowait().payload)
        return got

    async def check_shared(pub_client, bound_s=None):
        """Invariant 6: a steady-state burst into the share group lands
        exactly once per message across the members. A settle probe
        first absorbs the post-heal transition (stale members purge,
        dirty slots, snapshot rebuild)."""
        nonlocal shared_epoch
        shared_epoch += 1
        bound_s = (bound_s or 8.0) * LAX
        drain_shared()
        t0 = time.monotonic()
        while time.monotonic() - t0 < bound_s:     # settle probe
            await pub_client.publish("shgrp/t", b"probe", qos=QOS,
                                     timeout=bound_s + 2)
            await asyncio.sleep(0.15)
            if b"probe" in drain_shared():
                break
        else:
            raise AssertionError("share group never resumed")
        mark = f"e{shared_epoch}-".encode()
        expected = [mark + str(i).encode() for i in range(10)]
        for p in expected:
            await pub_client.publish("shgrp/t", p, qos=QOS,
                                     timeout=bound_s + 2)
        got: list = []
        t0 = time.monotonic()
        while time.monotonic() - t0 < bound_s:
            got += [p for p in drain_shared() if p.startswith(mark)]
            if len(got) >= len(expected):
                break
            await asyncio.sleep(0.1)
        # grace drain: a late DUPLICATE must not escape the assertion by
        # arriving after the count was reached
        await asyncio.sleep(0.5 * LAX)
        got += [p for p in drain_shared() if p.startswith(mark)]
        assert sorted(got) == sorted(expected), \
            f"shared group: want {len(expected)} exactly-once, got {got}"

    seq = 0
    received: set = set()
    dupes: list = []

    async def drain_anchor():
        while not anchor.messages.empty():
            m = anchor.messages.get_nowait()
            n = int(m.payload)
            if n in received and QOS == 2:
                dupes.append(n)
            received.add(n)

    async def publish_burst(cl, n, bound_s=None):
        """Invariant 2: every QoS1 publish earns its PUBACK in bound."""
        bound_s = (bound_s or 3.0) * LAX
        nonlocal seq
        for _ in range(n):
            t0 = time.monotonic()
            await cl.publish("chaos/t", str(seq).encode(), qos=QOS,
                             timeout=bound_s + 2)
            dt = time.monotonic() - t0
            assert dt < bound_s, f"PUBACK took {dt:.1f}s"
            seq += 1
            await asyncio.sleep(0)

    async def wait_resume(deadline_s=None, bound_s=None):
        """Invariant 3: the anchor sees NEW messages within the bound."""
        deadline_s = (deadline_s or 8.0) * LAX
        start_seq = seq
        pub2 = await connect_fast(seed["mqtt"], "probe-pub",
                                  bound_s=bound_s)
        t0 = time.monotonic()
        while time.monotonic() - t0 < deadline_s:
            await publish_burst(pub2, 1, bound_s=bound_s)
            await asyncio.sleep(0.1)
            await drain_anchor()
            if any(s >= start_seq for s in received):
                await pub2.disconnect()
                return
        raise AssertionError(f"anchor got nothing new in {deadline_s}s")

    async def wait_members(n, deadline_s=None):
        """Invariant 4: membership converges to n running nodes."""
        deadline_s = (deadline_s or 15.0) * LAX
        from emqx_tpu.cluster.rpc import RpcNode
        probe = RpcNode("probe@x", port=0)
        await probe.start()
        try:
            probe.add_peer("seed", "127.0.0.1", seed["rpc"])
            t0 = time.monotonic()
            last = None
            while time.monotonic() - t0 < deadline_s:
                try:
                    info = await probe.call("seed", "ekka.heartbeat",
                                            ["probe@x", None], timeout=2)
                    last = sorted(k for k, v in info.items()
                                  if v["status"] == "running"
                                  and not k.startswith("probe"))
                    if len(last) == n:
                        return
                except Exception:  # noqa: BLE001 — retry until deadline
                    pass
                await asyncio.sleep(0.3)
            raise AssertionError(f"membership stuck at {last}, want {n}")
        finally:
            await probe.stop()

    # steady state: publisher on b, extra subscriber on c
    pub = await connect_fast(b["mqtt"], "chaos-pub")
    extra = await connect_fast(c["mqtt"], "extra-sub")
    await extra.subscribe([("chaos/#", P.SubOpts(qos=1))])
    await publish_burst(pub, 20)
    await wait_resume()
    await check_shared(pub)

    for cycle in range(cycles):
        victim_name = rng.choice(list(others))
        victim = others[victim_name]

        # mixed mode: some cycles FREEZE (SIGSTOP — gray failure: TCP
        # open, nothing answers) instead of killing. Bounds are larger:
        # pre-detection, each RPC against the frozen node costs its
        # short timeout rather than failing instantly.
        mode = os.environ.get("CHAOS_MODE", "mixed")
        freeze = mode == "freeze" or (mode == "mixed"
                                      and cycle % 3 == 2)
        if freeze:
            print(f"[cycle {cycle}] SIGSTOP {victim_name}", flush=True)
            os.kill(victim["p"].pid, signal.SIGSTOP)
            try:
                if pub.port == victim["mqtt"]:
                    # re-home: same clientid, owner frozen — takeover
                    # must give up on the corpse within its bound
                    pub = await connect_fast(seed["mqtt"], "chaos-pub",
                                             bound_s=8.0)
                if extra.port == victim["mqtt"]:
                    extra = await connect_fast(seed["mqtt"], "extra-sub",
                                               bound_s=8.0)
                    await extra.subscribe([("chaos/#", P.SubOpts(qos=1))])
                # share2 is NOT re-homed on freeze: its socket to the
                # frozen node survives the thaw (deliveries buffer in
                # the socket), and a same-clientid reconnect would
                # leave a zombie member behind — the discard RPC times
                # out against the frozen owner
                probe = await connect_fast(seed["mqtt"],
                                           f"frz-{cycle}", bound_s=8.0)
                await probe.disconnect()
                await publish_burst(pub, 10, bound_s=8.0)
                await wait_resume(deadline_s=16.0, bound_s=8.0)
            finally:
                os.kill(victim["p"].pid, signal.SIGCONT)
            await wait_members(3)             # thaw: autoheal
            await publish_burst(pub, 10)
            await wait_resume()
            await check_shared(pub)           # invariant 6 after thaw
            print(f"[cycle {cycle}] thawed, seq={seq}, "
                  f"anchor_received={len(received)}", flush=True)
            continue

        print(f"[cycle {cycle}] kill -9 {victim_name}", flush=True)
        victim["p"].kill()
        victim["p"].wait(10)

        # clients that lived on the victim re-home to the seed with the
        # SAME clientid — exercises cross-node takeover while the old
        # owner is an undetected corpse
        if pub.port == victim["mqtt"]:
            pub = await connect_fast(seed["mqtt"], "chaos-pub")
        if extra.port == victim["mqtt"]:
            extra = await connect_fast(seed["mqtt"], "extra-sub")
            await extra.subscribe([("chaos/#", P.SubOpts(qos=1))])
        if share2.port == victim["mqtt"]:
            share2 = await connect_fast(seed["mqtt"], "share-2")
            await share2.subscribe(
                [("$share/grp/shgrp/t", P.SubOpts(qos=QOS))])

        await publish_burst(pub, 10)          # invariant 2 during outage
        await wait_resume()                   # invariant 3

        # heal: restart victim, rejoin
        fresh = spawn(victim_name, join=f"127.0.0.1:{seed['rpc']}")
        others[victim_name] = fresh
        procs.append(fresh)
        await wait_members(3)                 # invariant 4
        await publish_burst(pub, 10)
        await wait_resume()

        # invariant 5: the REJOINED node (new dynamic ports) must be
        # deliverable-to from survivors — the stale-peer regression
        # (add_peer keeping the old channel pool) made exactly this path
        # silently dead while everything else stayed green
        back = await connect_fast(fresh["mqtt"], f"back-{cycle}")
        await back.subscribe([(f"back/{cycle}", P.SubOpts(qos=1))])
        t0 = time.monotonic()
        got_back = False
        while time.monotonic() - t0 < 8.0 and not got_back:
            await pub.publish(f"back/{cycle}", b"x", qos=1, timeout=5)
            try:
                await asyncio.wait_for(back.messages.get(), 0.3)
                got_back = True
            except asyncio.TimeoutError:
                pass
        assert got_back, f"rejoined {victim_name} unreachable (stale peer)"
        await back.disconnect()
        await check_shared(pub)               # invariant 6 after heal
        print(f"[cycle {cycle}] healed, seq={seq}, "
              f"anchor_received={len(received)}", flush=True)

    await drain_anchor()
    # the anchor lives on the never-killed seed: everything published
    # while it was subscribed must have arrived (QoS1, local or relayed
    # from a LIVE publisher node — kills happen between bursts)
    missing = [s for s in range(seq) if s not in received]
    assert not missing, f"anchor lost {len(missing)} messages: " \
                        f"{missing[:10]}..."
    assert not dupes, f"QoS2 duplicates delivered: {dupes[:10]}"
    print(f"CHAOS OK: {cycles} cycles, {seq} published, "
          f"{len(received)} received, 0 lost", flush=True)
    for cl in (anchor, pub, extra):
        try:
            await cl.disconnect()
        except Exception:  # noqa: BLE001
            pass


def _reap():
    """Kill every node this drive spawned — an assertion failure must
    not leak broker processes onto the box (leaked nodes kept beating
    and skewed later benchmarks). SIGCONT first so a frozen victim's
    kill takes effect immediately."""
    for pr in _ALL_PROCS:
        if pr["p"].poll() is None:
            try:
                os.kill(pr["p"].pid, signal.SIGCONT)
            except ProcessLookupError:
                pass
            pr["p"].kill()
    for pr in _ALL_PROCS:
        try:
            pr["p"].wait(5)
        except Exception:  # noqa: BLE001
            pass


_ALL_PROCS: list = []


if __name__ == "__main__":
    try:
        asyncio.run(main(int(sys.argv[1]) if len(sys.argv) > 1 else 6))
    finally:
        _reap()
