#!/usr/bin/env python
"""Sustained-overdrive overload benchmark: the ISSUE-14 acceptance row.

A real-TCP flood deliberately sized past the box's capacity — many
connections each writing a pre-serialized stream of QoS0 PUBLISHes with
QoS1 rows interleaved — run twice in subprocess isolation:

  governor=1  broker.overload on: the graded load-shed ladder climbs,
              sheds ONLY QoS0 at batcher admit, and the routed p99 of
              what it accepts stays inside the configured SLO
  governor=0  the pre-ISSUE-14 broker: nothing sheds, every message
              queues, and the ingress→routed tail saturates (p99
              blowout — the latency IS the unbounded queue wait)

The oracle (graded by the parent):

- **QoS1 is never shed**: the governor-on twin delivers exactly as
  many QoS1 messages as the governor-off twin (and as were sent), in
  per-publisher order (payload-sequence monotone per connection);
- **only QoS0 sheds**: `pipeline.overload.qos0_shed` > 0 on the
  governor-on twin, 0 on the off twin;
- **the SLO holds under the governor**: the latency observatory's
  merged routed p99 <= the objective on the on-twin, while the
  off-twin's p99 demonstrably blows past it;
- **recovery**: after the flood drains the governor steps back to
  `normal` with every shed action unwound.

Env knobs: OVERLOAD_CONNS (16), OVERLOAD_MSGS_PER_CONN (7000),
OVERLOAD_QOS1_EVERY (16: every Nth row is QoS1), OVERLOAD_TOPICS (8),
OVERLOAD_PAYLOAD (64), OVERLOAD_SLO_MS (500: the CPU-honest objective;
the hardware target stays 2ms), OVERLOAD_TIMEOUT_S (240),
OVERLOAD_ONE_TIMEOUT_S (420), OVERLOAD_POLL_S (0.05: governor\
tick), OVERLOAD_RATE_MSGS_S (18000: aggregate paced inflow —\
size it above the box's routing capacity).

Run directly or as `python bench.py` (the `overload` checkpointed
phase, BENCH_OVERLOAD=0 skips).
"""

import asyncio
import gc
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _blob(conn_id: int, n_msgs: int, n_topics: int, payload: int,
          qos1_every: int) -> bytes:
    """One publisher's whole flood, pre-serialized: QoS0 rows with a
    QoS1 row every `qos1_every` frames (own topic family so the
    subscriber can tally the legs separately). Payload head is
    (conn, seq) for the per-publisher order oracle."""
    from emqx_tpu.mqtt import packet as P
    from emqx_tpu.mqtt.frame import serialize
    out = bytearray()
    pad = b"x" * max(0, payload - 16)
    pid = 0
    for i in range(n_msgs):
        head = b"%08d%08d" % (conn_id, i)
        if qos1_every and i % qos1_every == qos1_every - 1:
            pid = pid % 65535 + 1
            out += serialize(P.Publish(
                topic=f"ov/q1/t{i % n_topics}", payload=head + pad,
                qos=1, packet_id=pid), 4)
        else:
            out += serialize(P.Publish(
                topic=f"ov/q0/t{i % n_topics}", payload=head + pad,
                qos=0), 4)
    return bytes(out)


async def _connect_raw(port: int, clientid: str):
    from emqx_tpu.mqtt import packet as P
    from emqx_tpu.mqtt.frame import FrameParser, serialize
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(serialize(P.Connect(proto_name="MQTT", proto_ver=4,
                                     clientid=clientid), 4))
    await writer.drain()
    parser = FrameParser(version=4)
    while True:
        data = await reader.read(64)
        if not data:
            raise RuntimeError("connection closed before CONNACK")
        if parser.feed(data):
            return reader, writer


async def _run_child(governor: bool) -> dict:
    from emqx_tpu.broker.connection import Listener
    from emqx_tpu.broker.node import Node
    from emqx_tpu.client import Client

    conns = int(os.environ.get("OVERLOAD_CONNS", 16))
    n_msgs = int(os.environ.get("OVERLOAD_MSGS_PER_CONN", 7000))
    qos1_every = int(os.environ.get("OVERLOAD_QOS1_EVERY", 16))
    n_topics = int(os.environ.get("OVERLOAD_TOPICS", 8))
    payload = int(os.environ.get("OVERLOAD_PAYLOAD", 64))
    slo_ms = float(os.environ.get("OVERLOAD_SLO_MS", 500))
    timeout_s = float(os.environ.get("OVERLOAD_TIMEOUT_S", 240))
    poll_s = float(os.environ.get("OVERLOAD_POLL_S", 0.05))

    node = Node({"broker": {"overload": governor,
                            "slo_route_p99_ms": slo_ms},
                 "log": {"enable": False}})
    lst = Listener(node, bind="127.0.0.1", port=0)
    await lst.start()
    node.start_timers(poll_s)
    gov = node.overload_governor
    grade_max = [0]
    if gov is not None:
        # overdrive on a 2-core CI box must still climb the ladder
        # deterministically: tighten the sustain windows (the
        # production defaults ride the 1s housekeeping tick; the bench
        # polls at poll_s)
        gov.up_sustain = 2
        # the steady phase must STAY shed for its whole measured span:
        # a sustained-healthy interval of down_sustain polls would
        # otherwise re-admit QoS0 mid-measurement and the p99 would
        # grade the oscillation, not the governed state
        gov.down_sustain = int(os.environ.get("OVERLOAD_DOWN_SUSTAIN",
                                              200))
        # engagement thresholds sized to this flood's queue dynamics:
        # under burst-synchronized backpressure the submit-queue fill
        # equilibrates around ~0.8 of max_pending regardless of how
        # far demand exceeds capacity, so the production 0.9 critical
        # bound never triggers — the bench (like the tier-1 drive
        # test) configures the ladder for its shape
        gov.thresholds = dict(gov.thresholds,
                              queue_fill=(0.25, 0.45, 0.65))

    sub = Client(port=lst.port, clientid="ov-sub")
    await sub.connect()
    # qos=0 grants: deliveries are plain socket writes, so the
    # subscriber's session window/mqueue can never become the measured
    # wall — the invariant under test is the BROKER never shedding
    # QoS1 at admit, not subscriber ack throughput
    await sub.subscribe("ov/q1/#", qos=0)
    await sub.subscribe("ov/q0/#", qos=0)
    q1_delivered = [0]
    q0_delivered = [0]
    order_violations = [0]
    last_seq: dict = {}

    async def _drain_sub():
        while True:
            msg = await sub.messages.get()
            head = bytes(msg.payload[:16])
            conn_id, seqno = int(head[:8]), int(head[8:])
            if msg.topic.startswith("ov/q1/"):
                q1_delivered[0] += 1
                # per-publisher order: QoS1 seq must be monotone per
                # conn (QoS0 rows may be shed BETWEEN them — monotone,
                # not contiguous, is the preserved invariant)
                if last_seq.get(conn_id, -1) >= seqno:
                    order_violations[0] += 1
                last_seq[conn_id] = seqno
            else:
                q0_delivered[0] += 1

    drain_task = asyncio.create_task(_drain_sub())

    # warm pass (same discipline as ingress_bench): the flood's window
    # class must be compiled BEFORE the measured span, or a handful of
    # cold-class device windows (seconds of XLA-CPU compile) become the
    # governed twin's tail — a compile stall is not overload
    eng = node.device_engine
    if eng is not None:
        warm_r, warm_w = await _connect_raw(lst.port, "ovwarm")
        wblob = b"".join(
            _blob(99, 64, n_topics, payload, 0) for _ in range(2))
        warm_w.write(wblob)
        await warm_w.drain()
        wdeadline = time.perf_counter() + 30
        while node.metrics.val("messages.publish") < 128 \
                and time.perf_counter() < wdeadline:
            await asyncio.sleep(0.05)
        bmax = node.publish_batcher.max_batch \
            if node.publish_batcher is not None else 1024
        wdeadline = time.perf_counter() + 90
        while time.perf_counter() < wdeadline:
            try:
                if eng.batch_class_warm(bmax):
                    break
                eng._kick_class_warm()
            except Exception:  # noqa: BLE001 — engine w/o snapshot
                break
            await asyncio.sleep(0.05)
        warm_w.close()

    pairs = [await _connect_raw(lst.port, f"ovpub{i}")
             for i in range(conns)]
    blobs = [_blob(i, n_msgs, n_topics, payload, qos1_every)
             for i in range(conns)]
    q1_per_conn = sum(1 for i in range(n_msgs)
                      if qos1_every and i % qos1_every == qos1_every - 1)
    q1_sent = conns * q1_per_conn
    q0_sent = conns * (n_msgs - q1_per_conn)
    async def _sink(reader):
        try:                   # PUBACKs must be read or the peer's
            while True:        # send buffer to us fills
                if not await reader.read(65536):
                    return
        except (ConnectionError, OSError):
            return
    sinks = [asyncio.create_task(_sink(r)) for r, _w in pairs]

    # paced writers: SUSTAINED overdrive is a rate above capacity held
    # for seconds, not one instantaneous burst — each conn streams its
    # blob at rate/conns msgs/s so the aggregate inflow is a steady
    # OVERLOAD_RATE_MSGS_S against the box's routing capacity
    rate = float(os.environ.get("OVERLOAD_RATE_MSGS_S", 18000))
    frame_bytes = None

    async def one(writer, blob):
        per_conn_bps = frame_bytes * (rate / conns)
        w = 0
        start = time.perf_counter()
        try:
            while w < len(blob):
                # clock-corrected pacing: write up to where the target
                # rate says we should be by now (sleep/drain overhead
                # self-corrects instead of silently halving the rate)
                due = int(per_conn_bps
                          * (time.perf_counter() - start + 0.02))
                if due > w:
                    writer.write(blob[w:due])
                    w = due
                    await writer.drain()
                await asyncio.sleep(0.02)
        except (ConnectionError, OSError):
            # the governor's critical-grade offender shed disconnected
            # this flooder mid-stream — that IS the mechanism working;
            # unsent rows were never accepted (the zero-loss oracle
            # compares delivered against broker-ACCEPTED counts)
            pass

    async def poll_grade():
        while gov is not None:
            grade_max[0] = max(grade_max[0], gov.grade)
            await asyncio.sleep(poll_s)
    gtask = asyncio.create_task(poll_grade())

    gc.collect()
    frame_bytes = len(blobs[0]) / n_msgs
    # one CONTINUOUS paced flood; the measured span starts once the
    # steady state is established — governed twin: the ladder reached
    # critical AND the pre-shed backlog drained (QoS0 already admitted
    # predates the shed; steady QoS1 queueing behind it would bill the
    # ramp to the governed p99); off twin: the queue saturated. Then
    # the observatory resets, so the graded p99 measures the steady
    # state each twin actually holds.
    flood_task = asyncio.gather(*[one(w, b)
                                  for (_r, w), b in zip(pairs, blobs)])
    b = node.publish_batcher
    eng_deadline = time.perf_counter() + 30
    if gov is not None:
        while gov.grade < 3 and time.perf_counter() < eng_deadline \
                and not flood_task.done():
            await asyncio.sleep(poll_s)
        while b is not None and time.perf_counter() < eng_deadline \
                and not flood_task.done():
            # flush the PRE-SHED backlog before measuring: formed
            # windows in the _inflight ring (pipeline_depth x
            # max_batch messages) carry ramp-aged stamps that would
            # bill the ramp to the governed p99. Full journal
            # quiescence is NOT required — QoS1 keeps flowing through
            # the measured span by design
            if len(b._queue) <= 64 and b._inflight is not None \
                    and b._inflight.qsize() <= 1:
                break
            await asyncio.sleep(poll_s)
    else:
        # off twin: same relative ramp — a quarter of the flood's paced
        # duration — before the measured span begins (its queue is
        # already deep by then; waiting on a fill level instead proved
        # racy against the drain rate)
        await asyncio.sleep((n_msgs * conns / rate) / 4)
    obs = node.latency_observatory
    if obs is not None:
        obs.reset()
    t0 = time.perf_counter()
    await flood_task

    # settle: QoS1 is the invariant — wait until the broker-accepted
    # QoS1 count stops growing AND every accepted one is delivered
    deadline = t0 + timeout_s
    quiet = 0
    last_recv = -1
    while time.perf_counter() < deadline and quiet < 10:
        recv = node.metrics.val("messages.qos1.received")
        if recv == last_recv and q1_delivered[0] >= recv:
            quiet += 1
        else:
            quiet = 0
        last_recv = recv
        await asyncio.sleep(0.05)
    wall = time.perf_counter() - t0
    # quiesce the QoS0 stragglers
    stable = q0_delivered[0]
    quiet = 0
    qdeadline = time.perf_counter() + 20
    while quiet < 10 and time.perf_counter() < qdeadline:
        await asyncio.sleep(0.05)
        if q0_delivered[0] == stable:
            quiet += 1
        else:
            stable = q0_delivered[0]
            quiet = 0
    # recovery: with the flood gone the governor must walk back down
    recovered = gov is None
    rdeadline = time.perf_counter() + max(
        20, (gov.down_sustain * 4 * poll_s) if gov else 0)
    while gov is not None and time.perf_counter() < rdeadline:
        if gov.grade == 0 and not gov._armed:
            recovered = True
            break
        await asyncio.sleep(poll_s)
    snap = node.pipeline_telemetry.snapshot()
    lat = snap.get("latency") or {}
    slo = lat.get("slo") or {}
    m = node.metrics
    row = {
        "governor": bool(governor),
        "conns": conns,
        "wall_s": round(wall, 3),
        "qos1_sent": q1_sent,
        "qos1_received": m.val("messages.qos1.received"),
        "qos1_delivered": q1_delivered[0],
        "qos0_sent": q0_sent,
        "qos0_delivered": q0_delivered[0],
        "qos0_shed": m.val("pipeline.overload.qos0_shed"),
        "disconnects": m.val("pipeline.overload.disconnects"),
        "order_violations": order_violations[0],
        "routed_p99_ms": slo.get("routed_p99_ms"),
        "objective_p99_ms": slo.get("objective_p99_ms"),
        "verdict": slo.get("verdict"),
        "burn": slo.get("burn"),
        "grade_max": grade_max[0],
        "recovered_to_normal": recovered,
        "overload": snap.get("overload"),
        "latency": lat,
    }
    gtask.cancel()
    drain_task.cancel()
    for s in sinks:
        s.cancel()
    for _r, w in pairs:
        w.close()
    await sub.close()
    node.stop_timers()
    await lst.stop()
    if node.publish_batcher is not None:
        await node.publish_batcher.stop()
    return row


def run_one(governor: bool) -> dict:
    return asyncio.run(_run_child(governor))


def run_overload() -> dict:
    one_timeout = int(os.environ.get("OVERLOAD_ONE_TIMEOUT_S", 420))
    rows = {}
    for governor in (1, 0):
        sp = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--one",
             str(governor)],
            capture_output=True, text=True, timeout=one_timeout)
        row = None
        for ln in reversed(sp.stdout.splitlines()):
            if ln.strip().startswith("{"):
                row = json.loads(ln)
                break
        if row is None:
            raise RuntimeError(
                f"governor={governor} child failed "
                f"rc={sp.returncode}: {sp.stderr[-300:]}")
        rows[governor] = row
        log(f"governor={governor}: routed p99 "
            f"{row['routed_p99_ms']}ms vs SLO "
            f"{row['objective_p99_ms']}ms ({row['verdict']}), "
            f"qos1 {row['qos1_delivered']}/{row['qos1_sent']}, "
            f"qos0 shed {row['qos0_shed']}")
    on, off = rows[1], rows[0]

    def q1_p99(row):
        """Merged p99 of the PROTECTED class (QoS1 — never shed, the
        SLO the governor defends). A handful of pre-shed QoS0
        stragglers settling just inside the measured span carry
        ramp-aged stamps; grading them would grade the ramp."""
        vals = [v.get("p99_ms") or 0
                for k, v in ((row.get("latency") or {})
                             .get("routed") or {}).items()
                if k.startswith("q1.")]
        return max(vals) if vals else 0
    p99_on = q1_p99(on)
    p99_off = q1_p99(off)
    slo = on.get("objective_p99_ms") or 1
    return {
        "metric": "overload_governed_p99",
        "unit": "ms",
        "value": p99_on,
        "value_is": "governed QoS1 routed p99 (the protected class)",
        "overall_p99_on_ms": on.get("routed_p99_ms"),
        "overall_p99_off_ms": off.get("routed_p99_ms"),
        # the four acceptance legs, graded here so a bench row is
        # self-describing (the tier-1 drive test re-asserts them on a
        # smaller deterministic flood)
        "held_slo": bool(p99_on and p99_on <= slo),
        "off_saturated": bool(p99_off and p99_off > slo),
        # zero QoS1 loss = every ACCEPTED QoS1 message delivered, in
        # per-publisher order (an offender disconnect mid-stream means
        # unsent rows were never accepted — not loss; a real client
        # retries unacked QoS1 on reconnect, the at-least-once
        # contract this bench's raw flooders skip)
        "qos1_zero_loss": (
            on["qos1_delivered"] == on["qos1_received"]
            and off["qos1_delivered"] == off["qos1_received"]
            and on["order_violations"] == 0
            and off["order_violations"] == 0),
        "shed_only_qos0": bool(on["qos0_shed"]) and not off["qos0_shed"],
        "recovered": on["recovered_to_normal"],
        # CPU-honest caveat for the held_slo leg: on an XLA-CPU box a
        # single DEVICE window's e2e latency is ~300ms (the ROADMAP
        # item-1 device-e2e wall), so the governed p99 floors at 1-2
        # window latencies regardless of shedding — the leg passes
        # only where window e2e << the objective (real TPU). The
        # structural legs (zero QoS1 loss, shed-only-QoS0, order,
        # recovery, off-twin saturation) are hardware-independent.
        "held_slo_note": (
            "governed p99 is BOUNDED at ~1-2 device-window e2e"
            " latencies; on XLA-CPU that floor can exceed the"
            " objective — compare p99_ratio_off_over_on and the"
            " governed p50 for the shed's effect"),
        "governed_q1_p50_ms": min(
            (v.get("p50_ms") or 1e9
             for k, v in ((on.get("latency") or {}).get("routed")
                          or {}).items() if k.startswith("q1.")),
            default=None),
        "p99_ratio_off_over_on": round(p99_off / p99_on, 2)
        if p99_on else None,
        "governor_on": on,
        "governor_off": off,
    }


def main():
    if "--one" in sys.argv:
        i = sys.argv.index("--one")
        print(json.dumps(run_one(bool(int(sys.argv[i + 1])))),
              flush=True)
        return
    print(json.dumps(run_overload()), flush=True)


if __name__ == "__main__":
    main()
