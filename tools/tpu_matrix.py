#!/usr/bin/env python
"""One-shot TPU measurement matrix: everything round 3 needs from a
single working relay window, in ONE process (concurrent TPU processes
wedge the pool — see .claude/skills/verify/SKILL.md).

Covers, in order of importance:
  1. per-stage profile of the fused step at bench scale (profile_step)
  2. fold backends: xla vs lane-major pallas (match-only window)
  3. rank-scan block-width sweep (the sort-free kernel's knob)
  4. fuse-width sweep (per-dispatch overhead amortization curve)

Prints a JSON summary line at the end; everything logs to stderr as it
goes so a killed run still leaves partial numbers.

Usage: python tools/tpu_matrix.py [subs] [batch]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    subs = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    B = int(sys.argv[2]) if len(sys.argv) > 2 else 131072

    import jax
    import jax.numpy as jnp

    from bench import (device_filter_set, device_topic_batch,
                       make_window_runner, put_tree_chunked, _put_retry)
    from emqx_tpu.models.router_engine import ShapeRouterTables
    from emqx_tpu.ops.fanout import SubTable
    from emqx_tpu.ops.shapes import (build_shape_tables, shape_match,
                                     shape_match_pallas)
    from emqx_tpu.ops.shared import STRATEGY_ROUND_ROBIN

    out = {"subs": subs, "batch": B, "device": str(jax.devices()[0])}
    log(f"matrix: {out}")

    fs = device_filter_set(subs)
    t0 = time.time()
    shapes = build_shape_tables(fs["rows"], fs["lens"])
    out["table_build_s"] = round(time.time() - t0, 2)
    out["table_mb"] = round(sum(np.asarray(v).nbytes
                                for v in shapes) / 1e6)
    log(f"build {out['table_build_s']}s {out['table_mb']}MB")

    F = fs["ids"] * fs["nums"]
    n_shared = F // 2
    group_of = np.arange(n_shared, dtype=np.int32) // 16
    n_groups = max(1, int(group_of.max(initial=0)) + 1)
    fs_start = np.zeros(F + 1, np.int32)
    fs_start[1:n_shared + 1] = 1
    np.cumsum(fs_start, out=fs_start)
    subs_tbl = SubTable(
        np.arange(F + 1, dtype=np.int32), np.arange(F, dtype=np.int32),
        np.ones(F, np.int8), fs_start,
        group_of if n_shared else np.full(1, -1, np.int32),
        np.arange(n_groups + 1, dtype=np.int32) * 8,
        F + np.arange(n_groups * 8, dtype=np.int32),
        np.ones(n_groups * 8, np.int8))
    tables = put_tree_chunked(ShapeRouterTables(shapes=shapes,
                                                subs=subs_tbl))
    jax.block_until_ready(tables)
    cursors0 = _put_retry(np.zeros(n_groups, np.int32))
    strat = _put_retry(np.int32(STRATEGY_ROUND_ROBIN))
    rng = np.random.RandomState(7)
    staged = []
    for _ in range(8):
        tp, tl = device_topic_batch(fs, rng, B)
        staged.append((_put_retry(tp), _put_retry(tl),
                       _put_retry(np.zeros(B, bool)),
                       _put_retry(rng.randint(0, 1 << 30, B)
                                  .astype(np.int32))))
    log("staged")

    # ---- 2. fold backends --------------------------------------------
    def match_window(fn, n=16):
        acc = _put_retry(np.int32(0))
        t0 = time.time()
        for i in range(n):
            t_, l_, d_, _ = staged[i % 8]
            r = fn(tables.shapes, t_, l_, d_)
            acc = acc + r.matches.sum(dtype=jnp.int32)
        _ = int(np.asarray(acc))
        return B * n / (time.time() - t0)

    try:
        rx = shape_match(tables.shapes, *staged[0][:3])
        rp = shape_match_pallas(tables.shapes, *staged[0][:3])
        out["pallas_bit_identical"] = bool(
            (np.asarray(rx.matches) == np.asarray(rp.matches)).all())
        match_window(shape_match, 2)
        match_window(shape_match_pallas, 2)
        out["match_xla_per_s"] = round(match_window(shape_match))
        out["match_pallas_per_s"] = round(match_window(shape_match_pallas))
        log(f"fold: xla {out['match_xla_per_s']/1e6:.1f}M/s "
            f"pallas {out['match_pallas_per_s']/1e6:.1f}M/s "
            f"identical={out['pallas_bit_identical']}")
    except Exception as e:  # noqa: BLE001
        out["pallas_error"] = f"{type(e).__name__}: {str(e)[:160]}"
        log("pallas failed:", out["pallas_error"])

    # ---- 4. fuse-width sweep (also yields the headline number) -------
    out["fuse_sweep"] = {}
    for fuse in (1, 2, 4, 8, 16):
        stacked = tuple(jnp.stack([staged[k % 8][i] for k in range(fuse)])
                        for i in range(4))
        run = make_window_runner(tables, cursors0, strat, stacked, 4, 2)
        run(1)
        n_calls = max(1, 32 // fuse)
        dt = run(n_calls)
        per_s = B * fuse * n_calls / dt
        out["fuse_sweep"][str(fuse)] = round(per_s)
        log(f"fuse={fuse}: {per_s/1e6:.2f}M matches/s "
            f"({dt/ (n_calls*fuse) * 1000:.2f}ms/batch)")
    out["value"] = max(out["fuse_sweep"].values())

    # ---- 3. rank-block sweep (in-process: block width is a static
    # jit arg, so one relay window covers the whole curve) -------------
    import functools

    from emqx_tpu.ops.fanout import shared_slots
    from emqx_tpu.ops.shared import _rank_and_occur_blocked

    @jax.jit
    def mk_sids(tb, t, l, d):
        r = shape_match(tb.shapes, t, l, d)
        s, _ = shared_slots(tb.subs, r.matches, slot_cap=2)
        return s

    sids_staged = [mk_sids(tables, *staged[i][:3]) for i in range(8)]
    jax.block_until_ready(sids_staged)
    out["rank_sweep"] = {}
    for blk in (256, 512, 1024, 2048, 4096):
        f = jax.jit(functools.partial(
            _rank_and_occur_blocked, n_slots=n_groups, block=blk))
        try:
            def run_rank(n):
                acc = _put_retry(np.int32(0))
                t0 = time.time()
                for i in range(n):
                    r, oc = f(sids_staged[i % 8])
                    acc = acc + r.sum(dtype=jnp.int32) \
                        + oc.sum(dtype=jnp.int32)
                _ = int(np.asarray(acc))
                return time.time() - t0
            run_rank(2)
            ms = run_rank(16) / 16 * 1000
            out["rank_sweep"][str(blk)] = round(ms, 2)
            log(f"rank block={blk}: {ms:.2f} ms/batch")
        except Exception as e:  # noqa: BLE001 — record, keep sweeping
            out["rank_sweep"][str(blk)] = f"{type(e).__name__}"
            log(f"rank block={blk} failed: {e}")
    out["rank_block"] = int(os.environ.get("EMQX_TPU_RANK_BLOCK", 512))

    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
