#!/usr/bin/env python
"""Persistent relay-window watcher (round 5, VERDICT item 1).

Rounds 3 and 4 both ended with BENCH value=0 because the axon relay was
dead during the round-end window — while it may well have been alive
mid-round. This watcher runs for the whole round:

  * every POLL_S seconds, cheaply checks for a relay listener
    (`ss -ltn`, can never hang);
  * at the first live window, proves the backend answers with a
    disposable child process (a wedged pool hangs jax.devices() inside
    C, unkillable by Python signals — the watcher itself never imports
    jax);
  * then runs the measurement phases SERIALLY, one child process at a
    time (concurrent pool claims wedge the grant for everyone):
        1. tools/tpu_matrix.py   — per-stage profile, fold backends,
                                   rank-block + fuse-width sweeps
        2. bench.py              — the headline number + configs + e2e
  * merges each phase's JSON into MEASURED_r05.json and git-commits it
    IMMEDIATELY, so a window that dies mid-suite still leaves the
    earlier phases on record and a dead round-end relay can never again
    erase real data.

After a full success it idles (still probing, still logging) and only
re-measures when tools/.remeasure exists — drop that file after landing
a perf change to request a fresh run at the next window.

Round pinning (ISSUE 9 satellite, VERDICT weak #4): the artifact name
is DERIVED, not hardcoded — the current round is the highest N across
`BENCH_r*.json` / `MEASURED_r*.json`, bumped by one when that round's
measurement is already complete (a committed bench value), so a watcher
left running across a round boundary writes `MEASURED_r{N+1}.json`
instead of clobbering a finished round's record. `WATCHER_ROUND=N`
overrides.

Re-arm guard (same satellite): `python tools/relay_watcher.py --rearm`
checks `tools/watcher.pid` and respawns a detached watcher when that
pid is dead — sessions call it at start (bench.py does, gated on a
configured axon pool), so a watcher killed by a container restart can
no longer leave a whole round uncovered. Exit code 0 = a watcher is
running (pre-existing or respawned).

Run:  nohup python tools/relay_watcher.py >> tools/watcher.log 2>&1 &
Stop: kill $(cat tools/watcher.pid)   (ALWAYS stop it before the driver
runs its own round-end bench — two claimants wedge the pool.)
"""

import glob
import json
import os
import re as _re
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REMEASURE = os.path.join(REPO, "tools", ".remeasure")
PIDFILE = os.path.join(REPO, "tools", "watcher.pid")
POLL_S = int(os.environ.get("WATCHER_POLL_S", 20))


def current_round() -> int:
    """The round this watcher measures for: max N over the committed
    BENCH_r*/MEASURED_r* artifacts, +1 when that MEASURED round already
    holds a successful bench value (its record is closed — a new
    measurement belongs to the NEXT round). WATCHER_ROUND overrides."""
    env = os.environ.get("WATCHER_ROUND")
    if env:
        return int(env)
    rounds = [0]
    for path in glob.glob(os.path.join(REPO, "BENCH_r*.json")) \
            + glob.glob(os.path.join(REPO, "MEASURED_r*.json")):
        m = _re.search(r"_r(\d+)\.json$", path)
        if m:
            rounds.append(int(m.group(1)))
    n = max(rounds)
    # a round is CLOSED once either artifact committed a real value —
    # a fresh measurement then belongs to the next round, not to
    # overwriting a finished record
    for name, key in ((f"MEASURED_r{n:02d}.json", ("bench", "value")),
                      (f"BENCH_r{n:02d}.json", ("value",))):
        path = os.path.join(REPO, name)
        if not os.path.exists(path):
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
            for k in key:
                doc = doc.get(k) if isinstance(doc, dict) else None
            if doc:
                return n + 1
        except Exception:  # noqa: BLE001 — half-written: keep the round
            pass
    return max(n, 1)


def artifact_path() -> str:
    """Re-derived on EVERY use (never cached at import): a watcher
    running across a round boundary must start writing the next
    round's artifact, not keep appending to — or overwriting — the
    round it was started in."""
    return os.path.join(REPO, f"MEASURED_r{current_round():02d}.json")


_child = None


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except Exception:  # noqa: BLE001 — unknown: assume alive
        return True
    return True


def rearm() -> int:
    """Session-start guard: respawn a detached watcher when the
    recorded pid is dead. Returns 0 when a watcher is running after the
    call (pre-existing or respawned), 1 when the spawn failed."""
    # a fresh .hold keeps the watcher — pre-existing OR respawned —
    # idle while the re-arming session (often a bench about to claim
    # the pool itself) works; two concurrent claimants wedge the
    # grant, so the hold is armed in BOTH paths. The hold EXPIRES
    # (WATCHER_HOLD_TTL_S, default 2h), so a session that dies without
    # cleanup can no longer silence the watcher for the rest of the
    # round.
    try:
        with open(os.path.join(REPO, "tools", ".hold"), "w") as f:
            f.write(str(time.time()))
    except Exception:  # noqa: BLE001 — hold is a courtesy, not a lock
        pass
    try:
        with open(PIDFILE) as f:
            pid = int(f.read().strip())
        if _pid_alive(pid):
            log(f"rearm: watcher pid={pid} alive; hold refreshed")
            return 0
        log(f"rearm: watcher pid={pid} is dead; respawning")
    except Exception:  # noqa: BLE001 — no/garbled pidfile: spawn
        log("rearm: no live watcher on record; spawning")
    logf = open(os.path.join(REPO, "tools", "watcher.log"), "ab")
    try:
        p = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            stdout=logf, stderr=logf, cwd=REPO,
            start_new_session=True)   # survives the caller's session
    except Exception as e:  # noqa: BLE001
        log(f"rearm: spawn failed: {type(e).__name__}: {e}")
        return 1
    finally:
        logf.close()
    log(f"rearm: spawned watcher pid={p.pid}")
    return 0


def hold_active() -> bool:
    """The foreground-session hold, TTL-bounded: a .hold younger than
    WATCHER_HOLD_TTL_S (default 2h) pauses measuring; an older one is
    stale (the session died without cleanup) and is ignored."""
    path = os.path.join(REPO, "tools", ".hold")
    try:
        age = time.time() - os.path.getmtime(path)
    except OSError:
        return False
    return age < float(os.environ.get("WATCHER_HOLD_TTL_S", 7200))


def log(*a):
    print(time.strftime("[%H:%M:%S]"), *a, file=sys.stderr, flush=True)


def relay_listening() -> bool:
    """The axon relay listens on 127.0.0.1:8082+ when alive. Match the
    local-address column exactly (a dev server on e.g. :8080 must not
    read as a relay window and churn probe children)."""
    import re
    try:
        r = subprocess.run(["ss", "-ltn"], capture_output=True,
                           text=True, timeout=10)
        for ln in r.stdout.splitlines()[1:]:
            cols = ln.split()
            if len(cols) >= 4 and re.search(r":(808[2-9]|809\d)$",
                                            cols[3]):
                return True
        return False
    except Exception:  # noqa: BLE001 — unknown: let the probe decide
        return True


def run_child(argv, timeout, env=None):
    """Run one child, return (rc, stdout_text). SIGKILL on timeout —
    a hung TPU child holds the pool claim, and a plain terminate can
    leave it wedged in C."""
    global _child
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    _child = subprocess.Popen(argv, stdout=subprocess.PIPE,
                              text=True, cwd=REPO, env=full_env)
    try:
        out, _ = _child.communicate(timeout=timeout)
        rc = _child.returncode
    except subprocess.TimeoutExpired:
        _child.kill()
        out, _ = _child.communicate()
        rc = -9
    finally:
        _child = None
    return rc, out or ""


def probe_backend() -> bool:
    rc, out = run_child(
        [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
        timeout=150)
    log(f"backend probe rc={rc} out={out.strip()[-120:]!r}")
    return rc == 0


def load_out() -> dict:
    try:
        with open(artifact_path()) as f:
            return json.load(f)
    except Exception:  # noqa: BLE001
        return {}


def save_and_commit(doc: dict, msg: str):
    out = artifact_path()
    out_name = os.path.basename(out)
    doc["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(out, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    # commit ONLY this file (pathspec form), retrying index.lock races
    # with the foreground session's own commits
    for i in range(12):
        subprocess.run(["git", "add", out_name], cwd=REPO,
                       capture_output=True)
        r = subprocess.run(
            ["git", "commit", "-m", msg, "--", out_name],
            cwd=REPO, capture_output=True, text=True)
        if r.returncode == 0:
            log(f"committed: {msg}")
            return
        time.sleep(5 + i)
    log(f"commit FAILED after retries: {r.stdout} {r.stderr}")


def last_json_line(text: str):
    for ln in reversed(text.splitlines()):
        ln = ln.strip()
        if ln.startswith("{"):
            try:
                return json.loads(ln)
            except Exception:  # noqa: BLE001
                continue
    return None


def measure_window() -> bool:
    """One full measurement pass. Returns True if the headline bench
    phase succeeded with a non-zero value."""
    doc = load_out()
    doc.setdefault("attempts", 0)
    doc["attempts"] += 1
    git_rev = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                             cwd=REPO, capture_output=True, text=True)
    doc["git_rev"] = git_rev.stdout.strip()

    # phase 1: the matrix (sweeps first — they inform the perf work and
    # are the data the judge asked for even if the window dies later)
    log("phase 1: tpu_matrix")
    t0 = time.time()
    rc, out = run_child([sys.executable, "tools/tpu_matrix.py"],
                        timeout=int(os.environ.get("WATCHER_MATRIX_S",
                                                   2400)))
    j = last_json_line(out)
    if j:
        doc["matrix"] = j
        doc["matrix_s"] = round(time.time() - t0)
        save_and_commit(doc, "measure: tpu_matrix sweep on hardware")
        log(f"matrix ok in {doc['matrix_s']}s: value={j.get('value')}")
    else:
        doc["matrix_error"] = f"rc={rc}, no JSON (out tail: {out[-200:]!r})"
        save_and_commit(doc, "measure: tpu_matrix attempt failed")
        log(f"matrix FAILED rc={rc}")
        if not (relay_listening() and probe_backend()):
            return False  # window died; wait for the next one

    # phase 2: the full bench (headline + configs + config5 + e2e)
    log("phase 2: bench.py")
    t0 = time.time()
    rc, out = run_child(
        [sys.executable, "bench.py"],
        timeout=int(os.environ.get("WATCHER_BENCH_S", 4500)),
        env={"BENCH_INIT_TIMEOUT_S": "120"})
    j = last_json_line(out)
    if j:
        doc["bench"] = j
        doc["bench_s"] = round(time.time() - t0)
        ok = bool(j.get("value"))
        save_and_commit(doc, "measure: full bench on hardware"
                        if ok else "measure: bench ran, value=0")
        log(f"bench rc={rc} value={j.get('value')} in {doc['bench_s']}s")
        return ok
    doc["bench_error"] = f"rc={rc}, no JSON (out tail: {out[-200:]!r})"
    save_and_commit(doc, "measure: bench attempt failed")
    log(f"bench FAILED rc={rc}")
    return False


def main():
    if "--rearm" in sys.argv:
        sys.exit(rearm())
    with open(PIDFILE, "w") as f:
        f.write(str(os.getpid()))
    log(f"artifact for this round: {os.path.basename(artifact_path())}")

    def bail(signum, frame):
        log(f"signal {signum}: killing child and exiting")
        if _child is not None:
            try:
                _child.kill()
            except Exception:  # noqa: BLE001
                pass
        os._exit(0)

    signal.signal(signal.SIGTERM, bail)
    signal.signal(signal.SIGINT, bail)

    log(f"watcher up, pid={os.getpid()}, poll={POLL_S}s")
    last_note = 0.0
    cooloff_until = 0.0
    max_attempts = int(os.environ.get("WATCHER_MAX_ATTEMPTS", 8))
    while True:
        have = load_out()
        done = bool(have.get("bench", {}).get("value"))
        want = (not done) or os.path.exists(REMEASURE)
        if hold_active():
            # foreground session is mid-edit/mid-claim; don't measure.
            # TTL-bounded (hold_active): a dead session's stale .hold
            # stops silencing the watcher after WATCHER_HOLD_TTL_S.
            want = False
        if time.time() < cooloff_until:
            want = False  # last pass failed: don't hammer the pool
        if have.get("attempts", 0) >= max_attempts \
                and not os.path.exists(REMEASURE):
            want = False  # persistent failure is not a retry loop

        if want and relay_listening():
            log("relay window detected; probing backend")
            if probe_backend():
                if os.path.exists(REMEASURE):
                    os.unlink(REMEASURE)
                ok = measure_window()
                log(f"measurement pass done, headline_ok={ok}")
                if not ok:
                    # a full failed pass holds the pool claim for up to
                    # ~2h — cool off so the driver (or a later fix) can
                    # get a window instead of a tight rerun loop
                    cooloff_until = time.time() + 900
            else:
                time.sleep(POLL_S)
        else:
            if time.time() - last_note > 600:
                state = "complete; drop tools/.remeasure to re-run" \
                    if done and not want else "waiting for relay window"
                log(f"idle: {state}")
                last_note = time.time()
            time.sleep(POLL_S)


if __name__ == "__main__":
    main()
