#!/usr/bin/env python
"""Boot one broker node as an OS process (the two-node deployment shape
the reference exercises with scripts/start-two-nodes-in-docker.sh).

Usage:
    python tools/run_node.py --name a@127.0.0.1 [--config etc/emqx.conf]
        [--mqtt-port 0] [--rpc-port 0] [--join host:port] [--no-device]

Prints one `READY <mqtt_port> <rpc_port>` line on stdout once serving,
then runs until SIGTERM/SIGINT. A test harness (or an operator) parses
that line to wire clients and cluster joins.
"""

import argparse
import asyncio
import faulthandler
import os
import signal
import sys

# SIGUSR1 dumps every thread's stack to stderr — the first tool to reach
# for when a node stops answering (a wedged loop can't be introspected
# any other way from outside)
faulthandler.register(signal.SIGUSR1)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


async def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--name", default="emqx_tpu@127.0.0.1")
    ap.add_argument("--config", default=None)
    ap.add_argument("--mqtt-port", type=int, default=0)
    ap.add_argument("--rpc-port", type=int, default=0)
    ap.add_argument("--join", default=None, help="seed node host:port")
    ap.add_argument("--no-device", action="store_true")
    args = ap.parse_args()

    from emqx_tpu.broker.connection import Listener
    from emqx_tpu.broker.node import Node
    from emqx_tpu.cluster import ClusterNode

    join_addr = None
    if args.join:
        host, sep, port = args.join.rpartition(":")
        if not sep or not host or not port.isdigit():
            ap.error(f"--join expects host:port, got {args.join!r}")
        join_addr = (host, int(port))

    kw = {"use_device": False} if args.no_device else {}
    if args.config:
        if args.mqtt_port:
            ap.error("--mqtt-port has no effect with --config "
                     "(set the port in the config's listeners block)")
        node = Node.from_config_file(args.config, name=args.name, **kw)
        listeners = await node.start_listeners()
        # advertise the first plain MQTT TCP listener (a ws/quic port
        # would mislead a TCP harness)
        tcp = [lst for lst in listeners if isinstance(lst, Listener)]
        mqtt_port = tcp[0].port if tcp else 0
    else:
        node = Node(name=args.name, **kw)
        lst = Listener(node, bind="127.0.0.1", port=args.mqtt_port)
        await lst.start()
        node.listeners.append(lst)
        mqtt_port = lst.port

    rpc_conf = node.config.get("rpc") or {}
    cluster_conf = node.config.get("cluster") or {}
    cn = ClusterNode(node, port=args.rpc_port,
                     cookie=cluster_conf.get("cookie",
                                             "emqxsecretcookie"),
                     rpc_mode=rpc_conf.get("mode", "async"))
    if rpc_conf.get("tcp_client_num"):
        cn.rpc.n_channels = int(rpc_conf["tcp_client_num"])
    await cn.start()
    if join_addr:
        await cn.join(*join_addr)
    elif cluster_conf.get("discovery", "manual") != "manual":
        # config-driven autocluster (static/dns/etcd/k8s/mcast seeds)
        from emqx_tpu.cluster.discovery import autocluster
        await autocluster(cn)

    node.start_timers()
    if args.config:
        # config-driven feature apps + mgmt REST + dashboard + gateways
        # (after cluster start so the API sees the cluster view)
        await node.start_apps()
        await node.start_dashboard()
        await node.start_gateways()
    print(f"READY {mqtt_port} {cn.address[1]}", flush=True)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)

    def dump_tasks():
        print(f"=== {len(asyncio.all_tasks(loop))} tasks ===",
              file=sys.stderr)
        for t in asyncio.all_tasks(loop):
            print(f"--- {t.get_name()}", file=sys.stderr)
            # walk the await chain (get_stack only shows the outer frame)
            obj = t.get_coro()
            depth = 0
            while obj is not None and depth < 40:
                fr = getattr(obj, "cr_frame", None) or \
                    getattr(obj, "gi_frame", None)
                if fr is not None:
                    print(f"    {fr.f_code.co_filename}:{fr.f_lineno} "
                          f"{fr.f_code.co_name}", file=sys.stderr)
                nxt = getattr(obj, "cr_await", None) or \
                    getattr(obj, "gi_yieldfrom", None)
                if nxt is None:
                    print(f"    -> awaiting {obj!r}"
                          if fr is None else f"    -> leaf {obj!r}",
                          file=sys.stderr)
                obj = nxt
                depth += 1
        sys.stderr.flush()

    # SIGUSR2 dumps every asyncio task's await stack (faulthandler's
    # SIGUSR1 shows threads, but a PARKED coroutine is invisible there)
    loop.add_signal_handler(signal.SIGUSR2, dump_tasks)
    await stop.wait()
    await cn.stop()
    await node.stop_listeners()


if __name__ == "__main__":
    asyncio.run(main())
