#!/usr/bin/env python
"""Churn microbenchmark: the delta overlay's win (ISSUE 4).

Measures topic-matches/sec through the REAL DeviceRouteEngine serving
path (route_batch: prepare → dispatch → materialize → finish, including
the consume stage — churn's cost lives there too) under SUSTAINED
subscription churn (>= 1 route change per batch window), twice on one
machine:

  overlay    delta overlay ON (the default engine): post-snapshot
             filters match + deliver on device, full rebuilds demoted
             to rare compactions
  baseline   delta overlay OFF (EMQX_TPU_DELTA_OVERLAY=0 equivalent):
             the pre-ISSUE-4 behavior — every message pays the host
             delta-trie walk, the vectorized fast consume stands down,
             and the engine full-rebuilds (inline, on this path) every
             `rebuild_threshold` route changes

A third, no-churn pass on the overlay engine records the steady-state
rate, which must stay within noise of the PR-3 numbers (the overlay is
free when the overlay is empty). The JSON row carries matches/sec for
all three, the full-rebuild counts (acceptance: overlay reduced >= 5x),
and the routing.device.host_delta counters (acceptance: overlay ~ 0,
with the baseline's non-zero count measuring the hole being closed).

Env knobs: CHURN_FILTERS (5000), CHURN_BATCH (512), CHURN_BATCHES (48),
CHURN_RATE (4 subscribes/batch), CHURN_LIVE (64 rolling live churn
subscriptions), CHURN_THRESHOLD (32), CHURN_WARM_PASSES (2),
CHURN_COVER_RATIO (0 — >0 swaps in the cover-heavy population from
tools/workloads.py).

Run directly or as `python bench.py --churn`.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


class _Sink:
    def deliver(self, topic_filter, msg):
        return True


def _mk_node(overlay: bool, threshold: int):
    from emqx_tpu.broker.node import Node
    return Node({"broker": {"delta_overlay": overlay,
                            "rebuild_threshold": threshold,
                            "device_fanout_cap": 4,
                            "device_slot_cap": 2}})


def _subscribe_base(node, n_filters: int) -> list:
    """Built-snapshot filters from the shared generator
    (tools/workloads.py, ISSUE 18 satellite): CHURN_COVER_RATIO=0 keeps
    the legacy zero-cover shape-spread population byte-identical (rates
    comparable with history AND with tools/skew_bench.py); >0 switches
    to the cover-heavy population so churn cost can be measured where
    covering actually bites."""
    from tools.workloads import cover_heavy_filters, shape_spread_filters
    ratio = float(os.environ.get("CHURN_COVER_RATIO", 0))
    filters = cover_heavy_filters(n_filters, cover_ratio=ratio) if ratio \
        else shape_spread_filters(n_filters)
    b = node.broker
    sid = b.register(_Sink(), "churn-base")
    for f in filters:
        b.subscribe(sid, f, {"qos": 0})
    return filters


def _topics_for(filters, rng, batch: int, n_batches: int,
                churn_frac: float = 0.25):
    """Per-batch topic lists: mostly built-filter traffic, with a slice
    reserved for churn topics (filled in per round — the messages the
    rolling fresh subscriptions must catch)."""
    from tools.workloads import concretize

    pool = [concretize(f) for f in filters[:4096]]
    out = []
    n_churn = int(batch * churn_frac)
    for _ in range(n_batches):
        idx = rng.randint(0, len(pool), batch - n_churn)
        out.append(([pool[i] for i in idx], n_churn))
    return out


def _run(node, batches, rate: int, label: str):
    """Route every batch; between batches, subscribe `rate` fresh
    filters (the sustained churn). Two identical passes: the first
    warms — route_batch compiles cold program classes IN-PATH by design
    (the serving pipeline's gate_cold machinery compiles them in the
    background instead, which a loop-less bench cannot drive), and the
    churn schedule walks the overlay through its row classes, so pass 1
    pays every XLA compile the steady state needs — the second is the
    measurement. The baseline gets the identical two-pass treatment
    (its full rebuilds recur every `rebuild_threshold` route changes in
    BOTH passes, so they are measured, not amortized away). Returns
    (topics/sec, rebuilds, host_delta) over the timed pass."""
    from emqx_tpu.broker.message import make
    eng = node.device_engine
    b = node.broker
    sid = b.register(_Sink(), f"churn-{label}")
    eng.rebuild()
    seq = 0
    live = []       # rolling window of churn subscriptions (FIFO)
    window = int(os.environ.get("CHURN_LIVE", 64))

    def one_pass():
        nonlocal seq
        total = 0
        for topics, n_churn in batches:
            if rate:
                # rolling churn: subscribe `rate` fresh filters and
                # unsubscribe the oldest once the live window is full —
                # the sub+unsub pattern brokers actually see (clients
                # cycling), not a monotonically growing filter set
                for _ in range(rate):
                    f = f"churn/{label}/{seq}/+"
                    b.subscribe(sid, f, {"qos": 0})
                    live.append(f)
                    seq += 1
                while len(live) > window:
                    b.unsubscribe(sid, live.pop(0))
            fresh = [
                f"churn/{label}/{max(0, seq - 1 - k % max(1, rate))}/z"
                for k in range(n_churn)] if rate else \
                [topics[k % len(topics)] for k in range(n_churn)]
            msgs = [make("p", 0, t, b"x") for t in topics + fresh]
            counts = eng.route_batch(msgs)
            assert counts is not None
            if rate:
                # every fresh-subscription topic must have been
                # delivered — the correctness floor under churn
                assert all(c >= 1 for c in counts[len(topics):]), label
            total += len(msgs)
        return total

    # two warm passes: the first compiles the base + small overlay
    # classes, the second walks the overlay far enough up its row-class
    # ladder that the timed pass's crossings land on already-compiled
    # classes (jit cache hits) instead of multi-second inline traces
    for _ in range(int(os.environ.get("CHURN_WARM_PASSES", 2))):
        one_pass()
    r0 = node.metrics.val("routing.device.rebuilds")
    h0 = node.metrics.val("routing.device.host_delta")
    t0 = time.perf_counter()
    total = one_pass()
    dt = time.perf_counter() - t0
    rebuilds = node.metrics.val("routing.device.rebuilds") - r0
    host_delta = node.metrics.val("routing.device.host_delta") - h0
    log(f"{label}: {total} topics in {dt:.3f}s "
        f"({total / dt / 1e3:.1f}k matches/s, {rebuilds} rebuilds, "
        f"host_delta={host_delta})")
    return total / dt, rebuilds, host_delta


def run_churn() -> dict:
    n_filters = int(os.environ.get("CHURN_FILTERS", 5000))
    batch = int(os.environ.get("CHURN_BATCH", 512))
    n_batches = int(os.environ.get("CHURN_BATCHES", 48))
    rate = int(os.environ.get("CHURN_RATE", 4))
    threshold = int(os.environ.get("CHURN_THRESHOLD", 32))

    rng = np.random.RandomState(13)
    overlay = _mk_node(True, threshold)
    baseline = _mk_node(False, threshold)
    assert overlay.device_engine.delta_overlay
    assert not baseline.device_engine.delta_overlay
    filters = _subscribe_base(overlay, n_filters)
    _subscribe_base(baseline, n_filters)
    log(f"churn bench: {n_filters} filters, {n_batches} batches of "
        f"{batch}, {rate} subscribes/batch, threshold {threshold}, "
        f"backend={overlay.device_engine.stats()['backend'] or 'unbuilt'}")
    batches = _topics_for(filters, rng, batch, n_batches)

    base_ps, base_rb, base_hd = _run(baseline, batches, rate, "baseline")
    over_ps, over_rb, over_hd = _run(overlay, batches, rate, "overlay")
    # overlay telemetry BEFORE the steady pass: its rebuild() folds the
    # delta set into a fresh snapshot and resets the overlay to None
    overlay_stats = overlay.device_engine.stats()["overlay"]
    # steady state: same engine, churn already absorbed, no new churn
    steady_ps, _srb, _shd = _run(overlay, batches, 0, "steady")

    snap = overlay.pipeline_telemetry.snapshot()
    out = {
        "metric": "churn_topic_matches_per_sec",
        "unit": "topic-matches/s",
        "overlay_per_s": round(over_ps),
        "baseline_per_s": round(base_ps),
        "speedup": round(over_ps / base_ps, 2),
        # full-rebuild pressure: the baseline recompiles the world at
        # the threshold; the overlay compacts rarely (acceptance >= 5x
        # fewer — 0 rebuilds in-window reports as the batch count floor)
        "rebuilds_overlay": over_rb,
        "rebuilds_baseline": base_rb,
        "rebuild_reduction": round(base_rb / max(1, over_rb), 2),
        "host_delta_overlay": over_hd,      # acceptance: ~= 0
        "host_delta_baseline": base_hd,     # the hole being closed
        "steady_per_s": round(steady_ps),
        "workload": {
            "filters": n_filters, "batch": batch, "batches": n_batches,
            "churn_rate": rate, "rebuild_threshold": threshold,
        },
        "backend": overlay.device_engine.stats()["backend"],
        "overlay": overlay_stats,
        "rebuild": snap.get("rebuild"),
    }
    return out


def main():
    print(json.dumps(run_churn()), flush=True)


if __name__ == "__main__":
    main()
