#!/usr/bin/env python
"""Skewed-topic microbenchmark: the device-match reuse layers' win.

Measures topic-matches/sec through the REAL DeviceRouteEngine serving
stages (prepare → dispatch → materialize) twice on one machine —

  cached     dedup + snapshot-keyed match cache ON (the default engine)
  uncached   both layers OFF (EMQX_TPU_DEDUP=0 equivalent)

— over a 90/10 hot-set publish stream (SKEW_ZIPF=1 switches to a Zipf
draw): the skew real MQTT brokers see (arXiv:1811.07088, 2603.21600),
where the cache should route almost every lane without running the
shape-hash/NFA match. Consume (host delivery fan-out) is excluded: it is
identical on both paths and would only dilute the number under test.

The JSON row embeds the PR-1 pipeline-telemetry snapshot of the cached
node, whose `match_cache` / `dedup` sections carry the hit-rate and
dedup-ratio counters — so the speedup is attributable to the measured
reuse rate, not vibes. ISSUE 2 acceptance: speedup >= 2x.

A third engine (reuse layers on, compact readback OFF) grades the
ISSUE 3 acceptance pair on the same traffic: readback bytes-per-window
reduction (compact vs dense, >= 4x at this workload's fan-out of 1)
with no matches/s regression (`compact_vs_dense`).

Env knobs: SKEW_FILTERS (10000), SKEW_BATCH (1024), SKEW_BATCHES (48),
SKEW_HOT (16), SKEW_HOT_PCT (90), SKEW_ZIPF (0), SKEW_COVER_RATIO (0 —
>0 swaps in the cover-heavy population from tools/workloads.py).

Run directly or as `python bench.py --skew`.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


class _Sink:
    def deliver(self, topic_filter, msg):
        return True


def _mk_node(dedup: bool, compact: bool = True):
    from emqx_tpu.broker.node import Node

    # tight fan-out/slot caps: the bench workload has one subscriber per
    # filter, so generous caps would just pad the post stage and dilute
    # the match-stage difference under test (same trim as bench.py)
    return Node({"broker": {"topic_dedup": dedup,
                            "compact_readback": compact,
                            "device_fanout_cap": 4,
                            "device_slot_cap": 2}})


def _subscribe_all(node, n_filters: int) -> list:
    """`n_filters` wildcard filters from the shared generator
    (tools/workloads.py, ISSUE 18 satellite). SKEW_COVER_RATIO=0 keeps
    the legacy zero-cover shape-spread population byte-identical (many
    shapes, real per-shape match work — the component the reuse layers
    remove); >0 switches to the cover-heavy population."""
    from tools.workloads import cover_heavy_filters, shape_spread_filters
    ratio = float(os.environ.get("SKEW_COVER_RATIO", 0))
    filters = cover_heavy_filters(n_filters, cover_ratio=ratio) if ratio \
        else shape_spread_filters(n_filters, tail_hash=True)
    b = node.broker
    sid = b.register(_Sink(), "skew-sink")
    for f in filters:
        b.subscribe(sid, f, {"qos": 0})
    return filters


def _topics_for(filters: list, rng, n_hot: int, hot_pct: int,
                zipf: bool, batch: int, n_batches: int):
    """Pre-built per-batch topic lists: hot-set (or Zipf) skewed over
    concrete topics that each match one filter."""
    from tools.workloads import concretize

    hot = [concretize(f) for f in filters[:n_hot]]
    cold_pool = [concretize(f) for f in filters[n_hot:n_hot + 4096]]
    batches = []
    for _ in range(n_batches):
        if zipf:
            ranks = np.minimum(rng.zipf(1.3, size=batch) - 1,
                               len(hot) + len(cold_pool) - 1)
            topics = [(hot + cold_pool)[r] for r in ranks]
        else:
            hot_mask = rng.randint(0, 100, batch) < hot_pct
            hi = rng.randint(0, len(hot), batch)
            ci = rng.randint(0, len(cold_pool), batch)
            topics = [hot[hi[k]] if hot_mask[k] else cold_pool[ci[k]]
                      for k in range(batch)]
        batches.append(topics)
    return batches


def _run_engine(node, batches, label: str) -> float:
    """Route every batch through prepare/dispatch/materialize; wall
    seconds. One full pre-pass first: XLA compiles and cache seeding are
    setup (a production broker warms before peak traffic), so the timed
    pass measures the STEADY state of each configuration — symmetric for
    the uncached engine, which gains nothing from the pre-pass."""
    from emqx_tpu.broker.message import make

    eng = node.device_engine
    msg_batches = [[make("p", 0, t, b"x") for t in topics]
                   for topics in batches]
    eng.rebuild()

    def one(msgs):
        h = eng.prepare(msgs, gate_cold=False)
        assert h is not None
        eng.dispatch(h)
        eng.materialize(h)
        eng.abandon(h)      # consume excluded: identical on both paths

    for msgs in msg_batches:    # warm pass: compiles + cache seeding
        one(msgs)
    # best of two timed passes: one-time process effects (allocator /
    # BLAS / frequency warmup) otherwise systematically favor whichever
    # engine is measured later and fake a speedup at identical work
    dt = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        for msgs in msg_batches:
            one(msgs)
        dt = min(dt, time.perf_counter() - t0)
    total = sum(len(m) for m in msg_batches)
    log(f"{label}: {total} topics in {dt:.3f}s "
        f"({total / dt / 1e3:.1f}k matches/s)")
    return total / dt


def run_skew() -> dict:
    n_filters = int(os.environ.get("SKEW_FILTERS", 10_000))
    batch = int(os.environ.get("SKEW_BATCH", 1024))
    n_batches = int(os.environ.get("SKEW_BATCHES", 48))
    n_hot = int(os.environ.get("SKEW_HOT", 16))
    hot_pct = int(os.environ.get("SKEW_HOT_PCT", 90))
    zipf = os.environ.get("SKEW_ZIPF", "0") == "1"

    rng = np.random.RandomState(11)
    fast = _mk_node(dedup=True)                    # compact readback on
    dense = _mk_node(dedup=True, compact=False)    # ISSUE 3 A/B twin
    plain = _mk_node(dedup=False)
    filters = _subscribe_all(fast, n_filters)
    _subscribe_all(dense, n_filters)
    _subscribe_all(plain, n_filters)
    log(f"skew bench: {n_filters} filters, "
        f"{'zipf' if zipf else f'{hot_pct}/{100 - hot_pct} hot-set'} "
        f"({n_hot} hot), {n_batches} batches of {batch}, "
        f"backend={fast.device_engine.stats()['backend'] or 'unbuilt'}")
    batches = _topics_for(filters, rng, n_hot, hot_pct, zipf, batch,
                          n_batches)

    uncached_ps = _run_engine(plain, batches, "uncached")
    dense_ps = _run_engine(dense, batches, "cached+dense")
    cached_ps = _run_engine(fast, batches, "cached+compact")

    def per_window(node, path):
        w = node.metrics.val(f"pipeline.readback.windows.{path}")
        return (node.metrics.val(f"pipeline.readback.bytes.{path}") / w) \
            if w else None

    rb_compact = per_window(fast, "compact")
    rb_dense = per_window(dense, "dense")
    snap = fast.pipeline_telemetry.snapshot()
    cache_stats = fast.device_engine.stats()["match_cache"]
    out = {
        "metric": "skew_topic_matches_per_sec",
        "unit": "topic-matches/s",
        "cached_per_s": round(cached_ps),
        "uncached_per_s": round(uncached_ps),
        "speedup": round(cached_ps / uncached_ps, 2),
        # ISSUE 3 acceptance pair: same reuse layers, compact vs dense
        # readback — bytes-per-window reduction (>= 4x at fan-out <= 8)
        # with no matches/s regression (compact_vs_dense ~>= 1.0)
        "cached_dense_per_s": round(dense_ps),
        "compact_vs_dense": round(cached_ps / dense_ps, 2),
        "readback_bytes_per_window_compact": round(rb_compact)
        if rb_compact else None,
        "readback_bytes_per_window_dense": round(rb_dense)
        if rb_dense else None,
        "readback_reduction": round(rb_dense / rb_compact, 2)
        if rb_compact and rb_dense else None,
        "hit_rate": cache_stats["hit_rate"],
        "dedup_ratio": snap.get("dedup", {}).get("ratio"),
        "workload": {
            "filters": n_filters, "batch": batch, "batches": n_batches,
            "hot": n_hot,
            "skew": "zipf1.3" if zipf else f"{hot_pct}/{100 - hot_pct}",
        },
        "backend": fast.device_engine.stats()["backend"],
        # the PR-1 telemetry snapshot: match_cache/dedup/readback
        # counters + dispatch vs dispatch_cached stage split ride along,
        # so the speedup is attributable to the exported reuse rate
        "telemetry": snap,
    }
    return out


def main():
    print(json.dumps(run_skew()), flush=True)


if __name__ == "__main__":
    main()
