#!/usr/bin/env python
"""Deterministic chaos harness for the pipeline supervision layer (ISSUE 6).

Drives the REAL serving path (Node → PublishBatcher → device engine →
delivery lanes) through a deterministic publish schedule while the
`EMQX_TPU_FAULTS` injection machinery fails one stage at a time, and
grades the run against the fault-free twin:

- **zero lost QoS≥1 deliveries** — every publish's settled delivery
  count equals the twin's (the window-journal replay re-routes a dying
  window through the next ladder rung, it never drops it);
- **per-session order bit-identical** — each subscriber's delivered
  (filter, topic) sequence equals the twin's (sessions subscribe one
  filter each, so the order oracle is path-independent by construction);
- **degradation within one window** — the stage breaker opens on the
  faulted window (threshold 1 here) and the ladder steps down;
- **recovery** — the half-open probe re-closes the breaker once the
  armed fault clauses are spent.

Run standalone (`python tools/chaos_bench.py`) for the full
point × kind matrix as one JSON line; tests/test_supervise.py imports
`run_case`/`run_twin` and asserts the same oracle per combination.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from emqx_tpu.broker.message import make                    # noqa: E402
from emqx_tpu.broker.node import Node                       # noqa: E402
from emqx_tpu.broker.supervise import (FAULT_KINDS,         # noqa: E402
                                       FAULT_POINTS, FaultInjector,
                                       parse_faults)

N_FILTERS = 8
BATCH = 80          # > 64: the dedup/cache plan analysis engages, so
                    # the cache_insert point is traversed (Bp = 256)
WINDOWS = 8


class Rec:
    """Recording sink: per-session delivery log for the order oracle."""

    def __init__(self):
        self.got = []

    def deliver(self, topic_filter, msg):
        self.got.append((topic_filter, msg.topic, bytes(msg.payload)))
        return True


def build_node(*, lanes: int = 2, supervise: bool = True,
               threshold: int = 1) -> Node:
    return Node({"broker": {
        "device_fanout_cap": 16, "device_slot_cap": 4,
        "deliver_lanes": lanes, "device_min_batch": 4,
        "batch_window_us": 2000, "supervise": supervise,
        "supervise_threshold": threshold}})


def build_world(node: Node, *, with_delta: bool = False) -> dict:
    """N_FILTERS filters × 2 subscribers (one QoS1). Each session
    subscribes exactly ONE filter, so its delivered sequence is the
    publish-order subsequence of its topic — identical across the
    device/lanes/host paths by construction (the oracle's ground)."""
    b = node.broker
    sinks = {}
    for i in range(N_FILTERS):
        for q in (0, 1):
            s = Rec()
            sid = b.register(s, f"c{i}-{q}")
            sinks[sid] = s
            b.subscribe(sid, f"t/{i}/+", {"qos": q})
    if with_delta:
        s = Rec()
        sid = b.register(s, "cd")
        sinks[sid] = s
        # subscribed AFTER rebuild by the driver (delta filter)
        sinks["delta_sid"] = sid
    return sinks


def schedule(windows: int = WINDOWS, batch: int = BATCH) -> list:
    """Deterministic topic schedule: round-robin over the filters with
    a unique payload per message."""
    wins = []
    seq = 0
    for _w in range(windows):
        msgs = []
        for i in range(batch):
            msgs.append((f"t/{(seq + i) % N_FILTERS}/x",
                         b"m%06d" % (seq + i)))
        seq += batch
        wins.append(msgs)
    return wins


async def _warm(node: Node) -> None:
    """Compile the standard batch classes off-path so the batcher's
    warm gate admits device dispatches deterministically (the chaos
    clauses must hit the DEVICE path, not a cold-class host detour)."""
    eng = node.device_engine
    eng.rebuild()
    eng._kick_class_warm()
    if eng._fuse_warm_task is not None:
        await eng._fuse_warm_task


async def _drive(node: Node, wins, *, delta_sub=None,
                 settle_s: float = 6.0) -> list:
    """Publish the schedule through the real batcher; after the last
    window keep publishing single-lane ticks until every armed fault has
    fired and every breaker re-closed (or `settle_s` elapses)."""
    counts = []
    delta_live = False
    for w, msgs in enumerate(wins):
        if delta_sub is not None and w == 2:
            # churn mid-schedule: a post-snapshot (delta) filter —
            # makes the overlay stale, so the overlay_apply point is
            # traversed on the next prepare; its topic gets traffic so
            # the host-delta fallback's zero-loss claim is exercised
            sid, filt = delta_sub
            node.broker.subscribe(sid, filt, {"qos": 1})
            delta_live = True
        if delta_live:
            msgs = msgs + [("d/x", b"d%03d" % w)]
        counts.append(await asyncio.gather(*[
            node.publish_async(make("pub", 1, t, p)) for t, p in msgs]))
        await asyncio.sleep(0.02)
    sup = node.supervisor
    deadline = time.monotonic() + settle_s
    while sup is not None and time.monotonic() < deadline:
        spent = all(f.fired >= f.count for f in sup.injector.faults)
        closed = all(b.state == "closed"
                     for b in sup.breakers.values())
        if spent and closed:
            break
        # tick: publishes drive poll_rebuild → sup.poll() → probes
        counts.append(await asyncio.gather(*[
            node.publish_async(make("pub", 1, f"t/{i}/x", b"tick"))
            for i in range(N_FILTERS)]))
        await asyncio.sleep(0.05)
    pool = node.deliver_lanes
    if pool is not None:
        await pool.drain()
    return counts


def run_case(point: str, kind: str, *, lanes: int = 2,
             hang_s: float = 0.5, count: int = 1) -> dict:
    """One faulted run: returns settled counts, per-session order and
    the supervision counters for the oracle."""
    node = build_node(lanes=lanes, threshold=1)
    sup = node.supervisor
    # fast breaker cycle + tight watchdog so hang faults resolve in
    # test time (hang_s > watchdog floor ⇒ the stall detector trips)
    for br in sup.breakers.values():
        br.base_cooldown_s = br.cooldown_s = 0.05
    sup.wd_floor_s = 0.1
    sup.wd_mult = 0.0       # deterministic: deadline == floor
    delta = point == "overlay_apply"
    sinks = build_world(node, with_delta=delta)
    delta_sid = sinks.pop("delta_sid", None)
    wins = schedule()

    async def go():
        if point != "snapshot_swap":
            # snapshot_swap must fault the FIRST build; everything else
            # warms first so the fault hits a serving device path
            await _warm(node)
        spec = f"{point}:{kind}:count={count}"
        if kind == "hang":
            spec += f":hang_s={hang_s}"
        sup.injector = FaultInjector(parse_faults(spec))
        return await _drive(
            node, wins,
            delta_sub=(delta_sid, "d/+") if delta_sid is not None
            else None)

    counts = asyncio.new_event_loop().run_until_complete(go())
    m = node.metrics
    return {
        "counts": [list(c) for c in counts],
        "order": {sid: list(s.got) for sid, s in sinks.items()},
        "faults": m.val(f"supervise.faults.{point}"),
        "trips": m.val("supervise.trips"),
        "replays": m.val("supervise.replays"),
        "stalls": m.val("supervise.stalls"),
        "probes": m.val("supervise.probes"),
        "rung_changes": m.val("supervise.rung_changes"),
        "breakers": {s: b.state for s, b in sup.breakers.items()},
        "journal_depth": sup.journal_depth(),
        "fired": sum(f.fired for f in sup.injector.faults),
        "dropped": m.val("messages.dropped"),
    }


def run_twin(*, lanes: int = 2, delta: bool = False) -> dict:
    """The fault-free twin: same node shape, same schedule, no armed
    clauses — the oracle both the counts and the order compare to."""
    node = build_node(lanes=lanes, threshold=1)
    sinks = build_world(node, with_delta=delta)
    delta_sid = sinks.pop("delta_sid", None)
    wins = schedule()

    async def go():
        await _warm(node)
        return await _drive(
            node, wins, settle_s=0.0,
            delta_sub=(delta_sid, "d/+") if delta_sid is not None
            else None)

    counts = asyncio.new_event_loop().run_until_complete(go())
    return {
        "counts": [list(c) for c in counts],
        "order": {sid: list(s.got) for sid, s in sinks.items()},
    }


# stages whose consumer-side await is watchdog-bounded: a hang there
# MUST trip the breaker (stall detection); at every other point a
# bounded hang completes inline — slow, but nothing failed and nothing
# was lost, so the correct outcome is NO trip
WATCHDOGGED = ("dispatch", "materialize", "mesh_exchange")


def grade(case: dict, twin: dict, point: str = "dispatch",
          kind: str = "exception") -> list:
    """The chaos oracle. Returns a list of violation strings (empty =
    green). Counts compare only over the twin's windows (the faulted
    run's extra settle ticks are all-delivered by the journal contract:
    every settled count must equal the subscriber fan-out, 2)."""
    bad = []
    expect_trip = kind != "hang" or point in WATCHDOGGED
    # zero lost QoS≥1 deliveries on the scheduled windows
    for w, twin_counts in enumerate(twin["counts"][:WINDOWS]):
        if case["counts"][w] != twin_counts:
            bad.append(f"window {w}: counts diverged "
                       f"{case['counts'][w][:8]}... != "
                       f"{twin_counts[:8]}...")
    for w, counts in enumerate(case["counts"]):
        if any(c == 0 for c in counts):
            bad.append(f"window {w}: lost deliveries (count=0)")
    # per-session order: the twin's sequence must be a PREFIX of the
    # faulted run's (the settle ticks append extra deliveries)
    for sid, seq in twin["order"].items():
        got = case["order"].get(sid, [])
        if got[:len(seq)] != seq:
            bad.append(f"sid {sid}: order diverged")
    if case["fired"] == 0:
        bad.append("no armed fault ever fired (harness bug)")
    if expect_trip and case["trips"] < 1:
        bad.append("breaker never opened")
    if kind == "hang" and point in WATCHDOGGED and case["stalls"] < 1:
        bad.append("hang at a watchdogged stage never counted a stall")
    if any(s != "closed" for s in case["breakers"].values()):
        bad.append(f"breaker(s) stuck open: {case['breakers']}")
    if case["journal_depth"] != 0:
        bad.append(f"window journal leaked {case['journal_depth']}")
    if case["dropped"] != 0:
        bad.append(f"{case['dropped']} messages dropped")
    return bad


# the full single-node matrix; mesh_exchange needs a multichip node and
# rides its own test (tests/test_supervise.py::TestMeshChaos)
MATRIX_POINTS = tuple(
    p for p in FAULT_POINTS
    # mesh_exchange needs a multichip node (own test); the ISSUE-14
    # overload points are traversed by the governor's poll, not a
    # pipeline stage — they ride the overload cells below
    if p not in ("mesh_exchange", "signal_spike", "stuck_grade"))


# ---- overload cells (ISSUE 14) -----------------------------------------
# The governor's chaos surface: signal_spike drives a deterministic
# grade climb → shed arming → recovery; stuck_grade freezes the ladder
# until the clause is spent → the overload_stuck alarm. Pure poll-driven
# (no event loop, no traffic needed), mirroring the matrix pattern:
# run_overload_case returns the trajectory, grade_overload is the
# oracle. Both ride tier-1 via the `chaos` marker (tests/
# test_overload.py) like the PR 6 matrix.

def _overload_node() -> Node:
    node = build_node(lanes=0, threshold=1)
    gov = node.overload_governor
    assert gov is not None, "overload governor knob unexpectedly off"
    # tight hysteresis so the cells converge in a handful of polls
    gov.up_sustain = 1
    gov.down_sustain = 2
    return node


def run_overload_case(point: str, *, count: int = 6,
                      polls: int = 40) -> dict:
    """Drive `polls` governor ticks with a `point:corrupt:count=N`
    clause armed; record the grade trajectory, armed-action history,
    alarm states and the unwind proof."""
    node = _overload_node()
    gov = node.overload_governor
    sup = node.supervisor
    sup.injector = FaultInjector(parse_faults(
        f"{point}:corrupt:count={count}"))
    rec = node.flight_recorder
    sample0 = rec.sample if rec is not None else None
    depth0 = node.publish_batcher.dispatch_depth \
        if node.publish_batcher is not None else None
    grades, actions_hist = [], []
    alarm_seen = stuck_alarm_seen = False
    if point == "stuck_grade":
        # the stuck cell needs a PENDING transition to block: force a
        # high raw grade from the signals themselves while the stuck
        # clause fires
        gov.sample_signals = lambda: {"queue_fill": 0.95}
    for _i in range(polls):
        gov.poll()
        grades.append(gov.grade)
        actions_hist.append(list(gov._armed))
        if node.alarms.is_active("overload"):
            alarm_seen = True
        if node.alarms.is_active("overload_stuck"):
            stuck_alarm_seen = True
        if point == "stuck_grade" \
                and all(f.fired >= f.count for f in sup.injector.faults):
            # clause spent: let the signals recover so the (now
            # unblocked) ladder can step down
            gov.sample_signals = lambda: {"queue_fill": 0.0}
    m = node.metrics
    return {
        "grades": grades,
        "max_grade": max(grades),
        "final_grade": gov.grade,
        "actions_hist": actions_hist,
        "final_actions": list(gov._armed),
        "alarm_seen": alarm_seen,
        "alarm_active": node.alarms.is_active("overload"),
        "stuck_alarm_seen": stuck_alarm_seen,
        "stuck_alarm_active": node.alarms.is_active("overload_stuck"),
        "stuck_polls": m.val("pipeline.overload.stuck_polls"),
        "sheds": m.val("pipeline.overload.sheds"),
        "grade_changes": m.val("pipeline.overload.grade_changes"),
        "fired": sum(f.fired for f in sup.injector.faults),
        "sample_restored": rec is None or rec.sample == sample0,
        "depth_restored": depth0 is None
        or node.publish_batcher.dispatch_depth == depth0,
        "flags_clear": not (gov.shed_qos0 or gov.connects_paused
                            or gov.retained_deferred),
    }


def grade_overload(case: dict, point: str) -> list:
    """The overload-cell oracle: violations (empty = green)."""
    bad = []
    if case["fired"] == 0:
        bad.append("no armed overload clause ever fired (harness bug)")
    if point == "signal_spike":
        if case["max_grade"] < 3:
            bad.append(f"spike never reached critical "
                       f"(max grade {case['max_grade']})")
        if case["sheds"] < 1:
            bad.append("no shed action ever armed")
        if not case["alarm_seen"]:
            bad.append("overload $SYS alarm never raised")
        if case["final_grade"] != 0:
            bad.append(f"never recovered to normal "
                       f"(final grade {case['final_grade']})")
        if case["final_actions"]:
            bad.append(f"actions not unwound: {case['final_actions']}")
        if case["alarm_active"]:
            bad.append("overload alarm stuck active after recovery")
        if not (case["sample_restored"] and case["depth_restored"]
                and case["flags_clear"]):
            bad.append("shed side-effects not restored on recovery")
        # the grade path must be a ladder, never a jump: adjacent
        # grades differ by at most 1
        for a, b in zip(case["grades"], case["grades"][1:]):
            if abs(a - b) > 1:
                bad.append(f"grade jumped {a}->{b}")
                break
    elif point == "stuck_grade":
        if not case["stuck_alarm_seen"]:
            bad.append("overload_stuck alarm never raised")
        if case["stuck_polls"] < 3:
            bad.append(f"stuck polls never accumulated "
                       f"({case['stuck_polls']})")
        if case["final_grade"] != 0:
            bad.append(f"ladder never recovered once unstuck "
                       f"(final grade {case['final_grade']})")
        if case["stuck_alarm_active"]:
            bad.append("overload_stuck alarm never cleared")
    return bad


OVERLOAD_POINTS = ("signal_spike", "stuck_grade")


def main() -> int:
    t0 = time.time()
    twin = run_twin()
    twin_delta = run_twin(delta=True)
    rows = {}
    failures = 0
    for point in MATRIX_POINTS:
        for kind in FAULT_KINDS:
            case = run_case(point, kind)
            bad = grade(case,
                        twin_delta if point == "overlay_apply" else twin,
                        point, kind)
            rows[f"{point}:{kind}"] = {
                "ok": not bad, "violations": bad,
                "faults": case["faults"], "trips": case["trips"],
                "replays": case["replays"], "stalls": case["stalls"],
            }
            failures += bool(bad)
            print(f"{point}:{kind}: "
                  f"{'ok' if not bad else bad}", file=sys.stderr)
    for point in OVERLOAD_POINTS:
        case = run_overload_case(point)
        bad = grade_overload(case, point)
        rows[f"overload:{point}"] = {
            "ok": not bad, "violations": bad,
            "max_grade": case["max_grade"],
            "sheds": case["sheds"],
            "grade_changes": case["grade_changes"],
        }
        failures += bool(bad)
        print(f"overload:{point}: {'ok' if not bad else bad}",
              file=sys.stderr)
    out = {
        "metric": "chaos_matrix",
        "value": len(rows) - failures,
        "total": len(rows),
        "unit": "green-cells",
        "seconds": round(time.time() - t0, 1),
        "cells": rows,
    }
    print(json.dumps(out), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
