#!/usr/bin/env python
"""Deterministic chaos harness for the pipeline supervision layer (ISSUE 6).

Drives the REAL serving path (Node → PublishBatcher → device engine →
delivery lanes) through a deterministic publish schedule while the
`EMQX_TPU_FAULTS` injection machinery fails one stage at a time, and
grades the run against the fault-free twin:

- **zero lost QoS≥1 deliveries** — every publish's settled delivery
  count equals the twin's (the window-journal replay re-routes a dying
  window through the next ladder rung, it never drops it);
- **per-session order bit-identical** — each subscriber's delivered
  (filter, topic) sequence equals the twin's (sessions subscribe one
  filter each, so the order oracle is path-independent by construction);
- **degradation within one window** — the stage breaker opens on the
  faulted window (threshold 1 here) and the ladder steps down;
- **recovery** — the half-open probe re-closes the breaker once the
  armed fault clauses are spent.

Run standalone (`python tools/chaos_bench.py`) for the full
point × kind matrix as one JSON line; tests/test_supervise.py imports
`run_case`/`run_twin` and asserts the same oracle per combination.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from emqx_tpu.broker.message import make                    # noqa: E402
from emqx_tpu.broker.node import Node                       # noqa: E402
from emqx_tpu.broker.supervise import (FAULT_KINDS,         # noqa: E402
                                       FAULT_POINTS, FaultInjector,
                                       parse_faults)

N_FILTERS = 8
BATCH = 80          # > 64: the dedup/cache plan analysis engages, so
                    # the cache_insert point is traversed (Bp = 256)
WINDOWS = 8


class Rec:
    """Recording sink: per-session delivery log for the order oracle."""

    def __init__(self):
        self.got = []

    def deliver(self, topic_filter, msg):
        self.got.append((topic_filter, msg.topic, bytes(msg.payload)))
        return True


def build_node(*, lanes: int = 2, supervise: bool = True,
               threshold: int = 1) -> Node:
    return Node({"broker": {
        "device_fanout_cap": 16, "device_slot_cap": 4,
        "deliver_lanes": lanes, "device_min_batch": 4,
        "batch_window_us": 2000, "supervise": supervise,
        "supervise_threshold": threshold}})


def build_world(node: Node, *, with_delta: bool = False) -> dict:
    """N_FILTERS filters × 2 subscribers (one QoS1). Each session
    subscribes exactly ONE filter, so its delivered sequence is the
    publish-order subsequence of its topic — identical across the
    device/lanes/host paths by construction (the oracle's ground)."""
    b = node.broker
    sinks = {}
    for i in range(N_FILTERS):
        for q in (0, 1):
            s = Rec()
            sid = b.register(s, f"c{i}-{q}")
            sinks[sid] = s
            b.subscribe(sid, f"t/{i}/+", {"qos": q})
    if with_delta:
        s = Rec()
        sid = b.register(s, "cd")
        sinks[sid] = s
        # subscribed AFTER rebuild by the driver (delta filter)
        sinks["delta_sid"] = sid
    return sinks


def schedule(windows: int = WINDOWS, batch: int = BATCH) -> list:
    """Deterministic topic schedule: round-robin over the filters with
    a unique payload per message."""
    wins = []
    seq = 0
    for _w in range(windows):
        msgs = []
        for i in range(batch):
            msgs.append((f"t/{(seq + i) % N_FILTERS}/x",
                         b"m%06d" % (seq + i)))
        seq += batch
        wins.append(msgs)
    return wins


async def _warm(node: Node) -> None:
    """Compile the standard batch classes off-path so the batcher's
    warm gate admits device dispatches deterministically (the chaos
    clauses must hit the DEVICE path, not a cold-class host detour)."""
    eng = node.device_engine
    eng.rebuild()
    eng._kick_class_warm()
    if eng._fuse_warm_task is not None:
        await eng._fuse_warm_task


async def _drive(node: Node, wins, *, delta_sub=None,
                 settle_s: float = 6.0) -> list:
    """Publish the schedule through the real batcher; after the last
    window keep publishing single-lane ticks until every armed fault has
    fired and every breaker re-closed (or `settle_s` elapses)."""
    counts = []
    delta_live = False
    for w, msgs in enumerate(wins):
        if delta_sub is not None and w == 2:
            # churn mid-schedule: a post-snapshot (delta) filter —
            # makes the overlay stale, so the overlay_apply point is
            # traversed on the next prepare; its topic gets traffic so
            # the host-delta fallback's zero-loss claim is exercised
            sid, filt = delta_sub
            node.broker.subscribe(sid, filt, {"qos": 1})
            delta_live = True
        if delta_live:
            msgs = msgs + [("d/x", b"d%03d" % w)]
        counts.append(await asyncio.gather(*[
            node.publish_async(make("pub", 1, t, p)) for t, p in msgs]))
        await asyncio.sleep(0.02)
    sup = node.supervisor
    deadline = time.monotonic() + settle_s
    while sup is not None and time.monotonic() < deadline:
        spent = all(f.fired >= f.count for f in sup.injector.faults)
        closed = all(b.state == "closed"
                     for b in sup.breakers.values())
        if spent and closed:
            break
        # tick: publishes drive poll_rebuild → sup.poll() → probes
        counts.append(await asyncio.gather(*[
            node.publish_async(make("pub", 1, f"t/{i}/x", b"tick"))
            for i in range(N_FILTERS)]))
        await asyncio.sleep(0.05)
    pool = node.deliver_lanes
    if pool is not None:
        await pool.drain()
    return counts


def run_case(point: str, kind: str, *, lanes: int = 2,
             hang_s: float = 0.5, count: int = 1) -> dict:
    """One faulted run: returns settled counts, per-session order and
    the supervision counters for the oracle."""
    node = build_node(lanes=lanes, threshold=1)
    sup = node.supervisor
    # fast breaker cycle + tight watchdog so hang faults resolve in
    # test time (hang_s > watchdog floor ⇒ the stall detector trips)
    for br in sup.breakers.values():
        br.base_cooldown_s = br.cooldown_s = 0.05
    sup.wd_floor_s = 0.1
    sup.wd_mult = 0.0       # deterministic: deadline == floor
    delta = point == "overlay_apply"
    sinks = build_world(node, with_delta=delta)
    delta_sid = sinks.pop("delta_sid", None)
    wins = schedule()

    async def go():
        if point != "snapshot_swap":
            # snapshot_swap must fault the FIRST build; everything else
            # warms first so the fault hits a serving device path
            await _warm(node)
        spec = f"{point}:{kind}:count={count}"
        if kind == "hang":
            spec += f":hang_s={hang_s}"
        sup.injector = FaultInjector(parse_faults(spec))
        return await _drive(
            node, wins,
            delta_sub=(delta_sid, "d/+") if delta_sid is not None
            else None)

    counts = asyncio.new_event_loop().run_until_complete(go())
    m = node.metrics
    return {
        "counts": [list(c) for c in counts],
        "order": {sid: list(s.got) for sid, s in sinks.items()},
        "faults": m.val(f"supervise.faults.{point}"),
        "trips": m.val("supervise.trips"),
        "replays": m.val("supervise.replays"),
        "stalls": m.val("supervise.stalls"),
        "probes": m.val("supervise.probes"),
        "rung_changes": m.val("supervise.rung_changes"),
        "breakers": {s: b.state for s, b in sup.breakers.items()},
        "journal_depth": sup.journal_depth(),
        "fired": sum(f.fired for f in sup.injector.faults),
        "dropped": m.val("messages.dropped"),
    }


def run_twin(*, lanes: int = 2, delta: bool = False) -> dict:
    """The fault-free twin: same node shape, same schedule, no armed
    clauses — the oracle both the counts and the order compare to."""
    node = build_node(lanes=lanes, threshold=1)
    sinks = build_world(node, with_delta=delta)
    delta_sid = sinks.pop("delta_sid", None)
    wins = schedule()

    async def go():
        await _warm(node)
        return await _drive(
            node, wins, settle_s=0.0,
            delta_sub=(delta_sid, "d/+") if delta_sid is not None
            else None)

    counts = asyncio.new_event_loop().run_until_complete(go())
    return {
        "counts": [list(c) for c in counts],
        "order": {sid: list(s.got) for sid, s in sinks.items()},
    }


# stages whose consumer-side await is watchdog-bounded: a hang there
# MUST trip the breaker (stall detection); at every other point a
# bounded hang completes inline — slow, but nothing failed and nothing
# was lost, so the correct outcome is NO trip
WATCHDOGGED = ("dispatch", "materialize", "mesh_exchange")


def grade(case: dict, twin: dict, point: str = "dispatch",
          kind: str = "exception") -> list:
    """The chaos oracle. Returns a list of violation strings (empty =
    green). Counts compare only over the twin's windows (the faulted
    run's extra settle ticks are all-delivered by the journal contract:
    every settled count must equal the subscriber fan-out, 2)."""
    bad = []
    expect_trip = kind != "hang" or point in WATCHDOGGED
    # zero lost QoS≥1 deliveries on the scheduled windows
    for w, twin_counts in enumerate(twin["counts"][:WINDOWS]):
        if case["counts"][w] != twin_counts:
            bad.append(f"window {w}: counts diverged "
                       f"{case['counts'][w][:8]}... != "
                       f"{twin_counts[:8]}...")
    for w, counts in enumerate(case["counts"]):
        if any(c == 0 for c in counts):
            bad.append(f"window {w}: lost deliveries (count=0)")
    # per-session order: the twin's sequence must be a PREFIX of the
    # faulted run's (the settle ticks append extra deliveries)
    for sid, seq in twin["order"].items():
        got = case["order"].get(sid, [])
        if got[:len(seq)] != seq:
            bad.append(f"sid {sid}: order diverged")
    if case["fired"] == 0:
        bad.append("no armed fault ever fired (harness bug)")
    if expect_trip and case["trips"] < 1:
        bad.append("breaker never opened")
    if kind == "hang" and point in WATCHDOGGED and case["stalls"] < 1:
        bad.append("hang at a watchdogged stage never counted a stall")
    if any(s != "closed" for s in case["breakers"].values()):
        bad.append(f"breaker(s) stuck open: {case['breakers']}")
    if case["journal_depth"] != 0:
        bad.append(f"window journal leaked {case['journal_depth']}")
    if case["dropped"] != 0:
        bad.append(f"{case['dropped']} messages dropped")
    return bad


# the full single-node matrix; mesh_exchange needs a multichip node and
# rides its own test (tests/test_supervise.py::TestMeshChaos)
MATRIX_POINTS = tuple(p for p in FAULT_POINTS if p != "mesh_exchange")


def main() -> int:
    t0 = time.time()
    twin = run_twin()
    twin_delta = run_twin(delta=True)
    rows = {}
    failures = 0
    for point in MATRIX_POINTS:
        for kind in FAULT_KINDS:
            case = run_case(point, kind)
            bad = grade(case,
                        twin_delta if point == "overlay_apply" else twin,
                        point, kind)
            rows[f"{point}:{kind}"] = {
                "ok": not bad, "violations": bad,
                "faults": case["faults"], "trips": case["trips"],
                "replays": case["replays"], "stalls": case["stalls"],
            }
            failures += bool(bad)
            print(f"{point}:{kind}: "
                  f"{'ok' if not bad else bad}", file=sys.stderr)
    out = {
        "metric": "chaos_matrix",
        "value": len(rows) - failures,
        "total": len(rows),
        "unit": "green-cells",
        "seconds": round(time.time() - t0, 1),
        "cells": rows,
    }
    print(json.dumps(out), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
