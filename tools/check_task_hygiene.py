#!/usr/bin/env python
"""Static task-hygiene pass over emqx_tpu/ (ISSUE 6 satellite).

Two classes of silent-failure bugs keep reappearing in asyncio code,
and both defeated the pipeline's observability before the supervision
layer landed (a lane or consumer task could die between windows with no
trace):

1. **Fire-and-forget tasks** — an ``asyncio.create_task(...)`` /
   ``ensure_future(...)`` whose handle is discarded (a bare expression
   statement). The loop holds only a weak reference (GC can collect the
   task mid-flight) and any exception is deferred to a
   "Task exception was never retrieved" warning at collection time, if
   ever. The fix is ``supervise.spawn(...)`` (strong ref + logged/
   counted death) or holding the handle + ``supervise.guard_task``.

2. **Swallowed exceptions** — ``except Exception: pass`` (or a bare
   ``except:``) with no explanation. Sometimes legitimate (best-effort
   cleanup), but then the author owes the reader one comment line
   saying why; a COMMENT-LESS swallow is indistinguishable from a bug.
   Handlers carrying any comment (e.g. ``# noqa: BLE001 — best-effort``)
   are accepted.

Run as a script (exit 1 on findings, grep-friendly report) or through
``check(paths)`` from the tier-1 test (tests/test_supervise.py wires it
in, so a regression fails CI).
"""

from __future__ import annotations

import ast
import os
import sys

_TASK_FNS = ("create_task", "ensure_future")


class Finding:
    def __init__(self, path: str, line: int, kind: str, detail: str):
        self.path = path
        self.line = line
        self.kind = kind
        self.detail = detail

    def __repr__(self):
        return f"{self.path}:{self.line}: [{self.kind}] {self.detail}"


def _call_name(call: ast.Call) -> str:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _is_exception_catch(handler: ast.ExceptHandler) -> bool:
    """bare `except:` or `except Exception/BaseException [as e]:`."""
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in ("Exception", "BaseException")
    if isinstance(t, ast.Attribute):
        return t.attr in ("Exception", "BaseException")
    return False


def _has_comment(lines: list[str], lo: int, hi: int) -> bool:
    """Any comment text on source lines [lo, hi] (1-indexed)? A string
    scan is enough: the only '#' that can appear inside the code of an
    `except ...: pass` region is in a string literal, and a string
    literal in that region would itself be a (flagged) non-pass body."""
    for ln in lines[lo - 1:hi]:
        if "#" in ln:
            return True
    return False


def check_source(path: str, src: str) -> list[Finding]:
    out: list[Finding] = []
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, "syntax", str(e))]
    lines = src.splitlines()
    for node in ast.walk(tree):
        # 1: fire-and-forget task — the Call is the entire statement
        if isinstance(node, ast.Expr) \
                and isinstance(node.value, ast.Call) \
                and _call_name(node.value) in _TASK_FNS:
            out.append(Finding(
                path, node.lineno, "fire-and-forget",
                f"{_call_name(node.value)}(...) result discarded — "
                f"use supervise.spawn(...) or hold the handle + "
                f"supervise.guard_task"))
        # 2: comment-less `except Exception: pass`
        if isinstance(node, ast.ExceptHandler) \
                and _is_exception_catch(node) \
                and len(node.body) == 1 \
                and isinstance(node.body[0], ast.Pass):
            hi = node.body[0].lineno
            if not _has_comment(lines, node.lineno, hi):
                out.append(Finding(
                    path, node.lineno, "except-pass",
                    "except Exception: pass with no explaining "
                    "comment — say why the swallow is safe (or stop "
                    "swallowing)"))
    return out


def check(root: str) -> list[Finding]:
    out: list[Finding] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as f:
                out.extend(check_source(path, f.read()))
    return out


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "emqx_tpu")
    findings = check(root)
    for f in findings:
        print(f)
    print(f"{len(findings)} finding(s) in {root}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
