#!/usr/bin/env python
"""Static HBM-hygiene pass over emqx_tpu/ — CLI-compatible shim.

The real pass now lives in the unified analyzer
(``tools/analysis/passes/hbm_hygiene.py`` — ISSUE 12 migrated both
ad-hoc checkers onto the shared AST/framework infrastructure; see
docs/ANALYSIS.md). This shim keeps the original entry points bit-
compatible so existing tier-1 wiring (tests/test_hbm_ledger.py) and
muscle memory keep working: ``check_source(path, src)`` /
``check(root)`` return legacy ``Finding`` objects, the script prints
the same report and exits 1 on findings, 0 clean.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from analysis.core import Module                      # noqa: E402
from analysis.passes import hbm_hygiene as _pass      # noqa: E402


class Finding:
    def __init__(self, path: str, line: int, detail: str):
        self.path = path
        self.line = line
        self.detail = detail

    def __repr__(self):
        return f"{self.path}:{self.line}: [hbm] {self.detail}"


def check_source(path: str, src: str) -> list[Finding]:
    mod = Module(path, src)
    if mod.error is not None:
        return [Finding(path, mod.error.lineno or 0,
                        f"syntax: {mod.error}")]
    # honor the shared `# analysis: ok(hbm-hygiene) — ...` grammar the
    # framework applies, so this gate and `make analyze` always agree
    return [Finding(f.path, f.line, f.detail)
            for f in _pass.check_module(mod)
            if not mod.ok_for(_pass.NAME,
                              min(f.stmt_line, f.line), f.end_line)]


def check(root: str) -> list[Finding]:
    out: list[Finding] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py") or fn == "hbm_ledger.py":
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as f:
                out.extend(check_source(path, f.read()))
    return out


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "emqx_tpu")
    findings = check(root)
    for f in findings:
        print(f)
    print(f"{len(findings)} finding(s) in {root}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
