#!/usr/bin/env python
"""Static HBM-hygiene pass over emqx_tpu/ (ISSUE 8 satellite).

The HBM ledger (broker/hbm_ledger.py) only works if every persistent
`jax.device_put` actually routes through it — one forgotten site and
`accounted_fraction` silently drifts below 1 while the capacity
forecast (tools/hbm_report.py) under-counts. This audit is the static
half of that guarantee (the runtime half is the `memory_stats()`
cross-check in the telemetry section): it flags every `device_put`
call in emqx_tpu/ that bypasses the ledger.

A `device_put` call is ACCOUNTED when any of:

1. it is (transitively) an argument of a `hold(...)` / `_hold(...)`
   call — the direct-wrap idiom
   (``self._hold("snapshot_tables", jax.device_put(tables))``);
2. its statement, or the line right above it, carries an ``# hbm:``
   comment naming where the hold happens — the split-site idiom
   (``parallel/sharded.py`` holds the tree two lines below the put,
   inside a ``jax.tree.map``) or an explicit transient exemption
   (``# hbm: transient — consumed by this dispatch``);
3. it lives in ``broker/hbm_ledger.py`` itself.

Anything else is a finding: either wrap it in ``ledger.hold`` (with
the knob-off `None` passthrough every call site already has) or write
the one ``# hbm:`` line saying why the bytes are not persistent. The
sibling of tools/check_task_hygiene.py: run as a script (exit 1 on
findings) or through ``check(root)`` from the tier-1 test
(tests/test_hbm_ledger.py wires it in, so a bypassing allocation
fails CI).
"""

from __future__ import annotations

import ast
import os
import sys


class Finding:
    def __init__(self, path: str, line: int, detail: str):
        self.path = path
        self.line = line
        self.detail = detail

    def __repr__(self):
        return f"{self.path}:{self.line}: [hbm] {self.detail}"


def _is_device_put(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr == "device_put"
    if isinstance(fn, ast.Name):
        return fn.id == "device_put"
    return False


def _is_hold(call: ast.Call) -> bool:
    fn = call.func
    name = fn.attr if isinstance(fn, ast.Attribute) else \
        fn.id if isinstance(fn, ast.Name) else ""
    return name in ("hold", "_hold")


def _annotate_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._hbm_parent = node


def _inside_hold(node: ast.AST) -> bool:
    """Is this device_put (transitively) an argument of a hold call?
    The walk stops at statement boundaries — a hold elsewhere in the
    function does not bless this put."""
    cur = getattr(node, "_hbm_parent", None)
    while cur is not None and not isinstance(cur, ast.stmt):
        if isinstance(cur, ast.Call) and _is_hold(cur):
            return True
        cur = getattr(cur, "_hbm_parent", None)
    return False


def _stmt_of(node: ast.AST) -> ast.AST:
    cur = node
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = getattr(cur, "_hbm_parent", None)
    return cur if cur is not None else node

def _has_hbm_comment(lines: list[str], lo: int, hi: int) -> bool:
    """`# hbm:` anywhere on source lines [lo, hi] (1-indexed), or on
    the line just above (the split-site idiom puts the pointer comment
    on its own line before the statement)."""
    for ln in lines[max(0, lo - 2):hi]:
        if "# hbm:" in ln:
            return True
    return False


def check_source(path: str, src: str) -> list[Finding]:
    out: list[Finding] = []
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, f"syntax: {e}")]
    _annotate_parents(tree)
    lines = src.splitlines()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _is_device_put(node)):
            continue
        if _inside_hold(node):
            continue
        stmt = _stmt_of(node)
        hi = getattr(stmt, "end_lineno", stmt.lineno)
        if _has_hbm_comment(lines, stmt.lineno, hi):
            continue
        out.append(Finding(
            path, node.lineno,
            "jax.device_put bypasses the HBM ledger — wrap in "
            "ledger.hold(category, ...) or annotate the statement "
            "with `# hbm: <where held / why transient>`"))
    return out


def check(root: str) -> list[Finding]:
    out: list[Finding] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py") or fn == "hbm_ledger.py":
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as f:
                out.extend(check_source(path, f.read()))
    return out


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "emqx_tpu")
    findings = check(root)
    for f in findings:
        print(f)
    print(f"{len(findings)} finding(s) in {root}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
