#!/usr/bin/env python
"""Shared bench topic populations (ISSUE 18 satellite).

The microbenches (skew/churn/cover) used to hand-roll their filter
generators inline — all uniform populations with zero filter-over-filter
cover relations, which silently hides what subscription covering buys.
This module is the one place bench populations come from:

  shape_spread_filters   the legacy generator the skew/churn benches
                         inlined (byte-identical output, so historical
                         rates stay comparable): depth 3..10, one '+'
                         at a rotating level, shared d%97 vocabulary up
                         front. NO cover relations by construction
                         (every filter carries its own s{i} literals).
  cover_heavy_filters    what real broker populations look like per
                         arXiv:1811.07088: umbrella filters (`fleet/#`)
                         cover a configurable fraction of narrower
                         subscriptions under their prefix; depths drawn
                         from a Zipf so shallow umbrellas dominate.
  concretize             filter -> one concrete matching topic (wildcard
                         levels materialized; a trailing '#' gains one
                         concrete level so the topic exercises the
                         multi-level tail).

Populations are deterministic per (n, knobs, seed): benches stay
reproducible and resume signatures can key on the knobs alone.
"""

from __future__ import annotations

import numpy as np


def shape_spread_filters(n: int, *, tail_hash: bool = False) -> list:
    """The legacy inline generator, extracted verbatim: `n` wildcard
    filters spread over many SHAPES (depth and '+' position vary) with
    zero cover relations. tail_hash alternates '#' tails (the skew
    bench's variant); off, every filter ends in its own t{i} literal
    (the churn bench's variant)."""
    filters = []
    for i in range(n):
        depth = 3 + (i % 8)            # 8 depths x 2 tails = 16 shapes
        mid = i % depth
        levels = [f"s{i}" if li != mid else "+" for li in range(depth)]
        levels[0] = f"d{i % 97}"       # shared vocabulary up front
        tail = ("#" if i % 2 else f"t{i}") if tail_hash else f"t{i}"
        filters.append("/".join(levels) + "/" + tail)
    return filters


def cover_heavy_filters(n: int, *, cover_ratio: float = 0.5,
                        zipf_a: float = 1.4, max_depth: int = 8,
                        vocab: int = 97, seed: int = 7) -> list:
    """Cover-heavy population: ~`cover_ratio` of the `n` filters are
    covered by a broader umbrella filter already in the set.

    Roots (the covering set) split into umbrellas — trailing-'#'
    filters at a Zipf-drawn depth (shallow dominates, like real fleet/
    building/sensor hierarchies) — and standalone exact/'+' filters
    that cover nothing. Covered filters extend an umbrella's prefix by
    1-2 levels, every third one through a '+' (covered-with-wildcard is
    the case naive prefix tricks get wrong; the device detection must
    still fold it). Umbrella fan-in stays far below the engine's
    per-cover own_budget so the requested ratio is what the snapshot
    actually detects."""
    if not 0 <= cover_ratio < 1:
        raise ValueError(f"cover_ratio {cover_ratio} outside [0, 1)")
    rng = np.random.RandomState(seed)
    n_cov = int(round(n * cover_ratio))
    n_roots = max(1, n - n_cov)
    filters = []
    umbrellas = []
    depths = 1 + (rng.zipf(zipf_a, size=n_roots) - 1) % max_depth
    for i in range(n_roots):
        depth = int(depths[i])
        levels = [f"d{i % vocab}"] + [f"u{i}l{li}"
                                      for li in range(1, depth)]
        if i % 3 == 0:                 # every third root is an umbrella
            umbrellas.append(levels)
            filters.append("/".join(levels) + "/#")
        else:
            filters.append("/".join(levels) + f"/t{i}")
    if not umbrellas:                  # tiny n: keep the ratio honest
        umbrellas.append(["d0"])
        filters[0] = "d0/#"
    for j in range(n_cov):
        base = umbrellas[j % len(umbrellas)]
        ext = 1 + j % 3                # 1-3 levels past the umbrella
        tail = []
        for e in range(ext - 1):
            # '+' per a bitmask of j: covered-with-wildcard plus
            # depth x plus-mask diversity — the full set's SHAPE count
            # far exceeds the covering set's, which is the whole
            # covering bet
            tail.append("+" if (j >> e) & 1 else f"m{j}e{e}")
        tail.append(f"c{j}")
        filters.append("/".join(base + tail))
    return filters


def concretize(f: str, salt: str = "x") -> str:
    """One concrete topic matching `f`: '+' levels materialize to a
    positional literal; a trailing '#' becomes one extra concrete level
    (so `a/#` yields `a/x1`, exercising the hash tail)."""
    parts = f.split("/")
    out = [p if p not in ("+", "#") else f"{salt}{i}"
           for i, p in enumerate(parts)]
    return "/".join(out)
