#!/usr/bin/env python
"""Subscription-covering microbenchmark (ISSUE 18 acceptance).

Measures topic-matches/sec through the REAL DeviceRouteEngine match
stages (prepare → dispatch → materialize) with subscription covering ON
vs OFF, on two populations from tools/workloads.py:

  cover-heavy   cover_heavy_filters(ratio COVER_RATIO >= 0.5): umbrella
                filters cover most of the population, so the covering
                set the device actually matches is a fraction of the
                subscription count — the arXiv:1811.07088 shape of real
                broker populations. Acceptance: covering ON >= 2x OFF,
                reported next to the covering-set reduction factor so
                the speedup is attributable.
  uniform       shape_spread_filters: ZERO cover relations by
                construction. Covering ON must not regress (>= 0.95x)
                — detection finds nothing and the engine skips the
                expansion stage entirely.

Both engines run with dedup + match cache OFF: the cache would serve
repeated pool topics host-side and hide the match-stage difference
under test (the cache's own win is tools/skew_bench.py's number).
Consume is excluded for the same reason as the skew bench — identical
on both paths. A final full route_batch per engine pair cross-checks
delivery counts, so the measured twin is also a correct twin.

Env knobs: COVER_FILTERS (10000), COVER_BATCH (1024), COVER_BATCHES
(32), COVER_RATIO (0.6).

Run directly or as `python bench.py --cover`.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


class _Sink:
    def deliver(self, topic_filter, msg):
        return True


def _mk_node(covering: bool):
    from emqx_tpu.broker.node import Node

    # dedup/cache off (see module docstring); tight fan-out/slot caps —
    # one subscriber per filter, same trim as skew_bench/bench.py
    return Node({"broker": {"subscription_covering": covering,
                            "topic_dedup": False,
                            "device_fanout_cap": 4,
                            "device_slot_cap": 2}})


def _subscribe(node, filters: list, tag: str) -> None:
    b = node.broker
    sid = b.register(_Sink(), f"cover-{tag}")
    for f in filters:
        b.subscribe(sid, f, {"qos": 0})


def _topics_for(filters: list, rng, batch: int, n_batches: int):
    """Concrete topics drawn uniformly over the WHOLE population (roots
    and covered filters both get traffic — expansion correctness and
    cost are part of the measured path)."""
    from tools.workloads import concretize

    pool = [concretize(f) for f in filters]
    return [[pool[i] for i in rng.randint(0, len(pool), batch)]
            for _ in range(n_batches)]


def _run_engine(node, batches, label: str) -> float:
    """Route every batch through prepare/dispatch/materialize; best of
    two timed passes after a warm pass (same discipline as skew_bench —
    symmetric for both engines)."""
    from emqx_tpu.broker.message import make

    eng = node.device_engine
    msg_batches = [[make("p", 0, t, b"x") for t in topics]
                   for topics in batches]
    eng.rebuild()

    def one(msgs):
        h = eng.prepare(msgs, gate_cold=False)
        assert h is not None
        eng.dispatch(h)
        eng.materialize(h)
        eng.abandon(h)      # consume excluded: identical on both paths

    for msgs in msg_batches:            # warm pass: XLA compiles
        one(msgs)
    dt = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        for msgs in msg_batches:
            one(msgs)
        dt = min(dt, time.perf_counter() - t0)
    total = sum(len(m) for m in msg_batches)
    log(f"{label}: {total} topics in {dt:.3f}s "
        f"({total / dt / 1e3:.1f}k matches/s)")
    return total / dt


def _counts_equal(node_on, node_off, topics: list) -> bool:
    """Delivery-count cross-check on a fresh batch (full route_batch,
    consume included): the measured twin must also be a correct twin."""
    from emqx_tpu.broker.message import make
    on = node_on.device_engine.route_batch(
        [make("p", 0, t, b"v") for t in topics])
    off = node_off.device_engine.route_batch(
        [make("p", 0, t, b"v") for t in topics])
    return on is not None and on == off


def _pair(filters, batches, check_topics, tag: str):
    on, off = _mk_node(True), _mk_node(False)
    _subscribe(on, filters, tag)
    _subscribe(off, filters, tag)
    off_ps = _run_engine(off, batches, f"{tag}:covering-off")
    on_ps = _run_engine(on, batches, f"{tag}:covering-on")
    st = on.device_engine.stats()
    assert st["subscription_covering"] and not \
        off.device_engine.stats()["subscription_covering"]
    return {
        "on_per_s": round(on_ps),
        "off_per_s": round(off_ps),
        "speedup": round(on_ps / off_ps, 2),
        "cover": st["cover"],
        "backend": st["backend"],
        "counts_equal": _counts_equal(on, off, check_topics),
    }


def run_cover() -> dict:
    from tools.workloads import (concretize, cover_heavy_filters,
                                 shape_spread_filters)

    n_filters = int(os.environ.get("COVER_FILTERS", 10_000))
    batch = int(os.environ.get("COVER_BATCH", 1024))
    n_batches = int(os.environ.get("COVER_BATCHES", 32))
    ratio = float(os.environ.get("COVER_RATIO", 0.6))

    rng = np.random.RandomState(17)
    heavy = cover_heavy_filters(n_filters, cover_ratio=ratio)
    uniform = shape_spread_filters(n_filters)
    log(f"cover bench: {n_filters} filters, ratio {ratio}, "
        f"{n_batches} batches of {batch}")
    heavy_batches = _topics_for(heavy, rng, batch, n_batches)
    uni_batches = _topics_for(uniform, rng, batch, n_batches)
    check = [concretize(f) for f in heavy[:: max(1, n_filters // 64)]]

    heavy_row = _pair(heavy, heavy_batches, check, "cover-heavy")
    uni_row = _pair(uniform, uni_batches,
                    [concretize(f) for f in uniform[:64]], "uniform")
    return {
        "metric": "cover_topic_matches_per_sec",
        "unit": "topic-matches/s",
        # acceptance: >= 2.0 at ratio >= 0.5, next to the reduction
        # factor that explains it
        "cover_heavy": heavy_row,
        # acceptance: >= 0.95 (covering free when nothing covers)
        "uniform": uni_row,
        "workload": {"filters": n_filters, "batch": batch,
                     "batches": n_batches, "cover_ratio": ratio},
    }


def main():
    print(json.dumps(run_cover()), flush=True)


if __name__ == "__main__":
    main()
