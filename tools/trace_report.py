#!/usr/bin/env python
"""Offline bubble analysis of a flight-recorder dump (ISSUE 7).

Input: a Chrome trace-event JSON produced by the window-causal flight
recorder — `GET /api/v5/pipeline/trace?format=perfetto`,
`FlightRecorder.dump(path)`, or a file saved from the REST endpoint.
The same file loads in https://ui.perfetto.dev for the visual timeline;
this report is the terminal-side triage: per-window stage occupancy,
the dispatch<->materialize overlap fraction, and the bubble
attribution (host_stall / device_stall / lane_backpressure) that says
where a window's time actually went.

Usage:
    python tools/trace_report.py TRACE.json [--json] [--top N]
                                 [--windows N]

--json       emit the raw analysis document instead of the table
--top N      bubble attributions per window (default 3)
--windows N  only print the last N window rows (default: all)

Exit status 2 when the file holds no analyzable window spans (so CI
can assert a bench run actually produced a trace).
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

from emqx_tpu.broker.trace import analyze_chrome  # noqa: E402


def _fmt_s(v: float) -> str:
    if v >= 1.0:
        return f"{v:8.3f}s "
    return f"{v * 1000:8.3f}ms"


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    if as_json:
        argv.remove("--json")
    top = 3
    last = None
    if "--top" in argv:
        i = argv.index("--top")
        top = int(argv[i + 1])
        del argv[i:i + 2]
    if "--windows" in argv:
        i = argv.index("--windows")
        last = int(argv[i + 1])
        del argv[i:i + 2]
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    with open(argv[0]) as f:
        doc = json.load(f)
    a = analyze_chrome(doc, top=top)
    if not a.get("windows"):
        print("no window spans in trace (tracing off, or the ring "
              "only holds node events)", file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps(a, indent=1))
        return 0

    print(f"windows analyzed: {a['windows']}")
    ov = a.get("overlap") or {}
    if ov:
        print(f"dispatch<->materialize overlap: "
              f"{ov['dispatch_materialize']:.1%} "
              f"({_fmt_s(ov['overlapped_s']).strip()} of "
              f"{_fmt_s(ov['materialize_s']).strip()} readback hidden "
              f"under another window's dispatch)")
    occ = a.get("stage_occupancy") or {}
    if occ:
        print("\nstage occupancy (share of its window's span):")
        for name, row in sorted(occ.items(),
                                key=lambda kv: -kv[1]["total_s"]):
            print(f"  {name:18s} total {_fmt_s(row['total_s'])} "
                  f" mean {row['mean_frac']:.1%} of window")
    bub = a.get("bubbles") or {}
    if bub:
        print("\nbubbles (uncovered window time, by attribution):")
        for k, v in bub.get("top", []):
            print(f"  {k:18s} {_fmt_s(v)}")
        print(f"  {'total':18s} {_fmt_s(bub['total_s'])}")
    rows = a.get("last_windows") or []
    if last is not None:
        rows = rows[-last:]
    if rows:
        print(f"\nper-window (last {len(rows)}):")
        for r in rows:
            stages = " ".join(
                f"{k}={v * 1000:.2f}ms"
                for k, v in sorted(r["stages"].items(),
                                   key=lambda kv: -kv[1])[:4])
            bubbles = " ".join(f"{k}={v * 1000:.2f}ms"
                               for k, v in r["bubbles"])
            print(f"  w{r['trace_id']:<6d} span "
                  f"{_fmt_s(r['span_s'])}  {stages}")
            if bubbles:
                print(f"    {'bubbles:':8s} {bubbles}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
