#!/usr/bin/env python
"""Columnar-ingress end-to-end benchmark: the ISSUE-11 acceptance row.

Measures END-TO-END msgs/s — real TCP connections through the live
broker (Listener → Connection → FrameParser → Channel → PublishBatcher
→ route → deliver) — once per (connection count, ingress path):

  columnar=0   the per-packet path: parser.feed, one Packet +
               handle_in + publish per frame — the A/B baseline
  columnar=1   the columnar path: native burst decode → PublishBurst →
               handle_publish_burst → batcher.submit_burst, plus the
               SO_REUSEPORT acceptor lanes

This is the IoT-broker-benchmarking framing (arXiv:2603.21600,
PAPERS.md): committed messages per second under realistic
many-connection traffic, not isolated match throughput. Each
configuration runs in its OWN subprocess (same discipline as
fanout_bench: a config must not inherit the previous one's GC pressure
or jit caches). The child reports msgs/s plus the stage decomposition
(pipeline telemetry snapshot) and the `ingress` section, so a missed
speedup target still ships the evidence of where the wall is.

Correctness rides along: a subscriber counts its deliveries and the
parent asserts the columnar/per-packet twins delivered identical
counts.

Env knobs: INGRESS_CONNS ("64,256" sweep), INGRESS_MSGS_PER_CONN (400),
INGRESS_TOPICS (16), INGRESS_PAYLOAD (64 bytes), INGRESS_SUB_TOPICS (1:
subscriber covers bench/t0..t{n-1} — 1/16 of traffic by default so
egress cannot become the measured wall), INGRESS_TIMEOUT_S (240),
INGRESS_ONE_TIMEOUT_S (300).

Run directly or as `python bench.py` (the `ingress` checkpointed phase).
"""

import asyncio
import gc
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _blob(conn_id: int, n_msgs: int, n_topics: int, payload: int) -> bytes:
    """One publisher connection's whole flood, pre-serialized: CONNECT
    is sent separately; this is n_msgs QoS0 PUBLISH frames."""
    from emqx_tpu.mqtt import packet as P
    from emqx_tpu.mqtt.frame import serialize
    out = bytearray()
    pad = b"x" * max(0, payload - 16)
    for i in range(n_msgs):
        out += serialize(P.Publish(
            topic=f"bench/t{i % n_topics}",
            payload=b"%08d%08d" % (conn_id, i) + pad, qos=0), 4)
    return bytes(out)


async def _connect_raw(port: int, clientid: str):
    """CONNECT over a raw socket; returns (reader, writer) past the
    CONNACK (the flood writes pre-serialized frames, no client object)."""
    from emqx_tpu.mqtt import packet as P
    from emqx_tpu.mqtt.frame import FrameParser, serialize
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(serialize(P.Connect(proto_name="MQTT", proto_ver=4,
                                     clientid=clientid), 4))
    await writer.drain()
    parser = FrameParser(version=4)
    while True:
        data = await reader.read(64)
        if not data:
            raise RuntimeError("connection closed before CONNACK")
        if parser.feed(data):
            return reader, writer


async def _run_child(conns: int, columnar: bool) -> dict:
    from emqx_tpu.broker.connection import Listener
    from emqx_tpu.broker.node import Node
    from emqx_tpu.client import Client

    n_msgs = int(os.environ.get("INGRESS_MSGS_PER_CONN", 400))
    n_topics = int(os.environ.get("INGRESS_TOPICS", 16))
    payload = int(os.environ.get("INGRESS_PAYLOAD", 64))
    sub_topics = int(os.environ.get("INGRESS_SUB_TOPICS", 1))
    timeout_s = float(os.environ.get("INGRESS_TIMEOUT_S", 240))

    node = Node({"broker": {"columnar_ingress": columnar},
                 "log": {"enable": False}})
    lst = Listener(node, bind="127.0.0.1", port=0)
    await lst.start()

    sub = Client(port=lst.port, clientid="ingress-sub")
    await sub.connect()
    for k in range(sub_topics):
        await sub.subscribe(f"bench/t{k}", qos=0)
    delivered = [0]
    order_violations = [0]
    last_seq: dict = {}

    async def _drain_sub():
        # per-publisher order oracle: payload is b"%08d%08d" (conn,
        # seq) — within one publisher the seq must be monotone at the
        # subscriber, whatever the ingress path did
        while True:
            msg = await sub.messages.get()
            delivered[0] += 1
            head = bytes(msg.payload[:16])
            conn_id, seqno = int(head[:8]), int(head[8:])
            if last_seq.get(conn_id, -1) >= seqno:
                order_violations[0] += 1
            last_seq[conn_id] = seqno

    drain_task = asyncio.create_task(_drain_sub())

    async def flood(pairs, blobs):
        async def one(writer, blob):
            w = 0
            while w < len(blob):
                writer.write(blob[w:w + 65536])
                w += 65536
                await writer.drain()
        await asyncio.gather(*[one(w, b)
                               for (_r, w), b in zip(pairs, blobs)])

    async def settle(expect: int, deadline: float) -> bool:
        while time.perf_counter() < deadline:
            if node.metrics.val("messages.publish") >= expect:
                return True
            await asyncio.sleep(0.02)
        return False

    # warm pass: compiles, allocator, acceptor lanes — not timed. The
    # warm flood mirrors the timed flood's batch shape (full windows at
    # max_publish_batch) so the device route class the flood will use
    # compiles NOW, then we wait for the background warm to land —
    # otherwise every timed window cold-classes to the host path and
    # the bench measures the host trie, not the ingest stack.
    n_warm = min(conns, 8)
    warm_pairs = [await _connect_raw(lst.port, f"warm{i}")
                  for i in range(n_warm)]
    warm_blobs = [_blob(900 + i, n_msgs, n_topics, payload)
                  for i in range(n_warm)]
    await flood(warm_pairs, warm_blobs)
    await settle(n_warm * n_msgs, time.perf_counter() + 120)
    for _r, w in warm_pairs:
        w.close()
    eng = node.device_engine
    if eng is not None:
        bmax = node.publish_batcher.max_batch \
            if node.publish_batcher is not None else 1024
        deadline = time.perf_counter() + 90
        while time.perf_counter() < deadline:
            try:
                if eng.batch_class_warm(bmax):
                    break
            except Exception:  # noqa: BLE001 — engine without a snapshot
                break
            await asyncio.sleep(0.05)

    pairs = [await _connect_raw(lst.port, f"pub{i}")
             for i in range(conns)]
    blobs = [_blob(i, n_msgs, n_topics, payload) for i in range(conns)]
    base = node.metrics.val("messages.publish")
    total = conns * n_msgs
    gc.collect()
    t0 = time.perf_counter()
    await flood(pairs, blobs)
    ok = await settle(base + total, t0 + timeout_s)
    wall = time.perf_counter() - t0
    # let in-flight deliveries land before comparing twins: wait until
    # the delivered count stops moving (a fixed sleep raced the lanes
    # at the higher columnar rates)
    stable_at = delivered[0]
    quiet = 0
    deadline = time.perf_counter() + 30
    while quiet < 10 and time.perf_counter() < deadline:
        await asyncio.sleep(0.05)
        if delivered[0] == stable_at:
            quiet += 1
        else:
            stable_at = delivered[0]
            quiet = 0
    snap = node.pipeline_telemetry.snapshot()
    row = {
        "conns": conns,
        "columnar": bool(columnar),
        "msgs": total,
        "completed": ok,
        "wall_s": round(wall, 3),
        "msgs_per_s": round(total / wall) if ok and wall > 0 else 0,
        "delivered": delivered[0],
        "order_violations": order_violations[0],
        "ingress": snap.get("ingress"),
        "stages": snap.get("stages"),
        "decisions": snap.get("decisions"),
        "lanes": getattr(node, "ingress_lanes", None),
    }
    drain_task.cancel()
    for _r, w in pairs:
        w.close()
    await sub.close()
    await lst.stop()
    if node.publish_batcher is not None:
        await node.publish_batcher.stop()
    return row


def run_one(conns: int, columnar: bool) -> dict:
    return asyncio.run(_run_child(conns, columnar))


def run_ingress() -> dict:
    sweep = [int(x) for x in os.environ.get(
        "INGRESS_CONNS", "64,256").split(",")]
    one_timeout = int(os.environ.get("INGRESS_ONE_TIMEOUT_S", 300))
    rows = []
    for conns in sweep:
        for columnar in (0, 1):
            sp = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--one",
                 str(conns), str(columnar)],
                capture_output=True, text=True, timeout=one_timeout)
            row = None
            for ln in reversed(sp.stdout.splitlines()):
                if ln.strip().startswith("{"):
                    row = json.loads(ln)
                    break
            if row is None:
                raise RuntimeError(
                    f"conns={conns} columnar={columnar} child failed "
                    f"rc={sp.returncode}: {sp.stderr[-300:]}")
            rows.append(row)
            log(f"conns={conns} columnar={columnar}: "
                f"{row['msgs_per_s'] / 1e3:.1f}k msgs/s "
                f"delivered={row['delivered']}")
    by = {(r["conns"], r["columnar"]): r for r in rows}
    twins = {}
    delivery_ok = True
    for conns in sweep:
        off, on = by[(conns, False)], by[(conns, True)]
        twins[str(conns)] = {
            "per_packet_msgs_per_s": off["msgs_per_s"],
            "columnar_msgs_per_s": on["msgs_per_s"],
            "speedup": round(on["msgs_per_s"]
                             / max(1, off["msgs_per_s"]), 2),
            "delivered": on["delivered"],
        }
        if on["delivered"] != off["delivered"] \
                or on["order_violations"] or off["order_violations"]:
            delivery_ok = False
    top = max(sweep)
    head = by[(top, True)]
    return {
        "metric": "ingress_msgs_per_sec",
        "unit": "msgs/s",
        "per_conns": twins,
        "best_per_s": head["msgs_per_s"],
        # ISSUE 11 acceptance: >= 3x the per-packet path at the
        # 256-connection CPU flood; the stage decomposition below is
        # the honest-number evidence either way
        "speedup": twins[str(top)]["speedup"],
        "delivery_twin_ok": delivery_ok,
        "ingress": head["ingress"],
        "stage_decomposition": head["stages"],
        "per_packet_stages": by[(top, False)]["stages"],
        "decisions": head["decisions"],
        "lanes": head["lanes"],
        "workload": {
            "conns_sweep": sweep,
            "msgs_per_conn": int(os.environ.get(
                "INGRESS_MSGS_PER_CONN", 400)),
            "topics": int(os.environ.get("INGRESS_TOPICS", 16)),
            "payload": int(os.environ.get("INGRESS_PAYLOAD", 64)),
            "qos": 0,
        },
    }


def main():
    if "--one" in sys.argv:
        i = sys.argv.index("--one")
        conns = int(sys.argv[i + 1])
        columnar = bool(int(sys.argv[i + 2]))
        print(json.dumps(run_one(conns, columnar)), flush=True)
        return
    print(json.dumps(run_ingress()), flush=True)


if __name__ == "__main__":
    main()
