#!/usr/bin/env python
"""Per-stage profile of the fused route step on the real TPU.

VERDICT round-2 weak #1: the fused step runs at 2.0M matches/s while the
match fold alone does 9.3M/s — ~78% of the 65ms batch is somewhere in
fan-out/shared/digest. This script times each stage in isolation using the
same pipelined-window + digest-readback methodology as bench.py, so the
numbers decompose the real batch cost instead of guessing.

Usage: python tools/profile_step.py [subs] [batch] [window]
                                    [--telemetry-out FILE]
                                    [--cost-out FILE]
                                    [--pipeline]

--pipeline (ISSUE 9 satellite) profiles the double-buffered window
pipeline instead of the kernels: drives N windows (PIPE_WINDOWS,
default 48, of PIPE_BATCH messages, default 256) through a REAL
Node → PublishBatcher → device engine at dispatch depth 1 and then
depth 2 (or EMQX_TPU_DISPATCH_DEPTH when set higher), and prints per
depth the flight-recorder dispatch↔materialize overlap fraction and
the amortized ms/window — the two numbers the ISSUE-9 acceptance
criteria gate on, measured the same way bench.py's e2e phase embeds
them.

--telemetry-out dumps the run as a pipeline-telemetry snapshot
(broker.telemetry SCHEMA — the same JSON shape bench.py embeds and
GET /api/v5/pipeline/stats serves): each profiled kernel becomes a stage
row (per-batch ms) and its warm/compile cost lands in the compile
accounting, so profiling rounds and bench rounds share one schema.

--cost-out (ISSUE 8 satellite) dumps the jit-program cost-registry
table: every profiled kernel registers its compile wall-time AND its
lowered `cost_analysis()` (flops, bytes accessed) under the same
`program_costs` section schema `snapshot()["program_costs"]` embeds —
`{program: {class_label: {compiles, compile_ms, flops,
bytes_accessed}}}` — so the ROADMAP-item-2 stage-graph builder reads
one oracle whether the numbers came from a profiling round or a
serving run (`cost_stats(analyze=True)` fills any route-program rows
recorded during this run too).

The FULL schema (ISSUE 7 satellite): the snapshot carries every
section bench rounds now emit, not just the PR-1 stages/occupancy/
compiles — `rebuild` (the table build + device upload measured as
capture/build/swap spans), `readback` (one full-step dense D2H,
actual bytes), `supervise` (a standalone supervisor's live state —
armed EMQX_TPU_FAULTS clauses included), `trace` (the flight
recorder's per-kernel spans + analysis) and `deliver` (present,
empty — no lane pool in a kernel profile), so snapshot diffs across
rounds see a stable shape.
"""

import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _parse_args(argv):
    """Positional [subs] [batch] [window] + --telemetry-out FILE
    + --cost-out FILE + --pipeline."""
    out = None
    cost_out = None
    pipeline = False
    pos = []
    it = iter(argv)
    for a in it:
        if a == "--telemetry-out":
            out = next(it, None)
        elif a.startswith("--telemetry-out="):
            out = a.split("=", 1)[1]
        elif a == "--cost-out":
            cost_out = next(it, None)
        elif a.startswith("--cost-out="):
            cost_out = a.split("=", 1)[1]
        elif a == "--pipeline":
            pipeline = True
        else:
            pos.append(a)
    return pos, out, cost_out, pipeline


def _slug(name: str) -> str:
    return re.sub(r"[^a-z0-9]+", "_", name.lower()).strip("_")


def _engine_window_loop(depth: int, windows: int, batch: int,
                        n_filters: int) -> dict:
    """One depth's engine-level window-pipeline measurement: drives N
    windows through the REAL DeviceRouteEngine stages with the REAL
    batcher concurrency contract at each depth, minus the event-loop
    work (hook folds, sockets, publish futures) that dominates a
    2-core CPU box:

    - dispatch launches AT ADMIT on one ordered thread at EVERY depth
      (the producer has done that since the round-2 pipelined serving
      path — it is part of the pre-ISSUE-9 baseline, so the depth-1
      twin must not be penalized with a serialized dispatch);
    - at depth 1 the consumer is the synchronous loop: await the
      window's dispatch, materialize it on the read pool, finish —
      strictly one window at a time (materialize(W+1) starts only
      after finish(W), the exact ordering tests/test_pipeline_depth's
      trace-shape guard pins);
    - at depth >= 2 up to ``depth`` stage tasks (await-dispatch →
      materialize on the 2-thread read pool) run concurrently ahead of
      their FIFO settle turn — admission is gated on LIVE stage tasks,
      not on settles, exactly like PublishBatcher._consume_pipelined
      (settle-gated admission collapses the effective depth to ~1).

    Each stage records a flight-recorder span, so the SAME analyzer
    that grades bench rounds computes the dispatch↔materialize overlap
    fraction."""
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    from emqx_tpu.broker.message import make
    from emqx_tpu.broker.node import Node
    from emqx_tpu.broker.trace import FlightRecorder, analyze_spans

    node = Node({"broker": {
        "dispatch_depth": depth, "device_fanout_cap": 16,
        "device_slot_cap": 4, "deliver_lanes": 0,
        "device_min_batch": 4,
        # pin the adaptive layers OFF: each one (dedup plan, match
        # cache, compact class ladder, delta overlay) switches fused
        # programs mid-run on its own count/EWMA trigger, and a cold
        # compile inside the timed loop would swamp the per-window
        # number this profile exists to compare across depths
        "topic_dedup": False, "match_cache_size": 0,
        "compact_readback": False, "delta_overlay": False}})

    class _Null:
        def deliver(self, f, m):
            return True
    b = node.broker
    for i in range(n_filters):
        b.subscribe(b.register(_Null(), f"p{i}"), f"t/{i}/+",
                    {"qos": 1})
    eng = node.device_engine
    eng.rebuild()
    rec = node.flight_recorder or FlightRecorder(node.metrics)

    def mkwin(w):
        return [make("p", 1, f"t/{(w * batch + i) % n_filters}/x",
                     b"m%07d" % (w * batch + i)) for i in range(batch)]

    # pool sizes mirror PublishBatcher: one ordered dispatch thread
    # (the engine threads cursors batch-to-batch), two readback threads
    disp_pool = ThreadPoolExecutor(1, thread_name_prefix="pipe-disp")
    read_pool = ThreadPoolExecutor(2, thread_name_prefix="pipe-read")
    # the in-flight stage-task bound: the pool's worker count IS the
    # ring's live-stage-task cap, and its FIFO queue preserves
    # admission order (depth 1 never uses it — see the settle loop)
    stage_pool = ThreadPoolExecutor(max(1, depth),
                                    thread_name_prefix="pipe-stage")
    spans = []

    def disp(h, tid):
        t0 = time.perf_counter()
        eng.dispatch(h)
        spans.append((tid, "dispatch", t0, time.perf_counter()))

    def mat(h, tid):
        m0 = time.perf_counter()
        eng.materialize(h)
        spans.append((tid, "materialize", m0, time.perf_counter()))

    def stage(h, dfut, tid):
        # one window's in-flight stages, the batcher's _run_stages
        # shape: await its admit-launched dispatch, then materialize on
        # the shared read pool
        dfut.result()
        read_pool.submit(mat, h, tid).result()

    def finish(h):
        counts = eng.finish(h)
        assert len(counts) == batch
        return sum(counts)

    # warm laps: compile every program variant the timed loop will hit.
    # The engine ADAPTS across windows (dedup/match-cache engages after
    # the cache fills, compact readback after the payload EWMA seeds),
    # each switch compiling a new fused program — so warm until three
    # consecutive windows ran compile-free (fast), not a fixed count.
    calm, t_min = 0, None
    for w in range(64):
        t_w = time.perf_counter()
        hw = eng.prepare(mkwin(w), gate_cold=False)
        assert hw is not None, "engine stood down on a warm window"
        eng.dispatch(hw)
        eng.materialize(hw)
        eng.finish(hw)
        dt = time.perf_counter() - t_w
        t_min = dt if t_min is None else min(t_min, dt)
        # compile-free = close to the best window seen (an armed hang
        # proxy inflates EVERY window equally, so relative is right)
        calm = calm + 1 if dt < max(0.02, 1.5 * t_min) else 0
        if calm >= 3:
            break

    # the producer's admit bound: how many windows may sit prepared
    # with their dispatch launched ahead of settle (the batcher's
    # _inflight queue depth)
    admit_bound = max(depth, 8)
    routed = 0
    ring: deque = deque()       # (w, handle, dispatch fut, stage fut)
    next_w = 0
    t0 = time.perf_counter()
    while next_w < windows or ring:
        while next_w < windows and len(ring) < admit_bound:
            h = eng.prepare(mkwin(next_w))
            assert h is not None, \
                f"engine stood down at window {next_w}"
            tid = rec.new_trace()
            dfut = disp_pool.submit(disp, h, tid)
            sfut = stage_pool.submit(stage, h, dfut, tid) \
                if depth > 1 else dfut
            ring.append((next_w, h, sfut, tid))
            next_w += 1
        w, h, sfut, tid = ring.popleft()
        sfut.result()
        if depth == 1:
            # synchronous consumer: materialize THIS window now, one
            # at a time
            read_pool.submit(mat, h, tid).result()
        routed += finish(h)
    wall = time.perf_counter() - t0
    disp_pool.shutdown(wait=False)
    read_pool.shutdown(wait=False)
    stage_pool.shutdown(wait=False)
    for tid, name, s0, s1 in spans:
        rec.record(tid, name, s0, s1, track=name)
    a = analyze_spans(rec.spans())
    ov = (a.get("overlap") or {})
    return {
        "dispatch_depth": depth,
        "windows": windows,
        "overlap": ov.get("dispatch_materialize"),
        "ms_per_window": round(wall / windows * 1000, 3),
        "msgs_per_s": round(windows * batch / wall),
        "wall_s": round(wall, 3),
        "routed": routed,
    }


def run_pipeline_profile(windows: int, batch: int,
                         out_path=None) -> dict:
    """ISSUE 9 satellite: the depth-1 vs depth-2 window-pipeline
    profile. Default mode drives the engine window loop directly
    (prepare/dispatch/materialize/finish ring — the device pipeline
    itself); ``PIPE_E2E=1`` instead pushes the same schedule through a
    full Node → PublishBatcher path (hook folds, lanes, publish
    futures — event-loop-bound on small boxes). Either way the flight
    recorder's analyzer reports the dispatch↔materialize overlap
    fraction and the wall clock gives amortized ms/window, per depth.
    Arm `EMQX_TPU_FAULTS="dispatch:hang:...,materialize:hang:..."` to
    emulate the axon relay's link turnaround on a CPU box (the hangs
    sleep with the GIL released, exactly like the HTTP wait)."""
    n_filters = int(os.environ.get("PIPE_FILTERS", 64))
    depths = sorted({1, max(2, int(os.environ.get(
        "EMQX_TPU_DISPATCH_DEPTH", 2) or 2))})
    rows = {}
    if os.environ.get("PIPE_E2E", "0") != "1":
        for depth in depths:
            rows[depth] = _engine_window_loop(depth, windows, batch,
                                              n_filters)
            log(f"depth {depth}: "
                f"{rows[depth]['ms_per_window']:8.2f} ms/window  "
                f"{rows[depth]['msgs_per_s']:>8d} msgs/s  "
                f"overlap={rows[depth]['overlap']}")
        base, top = rows[depths[0]], rows[depths[-1]]
        if base["wall_s"] and top["wall_s"]:
            log(f"depth {depths[-1]} vs {depths[0]}: "
                f"{base['wall_s'] / top['wall_s']:.2f}x msgs/s")
        doc = {"metric": "pipeline_profile", "mode": "engine",
               "windows": windows, "batch": batch, "depths": rows}
        print(json.dumps(doc), flush=True)
        if out_path:
            with open(out_path, "w") as f:
                json.dump(doc, f, indent=1)
        return doc
    import asyncio

    from emqx_tpu.broker.message import make
    from emqx_tpu.broker.node import Node

    for depth in depths:
        node = Node({"broker": {
            "dispatch_depth": depth,
            "device_fanout_cap": 16, "device_slot_cap": 4,
            "deliver_lanes": 2, "device_min_batch": 4,
            "batch_window_us": 2000,
            "max_publish_batch": batch + 1}})
        # pin the adaptive chooser to the device: this profile measures
        # the DEVICE window pipeline, not the host-probe cadence
        node.publish_batcher._device_worth_it = lambda n: True

        class _Null:
            def deliver(self, f, m):
                return True
        b = node.broker
        for i in range(n_filters):
            b.subscribe(b.register(_Null(), f"p{i}"), f"t/{i}/+",
                        {"qos": 1})

        async def go():
            eng = node.device_engine
            eng.rebuild()
            eng._kick_class_warm()
            if eng._fuse_warm_task is not None:
                await eng._fuse_warm_task
            # warm lap (compiles out of the timed window)
            await asyncio.gather(*[
                node.publish_async(make("p", 1, f"t/{i % n_filters}/w",
                                        b"warm"))
                for i in range(batch)])
            pool = node.deliver_lanes
            if pool is not None:
                await pool.drain()
            rec0 = node.flight_recorder
            mark = rec0.recorded() if rec0 is not None else 0
            t0 = time.perf_counter()
            futs = []
            for w in range(windows):
                futs.extend(asyncio.ensure_future(node.publish_async(
                    make("p", 1, f"t/{(w * batch + i) % n_filters}/x",
                         b"m%07d" % (w * batch + i))))
                    for i in range(batch))
            await asyncio.gather(*futs)
            if pool is not None:
                await pool.drain()
            return time.perf_counter() - t0, mark

        wall, mark = asyncio.new_event_loop().run_until_complete(go())
        rec = node.flight_recorder
        if rec is not None:
            # analyze ONLY the timed window's spans (the warm lap's
            # compile-skewed spans would poison the overlap fraction)
            from emqx_tpu.broker.trace import analyze_spans
            analysis = analyze_spans(
                [s for s in rec.spans() if s.slot >= mark])
        else:
            analysis = {}
        ov = (analysis.get("overlap") or {})
        rows[depth] = {
            "dispatch_depth": depth,
            "windows": analysis.get("windows"),
            "overlap": ov.get("dispatch_materialize"),
            "ms_per_window": round(wall / windows * 1000, 3),
            "msgs_per_s": round(windows * batch / wall),
            "wall_s": round(wall, 3),
            "device_windows":
                node.metrics.val("routing.device.batches"),
        }
        log(f"depth {depth}: {rows[depth]['ms_per_window']:8.2f} "
            f"ms/window  {rows[depth]['msgs_per_s']:>8d} msgs/s  "
            f"overlap={rows[depth]['overlap']}")
    base, top = rows[depths[0]], rows[depths[-1]]
    if base["ms_per_window"] and top["ms_per_window"]:
        log(f"depth {depths[-1]} vs {depths[0]}: "
            f"{base['ms_per_window'] / top['ms_per_window']:.2f}x "
            f"msgs/s")
    doc = {"metric": "pipeline_profile", "mode": "e2e",
           "windows": windows, "batch": batch, "depths": rows}
    print(json.dumps(doc), flush=True)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1)
    return doc


def main():
    pos, telemetry_out, cost_out, pipeline = _parse_args(sys.argv[1:])
    if pipeline:
        run_pipeline_profile(
            int(os.environ.get("PIPE_WINDOWS", 48)),
            int(os.environ.get("PIPE_BATCH", 256)),
            out_path=telemetry_out)
        return
    subs = int(pos[0]) if len(pos) > 0 else 1_000_000
    B = int(pos[1]) if len(pos) > 1 else 131072
    window = int(pos[2]) if len(pos) > 2 else 16

    from emqx_tpu.broker.supervise import PipelineSupervisor
    from emqx_tpu.broker.telemetry import PipelineTelemetry
    from emqx_tpu.broker.trace import FlightRecorder
    tele = PipelineTelemetry()
    # the newer snapshot sections ride this run too: supervise (armed
    # chaos clauses + breaker state), trace (per-kernel spans)
    sup = PipelineSupervisor(tele.metrics, telemetry=tele)
    tele.supervise_state_fn = sup.state
    rec = FlightRecorder(tele.metrics)
    tele.recorder = rec

    import jax
    import jax.numpy as jnp

    from bench import put_tree_chunked, _put_retry
    from emqx_tpu.models.router_engine import (ShapeRouterTables,
                                               route_step_shapes)
    from emqx_tpu.ops import intern as I
    from emqx_tpu.ops.fanout import (SubTable, fanout_normal, shared_slots)
    from emqx_tpu.ops.shapes import build_shape_tables, shape_match
    from emqx_tpu.ops.shared import (STRATEGY_ROUND_ROBIN, pick_members,
                                     _rank_and_occur)

    log(f"profile: subs={subs} B={B} window={window} dev={jax.devices()[0]}")

    # same filter set as bench.py
    ids = max(64, int(np.sqrt(subs)))
    nums = max(1, subs // ids)
    F = ids * nums
    intern = I.InternTable()
    wd = intern.intern("device")
    id_ids = np.array([intern.intern(f"d{i}") for i in range(ids)], np.int32)
    num_ids = np.array([intern.intern(f"n{n}") for n in range(nums)], np.int32)
    rows = np.zeros((F, 8), np.int32)
    lens = np.full(F, 5, np.int64)
    rows[:, 0] = wd
    rows[:, 1] = np.repeat(id_ids, nums)
    rows[:, 2] = I.PLUS
    rows[:, 3] = np.tile(num_ids, ids)
    rows[:, 4] = I.HASH

    t0 = time.time()
    shapes = build_shape_tables(rows, lens)
    # the table build is this run's `rebuild.build` — profiling and
    # bench rounds share the rebuild-stage schema (ISSUE 7 satellite)
    tele.observe_rebuild("build", time.time() - t0)
    log(f"build {time.time()-t0:.1f}s buckets={shapes.buckets.shape[0]}")

    shared_pct = 50
    n_shared_filters = F * shared_pct // 100
    sub_start = np.arange(F + 1, dtype=np.int32)
    sub_row = np.arange(F, dtype=np.int32)
    sub_opts = np.ones(F, np.int8)
    group_of = np.arange(n_shared_filters, dtype=np.int32) // 16
    n_groups = max(1, int(group_of.max(initial=0)) + 1)
    fs_start = np.zeros(F + 1, np.int32)
    fs_start[1:n_shared_filters + 1] = 1
    np.cumsum(fs_start, out=fs_start)
    fs_slot = group_of if n_shared_filters else np.full(1, -1, np.int32)
    shared_start = np.arange(n_groups + 1, dtype=np.int32) * 8
    shared_row = F + np.arange(n_groups * 8, dtype=np.int32)
    shared_opts_a = np.ones(n_groups * 8, np.int8)
    subs_tbl = SubTable(sub_start, sub_row, sub_opts, fs_start, fs_slot,
                        shared_start, shared_row, shared_opts_a)
    t_up = time.time()
    tables = put_tree_chunked(ShapeRouterTables(shapes=shapes, subs=subs_tbl))
    jax.block_until_ready(tables)
    # the device upload is the profiling analog of `rebuild.swap`
    tele.observe_rebuild("swap", time.time() - t_up)
    cursors0 = _put_retry(np.zeros(n_groups, np.int32))
    strat = _put_retry(np.int32(STRATEGY_ROUND_ROBIN))

    x = intern.intern("x")
    tail = intern.intern("t")
    rng = np.random.RandomState(7)
    staged = []
    for k in range(8):
        zipf = np.minimum(rng.zipf(1.3, size=B) - 1, ids - 1)
        tp = np.zeros((B, 8), np.int32)
        tp[:, 0] = wd
        tp[:, 1] = id_ids[zipf]
        tp[:, 2] = x
        tp[:, 3] = num_ids[rng.randint(0, nums, B)]
        tp[:, 4] = tail
        staged.append((_put_retry(tp),
                       _put_retry(np.full(B, 5, np.int32)),
                       _put_retry(np.zeros(B, bool)),
                       _put_retry(rng.randint(0, 1 << 30, B)
                                  .astype(np.int32))))

    FAN_CAP = int(os.environ.get("BENCH_FANOUT_CAP", 4))
    SLOT_CAP = int(os.environ.get("BENCH_SLOT_CAP", 2))

    from emqx_tpu.models.router_engine import (_analyze_lowered,
                                               record_program_cost)

    def _record_cost(stage, fn, warm_ms):
        """One cost-registry row per profiled kernel (ISSUE 8
        satellite): the warm pass's compile wall-time plus the lowered
        program's cost_analysis (flops, bytes accessed) — the same
        `program_costs` table the serving path's route programs
        populate, so --cost-out and the telemetry snapshot share one
        schema. Lowering is tracing-only (no backend compile); kernels
        without .lower (the fused-window wrapper) record wall only.
        The re-lower (a full re-trace per kernel) runs only when a
        consumer asked for the table — a bare profiling run stays at
        wall-time-only rows."""
        flops = ba = None
        if cost_out or telemetry_out:
            try:
                low = fn.lower(_put_retry(np.int32(0)), tables,
                               staged[0])
                flops, ba = _analyze_lowered(low)
            except Exception:  # noqa: BLE001 — analysis is best-effort
                pass
        record_program_cost(stage, f"profile {stage}",
                            compile_ms=warm_ms, flops=flops,
                            bytes_accessed=ba)

    def timed(name, fn, topics_per_call=B):
        """Pipelined window of `fn(acc, tables, staged[i])` closed by one
        scalar read. Tables ride as explicit jit arguments — closing over
        them would bake the bucket table into the HLO, which the relay
        rejects at bench scale (same rule as bench.py's step_digest).
        topics_per_call: how many topics one call routes (a fused-window
        call routes FUSE*B — the table stays per-batch honest)."""
        batches_per_call = topics_per_call // B
        stage = _slug(name)

        def run(n):
            acc = _put_retry(np.int32(0))
            t0 = time.time()
            for i in range(n):
                acc = fn(acc, tables, staged[i % 8])
            _ = int(np.asarray(acc))
            return time.time() - t0
        t_warm = time.perf_counter()
        with tele.compile_context(f"profile {stage}"):
            run(2)  # warm/compile (attributed to this kernel's shape)
        _record_cost(stage, fn,
                     (time.perf_counter() - t_warm) * 1000.0)
        t_meas = time.perf_counter()
        dt = run(window)
        # each timed kernel is one "window" on the flight recorder:
        # the trace section shows the measurement timeline per kernel
        rec.record(rec.new_trace(), stage, t_meas,
                   time.perf_counter(), track="profile")
        per_ms = dt / (window * batches_per_call) * 1000
        tele.observe_stage(stage, per_ms / 1000.0)
        log(f"{name:34s} {per_ms:8.2f} ms/batch   "
            f"{topics_per_call*window/dt/1e6:6.1f}M/s")
        return per_ms

    # 1. match only
    @jax.jit
    def f_match(acc, tb, batch):
        t, l, d, h = batch
        r = shape_match(tb.shapes, t, l, d)
        return acc + r.matches.sum(dtype=jnp.int32) + r.counts.sum()

    # 2. match + fanout_normal
    @jax.jit
    def f_fan(acc, tb, batch):
        t, l, d, h = batch
        r = shape_match(tb.shapes, t, l, d)
        fr = fanout_normal(tb.subs, r.matches, fanout_cap=FAN_CAP)
        return (acc + fr.rows.sum(dtype=jnp.int32) + fr.counts.sum()
                + fr.opts.sum(dtype=jnp.int32))

    # 3. match + shared_slots
    @jax.jit
    def f_slots(acc, tb, batch):
        t, l, d, h = batch
        r = shape_match(tb.shapes, t, l, d)
        sids, ov = shared_slots(tb.subs, r.matches, slot_cap=SLOT_CAP)
        return acc + sids.sum(dtype=jnp.int32) + ov.sum()

    # 4. match + slots + pick_members (full shared path)
    @jax.jit
    def f_shared(acc, tb, batch):
        t, l, d, h = batch
        r = shape_match(tb.shapes, t, l, d)
        sids, ov = shared_slots(tb.subs, r.matches, slot_cap=SLOT_CAP)
        sp = pick_members(tb.subs, cursors0, sids, strat, h)
        return (acc + sp.rows.sum(dtype=jnp.int32)
                + sp.new_cursors.sum(dtype=jnp.int32))

    # 4b. rank+occur alone (the sort-free blocked kernel on accelerators)
    @jax.jit
    def f_rank(acc, tb, batch):
        t, l, d, h = batch
        sids = jnp.stack([h % np.int32(n_groups),
                          jnp.full((B,), -1, jnp.int32)], axis=1)
        rank, occur = _rank_and_occur(sids, n_groups)
        return (acc + rank.sum(dtype=jnp.int32)
                + occur.sum(dtype=jnp.int32))

    # 4c. occur scatter-add alone
    @jax.jit
    def f_occur(acc, tb, batch):
        t, l, d, h = batch
        safe = (h % np.int32(n_groups)).astype(jnp.int32)
        occur = jnp.zeros(n_groups, jnp.int32).at[safe].add(1, mode="drop")
        return acc + occur.sum(dtype=jnp.int32)

    # 5. full fused step + digest (= the bench single-batch step)
    @jax.jit
    def f_full(acc, tb, batch):
        t, l, d, h = batch
        r = route_step_shapes(tb, cursors0, t, l, d, h, strat,
                              fanout_cap=FAN_CAP, slot_cap=SLOT_CAP)
        return (acc + r.rows.sum(dtype=jnp.int32)
                + r.fan_counts.sum(dtype=jnp.int32)
                + r.shared_rows.sum(dtype=jnp.int32)
                + r.match_counts.sum(dtype=jnp.int32)
                + r.opts.sum(dtype=jnp.int32))

    # 6. W-fused window (one dispatch per FUSE batches) — what bench.py
    # now measures; the delta vs f_full isolates per-dispatch overhead
    from emqx_tpu.models.router_engine import route_window_shapes
    FUSE = max(1, min(int(os.environ.get("BENCH_FUSE", 8)), 8))
    stacked = tuple(jnp.stack([staged[k % 8][i] for k in range(FUSE)])
                    for i in range(4))

    @jax.jit
    def f_window_impl(acc, tb, t4, l4, d4, h4):
        new_cur, digests = route_window_shapes(
            tb, cursors0, t4, l4, d4, h4, strat,
            fanout_cap=FAN_CAP, slot_cap=SLOT_CAP)
        return acc + digests.sum(dtype=jnp.int32)

    def f_window(acc, tb, _batch):
        return f_window_impl(acc, tb, *stacked)

    # 7. pallas fold backend (match-only, lane-major kernel)
    from emqx_tpu.ops.shapes import shape_match_pallas

    @jax.jit
    def f_match_pallas(acc, tb, batch):
        t, l, d, h = batch
        r = shape_match_pallas(tb.shapes, t, l, d)
        return acc + r.matches.sum(dtype=jnp.int32) + r.counts.sum()

    timed("match only", f_match)
    timed("match only (pallas fold)", f_match_pallas)
    timed("match+fanout", f_fan)
    timed("match+shared_slots", f_slots)
    timed("match+slots+pick_members", f_shared)
    timed("rank/occur alone", f_rank)
    timed("occur scatter-add alone", f_occur)
    timed("FULL route_step + digest", f_full)
    timed(f"FUSED window x{FUSE} (per batch)", f_window,
          topics_per_call=B * FUSE)

    # one full-step DENSE readback: the actual device→host transfer the
    # broker's materialize stage pays, measured here so the snapshot's
    # `readback` section carries real bytes/span next to the kernel
    # times (the digest-closed windows above deliberately avoid D2H)
    @jax.jit
    def _step_full(tb, t, l, d, h):
        return route_step_shapes(tb, cursors0, t, l, d, h, strat,
                                 fanout_cap=FAN_CAP, slot_cap=SLOT_CAP)

    with tele.compile_context("profile dense_readback"):
        r_full = _step_full(tables, *staged[0])
        jax.block_until_ready(r_full.matches)
    t_mat = time.perf_counter()
    planes = [np.asarray(x) for x in
              (r_full.matches, r_full.rows, r_full.opts,
               r_full.shared_sids, r_full.shared_rows,
               r_full.shared_opts, r_full.overflow, r_full.occur)]
    tele.observe_stage("materialize", time.perf_counter() - t_mat)
    tele.metrics.inc("pipeline.readback.bytes.dense",
                     sum(p.nbytes for p in planes))
    tele.metrics.inc("pipeline.readback.windows.dense")
    log(f"dense readback: {sum(p.nbytes for p in planes) / 1e6:.1f}MB "
        f"in {(time.perf_counter() - t_mat) * 1000:.1f}ms")

    if telemetry_out:
        snap = tele.snapshot(full=True)
        snap["profile"] = {"subs": subs, "batch": B, "window": window,
                           "fuse": FUSE}
        with open(telemetry_out, "w") as f:
            json.dump(snap, f, indent=1)
        log(f"telemetry snapshot -> {telemetry_out}")

    if cost_out:
        # the per-program cost table (ISSUE 8): analyze=True fills
        # flops/bytes for any route-program rows this run compiled
        # (tracing cost only — exactly the off-path consumer the lazy
        # analysis exists for); the profiled kernels' rows were
        # recorded eagerly above
        from emqx_tpu.broker.telemetry import SCHEMA as PIPE_SCHEMA
        from emqx_tpu.models.router_engine import cost_stats
        doc = {"schema": PIPE_SCHEMA,
               "program_costs": cost_stats(analyze=True),
               "profile": {"subs": subs, "batch": B, "window": window,
                           "fuse": FUSE}}
        with open(cost_out, "w") as f:
            json.dump(doc, f, indent=1)
        log(f"program cost table -> {cost_out}")


if __name__ == "__main__":
    main()
