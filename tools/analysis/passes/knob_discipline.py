"""knob-discipline: every ``EMQX_TPU_*`` env knob resolves, is
documented, and has a test reference.

The repo's knob contract (established in PR 2 and repeated in every
PR since): **config beats env beats default**, resolved in exactly one
``resolve_*`` function per knob, with an off-twin test pinning the
disabled behavior and a doc naming the knob. Drift in any leg is
silent: an env read outside a resolver can't be overridden by config
(the config value silently loses), an undocumented knob is invisible
to operators, and an untested knob's off-path rots. Four checks:

1. **resolver routing** — every AST-level read of an ``EMQX_TPU_*``
   env var (``os.environ.get``/``[]``/``os.getenv``) must sit inside a
   function whose name starts with ``resolve_`` (the per-knob
   config-beats-env-beats-default resolver convention; module-level
   one-shot knobs call their resolver at import:
   ``_X = resolve_x()``).
2. **doc presence** — the knob name appears in ``docs/*.md``
   (extends PR 7's doc-drift gate from metric names to knobs).
3. **test reference** — the knob name, or the ``broker.*``/``mqtt.*``
   config key its resolver names, appears under ``tests/`` (the
   off-twin test the A/B contract requires).
4. **doc drift, reverse direction** — every ``EMQX_TPU_*`` token
   cited in ``docs/*.md`` is read somewhere in the repo (package,
   tools/, bench.py, tests/) — docs must not advertise dead knobs.

Annotate deliberate exceptions with
``# analysis: ok(knob-discipline) — <reason>`` at the env-read site.
"""

from __future__ import annotations

import ast
import re

from analysis.core import Finding, Repo, dotted_name, parent_chain, \
    stmt_span

NAME = "knob-discipline"

_KNOB_RE = re.compile(r"EMQX_TPU_[A-Z0-9_]+")
_CONF_KEY_RE = re.compile(r"\b(?:broker|mqtt)\.[a-z][a-z0-9_]*")


def _env_read(call: ast.Call) -> str:
    """The EMQX_TPU_* name this call reads, or ''."""
    dot = dotted_name(call.func)
    # `import os as _os` is a live idiom (ops/shared.py) — match on
    # the environ.get / getenv suffix, not the exact alias
    if not (dot.endswith("environ.get") or dot.endswith(".getenv")
            or dot == "getenv"):
        return ""
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str) \
            and call.args[0].value.startswith("EMQX_TPU_"):
        return call.args[0].value
    return ""


def _env_subscript(node: ast.Subscript) -> str:
    if not dotted_name(node.value).endswith("environ"):
        return ""
    sl = node.slice
    if isinstance(sl, ast.Constant) and isinstance(sl.value, str) \
            and sl.value.startswith("EMQX_TPU_"):
        return sl.value
    return ""


def _enclosing_resolver(node) -> str:
    for p in parent_chain(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return p.name if p.name.startswith("resolve_") else ""
    return ""


def _resolver_config_keys(node, mod) -> set:
    """The broker.*/mqtt.* config keys the enclosing resolver names
    (docstring or body) — the knob's test may pin the config twin
    instead of the env name."""
    for p in parent_chain(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            lo = p.lineno
            hi = getattr(p, "end_lineno", lo)
            seg = "\n".join(mod.lines[lo - 1:hi])
            return set(_CONF_KEY_RE.findall(seg))
    return set()


def run(repo: Repo) -> list[Finding]:
    tests_blob = "\n".join(repo.tests.values())
    docs_blob = "\n".join(repo.docs.values())
    code_knob_reads: set[str] = set()
    out: list[Finding] = []
    for mod in repo.modules.values():
        if mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                knob = _env_read(node)
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load):
                knob = _env_subscript(node)
            else:
                continue
            if not knob:
                continue
            code_knob_reads.add(knob)
            lo, hi = stmt_span(node)
            resolver = _enclosing_resolver(node)
            if not resolver:
                out.append(Finding(
                    NAME, mod.path, node.lineno,
                    f"{knob}:resolver",
                    f"{knob} read outside a resolve_* function — "
                    f"route it through a config-beats-env-beats-"
                    f"default resolver (module-level knobs call the "
                    f"resolver at import: `_X = resolve_x()`)",
                    end_line=hi, stmt_line=lo))
            if knob not in docs_blob:
                out.append(Finding(
                    NAME, mod.path, node.lineno,
                    f"{knob}:docs",
                    f"{knob} is read here but documented in no "
                    f"docs/*.md — operators can't discover it",
                    end_line=hi, stmt_line=lo))
            conf_keys = _resolver_config_keys(node, mod)
            # tests reference the config twin as a nested dict key
            # ({"broker": {"topic_dedup": ...}}), so the bare last
            # component counts as a reference too
            if knob not in tests_blob and not any(
                    k in tests_blob or k.split(".", 1)[1] in tests_blob
                    for k in conf_keys):
                alias = (f" (nor its config twin "
                         f"{'/'.join(sorted(conf_keys))})"
                         if conf_keys else "")
                out.append(Finding(
                    NAME, mod.path, node.lineno,
                    f"{knob}:tests",
                    f"{knob} appears in no test{alias} — the off-twin "
                    f"A/B contract is unpinned",
                    end_line=hi, stmt_line=lo))
    # reverse doc drift: docs must not cite dead knobs. Findings anchor
    # on the doc file; suppression is code-side only, so a dead doc
    # knob can only be fixed by fixing the doc (or the code) — exactly
    # the doc-drift-gate posture PR 7 set for metric names.
    live = set(code_knob_reads)
    for blob in repo.extra_code.values():
        live.update(_KNOB_RE.findall(blob))
    live.update(_KNOB_RE.findall(tests_blob))
    for dpath, dtext in sorted(repo.docs.items()):
        for i, ln in enumerate(dtext.splitlines(), start=1):
            for m in _KNOB_RE.finditer(ln):
                if m.group(0) not in live:
                    out.append(Finding(
                        NAME, dpath, i,
                        f"{m.group(0)}:dead-doc",
                        f"docs cite {m.group(0)} but nothing in the "
                        f"repo reads it — dead knob or typo"))
    # one finding per (file, defect), not one per read site — a knob
    # read twice in one module is still one missing doc
    seen: set[tuple[str, str]] = set()
    deduped: list[Finding] = []
    for f in out:
        key = (f.path, f.anchor)
        if key in seen:
            continue
        seen.add(key)
        deduped.append(f)
    return deduped
