"""The analyzer passes. Each module exposes ``run(repo) -> [Finding]``
and a ``NAME`` matching its key in ``analysis.core.PASS_NAMES``. See
docs/ANALYSIS.md for the catalog and how to add one."""
