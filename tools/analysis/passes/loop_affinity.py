"""loop-affinity: blocking calls reachable inside loop-context code.

The event loop is the broker's shared artery — every connection, lane
worker, batcher stage and telemetry tick multiplexes over it. One
blocking call in loop-reachable code stalls all of them at once (the
PR 3 deflaking saga measured exactly this class: tens of ms of loop
stall from an inline build). This pass flags, in any function the
context engine classifies loop-reachable:

- ``time.sleep(...)``
- blocking ``<...lock...>.acquire()`` — the bare-acquire form whose
  release may sit arbitrarily far away; ``with lock:`` critical
  sections are the accepted idiom and are NOT flagged, and
  ``acquire(blocking=False)`` / ``acquire(timeout=0)`` are non-blocking
- sync subprocess use (``subprocess.run/call/check_*/Popen``,
  ``os.system``)
- sync socket ops (``.recv/.recvfrom/.accept/.sendall/.makefile`` on a
  ``*sock*`` receiver, ``select.select``)
- ``.block_until_ready()`` — a device sync on the loop stalls serving
  for a full round-trip
- ctypes native calls (any ``_lib.*`` call — the package's one ctypes
  handle lives in ``emqx_tpu/native.py``)

A call that is directly ``await``-ed is not blocking (that's the
point of awaiting). Deliberate exceptions carry
``# analysis: ok(loop-affinity) — <reason>`` at the blocking site; the
finding names the loop-reachability chain so the reviewer can check
the analyzer's claim, not just trust it.
"""

from __future__ import annotations

import ast

from analysis.core import Finding, Repo, dotted_name, stmt_span
from analysis.contexts import _body_walk

NAME = "loop-affinity"

_SUBPROCESS = ("run", "call", "check_call", "check_output", "Popen")
_SOCK_METHODS = ("recv", "recvfrom", "accept", "sendall", "makefile")


def _is_awaited(call: ast.Call) -> bool:
    return isinstance(getattr(call, "_an_parent", None), ast.Await)


def _kw(call: ast.Call, name: str):
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def _nonblocking_acquire(call: ast.Call) -> bool:
    b = _kw(call, "blocking")
    if isinstance(b, ast.Constant) and b.value is False:
        return True
    if call.args and isinstance(call.args[0], ast.Constant) \
            and call.args[0].value is False:
        return True
    t = _kw(call, "timeout")
    return isinstance(t, ast.Constant) and t.value == 0


def _blocking_reason(call: ast.Call) -> str:
    """Why this call blocks, or '' when it does not."""
    fn = call.func
    dot = dotted_name(fn)
    attr = fn.attr if isinstance(fn, ast.Attribute) else dot
    head = dot.split(".")[0] if dot else ""
    if dot == "time.sleep":
        return "time.sleep blocks the loop"
    if attr == "acquire" and isinstance(fn, ast.Attribute):
        recv = dotted_name(fn.value).lower()
        if ("lock" in recv or "sem" in recv or "cond" in recv) \
                and not _nonblocking_acquire(call):
            return (f"blocking {dotted_name(fn)}() — use `with` for a "
                    f"bounded critical section or acquire(blocking="
                    f"False)")
    if head == "subprocess" and attr in _SUBPROCESS:
        return f"sync subprocess.{attr} blocks the loop"
    if dot == "os.system":
        return "os.system blocks the loop"
    if dot == "select.select":
        return "select.select blocks the loop"
    if attr == "block_until_ready":
        return (".block_until_ready() synchronizes with the device on "
                "the loop — a full link round-trip of stall")
    if attr in _SOCK_METHODS and isinstance(fn, ast.Attribute) \
            and "sock" in dotted_name(fn.value).lower():
        return f"sync socket .{attr} blocks the loop"
    if head == "_lib":
        return (f"ctypes native call {dot} holds the loop for its "
                f"full native runtime")
    return ""


def run(repo: Repo) -> list[Finding]:
    graph = repo.contexts
    out: list[Finding] = []
    for fi in graph.functions:
        if "loop" not in fi.contexts:
            continue
        for node in _body_walk(fi.node):
            if not isinstance(node, ast.Call) or _is_awaited(node):
                continue
            why = _blocking_reason(node)
            if not why:
                continue
            lo, hi = stmt_span(node)
            chain = graph.chain_str(fi, "loop")
            out.append(Finding(
                NAME, fi.mod.path, node.lineno,
                f"{fi.qualname}:{dotted_name(node.func)}",
                f"{why}; loop-reachable via {chain}",
                end_line=hi, stmt_line=lo))
    return out
