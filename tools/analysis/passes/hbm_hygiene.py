"""hbm-hygiene: every persistent ``jax.device_put`` rides the HBM
ledger.

Migrated from tools/check_hbm_hygiene.py (ISSUE 8 satellite) onto the
shared framework; the script remains as a CLI-compatible shim. The
ledger (broker/hbm_ledger.py) only works if every persistent device
allocation routes through it — one forgotten site and
``accounted_fraction`` silently drifts below 1 while the capacity
forecast under-counts. A ``device_put`` call is ACCOUNTED when any of:

1. it is (transitively, within its statement) an argument of a
   ``hold(...)``/``_hold(...)`` call — the direct-wrap idiom;
2. its statement (or the line above) carries an ``# hbm:`` comment
   naming where the hold happens or why the bytes are transient —
   the split-site idiom (``# analysis: ok(hbm-hygiene) — <reason>``
   works too, via the shared annotation grammar);
3. it lives in ``broker/hbm_ledger.py`` itself.
"""

from __future__ import annotations

import ast

from analysis.core import Finding, Repo, enclosing_qual, parent_chain

NAME = "hbm-hygiene"


def _is_device_put(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr == "device_put"
    if isinstance(fn, ast.Name):
        return fn.id == "device_put"
    return False


def _is_hold(call: ast.Call) -> bool:
    fn = call.func
    name = fn.attr if isinstance(fn, ast.Attribute) else \
        fn.id if isinstance(fn, ast.Name) else ""
    return name in ("hold", "_hold")


def _inside_hold(node: ast.AST) -> bool:
    """Is this device_put (transitively) an argument of a hold call?
    The walk stops at statement boundaries — a hold elsewhere in the
    function does not bless this put."""
    for cur in parent_chain(node):
        if isinstance(cur, ast.stmt):
            return False
        if isinstance(cur, ast.Call) and _is_hold(cur):
            return True
    return False


def _stmt_of(node: ast.AST) -> ast.AST:
    for cur in parent_chain(node):
        if isinstance(cur, ast.stmt):
            return cur
    return node


def _has_hbm_comment(lines: list, lo: int, hi: int) -> bool:
    """`# hbm:` anywhere on source lines [lo, hi] (1-indexed), or on
    the line just above (the split-site idiom puts the pointer comment
    on its own line before the statement)."""
    for ln in lines[max(0, lo - 2):hi]:
        if "# hbm:" in ln:
            return True
    return False


def check_module(mod) -> list[Finding]:
    out: list[Finding] = []
    if mod.tree is None or mod.path.endswith("hbm_ledger.py"):
        return out
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and _is_device_put(node)):
            continue
        if _inside_hold(node):
            continue
        stmt = _stmt_of(node)
        lo = stmt.lineno
        hi = getattr(stmt, "end_lineno", lo)
        if _has_hbm_comment(mod.lines, lo, hi):
            continue
        out.append(Finding(
            NAME, mod.path, node.lineno,
            f"device_put:{enclosing_qual(node)}",
            "jax.device_put bypasses the HBM ledger — wrap in "
            "ledger.hold(category, ...) or annotate the statement "
            "with `# hbm: <where held / why transient>`",
            end_line=hi, stmt_line=lo))
    return out


def run(repo: Repo) -> list[Finding]:
    out: list[Finding] = []
    for mod in repo.modules.values():
        out.extend(check_module(mod))
    return out
