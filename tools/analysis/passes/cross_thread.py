"""cross-thread-state: unguarded read-modify-write races on shared
attributes.

The defect class PR 7's review caught by hand: a counter incremented
from executor-thread code (``self.recorded += 1`` in the flight
recorder's hot path) while loop-side code reads or writes it — a
preempted writer's stale store silently corrupts the count. Under
CPython's GIL a PLAIN attribute store or load is atomic, and this
codebase leans on that deliberately ("all gates are plain attribute
reads — no locks on the serving path"), so plain stores/loads are NOT
findings. The race needs a read-modify-write:

- an ``AugAssign`` on ``self.attr`` (or on ``self.attr[key]``), or an
  ``Assign`` to ``self.attr`` whose value reads the same attribute,
- in a function the context engine classifies THREAD-reachable (it can
  race loop code and its own pool siblings) — or loop-reachable while
  a thread-context function of the same class writes the attribute,
- with the attribute also touched from at least one OTHER method of
  the class (a single-method private counter cannot race itself on
  the loop),
- and the RMW site not inside a ``with <...lock...>:`` block.

Additionally, once a class guards an attribute with a lock anywhere,
every non-``__init__`` WRITE of it must be guarded too — a
half-locked attribute is worse than an unlocked one (the lock
documents an intent the bypassing site silently breaks).

Fix with a lock at both sites, or annotate the site with
``# analysis: ok(cross-thread-state) — <why the race is benign>``.
"""

from __future__ import annotations

import ast
from typing import Optional

from analysis.core import Finding, Repo, dotted_name, parent_chain, \
    stmt_span
from analysis.contexts import _body_walk

NAME = "cross-thread-state"


def _self_attr(expr) -> Optional[str]:
    """'attr' when expr is self.attr (or self.attr[...]), else None."""
    if isinstance(expr, ast.Subscript):
        expr = expr.value
    if isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Name) \
            and expr.value.id in ("self", "cls"):
        return expr.attr
    return None


def _reads_attr(expr, attr: str) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr == attr \
                and isinstance(node.value, ast.Name) \
                and node.value.id in ("self", "cls") \
                and isinstance(node.ctx, ast.Load):
            return True
    return False


def _lock_guarded(node) -> bool:
    """Is the site lexically inside `with <something lock-ish>:`?"""
    for p in parent_chain(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        if isinstance(p, ast.With):
            for item in p.items:
                name = dotted_name(item.context_expr).lower()
                if not name and isinstance(item.context_expr, ast.Call):
                    name = dotted_name(
                        item.context_expr.func).lower()
                if "lock" in name or "mutex" in name or "cond" in name:
                    return True
    return False


class _Site:
    __slots__ = ("fi", "node", "attr", "write", "rmw", "guarded")

    def __init__(self, fi, node, attr, write, rmw):
        self.fi = fi
        self.node = node
        self.attr = attr
        self.write = write
        self.rmw = rmw
        self.guarded = _lock_guarded(node)


def _collect_sites(ci, graph) -> dict[str, list[_Site]]:
    sites: dict[str, list[_Site]] = {}

    def add(fi, node, attr, write, rmw):
        sites.setdefault(attr, []).append(
            _Site(fi, node, attr, write, rmw))

    for fi in _class_funcs(ci, graph):
        for node in _body_walk(fi.node):
            if isinstance(node, ast.AugAssign):
                a = _self_attr(node.target)
                if a:
                    add(fi, node, a, True, True)
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    a = _self_attr(tgt)
                    if a:
                        add(fi, node, a, True,
                            _reads_attr(node.value, a))
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                a = _self_attr(node)
                if a:
                    add(fi, node, a, False, False)
    return sites


def _class_funcs(ci, graph):
    """The class's methods plus functions nested inside them (a worker
    closure defined in a method touches the same self)."""
    out = list(ci.methods.values())
    i = 0
    while i < len(out):
        out.extend(out[i].nested.values())
        i += 1
    return out


def run(repo: Repo) -> list[Finding]:
    graph = repo.contexts
    out: list[Finding] = []
    for ci in graph.classes:
        sites = _collect_sites(ci, graph)
        for attr, ss in sites.items():
            funcs = {s.fi for s in ss}
            if len(funcs) < 2:
                continue
            thread_writers = [
                s for s in ss if s.write
                and "thread" in s.fi.contexts
                and s.fi.name != "__init__"]
            any_guarded = any(s.guarded for s in ss)
            reported: set[int] = set()
            for s in ss:
                if not s.rmw or s.guarded \
                        or s.fi.name == "__init__":
                    continue
                racy = None
                if "thread" in s.fi.contexts:
                    racy = ("runs on executor threads "
                            f"({graph.chain_str(s.fi, 'thread')})")
                elif "loop" in s.fi.contexts and any(
                        w.fi is not s.fi for w in thread_writers):
                    w = next(w for w in thread_writers
                             if w.fi is not s.fi)
                    racy = (f"races the thread-context write in "
                            f"{w.fi.qualname} "
                            f"({graph.chain_str(w.fi, 'thread')})")
                if racy is None:
                    continue
                reported.add(id(s))
                lo, hi = stmt_span(s.node)
                out.append(Finding(
                    NAME, s.fi.mod.path, s.node.lineno,
                    f"{ci.name}.{attr}:rmw:{s.fi.qualname}",
                    f"unguarded read-modify-write of self.{attr} "
                    f"{racy}; also touched in "
                    f"{sorted(f.qualname for f in funcs if f is not s.fi)[0]}"
                    f" — lock both sites or annotate",
                    end_line=hi, stmt_line=lo))
            if any_guarded:
                # the half-locked rule covers RMW sites too: an
                # unguarded += in a method the context engine could
                # not classify still breaks the intent the lock
                # documents (only sites rule 1 already reported skip)
                for s in ss:
                    if not s.write or s.guarded \
                            or s.fi.name == "__init__" \
                            or id(s) in reported:
                        continue
                    lo, hi = stmt_span(s.node)
                    out.append(Finding(
                        NAME, s.fi.mod.path, s.node.lineno,
                        f"{ci.name}.{attr}:bypass:{s.fi.qualname}",
                        f"write to self.{attr} bypasses the lock that "
                        f"guards it elsewhere in {ci.name} — guard it "
                        f"or annotate why the bare store is safe",
                        end_line=hi, stmt_line=lo))
    return out
