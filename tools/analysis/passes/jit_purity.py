"""jit-purity: fused route programs must be pure under trace.

Every headline number rests on twin-oracle equivalence between the
fused device programs and the host reference. A trace-impure program
breaks that silently: the impurity runs ONCE at trace time, bakes a
stale value into the compiled program, and every later call replays
it — no exception, just wrong answers after the first recompile or a
different-looking divergence per jit-cache entry. In any function the
context engine classifies jit-reachable (the ``router_engine``
fused-program registry seeds, plus everything they call), this pass
flags:

- mutation of ``global``/``nonlocal`` state (the declaration +
  a store, or a subscript-store on a module-level name): trace-time
  side effects run once, not per call;
- wall-clock and RNG calls (``time.*``, ``random.*``,
  ``np.random.*``): traced to a constant;
- ``.item()`` / ``float()``-style host materialization is concretized
  at trace time (``.item()`` additionally forces a device sync);
- host callbacks (``io_callback`` / ``host_callback`` /
  ``pure_callback`` / ``jax.debug.callback``): legal but must be a
  deliberate, annotated decision in a serving-path program;
- ``print(...)`` executes at trace time only — a debugging landmine.

Deliberate exceptions (e.g. an op that is genuinely host-side too and
only conditionally traced) carry
``# analysis: ok(jit-purity) — <reason>``.
"""

from __future__ import annotations

import ast

from analysis.core import Finding, Repo, dotted_name, stmt_span
from analysis.contexts import _body_walk

NAME = "jit-purity"

_HOST_CALLBACKS = ("io_callback", "host_callback", "pure_callback",
                   "callback")
_TIME_FNS = ("time", "perf_counter", "monotonic", "process_time",
             "time_ns", "perf_counter_ns", "monotonic_ns", "sleep")


def _module_level_names(mod) -> set:
    out: set = set()
    if mod.tree is None:
        return out
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)) \
                and isinstance(node.target, ast.Name):
            out.add(node.target.id)
    return out


def _impurity(node, mod_globals: set) -> str:
    if isinstance(node, (ast.Global, ast.Nonlocal)):
        kind = "global" if isinstance(node, ast.Global) else "nonlocal"
        return (f"declares `{kind} {', '.join(node.names)}` — "
                f"mutating {kind} state under trace runs at trace "
                f"time only")
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            if isinstance(t, ast.Subscript) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id in mod_globals:
                return (f"subscript-store into module-level "
                        f"`{t.value.id}` — a trace-time side effect, "
                        f"runs once per compile, not per call")
    if not isinstance(node, ast.Call):
        return ""
    fn = node.func
    dot = dotted_name(fn)
    attr = fn.attr if isinstance(fn, ast.Attribute) else dot
    head = dot.split(".")[0] if dot else ""
    if head == "time" and attr in _TIME_FNS:
        return f"{dot} is traced to a constant (and sleep blocks)"
    if head in ("random", "secrets") or dot.startswith("np.random") \
            or dot.startswith("numpy.random"):
        return f"{dot} under trace bakes one sample into the program"
    if attr == "item":
        return (".item() forces a host sync and concretizes the "
                "traced value")
    if attr in _HOST_CALLBACKS and (head in ("jax", "hcb") or
                                    "callback" in dot):
        return (f"host callback {dot} in a fused program — must be a "
                f"deliberate, annotated decision")
    if dot == "print":
        return "print() under trace fires at trace time only"
    return ""


def run(repo: Repo) -> list[Finding]:
    graph = repo.contexts
    out: list[Finding] = []
    for fi in graph.functions:
        if "jit" not in fi.contexts:
            continue
        mod_globals = _module_level_names(fi.mod)
        for node in _body_walk(fi.node):
            why = _impurity(node, mod_globals)
            if not why:
                continue
            lo, hi = stmt_span(node)
            out.append(Finding(
                NAME, fi.mod.path, node.lineno,
                f"{fi.qualname}:{why[:40]}",
                f"{why}; jit-reachable via "
                f"{graph.chain_str(fi, 'jit')}",
                end_line=hi, stmt_line=lo))
    return out
