"""task-hygiene: fire-and-forget asyncio tasks + comment-less
exception swallows.

Migrated from tools/check_task_hygiene.py (ISSUE 6 satellite) onto the
shared framework; the script remains as a CLI-compatible shim. The
rules are unchanged:

1. **fire-and-forget** — a bare-expression ``asyncio.create_task`` /
   ``ensure_future`` discards its handle: the loop holds only a weak
   reference (GC can collect the task mid-flight) and exceptions
   surface, at best, as "Task exception was never retrieved" at
   collection time. Use ``supervise.spawn(...)`` or hold the handle +
   ``supervise.guard_task``.
2. **except-pass** — ``except Exception: pass`` (or bare ``except:``)
   with no comment. A best-effort swallow is sometimes right, but the
   author owes the reader ONE line saying why; any comment in the
   handler region (including ``# analysis: ok(...)``) satisfies it.
"""

from __future__ import annotations

import ast

from analysis.core import Finding, Repo, enclosing_qual

NAME = "task-hygiene"

_TASK_FNS = ("create_task", "ensure_future")


def _call_name(call: ast.Call) -> str:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _is_exception_catch(handler: ast.ExceptHandler) -> bool:
    """bare `except:` or `except Exception/BaseException [as e]:`."""
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in ("Exception", "BaseException")
    if isinstance(t, ast.Attribute):
        return t.attr in ("Exception", "BaseException")
    return False


def _has_comment(lines: list, lo: int, hi: int) -> bool:
    """Any comment text on source lines [lo, hi] (1-indexed)? A string
    scan is enough: the only '#' that can appear inside the code of an
    `except ...: pass` region is in a string literal, and a string
    literal in that region would itself be a (flagged) non-pass body."""
    for ln in lines[lo - 1:hi]:
        if "#" in ln:
            return True
    return False


def check_module(mod) -> list[Finding]:
    out: list[Finding] = []
    if mod.tree is None:
        return out
    for node in ast.walk(mod.tree):
        # 1: fire-and-forget task — the Call is the entire statement
        if isinstance(node, ast.Expr) \
                and isinstance(node.value, ast.Call) \
                and _call_name(node.value) in _TASK_FNS:
            out.append(Finding(
                NAME, mod.path, node.lineno,
                f"fire-and-forget:{_call_name(node.value)}"
                f":{enclosing_qual(node)}",
                f"{_call_name(node.value)}(...) result discarded — "
                f"use supervise.spawn(...) or hold the handle + "
                f"supervise.guard_task",
                end_line=getattr(node, "end_lineno", node.lineno)))
        # 2: comment-less `except Exception: pass`
        if isinstance(node, ast.ExceptHandler) \
                and _is_exception_catch(node) \
                and len(node.body) == 1 \
                and isinstance(node.body[0], ast.Pass):
            hi = node.body[0].lineno
            if not _has_comment(mod.lines, node.lineno, hi):
                out.append(Finding(
                    NAME, mod.path, node.lineno,
                    f"except-pass:{enclosing_qual(node)}",
                    "except Exception: pass with no explaining "
                    "comment — say why the swallow is safe (or stop "
                    "swallowing)",
                    end_line=hi))
    return out


def run(repo: Repo) -> list[Finding]:
    out: list[Finding] = []
    for mod in repo.modules.values():
        out.extend(check_module(mod))
    return out
