"""Shared analyzer infrastructure: module loading, the annotation
grammar, findings with stable IDs, and the pass runner.

Everything here is pure and filesystem-optional: `Repo.from_sources`
builds a whole analyzable "repository" out of in-memory strings, which
is how the seeded-violation corpus in tests/test_analysis.py proves
each pass catches its defect class without touching the real tree.
"""

from __future__ import annotations

import ast
import hashlib
import os
import re
from typing import Callable, Iterable, Optional

# ---- the annotation grammar ---------------------------------------------
#
#   # analysis: ok(<pass>[, <pass>...]) — <reason>
#
# suppresses findings of the named pass(es) on the annotated statement
# (the annotation may sit on any line of the statement, or on the line
# directly above it). The reason is MANDATORY — a reason-less ok() is
# indistinguishable from a drive-by silence and is itself reported as a
# malformed annotation. Separator: em-dash, en-dash, "--" or "-".
_ANNOT_RE = re.compile(
    r"#\s*analysis:\s*ok\(\s*([^)]*?)\s*\)\s*(?:(?:—|–|--|-)\s*(\S.*))?")
# anything that LOOKS like it wants to be an analysis annotation — used
# to flag malformed variants that would otherwise silently not suppress
_ANNOT_INTENT_RE = re.compile(r"#\s*analysis\s*:")

PASS_NAMES = (
    "loop-affinity",
    "cross-thread-state",
    "jit-purity",
    "knob-discipline",
    "task-hygiene",
    "hbm-hygiene",
)


class Finding:
    """One analyzer finding. The ID is stable across line drift: it
    hashes (path, pass, anchor) where `anchor` names the defect site
    structurally (qualname + symbol), never by line number."""

    __slots__ = ("pass_name", "path", "line", "end_line", "stmt_line",
                 "anchor", "detail")

    def __init__(self, pass_name: str, path: str, line: int,
                 anchor: str, detail: str,
                 end_line: Optional[int] = None,
                 stmt_line: Optional[int] = None):
        self.pass_name = pass_name
        self.path = path
        self.line = line
        self.end_line = end_line if end_line is not None else line
        # first line of the enclosing statement: the annotation window
        # starts one line above THIS, so a multi-line statement can be
        # annotated at its head even when the finding is mid-statement
        self.stmt_line = stmt_line if stmt_line is not None else line
        self.anchor = anchor
        self.detail = detail

    @property
    def fid(self) -> str:
        h = hashlib.sha1(
            f"{self.path}|{self.pass_name}|{self.anchor}".encode()
        ).hexdigest()[:8]
        return f"{self.pass_name.upper().replace('-', '_')[:4]}-{h}"

    def __repr__(self):
        return (f"{self.path}:{self.line}: [{self.pass_name}] "
                f"{self.fid} {self.detail}")


class Module:
    """One parsed source file: AST with parent links + the parsed
    `# analysis:` annotations per line."""

    def __init__(self, path: str, src: str):
        self.path = path
        self.src = src
        self.lines = src.splitlines()
        self.error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.AST] = ast.parse(src)
        except SyntaxError as e:
            self.tree = None
            self.error = e
        if self.tree is not None:
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    child._an_parent = node
        # lineno -> (set of pass names, reason); malformed annotations
        # land in self.bad_annotations instead
        self.annotations: dict[int, tuple[set, str]] = {}
        self.bad_annotations: list[tuple[int, str]] = []
        for i, ln in enumerate(self.lines, start=1):
            if not _ANNOT_INTENT_RE.search(ln):
                continue
            m = _ANNOT_RE.search(ln)
            if m is None:
                self.bad_annotations.append(
                    (i, "does not parse as `# analysis: ok(<pass>) — "
                        "<reason>`"))
                continue
            passes = {p.strip() for p in m.group(1).split(",")
                      if p.strip()}
            reason = (m.group(2) or "").strip()
            unknown = passes - set(PASS_NAMES)
            if not passes:
                self.bad_annotations.append((i, "names no pass"))
            elif unknown:
                self.bad_annotations.append(
                    (i, f"names unknown pass(es) {sorted(unknown)} — "
                        f"known: {', '.join(PASS_NAMES)}"))
            elif not reason:
                self.bad_annotations.append(
                    (i, "carries no reason — say WHY the finding is ok "
                        "(`# analysis: ok(<pass>) — <reason>`)"))
            else:
                self.annotations[i] = (passes, reason)

    @property
    def modname(self) -> str:
        name = self.path[:-3] if self.path.endswith(".py") else self.path
        name = name.replace(os.sep, ".").replace("/", ".")
        if name.endswith(".__init__"):
            name = name[: -len(".__init__")]
        return name

    def ok_for(self, pass_name: str, lo: int, hi: int) -> bool:
        """Is a finding of `pass_name` on statement lines [lo, hi]
        suppressed by an annotation on those lines, or anywhere in the
        contiguous comment block directly above the statement? (The
        block rule lets a multi-line justification start with the
        ``# analysis: ok(...)`` marker and keep explaining below it.)"""
        for i in range(lo, hi + 1):
            ann = self.annotations.get(i)
            if ann is not None and pass_name in ann[0]:
                return True
        i = lo - 1
        while i >= 1 and self.lines[i - 1].lstrip().startswith("#"):
            ann = self.annotations.get(i)
            if ann is not None and pass_name in ann[0]:
                return True
            i -= 1
        return False


def stmt_span(node: ast.AST) -> tuple[int, int]:
    """(first, last) source line of the statement containing `node`."""
    cur = node
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = getattr(cur, "_an_parent", None)
    cur = cur if cur is not None else node
    lo = getattr(cur, "lineno", 0)
    return lo, getattr(cur, "end_lineno", lo)


def parent_chain(node: ast.AST) -> Iterable[ast.AST]:
    cur = getattr(node, "_an_parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "_an_parent", None)


def enclosing_qual(node: ast.AST) -> str:
    """'Class.method.nested' of the nearest enclosing defs — a stable,
    line-free anchor for findings."""
    names: list[str] = []
    for p in parent_chain(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            names.append(p.name)
    return ".".join(reversed(names)) or "<module>"


def dotted_name(expr: ast.AST) -> str:
    """'a.b.c' for nested Attribute/Name chains, '' when not one."""
    parts: list[str] = []
    cur = expr
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    elif parts:
        parts.append("?")
    else:
        return ""
    return ".".join(reversed(parts))


class Repo:
    """The analyzable universe: the package's parsed modules plus the
    raw text of docs/ and tests/ (the knob-discipline pass checks both
    directions of doc/test drift) and any extra code roots that may
    legitimately consume documented knobs (tools/, bench.py)."""

    def __init__(self, modules: dict[str, Module],
                 docs: Optional[dict[str, str]] = None,
                 tests: Optional[dict[str, str]] = None,
                 extra_code: Optional[dict[str, str]] = None):
        self.modules = modules
        self.docs = docs or {}
        self.tests = tests or {}
        self.extra_code = extra_code or {}
        self._contexts = None

    # ---- construction ----------------------------------------------------
    @staticmethod
    def _walk_py(root: str, rel_prefix: str) -> dict[str, str]:
        out: dict[str, str] = {}
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames if d != "__pycache__")
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.join(
                    rel_prefix, os.path.relpath(path, root))
                with open(path, encoding="utf-8") as f:
                    out[rel.replace(os.sep, "/")] = f.read()
        return out

    @classmethod
    def from_fs(cls, repo_root: str,
                package: str = "emqx_tpu") -> "Repo":
        pkg_root = os.path.join(repo_root, package)
        modules = {p: Module(p, s)
                   for p, s in cls._walk_py(pkg_root, package).items()}
        docs: dict[str, str] = {}
        docs_root = os.path.join(repo_root, "docs")
        if os.path.isdir(docs_root):
            for fn in sorted(os.listdir(docs_root)):
                if fn.endswith(".md"):
                    with open(os.path.join(docs_root, fn),
                              encoding="utf-8") as f:
                        docs[f"docs/{fn}"] = f.read()
        tests: dict[str, str] = {}
        tests_root = os.path.join(repo_root, "tests")
        if os.path.isdir(tests_root):
            tests = cls._walk_py(tests_root, "tests")
        extra: dict[str, str] = {}
        tools_root = os.path.join(repo_root, "tools")
        if os.path.isdir(tools_root):
            extra = cls._walk_py(tools_root, "tools")
        bench = os.path.join(repo_root, "bench.py")
        if os.path.exists(bench):
            with open(bench, encoding="utf-8") as f:
                extra["bench.py"] = f.read()
        return cls(modules, docs=docs, tests=tests, extra_code=extra)

    @classmethod
    def from_sources(cls, files: dict[str, str],
                     docs: Optional[dict[str, str]] = None,
                     tests: Optional[dict[str, str]] = None,
                     extra_code: Optional[dict[str, str]] = None
                     ) -> "Repo":
        return cls({p: Module(p, s) for p, s in files.items()},
                   docs=docs, tests=tests, extra_code=extra_code)

    # ---- the context engine (lazy, shared by the passes) -----------------
    @property
    def contexts(self):
        if self._contexts is None:
            from analysis.contexts import ContextGraph
            self._contexts = ContextGraph(self)
        return self._contexts


def _load_passes() -> dict[str, Callable]:
    from analysis.passes import cross_thread, hbm_hygiene, jit_purity, \
        knob_discipline, loop_affinity, task_hygiene
    return {
        "loop-affinity": loop_affinity.run,
        "cross-thread-state": cross_thread.run,
        "jit-purity": jit_purity.run,
        "knob-discipline": knob_discipline.run,
        "task-hygiene": task_hygiene.run,
        "hbm-hygiene": hbm_hygiene.run,
    }


def ALL_PASSES() -> dict[str, Callable]:
    return _load_passes()


def _annotation_findings(repo: Repo) -> list[Finding]:
    """Malformed `# analysis:` comments are findings in their own
    right: a typo'd suppression silently fails to suppress, which is
    exactly the silent-drift class this framework exists to kill.
    Never suppressible."""
    out: list[Finding] = []
    for mod in repo.modules.values():
        for line, why in mod.bad_annotations:
            out.append(Finding(
                "annotation", mod.path, line,
                f"line{line}:{mod.lines[line - 1].strip()[:60]}",
                f"malformed analysis annotation: {why}"))
        if mod.error is not None:
            out.append(Finding(
                "annotation", mod.path, mod.error.lineno or 0,
                "syntax", f"module does not parse: {mod.error}"))
    return out


def run_repo(repo: Repo, passes: Optional[Iterable[str]] = None,
             only: Optional[Iterable[str]] = None
             ) -> tuple[list[Finding], list[Finding]]:
    """Run the framework. Returns (findings, suppressed): `findings`
    is what the caller should fail on, `suppressed` the annotated-ok
    sites (reported for transparency, never fatal). `only` filters the
    REPORT to a path subset — analysis always sees the whole repo, so
    cross-file passes (contexts, knob discipline) stay sound on the
    changed-files fast path."""
    table = _load_passes()
    names = list(passes) if passes else list(table)
    for n in names:
        if n not in table:
            raise KeyError(
                f"unknown pass {n!r} — known: {', '.join(table)}")
    raw: list[Finding] = _annotation_findings(repo)
    for n in names:
        raw.extend(table[n](repo))
    findings: list[Finding] = []
    suppressed: list[Finding] = []
    for f in raw:
        mod = repo.modules.get(f.path)
        if mod is not None and f.pass_name != "annotation" \
                and mod.ok_for(f.pass_name,
                               min(f.stmt_line, f.line), f.end_line):
            suppressed.append(f)
        else:
            findings.append(f)
    if only is not None:
        onlyset = {p.replace(os.sep, "/") for p in only}
        findings = [f for f in findings if f.path in onlyset]
        suppressed = [f for f in suppressed if f.path in onlyset]
    key = lambda f: (f.path, f.line, f.pass_name)  # noqa: E731
    return sorted(findings, key=key), sorted(suppressed, key=key)
