"""CLI: run the pipeline contract analyzer.

    PYTHONPATH=tools python -m analysis [options] [PATH...]

PATH arguments (repo-relative file paths) restrict the REPORT — the
analysis itself always loads the whole package so cross-file passes
(context propagation, knob discipline) stay sound on the changed-files
fast path (`make analyze-changed`).

Exit codes: 0 clean, 1 findings, 2 usage/internal error — the same
contract the two legacy checker scripts had.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from analysis.core import PASS_NAMES, Repo, run_repo


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="analysis",
        description="pipeline contract analyzer (docs/ANALYSIS.md)")
    ap.add_argument("paths", nargs="*",
                    help="restrict the report to these repo-relative "
                         "files (analysis still sees the whole repo)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto from this file)")
    ap.add_argument("--pass", dest="passes", action="append",
                    metavar="NAME",
                    help=f"run only this pass (repeatable); known: "
                         f"{', '.join(PASS_NAMES)}")
    ap.add_argument("--list", action="store_true",
                    help="list passes and exit")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print annotated-ok findings")
    args = ap.parse_args(argv)

    if args.list:
        for n in PASS_NAMES:
            print(n)
        return 0

    root = args.root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    if not os.path.isdir(os.path.join(root, "emqx_tpu")):
        print(f"analysis: no emqx_tpu/ package under {root!r}",
              file=sys.stderr)
        return 2

    only = None
    if args.paths:
        only = []
        for p in args.paths:
            rel = os.path.relpath(os.path.abspath(p), root) \
                if os.path.isabs(p) or os.path.exists(p) else p
            only.append(rel.replace(os.sep, "/"))

    try:
        repo = Repo.from_fs(root)
        findings, suppressed = run_repo(repo, passes=args.passes,
                                        only=only)
    except KeyError as e:
        print(f"analysis: {e.args[0]}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps({
            "findings": [
                {"id": f.fid, "pass": f.pass_name, "path": f.path,
                 "line": f.line, "detail": f.detail}
                for f in findings],
            "suppressed": len(suppressed),
        }, indent=2))
    else:
        for f in findings:
            print(repr(f))
        if args.show_suppressed:
            for f in suppressed:
                print(f"suppressed: {f!r}")
        print(f"{len(findings)} finding(s), {len(suppressed)} "
              f"suppressed by annotation, over "
              f"{len(repo.modules)} modules")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
