"""Call-graph + execution-context engine.

Classifies every function in the analyzed package by the context(s) it
can execute in:

- ``loop``   — the asyncio event loop. Seeds: every ``async def``, and
  callbacks handed to ``call_soon``/``call_later``/``call_at``/
  ``call_soon_threadsafe``/``add_done_callback`` (all run on the loop).
- ``thread`` — an executor / raw thread. Seeds: ``Thread(target=f)``,
  ``loop.run_in_executor(pool, f, ...)``, ``<executor>.submit(f, ...)``
  (the batcher's dispatch thread and readback pool, the engine warm
  threads, the mesh rebuild thread).
- ``jit``    — traced inside a fused route program. Seeds: functions
  decorated with ``jax.jit``/``pjit`` (the ``router_engine`` fused-
  program registry binds exactly these), plus callables passed to
  ``jit``/``pjit``/``vmap``/``pmap``/``shard_map`` call-forms.

Contexts PROPAGATE along resolved call edges to a fixpoint: a sync
helper called from a coroutine is loop-context; a helper called from
``dispatch`` (which runs on the dispatch thread) is thread-context; an
op called from a jitted program is jit-context. A function can hold
several contexts at once — ``FlightRecorder.record`` is deliberately
loop+thread, which is precisely why the cross-thread-state pass exists.

Call resolution is name-based and deliberately over-approximate in one
bounded way: an attribute call ``obj.m(...)`` whose receiver cannot be
typed resolves to every method named ``m`` in the package, but only
when ``m`` is distinctive (defined by at most ``DUCK_MAX`` classes and
not on the common-name stoplist). Thread/loop seed extraction from
``run_in_executor``/``Thread(target=...)``/``submit`` has no such cap —
those hand-offs are explicit.

Each propagated context keeps its predecessor, so a finding can print
WHY the analyzer believes a function is loop- or thread-reachable
(``chain_str``) instead of asserting it bare.
"""

from __future__ import annotations

import ast
from collections import deque
from typing import Optional

from analysis.core import Module, Repo, dotted_name

# attribute calls on untyped receivers resolve by method name only when
# the name is defined by at most this many classes...
DUCK_MAX = 6
# ...and is not one of these (generic container/protocol names resolve
# to half the package and would smear contexts everywhere)
DUCK_STOP = frozenset((
    "get", "put", "set", "add", "pop", "close", "open", "run", "send",
    "write", "read", "append", "appendleft", "clear", "items", "keys",
    "values", "update", "start", "stop", "wait", "cancel", "done",
    "result", "copy", "encode", "decode", "inc", "observe", "join",
    "flush", "reset", "next", "state", "snapshot", "section", "match",
    "feed", "drain", "release", "acquire", "count", "name",
    "send_packet", "lookup", "register", "info", "error", "warning",
    "debug", "exception", "remove", "discard", "insert", "extend",
    # NOT stoplisted though they look generic: "submit" (the delivery
    # lane pool's loop-side entry — verify drives proved stoplisting
    # it blinds loop-affinity to the whole lane submit path) and
    # "record" (the flight recorder's loop+thread hot path — the very
    # PR-7 surface the cross-thread pass exists for)
))

LOOP_CB_METHODS = frozenset((
    "call_soon", "call_soon_threadsafe", "call_later", "call_at",
    "add_done_callback",
))
JIT_WRAPPERS = frozenset(("jit", "pjit", "vmap", "pmap", "shard_map"))


class FuncInfo:
    __slots__ = ("mod", "node", "name", "qualname", "cls", "is_async",
                 "parent", "contexts", "pred", "edges", "nested")

    def __init__(self, mod: Module, node, qualname: str,
                 cls: Optional[str], parent: Optional["FuncInfo"]):
        self.mod = mod
        self.node = node
        self.name = node.name
        self.qualname = qualname
        self.cls = cls
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        self.parent = parent
        self.contexts: set[str] = set()
        # ctx -> (reason, predecessor FuncInfo | None)
        self.pred: dict[str, tuple[str, Optional["FuncInfo"]]] = {}
        self.edges: list["FuncInfo"] = []
        self.nested: dict[str, "FuncInfo"] = {}

    def __repr__(self):
        return f"<fn {self.mod.path}::{self.qualname}>"


class ClassInfo:
    __slots__ = ("mod", "node", "name", "bases", "methods")

    def __init__(self, mod: Module, node: ast.ClassDef):
        self.mod = mod
        self.node = node
        self.name = node.name
        self.bases = [dotted_name(b) for b in node.bases]
        self.methods: dict[str, FuncInfo] = {}


def _body_walk(fn_node):
    """Walk a function body WITHOUT descending into nested function /
    class definitions (their calls run in their own context, and they
    are their own FuncInfo nodes) — lambdas stay in, they execute
    inline for our purposes."""
    stack = list(fn_node.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _decorated_jit(node) -> bool:
    for dec in node.decorator_list:
        for sub in ast.walk(dec):
            if isinstance(sub, ast.Name) and sub.id in ("jit", "pjit"):
                return True
            if isinstance(sub, ast.Attribute) \
                    and sub.attr in ("jit", "pjit"):
                return True
    return False


class ContextGraph:
    def __init__(self, repo: Repo):
        self.repo = repo
        self.functions: list[FuncInfo] = []
        self.by_module: dict[str, list[FuncInfo]] = {}
        self.classes: list[ClassInfo] = []
        self._methods_by_name: dict[str, list[FuncInfo]] = {}
        self._mod_funcs: dict[str, dict[str, FuncInfo]] = {}
        self._mod_classes: dict[str, dict[str, ClassInfo]] = {}
        self._imports: dict[str, dict[str, str]] = {}
        self._from_imports: dict[str, dict[str, tuple[str, str]]] = {}
        self._by_node: dict[int, FuncInfo] = {}
        self._modname_to_path: dict[str, str] = {}
        for mod in repo.modules.values():
            self._modname_to_path[mod.modname] = mod.path
        for mod in repo.modules.values():
            if mod.tree is not None:
                self._collect_module(mod)
        self._resolve_edges_and_seeds()
        self._propagate()

    # ---- collection ------------------------------------------------------
    def _collect_module(self, mod: Module) -> None:
        self.by_module[mod.path] = []
        self._mod_funcs[mod.path] = {}
        self._mod_classes[mod.path] = {}
        imports: dict[str, str] = {}
        from_imports: dict[str, tuple[str, str]] = {}
        self._imports[mod.path] = imports
        self._from_imports[mod.path] = from_imports
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    from_imports[a.asname or a.name] = \
                        (node.module, a.name)

        def visit(body, cls: Optional[ClassInfo],
                  parent: Optional[FuncInfo], prefix: str):
            for node in body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    qual = f"{prefix}{node.name}"
                    fi = FuncInfo(mod, node, qual,
                                  cls.name if cls else None, parent)
                    self.functions.append(fi)
                    self.by_module[mod.path].append(fi)
                    self._by_node[id(node)] = fi
                    if parent is not None:
                        parent.nested[node.name] = fi
                    elif cls is not None:
                        cls.methods[node.name] = fi
                        self._methods_by_name.setdefault(
                            node.name, []).append(fi)
                    else:
                        self._mod_funcs[mod.path][node.name] = fi
                    visit(node.body, cls if parent is None else cls,
                          fi, qual + ".")
                elif isinstance(node, ast.ClassDef):
                    ci = ClassInfo(mod, node)
                    self.classes.append(ci)
                    self._mod_classes[mod.path][node.name] = ci
                    visit(node.body, ci, None, f"{node.name}.")
                else:
                    # functions defined under `if TYPE_CHECKING:` etc.
                    for child in ast.iter_child_nodes(node):
                        if isinstance(child, (ast.FunctionDef,
                                              ast.AsyncFunctionDef,
                                              ast.ClassDef)):
                            visit([child], cls, parent, prefix)

        visit(mod.tree.body, None, None, "")

    # ---- resolution ------------------------------------------------------
    def _module_for(self, path: str, alias: str) -> Optional[str]:
        """Map a name used in `path` to an analyzed module path, via
        `import x.y as alias` / `from pkg import mod [as alias]`."""
        full = self._imports.get(path, {}).get(alias)
        if full is not None:
            return self._modname_to_path.get(full)
        fi = self._from_imports.get(path, {}).get(alias)
        if fi is not None:
            return self._modname_to_path.get(f"{fi[0]}.{fi[1]}")
        return None

    def resolve(self, expr, fi: FuncInfo) -> list[FuncInfo]:
        """Resolve a callable expression to candidate FuncInfos."""
        if isinstance(expr, ast.Name):
            n = expr.id
            cur = fi
            while cur is not None:
                if n in cur.nested:
                    return [cur.nested[n]]
                cur = cur.parent
            mf = self._mod_funcs.get(fi.mod.path, {}).get(n)
            if mf is not None:
                return [mf]
            imp = self._from_imports.get(fi.mod.path, {}).get(n)
            if imp is not None:
                src_path = self._modname_to_path.get(imp[0])
                if src_path is not None:
                    tgt = self._mod_funcs.get(src_path, {}) \
                        .get(imp[1])
                    if tgt is not None:
                        return [tgt]
            return []
        if isinstance(expr, ast.Attribute):
            m = expr.attr
            recv = expr.value
            if isinstance(recv, ast.Name) and recv.id in ("self", "cls") \
                    and fi.cls is not None:
                ci = self._mod_classes.get(fi.mod.path, {}).get(fi.cls)
                seen: set[str] = set()
                while ci is not None:
                    if m in ci.methods:
                        return [ci.methods[m]]
                    seen.add(ci.name)
                    nxt = None
                    for b in ci.bases:
                        base = b.split(".")[-1]
                        if base in seen:
                            continue
                        cand = self._mod_classes.get(
                            ci.mod.path, {}).get(base)
                        if cand is None:
                            src = self._module_for(ci.mod.path,
                                                   b.split(".")[0])
                            if src is not None:
                                cand = self._mod_classes.get(
                                    src, {}).get(base)
                        if cand is not None:
                            nxt = cand
                            break
                    ci = nxt
                return []
            if isinstance(recv, ast.Name):
                src = self._module_for(fi.mod.path, recv.id)
                if src is not None:
                    tgt = self._mod_funcs.get(src, {}).get(m)
                    return [tgt] if tgt is not None else []
                if recv.id in self._imports.get(fi.mod.path, {}):
                    # `import x as y; y.m(...)` where x is NOT a repo
                    # module: the receiver is an external module, so
                    # duck-matching repo methods named m (jnp.all ->
                    # Banned.all) would fabricate edges
                    return []
            cands = self._methods_by_name.get(m, [])
            if cands and len(cands) <= DUCK_MAX and m not in DUCK_STOP:
                return list(cands)
            return []
        return []

    # ---- seeds + edges ---------------------------------------------------
    def _resolve_edges_and_seeds(self) -> None:
        self._seeds: list[tuple[FuncInfo, str, str]] = []
        for fi in self.functions:
            if fi.is_async:
                self._seeds.append((fi, "loop", "async def"))
            if _decorated_jit(fi.node):
                self._seeds.append((fi, "jit", "jit-decorated"))
            for node in _body_walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                where = f"{fi.mod.path}:{node.lineno}"
                fn = node.func
                fdot = dotted_name(fn)
                fattr = fn.attr if isinstance(fn, ast.Attribute) \
                    else fdot
                # thread entries
                if fattr in ("Thread", "Timer"):
                    for kw in node.keywords:
                        if kw.arg == "target":
                            self._seed_arg(kw.value, fi, "thread",
                                           f"Thread target at {where}")
                elif fattr == "run_in_executor" and len(node.args) >= 2:
                    self._seed_arg(node.args[1], fi, "thread",
                                   f"run_in_executor at {where}")
                elif fattr == "submit" and node.args:
                    # seeds only when the arg resolves to a function —
                    # `pool.submit(plan_obj)` (the delivery lanes' own
                    # submit) resolves to nothing and seeds nothing
                    self._seed_arg(node.args[0], fi, "thread",
                                   f"executor submit at {where}")
                elif fattr in LOOP_CB_METHODS and node.args:
                    cb = node.args[1] if fattr in ("call_later",
                                                   "call_at") \
                        and len(node.args) >= 2 else node.args[0]
                    self._seed_arg(cb, fi, "loop",
                                   f"loop callback at {where}")
                elif (fattr in JIT_WRAPPERS
                      or (isinstance(fn, ast.Name)
                          and fn.id in JIT_WRAPPERS)) and node.args:
                    self._seed_arg(node.args[0], fi, "jit",
                                   f"traced via {fattr} at {where}")
                # ordinary call edge
                fi.edges.extend(self.resolve(fn, fi))

    def _seed_arg(self, expr, fi: FuncInfo, ctx: str,
                  why: str) -> None:
        for t in self.resolve(expr, fi):
            self._seeds.append((t, ctx, why))

    # ---- propagation -----------------------------------------------------
    def _propagate(self) -> None:
        for ctx in ("loop", "thread", "jit"):
            q: deque[FuncInfo] = deque()
            for fi, c, why in self._seeds:
                if c != ctx or ctx in fi.contexts:
                    continue
                fi.contexts.add(ctx)
                fi.pred[ctx] = (why, None)
                q.append(fi)
            while q:
                fi = q.popleft()
                for tgt in fi.edges:
                    if ctx in tgt.contexts:
                        continue
                    # a thread (or a trace) cannot transparently enter
                    # a coroutine — crossing back onto the loop takes
                    # an explicit hand-off, which is its own seed
                    if ctx in ("thread", "jit") and tgt.is_async:
                        continue
                    tgt.contexts.add(ctx)
                    tgt.pred[ctx] = (f"called from {fi.qualname}", fi)
                    q.append(tgt)

    # ---- reporting helpers ----------------------------------------------
    def func_for_node(self, node) -> Optional[FuncInfo]:
        from analysis.core import parent_chain
        if id(node) in self._by_node:
            return self._by_node[id(node)]
        for p in parent_chain(node):
            if id(p) in self._by_node:
                return self._by_node[id(p)]
        return None

    def chain_str(self, fi: FuncInfo, ctx: str, cap: int = 6) -> str:
        """'f <- g <- seed(reason)': why fi holds ctx."""
        hops: list[str] = []
        cur: Optional[FuncInfo] = fi
        why = ""
        while cur is not None and len(hops) < cap:
            hops.append(cur.qualname)
            why, cur = cur.pred.get(ctx, ("", None))
        return " <- ".join(hops) + (f" [{why}]" if why else "")
