"""Pipeline contract analyzer (ISSUE 12): one static-analysis framework
for the correctness contracts eleven PRs of review kept re-finding by
hand.

The repo's concurrency model is layered — event-loop coroutines, one
ordered dispatch thread, a two-worker readback pool, warm/rebuild
threads, delivery-lane tasks, jit-traced fused route programs — and
each layer has contracts that a silent violation turns into a race, a
wedge, or a twin-oracle divergence:

- loop code must not block (``loop-affinity``);
- state shared across threads must be lock-guarded where it is
  read-modify-written (``cross-thread-state`` — the PR 7 ring-counter
  race, machine-checked);
- fused route programs must stay trace-pure (``jit-purity``);
- every ``EMQX_TPU_*`` env knob must route through a
  config-beats-env-beats-default ``resolve_*`` function, be documented,
  and have a test reference (``knob-discipline``);
- asyncio tasks must not be fire-and-forgotten and exception swallows
  must explain themselves (``task-hygiene``, migrated from
  tools/check_task_hygiene.py);
- persistent ``device_put`` allocations must ride the HBM ledger
  (``hbm-hygiene``, migrated from tools/check_hbm_hygiene.py).

Shared infrastructure: an AST module loader over ``emqx_tpu/``
(:mod:`analysis.core`), a call-graph/context engine classifying every
function as loop / thread / jit reachable (:mod:`analysis.contexts`),
the ``# analysis: ok(<pass>) — <reason>`` annotation grammar, and a
findings report with stable IDs. Run ``python -m analysis --help``
(with ``tools/`` on ``PYTHONPATH``) or ``make analyze``; the whole
framework is also wired as tier-1 tests (tests/test_analysis.py).

Docs: docs/ANALYSIS.md (pass catalog, the thread-affinity model, the
annotation grammar, how to add a pass).
"""

from analysis.core import (  # noqa: F401
    Finding,
    Module,
    Repo,
    ALL_PASSES,
    run_repo,
)
