#!/usr/bin/env python
"""Sharded (multichip) serving bench at realistic scale — VERDICT r4 #6.

Boots a node in multichip SERVING mode over an 8-device virtual CPU mesh
(dp×route — correctness/scale proof; the chip bench measures raw speed)
and drives it through:

  1. full build of a >=100k-filter table (per-shard compile + stack +
     mesh placement), timed;
  2. a route_batch flood through the mesh step, with a host-router
     oracle spot-check on every batch's counts;
  3. churn WHILE serving: subscribe/unsubscribe bursts between batches —
     each burst dirties shards, the per-shard update path
     (parallel.sharded.update_shard) applies synchronously-before-serve;
  4. a shard OUTGROWING its capacity class mid-flood: a fan-out burst
     onto one filter blows the 'subs' class, kicking the background
     full rebuild; serving continues (host-side) during the rebuild and
     returns to the mesh after the swap — delivery counts stay correct
     throughout.

Prints ONE JSON line. Run standalone (CPU env is forced) or via
bench.py, which spawns it with the CPU-bypass env so it can never touch
the axon pool. Reference analog: route replication + dispatch at scale,
emqx_router.erl:77-86, emqx_broker.erl:199-308.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# force the virtual CPU mesh BEFORE jax loads (same dance as
# __graft_entry__.dryrun_multichip — the axon backend must not init)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
N_DEV = int(os.environ.get("BENCH_SHARDED_DEVICES", 8))
flag = "--xla_force_host_platform_device_count"
if flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = \
        f"{os.environ.get('XLA_FLAGS', '')} {flag}={N_DEV}".strip()


def log(*a):
    print(*a, file=sys.stderr, flush=True)


class Cap:
    __slots__ = ("n",)

    def __init__(self):
        self.n = 0

    def deliver(self, tf, msg):
        self.n += 1
        return True


class _Flat:
    """Flatten a future-of-future (dispatch stage returning the
    materialize future) into one result() — the flood's settle point."""

    __slots__ = ("fut",)

    def __init__(self, fut):
        self.fut = fut

    def result(self):
        return self.fut.result().result()


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    t_start = time.time()
    n_filters = int(os.environ.get("BENCH_SHARDED_FILTERS", 100_000))
    B = int(os.environ.get("BENCH_SHARDED_BATCH", 128))

    from emqx_tpu.broker.message import make
    from emqx_tpu.broker.node import Node

    node = Node({"broker": {"multichip": {
        "enable": True, "devices": N_DEV, "dp": 2,
        "max_batch": B}}})
    broker = node.broker
    eng = node.device_engine
    out = {"devices": N_DEV, "mesh": {"dp": eng.n_dp,
                                      "route": eng.n_route},
           "filters": n_filters, "batch": B}

    # ---- 1. population + full build ---------------------------------
    ids = max(8, int(n_filters ** 0.5))
    nums = max(1, n_filters // ids)
    caps = []
    t0 = time.time()
    for i in range(ids):
        for n in range(nums):
            c = Cap()
            caps.append(c)
            broker.subscribe(broker.register(c, f"s{i}-{n}"),
                             f"dev/d{i}/+/n{n}/#")
    out["subscribe_s"] = round(time.time() - t0, 2)
    t0 = time.time()
    eng.rebuild()
    out["build_s"] = round(time.time() - t0, 2)
    st = eng.stats()
    out["built_filters"] = st["filters"]
    out["caps"] = st["caps"]
    log(f"built {st['filters']} filters over {eng.n_route} shards "
        f"in {out['build_s']}s (caps {st['caps']})")

    # ---- 2. flood with oracle spot-checks ----------------------------
    # ISSUE 9: the flood runs the PIPELINED dispatch loop the serving
    # path now uses — dispatch runs on its own thread and materialize
    # on another (the batcher's dispatch-pool/read-pool split), with up
    # to EMQX_TPU_DISPATCH_DEPTH windows in flight; settle order stays
    # FIFO and every batch's counts are still oracle-checked.
    # EMQX_TPU_DISPATCH_DEPTH=1 restores the synchronous
    # prepare→dispatch→materialize→finish round-trip exactly.
    import numpy as np
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    from emqx_tpu.broker.batcher import resolve_dispatch_depth
    depth = resolve_dispatch_depth(None)
    n_batches = int(os.environ.get("BENCH_SHARDED_BATCHES", 40))
    # mesh warm/ready before the timed window (route_batch wait=True
    # used to do this implicitly on the first flood batch)
    eng.route_batch([make("p", 0, "dev/d0/x/n0/t", b"x")] * B,
                    wait=True)
    # exchange stage (ISSUE 15): warm the segment-capacity class BEFORE
    # any timed window, letting the EWMA ladder adapt (a cold or
    # undersized class gathers — that would be the OLD path wearing the
    # new name). Adaptation routes use FLOOD-SHAPED traffic: a
    # degenerate warm batch (one hot topic) would teach the EWMA an
    # everything-to-one-dest peak and oversize the landed plans.
    metrics = node.metrics
    if eng.device_exchange:
        wrng = np.random.RandomState(7)
        for _ in range(5):
            before = metrics.val("pipeline.exchange.windows")
            eng.warm_exchange(B)
            wm = [make("p", 0, f"dev/d{i}/x/n{n}/t", b"x")
                  for i, n in zip(wrng.randint(0, ids, B),
                                  wrng.randint(0, nums, B))]
            eng.route_batch(wm, wait=True)
            if metrics.val("pipeline.exchange.windows") > before:
                break
        log(f"exchange warm: classes {sorted(eng._exch_warm)} "
            f"ewma {eng._exch_ewma}")

    def run_flood(n_batches, seed=11):
        """Pipelined oracle-checked flood; returns (msgs, wall_s)."""
        rng = np.random.RandomState(seed)
        disp_pool = ThreadPoolExecutor(1,
                                       thread_name_prefix="bench-disp")
        read_pool = ThreadPoolExecutor(1,
                                       thread_name_prefix="bench-read")
        t0 = time.time()
        routed = 0
        inflight: deque = deque()

        def settle(rec):
            nonlocal routed
            bi, h, mat_fut = rec
            mat_fut.result()
            counts = eng.finish(h)
            assert counts == [1] * B, f"batch {bi}: {counts[:8]}..."
            routed += B

        try:
            for bi in range(n_batches):
                i_ = rng.randint(0, ids, B)
                n_ = rng.randint(0, nums, B)
                msgs = [make("p", 0, f"dev/d{i}/x/n{n}/t", b"x")
                        for i, n in zip(i_, n_)]
                while len(inflight) >= depth:
                    settle(inflight.popleft())
                h = eng.prepare(msgs)
                assert h is not None, f"mesh stood down at batch {bi}"

                def stages(h=h):
                    eng.dispatch(h)
                    return read_pool.submit(eng.materialize, h)

                # dispatch(W+1) launches while materialize(W)/finish(W)
                # run
                dfut = disp_pool.submit(stages)
                inflight.append((bi, h, _Flat(dfut)))
            while inflight:
                settle(inflight.popleft())
            dt = time.time() - t0
        finally:
            disp_pool.shutdown(wait=True)
            read_pool.shutdown(wait=True)
        return routed, dt

    def _landed_snapshot():
        return {k: metrics.val(k) for k in (
            "pipeline.exchange.windows",
            "pipeline.exchange.host_landed_bytes",
            "pipeline.readback.windows.compact",
            "pipeline.readback.bytes.compact",
            "pipeline.readback.windows.dense",
            "pipeline.readback.bytes.dense")}

    def _landed_per_window(before, after):
        d = {k: after[k] - before[k] for k in before}
        xw = d["pipeline.exchange.windows"]
        gw = d["pipeline.readback.windows.compact"] \
            + d["pipeline.readback.windows.dense"]
        gb = d["pipeline.readback.bytes.compact"] \
            + d["pipeline.readback.bytes.dense"]
        xb = d["pipeline.exchange.host_landed_bytes"]
        total_w = xw + gw
        return {
            "windows_exchange": xw, "windows_gather": gw,
            "host_landed_bytes_per_window":
                round((xb + gb) / total_w) if total_w else None,
        }

    routed, dt = run_flood(n_batches)
    out["flood"] = {"msgs": routed, "per_s": round(routed / dt),
                    "wall_s": round(dt, 2),
                    "dispatch_depth": depth}
    log(f"flood: {routed} msgs in {dt:.1f}s = {routed / dt:.0f}/s "
        f"(depth {depth})")

    # ---- 2b. exchange twin row (ISSUE 15 satellite) ------------------
    # host-landed bytes/window + flood msgs/s, exchange on vs off, on
    # the SAME node/state. The flood above ran with the resolved knob
    # (default on); the twin re-floods with the stage forced off — the
    # host gather/merge baseline. EXCHANGE_BATCHES sizes the twin
    # floods (resume-signature relevant, like every EXCHANGE_* knob).
    if eng.device_exchange and \
            os.environ.get("BENCH_SHARDED_EXCHANGE", "1") != "0":
        n_tw = int(os.environ.get("EXCHANGE_BATCHES", n_batches))
        # the twin MUST compare identical traffic: both rows re-flood
        # with the same seed (the main flood above used seed 11 and
        # serves as the headline row, not the A/B)
        s0 = _landed_snapshot()
        r_on, dt_on = run_flood(n_tw, seed=13)
        on_row = dict(_landed_per_window(s0, _landed_snapshot()),
                      per_s=round(r_on / dt_on))
        eng.device_exchange = False      # twin: host gather/merge
        # warm the CSR compact payload class so the baseline is the
        # established SHARDED_r05 gather path, not cold dense windows
        wrng = np.random.RandomState(5)
        for _ in range(100):
            before = metrics.val("pipeline.readback.windows.compact")
            wm = [make("p", 0, f"dev/d{i}/x/n{n}/t", b"x")
                  for i, n in zip(wrng.randint(0, ids, B),
                                  wrng.randint(0, nums, B))]
            eng.route_batch(wm, wait=True)
            if metrics.val("pipeline.readback.windows.compact") \
                    > before:
                break
            time.sleep(0.05)
        s0 = _landed_snapshot()
        r_off, dt_off = run_flood(n_tw, seed=13)
        off_row = dict(_landed_per_window(s0, _landed_snapshot()),
                       per_s=round(r_off / dt_off))
        eng.device_exchange = True
        row = {"on": on_row, "off": off_row}
        lb_on = on_row["host_landed_bytes_per_window"]
        lb_off = off_row["host_landed_bytes_per_window"]
        if lb_on and lb_off:
            row["landed_reduction"] = round(lb_off / lb_on, 2)
        if off_row["per_s"]:
            row["flood_speedup"] = round(on_row["per_s"]
                                         / off_row["per_s"], 2)
        out["exchange"] = row
        log(f"exchange twin: landed/window on={lb_on} off={lb_off} "
            f"reduction={row.get('landed_reduction')} "
            f"speedup={row.get('flood_speedup')}")

    # ---- 3. churn while serving --------------------------------------
    t0 = time.time()
    churn_caps = []
    updates = 0
    for round_i in range(10):
        # subscribe burst (dirties shards)
        for k in range(32):
            c = Cap()
            churn_caps.append(c)
            broker.subscribe(
                broker.register(c, f"ch{round_i}-{k}"),
                f"churn/r{round_i}/k{k}/+")
        assert eng.dirty_shards
        updates += len(eng.dirty_shards)
        # serve: the dirty shards update synchronously-before-serve
        msgs = [make("p", 0, f"churn/r{round_i}/k{k}/z", b"y")
                for k in range(min(32, B))]
        counts = eng.route_batch(msgs, wait=True)
        assert counts == [1] * len(msgs), counts[:8]
        assert not eng.dirty_shards
        # unsubscribe burst
        if round_i % 2:
            for k, c in enumerate(churn_caps[-32:]):
                pass   # keep them; deletes covered by device tests
    out["churn"] = {"rounds": 10, "shard_updates": updates,
                    "wall_s": round(time.time() - t0, 2)}
    log(f"churn: {updates} shard updates while serving, "
        f"{out['churn']['wall_s']}s")

    # ---- 4. capacity overflow mid-flood ------------------------------
    # blow ONE shard's 'slots' class with shared groups on a hot filter:
    # poll_rebuild sees the shard no longer fits, kicks the BACKGROUND
    # full rebuild, and serving continues host-side until the swap
    t0 = time.time()
    caps_before = dict(eng._caps)
    n_groups = int(caps_before["slots"]) + 2
    grow = []
    for k in range(n_groups):
        c = Cap()
        grow.append(c)
        broker.subscribe(broker.register(c, f"g{k}"),
                         f"$share/g{k}/grow/hot/topic")
    per_msg = n_groups          # one pick per group
    host_served = 0
    mesh_served = 0
    deadline = time.time() + 120
    while time.time() < deadline:
        msgs = [make("p", 0, "grow/hot/topic", b"z")]
        counts = eng.route_batch(msgs)
        if counts is None:
            # mesh rebuilding: the production path routes host-side
            broker._route(msgs[0], broker.router.match(msgs[0].topic))
            host_served += 1
            time.sleep(0.01)
        else:
            assert counts == [per_msg], counts
            mesh_served += 1
            if eng._caps["slots"] > caps_before["slots"] \
                    and mesh_served >= 3:
                break
    assert eng._caps["slots"] > caps_before["slots"], \
        (caps_before, eng._caps)
    got = sum(c.n for c in grow)
    want = (host_served + mesh_served) * per_msg
    assert got == want, \
        f"deliveries lost across the capacity rebuild: {got} != {want}"
    out["overflow"] = {
        "slots_cap": [caps_before["slots"], eng._caps["slots"]],
        "host_served_during_rebuild": host_served,
        "mesh_served_after": mesh_served,
        "wall_s": round(time.time() - t0, 2),
    }
    log(f"overflow: slots cap {caps_before['slots']} -> "
        f"{eng._caps['slots']}, {host_served} host-served during "
        f"rebuild, mesh resumed ({mesh_served})")

    out["total_wall_s"] = round(time.time() - t_start)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
