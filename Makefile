# emqx_tpu — repo-level targets. (native/ has its own Makefile for the
# C codec; this one is the operator/CI surface.)

PY ?= python
REPO := $(dir $(abspath $(lastword $(MAKEFILE_LIST))))

.PHONY: help analyze analyze-changed test test-fast native

help:
	@echo "targets:"
	@echo "  analyze          run the pipeline contract analyzer"
	@echo "                   (tools/analysis, all 6 passes over"
	@echo "                   emqx_tpu/ — docs/ANALYSIS.md; exit 1"
	@echo "                   on findings)"
	@echo "  analyze-changed  same framework, report filtered to"
	@echo "                   files changed vs HEAD (the fast path:"
	@echo "                   analysis still sees the whole repo, so"
	@echo "                   cross-file passes stay sound)"
	@echo "  test             tier-1 test suite (pytest -m 'not slow')"
	@echo "  test-fast        analyzer + frame/topic unit slices only"
	@echo "  native           build the native codec (native/)"

analyze:
	PYTHONPATH=$(REPO)tools $(PY) -m analysis --root $(REPO)

# changed-files fast path: full-repo analysis (cheap — seconds), report
# narrowed to your diff so pre-existing annotated context stays quiet
analyze-changed:
	@changed=$$( (git -C $(REPO) diff --name-only HEAD -- \
	    'emqx_tpu/*.py' 'emqx_tpu/**/*.py' 'docs/*.md'; \
	    git -C $(REPO) ls-files --others --exclude-standard -- \
	    'emqx_tpu/*.py' 'emqx_tpu/**/*.py' 'docs/*.md') | sort -u); \
	if [ -z "$$changed" ]; then \
	    echo "analyze-changed: no changed emqx_tpu/docs files"; \
	else \
	    PYTHONPATH=$(REPO)tools $(PY) -m analysis --root $(REPO) \
	        $$changed; \
	fi

test:
	cd $(REPO) && JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q \
	    -m 'not slow' --continue-on-collection-errors \
	    -p no:cacheprovider

test-fast:
	cd $(REPO) && JAX_PLATFORMS=cpu $(PY) -m pytest \
	    tests/test_analysis.py tests/test_frame.py \
	    tests/test_topic.py -q -p no:cacheprovider

native:
	$(MAKE) -C $(REPO)native
