"""MQTT v5 protocol conformance suite.

A 1:1 port of the reference's full-conformance suite
/root/reference/apps/emqx/test/emqx_mqtt_protocol_v5_SUITE.erl — every
test name below maps onto the t_* case of the same name (the reference's
typos `assigned_clienid` / `unscbsctibe` are preserved so the mapping is
greppable). Cases drive a live broker with the bundled client over BOTH
transports the reference's groups/1 runs — tcp and quic — exactly as the
reference drives emqx with emqtt / emqtt-quic.

The one commented-out reference case (t_connect_will_delay_interval,
marked "REFACTOR NEED" upstream) is ported as a working test of the same
property where possible or skipped with the same status.
"""

import asyncio

import pytest

from emqx_tpu.broker.connection import Listener
from emqx_tpu.broker.node import Node
from emqx_tpu.client import Client, MqttError
from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt import packet as P

TOPICS = ["TopicA", "TopicA/B", "Topic/C", "TopicA/C", "/TopicA"]
WILD_TOPICS = ["TopicA/+", "+/C", "#", "/#", "/+", "+/+", "TopicA/#"]


@pytest.fixture()
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    from emqx_tpu.utils.tls import generate_self_signed
    return generate_self_signed(str(tmp_path_factory.mktemp("v5-certs")))


def _make_transport(loop, node, transport, certs):
    """Start a tcp or quic listener on `node`; return (mk, cleanup) where
    mk(clientid, **kw) builds an UNCONNECTED client wired for that
    transport — the reference suite's {tcp, quic} groups over one case
    list (emqx_mqtt_protocol_v5_SUITE groups/1)."""
    if transport == "tcp":
        lst = Listener(node, bind="127.0.0.1", port=0)
        loop.run_until_complete(lst.start())

        def mk(clientid="", **kw):
            return Client(port=lst.port, clientid=clientid,
                          proto_ver=C.MQTT_V5, **kw)

        def cleanup():
            loop.run_until_complete(lst.stop())
        return mk, cleanup

    from emqx_tpu.quic import QuicClientConnection, QuicListener
    lst = QuicListener(node, bind="127.0.0.1", port=0,
                       certfile=certs["certfile"],
                       keyfile=certs["keyfile"])
    loop.run_until_complete(lst.start())
    qcs: list = []

    def mk(clientid="", **kw):
        async def factory():
            qc = QuicClientConnection(port=lst.port,
                                      cafile=certs["cacertfile"])
            await qc.connect()
            qcs.append(qc)
            return qc.open_stream()
        return Client(clientid=clientid, proto_ver=C.MQTT_V5,
                      conn_factory=factory, **kw)

    def cleanup():
        for qc in qcs:
            try:
                qc.close(0, "test end", app=True)
            except Exception:   # noqa: BLE001 — teardown best-effort
                pass
        loop.run_until_complete(lst.stop())
    return mk, cleanup


@pytest.fixture(params=["tcp", "quic"])
def broker(loop, request, certs):
    """A live node reachable over the parametrized transport — every
    case below runs twice, exactly like the reference's tcp/quic
    groups."""
    node = Node()
    mk, cleanup = _make_transport(loop, node, request.param, certs)
    yield node, mk
    cleanup()


def run(loop, coro, timeout=20):
    return loop.run_until_complete(asyncio.wait_for(coro, timeout))


def make_broker(loop, config):
    """Config-variant cases (fresh node, TCP): the zone knobs under test
    are transport-independent. Returns (node, listener, mk)."""
    node = Node(config)
    listener = Listener(node, bind="127.0.0.1", port=0)
    loop.run_until_complete(listener.start())

    def mk(clientid="", **kw):
        return Client(port=listener.port, clientid=clientid,
                      proto_ver=C.MQTT_V5, **kw)
    return node, listener, mk


async def v5(mk, clientid="", **kw) -> Client:
    c = mk(clientid, **kw)
    await c.connect()
    return c


async def receive_messages(c: Client, count: int, timeout=1.0) -> list:
    """The suite's receive_messages/1: collect up to `count` publishes,
    give up after `timeout` of silence."""
    msgs = []
    while len(msgs) < count:
        try:
            msgs.append(await c.recv(timeout=timeout))
        except asyncio.TimeoutError:
            break
    return msgs


async def receive_disconnect_reasoncode(c: Client, timeout=5.0) -> int:
    await asyncio.wait_for(c.closed.wait(), timeout)
    assert c.disconnect_pkt is not None, "no disconnect packet"
    return c.disconnect_pkt.reason_code


class TestBasic:
    def test_basic_test(self, loop, broker):
        """t_basic_test: subscribe qos1 then qos2, 3 qos2 publishes, 3
        deliveries."""
        _node, mk = broker

        async def go():
            c = await v5(mk, "basic")
            assert (await c.subscribe(TOPICS[0], qos=1)).reason_codes == [1]
            assert (await c.subscribe(TOPICS[0], qos=2)).reason_codes == [2]
            for _ in range(3):
                await c.publish(TOPICS[0], b"qos 2", qos=2)
            assert len(await receive_messages(c, 3)) == 3
            await c.disconnect()
        run(loop, go())


class TestConnection:
    def test_connect_clean_start(self, loop, broker):
        """t_connect_clean_start: MQTT-3.1.2-4/-5/-6 session-present
        semantics + DISCONNECT 0x8E (142) to the displaced connection."""
        _node, mk = broker

        async def go():
            c1 = await v5(mk, "t_connect_clean_start",
                          clean_start=True)
            assert c1.connack.session_present is False   # [MQTT-3.1.2-4]
            c2 = await v5(mk, "t_connect_clean_start",
                          clean_start=False)
            assert c2.connack.session_present is True    # [MQTT-3.1.2-5]
            assert await receive_disconnect_reasoncode(c1) == 142
            await c2.disconnect()

            c3 = await v5(mk, "new_client", clean_start=False)
            assert c3.connack.session_present is False   # [MQTT-3.1.2-6]
            await c3.disconnect()
        run(loop, go())

    def test_connect_will_message(self, loop, broker):
        """t_connect_will_message: will stored on CONNECT (MQTT-3.1.2-7),
        published on disconnect-with-will rc=0x04 (MQTT-3.14.2-1,
        MQTT-3.1.2-8), dropped on normal disconnect (MQTT-3.1.2-10)."""
        node, mk = broker

        async def go():
            will = P.Will(topic=TOPICS[0], payload=b"will message")
            c1 = await v5(mk, "will1", will=will)
            ch = node.cm.lookup_channel("will1")
            assert ch is not None and ch.will_msg is not None  # 3.1.2-7
            c2 = await v5(mk, "will-sub")
            await c2.subscribe(TOPICS[0], qos=2)
            await c1.disconnect(reason_code=4)   # disconnect WITH will
            [msg] = await receive_messages(c2, 1)
            assert msg.topic == TOPICS[0]        # [MQTT-3.1.2-8]
            assert msg.payload == b"will message"
            assert msg.qos == 0
            await c2.disconnect()

            c3 = await v5(mk, "will2", will=will)
            c4 = await v5(mk, "will-sub2")
            await c4.subscribe(TOPICS[0], qos=2)
            await c3.disconnect()                # rc 0: will dropped
            assert await receive_messages(c4, 1) == []   # [MQTT-3.1.2-10]
            await c4.disconnect()
        run(loop, go())

    def test_batch_subscribe(self, loop, broker):
        """t_batch_subscribe: with authorization denying, a batch
        SUBSCRIBE acks 0x87 per filter and batch UNSUBSCRIBE acks 0x11
        per unknown filter."""
        node, mk = broker
        node.hooks.add("client.authorize",
                       lambda _ci, _act, _t, _acc: ("stop", "deny"))

        async def go():
            c = await v5(mk, "batch_test")
            sa = await c.subscribe([("t1", P.SubOpts(qos=1)),
                                    ("t2", P.SubOpts(qos=2)),
                                    ("t3", P.SubOpts(qos=0))])
            assert sa.reason_codes == [C.RC_NOT_AUTHORIZED] * 3
            ua = await c.unsubscribe(["t1", "t2", "t3"])
            assert ua.reason_codes == [C.RC_NO_SUBSCRIPTION_EXISTED] * 3
            await c.disconnect()
        run(loop, go())

    def test_connect_will_retain(self, loop, broker):
        """t_connect_will_retain: will_retain=False delivers retain=False
        (MQTT-3.1.2-14); will_retain=True delivers retain=True to a
        rap subscriber (MQTT-3.1.2-15)."""
        _node, mk = broker

        async def go():
            will = P.Will(topic=TOPICS[0], payload=b"will message",
                          retain=False)
            c1 = await v5(mk, "wr1", will=will)
            c2 = await v5(mk, "wr-sub")
            await c2.subscribe(TOPICS[0], qos=2, opts={"rap": 1})
            await c1.disconnect(reason_code=4)
            [m1] = await receive_messages(c2, 1)
            assert m1.retain is False            # [MQTT-3.1.2-14]
            await c2.disconnect()

            will_r = P.Will(topic=TOPICS[0], payload=b"will message",
                            qos=1, retain=True)
            c3 = await v5(mk, "wr2", will=will_r)
            c4 = await v5(mk, "wr-sub2")
            await c4.subscribe(TOPICS[0], qos=2, opts={"rap": 1})
            await c3.disconnect(reason_code=4)
            [m2] = await receive_messages(c4, 1)
            assert m2.retain is True             # [MQTT-3.1.2-15]
            await c4.disconnect()
            # clean_retained
            cl = await v5(mk, "clean")
            await cl.publish(TOPICS[0], b"", qos=1, retain=True)
            await cl.disconnect()
        run(loop, go())

    def test_connect_idle_timeout(self, loop):
        """t_connect_idle_timeout: a socket that never sends CONNECT is
        closed after the zone idle_timeout."""
        node, lst, _mk = make_broker(loop, {"mqtt": {"idle_timeout": 0.3}})

        async def go():
            r, _w = await asyncio.open_connection("127.0.0.1", lst.port)
            data = await asyncio.wait_for(r.read(64), 3)
            assert data == b""      # closed by the broker
        try:
            run(loop, go())
        finally:
            loop.run_until_complete(lst.stop())

    def test_connect_emit_stats_timeout(self, loop, broker):
        """t_connect_emit_stats_timeout: the reference cancels each
        connection's stats timer once idle (snabbkaffe
        cancel_stats_timer). This design has no per-connection stats
        timer AT ALL — stats are pulled by the node-level sampler — so
        the asserted property (an idle connection schedules no stats
        work) holds by construction; assert the pull surface works on an
        idle connection."""
        node, mk = broker

        async def go():
            c = await v5(mk, "stats-idle", keepalive=60)
            await asyncio.sleep(0.2)     # idle
            info = node.cm.get_channel_info("stats-idle")
            assert info is not None and info.get("clientid") == "stats-idle"
            ch = node.cm.lookup_channel("stats-idle")
            # no stats timer attribute exists on the channel: the idle
            # cost is zero by design, the property the reference asserts
            assert not hasattr(ch, "stats_timer")
            await c.disconnect()
        run(loop, go())

    def test_connect_keepalive_timeout(self, loop, broker):
        """t_connect_keepalive_timeout: MQTT-3.1.2-22 — a silent client
        is disconnected with rc 141 after ~1.5x keepalive."""
        _node, mk = broker

        async def go():
            c = await v5(mk, "ka", keepalive=1)
            # the client sends nothing (no auto-ping): broker must kill it
            rc = await receive_disconnect_reasoncode(c, timeout=6)
            assert rc == 141
        run(loop, go())

    def test_connect_session_expiry_interval(self, loop, broker):
        """t_connect_session_expiry_interval: MQTT-3.1.2-23 — a qos2
        message published while offline is delivered on resume."""
        _node, mk = broker

        async def go():
            c1 = await v5(mk, "t_connect_session_expiry_interval",
                          properties={"session_expiry_interval": 7200})
            await c1.subscribe(TOPICS[0], qos=2)
            await c1.disconnect()

            c2 = await v5(mk, "pub")
            await c2.publish(TOPICS[0], b"test message", qos=2)
            await c2.disconnect()

            c3 = await v5(mk, "t_connect_session_expiry_interval",
                          clean_start=False)
            [msg] = await receive_messages(c3, 1, timeout=3)
            assert msg.topic == TOPICS[0]
            assert msg.payload == b"test message"
            assert msg.qos == 2
            await c3.disconnect()
        run(loop, go())

    def test_connect_duplicate_clientid(self, loop, broker):
        """t_connect_duplicate_clientid: MQTT-3.1.4-3 — the first
        connection gets DISCONNECT 142."""
        _node, mk = broker

        async def go():
            c1 = await v5(mk, "t_connect_duplicate_clientid")
            c2 = await v5(mk, "t_connect_duplicate_clientid")
            assert await receive_disconnect_reasoncode(c1) == 142
            await c2.disconnect()
        run(loop, go())


class TestConnack:
    def test_connack_session_present(self, loop, broker):
        """t_connack_session_present: MQTT-3.2.2-2/-3."""
        _node, mk = broker

        async def go():
            c1 = await v5(mk, "sp",
                          properties={"session_expiry_interval": 7200},
                          clean_start=True)
            assert c1.connack.session_present is False   # [MQTT-3.2.2-2]
            await c1.disconnect()
            c2 = await v5(mk, "sp",
                          properties={"session_expiry_interval": 7200},
                          clean_start=False)
            assert c2.connack.session_present is True    # [MQTT-3.2.2-3]
            await c2.disconnect()
        run(loop, go())

    @pytest.mark.parametrize("max_qos", [0, 1])
    def test_connack_max_qos_allowed(self, loop, max_qos):
        """t_connack_max_qos_allowed: MQTT-3.2.2-9/-10/-11/-12 for
        max_qos_allowed of 0 and 1 (the =2 leg is the case below)."""
        node, lst, mk = make_broker(
            loop, {"mqtt": {"max_qos_allowed": max_qos}})

        async def go():
            c1 = await v5(mk, "mq")
            assert c1.connack.properties.get("maximum_qos") == max_qos
            # subscription grants are NOT capped        [MQTT-3.2.2-10]
            assert (await c1.subscribe(TOPICS[0], qos=0)).reason_codes == [0]
            assert (await c1.subscribe(TOPICS[0], qos=1)).reason_codes == [1]
            assert (await c1.subscribe(TOPICS[0], qos=2)).reason_codes == [2]
            # publishing above the cap: DISCONNECT 155  [MQTT-3.2.2-11]
            try:
                await c1.publish(TOPICS[0], b"Unsupported Qos",
                                 qos=max_qos + 1, timeout=3)
            except MqttError:
                pass
            assert await receive_disconnect_reasoncode(c1) == 155

            # a will above the cap refuses the CONNECT  [MQTT-3.2.2-12]
            c2 = Client(port=lst.port, clientid="mq-will",
                        proto_ver=C.MQTT_V5,
                        will=P.Will(topic=TOPICS[0],
                                    payload=b"Unsupported Qos", qos=2))
            with pytest.raises(MqttError):
                await c2.connect()
            assert c2.connack.reason_code == C.RC_QOS_NOT_SUPPORTED
            await c2.close()
        try:
            run(loop, go())
        finally:
            loop.run_until_complete(lst.stop())

    def test_connack_max_qos_allowed_full_range(self, loop, broker):
        """t_connack_max_qos_allowed (max=2 leg): Maximum-QoS is ABSENT
        from CONNACK when the full range is supported [MQTT-3.2.2-9]."""
        _node, mk = broker

        async def go():
            c = await v5(mk, "mq2")
            assert "maximum_qos" not in c.connack.properties
            await c.disconnect()
        run(loop, go())

    def test_connack_assigned_clienid(self, loop, broker):
        """t_connack_assigned_clienid (sic): MQTT-3.2.2-16 — empty
        clientid gets a broker-assigned one."""
        _node, mk = broker

        async def go():
            c = await v5(mk, "")
            assigned = c.connack.properties.get("assigned_client_identifier")
            assert isinstance(assigned, str) and assigned
            await c.disconnect()
        run(loop, go())


class TestPublish:
    def test_publish_rap(self, loop, broker):
        """t_publish_rap: MQTT-3.3.1-12/-13 retain-as-published."""
        _node, mk = broker

        async def go():
            c1 = await v5(mk, "rap1")
            await c1.subscribe(TOPICS[0], qos=2, opts={"rap": 1})
            await c1.publish(TOPICS[0], b"retained message", qos=1,
                             retain=True)
            [m1] = await receive_messages(c1, 1)
            assert m1.retain is True             # [MQTT-3.3.1-12]
            await c1.disconnect()

            c2 = await v5(mk, "rap2")
            await c2.subscribe(TOPICS[0], qos=2, opts={"rap": 0})
            await c2.publish(TOPICS[0], b"retained message", qos=1,
                             retain=True)
            [m2] = await receive_messages(c2, 1)
            assert m2.retain is False            # [MQTT-3.3.1-13]
            await c2.disconnect()

            cl = await v5(mk, "clean")
            await cl.publish(TOPICS[0], b"", qos=1, retain=True)
            await cl.disconnect()
        run(loop, go())

    def test_publish_wildtopic(self, loop, broker):
        """t_publish_wildtopic: publishing to a wildcard topic NAME gets
        DISCONNECT 144."""
        _node, mk = broker

        async def go():
            c = await v5(mk, "wt")
            await c.publish(WILD_TOPICS[0], b"error topic")
            assert await receive_disconnect_reasoncode(c) == 144
        run(loop, go())

    def test_publish_payload_format_indicator(self, loop, broker):
        """t_publish_payload_format_indicator: MQTT-3.3.2-6 — the
        property is forwarded unaltered."""
        _node, mk = broker

        async def go():
            props = {"payload_format_indicator": 233 & 0xFF}
            c = await v5(mk, "pfi")
            await c.subscribe(TOPICS[0], qos=2)
            await c.publish(TOPICS[0], b"Payload Format Indicator",
                            properties=props)
            [m] = await receive_messages(c, 1)
            assert m.properties.get("payload_format_indicator") == \
                props["payload_format_indicator"]
            await c.disconnect()
        run(loop, go())

    def test_publish_topic_alias(self, loop, broker):
        """t_publish_topic_alias: alias 0 is invalid (DISCONNECT 148,
        MQTT-3.3.2-8); a registered alias routes an empty-topic publish
        (MQTT-3.3.2-12)."""
        _node, mk = broker

        async def go():
            c1 = await v5(mk, "ta1")
            await c1.publish(TOPICS[0], b"Topic-Alias",
                             properties={"topic_alias": 0})
            assert await receive_disconnect_reasoncode(c1) == 148

            c2 = await v5(mk, "ta2")
            await c2.subscribe(TOPICS[0], qos=2)
            await c2.publish(TOPICS[0], b"Topic-Alias",
                             properties={"topic_alias": 233})
            await c2.publish("", b"Topic-Alias",
                             properties={"topic_alias": 233})
            assert len(await receive_messages(c2, 2)) == 2
            await c2.disconnect()
        run(loop, go())

    def test_publish_response_topic(self, loop, broker):
        """t_publish_response_topic: a wildcard Response-Topic gets
        DISCONNECT 130 (MQTT-3.3.2-14)."""
        _node, mk = broker

        async def go():
            c = await v5(mk, "rt")
            await c.publish(TOPICS[0], b"Response-Topic",
                            properties={"response_topic": WILD_TOPICS[0]})
            assert await receive_disconnect_reasoncode(c) == 130
        run(loop, go())

    def test_publish_properties(self, loop, broker):
        """t_publish_properties: MQTT-3.3.2-15/-16/-18/-20 — all
        request/response + user properties forwarded unaltered."""
        _node, mk = broker

        async def go():
            props = {
                "response_topic": TOPICS[0],         # [MQTT-3.3.2-15]
                "correlation_data": b"233",          # [MQTT-3.3.2-16]
                "user_property": [("a", "2333")],    # [MQTT-3.3.2-18]
                "content_type": "2333",              # [MQTT-3.3.2-20]
            }
            c = await v5(mk, "pp")
            await c.subscribe(TOPICS[0], qos=2)
            await c.publish(TOPICS[0], b"Publish Properties",
                            properties=props)
            [m] = await receive_messages(c, 1)
            got = dict(m.properties)
            assert got.get("response_topic") == TOPICS[0]
            assert bytes(got.get("correlation_data")) == b"233"
            assert [tuple(p) for p in got.get("user_property")] == \
                [("a", "2333")]
            assert got.get("content_type") == "2333"
            await c.disconnect()
        run(loop, go())

    def test_publish_overlapping_subscriptions(self, loop, broker):
        """t_publish_overlapping_subscriptions: MQTT-3.3.4-2/-3 —
        overlapping subscriptions each deliver, QoS capped by the
        subscription, subscription identifier forwarded."""
        _node, mk = broker

        async def go():
            props = {"subscription_identifier": 2333}
            c = await v5(mk, "overlap")
            sa1 = await c.subscribe(WILD_TOPICS[0], qos=1,
                                    properties=props)
            assert sa1.reason_codes == [1]
            sa2 = await c.subscribe(WILD_TOPICS[2], qos=0,
                                    properties=props)
            assert sa2.reason_codes == [0]
            await c.publish(TOPICS[0], b"t_publish_overlapping", qos=2)
            msgs = await receive_messages(c, 2)
            assert len(msgs) >= 1
            assert msgs[0].qos < 2               # [MQTT-3.3.4-2]
            subids = msgs[0].properties.get("subscription_identifier")
            assert subids == 2333 or subids == [2333]   # [MQTT-3.3.4-3]
            await c.disconnect()
        run(loop, go())


class TestSubscribe:
    def test_subscribe_topic_alias(self, loop, broker):
        """t_subscribe_topic_alias: outbound aliasing under the client's
        Topic-Alias-Maximum — first delivery topic+alias, repeat delivery
        alias only, second topic un-aliased (budget of 1)."""
        _node, mk = broker

        async def go():
            c = await v5(mk, "sta",
                         properties={"topic_alias_maximum": 1})
            await c.subscribe(TOPICS[0], qos=2)
            await c.subscribe(TOPICS[1], qos=2)

            await c.publish(TOPICS[0], b"Topic-Alias")
            [m1] = await receive_messages(c, 1)
            assert m1.properties.get("topic_alias") == 1
            assert m1.topic == TOPICS[0]

            await c.publish(TOPICS[0], b"Topic-Alias")
            [m2] = await receive_messages(c, 1)
            assert m2.properties.get("topic_alias") == 1
            assert m2.topic == ""

            await c.publish(TOPICS[1], b"Topic-Alias")
            [m3] = await receive_messages(c, 1)
            assert "topic_alias" not in (m3.properties or {})
            assert m3.topic == TOPICS[1]
            await c.disconnect()
        run(loop, go())

    def test_subscribe_no_local(self, loop, broker):
        """t_subscribe_no_local: MQTT-3.8.3-3 — the publishing client's
        own no-local subscription stays silent; the other client's
        delivery arrives."""
        _node, mk = broker

        async def go():
            c1 = await v5(mk, "nl1")
            await c1.subscribe(TOPICS[0], qos=2, opts={"nl": 1})
            c2 = await v5(mk, "nl2")
            await c2.subscribe(TOPICS[0], qos=2, opts={"nl": 1})
            await c1.publish(TOPICS[0], b"t_subscribe_no_local")
            got_c2 = await receive_messages(c2, 1)
            got_c1 = await receive_messages(c1, 1, timeout=0.3)
            assert len(got_c2) == 1 and len(got_c1) == 0
            await c1.disconnect()
            await c2.disconnect()
        run(loop, go())

    def test_subscribe_actions(self, loop, broker):
        """t_subscribe_actions: MQTT-3.8.4-3/-5/-6/-7/-8 — resubscribe
        replaces the subscription (delivery at the new QoS), batch
        subscribe acks per filter."""
        _node, mk = broker

        async def go():
            props = {"subscription_identifier": 2333}
            c = await v5(mk, "actions")
            assert (await c.subscribe(TOPICS[0], qos=2,
                                      properties=props)).reason_codes == [2]
            assert (await c.subscribe(TOPICS[0], qos=1,
                                      properties=props)).reason_codes == [1]
            await c.publish(TOPICS[0], b"t_subscribe_actions", qos=2)
            [m] = await receive_messages(c, 1)
            assert m.qos == 1                    # [MQTT-3.8.4-3/-8]
            sa = await c.subscribe([(TOPICS[0], P.SubOpts(qos=2)),
                                    (TOPICS[1], P.SubOpts(qos=2))])
            assert sa.reason_codes == [2, 2]            # [MQTT-3.8.4-5/-6/-7]
            await c.disconnect()
        run(loop, go())


class TestFlowControl:
    def test_receive_maximum_flow_control(self, loop, broker):
        """MQTT-3.3.4-9 flow control (the conformance property behind the
        reference's receive-maximum cases): the broker must never exceed
        the client's advertised Receive Maximum of unacknowledged QoS1
        deliveries; acking one frees exactly one more."""
        _node, mk = broker

        async def go():
            c = await v5(mk, "rm-flow",
                         properties={"receive_maximum": 3})
            c.auto_ack = False      # hold PUBACKs: the window must cap
            await c.subscribe(TOPICS[0], qos=1)
            pub = await v5(mk, "rm-pub")
            for i in range(10):
                await pub.publish(TOPICS[0], b"m%d" % i, qos=1)
            got = await receive_messages(c, 10, timeout=1.0)
            assert len(got) == 3, f"window breached: {len(got)}"
            # ack one → exactly one more arrives
            c._send(P.Puback(packet_id=got[0].packet_id))
            more = await receive_messages(c, 10, timeout=1.0)
            assert len(more) == 1, f"expected 1 freed slot, got {len(more)}"
            # ack everything → the rest drains
            total = len(got) + len(more)
            pending = got[1:] + more
            while pending:
                for m in pending:
                    c._send(P.Puback(packet_id=m.packet_id))
                pending = await receive_messages(c, 10, timeout=1.0)
                total += len(pending)
            assert total == 10
            await c.disconnect()
            await pub.disconnect()
        run(loop, go())


class TestUnsubscribe:
    def test_unscbsctibe(self, loop, broker):
        """t_unscbsctibe (sic): MQTT-3.10.4-4/-5/-6, MQTT-3.11.3-1/-2 —
        per-filter UNSUBACK codes incl. 0x11 for unknown filters."""
        _node, mk = broker

        async def go():
            c = await v5(mk, "unsub")
            assert (await c.subscribe(TOPICS[0], qos=2)).reason_codes == [2]
            assert (await c.unsubscribe(TOPICS[0])).reason_codes == [0]
            assert (await c.unsubscribe("noExistTopic")).reason_codes == [0x11]
            sa = await c.subscribe([(TOPICS[0], P.SubOpts(qos=2)),
                                    (TOPICS[1], P.SubOpts(qos=2))])
            assert sa.reason_codes == [2, 2]
            ua = await c.unsubscribe([TOPICS[0], TOPICS[1],
                                      "noExistTopic"])
            assert ua.reason_codes == [0, 0, 0x11]
            await c.disconnect()
        run(loop, go())


class TestPingreq:
    def test_pingreq(self, loop, broker):
        """t_pingreq: MQTT-3.12.4-1 — PINGREQ gets PINGRESP."""
        node, mk = broker

        async def go():
            c = await v5(mk, "ping")
            await c.ping()
            await asyncio.sleep(0.1)
            await c.disconnect()
        run(loop, go())
        assert node.metrics.val("packets.pingresp.sent") == 1


class TestSharedSubscriptions:
    def test_shared_subscriptions_client_terminates_when_qos_eq_2(
            self, loop):
        """t_shared_subscriptions_client_terminates_when_qos_eq_2: with
        dispatch-ack enabled, a qos2 publish into a 2-member share group
        is dispatched to exactly ONE member (which dies on receipt, as
        the reference's mecked emqtt does)."""
        node, lst, mk = make_broker(
            loop, {"broker": {"shared_dispatch_ack_enabled": True}})
        shared = "$share/sharename/" + TOPICS[0]
        received = []

        async def go():
            subs = []
            for cid in ("sub_client_1", "sub_client_2"):
                s = Client(port=lst.port, clientid=cid,
                           proto_ver=C.MQTT_V5, keepalive=5)
                s.auto_ack = False      # die before acking, like the meck
                await s.connect()
                assert (await s.subscribe(shared, qos=2)).reason_codes == [2]
                subs.append(s)

            pub = await v5(mk, "pub_client")
            await pub.publish(
                TOPICS[0],
                b"t_shared_subscriptions_client_terminates_when_qos_eq_2",
                qos=2)
            # whichever member got it terminates immediately
            for s in subs:
                try:
                    m = await s.recv(timeout=1.0)
                    received.append((s.clientid, m))
                    await s.close()    # hard kill, no DISCONNECT
                except asyncio.TimeoutError:
                    pass
            await pub.disconnect()
            for s in subs:
                await s.close()
        try:
            run(loop, go())
        finally:
            loop.run_until_complete(lst.stop())
        assert len(received) == 1
