"""Fault-domain supervision for the device route pipeline (ISSUE 6).

The chaos acceptance criteria, as tests:

- **Injection matrix** (marked `chaos`): for each injection point ×
  fault kind, the twin-engine oracle shows zero lost QoS≥1 deliveries,
  per-session order bit-identical to the fault-free run, degradation to
  the next ladder rung within one window (threshold 1 here), and the
  breaker re-closing after the half-open probe.
- **EMQX_TPU_SUPERVISE=0** reproduces the pre-ISSUE-6 behavior exactly
  (no supervisor object anywhere; the old unwind paths untouched).
- **Watchdogs**: a hung dispatch/materialize trips the stall detector
  instead of wedging the consumer; a dead lane worker is restarted by
  the drain watchdog and drains its queue in order.
- Plus the satellite coverage for error paths that had none: compact
  payload overflow concurrent with a snapshot swap, a delta-overlay
  overflow racing `_overlay_sync`, and `pool.drain()` after loop
  teardown — and the task-hygiene static pass wired as a tier-1 gate.
"""

import asyncio
import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))

import chaos_bench as CB                                    # noqa: E402
import check_task_hygiene as hygiene                        # noqa: E402

from emqx_tpu.broker import device_engine as DE             # noqa: E402
from emqx_tpu.broker import supervise as S                  # noqa: E402
from emqx_tpu.broker.message import make                    # noqa: E402
from emqx_tpu.broker.node import Node                       # noqa: E402


def run(coro, timeout=120):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


def mkmsg(topic, payload=b"x", qos=1):
    return make("pub", qos, topic, payload)


class Rec:
    def __init__(self):
        self.got = []

    def deliver(self, topic_filter, msg):
        self.got.append((topic_filter, msg.topic, bytes(msg.payload)))
        return True


# ---------- fault spec grammar + injector determinism ----------

class TestFaultSpec:
    def test_grammar(self):
        faults = S.parse_faults(
            "dispatch:exception,materialize:hang:after=2:count=3:"
            "hang_s=0.25, lane_deliver:resource")
        assert [(f.point, f.kind) for f in faults] == [
            ("dispatch", "exception"), ("materialize", "hang"),
            ("lane_deliver", "resource")]
        assert faults[1].after == 2 and faults[1].count == 3
        assert faults[1].hang_s == 0.25
        assert S.parse_faults(None) == [] and S.parse_faults("") == []

    @pytest.mark.parametrize("bad", [
        "dispatch",                    # no kind
        "nosuchpoint:exception",       # unknown point
        "dispatch:nosuchkind",         # unknown kind
        "dispatch:exception:after",    # option not k=v
        "dispatch:exception:welp=1",   # unknown option
    ])
    def test_malformed_specs_fail_loudly(self, bad):
        with pytest.raises(ValueError):
            S.parse_faults(bad)

    def test_after_count_window(self):
        inj = S.FaultInjector(S.parse_faults(
            "dispatch:exception:after=2:count=2"))
        fired = []
        for _ in range(6):
            try:
                inj.fire("dispatch")
                fired.append(False)
            except S.InjectedFault:
                fired.append(True)
        # traversals 3 and 4 fire, nothing before or after
        assert fired == [False, False, True, True, False, False]

    def test_resource_kind_reads_like_oom(self):
        inj = S.FaultInjector(S.parse_faults("materialize:resource"))
        with pytest.raises(S.InjectedResourceExhausted) as ei:
            inj.fire("materialize")
        assert "RESOURCE_EXHAUSTED" in str(ei.value)

    def test_corrupt_decays_to_exception_unless_handled(self):
        inj = S.FaultInjector(S.parse_faults(
            "dispatch:corrupt,materialize:corrupt"))
        with pytest.raises(S.InjectedFault):
            inj.fire("dispatch")            # corrupt_ok=False: raises
        assert inj.fire("materialize", corrupt_ok=True) == "corrupt"

    def test_unarmed_is_free(self):
        sup = S.PipelineSupervisor(Node(use_device=False).metrics,
                                   injector=S.FaultInjector([]))
        assert sup.fire("dispatch") is None


# ---------- circuit breaker state machine ----------

class TestBreaker:
    def test_open_after_threshold_consecutive(self):
        t = [0.0]
        br = S.CircuitBreaker("dispatch", threshold=3, cooldown_s=1.0,
                              clock=lambda: t[0])
        assert br.allow()
        br.record_fault()
        br.record_ok()              # a success resets the streak
        br.record_fault()
        br.record_fault()
        assert br.allow() and br.state == "closed"
        assert br.record_fault()    # third consecutive: opens
        assert br.state == "open" and not br.allow() and br.trips == 1

    def test_half_open_probe_cycle_with_backoff(self):
        t = [0.0]
        br = S.CircuitBreaker("dispatch", threshold=1, cooldown_s=1.0,
                              max_cooldown_s=4.0, clock=lambda: t[0])
        br.record_fault()
        assert not br.probe_due()
        t[0] = 1.5
        assert br.probe_due()
        br.begin_probe()
        assert br.state == "half_open" and not br.allow()
        br.probe_fail()             # still broken: cooldown doubles
        assert br.state == "open" and br.cooldown_s == 2.0
        t[0] = 4.0
        br.begin_probe()
        br.probe_ok()
        assert br.state == "closed" and br.allow()
        assert br.cooldown_s == 1.0     # reset on close

    def test_faults_while_open_do_not_restack(self):
        br = S.CircuitBreaker("x", threshold=1)
        assert br.record_fault()
        assert not br.record_fault()    # already open: no second trip
        assert br.trips == 1


# ---------- the ladder ----------

class TestLadder:
    def _sup(self):
        return S.PipelineSupervisor(
            Node(use_device=False).metrics,
            injector=S.FaultInjector([]), threshold=1)

    def test_rungs(self):
        sup = self._sup()
        assert sup.rung() == S.RUNG_FULL
        assert sup.allow_device() and sup.reuse_enabled()
        sup.note_fault("cache_insert")
        assert sup.rung() == S.RUNG_DEVICE_PLAIN
        assert sup.allow_device() and not sup.reuse_enabled()
        sup.note_fault("materialize")
        assert sup.rung() == S.RUNG_HOST and not sup.allow_device()

    def test_open_lane_breaker_defers_inline_fallback_until_drained(self):
        """An open lane_deliver breaker must NOT flip the pool inactive
        while plans are still in flight — an immediate inline fallback
        could reorder a session's stream against its queued lane rows.
        New plans stop only once the lanes have drained."""
        node = Node({"broker": {"deliver_lanes": 2,
                                "supervise_threshold": 1,
                                "device_fanout_cap": 16,
                                "device_slot_cap": 4}})
        pool = node.deliver_lanes
        sup = node.supervisor
        assert pool.active()
        sup.note_fault("lane_deliver")      # breaker opens
        assert not sup.lanes_enabled()
        assert not pool.active()            # idle: inline is order-safe
        pool._live_plans = 1                # in-flight lane work
        assert pool.active()                # keep routing through lanes
        pool._live_plans = 0
        assert not pool.active()

    def test_lane_swap_mesh_gates_are_orthogonal_to_the_rung(self):
        sup = self._sup()
        sup.note_fault("lane_deliver")
        sup.note_fault("snapshot_swap")
        sup.note_fault("mesh_exchange")
        assert sup.rung() == S.RUNG_FULL
        assert not sup.lanes_enabled()
        assert not sup.rebuild_enabled()
        assert not sup.mesh_enabled()


# ---------- guard_task / spawn (the done-callback satellite) ----------

class TestTaskGuard:
    def test_guarded_death_is_logged_and_counted(self):
        node = Node(use_device=False)
        seen = []

        async def go():
            async def boom():
                raise RuntimeError("lane died")
            t = S.guard_task(asyncio.get_running_loop().create_task(
                boom()), "test-task", node.metrics,
                on_error=seen.append)
            await asyncio.sleep(0.05)
            assert t.done()
        before = S.task_error_count()
        run(go())
        assert S.task_error_count() == before + 1
        assert node.metrics.val("supervise.task_errors") == 1
        assert len(seen) == 1 and "lane died" in str(seen[0])

    def test_cancel_and_success_are_silent(self):
        node = Node(use_device=False)

        async def go():
            async def ok():
                return 1

            async def forever():
                await asyncio.sleep(60)
            t1 = S.guard_task(asyncio.get_running_loop().create_task(
                ok()), "t1", node.metrics)
            t2 = S.guard_task(asyncio.get_running_loop().create_task(
                forever()), "t2", node.metrics)
            await asyncio.sleep(0.02)
            t2.cancel()
            await asyncio.sleep(0.02)
            assert t1.done() and t2.cancelled()
        run(go())
        assert node.metrics.val("supervise.task_errors") == 0

    def test_spawn_holds_and_guards(self):
        node = Node(use_device=False)

        async def go():
            async def boom():
                raise ValueError("x")
            t = S.spawn(boom(), "spawned", node.metrics)
            assert t is not None
            await asyncio.sleep(0.05)
        run(go())
        assert node.metrics.val("supervise.task_errors") == 1

    def test_spawn_without_loop_closes_coro(self):
        async def never():
            raise AssertionError("must not run")
        assert S.spawn(never(), "no-loop") is None


# ---------- knob resolution + the A/B-off contract ----------

class TestKnob:
    def test_config_beats_env_beats_default(self, monkeypatch):
        monkeypatch.delenv("EMQX_TPU_SUPERVISE", raising=False)
        assert S.resolve_supervise(None) is True
        monkeypatch.setenv("EMQX_TPU_SUPERVISE", "0")
        assert S.resolve_supervise(None) is False
        assert S.resolve_supervise(True) is True    # config wins
        monkeypatch.setenv("EMQX_TPU_SUPERVISE", "1")
        assert S.resolve_supervise(False) is False

    def test_supervise_off_restores_pre_issue6_shape(self):
        node = Node({"broker": {"supervise": False,
                                "device_fanout_cap": 16,
                                "device_slot_cap": 4}})
        assert node.supervisor is None
        assert node.device_engine.sup is None
        if node.deliver_lanes is not None:
            assert node.deliver_lanes.sup is None
        assert node.publish_batcher.sup is None
        assert node.pipeline_telemetry.supervise_state_fn is None
        # and the old unwind still works: a consume error fails the
        # window's publishers (no replay machinery)
        s = Rec()
        sid = node.broker.register(s, "c1")
        node.broker.subscribe(sid, "t/+", {"qos": 1})

        async def go():
            return await node.publish_async(mkmsg("t/1"))
        assert run(go()) == 1
        assert "supervise" not in node.pipeline_telemetry.snapshot()

    def test_host_only_node_has_no_supervisor(self):
        assert Node(use_device=False).supervisor is None


# ---------- watchdog deadlines ----------

class TestWatchdogDeadline:
    def test_deadline_tracks_stage_p99(self):
        node = Node(use_device=False)
        sup = S.PipelineSupervisor(
            node.metrics, telemetry=node.pipeline_telemetry,
            injector=S.FaultInjector([]),
            watchdog_floor_s=0.1, watchdog_cap_s=10.0, watchdog_mult=4)
        # cold histogram: the floor holds
        assert sup.deadline("dispatch") == pytest.approx(0.1)
        for _ in range(100):
            node.pipeline_telemetry.observe_stage("dispatch", 0.2)
        d = sup.deadline("dispatch")
        # p99 of a 0.2s-dominated histogram is the 0.25-ish log2 bucket
        assert 0.4 <= d <= 4.0
        # the cap bounds a pathological history
        for _ in range(100):
            node.pipeline_telemetry.observe_stage("dispatch", 100.0)
        assert sup.deadline("dispatch") == 10.0


# ---------- the chaos injection matrix (the acceptance criterion) ----

@pytest.fixture(scope="module")
def twin():
    return CB.run_twin()


@pytest.fixture(scope="module")
def twin_delta():
    return CB.run_twin(delta=True)


@pytest.mark.chaos
class TestChaosMatrix:
    @pytest.mark.parametrize("point", CB.MATRIX_POINTS)
    @pytest.mark.parametrize("kind", S.FAULT_KINDS)
    def test_cell(self, point, kind, twin, twin_delta):
        case = CB.run_case(point, kind)
        oracle = twin_delta if point == "overlay_apply" else twin
        bad = CB.grade(case, oracle, point, kind)
        assert not bad, bad
        # hangs at watchdogged stages must be STALLS (tripped, not
        # wedged); raising kinds at pipeline stages must REPLAY
        if kind == "hang" and point in CB.WATCHDOGGED:
            assert case["stalls"] >= 1
        if kind in ("exception", "resource", "corrupt") \
                and point in ("dispatch", "materialize"):
            assert case["replays"] >= 1


@pytest.mark.chaos
class TestMeshChaos:
    def test_mesh_exchange_fault_replays_and_recovers(self):
        node = Node({"broker": {
            "multichip": {"enable": True, "devices": 2,
                          "max_batch": 64},
            "deliver_lanes": 0, "device_min_batch": 4,
            "batch_window_us": 2000, "supervise": True,
            "supervise_threshold": 1, "device_fanout_cap": 16,
            "device_slot_cap": 4}})
        sup = node.supervisor
        for br in sup.breakers.values():
            br.base_cooldown_s = br.cooldown_s = 0.05
        srv = node.device_engine
        b = node.broker
        sinks = {}
        for i in range(4):
            s = Rec()
            sid = b.register(s, f"c{i}")
            sinks[sid] = s
            b.subscribe(sid, f"t/{i}/+", {"qos": 1})
        srv.route_batch([mkmsg(f"t/{i}/w") for i in range(4)] * 2,
                        wait=True)
        import time as _time
        deadline = _time.monotonic() + 60
        while not srv.batch_class_warm(8) \
                and _time.monotonic() < deadline:
            srv._kick_class_warm()
            _time.sleep(0.05)
        assert srv.batch_class_warm(8), "mesh classes never warmed"
        sup.injector = S.FaultInjector(S.parse_faults(
            "mesh_exchange:exception:count=1"))

        async def go():
            outs = []
            for w in range(10):
                outs.extend(await asyncio.gather(*[
                    node.publish_async(mkmsg(f"t/{i}/x", b"m%d%d"
                                             % (w, i)))
                    for i in range(4) for _ in range(2)]))
                await asyncio.sleep(0.06)
                if sup.breakers["mesh_exchange"].state == "closed" \
                        and sup.injector.faults[0].fired:
                    break
            return outs
        outs = run(go(), timeout=180)
        assert all(c == 1 for c in outs)
        m = node.metrics
        assert m.val("supervise.faults.mesh_exchange") == 1
        assert sup.breakers["mesh_exchange"].state == "closed"
        assert m.val("messages.dropped") == 0


# ---------- lane-worker death + drain watchdog recovery ----------

class TestLaneRecovery:
    def test_dead_workers_revived_by_drain_watchdog_in_order(self):
        node = Node({"broker": {"deliver_lanes": 2,
                                "supervise_threshold": 8,
                                "device_fanout_cap": 16,
                                "device_slot_cap": 4}})
        sup = node.supervisor
        sup.wd_floor_s = 0.1
        sup.wd_mult = 0.0
        pool = node.deliver_lanes
        b = node.broker
        s = Rec()
        sid = b.register(s, "c1")      # even sid? force lane 0 rows
        lane_sid = sid if sid % 2 == 0 else sid + 0
        assert pool is not None

        async def go():
            pool.ensure_loop()
            pool.pause()
            msgs1 = [mkmsg("a/1", b"one")]
            msgs2 = [mkmsg("a/2", b"two")]
            p1 = pool.new_plan(msgs1)
            p1.register_fast([0])
            p1.add_rows_py(0, [(lane_sid, 0, "a/+")])
            pool.submit(p1)
            p2 = pool.new_plan(msgs2)
            p2.register_fast([0])
            p2.add_rows_py(0, [(lane_sid, 0, "a/+")])
            pool.submit(p2)
            await asyncio.sleep(0.05)   # workers hold plan1 at the gate
            for w in pool._workers:
                w.cancel()              # simulated worker death
            await asyncio.sleep(0.05)
            assert all(w.done() for w in pool._workers)
            pool.resume()
            # plan2's item is still queued with NO live worker: only the
            # drain watchdog's revival can complete it
            await pool.drain()
            return p1.done, p2.done
        d1, d2 = run(go(), timeout=30)
        assert d1 and d2
        m = node.metrics
        assert m.val("supervise.restarts") >= 1
        assert m.val("supervise.stalls.lane_deliver") >= 1
        # plan2's delivery survived the dead worker, in queue order
        assert (b"two" in [p for _f, _t, p in s.got])


# ---------- window journal ----------

class TestJournal:
    def test_depth_tracks_inflight_and_settles_to_zero(self):
        node = Node({"broker": {"deliver_lanes": 2,
                                "device_fanout_cap": 16,
                                "device_slot_cap": 4,
                                "device_min_batch": 4,
                                "batch_window_us": 1000}})
        sup = node.supervisor
        b = node.broker
        s = Rec()
        sid = b.register(s, "c1")
        b.subscribe(sid, "t/+", {"qos": 1})
        eng = node.device_engine
        eng.rebuild()

        async def go():
            eng._kick_class_warm()
            if eng._fuse_warm_task is not None:
                await eng._fuse_warm_task
            pool = node.deliver_lanes
            pool.ensure_loop()
            pool.pause()
            futs = [asyncio.ensure_future(
                node.publish_async(mkmsg(f"t/{i}"))) for i in range(8)]
            for _ in range(100):
                await asyncio.sleep(0.02)
                if sup.journal_depth() > 0 and pool.busy():
                    break
            depth_mid = sup.journal_depth()
            pool.resume()
            outs = await asyncio.gather(*futs)
            await pool.drain()
            return depth_mid, outs
        depth_mid, outs = run(go())
        assert depth_mid >= 1       # in-flight window was journaled
        assert outs == [1] * 8
        assert sup.journal_depth() == 0


# ---------- satellite: error paths that had no coverage ----------

class TestErrorPaths:
    def test_compact_overflow_concurrent_with_snapshot_swap(self):
        """A window whose payload class overflows (dense fallback) while
        a finished background rebuild waits on the handle pin: the
        overflow must not corrupt delivery, and the swap must apply the
        moment the handle releases."""
        node = Node({"broker": {"deliver_lanes": 0,
                                "device_fanout_cap": 64,
                                "device_slot_cap": 4}})
        b = node.broker
        sinks = []
        for i in range(30):
            s = Rec()
            sid = b.register(s, f"c{i}")
            sinks.append(s)
            b.subscribe(sid, "f/+", {"qos": 1 if i % 2 else 0})
        eng = node.device_engine
        eng.rebuild()
        eng.rebuild_threshold = 1
        # force the smallest payload class so 30-wide fan-out overflows
        eng._pay_ewma[64] = 4.0

        async def go():
            msgs = [mkmsg(f"f/{i}") for i in range(16)]
            h = eng.prepare(msgs, gate_cold=False)
            assert h is not None and h.pcap is not None
            old_sid = eng._built.sid
            # churn a BUILT filter past the threshold: a background
            # compaction starts while h pins the snapshot
            s2 = Rec()
            sid2 = b.register(s2, "late")
            b.subscribe(sid2, "f/+", {"qos": 0})
            assert eng.maybe_background_rebuild()
            for _ in range(600):
                if eng._pending_swap is not None:
                    break
                await asyncio.sleep(0.02)
            assert eng._pending_swap is not None   # gated by the pin
            assert eng._built.sid == old_sid
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, eng.dispatch, h)
            await loop.run_in_executor(None, eng.materialize, h)
            counts = eng.finish_sub(h, 0, defer=False)
            return old_sid, counts
        old_sid, counts = run(go(), timeout=180)
        # the dirty filter delivered host-side against live membership
        assert counts == [31] * 16
        assert node.metrics.val("routing.device.compact_overflow") >= 1
        # handle released -> the gated swap applied
        assert eng._built.sid != old_sid
        assert not eng._building

    def test_delta_overlay_overflow_racing_overlay_sync(self,
                                                        monkeypatch):
        """More delta filters than the overlay holds, with an overlay
        refresh racing an in-flight handle: the pinned version serves
        its rows, the uncovered tail host-routes, nothing is lost or
        double-delivered."""
        monkeypatch.setattr(DE, "_OVERLAY_MAX", 4)
        node = Node({"broker": {"deliver_lanes": 0,
                                "device_fanout_cap": 16,
                                "device_slot_cap": 4}})
        b = node.broker
        base = Rec()
        sid = b.register(base, "base")
        b.subscribe(sid, "t/+", {"qos": 1})
        eng = node.device_engine
        eng.rebuild()
        sinks = {}
        for i in range(6):          # 4 fit the overlay, 2 overflow
            s = Rec()
            dsid = b.register(s, f"d{i}")
            sinks[i] = s
            b.subscribe(dsid, f"d{i}/+", {"qos": 1})
        msgs = [mkmsg(f"d{i}/x") for i in range(6)] + [mkmsg("t/x")]
        h = eng.prepare(msgs, gate_cold=False)
        assert h is not None
        assert eng._overlay_uncovered == 2
        assert eng._compaction_reason() == "overflow"
        # race: churn + a fresh overlay version while h is in flight
        s7 = Rec()
        dsid7 = b.register(s7, "d7")
        b.subscribe(dsid7, "d7/+", {"qos": 1})
        eng._overlay_sync()
        eng.dispatch(h)
        eng.materialize(h)
        counts = eng.finish(h)
        assert counts == [1] * 7
        for i, s in sinks.items():
            assert [t for _f, t, _p in s.got] == [f"d{i}/x"]
        assert [t for _f, t, _p in base.got] == ["t/x"]
        assert node.metrics.val("routing.device.host_delta") >= 1

    def test_pool_drain_after_loop_teardown(self):
        """Plans stranded on a dead loop: a drain() from a NEW loop must
        finalize them (releasing pinned snapshot handles) and return —
        not hang on a wake event nobody can set."""
        node = Node({"broker": {"deliver_lanes": 2,
                                "device_fanout_cap": 16,
                                "device_slot_cap": 4}})
        b = node.broker
        s = Rec()
        sid = b.register(s, "c1")
        b.subscribe(sid, "t/+", {"qos": 1})
        eng = node.device_engine
        eng.rebuild()
        pool = node.deliver_lanes

        async def strand():
            pool.ensure_loop()
            pool.pause()
            msgs = [mkmsg(f"t/{i}") for i in range(4)]
            h = eng.prepare(msgs, gate_cold=False)
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, eng.dispatch, h)
            await loop.run_in_executor(None, eng.materialize, h)
            counts = eng.finish_sub(h, 0)   # defer=True: plan queued
            assert pool.busy()
            return counts
        run(strand())                        # loop A dies here
        assert eng._outstanding == 1         # handle pinned by the plan

        async def teardown_drain():
            await pool.drain()               # loop B
        run(teardown_drain(), timeout=30)
        assert not pool.busy()
        assert eng._outstanding == 0         # pin released: swaps free
        # stranded deliveries are LOST by contract (the loop died), but
        # accounted — never silently leaked
        assert node.metrics.val("messages.dropped.no_subscribers") >= 1


# ---------- satellite: task-hygiene static pass (tier-1 gate) ---------

class TestTaskHygiene:
    def test_flags_fire_and_forget(self):
        src = ("import asyncio\n"
               "async def f():\n"
               "    asyncio.create_task(g())\n"
               "    asyncio.ensure_future(g())\n")
        got = hygiene.check_source("x.py", src)
        assert [f.kind for f in got] == ["fire-and-forget"] * 2

    def test_accepts_held_or_guarded(self):
        src = ("import asyncio\n"
               "async def f():\n"
               "    t = asyncio.create_task(g())\n"
               "    ts.append(asyncio.ensure_future(g()))\n"
               "    await asyncio.create_task(g())\n"
               "    guard_task(asyncio.create_task(g()), 'n')\n")
        assert hygiene.check_source("x.py", src) == []

    def test_flags_commentless_except_pass(self):
        src = ("try:\n    f()\nexcept Exception:\n    pass\n")
        got = hygiene.check_source("x.py", src)
        assert [f.kind for f in got] == ["except-pass"]
        ok = ("try:\n    f()\n"
              "except Exception:  # noqa: BLE001 — best-effort close\n"
              "    pass\n")
        assert hygiene.check_source("x.py", ok) == []
        narrow = ("try:\n    f()\nexcept ValueError:\n    pass\n")
        assert hygiene.check_source("x.py", narrow) == []

    def test_repo_is_clean(self):
        """The tier-1 gate: emqx_tpu/ has zero hygiene findings."""
        root = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "emqx_tpu")
        findings = hygiene.check(root)
        assert findings == [], "\n".join(map(repr, findings))


# ---------- telemetry: the supervise section + exporters ----------

class TestSuperviseTelemetry:
    def test_snapshot_section(self):
        node = Node({"broker": {"device_fanout_cap": 16,
                                "device_slot_cap": 4}})
        sup = node.supervisor
        assert sup is not None
        sup.note_fault("dispatch")
        sup.note_stall("materialize")
        sup.note_replay()
        snap = node.pipeline_telemetry.snapshot()["supervise"]
        assert snap["faults"] == 2          # fault + stall's fault
        assert snap["replays"] == 1
        assert snap["stalls"] == 1
        assert snap["faults_by_point"] == {"dispatch": 1,
                                           "materialize": 1}
        assert snap["stalls_by_stage"] == {"materialize": 1}
        st = snap["state"]
        assert st["rung"] == 0
        assert set(st["breakers"]) == set(S.FAULT_POINTS)
        assert st["journal_depth"] == 0
        assert "watchdog" in st

    def test_prometheus_carries_supervise_counters(self):
        from emqx_tpu.apps.prometheus import collect
        node = Node({"broker": {"device_fanout_cap": 16,
                                "device_slot_cap": 4}})
        node.supervisor.note_fault("dispatch")
        text = collect(node)
        assert "emqx_supervise_faults" in text
        assert "emqx_supervise_faults_dispatch" in text

    def test_sys_publishes_supervise_section(self):
        from emqx_tpu.apps.sys import SysBroker
        node = Node({"broker": {"device_fanout_cap": 16,
                                "device_slot_cap": 4}})
        node.supervisor.note_fault("dispatch")
        published = {}
        app = SysBroker(node)
        app._pub = lambda topic, payload: published.update(
            {topic: payload})
        app.publish_pipeline()
        assert "pipeline/supervise" in published
        doc = json.loads(published["pipeline/supervise"])
        assert doc["faults"] == 1


# ---------- bench checkpoint (resumable phase ladder satellite) -------

class TestBenchCheckpoint:
    def _bench(self):
        import importlib.util
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "bench.py")
        spec = importlib.util.spec_from_file_location("bench_mod", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_roundtrip_and_sig_guard(self, tmp_path, monkeypatch):
        bench = self._bench()
        ck = tmp_path / "ckpt.json"
        monkeypatch.setenv("BENCH_CHECKPOINT", str(ck))
        monkeypatch.delenv("BENCH_RESUME", raising=False)
        sig = {"subs": 100, "batch": 8, "window": 2, "shared_pct": 0}
        phases = {}
        bench._ckpt_put("phase0", {"value": 42}, sig, phases)
        bench._ckpt_put("core@100", {"value": 7}, sig, phases)
        assert ck.exists()
        got = bench._ckpt_load(sig)
        assert got == {"phase0": {"value": 42}, "core@100": {"value": 7}}
        # a different config signature must NOT resume
        assert bench._ckpt_load(dict(sig, subs=999)) == {}
        # BENCH_RESUME=0 starts fresh
        monkeypatch.setenv("BENCH_RESUME", "0")
        assert bench._ckpt_load(sig) == {}
        monkeypatch.delenv("BENCH_RESUME")
        bench._ckpt_clear()
        assert not ck.exists()
        assert bench._ckpt_load(sig) == {}

    def test_corrupt_checkpoint_starts_fresh(self, tmp_path,
                                             monkeypatch):
        bench = self._bench()
        ck = tmp_path / "ckpt.json"
        ck.write_text("{half a json")
        monkeypatch.setenv("BENCH_CHECKPOINT", str(ck))
        monkeypatch.delenv("BENCH_RESUME", raising=False)
        assert bench._ckpt_load({"subs": 1}) == {}
