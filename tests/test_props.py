"""Property-based suites (hypothesis) mirroring the reference's PropEr
props (apps/emqx/test/props/: prop_emqx_frame, prop_emqx_reason_codes,
prop_emqx_psk — SURVEY §4 "Property-based" row). Hypothesis plays PropEr's
role: generative packets with shrinking, plus a parser-totality fuzz the
randomized tests can't express.
"""

import binascii

import pytest
from hypothesis import HealthCheck, given, settings
from hypothesis import strategies as st

from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt.frame import FrameError, FrameParser, serialize
from emqx_tpu.mqtt.packet import (Auth, Connect, Disconnect, Puback, Publish,
                                  SubOpts, Subscribe, Unsubscribe, Will)

SETTLE = settings(max_examples=120, deadline=None,
                  suppress_health_check=[HealthCheck.too_slow])

# MQTT UTF-8 strings: no NUL, bounded size
mqtt_text = st.text(
    alphabet=st.characters(blacklist_characters="\x00",
                           blacklist_categories=("Cs",)),
    min_size=1, max_size=24)
payloads = st.binary(max_size=256)
packet_ids = st.integers(min_value=1, max_value=0xFFFF)


@st.composite
def publishes(draw, version):
    qos = draw(st.integers(0, 2))
    props = {}
    if version == C.MQTT_V5:
        props = draw(st.fixed_dictionaries(
            {}, optional={
                "message_expiry_interval": st.integers(0, 2**32 - 1),
                "content_type": mqtt_text,
                "payload_format_indicator": st.integers(0, 1),
                "user_property": st.lists(
                    st.tuples(mqtt_text, mqtt_text), max_size=3),
            }))
        if props.get("user_property") == []:
            del props["user_property"]
    return Publish(
        topic=draw(mqtt_text), payload=draw(payloads), qos=qos,
        packet_id=draw(packet_ids) if qos else None,
        retain=draw(st.booleans()),
        dup=draw(st.booleans()) and qos > 0,
        properties=props)


@st.composite
def connects(draw):
    ver = draw(st.sampled_from([C.MQTT_V3, C.MQTT_V4, C.MQTT_V5]))
    will = None
    if draw(st.booleans()):
        will = Will(topic=draw(mqtt_text), payload=draw(payloads),
                    qos=draw(st.integers(0, 2)), retain=draw(st.booleans()))
    return Connect(
        proto_ver=ver,
        proto_name="MQIsdp" if ver == C.MQTT_V3 else "MQTT",
        clientid=draw(mqtt_text),
        keepalive=draw(st.integers(0, 0xFFFF)),
        clean_start=draw(st.booleans()),
        username=draw(st.none() | mqtt_text),
        password=draw(st.none() | payloads.filter(bool)),
        will=will)


@st.composite
def subscribes(draw):
    n = draw(st.integers(1, 5))
    return Subscribe(
        packet_id=draw(packet_ids),
        filters=[
            (draw(mqtt_text),
             SubOpts(qos=draw(st.integers(0, 2)),
                     nl=draw(st.integers(0, 1)),
                     rap=draw(st.integers(0, 1)),
                     rh=draw(st.integers(0, 2))))
            for _ in range(n)])


def _roundtrip(pkt, version):
    wire = serialize(pkt, version)
    p = FrameParser(version=None if pkt.type == C.CONNECT else version)
    out = p.feed(wire)
    assert len(out) == 1 and p.pending_bytes == 0
    return out[0]


class TestFrameProps:
    """prop_emqx_frame: serialize → parse == identity, any chunking."""

    @SETTLE
    @given(pkt=publishes(C.MQTT_V4))
    def test_publish_v4(self, pkt):
        assert _roundtrip(pkt, C.MQTT_V4) == pkt

    @SETTLE
    @given(pkt=publishes(C.MQTT_V5))
    def test_publish_v5(self, pkt):
        assert _roundtrip(pkt, C.MQTT_V5) == pkt

    @SETTLE
    @given(pkt=connects())
    def test_connect(self, pkt):
        assert _roundtrip(pkt, pkt.proto_ver) == pkt

    @SETTLE
    @given(pkt=subscribes())
    def test_subscribe_v5(self, pkt):
        assert _roundtrip(pkt, C.MQTT_V5) == pkt

    @SETTLE
    @given(packet_id=packet_ids, rc=st.sampled_from([0, 16, 128, 131]))
    def test_puback_v5(self, packet_id, rc):
        pkt = Puback(packet_id=packet_id, reason_code=rc)
        assert _roundtrip(pkt, C.MQTT_V5) == pkt

    @SETTLE
    @given(filters=st.lists(mqtt_text, min_size=1, max_size=4),
           packet_id=packet_ids)
    def test_unsubscribe(self, filters, packet_id):
        pkt = Unsubscribe(packet_id=packet_id, filters=filters)
        assert _roundtrip(pkt, C.MQTT_V5) == pkt

    @SETTLE
    @given(rc=st.sampled_from([0, 4, 129, 142, 152]))
    def test_disconnect_v5(self, rc):
        pkt = Disconnect(reason_code=rc)
        assert _roundtrip(pkt, C.MQTT_V5) == pkt

    @SETTLE
    @given(rc=st.sampled_from([0, 24, 25]), data=payloads)
    def test_auth(self, rc, data):
        props = {"authentication_method": "SCRAM-SHA-256",
                 "authentication_data": data} if data else {}
        pkt = Auth(reason_code=rc, properties=props)
        assert _roundtrip(pkt, C.MQTT_V5) == pkt

    @SETTLE
    @given(pkts=st.lists(publishes(C.MQTT_V4), min_size=1, max_size=8),
           data=st.data())
    def test_stream_chunking(self, pkts, data):
        """Any fragmentation of a valid stream parses to the same packets."""
        wire = b"".join(serialize(p, C.MQTT_V4) for p in pkts)
        parser = FrameParser(version=C.MQTT_V4)
        got, i = [], 0
        while i < len(wire):
            n = data.draw(st.integers(1, max(1, len(wire) - i)))
            got += parser.feed(wire[i:i + n])
            i += n
        assert got == pkts and parser.pending_bytes == 0


class TestParserTotality:
    """The parser is TOTAL over arbitrary bytes: any input yields packets
    or FrameError — never another exception, never an infinite loop.
    (The reference gets this from PropEr generators + fuzzing; it is the
    internet-facing surface.)"""

    @SETTLE
    @given(junk=st.binary(min_size=1, max_size=512),
           version=st.sampled_from([C.MQTT_V4, C.MQTT_V5, None]))
    def test_arbitrary_bytes(self, junk, version):
        p = FrameParser(version=version)
        try:
            p.feed(junk)
        except FrameError:
            pass

    @SETTLE
    @given(pkt=publishes(C.MQTT_V4),
           flips=st.lists(st.tuples(st.integers(0, 10**6),
                                    st.integers(1, 255)), max_size=3))
    def test_bitflipped_frames(self, pkt, flips):
        wire = bytearray(serialize(pkt, C.MQTT_V4))
        for pos, x in flips:
            wire[pos % len(wire)] ^= x
        p = FrameParser(version=C.MQTT_V4)
        try:
            p.feed(bytes(wire))
        except FrameError:
            pass


class TestReasonCodeProps:
    """prop_emqx_reason_codes: compat mapping is total over v5 codes and
    idempotent (a v3 code maps to itself)."""

    @SETTLE
    @given(rc=st.integers(0, 0xFF))
    def test_total_and_v3_valued(self, rc):
        v3 = C.rc_to_connack_v3(rc)
        assert 0 <= v3 <= 5

    @SETTLE
    @given(rc=st.integers(0, 0xFF))
    def test_idempotent(self, rc):
        once = C.rc_to_connack_v3(rc)
        assert C.rc_to_connack_v3(once) == once


class TestPskProps:
    """prop_emqx_psk: the identity:hexkey file format round-trips through
    the store for any identities/keys."""

    ident = st.text(
        alphabet=st.characters(blacklist_characters="\x00:\r\n#",
                               blacklist_categories=("Cs", "Zs")),
        min_size=1, max_size=16).map(str.strip).filter(bool)

    @SETTLE
    @given(entries=st.dictionaries(
        ident, st.binary(min_size=1, max_size=32), min_size=1, max_size=8))
    def test_file_roundtrip(self, entries):
        import tempfile

        from emqx_tpu.utils.psk import PskStore
        lines = ["# psk file"]
        for ident, key in entries.items():
            lines.append(f"{ident}:{binascii.hexlify(key).decode()}")
        with tempfile.NamedTemporaryFile("w", suffix=".psk",
                                         delete=False) as f:
            f.write("\n".join(lines) + "\n")
            path = f.name
        store = PskStore()
        assert store.load_file(path) == len(entries)
        for ident, key in entries.items():
            assert store.lookup(ident) == key


_hocon_keys = st.text(alphabet="abcdefghijklmnop_", min_size=1, max_size=8)
_hocon_leaf = (st.integers(-2**31, 2**31) | st.booleans()
               | st.text(alphabet=st.characters(
                   blacklist_characters="\x00\\\"\r\n$",
                   blacklist_categories=("Cs",)), max_size=16))
_hocon_trees = st.recursive(
    _hocon_leaf,
    lambda children: (st.dictionaries(_hocon_keys, children, max_size=4)
                      | st.lists(children, max_size=4)),
    max_leaves=12)


class TestHoconProps:
    """HOCON-lite dumps → loads == identity over config-shaped trees
    (the loader is layer-0 boot infrastructure; prop_emqx_json analog)."""

    @SETTLE
    @given(conf=st.dictionaries(_hocon_keys, _hocon_trees, max_size=4))
    def test_dumps_loads_identity(self, conf):
        from emqx_tpu.utils import hocon
        text = hocon.dumps(conf)
        assert hocon.loads(text) == conf


class TestStoreReplicationProps:
    """Replica convergence of the single-writer op log (cluster/store.py)
    under adversarial delivery: arbitrary reordering, duplication, and
    stragglers from dead incarnations. The invariant everything else
    (routes, shared groups, banned) rests on: once every op of the
    LATEST incarnation is delivered — in any order, interleaved with any
    garbage from older incarnations — the replica's view of that origin
    equals the origin's own sequential state."""

    @staticmethod
    def _mk_store():
        import asyncio

        from emqx_tpu.cluster.store import ClusterStore

        class _Rpc:
            node = "replica@x"

            def register(self, *_a):
                pass

        class _Membership:
            def monitor(self, *_a):
                pass

            def other_nodes(self):
                return []

        return ClusterStore(_Rpc(), _Membership()), asyncio

    @staticmethod
    def _model_apply(ops):
        """Sequentially apply [(op, key, value)] the way a bag table
        does: add dedups, del removes one instance."""
        state: dict = {}
        for op, key, value in ops:
            vals = state.setdefault(key, [])
            if op == "add":
                if value not in vals:
                    vals.append(value)
            elif value in vals:
                vals.remove(value)
            if not vals:
                state.pop(key, None)
        return state

    @settings(max_examples=60, deadline=None,
              suppress_health_check=[HealthCheck.too_slow])
    @given(
        ops1=st.lists(st.tuples(st.sampled_from(["add", "del"]),
                                st.sampled_from(["k1", "k2", "k3"]),
                                st.integers(0, 4)), max_size=12),
        ops2=st.lists(st.tuples(st.sampled_from(["add", "del"]),
                                st.sampled_from(["k1", "k2", "k3"]),
                                st.integers(0, 4)), max_size=12),
        seed=st.integers(0, 2**32 - 1),
        dup_frac=st.floats(0, 1),
    )
    def test_converges_despite_reorder_dup_stragglers(
            self, ops1, ops2, seed, dup_frac):
        def as_singles(store, origin, inc, ops):
            return [(store._h_op, (origin, inc, i + 1, op, "t", k, v))
                    for i, (op, k, v) in enumerate(ops)]

        store, origin, want = self._drive_adversarial(
            ops1, ops2, seed, dup_frac, as_singles)
        if ops1 or ops2:
            assert store._applied[origin] == (len(ops2) if ops2
                                              else len(ops1))

    @classmethod
    def _drive_adversarial(cls, ops1, ops2, seed, dup_frac, to_msgs):
        """Shared scaffold: deliver inc1 fully (any prefix state is fine
        — it gets purged on restart), then a shuffled mix of ALL inc2
        messages, duplicated inc2 messages, and straggler inc1 messages.
        `to_msgs(store, origin, inc, ops)` sets the delivery shape
        (single frames or op_batch chunks). Returns (store, origin,
        expected latest-incarnation model state)."""
        import random

        store, asyncio = cls._mk_store()
        origin, inc1, inc2 = "n1@x", 1000, 2000
        rng = random.Random(seed)
        mix = to_msgs(store, origin, inc2, ops2)[:]
        mix += [m for m in to_msgs(store, origin, inc2, ops2)
                if rng.random() < dup_frac]
        mix += [m for m in to_msgs(store, origin, inc1, ops1)
                if rng.random() < 0.5]
        rng.shuffle(mix)

        async def drive():
            for fn, args in to_msgs(store, origin, inc1, ops1):
                await fn(*args)
            for fn, args in mix:
                await fn(*args)

        asyncio.run(drive())
        # no inc2 ops ever sent: the replica legitimately still holds
        # inc1's state (a restart is only observable via its ops)
        want = cls._model_apply(ops2) if ops2 else cls._model_apply(ops1)
        cls._assert_converged(store, origin, want)
        return store, origin, want

    @staticmethod
    def _assert_converged(store, origin, want):
        got = {k: per[origin]
               for k, per in store.table("t").rows.items()
               if origin in per}
        assert {k: sorted(v) for k, v in got.items()} \
            == {k: sorted(v) for k, v in want.items()}

    @settings(max_examples=60, deadline=None,
              suppress_health_check=[HealthCheck.too_slow])
    @given(
        ops1=st.lists(st.tuples(st.sampled_from(["add", "del"]),
                                st.sampled_from(["k1", "k2", "k3"]),
                                st.integers(0, 4)), max_size=16),
        ops2=st.lists(st.tuples(st.sampled_from(["add", "del"]),
                                st.sampled_from(["k1", "k2", "k3"]),
                                st.integers(0, 4)), max_size=16),
        chunk=st.integers(1, 6),
        seed=st.integers(0, 2**32 - 1),
        dup_frac=st.floats(0, 1),
    )
    def test_batched_ops_converge_like_singles(self, ops1, ops2, chunk,
                                               seed, dup_frac):
        """store.op_batch (round-5 bulk replication) under the same
        adversarial delivery as singles: shuffled/duplicated CHUNKS and
        chunks from a dead incarnation — the replica converges to the
        latest incarnation's sequential state, and the O(1) count
        matches the model. (The in-batch restart-abort guard needs a
        >1024-op batch and is covered by
        test_batch_aborts_on_restart_mid_yield.)"""
        def as_batches(store, origin, inc, ops):
            items = [[i + 1, op, "t", k, v]
                     for i, (op, k, v) in enumerate(ops)]
            return [(store._h_op_batch,
                     (origin, inc, items[i:i + chunk]))
                    for i in range(0, len(items), chunk)]

        store, origin, want = self._drive_adversarial(
            ops1, ops2, seed, dup_frac, as_batches)
        assert store.table("t").count() == \
            sum(len(v) for v in want.values())

    def test_batch_aborts_on_restart_mid_yield(self):
        """The in-batch restart guard (store.py _h_op_batch: re-check
        the origin's incarnation after each 1024-op yield): a newer
        incarnation landing DURING a large batch's yield must abort the
        rest of the stale batch — otherwise dead-incarnation rows
        repopulate the freshly-reset seq buffer and later apply as live
        state."""
        import asyncio as aio

        store, _ = self._mk_store()
        origin = "n1@x"
        big = [[i + 1, "add", "t", f"k{i}", 0] for i in range(2048)]

        async def drive():
            task = aio.create_task(store._h_op_batch(origin, 1000, big))
            await aio.sleep(0)      # let it start and hit the yield
            # pin the interleave: the batch must have PARTIALLY applied
            # (reached its first 1024-op yield) before the restart —
            # otherwise the whole batch would be dropped at entry and
            # the mid-yield guard would go untested
            assert store._applied[origin] >= 1024, \
                store._applied.get(origin)
            # restart: newer incarnation's first op purges + resets
            await store._h_op(origin, 2000, 1, "add", "t", "fresh", 7)
            await task

        aio.run(drive())
        tab = store.table("t")
        # nothing from the stale batch may survive the restart purge,
        # and nothing may sit buffered at old seqs waiting to re-apply
        assert tab.lookup("fresh") == [(origin, 7)]
        assert tab.count() == 1, tab.count()
        assert not store._buffer.get(origin), store._buffer.get(origin)
        assert store._origin_inc[origin] == 2000
