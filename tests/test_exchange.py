"""Device-to-device sharded exchange stage (ISSUE 15).

Acceptance criteria, as tests:

- **Bit-identity A/B twin**: exchange on vs broker.device_exchange=0
  (host gather/merge) must produce identical delivery COUNTS and
  identical PER-SESSION delivery order, across mesh sizes 2/4/8 ×
  {clean traffic, shared groups, dirty shards at consume, churn
  mid-window, segment-capacity overflow} — every fallback rung must be
  invisible to subscribers.
- **Chaos**: an injected `mesh_exchange` fault mid-window replays
  through the host rung with zero QoS>=1 loss and the breaker
  re-closes; a dead ring (the exchange program itself raising)
  degrades THAT window to host gather without losing it.
- **Twin-selection tier-1 gate**: ops.pallas_exchange imports on every
  backend and selects the ppermute twin off-TPU (the Mosaic kernel is
  exercised by the slow-marked hardware smoke below).
- **Knob**: EMQX_TPU_EXCHANGE / broker.device_exchange=0 leaves no
  exchange aux, no exchange program, no pipeline.exchange.* traffic.
"""

import asyncio

import numpy as np
import pytest

import jax

from emqx_tpu.broker import supervise as S
from emqx_tpu.broker.message import make
from emqx_tpu.broker.node import Node

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


def run(coro, timeout=180):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


def mkmsg(topic, payload=b"x", qos=0):
    return make("pub", qos, topic, payload)


class Rec:
    """One subscriber session: records its delivery sequence."""

    def __init__(self):
        self.got = []

    def deliver(self, topic_filter, msg):
        self.got.append((topic_filter, msg.topic, bytes(msg.payload)))
        return True


def _mk_node(devices, dp, *, exchange, max_batch=16, lanes=0,
             extra=None):
    conf = {"broker": {"multichip": {"enable": True, "devices": devices,
                                     "dp": dp, "max_batch": max_batch},
                       "device_min_batch": 1, "deliver_lanes": lanes,
                       "device_exchange": 1 if exchange else 0}}
    if extra:
        conf["broker"].update(extra)
    return Node(conf)


def _subscribe(node, spec):
    """spec: [(client, filter, opts)] — one Rec per distinct client,
    subscribed (possibly to several filters, spread over shards)."""
    recs = {}
    broker = node.broker
    for client, f, opts in spec:
        if client not in recs:
            recs[client] = (Rec(), None)
            sid = broker.register(recs[client][0], client)
            recs[client] = (recs[client][0], sid)
        broker.subscribe(recs[client][1], f, dict(opts) if opts else None)
    return {c: r for c, (r, _sid) in recs.items()}


# one client on several filters (different shards) + fan-out filters
# with many clients: the per-session interleaving actually has content
_SPEC = ([("multi", "ab/+", 0), ("multi", "ab/x", 0),
          ("multi", "ab/#", 0), ("multi", "+/x", 0)]
         + [(f"fan{i}", "hot/+", 0) for i in range(6)]
         + [(f"solo{i}", f"solo/t{i}", 0) for i in range(6)])

_TOPICS = (["ab/x", "hot/1", "solo/t3", "ab/y", "nomatch/z", "hot/2"]
           + [f"solo/t{i}" for i in range(6)] + ["ab/x", "q/x"])


def _route(node, topics, wait=True):
    eng = node.device_engine
    msgs = [mkmsg(t, ("p%d" % i).encode()) for i, t in enumerate(topics)]
    counts = eng.route_batch(msgs, wait=wait)
    assert counts is not None
    return counts


class TestBitIdentityAB:
    """Exchange on vs off: identical counts AND per-session order."""

    @pytest.mark.parametrize("devices,dp", [(2, 1), (4, 2), (8, 2)])
    def test_clean_traffic(self, devices, dp):
        results = {}
        for mode in (True, False):
            node = _mk_node(devices, dp, exchange=mode)
            recs = _subscribe(node, _SPEC)
            eng = node.device_engine
            eng.rebuild()
            if mode:
                assert eng.warm_exchange(len(_TOPICS)), \
                    (eng._exch_warm, eng._wanted_ecap)
            # warm-up + segment-class adaptation (a small ring can
            # overflow the cold class — the EWMA then grows it); both
            # modes see the SAME warm-up traffic, captures cleared
            for _ in range(3):
                _route(node, _TOPICS)
                if not mode or \
                        node.metrics.val("pipeline.exchange.windows"):
                    break
                eng.warm_exchange(len(_TOPICS))
            for r in recs.values():
                r.got.clear()
            before = node.metrics.val("pipeline.exchange.windows")
            counts = _route(node, _TOPICS)
            counts2 = _route(node, list(reversed(_TOPICS)))
            if mode:
                assert node.metrics.val("pipeline.exchange.windows") \
                    >= before + 2, node.metrics.all()
            else:
                assert node.metrics.val("pipeline.exchange.windows") == 0
                assert eng.aux is None
            results[mode] = (counts, counts2,
                             {c: list(r.got) for c, r in recs.items()})
        on, off = results[True], results[False]
        assert on[0] == off[0] and on[1] == off[1]
        assert on[2] == off[2], (on[2], off[2])

    def test_shared_groups_fall_back_identically(self):
        results = {}
        spec = _SPEC + [(f"sh{i}", "$share/g/ab/+", 0) for i in range(3)]
        for mode in (True, False):
            node = _mk_node(8, 2, exchange=mode)
            recs = _subscribe(node, spec)
            eng = node.device_engine
            eng.rebuild()
            if mode:
                eng.warm_exchange(len(_TOPICS))
            counts = _route(node, _TOPICS)
            if mode:
                # a shared-slot hit is device-flagged unclean: the
                # window gathers, subscribers can't tell
                assert node.metrics.val(
                    "pipeline.exchange.fallback.unclean") >= 1
            results[mode] = (counts,
                             {c: list(r.got) for c, r in recs.items()})
        assert results[True] == results[False]

    def test_dirty_shards_at_consume_fall_back_identically(self):
        """Churn marks landing between dispatch and consume: the
        exchange-landed window must re-land dense (late fallback) and
        deliver exactly like the gather twin under the same churn."""
        results = {}
        for mode in (True, False):
            node = _mk_node(8, 2, exchange=mode)
            recs = _subscribe(node, _SPEC)
            eng = node.device_engine
            eng.rebuild()
            if mode:
                eng.warm_exchange(4)
                eng.warm_exchange(len(_TOPICS))
            _route(node, _TOPICS[:4])       # same warm-up both modes
            if mode:
                assert node.metrics.val("pipeline.exchange.windows") >= 1
            msgs = [mkmsg(t, b"late") for t in _TOPICS]
            h = eng.prepare(msgs)
            assert h is not None
            eng.dispatch(h)
            eng.materialize(h)
            # churn lands after materialize: consume must not trust the
            # snapshot's clean masks
            late = Rec()
            node.broker.subscribe(node.broker.register(late, "late"),
                                  "ab/+")
            assert eng.dirty_shards
            counts = eng.finish(h)
            if mode:
                assert node.metrics.val(
                    "pipeline.exchange.fallback.late") >= 1
            # drain the dirty marks for a deterministic end state
            assert eng.poll_rebuild()
            results[mode] = (counts, list(late.got),
                             {c: list(r.got) for c, r in recs.items()})
        assert results[True] == results[False]

    def test_churn_mid_stream_identical(self):
        """Subscribe bursts between batches (the per-shard update path)
        with exchange on vs off: same counts, same sequences."""
        results = {}
        for mode in (True, False):
            node = _mk_node(8, 2, exchange=mode)
            recs = _subscribe(node, _SPEC)
            eng = node.device_engine
            eng.rebuild()
            if mode:
                eng.warm_exchange(len(_TOPICS))
            seq = []
            added = {}
            for rnd in range(3):
                seq.append(_route(node, _TOPICS))
                r = Rec()
                added[f"ch{rnd}"] = r
                node.broker.subscribe(
                    node.broker.register(r, f"ch{rnd}"),
                    f"solo/t{rnd}")
            seq.append(_route(node, _TOPICS))
            results[mode] = (seq,
                             {c: list(r.got) for c, r in recs.items()},
                             {c: list(r.got) for c, r in added.items()})
        assert results[True] == results[False]

    def test_segment_overflow_falls_back_identically(self):
        """More rows to one delivery shard than the capacity class
        holds: the window must gather (counted) and deliver
        identically; the EWMA then grows the class."""
        spec = [(f"big{i}", "hot/+", 0) for i in range(80)]
        topics = ["hot/a"] * 4
        results = {}
        for mode in (True, False):
            node = _mk_node(8, 2, exchange=mode)
            recs = _subscribe(node, spec)
            eng = node.device_engine
            eng.rebuild()
            if mode:
                eng.warm_exchange(len(topics))
                assert eng._choose_ecap(eng._batch_class(
                    len(topics))) == 16   # 80 rows over 4 dests won't fit
            counts = _route(node, topics)
            if mode:
                assert node.metrics.val("pipeline.exchange.overflow") \
                    >= 1
                assert node.metrics.val("pipeline.exchange.windows") == 0
                # the miss taught the ladder: next class fits
                assert eng._choose_ecap(eng._batch_class(
                    len(topics))) > 16
            results[mode] = (counts,
                             {c: list(r.got) for c, r in recs.items()})
        assert results[True] == results[False]

    def test_lanes_preserve_per_session_order(self):
        """The delivery-lane path (plan.add_rows chunks): per-session
        sequences identical between exchange and gather."""
        results = {}
        for mode in (True, False):
            node = _mk_node(8, 2, exchange=mode, lanes=2,
                            extra={"batch_window_us": 1000})
            recs = _subscribe(node, _SPEC)
            eng = node.device_engine
            eng.rebuild()
            # warm the base batch classes in BOTH modes (a cold class
            # host-routes the window — a different order source than
            # the device path, and not what this test compares)
            eng._warm_one(2)
            eng._warm_one(4)
            if mode:
                eng.warm_exchange(2)
                eng.warm_exchange(4)

            async def go():
                for w in range(4):
                    await asyncio.gather(*[
                        node.publish_async(mkmsg(
                            t, b"w%d" % w, qos=1))
                        for t in ("ab/x", "hot/1", "solo/t0",
                                  "ab/y")])
                pool = node.deliver_lanes
                if pool is not None:
                    await pool.drain()
            run(go())
            if mode:
                assert node.metrics.val("pipeline.exchange.windows") \
                    >= 1
            results[mode] = {c: list(r.got) for c, r in recs.items()}
        assert results[True] == results[False]


class TestExchangeChaos:
    @pytest.mark.chaos
    def test_mid_ring_fault_replays_through_host_rung(self):
        """Injected mesh_exchange fault while exchange serves: the
        window replays through the host rung — zero QoS>=1 loss — and
        after the half-open probe the breaker re-closes and exchange
        windows resume."""
        node = _mk_node(8, 2, exchange=True, lanes=0,
                        extra={"supervise": True,
                               "supervise_threshold": 1,
                               "batch_window_us": 1000})
        sup = node.supervisor
        for br in sup.breakers.values():
            br.base_cooldown_s = br.cooldown_s = 0.05
        recs = _subscribe(node, _SPEC)
        eng = node.device_engine
        eng.rebuild()
        assert eng.warm_exchange(8)
        _route(node, ["ab/x"] * 8)
        assert node.metrics.val("pipeline.exchange.windows") >= 1
        sup.injector = S.FaultInjector(S.parse_faults(
            "mesh_exchange:exception:count=1"))

        async def go():
            outs = []
            import time as _t
            deadline = _t.monotonic() + 60
            while _t.monotonic() < deadline:
                outs.extend(await asyncio.gather(*[
                    node.publish_async(mkmsg("ab/x", b"c%d" % i,
                                             qos=1))
                    for i in range(8)]))
                await asyncio.sleep(0.05)
                if sup.breakers["mesh_exchange"].state == "closed" \
                        and sup.injector.faults[0].fired:
                    break
            return outs
        outs = run(go())
        assert sup.injector.faults[0].fired
        assert all(c >= 1 for c in outs)       # zero QoS1 loss
        assert node.metrics.val("messages.dropped") == 0
        assert sup.breakers["mesh_exchange"].state == "closed"
        # exchange serves again after recovery
        before = node.metrics.val("pipeline.exchange.windows")
        _route(node, ["ab/x"] * 8)
        assert node.metrics.val("pipeline.exchange.windows") > before

    def test_dead_ring_degrades_to_host_gather(self):
        """The exchange program itself dying (a dead ring, not an
        injected control fault) must cost only the exchange: the window
        lands via host gather, nothing is lost, the fault is counted
        against the mesh_exchange breaker."""
        node = _mk_node(8, 2, exchange=True, lanes=0,
                        extra={"supervise": True,
                               "supervise_threshold": 3})
        recs = _subscribe(node, _SPEC)
        eng = node.device_engine
        eng.rebuild()
        assert eng.warm_exchange(len(_TOPICS))
        baseline = _route(node, _TOPICS)

        Bp = eng._batch_class(len(_TOPICS))
        E = eng._choose_ecap(Bp)

        def dead_ring(*a, **k):
            raise RuntimeError("ring down")

        eng._exch_steps[E] = dead_ring
        counts = _route(node, _TOPICS)
        assert counts == baseline       # nothing lost to the dead ring
        m = node.metrics
        assert m.val("pipeline.exchange.fallback.error") >= 1
        assert m.val("supervise.faults.mesh_exchange") >= 1
        # consecutive ring faults ACCUMULATE (the step's success must
        # not reset the domain's count) — at threshold 3 the breaker
        # trips, shedding the mesh to the host rung with zero loss
        sup = node.supervisor
        counts2 = _route(node, _TOPICS)
        counts3 = _route(node, _TOPICS)
        assert counts2 == baseline and counts3 == baseline
        assert m.val("supervise.faults.mesh_exchange") >= 3
        assert sup.breakers["mesh_exchange"].state == "open"


class TestTwinSelectionGate:
    """Tier-1 gate: the kernel module must import everywhere and the
    portable twin must serve non-TPU backends."""

    def test_module_imports_and_selects_twin(self):
        from emqx_tpu.ops import pallas_exchange as PX
        assert PX.exchange_rotate_impl("cpu") == "ppermute"
        assert PX.exchange_rotate_impl("gpu") == "ppermute"
        assert PX.exchange_rotate_impl("tpu") == "pallas"
        if jax.default_backend() != "tpu":
            assert PX.exchange_rotate_impl() == "ppermute"

    def test_ring_rotate_matches_roll_oracle(self):
        """The ppermute twin over the 'route' ring == np.roll on the
        stacked blocks, for every hop count."""
        from emqx_tpu.ops.pallas_exchange import ring_rotate
        from emqx_tpu.parallel.mesh import make_mesh
        from emqx_tpu.parallel.sharded import _shard_map
        from jax.sharding import PartitionSpec as P
        mesh = make_mesh(8, dp=2, route=4)
        x = np.arange(2 * 4 * 6, dtype=np.int32).reshape(2, 4, 6)
        for k in range(1, 4):
            def local(xs, k=k):
                return ring_rotate(xs[0, 0], k, "route", 4,
                                   impl="ppermute")[None, None]

            fn = jax.jit(_shard_map(local, mesh, (P("dp", "route"),),
                                    P("dp", "route")))
            # device (dp, r) ends up holding source (r-k)%4's block
            np.testing.assert_array_equal(np.asarray(fn(x)),
                                          np.roll(x, k, axis=1))

    def test_exchange_program_registered_in_compile_stats(self):
        import gc

        from emqx_tpu.models.router_engine import compile_stats
        from emqx_tpu.parallel.mesh import make_mesh
        from emqx_tpu.parallel.sharded import make_exchange_step

        def n_steps():
            return sum(k.startswith("exchange_step")
                       for k in compile_stats())

        base = n_steps()
        fn = make_exchange_step(make_mesh(8, dp=2, route=4), seg_cap=16)
        assert n_steps() == base + 1
        # the registry holds programs weakly: dropping the fn must not
        # pin its compiled executables for the life of the process
        # (gc may also reap entries of earlier tests' dead servers, so
        # only the upper bound is meaningful)
        del fn
        gc.collect()
        assert n_steps() <= base



@pytest.mark.slow
class TestPallasKernelTPUSmoke:
    """Hardware smoke for the real remote-DMA kernel (slow-marked; the
    CPU tier-1 suite covers the ppermute twin + selection gate)."""

    def test_rotate_on_tpu(self):
        if jax.default_backend() != "tpu":
            pytest.skip("needs a real TPU backend")
        if len(jax.devices()) < 2:
            pytest.skip("needs >= 2 TPU devices")
        from emqx_tpu.ops.pallas_exchange import ring_rotate
        from emqx_tpu.parallel.mesh import make_mesh
        from emqx_tpu.parallel.sharded import _shard_map
        from jax.sharding import PartitionSpec as P
        n = len(jax.devices())
        mesh = make_mesh(n, dp=1)
        x = np.arange(n * 128, dtype=np.int32).reshape(n, 128)

        def local(xs):
            return ring_rotate(xs, 1, "route", n, impl="pallas")

        fn = jax.jit(_shard_map(local, mesh, (P("route"),), P("route")))
        np.testing.assert_array_equal(np.asarray(fn(x)),
                                      np.roll(x, 1, axis=0))


class TestKnobResolution:
    def test_resolver_config_beats_env(self, monkeypatch):
        from emqx_tpu.parallel.serving import resolve_device_exchange
        monkeypatch.setenv("EMQX_TPU_EXCHANGE", "0")
        assert resolve_device_exchange(1) is True
        assert resolve_device_exchange(None) is False
        monkeypatch.setenv("EMQX_TPU_EXCHANGE", "1")
        assert resolve_device_exchange(None) is True
        assert resolve_device_exchange(0) is False
        monkeypatch.delenv("EMQX_TPU_EXCHANGE")
        assert resolve_device_exchange(None) is True   # default-on
        # the sibling resolvers' spellings disable too (overload,
        # compact_readback precedent) — they must not crash boot
        for off in ("false", "off", "0"):
            monkeypatch.setenv("EMQX_TPU_EXCHANGE", off)
            assert resolve_device_exchange(None) is False
