"""Tests: HOCON-lite loader, schema check, config-file boot, runtime
updates with override persistence.

Mirrors the reference's emqx_config/emqx_config_handler behavior
(apps/emqx/src/emqx_config.erl, emqx_config_handler.erl) and the hocon
syntax its etc/emqx.conf files rely on.
"""

import asyncio

import pytest

from emqx_tpu.broker.config import Config, check_schema
from emqx_tpu.broker.node import Node
from emqx_tpu.client import Client
from emqx_tpu.utils import hocon


class TestHoconParse:
    def test_basics(self):
        conf = hocon.loads("""
        # comment
        broker {
          sys_msg_interval = 30        // trailing comment
          shared_subscription_strategy = random
        }
        mqtt.max_inflight = 64
        mqtt.retain_available = false
        listeners.default { type: tcp, port: 1883 }
        tags = [a, "b c", 3]
        nothing = null
        """)
        assert conf["broker"]["sys_msg_interval"] == 30
        assert conf["broker"]["shared_subscription_strategy"] == "random"
        assert conf["mqtt"] == {"max_inflight": 64,
                                "retain_available": False}
        assert conf["listeners"]["default"] == {"type": "tcp",
                                                "port": 1883}
        assert conf["tags"] == ["a", "b c", 3]
        assert conf["nothing"] is None

    def test_merge_and_append(self):
        conf = hocon.loads("""
        a { x = 1 }
        a { y = 2 }
        a.x = 3
        arr = [1]
        arr += 2
        fresh += "first"
        """)
        assert conf["a"] == {"x": 3, "y": 2}
        assert conf["arr"] == [1, 2]
        assert conf["fresh"] == ["first"]

    def test_substitution(self, monkeypatch):
        monkeypatch.setenv("EMQX_TEST_PORT", "2883")
        conf = hocon.loads("""
        base { port = 1883 }
        l1.port = ${base.port}
        l2.port = ${?EMQX_TEST_PORT}
        l3 { port = ${?MISSING_THING} }
        """)
        assert conf["l1"]["port"] == 1883
        assert conf["l2"]["port"] == 2883
        assert "port" not in conf["l3"]

    def test_missing_substitution_raises(self):
        with pytest.raises(hocon.HoconError):
            hocon.loads("x = ${no.such.path}")

    def test_include(self, tmp_path):
        (tmp_path / "base.conf").write_text('mqtt { max_inflight = 7 }\n')
        (tmp_path / "main.conf").write_text(
            'include "base.conf"\nmqtt.idle_timeout = 30\n')
        conf = hocon.load(str(tmp_path / "main.conf"))
        assert conf["mqtt"] == {"max_inflight": 7, "idle_timeout": 30}

    def test_strings_and_escapes(self):
        conf = hocon.loads(r'''
        a = "line\nbreak"
        b = """raw "quoted" text"""
        "key with space" = ok
        ''')
        assert conf["a"] == "line\nbreak"
        assert conf["b"] == 'raw "quoted" text'
        assert conf["key with space"] == "ok"

    def test_dumps_roundtrip(self):
        orig = {"broker": {"sys_msg_interval": 30, "flag": True},
                "tags": ["x", "y z"], "name": "emqx@127.0.0.1"}
        assert hocon.loads(hocon.dumps(orig)) == orig

    def test_durations_sizes(self):
        assert hocon.parse_duration("30s") == 30
        assert hocon.parse_duration("100ms") == 0.1
        assert hocon.parse_duration("2h") == 7200
        assert hocon.parse_duration("plain") is None
        assert hocon.parse_size("16KB") == 16384
        assert hocon.parse_size("1MB") == 1048576


class TestSchemaCheck:
    def test_coercion(self):
        conf = {"mqtt": {"idle_timeout": "30s",
                         "max_packet_size": "1MB"}}
        assert check_schema(conf) == []
        assert conf["mqtt"]["idle_timeout"] == 30
        assert conf["mqtt"]["max_packet_size"] == 1048576

    def test_type_errors(self):
        errs = check_schema({"mqtt": {"max_inflight": "lots",
                                      "retain_available": 3},
                             "broker": "not-an-object"})
        assert len(errs) == 3
        assert any("max_inflight" in e for e in errs)
        assert any("retain_available" in e for e in errs)
        assert any("broker" in e for e in errs)

    def test_unknown_keys_allowed(self):
        assert check_schema({"my_plugin": {"weird": 1}}) == []


class TestConfigFile:
    def test_load_update_persist(self, tmp_path):
        main = tmp_path / "emqx.conf"
        main.write_text("""
        mqtt { max_inflight = 12, idle_timeout = 20s }
        broker.sys_msg_interval = 45
        """)
        conf = Config.load_file(str(main))
        assert conf.get("mqtt", "max_inflight") == 12
        assert conf.get("mqtt", "idle_timeout") == 20
        assert conf.get("broker", "sys_msg_interval") == 45
        # defaults still merged underneath
        assert conf.get("mqtt", "max_qos_allowed") == 2

        seen = []
        conf.register_handler(("mqtt",),
                              lambda p, v, c: seen.append((p, v)))
        conf.update(("mqtt", "max_inflight"), 99)
        assert seen == [(("mqtt", "max_inflight"), 99)]
        assert conf.get("mqtt", "max_inflight") == 99
        # persisted override survives a reload
        conf2 = Config.load_file(str(main))
        assert conf2.get("mqtt", "max_inflight") == 99

    def test_override_file_survives_restart_updates(self, tmp_path):
        # overrides persisted by a previous run must not be discarded by
        # this run's first update()
        main = tmp_path / "emqx.conf"
        main.write_text("mqtt.max_inflight = 12\n")
        c1 = Config.load_file(str(main))
        c1.update(("mqtt", "max_inflight"), 64)
        c2 = Config.load_file(str(main))
        c2.update(("broker", "sys_msg_interval"), 99)
        c3 = Config.load_file(str(main))
        assert c3.get("mqtt", "max_inflight") == 64
        assert c3.get("broker", "sys_msg_interval") == 99

    def test_ssl_listener_without_certs_refused(self, tmp_path):
        main = tmp_path / "emqx.conf"
        main.write_text(
            'listeners.bad { type = ssl, port = 0 }\n')
        node = Node.from_config_file(str(main), use_device=False)
        loop = asyncio.new_event_loop()
        try:
            with pytest.raises(ValueError):
                loop.run_until_complete(node.start_listeners())
        finally:
            loop.close()

    def test_handler_veto(self, tmp_path):
        conf = Config()

        def veto(path, value, _c):
            raise ValueError("nope")
        conf.register_handler(("broker",), veto)
        with pytest.raises(ValueError):
            conf.update(("broker", "sys_msg_interval"), 1)
        assert conf.get("broker", "sys_msg_interval") == 60

    def test_schema_error_on_boot(self, tmp_path):
        bad = tmp_path / "bad.conf"
        bad.write_text("mqtt.max_inflight = banana\n")
        with pytest.raises(ValueError):
            Config.load_file(str(bad))


class TestNodeBootFromFile:
    def test_listeners_from_config(self, tmp_path):
        main = tmp_path / "emqx.conf"
        main.write_text("""
        listeners {
          default  { type = tcp, bind = "127.0.0.1", port = 0 }
          ws       { type = ws, bind = "127.0.0.1", port = 0 }
          disabled { type = tcp, port = 0, enabled = false }
        }
        mqtt.max_inflight = 5
        """)
        node = Node.from_config_file(str(main), use_device=False)
        loop = asyncio.new_event_loop()
        try:
            listeners = loop.run_until_complete(node.start_listeners())
            assert len(listeners) == 2
            tcp = listeners[0]

            async def go():
                c = Client(port=tcp.port, clientid="boot1")
                await c.connect()
                await c.subscribe("a/b")
                await c.publish("a/b", b"hi")
                m = await c.recv()
                assert m.payload == b"hi"
                await c.disconnect()
            loop.run_until_complete(asyncio.wait_for(go(), 15))
        finally:
            loop.run_until_complete(node.stop_listeners())
            loop.close()
