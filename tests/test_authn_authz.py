"""Tests for authn chain, authz sources, banned table, flapping detect.

Mirrors the reference suites emqx_authn tests, emqx_authz tests,
emqx_banned_SUITE, emqx_flapping_SUITE, emqx_access_control_SUITE.
"""

import asyncio
import base64
import hashlib
import hmac
import json
import time

import pytest

from emqx_tpu.apps.authn import (AuthnChain, BuiltinDB, HTTPAuthenticator,
                                 JWTAuthenticator)
from emqx_tpu.apps.authz import (ALLOW, DENY, NOMATCH, Authz, AuthzCache,
                                 ClientAclSource, FileSource, HTTPSource,
                                 Rule)
from emqx_tpu.broker.banned import Banned, FlappingDetect
from emqx_tpu.broker.connection import Listener
from emqx_tpu.broker.node import Node
from emqx_tpu.client import Client, MqttError
from emqx_tpu.mqtt import constants as C
from emqx_tpu.utils import passwd as PW


def jwt_make(payload: dict, secret: str, alg: str = "HS256") -> str:
    def b64(b):
        return base64.urlsafe_b64encode(b).rstrip(b"=").decode()
    head = b64(json.dumps({"alg": alg, "typ": "JWT"}).encode())
    body = b64(json.dumps(payload).encode())
    digest = {"HS256": hashlib.sha256, "HS384": hashlib.sha384,
              "HS512": hashlib.sha512}[alg]
    sig = b64(hmac.new(secret.encode(), f"{head}.{body}".encode(),
                       digest).digest())
    return f"{head}.{body}.{sig}"


# ---------- password hashing ----------

class TestPasswd:
    @pytest.mark.parametrize("algo", ["plain", "md5", "sha", "sha256",
                                      "sha512", "pbkdf2"])
    def test_roundtrip(self, algo):
        h = PW.hash_password(algo, b"secret", "salt123")
        assert PW.check_password(algo, h, b"secret", "salt123")
        assert not PW.check_password(algo, h, b"wrong", "salt123")
        assert not PW.check_password(algo, h, None, "salt123")

    def test_salt_position(self):
        pre = PW.hash_password("sha256", b"p", "s", "prefix")
        suf = PW.hash_password("sha256", b"p", "s", "suffix")
        assert pre != suf
        assert PW.check_password("sha256", suf, b"p", "s", "suffix")


# ---------- builtin DB ----------

class TestBuiltinDB:
    def test_auth_flow(self):
        db = BuiltinDB()
        db.add_user("alice", "pw1", is_superuser=True)
        v, extra = db.authenticate({"username": "alice"}, b"pw1")
        assert v == "ok" and extra["is_superuser"]
        v, _ = db.authenticate({"username": "alice"}, b"bad")
        assert v == "deny"
        v, _ = db.authenticate({"username": "nobody"}, b"x")
        assert v == "ignore"

    def test_clientid_type_and_mgmt(self):
        db = BuiltinDB(user_id_type="clientid", algorithm="plain")
        db.add_user("c1", "pw")
        v, _ = db.authenticate({"clientid": "c1"}, b"pw")
        assert v == "ok"
        assert db.update_user("c1", password="pw2")
        v, _ = db.authenticate({"clientid": "c1"}, b"pw2")
        assert v == "ok"
        assert db.delete_user("c1") and not db.delete_user("c1")
        assert db.list_users() == []


# ---------- JWT ----------

class TestJWT:
    def test_valid_token(self):
        a = JWTAuthenticator("s3cret")
        tok = jwt_make({"sub": "x", "exp": time.time() + 60}, "s3cret")
        v, extra = a.authenticate({"clientid": "c"}, tok.encode())
        assert v == "ok"

    def test_expired_and_bad_sig(self):
        a = JWTAuthenticator("s3cret")
        tok = jwt_make({"exp": time.time() - 10}, "s3cret")
        assert a.authenticate({}, tok.encode())[0] == "deny"
        tok2 = jwt_make({"exp": time.time() + 60}, "wrong")
        assert a.authenticate({}, tok2.encode())[0] == "ignore"
        assert a.authenticate({}, b"not-a-jwt")[0] == "ignore"

    def test_verify_claims_placeholders(self):
        a = JWTAuthenticator("k", verify_claims={"username": "%u"})
        ok = jwt_make({"username": "bob"}, "k")
        assert a.authenticate({"username": "bob"}, ok.encode())[0] == "ok"
        assert a.authenticate({"username": "eve"}, ok.encode())[0] == "deny"

    def test_acl_claim(self):
        a = JWTAuthenticator("k")
        tok = jwt_make({"acl": {"pub": ["t/%c"], "sub": []}}, "k")
        v, extra = a.authenticate({"clientid": "c"}, tok.encode())
        assert v == "ok" and extra["acl"]["pub"] == ["t/%c"]


# ---------- chain ----------

class TestAuthnChain:
    def run_auth(self, node, clientinfo, password):
        loop = asyncio.new_event_loop()
        try:
            return loop.run_until_complete(node.hooks.run_fold_async(
                "client.authenticate", (clientinfo,),
                {"ok": True, "password": password}))
        finally:
            loop.close()

    def test_chain_order_and_terminal_deny(self):
        node = Node()
        db = BuiltinDB()
        db.add_user("u", "pw")
        AuthnChain(node, [db], enable=True).load()
        assert self.run_auth(node, {"username": "u"}, b"pw")["ok"]
        res = self.run_auth(node, {"username": "u"}, b"no")
        assert not res["ok"] and res["rc"] == C.RC_BAD_USER_NAME_OR_PASSWORD
        # unknown user: all ignore → terminal deny
        res = self.run_auth(node, {"username": "ghost"}, b"x")
        assert not res["ok"] and res["rc"] == C.RC_NOT_AUTHORIZED

    def test_disabled_chain_allows(self):
        node = Node()
        AuthnChain(node, [], enable=False).load()
        assert self.run_auth(node, {"username": "any"}, None)["ok"]

    def test_fallthrough_to_second(self):
        node = Node()
        db1, db2 = BuiltinDB(), BuiltinDB(user_id_type="clientid")
        db2.add_user("c9", "pw")
        AuthnChain(node, [db1, db2], enable=True).load()
        assert self.run_auth(node, {"clientid": "c9"}, b"pw")["ok"]


# ---------- authz rules ----------

CI = {"clientid": "c1", "username": "u1", "peername": ("10.0.0.5", 1234)}


class TestAuthzRules:
    def test_who_forms(self):
        assert Rule("allow", "all").check(CI, "publish", "t") == ALLOW
        assert Rule("deny", {"username": "u1"}).check(CI, "publish", "t") == DENY
        assert Rule("deny", {"username": "zz"}).check(CI, "publish", "t") == NOMATCH
        assert Rule("allow", {"clientid": "c1"}).check(CI, "subscribe", "t") == ALLOW
        assert Rule("allow", {"ipaddr": "10.0.0.0/8"}).check(CI, "publish", "t") == ALLOW
        assert Rule("allow", {"ipaddr": "192.168.0.0/16"}).check(CI, "publish", "t") == NOMATCH
        assert Rule("allow", {"and": [{"username": "u1"}, {"clientid": "c1"}]}
                    ).check(CI, "publish", "t") == ALLOW
        assert Rule("allow", {"or": [{"username": "zz"}, {"clientid": "c1"}]}
                    ).check(CI, "publish", "t") == ALLOW

    def test_topic_placeholders_and_eq(self):
        r = Rule("allow", "all", "publish", ["dev/%c/#"])
        assert r.check(CI, "publish", "dev/c1/x") == ALLOW
        assert r.check(CI, "publish", "dev/c2/x") == NOMATCH
        r2 = Rule("allow", "all", "all", [{"eq": "a/+"}])
        assert r2.check(CI, "publish", "a/+") == ALLOW
        assert r2.check(CI, "publish", "a/b") == NOMATCH

    def test_action_scope(self):
        r = Rule("deny", "all", "subscribe", ["#"])
        assert r.check(CI, "publish", "t") == NOMATCH
        assert r.check(CI, "subscribe", "t") == DENY

    def test_file_source_order(self):
        src = FileSource([
            {"permit": "deny", "who": "all", "action": "subscribe",
             "topics": ["$SYS/#"]},
            {"permit": "allow"}])
        assert src.authorize(CI, "subscribe", "$SYS/brokers") == DENY
        assert src.authorize(CI, "subscribe", "normal") == ALLOW

    def test_client_acl_source(self):
        src = ClientAclSource()
        ci = dict(CI, acl={"pub": ["up/%c"], "sub": ["down/#"]})
        assert src.authorize(ci, "publish", "up/c1") == ALLOW
        assert src.authorize(ci, "publish", "down/x") == DENY
        assert src.authorize(ci, "subscribe", "down/x") == ALLOW
        assert src.authorize(CI, "publish", "t") == NOMATCH   # no acl claim


class TestAuthzApp:
    def run_authz(self, node, ci, action, topic):
        loop = asyncio.new_event_loop()
        try:
            return loop.run_until_complete(node.hooks.run_fold_async(
                "client.authorize", (ci, action, topic), "allow"))
        finally:
            loop.close()

    def test_no_match_default(self):
        node = Node({"authz": {"no_match": "deny"}})
        Authz(node, [FileSource([{"permit": "allow", "topics": ["ok/#"]}])],
              cache_enable=False).load()
        assert self.run_authz(node, CI, "publish", "ok/1") == "allow"
        assert self.run_authz(node, CI, "publish", "other") == "deny"

    def test_cache_hit(self):
        node = Node()
        az = Authz(node, [FileSource([{"permit": "allow"}])]).load()
        self.run_authz(node, CI, "publish", "t")
        self.run_authz(node, CI, "publish", "t")
        assert node.metrics.val("client.authorize.cache_hit") == 1
        az.drop_cache("c1")
        self.run_authz(node, CI, "publish", "t")
        assert node.metrics.val("client.authorize.cache_hit") == 1

    def test_cache_lru_ttl(self):
        c = AuthzCache(max_size=2, ttl=0.05)
        c.put(("publish", "a"), "allow")
        c.put(("publish", "b"), "allow")
        c.put(("publish", "c"), "allow")   # evicts a
        assert c.get(("publish", "a")) is None
        assert c.get(("publish", "c")) == "allow"
        time.sleep(0.06)
        assert c.get(("publish", "c")) is None


# ---------- banned / flapping ----------

class TestBanned:
    def test_check_kinds_and_expiry(self):
        b = Banned()
        b.create("clientid", "bad")
        b.create("peerhost", "1.2.3.4", duration=0.05)
        assert b.check({"clientid": "bad"})
        assert b.check({"clientid": "x", "peername": ("1.2.3.4", 1)})
        assert not b.check({"clientid": "good"})
        time.sleep(0.06)
        assert not b.check({"clientid": "x", "peername": ("1.2.3.4", 1)})
        assert b.delete("clientid", "bad")
        assert not b.check({"clientid": "bad"})

    def test_flapping_bans(self):
        node = Node({"flapping_detect": {
            "enable": True, "max_count": 3, "window_time": 10,
            "ban_time": 60}})
        FlappingDetect(node).load()
        for _ in range(3):
            node.hooks.run("client.disconnected",
                           ({"clientid": "flappy"}, "closed"))
        assert node.banned.check({"clientid": "flappy"})
        assert node.metrics.val("client.flapping.banned") == 1


# ---------- end-to-end over sockets ----------

class TestAuthEndToEnd:
    @pytest.fixture()
    def loop(self):
        loop = asyncio.new_event_loop()
        yield loop
        loop.close()

    def test_password_auth_and_acl(self, loop):
        node = Node({"authn": {"enable": True}})
        db = BuiltinDB()
        db.add_user("alice", "wonder")
        AuthnChain(node, [db], enable=True).load()
        Authz(node, [FileSource([
            {"permit": "deny", "action": "publish", "topics": ["secret/#"]},
            {"permit": "allow"}])]).load()
        lst = Listener(node, bind="127.0.0.1", port=0)
        loop.run_until_complete(lst.start())

        async def go():
            # wrong password refused
            bad = Client(port=lst.port, clientid="c0", username="alice",
                         password=b"nope")
            with pytest.raises(MqttError):
                await bad.connect()
            # good login
            c = Client(port=lst.port, clientid="c1", username="alice",
                       password=b"wonder", proto_ver=C.MQTT_V5)
            await c.connect()
            await c.subscribe("secret/x", qos=1)
            await c.subscribe("open/x", qos=1)
            ack = await c.publish("secret/x", b"pst", qos=1)
            assert ack.reason_code == C.RC_NOT_AUTHORIZED
            await c.publish("open/x", b"hi", qos=1)
            m = await c.recv()
            assert m.topic == "open/x"
            await c.disconnect()
        try:
            loop.run_until_complete(asyncio.wait_for(go(), 15))
        finally:
            loop.run_until_complete(lst.stop())

    def test_banned_rejected(self, loop):
        node = Node()
        node.banned.create("clientid", "evil")
        lst = Listener(node, bind="127.0.0.1", port=0)
        loop.run_until_complete(lst.start())

        async def go():
            c = Client(port=lst.port, clientid="evil", proto_ver=C.MQTT_V5)
            with pytest.raises(MqttError) as ei:
                await c.connect()
            assert f"{C.RC_BANNED}" in str(ei.value)
        try:
            loop.run_until_complete(asyncio.wait_for(go(), 15))
        finally:
            loop.run_until_complete(lst.stop())

    def test_http_authn_and_authz(self, loop):
        """Local asyncio HTTP stub server backs both HTTP sources."""
        seen = []

        async def handler(reader, writer):
            raw = await reader.read(4096)
            head, _, body = raw.partition(b"\r\n\r\n")
            line = head.split(b"\r\n")[0].decode()
            data = json.loads(body) if body else {}
            seen.append((line, data))
            if "/auth" in line:
                ok = data.get("username") == "hal" and \
                    data.get("password") == "9000"
                resp = {"result": "allow" if ok else "deny"}
            else:   # /acl
                resp = {"result": "deny"
                        if data.get("topic", "").startswith("forbidden")
                        else "allow"}
            payload = json.dumps(resp).encode()
            writer.write(b"HTTP/1.1 200 OK\r\ncontent-type: application/json"
                         b"\r\ncontent-length: " + str(len(payload)).encode()
                         + b"\r\nconnection: close\r\n\r\n" + payload)
            await writer.drain()
            writer.close()

        async def go():
            server = await asyncio.start_server(handler, "127.0.0.1", 0)
            hport = server.sockets[0].getsockname()[1]
            node = Node()
            AuthnChain(node, [HTTPAuthenticator(
                f"http://127.0.0.1:{hport}/auth")], enable=True).load()
            Authz(node, [HTTPSource(f"http://127.0.0.1:{hport}/acl")],
                  cache_enable=False).load()
            lst = Listener(node, bind="127.0.0.1", port=0)
            await lst.start()
            try:
                c = Client(port=lst.port, clientid="h1", username="hal",
                           password=b"9000", proto_ver=C.MQTT_V5)
                await c.connect()
                ack = await c.publish("forbidden/x", b"x", qos=1)
                assert ack.reason_code == C.RC_NOT_AUTHORIZED
                ack = await c.publish("fine/x", b"x", qos=1)
                assert ack.reason_code in (0, C.RC_NO_MATCHING_SUBSCRIBERS)
                await c.disconnect()
                bad = Client(port=lst.port, clientid="h2", username="hal",
                             password=b"wrong")
                with pytest.raises(MqttError):
                    await bad.connect()
            finally:
                await lst.stop()
                server.close()
                await server.wait_closed()
            assert any("/auth" in l for l, _ in seen)
            assert any("/acl" in l for l, _ in seen)
        loop.run_until_complete(asyncio.wait_for(go(), 20))
