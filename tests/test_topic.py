"""Topic algebra tests (parity oracle: reference emqx_topic.erl + its SUITE)."""

import pytest

from emqx_tpu.utils import topic as T


class TestWords:
    def test_tokens(self):
        assert T.tokens("a/b/c") == ["a", "b", "c"]
        assert T.tokens("/a") == ["", "a"]
        assert T.tokens("a//b") == ["a", "", "b"]
        assert T.tokens("a/b/") == ["a", "b", ""]
        assert T.tokens("") == [""]

    def test_levels(self):
        assert T.levels("a/b/c") == 3
        assert T.levels("/") == 2


class TestWildcard:
    def test_wildcard(self):
        assert T.wildcard("a/+/b")
        assert T.wildcard("a/b/#")
        assert T.wildcard("#")
        assert not T.wildcard("a/b/c")
        assert not T.wildcard("a/b+c")  # '+' inside a word is not a wildcard
        assert not T.wildcard("")


class TestMatch:
    # positive cases mirrored from the reference topic SUITE semantics
    @pytest.mark.parametrize("name,filt", [
        ("a/b/c", "a/b/c"),
        ("a/b/c", "a/+/c"),
        ("a/b/c", "a/#"),
        ("a/b/c", "#"),
        ("a/b/c", "+/+/+"),
        ("a", "a/#"),          # '#' matches zero levels
        ("a/b", "a/b/#"),
        ("a", "+"),
        ("/", "+/+"),          # empty levels are levels
        ("/a", "+/a"),
        ("a//b", "a/+/b"),
        ("a/", "a/+"),
        ("$SYS/broker", "$SYS/#"),      # '$' only excluded at root
        ("$SYS/broker", "$SYS/+"),
        ("$SYS", "$SYS/#"),             # sport/# matches sport, same for $SYS
        ("a/$b/c", "a/+/c"),            # mid-level '$' is ordinary
        ("a/$b/c", "a/#"),
    ])
    def test_match_true(self, name, filt):
        assert T.match(name, filt)

    @pytest.mark.parametrize("name,filt", [
        ("a/b/c", "a/b"),
        ("a/b", "a/b/c"),
        ("a/b", "a/b/+"),
        ("a/b/c", "a/+"),
        ("b/c", "a/#"),
        ("a", "b"),
        ("$SYS/broker", "#"),    # root wildcard excluded for $-topics
        ("$SYS/broker", "+/broker"),
        ("$SYS", "#"),
        ("$SYS", "+"),
        ("", "a"),
    ])
    def test_match_false(self, name, filt):
        assert not T.match(name, filt)

    def test_match_words_no_dollar_rule(self):
        # word-list form bypasses the root '$' exclusion (caller's concern)
        assert T.match_words(["$SYS", "b"], ["#"])


class TestValidate:
    @pytest.mark.parametrize("t", [
        "a/b/c", "+", "#", "a/+/#", "+/+", "/", "a//b", "a/b/", "$SYS/#",
        "a" * 65535,
    ])
    def test_valid_filters(self, t):
        assert T.validate(t, "filter")

    @pytest.mark.parametrize("t,code", [
        ("", "empty_topic"),
        ("a/" * 40000, "topic_too_long"),
        ("a/#/b", "topic_invalid_#"),
        ("#/b", "topic_invalid_#"),
        ("a/b+c/d", "topic_invalid_char"),
        ("a/b#/d", "topic_invalid_char"),
        ("a/+b", "topic_invalid_char"),
        ("a/\x00b", "topic_invalid_char"),
    ])
    def test_invalid_filters(self, t, code):
        with pytest.raises(T.TopicError) as e:
            T.validate(t, "filter")
        assert e.value.code == code

    @pytest.mark.parametrize("t", ["a/+/b", "#", "a/#"])
    def test_name_rejects_wildcards(self, t):
        with pytest.raises(T.TopicError) as e:
            T.validate(t, "name")
        assert e.value.code == "topic_name_error"

    def test_name_valid(self):
        assert T.validate("a/b/c", "name")


class TestParse:
    def test_plain(self):
        assert T.parse("a/b") == ("a/b", {})

    def test_share(self):
        assert T.parse("$share/g1/a/b") == ("a/b", {"share": "g1"})

    def test_share_deep(self):
        assert T.parse("$share/g/t/+/#") == ("t/+/#", {"share": "g"})

    def test_queue(self):
        assert T.parse("$queue/a/b") == ("a/b", {"share": "$queue"})

    @pytest.mark.parametrize("t", [
        "$share/g",              # no filter part
        "$share/g+/t",           # wildcard in group
        "$share/g#/t",
    ])
    def test_invalid_share(self, t):
        with pytest.raises(T.TopicError):
            T.parse(t)

    def test_nested_share_invalid(self):
        with pytest.raises(T.TopicError):
            T.parse("$share/g/$share/h/t")
        with pytest.raises(T.TopicError):
            T.parse("$queue/$share/h/t")


class TestHelpers:
    def test_join(self):
        assert T.join(["a", "b", "c"]) == "a/b/c"
        assert T.join(["", "a"]) == "/a"
        assert T.join([]) == ""

    def test_prepend(self):
        assert T.prepend(None, "t") == "t"
        assert T.prepend("", "t") == "t"
        assert T.prepend("mnt", "t") == "mnt/t"
        assert T.prepend("mnt/", "t") == "mnt/t"

    def test_feed_var(self):
        assert T.feed_var("%c", "cid1", "client/%c/up") == "client/cid1/up"
        assert T.feed_var("%u", "u", "a/b") == "a/b"

    def test_systop(self):
        assert T.systop("version", node="n1") == "$SYS/brokers/n1/version"
