"""The device route engine as the LIVE serving path.

Round 2's flagship requirement (VERDICT.md next-round #2): PUBLISHes flowing
through real TCP connections must be matched + fanned out by the fused
device route step (models.router_engine), with RouteResult rows driving the
actual deliveries — asserted via the `messages.routed.device` counter — and
stale-snapshot cases (membership churn, new filters) handled correctly.
Parity target: emqx_broker.erl:199-308 publish/dispatch semantics.
"""

import asyncio

import pytest

from emqx_tpu.broker.message import make
from emqx_tpu.broker.node import Node


class Sink:
    """Fake subscriber recording deliveries."""

    def __init__(self):
        self.got = []

    def deliver(self, topic_filter, msg):
        self.got.append((topic_filter, msg.topic, msg.payload,
                         msg.headers.get("subopts", {})))
        return True


def mkmsg(topic, payload=b"x", qos=0, from_="pub"):
    return make(from_, qos, topic, payload)


@pytest.fixture()
def node():
    n = Node()
    assert n.device_engine is not None  # default-on
    return n


class TestEngineDirect:
    """DeviceRouteEngine.route_batch consumed into deliveries (no sockets)."""

    def test_wildcard_and_exact_device_rows(self, node):
        b = node.broker
        s1, s2, s3 = Sink(), Sink(), Sink()
        sid1 = b.register(s1, "c1")
        sid2 = b.register(s2, "c2")
        sid3 = b.register(s3, "c3")
        b.subscribe(sid1, "dev/+/temp", {"qos": 1})
        b.subscribe(sid2, "dev/7/temp", {"qos": 0})
        b.subscribe(sid3, "exact/topic", {"qos": 2})

        msgs = [mkmsg("dev/7/temp"), mkmsg("exact/topic"),
                mkmsg("dev/9/temp"), mkmsg("none/match")]
        counts = node.device_engine.route_batch(msgs)
        assert counts == [2, 1, 1, 0]
        assert sorted(t for _f, t, _p, _o in s1.got) == \
            ["dev/7/temp", "dev/9/temp"]
        assert [t for _f, t, _p, _o in s2.got] == ["dev/7/temp"]
        assert [t for _f, t, _p, _o in s3.got] == ["exact/topic"]
        # subopts survive the packed-byte round trip
        assert s1.got[0][3]["qos"] == 1
        assert s3.got[0][3]["qos"] == 2
        assert node.metrics.val("messages.routed.device") == 4
        assert node.metrics.val("routing.device.batches") == 1
        assert node.metrics.val("messages.dropped.no_subscribers") == 1

    def test_membership_churn_goes_host(self, node):
        b = node.broker
        s1 = Sink()
        sid1 = b.register(s1, "c1")
        b.subscribe(sid1, "t/+", {"qos": 0})
        assert node.device_engine.route_batch([mkmsg("t/1")]) == [1]
        dev0 = node.metrics.val("messages.routed.device")

        # new member on a built filter -> filter dirty -> host dict path
        s2 = Sink()
        sid2 = b.register(s2, "c2")
        b.subscribe(sid2, "t/+", {"qos": 1})
        assert node.device_engine.route_batch([mkmsg("t/2")]) == [2]
        assert [t for _f, t, _p, _o in s2.got] == ["t/2"]
        assert len(s1.got) == 2
        assert node.metrics.val("messages.routed.device") == dev0

        # unsubscribe -> still dirty -> removed member gets nothing
        b.unsubscribe(sid1, "t/+")
        assert node.device_engine.route_batch([mkmsg("t/3")]) == [1]
        assert len(s1.got) == 2
        assert len(s2.got) == 2

    def test_new_filter_delta_path(self, node):
        b = node.broker
        s1 = Sink()
        sid1 = b.register(s1, "c1")
        b.subscribe(sid1, "a/b", {"qos": 0})
        assert node.device_engine.route_batch([mkmsg("a/b")]) == [1]

        s2 = Sink()
        sid2 = b.register(s2, "c2")
        b.subscribe(sid2, "fresh/#", {"qos": 0})
        counts = node.device_engine.route_batch(
            [mkmsg("fresh/x/y"), mkmsg("a/b")])
        assert counts == [1, 1]
        assert [t for _f, t, _p, _o in s2.got] == ["fresh/x/y"]
        assert node.device_engine.stats()["delta_filters"] == 1

    def test_rebuild_after_threshold(self, node):
        node.device_engine.rebuild_threshold = 4
        # delta overlay OFF restores the pre-ISSUE-4 contract under
        # test here: post-build filters count toward staleness and the
        # threshold crossing triggers a full rebuild (with the overlay
        # on they serve on device and never trip the threshold — see
        # tests/test_delta_overlay.py)
        node.device_engine.delta_overlay = False
        b = node.broker
        s1 = Sink()
        sid1 = b.register(s1, "c1")
        b.subscribe(sid1, "base/t", {"qos": 0})
        node.device_engine.route_batch([mkmsg("base/t")])
        for i in range(5):
            b.subscribe(sid1, f"extra/{i}", {"qos": 0})
        assert node.device_engine.staleness() >= 4
        node.device_engine.route_batch([mkmsg("extra/3")])
        assert node.device_engine.staleness() == 0   # rebuilt
        assert node.device_engine.stats()["delta_filters"] == 0
        assert len([x for x in s1.got if x[1] == "extra/3"]) == 1
        assert node.metrics.val("routing.device.rebuilds") >= 2

    def test_shared_round_robin_device_picks(self, node):
        b = node.broker
        sinks = [Sink() for _ in range(3)]
        sids = [b.register(s, f"m{i}") for i, s in enumerate(sinks)]
        for sid in sids:
            b.subscribe(sid, "$share/g/job/q", {"qos": 1})
        msgs = [mkmsg("job/q", str(i).encode()) for i in range(6)]
        counts = node.device_engine.route_batch(msgs)
        assert counts == [1] * 6
        per = [len(s.got) for s in sinks]
        assert sorted(per) == [2, 2, 2]          # strict round-robin
        assert all(o.get("share") == "g"
                   for s in sinks for _f, _t, _p, o in s.got)
        # cursors persist across batches: next 3 go one to each member
        node.device_engine.route_batch(
            [mkmsg("job/q", b"n1"), mkmsg("job/q", b"n2"),
             mkmsg("job/q", b"n3")])
        assert sorted(len(s.got) for s in sinks) == [3, 3, 3]

    def test_shared_sticky_device_picks(self, node):
        """VERDICT r4 #9: sticky serves ON DEVICE — the cursor is the
        affinity pointer, so every message of every batch goes to the
        same member with zero host feedback."""
        b = node.broker
        b.shared_strategy = "sticky"
        sinks = [Sink() for _ in range(3)]
        for i, s in enumerate(sinks):
            b.subscribe(b.register(s, f"st{i}"), "$share/sg/stick/q",
                        {"qos": 0})
        counts = node.device_engine.route_batch(
            [mkmsg("stick/q", str(i).encode()) for i in range(6)])
        assert counts == [1] * 6
        assert sorted(len(s.got) for s in sinks) == [0, 0, 6]
        # across batches: same member, still on device
        dev0 = node.metrics.val("messages.routed.device")
        assert node.device_engine.route_batch([mkmsg("stick/q", b"n")]) \
            == [1]
        assert sorted(len(s.got) for s in sinks) == [0, 0, 7]
        assert node.metrics.val("messages.routed.device") == dev0 + 1

    def test_sticky_repick_after_member_leave(self, node):
        """The feedback-dependent half stays host-side: when the sticky
        member leaves, the host re-pick re-homes the affinity and the
        next snapshot re-seeds the device cursor from it."""
        b = node.broker
        b.shared_strategy = "sticky"
        s1, s2 = Sink(), Sink()
        sid1, sid2 = b.register(s1, "sm1"), b.register(s2, "sm2")
        b.subscribe(sid1, "$share/sg/re/q", {"qos": 0})
        b.subscribe(sid2, "$share/sg/re/q", {"qos": 0})
        assert node.device_engine.route_batch([mkmsg("re/q")]) == [1]
        owner, other, osid = (s1, s2, sid1) if s1.got else (s2, s1, sid2)
        b.unsubscribe(osid, "$share/sg/re/q")
        counts = node.device_engine.route_batch(
            [mkmsg("re/q", b"2"), mkmsg("re/q", b"3")])
        assert counts == [1, 1]
        assert len(other.got) == 2          # re-homed to the survivor
        # affinity survives a full rebuild (re-seeded from host record)
        node.device_engine.rebuild()
        assert node.device_engine.route_batch([mkmsg("re/q", b"4")]) == [1]
        assert len(other.got) == 3

    def test_shared_dirty_slot_host_pick(self, node):
        b = node.broker
        s1, s2 = Sink(), Sink()
        sid1, sid2 = b.register(s1, "m1"), b.register(s2, "m2")
        b.subscribe(sid1, "$share/g/t", {"qos": 0})
        node.device_engine.route_batch([mkmsg("t")])
        # membership change dirties the slot -> host pick sees new member
        b.subscribe(sid2, "$share/g/t", {"qos": 0})
        counts = node.device_engine.route_batch(
            [mkmsg("t") for _ in range(4)])
        assert counts == [1] * 4
        assert len(s1.got) + len(s2.got) == 5
        assert len(s2.got) >= 1

    def test_new_group_on_built_filter(self, node):
        b = node.broker
        s1, s2 = Sink(), Sink()
        sid1, sid2 = b.register(s1, "m1"), b.register(s2, "m2")
        b.subscribe(sid1, "t/x", {"qos": 0})
        node.device_engine.route_batch([mkmsg("t/x")])
        b.subscribe(sid2, "$share/g2/t/x", {"qos": 0})
        counts = node.device_engine.route_batch([mkmsg("t/x")])
        assert counts == [2]
        assert len(s2.got) == 1

    def test_overflow_falls_back_host(self, node):
        node.device_engine.fanout_cap = 4   # force tiny capacity
        b = node.broker
        sinks = [Sink() for _ in range(8)]
        for i, s in enumerate(sinks):
            b.subscribe(b.register(s, f"c{i}"), "big/+", {"qos": 0})
        counts = node.device_engine.route_batch(
            [mkmsg("big/t"), mkmsg("big/u")])
        assert counts == [8, 8]
        assert all(len(s.got) == 2 for s in sinks)
        assert node.metrics.val("routing.device.host_fallback") == 2

    def test_deep_topic_falls_back_host(self, node):
        b = node.broker
        s = Sink()
        b.subscribe(b.register(s, "c"), "deep/#", {"qos": 0})
        deep = "deep/" + "/".join(str(i) for i in range(25))
        assert node.device_engine.route_batch([mkmsg(deep)]) == [1]
        assert len(s.got) == 1

    def test_rich_subopts_host_path(self, node):
        b = node.broker
        s = Sink()
        sid = b.register(s, "c")
        b.subscribe(sid, "r/+", {"qos": 1, "subid": 7})
        assert node.device_engine.route_batch([mkmsg("r/1")]) == [1]
        # subid must survive (packed byte cannot carry it -> host dict)
        assert s.got[0][3].get("subid") == 7

    def test_trie_backend_when_many_shapes(self, node):
        node.device_engine.shape_cap = 2
        b = node.broker
        s = Sink()
        sid = b.register(s, "c")
        for f in ["a", "a/b", "a/+/c", "+/b/#", "x/y/z/w"]:
            b.subscribe(sid, f, {"qos": 0})
        # 'a/b' matches both the exact filter and '+/b/#' ('#' = zero levels)
        assert node.device_engine.route_batch([mkmsg("a/b")]) == [2]
        assert node.device_engine.stats()["backend"] == "trie"
        assert sorted(f for f, _t, _p, _o in s.got) == ["+/b/#", "a/b"]


class TestEndToEnd:
    """Real TCP clients; concurrent publishes form a device batch."""

    def test_concurrent_publishes_routed_on_device(self):
        from emqx_tpu.broker.connection import Listener
        from emqx_tpu.client import Client

        loop = asyncio.new_event_loop()
        try:
            node = Node()
            listener = Listener(node, bind="127.0.0.1", port=0)
            loop.run_until_complete(listener.start())

            async def go():
                sub = Client(port=listener.port, clientid="sub")
                await sub.connect()
                await sub.subscribe("bench/+/t", qos=1)
                pubs = []
                for i in range(8):
                    c = Client(port=listener.port, clientid=f"pub{i}")
                    await c.connect()
                    pubs.append(c)
                # wait until the device path engages (compile classes
                # warm in the background; the batcher routes host-side
                # meanwhile) — raises if it never does
                from tests.test_pipeline import _await_device_engaged
                await _await_device_engaged(node, "warm/{}")
                # pin the choice for the asserted batch (the chooser may
                # legitimately bypass tiny batches on this backend)
                node.publish_batcher._device_worth_it = \
                    lambda n, n_subs=1: True
                # concurrent QoS1 publishes land in one batch window
                await asyncio.gather(*[
                    c.publish(f"bench/{i}/t", b"p%d" % i, qos=1)
                    for i, c in enumerate(pubs)])
                got = []
                for _ in range(8):
                    got.append(await asyncio.wait_for(
                        sub.messages.get(), 10))
                for c in pubs:
                    await c.disconnect()
                await sub.disconnect()
                return got

            got = loop.run_until_complete(asyncio.wait_for(go(), 30))
            assert sorted(m.topic for m in got) == \
                sorted(f"bench/{i}/t" for i in range(8))
            assert node.metrics.val("messages.routed.device") >= 8
            assert node.metrics.val("routing.device.batches") >= 1
            loop.run_until_complete(listener.stop())
        finally:
            loop.close()


class TestAdaptiveDeviceChoice:
    """SURVEY §7 hard-part 2: the batcher measures device-batch vs
    host-per-message cost and routes each batch to the cheaper path,
    re-probing the device periodically."""

    def _batcher(self):
        from emqx_tpu.broker.batcher import PublishBatcher
        node = Node(use_device=False)
        return PublishBatcher(node, None), node

    def test_optimistic_until_measured(self):
        b, _ = self._batcher()
        assert b._device_worth_it(1)        # no data yet -> try device

    def test_prefers_cheaper_path_and_reprobes(self):
        from emqx_tpu.broker import batcher as BM
        b, node = self._batcher()
        b._dev_batch_s = 0.200              # relay-like: 200ms per batch
        b._host_msg_s = 0.0001              # 10k msg/s host
        assert not b._device_worth_it(64)   # 64 * 0.1ms << 200ms
        assert node.metrics.val("routing.device.bypassed") == 1
        assert b._device_worth_it(4000)     # big batch amortizes
        # co-located-like: device far cheaper
        b._dev_batch_s = 0.001
        assert b._device_worth_it(64)
        # forced re-probe after a long host streak
        b._dev_batch_s = 10.0
        b._since_probe = BM._PROBE_EVERY
        assert b._device_worth_it(4)

    def test_ewma_pessimizes_fast_optimizes_slow(self):
        """Cost estimates pessimize fast but not on ONE bad sample: a
        first >3x outlier folds in smoothly and arms the streak; a
        SECOND consecutive outlier (sustained slowdown) is adopted
        outright. Improvements are always smooth (one fast sample must
        not hide a generally slow path — the probes re-measure)."""
        from emqx_tpu.broker.batcher import _ewma
        cur = 0.010
        # one spike: discarded, streak armed — baseline must NOT drift or
        # a sustained 3-4x slowdown would never trip the second check
        v1, s1 = _ewma(cur, 30.0)
        assert s1 == 1 and v1 == cur
        # second consecutive outlier: adopted outright
        v2, s2 = _ewma(v1, 30.0, s1)
        assert v2 == 30.0 and s2 == 2
        # a sustained moderate (3.5x) slowdown adopts on its second window
        w1, t1 = _ewma(0.010, 0.035)
        w2, t2 = _ewma(w1, 0.035, t1)
        assert (w2, t2) == (0.035, 2)
        # a normal sample disarms the streak
        _v3, s3 = _ewma(cur, 0.011, 1)
        assert s3 == 0
        # improvement is smooth
        fast, _ = _ewma(cur, 0.001)
        assert 0.005 < fast < cur
        assert _ewma(None, 0.5) == (0.5, 0)


class TestInternBounded:
    """SURVEY §7 hard-part 3 / round-2 VERDICT weak #9: publish-side topic
    words must NOT grow the intern table — only filter vocabulary
    allocates ids (ops/intern.py lookup() vs intern()). An attacker
    publishing unbounded unique topics must leave host memory bounded."""

    def test_publishes_do_not_grow_intern(self):
        node = Node()
        b = node.broker
        sink = Sink()
        sid = b.register(sink, "c1")
        b.subscribe(sid, "known/+/t", {"qos": 0})
        eng = node.device_engine
        # build the snapshot; record the filter-vocabulary size
        eng.route_batch([mkmsg("known/1/t")])
        base = len(eng.intern)
        # a flood of unique published topics (each word never seen in a
        # filter) routes correctly and interns NOTHING
        for k in range(0, 5000, 50):
            msgs = [mkmsg(f"attack/{k+i}/rnd{k+i}") for i in range(50)]
            eng.route_batch(msgs)
        assert len(eng.intern) == base, \
            "publish-side words leaked into the intern table"
        # known topics still match
        counts = eng.route_batch([mkmsg("known/9/t")])
        assert counts == [1]

    def test_unseen_words_lookup_unknown(self):
        from emqx_tpu.ops import intern as I
        t = I.InternTable()
        t.intern("level")
        n = len(t)
        assert t.lookup("never-seen") == I.UNKNOWN
        assert t.lookup("also-never") == I.UNKNOWN
        assert len(t) == n
