"""Parallel fan-out delivery lanes (ISSUE 5).

The egress stage must be invisible except for speed: per-session
delivery order with `deliver_lanes=N` is bit-identical to the inline
`deliver_lanes=0` loop across randomized windows — including shared-
group and dirty-filter slow-path interleaving and a mid-window
unsubscribe — and a blocked lane stalls the pipeline (backpressure to
`_inflight`) instead of dropping deliveries.
"""

import asyncio

import numpy as np
import pytest

from emqx_tpu.broker.deliver import (DeliveryView, OPT_TABLE,
                                     resolve_deliver_lanes)
from emqx_tpu.broker.message import Message, make
from emqx_tpu.broker.node import Node


class Rec:
    """Recording sink: per-session delivery log for the order oracle."""

    def __init__(self):
        self.got = []

    def deliver(self, topic_filter, msg):
        self.got.append((topic_filter, msg.topic, bytes(msg.payload)))
        return True


class RecBatch(Rec):
    """Recording sink with the coalesced-drain protocol."""

    def __init__(self):
        super().__init__()
        self.drains = 0

    def deliver_batch(self, items):
        self.drains += 1
        for f, m in items:
            self.got.append((f, m.topic, bytes(m.payload)))
        return len(items)


def mkmsg(topic, payload=b"x"):
    return make("pub", 0, topic, payload)


def run(coro, timeout=120):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


def _node(lanes: int, depth: int = 8) -> Node:
    return Node({"broker": {"deliver_lanes": lanes,
                            "deliver_lane_depth": depth,
                            "device_fanout_cap": 16,
                            "device_slot_cap": 4}})


def _build_world(node, rng, sink_cls=Rec):
    """Mixed subscription world: clean filters (2 subs each), shared
    groups, one rich-subopts filter — plus the sinks, keyed by sid."""
    b = node.broker
    sinks = {}

    def sub(filt, opts=None):
        s = sink_cls()
        sid = b.register(s, f"c{len(sinks)}")
        sinks[sid] = s
        b.subscribe(sid, filt, opts or {"qos": 0})
        return sid

    for i in range(24):
        sub(f"p/{i}/+")
        sub(f"p/{i}/+", {"qos": 1})
    for i in range(3):
        sub(f"$share/g/s/{i}/+")
        sub(f"$share/g/s/{i}/+")
    sub("rich/+", {"qos": 1, "subid": 7})   # rich: host-dict slow path
    return sinks


def _schedule(rng, n_windows=6, batch=48):
    """Deterministic topic schedule + churn actions between windows."""
    topics = [f"p/{i}/x" for i in range(24)] + \
        [f"s/{i}/y" for i in range(3)] + ["rich/z", "none/q"]
    wins = []
    seq = 0
    for _w in range(n_windows):
        msgs = []
        for _ in range(batch):
            t = topics[rng.randint(0, len(topics))]
            msgs.append((t, b"m%06d" % seq))
            seq += 1
        wins.append(msgs)
    return wins


async def _drive(node, windows, actions):
    """Run the serving stages window by window (dispatch/materialize on
    executor threads so lane delivery genuinely overlaps), applying the
    churn action scheduled before each window."""
    eng = node.device_engine
    eng.rebuild()
    loop = asyncio.get_running_loop()
    pool = node.deliver_lanes
    all_counts = []
    for w, msgs in enumerate(windows):
        act = actions.get(w)
        if act is not None:
            # churn is applied between windows with the lanes drained:
            # an unsubscribe legitimately RACES deliveries still in
            # flight (inline delivers "as of consume time", lanes "as
            # of delivery time" — MQTT allows either), so the oracle
            # synchronizes churn to pin order AND counts exactly
            if pool is not None:
                await pool.drain()
            act(node)
        batch = [mkmsg(t, p) for t, p in msgs]
        h = eng.prepare(batch, gate_cold=False)
        if h is None:
            eng.rebuild()
            h = eng.prepare(batch, gate_cold=False)
        await loop.run_in_executor(None, eng.dispatch, h)
        await loop.run_in_executor(None, eng.materialize, h)
        counts = eng.finish_sub(h, 0)
        if pool is not None:
            await pool.admit()
        all_counts.append(counts)
    if pool is not None:
        await pool.drain()
    return [list(c) for c in all_counts]


def _churn_actions():
    """Keyed by window index: subscribe-to-existing (dirty filter),
    mid-schedule unsubscribe, and a fresh delta filter."""
    extra = {}

    def dirty(node):
        s = Rec()
        sid = node.broker.register(s, "dirty-join")
        extra[id(node)] = (sid, s)
        node.broker.subscribe(sid, "p/3/+", {"qos": 0})

    def unsub(node):
        sid, _s = extra[id(node)]
        node.broker.unsubscribe(sid, "p/3/+")

    def fresh(node):
        s = Rec()
        sid = node.broker.register(s, "fresh")
        node.broker.subscribe(sid, "none/+", {"qos": 0})

    return {2: dirty, 3: unsub, 4: fresh}


class TestOrderProperty:
    @pytest.mark.parametrize("lanes", [1, 4])
    def test_per_session_order_identical_to_inline(self, lanes):
        """The acceptance oracle: per-session delivery sequences are
        bit-identical between deliver_lanes=0 and deliver_lanes=N,
        across clean/shared/rich/dirty interleaving, churn mid-schedule
        and a mid-window unsubscribe."""
        rng = np.random.RandomState(7)
        windows = _schedule(rng)

        n0 = _node(0)
        s0 = _build_world(n0, rng)
        c0 = run(_drive(n0, windows, _churn_actions()))

        nL = _node(lanes)
        sL = _build_world(nL, rng)
        cL = run(_drive(nL, windows, _churn_actions()))

        assert n0.deliver_lanes is None
        assert nL.deliver_lanes is not None

        got0 = {sid: s.got for sid, s in s0.items()}
        gotL = {sid: s.got for sid, s in sL.items()}
        assert got0.keys() == gotL.keys()
        for sid in got0:
            assert gotL[sid] == got0[sid], f"sid {sid} order diverged"
        # delivery counts settle identically too
        assert cL == c0

    def test_coalesced_batch_subscriber(self):
        """A subscriber with deliver_batch gets same-session runs in
        one call — fewer drains than deliveries, same content/order."""
        rng = np.random.RandomState(9)
        windows = _schedule(rng, n_windows=3)

        n0 = _node(0)
        s0 = _build_world(n0, rng, sink_cls=Rec)
        run(_drive(n0, windows, {}))

        n2 = _node(2)
        s2 = _build_world(n2, rng, sink_cls=RecBatch)
        run(_drive(n2, windows, {}))

        for sid in s0:
            assert s2[sid].got == s0[sid].got
        drains = n2.metrics.val("pipeline.deliver.drains")
        rows = n2.metrics.val("pipeline.deliver.deliveries")
        assert rows > 0 and drains < rows, (drains, rows)
        snap = n2.pipeline_telemetry.snapshot()["deliver"]
        assert snap["coalesce_ratio"] > 0


class TestBackpressure:
    def test_blocked_lane_stalls_admit_not_drops(self):
        """A paused (blocked) lane must stall admit() — the hook the
        batcher awaits, which fills `_inflight` and blocks publishers —
        while dropping nothing: on resume every delivery lands, in
        order."""
        node = _node(2, depth=1)
        b = node.broker
        sink = Rec()
        sid = b.register(sink, "c1")
        b.subscribe(sid, "t/+", {"qos": 0})

        async def go():
            eng = node.device_engine
            eng.rebuild()
            pool = node.deliver_lanes
            loop = asyncio.get_running_loop()
            pool.ensure_loop()
            pool.pause()
            outs = []
            for w in range(4):
                msgs = [mkmsg(f"t/{w}-{i}") for i in range(8)]
                h = eng.prepare(msgs, gate_cold=False)
                await loop.run_in_executor(None, eng.dispatch, h)
                await loop.run_in_executor(None, eng.materialize, h)
                outs.append(eng.finish_sub(h, 0))
            assert pool.busy()
            with pytest.raises(asyncio.TimeoutError):
                # > depth plans queued on a blocked lane: admit stalls
                await asyncio.wait_for(pool.admit(), 0.2)
            assert all(sum(c) == 0 for c in outs)   # nothing settled
            assert len(sink.got) == 0               # and nothing lost
            pool.resume()
            await pool.drain()
            return outs

        outs = run(go())
        assert all(all(c == 1 for c in counts) for counts in outs)
        assert [t for _f, t, _p in sink.got] == \
            [f"t/{w}-{i}" for w in range(4) for i in range(8)]
        assert node.metrics.val("messages.dropped") == 0
        assert node.metrics.val("pipeline.deliver.backpressure_waits") \
            >= 1

    def test_batcher_futures_resolve_after_lane_completion(self):
        """End to end through the PublishBatcher: publisher futures for
        a device-routed batch resolve only once the lanes delivered —
        and a paused pool holds them (backpressure), not drops them."""
        node = _node(2, depth=1)
        b = node.broker
        sink = Rec()
        sid = b.register(sink, "c1")
        b.subscribe(sid, "t/+", {"qos": 0})

        async def go():
            # warm until the device path engages
            for t in range(400):
                await asyncio.gather(*[
                    node.publish_async(mkmsg(f"t/w{t * 8 + i}"))
                    for i in range(8)])
                if node.metrics.val("routing.device.batches") >= 1:
                    break
            else:
                raise AssertionError("device path never engaged")
            warmed = len(sink.got)
            pool = node.deliver_lanes
            pool.pause()
            futs = [asyncio.ensure_future(
                node.publish_async(mkmsg(f"t/{i}"))) for i in range(8)]
            # give the pipeline time: with the pool paused the batch may
            # consume (plan queued) but futures must NOT resolve
            for _ in range(50):
                await asyncio.sleep(0.005)
                if node.metrics.val("routing.device.batches") >= 2:
                    break
            routed_dev = any(not f.done() for f in futs)
            pool.resume()
            counts = await asyncio.gather(*futs)
            await pool.drain()
            return warmed, routed_dev, counts

        warmed, saw_pending, counts = run(go())
        assert all(c == 1 for c in counts)
        assert len(sink.got) == warmed + 8
        # the batch may legitimately route host-side (adaptive chooser);
        # only assert the hold when the lanes actually carried it
        if saw_pending:
            assert node.metrics.val("messages.dropped") == 0


class TestDeliveryView:
    def test_view_quacks_like_message(self):
        m = Message(topic="a/b", payload=b"p", qos=1, from_="me",
                    headers={"properties": {"user": 1}},
                    flags={"retain": True})
        so = {"qos": 1, "nl": 0, "rap": 1, "rh": 0}
        v = DeliveryView(m, so)
        assert v.topic == "a/b" and v.qos == 1 and v.payload == b"p"
        assert v.headers["subopts"] is so
        assert v.headers.get("subopts") is so
        assert v.get_header("subopts") is so
        assert v.headers.get("properties") == {"user": 1}
        assert "subopts" in v.headers
        assert v.retain and not v.dup
        # copy() materializes a real, independent Message
        c = v.copy()
        assert isinstance(c, Message)
        assert c.headers["subopts"] == so
        c.headers["extra"] = 1
        assert "extra" not in m.headers and "extra" not in v.headers
        # copy-on-write: a header write never touches the base message
        v.set_header("x", 2)
        assert v.headers["x"] == 2 and "x" not in m.headers
        assert v.headers["subopts"] == so
        v.set_flag("dup", True)
        assert v.dup and not m.get_flag("dup")
        # wire form carries the overlay
        w = v.to_wire()
        assert w["topic"] == "a/b" and w["headers"]["subopts"] == so

    def test_opt_table_round_trips_packed_words(self):
        from emqx_tpu.broker.device_engine import _pack_opts
        for qos in (0, 1, 2):
            for nl in (0, 1):
                for rap in (0, 1):
                    for rh in (0, 1, 2):
                        opts = {"qos": qos, "nl": nl, "rap": rap,
                                "rh": rh}
                        assert OPT_TABLE[_pack_opts(opts)] == opts


class TestKnobs:
    def test_resolve_deliver_lanes(self, monkeypatch):
        monkeypatch.delenv("EMQX_TPU_DELIVER_LANES", raising=False)
        assert resolve_deliver_lanes(2) == 2
        assert resolve_deliver_lanes(0) == 0
        import os
        assert resolve_deliver_lanes(None) == min(4, os.cpu_count() or 1)
        monkeypatch.setenv("EMQX_TPU_DELIVER_LANES", "3")
        assert resolve_deliver_lanes(None) == 3
        assert resolve_deliver_lanes(1) == 1     # config beats env
        monkeypatch.setenv("EMQX_TPU_DELIVER_LANES", "junk")
        with pytest.raises(ValueError):
            resolve_deliver_lanes(None)
        with pytest.raises(ValueError):
            resolve_deliver_lanes(-1)

    def test_lanes_zero_restores_inline(self):
        node = _node(0)
        assert node.deliver_lanes is None
        # sync serving path still fully functional
        b = node.broker
        s = Rec()
        b.subscribe(b.register(s, "c"), "a/+", {"qos": 0})
        assert node.device_engine.route_batch([mkmsg("a/1")]) == [1]
        assert [t for _f, t, _p in s.got] == ["a/1"]


class TestHostsideMemo:
    def test_mask_memoized_until_churn(self):
        node = _node(0)
        b = node.broker
        s = Rec()
        sid = b.register(s, "c1")
        for i in range(8):
            b.subscribe(sid, f"m/{i}/+", {"qos": 0})
        eng = node.device_engine
        eng.rebuild()
        built = eng._built
        # no dirty filters: the snapshot's precomputed mask, no copy
        assert eng._hostside_mask(built) is built.fid_rich
        # dirty one filter: mask computed once, then reused by identity
        s2 = Rec()
        sid2 = b.register(s2, "c2")
        b.subscribe(sid2, "m/1/+", {"qos": 0})
        assert "m/1/+" in eng.dirty_filters
        m1 = eng._hostside_mask(built)
        fid = built.fid_of["m/1/+"]
        assert m1[fid]
        assert eng._hostside_mask(built) is m1
        # further churn invalidates (unsubscribe dirties another filter)
        b.subscribe(sid2, "m/2/+", {"qos": 0})
        m2 = eng._hostside_mask(built)
        assert m2 is not m1
        assert m2[built.fid_of["m/2/+"]]
        assert eng._hostside_mask(built) is m2


class TestTelemetry:
    def test_deliver_section_and_gauges(self):
        rng = np.random.RandomState(3)
        node = _node(2)
        _build_world(node, rng)
        run(_drive(node, _schedule(rng, n_windows=2), {}))
        snap = node.pipeline_telemetry.snapshot()
        d = snap["deliver"]
        assert d["plans"] >= 2
        assert d["deliveries"] > 0
        assert d["state"]["lanes"] == 2
        # per-lane stage histograms landed in the shared registry
        assert any(k.startswith("deliver_lane") for k in snap["stages"])
        # the lane-depth gauge rides the Stats table (all exporters)
        gauges = node.stats.sample()
        assert "pipeline.deliver.lane_depth" in gauges
        # Prometheus exposition carries the counters + gauge family
        from emqx_tpu.apps.prometheus import collect
        text = collect(node)
        assert "emqx_pipeline_deliver_plans" in text
        assert "emqx_pipeline_deliver_lane_depth" in text
