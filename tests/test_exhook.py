"""exhook tests: a real gRPC HookProvider server receiving broker hooks.

Mirrors the reference's emqx_exhook_SUITE (which runs a demo HookProvider
and checks every hookpoint plus the ValuedResponse chain semantics)."""

import asyncio
from concurrent import futures

import grpc
import pytest

from emqx_tpu.apps.exhook import Exhook
from emqx_tpu.apps.protos import exhook_pb2 as pb
from emqx_tpu.broker.message import make
from emqx_tpu.broker.node import Node


class Provider:
    """External HookProvider: records calls, scripts valued responses."""

    def __init__(self, hooks):
        self.hooks = hooks                  # [(name, topics)]
        self.calls = []
        self.auth_result = True
        self.authz_result = True
        self.publish_mutate = None          # fn(Message pb) -> Message pb

    def make_server(self):
        def unary(name, req_cls, resp_fn):
            def handler(request, _ctx):
                self.calls.append((name, request))
                return resp_fn(request)
            return grpc.unary_unary_rpc_method_handler(
                handler, request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString())

        def empty(_req):
            return pb.EmptySuccess()

        def loaded(_req):
            return pb.LoadedResponse(hooks=[
                pb.HookSpec(name=n, topics=t) for n, t in self.hooks])

        def auth(_req):
            return pb.ValuedResponse(
                type=pb.ValuedResponse.STOP_AND_RETURN,
                bool_result=self.auth_result)

        def authz(_req):
            return pb.ValuedResponse(
                type=pb.ValuedResponse.STOP_AND_RETURN,
                bool_result=self.authz_result)

        def on_publish(req):
            if self.publish_mutate is None:
                return pb.ValuedResponse(type=pb.ValuedResponse.IGNORE)
            return pb.ValuedResponse(
                type=pb.ValuedResponse.CONTINUE,
                message=self.publish_mutate(req.message))

        methods = {
            "OnProviderLoaded": unary("loaded",
                                      pb.ProviderLoadedRequest, loaded),
            "OnProviderUnloaded": unary("unloaded",
                                        pb.ProviderUnloadedRequest, empty),
            "OnClientAuthenticate": unary(
                "authenticate", pb.ClientAuthenticateRequest, auth),
            "OnClientAuthorize": unary("authorize",
                                       pb.ClientAuthorizeRequest, authz),
            "OnMessagePublish": unary("publish",
                                      pb.MessagePublishRequest,
                                      on_publish),
            "OnClientConnected": unary("connected",
                                       pb.ClientConnectedRequest, empty),
            "OnClientDisconnected": unary(
                "disconnected", pb.ClientDisconnectedRequest, empty),
            "OnSessionSubscribed": unary(
                "subscribed", pb.SessionSubscribedRequest, empty),
            "OnMessageDropped": unary("dropped",
                                      pb.MessageDroppedRequest, empty),
        }
        server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(
                "emqx.exhook.v1.HookProvider", methods),))
        port = server.add_insecure_port("127.0.0.1:0")
        server.start()
        return server, port

    def names(self):
        return [c[0] for c in self.calls]


@pytest.fixture()
def loop():
    lp = asyncio.new_event_loop()
    yield lp
    lp.close()


def run(loop, coro):
    return loop.run_until_complete(asyncio.wait_for(coro, 30))


def test_handshake_registers_wanted_hooks(loop):
    prov = Provider([("message.publish", []), ("client.connected", [])])
    server, port = prov.make_server()
    node = Node(use_device=False)
    ex = Exhook(node, {"servers": []})

    async def go():
        await ex.load()
        await ex.add_server("p1", f"127.0.0.1:{port}")
        assert prov.names() == ["loaded"]
        hooks = ex.servers["p1"].hooks_wanted
        assert set(hooks) == {"message.publish", "client.connected"}
        # only the wanted hookpoints are registered
        assert node.hooks.lookup("message.publish")
        assert not node.hooks.lookup("message.acked")
        await ex.unload()
        assert "unloaded" in prov.names()
        assert not node.hooks.lookup("message.publish")
    try:
        run(loop, go())
    finally:
        server.stop(grace=0.1)


def test_message_publish_mutation_and_topic_filter(loop):
    prov = Provider([("message.publish", ["only/#"])])
    prov.publish_mutate = lambda m: pb.Message(
        topic=m.topic, qos=m.qos, payload=m.payload + b"-mutated")
    server, port = prov.make_server()
    node = Node(use_device=False)

    class Cap:
        def __init__(self):
            self.msgs = []

        def deliver(self, f, m):
            self.msgs.append(m)
            return True

    async def go():
        ex = await Exhook(node, {"servers": []}).load()
        await ex.add_server("p1", f"127.0.0.1:{port}")
        cap = Cap()
        node.broker.subscribe(node.broker.register(cap, "c"), "#")
        # client publishes go through the awaited path (publish_async)
        await node.broker.publish_async(make("pub", 0, "only/x", b"data"))
        await node.broker.publish_async(make("pub", 0, "other/x", b"data"))
        assert cap.msgs[0].payload == b"data-mutated"   # filtered topic hit
        assert cap.msgs[1].payload == b"data"           # filter miss: as-is
        assert prov.names().count("publish") == 1
        await ex.unload()
    try:
        run(loop, go())
    finally:
        server.stop(grace=0.1)


def test_authenticate_and_authorize_valued(loop):
    prov = Provider([("client.authenticate", []),
                     ("client.authorize", [])])
    server, port = prov.make_server()
    node = Node(use_device=False)

    async def go():
        ex = await Exhook(node, {"servers": []}).load()
        await ex.add_server("p1", f"127.0.0.1:{port}")
        ci = {"clientid": "c1", "username": "u"}
        res = await node.hooks.run_fold_async(
            "client.authenticate", (ci,), {"ok": True})
        assert res["ok"] is True
        prov.auth_result = False
        res = await node.hooks.run_fold_async(
            "client.authenticate", (ci,), {"ok": True})
        assert res["ok"] is False
        res = await node.hooks.run_fold_async(
            "client.authorize", (ci, "publish", "t/1"), "allow")
        assert res == "allow"
        prov.authz_result = False
        res = await node.hooks.run_fold_async(
            "client.authorize", (ci, "subscribe", "t/1"), "allow")
        assert res == "deny"
        # the request carried the action type
        authz_reqs = [r for n, r in prov.calls if n == "authorize"]
        assert authz_reqs[-1].type == \
            pb.ClientAuthorizeRequest.SUBSCRIBE
        await ex.unload()
    try:
        run(loop, go())
    finally:
        server.stop(grace=0.1)


def test_failed_action_deny_vs_ignore(loop):
    node = Node(use_device=False)

    async def go():
        from emqx_tpu.apps.exhook import ExhookServer
        # dead server: channel to nowhere (load() itself would fail, so
        # build the handler directly like a server that died after load)
        srv = ExhookServer(node, "dead", "127.0.0.1:1",
                           timeout=0.3, failed_action="deny")
        srv.hooks_wanted = {"client.authenticate": []}
        h = srv._make_handler("client.authenticate")
        res = await h({"clientid": "x"}, {"ok": True})
        assert res == ("stop", {"ok": False})
        srv.failed_action = "ignore"
        res = await h({"clientid": "x"}, {"ok": True})
        assert res is None
    run(loop, go())


def test_nonvalued_events_forwarded(loop):
    prov = Provider([("client.connected", []),
                     ("session.subscribed", []),
                     ("message.dropped", [])])
    server, port = prov.make_server()
    node = Node(use_device=False)

    async def go():
        ex = await Exhook(node, {"servers": []}).load()
        await ex.add_server("p1", f"127.0.0.1:{port}")
        node.hooks.run("client.connected",
                       ({"clientid": "c9"}, {"proto_ver": 5}))
        node.hooks.run("session.subscribed",
                       ({"clientid": "c9"}, "a/b", {"qos": 1}))
        node.broker.publish(make("p", 0, "nobody/home", b""))
        for _ in range(50):
            await asyncio.sleep(0.05)
            if len([n for n in prov.names()
                    if n in ("connected", "subscribed", "dropped")]) >= 3:
                break
        names = prov.names()
        assert "connected" in names and "subscribed" in names
        assert "dropped" in names
        conn_req = next(r for n, r in prov.calls if n == "connected")
        assert conn_req.clientinfo.clientid == "c9"
        await ex.unload()
    try:
        run(loop, go())
    finally:
        server.stop(grace=0.1)
