"""Subscription covering (ISSUE 18): match the covering set, expand at
fan-out.

Covering must be INVISIBLE except for speed. The proof obligations:

- `covers_pair` (the pure-python covering oracle) against BRUTE-FORCE
  topic enumeration through HostTrie — trailing-'#', '+'-vs-literal per
  level, '$'-prefix exclusion, self-cover;
- vectorized `detect_covers` against exhaustive `covers_pair` pairwise
  sweeps over mixed populations;
- per-filter order keys reproduce both backends' emission order;
- engine A/B twins (covering on vs off) bit-identical on delivery
  counts AND per-session delivery order across clean / shared-group /
  '$'-topic / dirty-overlay / churn traffic and all backend pairings
  (shapes-shapes, trie-off vs shapes-root-on, trie-trie), plus the
  2/4/8-shard mesh;
- the append path: a covered new subscription lands in the expansion
  CSR (no rebuild) and the match cache drops cached topics against the
  EXPANDED set — insert and delete;
- knob resolution (broker.subscription_covering beats
  EMQX_TPU_COVERING beats default-on) and the stats/ledger surfaces
  (cover_csr HBM category);
- the shared workload generator actually produces the cover ratio it
  promises (tools/workloads.py) and the legacy population stays
  cover-free.
"""

import numpy as np
import pytest

from emqx_tpu.broker import device_engine as DE
from emqx_tpu.broker.message import make
from emqx_tpu.broker.node import Node
from emqx_tpu.ops import cover as C
from emqx_tpu.ops.intern import PAD, InternTable
from emqx_tpu.ops.trie import HostTrie


class Sink:
    def __init__(self):
        self.got = []

    def deliver(self, topic_filter, msg):
        self.got.append((topic_filter, msg.topic, bytes(msg.payload)))
        return True


def mkmsg(topic, payload=b"x"):
    return make("pub", 0, topic, payload)


def _encode(intern, filters):
    L = max(len(f.split("/")) for f in filters)
    rows = np.zeros((len(filters), L), np.int32)
    lens = np.zeros(len(filters), np.int64)
    for i, f in enumerate(filters):
        ids = intern.encode_filter(f.split("/"))
        rows[i, :len(ids)] = ids
        lens[i] = len(ids)
    dollar = np.fromiter((f.startswith("$") for f in filters), bool,
                         len(filters))
    return rows, lens, dollar


# the covering edge cases named by the issue, all in one population:
# trailing '#' over exact/'+'/deeper-'#', '+' vs literal per level,
# root-'$' exclusion, '#' root, identical-shape distinct filters
EDGE_FILTERS = [
    "#", "a/#", "a/b", "a/+", "+/b", "a/b/#", "a/b/c", "a/+/c",
    "+/+", "a/+/+", "+/b/c", "s/#", "s/+/t", "s/u/t", "s/u/v",
    "$SYS/#", "$SYS/x", "$SYS/+", "b/#", "b/+/#",
]


def _enum_topics(words, depth):
    """Every topic over `words` up to `depth` levels."""
    out = [[w] for w in words]
    frontier = [[w] for w in words]
    for _ in range(depth - 1):
        frontier = [t + [w] for t in frontier for w in words]
        out.extend(frontier)
    return out


class TestCoversPairOracle:
    def test_against_topic_enumeration(self):
        """A covers B == topics(B) subset-of topics(A), brute-forced
        through HostTrie over an alphabet that exercises '$' roots."""
        intern = InternTable()
        # every literal appearing in EDGE_FILTERS, so no filter's
        # enumerated topic set is vacuously empty
        alphabet = ["a", "b", "c", "s", "t", "u", "v", "x", "$SYS"]
        topics = _enum_topics(alphabet, 4)
        enc = {}
        for i, f in enumerate(EDGE_FILTERS):
            t = HostTrie()
            t.insert(intern.encode_filter(f.split("/")), i)
            enc[f] = t

        def topic_set(f):
            t = enc[f]
            out = set()
            for tw in topics:
                ids = [intern.lookup(w) for w in tw]
                if t.match(ids, is_dollar=tw[0].startswith("$")):
                    out.add(tuple(tw))
            return out

        tsets = {f: topic_set(f) for f in EDGE_FILTERS}
        for fa in EDGE_FILTERS:
            wa = intern.encode_filter(fa.split("/"))
            for fb in EDGE_FILTERS:
                wb = intern.encode_filter(fb.split("/"))
                got = C.covers_pair(wa, wb,
                                    b_dollar=fb.startswith("$"))
                want = tsets[fb] <= tsets[fa]
                assert got == want, (fa, fb, got, want)

    def test_pointwise_cases(self):
        it = InternTable()

        def cp(a, b):
            return C.covers_pair(it.encode_filter(a.split("/")),
                                 it.encode_filter(b.split("/")),
                                 b_dollar=b.startswith("$"))

        assert cp("a/#", "a/b") and cp("a/#", "a/+") and cp("a/#", "a")
        assert cp("a/#", "a/b/#") and cp("#", "a/b/c")
        assert not cp("a/b/#", "a/#")        # deeper '#' covers less
        assert cp("a/+", "a/b") and not cp("a/b", "a/+")
        assert not cp("a/+", "a/b/c")        # '+' is exactly one level
        assert not cp("a/+", "a/#")          # '#' matches deeper
        assert not cp("#", "$SYS/x") and not cp("+/#", "$SYS/x")
        assert cp("$SYS/#", "$SYS/x")        # '$' literal root is fine
        assert cp("a/b", "a/b")              # self-cover: caller excludes


class TestDetection:
    def test_matches_exhaustive_pairwise(self):
        from tools.workloads import cover_heavy_filters
        intern = InternTable()
        filters = sorted(set(EDGE_FILTERS
                             + cover_heavy_filters(120, cover_ratio=0.5)))
        rows, lens, dollar = _encode(intern, filters)
        covers, inc = C.detect_covers(rows, lens, dollar)
        assert not inc.any()
        n = len(filters)
        for b in range(n):
            wb = [int(x) for x in rows[b, :lens[b]]]
            want = {a for a in range(n) if a != b and C.covers_pair(
                [int(x) for x in rows[a, :lens[a]]], wb,
                b_dollar=bool(dollar[b]))}
            assert set(int(x) for x in covers[b]) == want, filters[b]

    def test_assign_owners_roots_and_budget(self):
        intern = InternTable()
        filters = ["a/#", "a/1", "a/2", "a/3", "b/c"]
        rows, lens, dollar = _encode(intern, filters)
        covers, inc = C.detect_covers(rows, lens, dollar)
        owner = C.assign_owners(covers, inc)
        assert owner[0] == -1 and owner[4] == -1       # roots
        assert list(owner[1:4]) == [0, 0, 0]
        # budget: each cover owns at most own_budget covered filters
        owner2 = C.assign_owners(covers, inc, own_budget=2)
        assert (owner2[1:4] == 0).sum() == 2
        assert (owner2 == -1).sum() == 3               # overflow -> root

    def test_order_keys_reproduce_trie_emission(self):
        import jax.numpy as jnp
        from emqx_tpu.ops.match import match_batch
        from emqx_tpu.ops.trie import build_tables
        intern = InternTable()
        filters = EDGE_FILTERS
        rows, lens, dollar = _encode(intern, filters)
        keys = C.trie_order_keys(rows, lens)
        tt = build_tables(rows, lens, node_capacity=256,
                          slot_capacity=1024)
        for topic in ("a/b", "a/b/c", "s/u/t", "s/u/v", "$SYS/x", "b"):
            tw = topic.split("/")
            ids = np.full((1, rows.shape[1]), PAD, np.int32)
            ids[0, :len(tw)] = [intern.lookup(w) for w in tw]
            mr = match_batch(tt, jnp.asarray(ids),
                             jnp.asarray([len(tw)], np.int32),
                             jnp.asarray([topic.startswith("$")]))
            row = [int(x) for x in np.asarray(mr.matches)[0]
                   if int(x) >= 0]
            assert row == sorted(row, key=lambda f: keys[f]), topic
            # keys are UNIQUE within one topic's match set — ties can
            # never co-occur, which is what makes the expansion sort
            # backend-independent
            assert len({int(keys[f]) for f in row}) == len(row)


# ---------------- engine A/B twins ----------------

POPULATIONS = {
    # both twins on the shapes backend (few shapes)
    "shapes": ["s/#", "s/+/t", "s/u/t", "s/u/v", "s/a/t",
               "q/1", "q/2", "w/+", "w/x"],
    # off twin trie (diverse shapes force past shape_cap via deep '+'
    # spread), on twin shapes-over-roots — the mixed-backend pairing
    "mixed": (["top/#"]
              + [f"top/{'+/' * (i % 4)}x{i}" for i in range(12)]
              + [f"d{i}/{'+/' * (i % 5)}m{i}/t{i}" for i in range(12)]
              + ["top/a/b", "top/+/c"]),
}


def _mk_twin_nodes(filters, conf=None):
    """(covering-on, covering-off) nodes with one sink+sid per filter."""
    nodes = []
    for covering in (True, False):
        cfg = {"broker": dict(conf or {},
                              subscription_covering=covering)}
        node = Node(cfg)
        sinks, sids = {}, {}
        for i, f in enumerate(filters):
            s = Sink()
            sid = node.broker.register(s, f"c{i}")
            node.broker.subscribe(sid, f, {"qos": 0})
            sinks[f], sids[f] = s, sid
        nodes.append((node, sinks, sids))
    return nodes


def _route_and_compare(on, off, topics, payload=b"x"):
    (n1, s1, _), (n2, s2, _) = on, off
    c1 = n1.device_engine.route_batch([mkmsg(t, payload)
                                       for t in topics])
    c2 = n2.device_engine.route_batch([mkmsg(t, payload)
                                       for t in topics])
    assert c1 is not None and c2 is not None
    assert c1 == c2, (c1, c2)
    # per-session delivery ORDER, not just counts
    for f in s1:
        assert s1[f].got == s2[f].got, f
    return c1


TRAFFIC = ["s/u/t", "s/u/v", "s/q", "s/a/t", "q/1", "w/x", "nomatch/z",
           "top/a/b", "top/zz", "top/x1", "d3/m3/t3", "$SYS/x"]


class TestEngineTwins:
    @pytest.mark.parametrize("pop", sorted(POPULATIONS))
    def test_clean_dirty_churn_twins(self, pop):
        filters = POPULATIONS[pop]
        on, off = _mk_twin_nodes(filters)
        # clean snapshot, repeated (cache-hit rounds included)
        for rnd in range(3):
            _route_and_compare(on, off, TRAFFIC, b"r%d" % rnd)
        if pop == "mixed":
            st = on[0].device_engine.stats()
            assert st["cover"] and st["cover"]["covered"] > 0
        # dirty overlay: post-snapshot subscriptions — for the shapes
        # population "s/u/new" is covered by the built "s/#" (append
        # path on the on-twin); for mixed there is no covering root, so
        # it rides the overlay on both; "fresh/+" is uncovered always
        for node, sinks, _sids in (on, off):
            s = Sink()
            sid = node.broker.register(s, "dirty")
            node.broker.subscribe(sid, "s/u/new", {"qos": 0})
            node.broker.subscribe(sid, "fresh/+", {"qos": 0})
            sinks["s/u/new"] = sinks["fresh/+"] = s
        _route_and_compare(on, off, TRAFFIC + ["s/u/new", "fresh/go"],
                           b"dirty")
        # churn: unsubscribe a BUILT literal filter (covered on the
        # on-twin — its tombstone must drop it from the expanded rows)
        victim = [f for f in filters if "+" not in f and "#" not in f][0]
        for node, _sinks, sids in (on, off):
            node.broker.unsubscribe(sids[victim], victim)
        _route_and_compare(on, off, TRAFFIC, b"churn")

    def test_trie_both_twins(self):
        """shape_cap=0 forces BOTH twins onto the trie backend."""
        filters = POPULATIONS["shapes"]
        on, off = _mk_twin_nodes(filters)
        for node, _sinks, _sids in (on, off):
            node.device_engine.shape_cap = 0
        for rnd in range(2):
            _route_and_compare(on, off, TRAFFIC, b"t%d" % rnd)
        assert on[0].device_engine.stats()["backend"] == "trie"
        assert off[0].device_engine.stats()["backend"] == "trie"
        assert on[0].device_engine.stats()["cover"]["covered"] > 0

    def test_shared_groups_post_expansion(self):
        """Shared-sub picks resolve on EXPANDED rows: a group on a
        covered filter must rotate identically across the twins."""
        filters = ["g/#", "g/+/t", "g/a/t"]
        on, off = _mk_twin_nodes(filters)
        for node, sinks, _sids in (on, off):
            a, bb = Sink(), Sink()
            node.broker.subscribe(node.broker.register(a, "m1"),
                                  "$share/grp/g/+/t")
            node.broker.subscribe(node.broker.register(bb, "m2"),
                                  "$share/grp/g/+/t")
            sinks["m1"], sinks["m2"] = a, bb
        for rnd in range(3):
            _route_and_compare(
                on, off, ["g/a/t", "g/b/t", "g/c", "g/a/t"],
                b"s%d" % rnd)

    def test_unsubscribe_covered_filter(self):
        """Deleting a covered filter must stop its deliveries on both
        twins identically (tombstone against the expanded set)."""
        filters = ["s/#", "s/+/t", "s/u/t"]
        on, off = _mk_twin_nodes(filters)
        _route_and_compare(on, off, ["s/u/t"])
        for node, _sinks, sids in (on, off):
            node.broker.unsubscribe(sids["s/+/t"], "s/+/t")
        _route_and_compare(on, off, ["s/u/t", "s/x/t"])


# ---------------- append path & cache invalidation ----------------

class TestAppendAndCache:
    def _node(self, **conf):
        node = Node({"broker": dict(conf, subscription_covering=True)})
        return node

    def test_covered_new_sub_is_csr_append_not_rebuild(self):
        node = self._node()
        s = Sink()
        sid = node.broker.register(s, "base")
        for f in ("s/#", "s/+/t", "other/x"):
            node.broker.subscribe(sid, f, {"qos": 0})
        eng = node.device_engine
        assert eng.route_batch([mkmsg("s/q")]) == [1]
        # new covered sub -> append, no overlay row, no rebuild
        s2 = Sink()
        node.broker.subscribe(node.broker.register(s2, "new"), "s/b")
        assert node.metrics.val("routing.cover.appends") == 1
        st = eng.stats()
        assert st["delta_filters"] == 0
        assert st["cover"]["appends"] == 1
        # s/b now matches s/# (base) and the appended s/b (new)
        assert eng.route_batch([mkmsg("s/b")]) == [2]
        assert [g[1] for g in s2.got] == ["s/b"]

    def test_cache_invalidation_walks_expanded_set(self):
        """The cached-topic drop must test the EXPANDED set: a cached
        topic whose row came from a covering root must be dropped when
        an appended filter matches it."""
        node = self._node()
        s = Sink()
        sid = node.broker.register(s, "base")
        for f in ("s/#", "s/+/t"):
            node.broker.subscribe(sid, f, {"qos": 0})
        eng = node.device_engine
        # seed the match cache for the topic the append will match
        # (batches must exceed the smallest class so analysis runs)
        assert eng.route_batch([mkmsg("s/b")] * 70
                               + [mkmsg("s/c")] * 70) == [1] * 140
        assert eng.route_batch([mkmsg("s/b")] * 70) == [1] * 70
        hits0 = eng.stats()["match_cache"]["hits"]
        assert hits0 >= 1
        s2 = Sink()
        node.broker.subscribe(node.broker.register(s2, "new"), "s/b")
        assert node.metrics.val("routing.cover.appends") == 1
        # cached row for s/b was dropped: the new subscriber delivers
        assert eng.route_batch([mkmsg("s/b")] * 70) == [2] * 70
        assert s2.got and all(g[1] == "s/b" for g in s2.got)
        # unrelated cached topics survive (drop is per expanded match,
        # not a flush)
        assert eng.route_batch([mkmsg("s/c")] * 70) == [1] * 70

    def test_overlay_delete_drops_cached_expanded_topic(self):
        node = self._node()
        s, s2 = Sink(), Sink()
        sid = node.broker.register(s, "base")
        for f in ("s/#", "s/+/t"):
            node.broker.subscribe(sid, f, {"qos": 0})
        sid2 = node.broker.register(s2, "victim")
        node.broker.subscribe(sid2, "s/u/t", {"qos": 0})
        eng = node.device_engine
        assert eng.route_batch([mkmsg("s/u/t")] * 70) == [3] * 70
        assert eng.route_batch([mkmsg("s/u/t")] * 70) == [3] * 70
        node.broker.unsubscribe(sid2, "s/u/t")
        # the drop walked the expanded set: covered filter's topic
        # re-resolves without the removed subscriber
        assert eng.route_batch([mkmsg("s/u/t")] * 70) == [2] * 70

    def test_new_covering_filter_counts_toward_compaction(self):
        node = self._node()
        s = Sink()
        sid = node.broker.register(s, "base")
        for f in ("s/#", "s/+/t", "q/x"):
            node.broker.subscribe(sid, f, {"qos": 0})
        eng = node.device_engine
        eng.rebuild()
        churn0 = eng._cover_churn
        # a new COVERING filter cannot append (it must own a segment):
        # it rides the overlay and marks cover churn for compaction
        node.broker.subscribe(sid, "q/#", {"qos": 0})
        assert eng._cover_churn > churn0
        assert node.metrics.val("routing.cover.append_rejects") >= 1
        assert eng._compaction_reason() in (None, "covering",
                                            "overflow", "churn",
                                            "tombstones")


# ---------------- knob & surfaces ----------------

class TestKnobAndSurfaces:
    def test_config_beats_env_beats_default(self, monkeypatch):
        assert DE.resolve_subscription_covering() is True
        monkeypatch.setenv("EMQX_TPU_COVERING", "0")
        assert DE.resolve_subscription_covering() is False
        assert DE.resolve_subscription_covering(True) is True
        monkeypatch.setenv("EMQX_TPU_COVERING", "off")
        assert DE.resolve_subscription_covering() is False
        monkeypatch.delenv("EMQX_TPU_COVERING")
        assert DE.resolve_subscription_covering(False) is False

    def test_env_routes_engine_and_mesh(self, monkeypatch):
        monkeypatch.setattr(DE, "_ENV_COVERING", False)
        node = Node({})
        assert node.device_engine.subscription_covering is False
        node2 = Node({"broker": {"subscription_covering": True}})
        assert node2.device_engine.subscription_covering is True

    def test_stats_and_ledger_category(self):
        node = Node({"broker": {"subscription_covering": True}})
        s = Sink()
        sid = node.broker.register(s, "c")
        for f in ("s/#", "s/+/t", "s/u/t"):
            node.broker.subscribe(sid, f, {"qos": 0})
        eng = node.device_engine
        eng.rebuild()
        st = eng.stats()
        assert st["subscription_covering"] is True
        cov = st["cover"]
        assert cov["roots"] >= 1 and cov["covered"] == 2
        assert cov["reduction"] == pytest.approx(3.0)
        # expansion-CSR buffers ride their own HBM category
        led = node.hbm_ledger
        assert led is not None
        cats = led.section()["categories"]
        assert "cover_csr" in cats
        assert cats["cover_csr"]["live_bytes"] > 0

    def test_off_twin_has_no_cover_state(self):
        node = Node({"broker": {"subscription_covering": False}})
        s = Sink()
        sid = node.broker.register(s, "c")
        for f in ("s/#", "s/+/t"):
            node.broker.subscribe(sid, f, {"qos": 0})
        node.device_engine.rebuild()
        st = node.device_engine.stats()
        assert st["subscription_covering"] is False
        assert st["cover"] is None


# ---------------- workloads generator ----------------

class TestWorkloads:
    def test_cover_ratio_is_detected(self):
        from tools.workloads import cover_heavy_filters
        filters = sorted(set(cover_heavy_filters(400, cover_ratio=0.5)))
        intern = InternTable()
        rows, lens, dollar = _encode(intern, filters)
        covers, inc = C.detect_covers(rows, lens, dollar)
        owner = C.assign_owners(covers, inc)
        frac = (owner >= 0).sum() / len(filters)
        assert frac >= 0.4, frac

    def test_legacy_population_is_cover_free(self):
        from tools.workloads import shape_spread_filters
        filters = shape_spread_filters(300, tail_hash=True)
        intern = InternTable()
        rows, lens, dollar = _encode(intern, filters)
        covers, _inc = C.detect_covers(rows, lens, dollar)
        assert all(len(c) == 0 for c in covers)

    def test_concretize_matches_its_filter(self):
        from tools.workloads import (concretize, cover_heavy_filters,
                                     shape_spread_filters)
        intern = InternTable()
        for f in (cover_heavy_filters(60, cover_ratio=0.5)
                  + shape_spread_filters(20)):
            t = concretize(f)
            trie = HostTrie()
            trie.insert(intern.encode_filter(f.split("/")), 0)
            ids = [intern.lookup(w) for w in t.split("/")]
            assert trie.match(ids, is_dollar=t.startswith("$")) == [0], \
                (f, t)


# ---------------- mesh twins ----------------

@pytest.mark.parametrize("route", [2, 4, 8])
def test_mesh_twin_bit_identical(route):
    filters = (["m/#", "m/+/t"] + [f"m/{i}/t" for i in range(6)]
               + [f"n{i}/+/w" for i in range(4)] + ["$SYS/#", "deep/#"])
    topics = ([f"m/{i}/t" for i in range(6)]
              + ["m/zz/t", "m/q", "n1/a/w", "$SYS/x", "none/x"])
    results = []
    for covering in (True, False):
        node = Node({"broker": {
            "multichip": {"enable": True, "devices": route, "dp": 1,
                          "max_batch": 32},
            "device_min_batch": 1,
            "subscription_covering": covering}})
        sinks = {}
        for i, f in enumerate(filters):
            s = Sink()
            node.broker.subscribe(node.broker.register(s, f"c{i}"), f)
            sinks[f] = s
        eng = node.device_engine
        eng.rebuild()
        counts = []
        for rnd in range(2):
            counts.append(eng.route_batch(
                [mkmsg(t, b"r%d" % rnd) for t in topics], wait=True))
        # churn: covered new sub + removal, served via per-shard rebuild
        s = Sink()
        node.broker.subscribe(node.broker.register(s, "late"), "m/late/t")
        sinks["m/late/t"] = s
        counts.append(eng.route_batch(
            [mkmsg(t, b"c") for t in topics + ["m/late/t"]], wait=True))
        st = eng.stats()
        assert st["subscription_covering"] is covering
        if covering:
            assert st["cover"]["covered"] > 0
        results.append((counts, {f: sinks[f].got for f in sinks}))
    (c_on, got_on), (c_off, got_off) = results
    assert c_on == c_off
    assert got_on == got_off
