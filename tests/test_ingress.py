"""Columnar zero-copy PUBLISH ingress tests (ISSUE 11).

Covers the whole layer: knob resolution, the parser's feed_columnar
equivalence against the strict per-packet path, the differential fuzz
corpus (columnar vs strict oracle — ZERO divergences; the same corpus
runs under `make -C native test-asan`), the burst hand-off through a
live broker over real TCP (A/B twin: delivery counts, per-publisher
order and telemetry shape vs `columnar_ingress=0`), submit_burst
semantics, the burst pre-encode's intern-version guard, and the
SO_REUSEPORT acceptor lanes.
"""

import asyncio
import os
import random
import subprocess

import numpy as np
import pytest

from emqx_tpu import native
from emqx_tpu.broker.connection import (Listener, resolve_columnar_ingress,
                                        resolve_ingress_lanes)
from emqx_tpu.broker.node import Node
from emqx_tpu.client import Client
from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt import packet as P
from emqx_tpu.mqtt.frame import (FrameError, FrameParser, PublishBurst,
                                 serialize)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------
# knob resolution
# ---------------------------------------------------------------------
class TestKnobs:
    def test_columnar_default_on(self, monkeypatch):
        monkeypatch.delenv("EMQX_TPU_COLUMNAR_INGRESS", raising=False)
        assert resolve_columnar_ingress() is True

    @pytest.mark.parametrize("val", ["0", "false", "off"])
    def test_columnar_env_off(self, monkeypatch, val):
        monkeypatch.setenv("EMQX_TPU_COLUMNAR_INGRESS", val)
        assert resolve_columnar_ingress() is False

    def test_columnar_config_beats_env(self, monkeypatch):
        monkeypatch.setenv("EMQX_TPU_COLUMNAR_INGRESS", "0")
        assert resolve_columnar_ingress(True) is True
        monkeypatch.setenv("EMQX_TPU_COLUMNAR_INGRESS", "1")
        assert resolve_columnar_ingress(False) is False

    def test_lanes_default(self, monkeypatch):
        monkeypatch.delenv("EMQX_TPU_INGRESS_LANES", raising=False)
        assert resolve_ingress_lanes() == min(4, os.cpu_count() or 1)

    def test_lanes_env_and_config(self, monkeypatch):
        monkeypatch.setenv("EMQX_TPU_INGRESS_LANES", "2")
        assert resolve_ingress_lanes() == 2
        assert resolve_ingress_lanes(6) == 6   # config beats env

    def test_lanes_malformed_fails_loudly(self, monkeypatch):
        monkeypatch.setenv("EMQX_TPU_INGRESS_LANES", "two")
        with pytest.raises(ValueError):
            resolve_ingress_lanes()
        with pytest.raises(ValueError):
            resolve_ingress_lanes(0)

    def test_columnar_off_forces_one_lane(self):
        node = Node({"broker": {"columnar_ingress": False,
                                "ingress_lanes": 4}})
        assert node.columnar_ingress is False
        assert node.ingress_lanes == 1


# ---------------------------------------------------------------------
# parser equivalence
# ---------------------------------------------------------------------
def _flatten(items):
    """Columnar items -> the Packet list the per-packet path yields."""
    out = []
    for it in items:
        if isinstance(it, PublishBurst):
            for j in range(len(it)):
                out.append(P.Publish(
                    topic=it.topics[j], payload=it.payloads[j],
                    qos=it.qos[j], retain=it.retain[j], dup=it.dup[j],
                    packet_id=it.pids[j], properties=it.props[j]))
        else:
            out.append(it)
    return out


def _mixed_stream(rng, ver, n=400):
    pkts = []
    for _ in range(n):
        k = rng.randrange(10)
        if k < 6:
            qos = rng.randrange(3)
            props = {}
            if ver == 5 and rng.randrange(3) == 0:
                props = {"message_expiry_interval": rng.randrange(1000),
                         "user_property": [("k", "v" * rng.randrange(5))]}
            pkts.append(P.Publish(
                topic=f"t/{rng.randrange(30)}/x",
                payload=bytes(rng.randrange(200)), qos=qos,
                retain=bool(rng.randrange(2)), dup=bool(qos and
                                                        rng.randrange(2)),
                packet_id=rng.randrange(1, 65535) if qos else None,
                properties=props))
        elif k == 6:
            pkts.append(P.Pingreq())
        elif k == 7:
            pkts.append(P.Puback(packet_id=rng.randrange(1, 65535)))
        elif k == 8:
            pkts.append(P.Subscribe(packet_id=rng.randrange(1, 65535),
                                    filters=[("a/+",
                                              P.SubOpts(qos=1))]))
        else:
            pkts.append(P.Pubrel(packet_id=rng.randrange(1, 65535)))
    return b"".join(serialize(p, ver) for p in pkts), pkts


class TestFeedColumnar:
    @pytest.mark.parametrize("ver", [4, 5])
    def test_mixed_stream_equivalence(self, ver):
        rng = random.Random(11 + ver)
        stream, _src = _mixed_stream(rng, ver)
        a = FrameParser(version=ver).feed(stream)
        cols = FrameParser(version=ver)
        b = _flatten(cols.feed_columnar(stream))
        assert len(a) == len(b)
        for x, y in zip(a, b):
            assert type(x) is type(y)
            if isinstance(x, P.Publish):
                assert (x.topic, bytes(x.payload), x.qos, x.retain,
                        x.dup, x.packet_id, x.properties or {}) == \
                       (y.topic, bytes(y.payload), y.qos, y.retain,
                        y.dup, y.packet_id, y.properties or {})
            else:
                assert x == y
        assert cols.pending_bytes == 0

    @pytest.mark.parametrize("ver", [4, 5])
    def test_chunked_equivalence(self, ver):
        """Frames split across arbitrary read boundaries: the columnar
        path buffers partial frames exactly like the per-packet path."""
        rng = random.Random(23 + ver)
        stream, _ = _mixed_stream(rng, ver, n=250)
        a_parser = FrameParser(version=ver)
        b_parser = FrameParser(version=ver)
        a, b = [], []
        pos = 0
        while pos < len(stream):
            step = rng.choice([1, 7, 100, 1500, 5000, 9000])
            chunk = stream[pos:pos + step]
            pos += step
            a.extend(a_parser.feed(chunk))
            b.extend(_flatten(b_parser.feed_columnar(chunk)))
        assert len(a) == len(b)
        assert a_parser.pending_bytes == b_parser.pending_bytes
        for x, y in zip(a, b):
            if isinstance(x, P.Publish):
                assert (x.topic, bytes(x.payload), x.qos,
                        x.packet_id) == (y.topic, bytes(y.payload),
                                         y.qos, y.packet_id)
            else:
                assert x == y

    def test_small_reads_stay_per_packet(self):
        p = FrameParser(version=4)
        items = p.feed_columnar(serialize(
            P.Publish(topic="a", payload=b"b", qos=0), 4))
        assert len(items) == 1 and isinstance(items[0], P.Publish)

    def test_unknown_version_stays_per_packet(self):
        """Pre-CONNECT bytes must parse after CONNECT fixes the
        version — the columnar decode never runs at version=None."""
        p = FrameParser()   # server-side fresh connection
        conn = serialize(P.Connect(proto_name="MQTT", proto_ver=4,
                                   clientid="c"), 4)
        blob = conn + b"".join(
            serialize(P.Publish(topic=f"t/{i}", payload=b"x" * 100,
                                qos=0), 4) for i in range(200))
        items = p.feed_columnar(blob)
        assert isinstance(items[0], P.Connect)
        assert sum(1 for it in items
                   if isinstance(it, P.Publish)) == 200


# ---------------------------------------------------------------------
# differential fuzz: columnar vs strict parser as oracle
# ---------------------------------------------------------------------
def _mutate(rng, stream: bytes) -> bytes:
    kind = rng.randrange(7)
    b = bytearray(stream)
    if not b:
        return stream
    if kind == 0:      # truncate mid-frame
        return bytes(b[:rng.randrange(len(b))])
    if kind == 1:      # flip random bytes
        for _ in range(rng.randrange(1, 6)):
            i = rng.randrange(len(b))
            b[i] ^= 1 << rng.randrange(8)
        return bytes(b)
    if kind == 2:      # unterminated varint (malformed)
        return bytes(b) + bytes([0x30, 0x80, 0x80, 0x80, 0x80, 0x01])
    if kind == 3:      # qos=3 PUBLISH (strict: invalid_qos)
        return bytes([0x36, 0x04, 0x00, 0x01, 0x61, 0x70]) + bytes(b)
    if kind == 4:      # packet id 0 on a qos1 PUBLISH
        return bytes([0x32, 0x05, 0x00, 0x01, 0x61, 0x00, 0x00]) \
            + bytes(b)
    if kind == 5:      # non-utf8 topic bytes
        return bytes([0x30, 0x04, 0x00, 0x02, 0xC3, 0x28]) + bytes(b)
    # truncated topic length past the body
    return bytes([0x30, 0x02, 0x00, 0x63]) + bytes(b)


def _drive(parser_kind: str, ver: int, chunks) -> tuple:
    """Feed chunks; return (normalized packets, error code or None,
    pending bytes) — the differential oracle's observable state."""
    p = FrameParser(version=ver)
    out = []
    err = None
    for chunk in chunks:
        try:
            if parser_kind == "columnar":
                items = _flatten(p.feed_columnar(chunk))
            else:
                items = p.feed(chunk)
        except FrameError as e:
            err = e.code
            break
        for pkt in items:
            if isinstance(pkt, P.Publish):
                out.append(("pub", pkt.topic, bytes(pkt.payload),
                            pkt.qos, pkt.retain, pkt.dup,
                            pkt.packet_id, repr(pkt.properties or {})))
            else:
                out.append(repr(pkt))
    return out, err, (p.pending_bytes if err is None else -1)


def fuzz_corpus(n_streams: int = 120):
    """The seeded corpus (also run under the Makefile asan target):
    valid mixed streams + mutations (truncated varints, bad props,
    qos2 flows, split-across-reads, flag/byte flips, max-frame
    overflows), each fed at several chunkings."""
    rng = random.Random(1299709)
    for si in range(n_streams):
        ver = 5 if si % 2 else 4
        stream, _ = _mixed_stream(rng, ver, n=rng.randrange(40, 200))
        if si % 3:
            stream = _mutate(rng, stream)
        if si % 7 == 0:   # qos2 flow: PUBLISH qos2 + PUBREL mixed
            stream = serialize(P.Publish(topic="q2/a", payload=b"z",
                                         qos=2, packet_id=9), ver) \
                + serialize(P.Pubrel(packet_id=9), ver) + stream
        # several chunkings per stream, including split-across-reads
        chunkings = [[stream]]
        for _ in range(2):
            chunks, pos = [], 0
            while pos < len(stream):
                step = rng.choice([1, 3, 50, 1024, 4096, 8192])
                chunks.append(stream[pos:pos + step])
                pos += step
            chunkings.append(chunks)
        for chunks in chunkings:
            yield ver, chunks


class TestDifferentialFuzz:
    def test_zero_divergences(self):
        n = 0
        for ver, chunks in fuzz_corpus():
            a = _drive("strict", ver, chunks)
            b = _drive("columnar", ver, chunks)
            assert a == b, (
                f"divergence on stream #{n} (ver {ver}): "
                f"strict={a[1:]}, columnar={b[1:]}")
            n += 1
        assert n > 300

    @pytest.mark.skipif(not native.available(),
                        reason="native lib not built")
    def test_native_vs_python_decode_bit_identical(self):
        """The pure-python fallback mirrors the C decoder array for
        array over the fuzz corpus (the repo's fallback-parity
        pattern)."""
        rng = random.Random(7)
        for si in range(60):
            ver = 5 if si % 2 else 4
            stream, _ = _mixed_stream(rng, ver, n=60)
            if si % 3:
                stream = _mutate(rng, stream)
            try:
                off, lens, _cons = native.frame_scan_np(stream)
            except native.FrameScanError:
                continue
            a = native.publish_decode_columnar(stream, off, lens,
                                               ver == 5)
            b = {k: np.zeros_like(v) for k, v in a.items()}
            native._publish_decode_columnar_py(stream, off, lens,
                                               ver == 5, b)
            for k in a:
                assert (a[k] == b[k]).all(), (si, k)


# ---------------------------------------------------------------------
# live broker A/B twin over real TCP
# ---------------------------------------------------------------------
@pytest.fixture()
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


def run(loop, coro, timeout=60):
    return loop.run_until_complete(asyncio.wait_for(coro, timeout))


async def _drive_broker(columnar: bool, lanes: int = 1) -> dict:
    node = Node({"broker": {"columnar_ingress": columnar,
                            "ingress_lanes": lanes}})
    lst = Listener(node, bind="127.0.0.1", port=0)
    await lst.start()
    sub = Client(port=lst.port, clientid="sub")
    await sub.connect()
    await sub.subscribe("t/#", qos=1)
    pub = Client(port=lst.port, clientid="pub")
    await pub.connect()
    # one big write: interleaved qos0 (bulk) — large enough that the
    # columnar node takes the burst path
    blob = bytearray()
    for i in range(1500):
        blob += serialize(P.Publish(topic=f"t/{i % 8}",
                                    payload=b"%06d" % i, qos=0), 4)
    pub._writer.write(bytes(blob))
    await pub._writer.drain()
    acks = []
    for i in range(30):
        n = await pub.publish(f"t/q1/{i % 4}", b"%06d" % i, qos=1)
        acks.append(n)
    got = []
    while len(got) < 1530:
        m = await asyncio.wait_for(sub.messages.get(), 15)
        got.append((m.topic, bytes(m.payload)))
    snap = node.pipeline_telemetry.snapshot()
    res = {
        "got": got,
        "acks": acks,
        "publish": node.metrics.val("messages.publish"),
        "recv": node.metrics.val("packets.publish.received"),
        "snapshot_sections": sorted(snap.keys()),
        "ingress": snap.get("ingress"),
        "lane_accepted": sum(
            v for k, v in node.metrics.all().items()
            if k.startswith("pipeline.ingress.lane")),
        "lane_servers": len(lst._lane_servers),
    }
    await pub.close()
    await sub.close()
    await lst.stop()
    if node.publish_batcher is not None:
        await node.publish_batcher.stop()
    return res


class TestBurstTwin:
    def test_ab_identical_delivery_and_shape(self, loop):
        """EMQX_TPU_COLUMNAR_INGRESS=0 restores the per-packet path
        exactly: identical packets received, delivery counts,
        per-publisher order — and the telemetry snapshot has no
        `ingress` section."""
        on = run(loop, _drive_broker(True), 120)
        off = run(loop, _drive_broker(False), 120)
        assert on["got"] == off["got"]           # order + payload twin
        assert on["acks"] == off["acks"]         # qos1 counts twin
        assert on["publish"] == off["publish"]
        assert on["recv"] == off["recv"]
        # per-publisher order: qos0 payload seq monotone
        seqs = [p for t, p in on["got"] if not t.startswith("t/q1")]
        assert seqs == sorted(seqs)
        assert on["ingress"] is not None
        assert on["ingress"]["rows"] >= 1500
        assert "ingress" not in off["snapshot_sections"]

    def test_acceptor_lanes(self, loop):
        res = run(loop, _drive_broker(True, lanes=2), 120)
        assert res["lane_servers"] == 2
        assert res["lane_accepted"] == 2         # sub + pub conns
        res_off = run(loop, _drive_broker(False, lanes=2), 120)
        assert res_off["lane_servers"] == 0      # single accept loop


class TestSubmitBurst:
    def test_order_futures_and_backpressure(self, loop):
        from emqx_tpu.broker.message import make
        node = Node()
        bt = node.publish_batcher
        bt.max_pending = 8

        async def go():
            rows = [(make("p", i % 2, f"sb/{i}", b"%d" % i), i % 2 == 1)
                    for i in range(12)]
            futs = bt.submit_burst(rows)
            # every qos1 row has a future; the last row is futured too
            # (backpressure bound crossed)
            assert set(futs) >= {i for i in range(12) if i % 2}
            assert 11 in futs
            assert [m.topic for m, _f in bt._queue] == \
                [f"sb/{i}" for i in range(12)]
            for f in futs.values():
                assert (await f) == 0   # no subscribers
        run(loop, go())
        loop.run_until_complete(bt.stop())

    def test_preencode_intern_version_guard(self):
        """A filter word interned between the burst pre-encode and the
        window encode invalidates the memo — the window re-encodes, so
        encodings are bit-identical to the unmemoized path."""
        node = Node()
        eng = node.device_engine
        eng.rebuild()
        topics = ["pe/a/b", "pe/c"]
        eng.preencode_burst(topics)
        assert eng._burst_enc is not None
        memo_hit = eng._encode_publish_batch(topics)
        from emqx_tpu.ops.match import encode_topics_str
        fresh = encode_topics_str(eng.intern, topics, eng.max_levels)
        for a, b in zip(memo_hit, fresh):
            assert (np.asarray(a) == np.asarray(b)).all()
        # intern a new word: the guard must drop the memo
        eng.intern.intern("pe-new-word")
        stale_guarded = eng._encode_publish_batch(topics)
        fresh2 = encode_topics_str(eng.intern, topics, eng.max_levels)
        for a, b in zip(stale_guarded, fresh2):
            assert (np.asarray(a) == np.asarray(b)).all()


# ---------------------------------------------------------------------
# native-lib tier-1 gate (satellite): a build break must FAIL, not
# silently demote every native test to the python fallback
# ---------------------------------------------------------------------
class TestNativeGate:
    def test_native_lib_builds_and_loads(self):
        if os.environ.get("EMQX_NATIVE_LIB"):
            assert native.available(), \
                "EMQX_NATIVE_LIB is set but did not load"
            return
        lib = os.path.join(REPO, "native", "libemqx_native.so")
        if not os.path.exists(lib):
            subprocess.run(["make", "-C", os.path.join(REPO, "native")],
                           check=True, capture_output=True, timeout=120)
        assert os.path.exists(lib), \
            "native build produced no libemqx_native.so"
        assert native.available(), (
            "libemqx_native.so exists but failed to load — every "
            "native test would silently run the python fallback")

    @pytest.mark.slow
    def test_make_asan_smoke(self):
        """`make -C native asan` builds and the sanitized lib loads in
        a clean subprocess (LD_PRELOADed ASAN runtime)."""
        ndir = os.path.join(REPO, "native")
        subprocess.run(["make", "-C", ndir, "asan"], check=True,
                       capture_output=True, timeout=180)
        cxx = os.environ.get("CXX", "g++")
        asan_rt = subprocess.run(
            [cxx, "-print-file-name=libasan.so"],
            capture_output=True, text=True).stdout.strip()
        env = dict(os.environ,
                   EMQX_NATIVE_LIB=os.path.join(
                       ndir, "libemqx_native_asan.so"),
                   LD_PRELOAD=asan_rt,
                   ASAN_OPTIONS="detect_leaks=0")
        sp = subprocess.run(
            [os.sys.executable if hasattr(os, "sys") else "python",
             "-c",
             "import emqx_tpu.native as n; assert n.available()"],
            capture_output=True, text=True, env=env, cwd=REPO,
            timeout=120)
        assert sp.returncode == 0, sp.stderr[-500:]
