"""End-to-end message latency SLO observatory (ISSUE 13).

Coverage, per the issue's satellite list:

- knob matrix: broker.latency_observatory / EMQX_TPU_LATENCY and
  broker.slo_route_p99_ms / EMQX_TPU_SLO_ROUTE_P99_MS
  (config-beats-env-beats-default, malformed fails loudly)
- knob-off A/B twin: EMQX_TPU_LATENCY=0 ⇒ no observatory object, no
  `latency` snapshot section, REST 404, bit-identical delivery counts
  and per-publisher order
- per-path attribution oracle: device / host / a FORCED host-fallback
  window (prepare_window declines) / a journal replay (injected
  dispatch fault) each land in their own (qos, path) series
- burst-vs-per-packet ingress-stamp equivalence (the PR 11 twins)
- the sub-millisecond Histogram mode (substeps) unit behavior + the
  stage-family migration (names unchanged, quarter-octave ladder)
- SLO engine: burn-rate windows, breach exemplars linked to the
  flight-recorder trace of the exact slow message, hook throttling
- exporter expositions (snapshot section, $SYS, Prometheus, REST)
- deterministic <3%-per-message overhead guard at default sampling
- tools/latency_report.py: report + the exit-2 CI gate against a
  p99-less bench row
"""

import asyncio
import json
import os
import sys
import time

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))

from emqx_tpu.broker import latency as L                  # noqa: E402
from emqx_tpu.broker import supervise as S                # noqa: E402
from emqx_tpu.broker.hooks import Hooks                   # noqa: E402
from emqx_tpu.broker.message import Message, make         # noqa: E402
from emqx_tpu.broker.metrics import Histogram, Metrics    # noqa: E402
from emqx_tpu.broker.node import Node                     # noqa: E402
from emqx_tpu.broker.trace import FlightRecorder          # noqa: E402
from emqx_tpu.mqtt import constants as C                  # noqa: E402
from emqx_tpu.mqtt import packet as P                     # noqa: E402
from emqx_tpu.mqtt.frame import (FrameParser, PublishBurst,  # noqa: E402
                                 serialize)


def run(coro, timeout=180):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


class Sink:
    def __init__(self):
        self.got = []

    def deliver(self, topic_filter, msg):
        self.got.append((msg.topic, bytes(msg.payload)))
        return True


def _mk_node(**over):
    conf = {"device_fanout_cap": 16, "device_slot_cap": 4,
            "device_min_batch": 4, "batch_window_us": 1000,
            "deliver_lanes": 2}
    conf.update(over)
    return Node({"broker": conf})


def _subscribe(node, n=8):
    sinks = []
    for i in range(n):
        s = Sink()
        sid = node.broker.register(s, f"c{i}")
        node.broker.subscribe(sid, f"t/{i}/+", {"qos": 1})
        sinks.append(s)
    return sinks


def _stamped(from_, qos, topic, payload=b""):
    """A publish message carrying a real ingress stamp — what the
    frame parser + channel produce for socket traffic."""
    m = make(from_, qos, topic, payload)
    m.ingress_ns = time.perf_counter_ns()
    return m


async def _warm(node, n=8):
    node.device_engine.route_batch(
        [make("p", 0, f"t/{i}/w", b"") for i in range(n)])
    eng = node.device_engine
    deadline = time.monotonic() + 90
    while not eng.batch_class_warm(n) and time.monotonic() < deadline:
        eng._kick_class_warm()
        await asyncio.sleep(0.05)
    assert eng.batch_class_warm(n), "device classes never warmed"


async def _drive(node, windows=4, n=8, qos=1, warm=True, tag="x"):
    if warm:
        await _warm(node, n)
    out = []
    for w in range(windows):
        out.extend(await asyncio.gather(*[
            node.publish_async(
                _stamped("p", qos, f"t/{i}/{tag}", b"m%d" % w))
            for i in range(n)]))
    pool = node.deliver_lanes
    if pool is not None and pool.busy():
        await pool.drain()
    return out


def _routed_paths(node):
    """The (leg, qos, path) series the observatory actually recorded."""
    return {key for key, h in
            node.latency_observatory._hist.items() if h.count}


# ---------- knob resolution ----------

class TestKnobs:
    def test_observatory_default_on(self, monkeypatch):
        monkeypatch.delenv("EMQX_TPU_LATENCY", raising=False)
        assert L.resolve_latency_observatory() is True

    def test_env_off(self, monkeypatch):
        monkeypatch.setenv("EMQX_TPU_LATENCY", "0")
        assert L.resolve_latency_observatory() is False

    def test_config_beats_env(self, monkeypatch):
        monkeypatch.setenv("EMQX_TPU_LATENCY", "0")
        assert L.resolve_latency_observatory(True) is True
        monkeypatch.setenv("EMQX_TPU_LATENCY", "1")
        assert L.resolve_latency_observatory(False) is False

    def test_objective_default(self, monkeypatch):
        monkeypatch.delenv("EMQX_TPU_SLO_ROUTE_P99_MS", raising=False)
        assert L.resolve_slo_route_p99_ms() == 2.0

    def test_objective_env_and_config(self, monkeypatch):
        monkeypatch.setenv("EMQX_TPU_SLO_ROUTE_P99_MS", "5.5")
        assert L.resolve_slo_route_p99_ms() == 5.5
        # config beats env
        assert L.resolve_slo_route_p99_ms(1.25) == 1.25

    def test_objective_malformed_fails_loudly(self, monkeypatch):
        monkeypatch.setenv("EMQX_TPU_SLO_ROUTE_P99_MS", "fast")
        with pytest.raises(ValueError):
            L.resolve_slo_route_p99_ms()
        with pytest.raises(ValueError):
            L.resolve_slo_route_p99_ms(0)
        with pytest.raises(ValueError):
            L.resolve_slo_route_p99_ms(-3)

    def test_node_env_knob_off(self, monkeypatch):
        monkeypatch.setenv("EMQX_TPU_LATENCY", "0")
        node = _mk_node()
        assert node.latency_observatory is None
        assert node.pipeline_telemetry.observatory is None
        assert node.broker.latency_obs is None
        assert node.publish_batcher.obs is None


# ---------- sub-millisecond Histogram mode (satellite 2) ----------

class TestFineHistogram:
    def test_bounds_quarter_octave(self):
        h = Histogram("x", lo=1e-6, n_buckets=16, substeps=4)
        for a, b in zip(h.bounds, h.bounds[1:]):
            assert b / a == pytest.approx(2 ** 0.25)
        # every 4th bound is an exact octave of lo
        assert h.bounds[4] == pytest.approx(2e-6)
        assert h.bounds[8] == pytest.approx(4e-6)

    def test_index_matches_reference(self):
        h = Histogram("x", lo=1e-6, n_buckets=40, substeps=4)

        def ref_index(v):
            if v <= h.lo:
                return 0
            for i, b in enumerate(h.bounds):
                if v <= b:
                    return i
            return len(h.bounds)

        import random
        rng = random.Random(7)
        probes = [0.0, 1e-9, 1e-6, 2e-6, 0.002, 0.5]
        probes += list(h.bounds)                      # exact bounds
        probes += [b * 1.0001 for b in h.bounds]      # just above
        probes += [rng.uniform(0, 2e-4) for _ in range(200)]
        for v in probes:
            assert h._index(v) == ref_index(v), v

    def test_resolves_2ms(self):
        """The satellite's point: a 2ms SLO objective falls between
        quarter-octave bounds ~19% apart, not the plain ladder's
        1.024ms/2.048ms factor-2 gap."""
        h = Histogram("x", lo=1e-6, n_buckets=112, substeps=4)
        below = max(b for b in h.bounds if b <= 0.002)
        above = min(b for b in h.bounds if b > 0.002)
        assert above / below <= 2 ** 0.25 + 1e-9
        # percentile over-estimates by at most one sub-step
        for _ in range(100):
            h.observe(0.0019)
        assert h.percentile(0.99) <= 0.0019 * 2 ** 0.25

    def test_substeps_1_unchanged(self):
        """The classic octave ladder is bit-identical to before."""
        h = Histogram("x", lo=1e-6, n_buckets=28)
        assert h.substeps == 1
        assert h.bounds == [1e-6 * (1 << i) for i in range(28)]
        h.observe(0.5e-6)
        h.observe(1e-6)
        h.observe(1.1e-6)
        assert h.counts[0] == 2 and h.counts[1] == 1

    def test_overflow_and_cumulative(self):
        h = Histogram("x", lo=1e-6, n_buckets=8, substeps=4)
        h.observe(1.0)                 # far beyond the last bound
        h.observe(1e-6)
        cum = h.cumulative()
        assert cum[-1][0] == float("inf") and cum[-1][1] == 2
        assert h.counts[-1] == 1

    def test_stage_families_migrated(self):
        """pipeline.stage.* ride the fine ladder with names unchanged
        (the PR 7 doc-drift gate keys on the names)."""
        node = _mk_node()
        h = node.metrics.histograms()["pipeline.stage.dispatch.seconds"]
        assert h.substeps == 4
        assert any(abs(b - 0.002) / 0.002 < 0.10 for b in h.bounds), \
            "no bound within 10% of the 2ms objective"
        # the watchdog deadline source still reads these names
        assert "pipeline.stage.materialize.seconds" in \
            node.metrics.histograms()


# ---------- SLO engine unit behavior ----------

class TestSloEngine:
    def _obs(self, objective_ms=2.0, hooks=None, recorder=None):
        return L.LatencyObservatory(Metrics(), hooks=hooks,
                                    recorder=recorder,
                                    objective_ms=objective_ms)

    def test_burn_rates(self):
        obs = self._obs()
        sid = int(time.monotonic() / L._SLOT_S)
        # 100 samples, 2 breaches in the current slot: burn = 2%/1% = 2
        obs._slots.append([sid, 100, 2])
        burn = obs.burn_rates()
        assert burn["1m"] == pytest.approx(2.0)
        assert burn["5m"] == pytest.approx(2.0)
        assert burn["30m"] == pytest.approx(2.0)
        # an old slot outside the 1m window but inside 30m
        obs._slots.appendleft([sid - 12, 100, 0])
        burn = obs.burn_rates()
        assert burn["1m"] == pytest.approx(2.0)
        assert burn["30m"] == pytest.approx(1.0)

    def test_verdict_and_merged_p99(self):
        obs = self._obs(objective_ms=2.0)
        m = Message(topic="a", qos=1)
        for _ in range(200):
            obs.record_routed(m, "device", 0.0005)
        sec = obs.section()
        assert sec["slo"]["verdict"] == "met"
        assert sec["slo"]["routed_p99_ms"] <= 2.0
        assert sec["routed"]["q1.device"]["count"] == 200
        for _ in range(200):
            obs.record_routed(m, "replay", 0.05)
        sec = obs.section()
        # the merged p99 now sits in the replay tail
        assert sec["slo"]["verdict"] == "breached"
        assert sec["slo"]["routed_p99_ms"] > 2.0
        assert set(sec["routed"]) == {"q1.device", "q1.replay"}

    def test_no_data_verdict(self):
        sec = self._obs().section()
        assert sec["slo"]["verdict"] == "no_data"

    def test_exemplar_trace_link_and_hook_throttle(self):
        hooks = Hooks()
        seen = []
        hooks.add("latency.breach", lambda ex: seen.append(ex))
        rec = FlightRecorder(Metrics(), cap=64)
        obs = self._obs(objective_ms=1.0, hooks=hooks, recorder=rec)
        tid = rec.new_trace()
        m = Message(topic="slow/one", qos=1)
        for _ in range(5):
            obs.record_routed(m, "replay", 0.25, trace=tid)
        # exemplars recorded for every breach, hook throttled to one
        assert len(obs.exemplars) == 5
        ex = obs.exemplars[0]
        assert ex["trace_id"] == tid and ex["path"] == "replay"
        assert len(seen) == 1 and seen[0]["topic"] == "slow/one"
        assert obs.hook_fires == 1 and obs.hook_throttled == 4
        # the slow message's trace carries the slo_breach event
        marks = [s for s in rec.spans()
                 if s.name == "slo_breach" and s.trace_id == tid]
        assert marks and marks[0].meta["path"] == "replay"

    def test_section_json_clean(self):
        obs = self._obs()
        obs.record_routed(Message(topic="a", qos=0), "host", 0.01)
        obs.record_delivered(Message(topic="a", qos=0), "host", 0.02)
        json.dumps(obs.section())


# ---------- ingress stamp: burst vs per-packet equivalence ----------

class TestIngressStamp:
    def _frames(self, n=220, payload=b"p" * 24):
        return b"".join(
            serialize(P.Publish(topic=f"s/t{i % 7}", payload=payload,
                                qos=1, packet_id=(i % 60000) + 1),
                      C.MQTT_V4)
            for i in range(n))

    def test_per_packet_feed_stamps_publishes(self):
        p = FrameParser(version=C.MQTT_V4)
        pkts = p.feed(self._frames(8))
        assert len(pkts) == 8
        assert all(pk.ingress_ns > 0 for pk in pkts)
        # non-PUBLISH frames stay unstamped (Publish-only attribute)
        p2 = FrameParser(version=C.MQTT_V4)
        (ping,) = p2.feed(serialize(P.Pingreq(), C.MQTT_V4))
        assert getattr(ping, "ingress_ns", 0) == 0

    def test_burst_one_clock_read_per_row_attribution(self):
        data = self._frames()
        assert len(data) > FrameParser.BURST_SCAN_MIN
        pc = FrameParser(version=C.MQTT_V4)
        items = pc.feed_columnar(data)
        bursts = [it for it in items if type(it) is PublishBurst]
        assert bursts, "columnar path produced no burst"
        for b in bursts:
            assert b.ingress_ns > 0
        # equivalence with the per-packet twin: same rows, and every
        # row of either path carries a stamp taken at frame decode
        pp = FrameParser(version=C.MQTT_V4)
        pkts = pp.feed(data)
        assert sum(len(b) for b in bursts) == len(pkts)
        assert [t for b in bursts for t in b.topics] == \
            [pk.topic for pk in pkts]
        assert all(pk.ingress_ns > 0 for pk in pkts)

    def test_stamp_rides_message_both_paths(self):
        """Channel-level: the burst hand-off and the per-packet path
        plant the same ingress_ns onto the Message."""
        m = make("c", 1, "a/b", b"x")
        assert m.ingress_ns == 0        # internal publishes unstamped
        m.ingress_ns = 123
        assert m.ingress_ns == 123
        # the burst constructor path (Channel.handle_publish_burst)
        mm = Message.__new__(Message)
        mm.__dict__ = {"topic": "a", "payload": b"", "qos": 0,
                       "from_": "c", "flags": {}, "headers": {},
                       "id": 1, "ts": 1, "extra": {},
                       "ingress_ns": 456}
        assert mm.ingress_ns == 456


# ---------- knob-off A/B twin ----------

class TestOffTwin:
    def test_off_is_pre_issue13_exactly(self):
        node_off = _mk_node(latency_observatory=False)
        assert node_off.latency_observatory is None
        sinks_off = _subscribe(node_off)
        counts_off = run(_drive(node_off))
        node_on = _mk_node(latency_observatory=True)
        assert node_on.latency_observatory is not None
        sinks_on = _subscribe(node_on)
        counts_on = run(_drive(node_on))
        # bit-identical delivery counts AND per-publisher order
        assert counts_off == counts_on
        assert [s.got for s in sinks_off] == [s.got for s in sinks_on]
        # snapshot schema identical minus the latency section
        snap_off = node_off.pipeline_telemetry.snapshot()
        snap_on = node_on.pipeline_telemetry.snapshot()
        assert "latency" not in snap_off
        assert set(snap_off) == set(snap_on) - {"latency"}
        # no latency metric leaks into the off registry
        assert not [n for n in node_off.metrics.histograms()
                    if n.startswith("pipeline.latency.")]
        assert node_off.metrics.val("pipeline.latency.breaches") == 0

    def test_rest_404_when_off(self):
        node = _mk_node(latency_observatory=False)
        from emqx_tpu.mgmt import make_api

        async def go():
            srv = make_api(node, port=0)
            await srv.start()
            try:
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", srv.port)
                writer.write(b"GET /api/v5/pipeline/latency HTTP/1.1"
                             b"\r\nhost: x\r\nconnection: close\r\n\r\n")
                await writer.drain()
                raw = await asyncio.wait_for(reader.read(-1), 10)
                writer.close()
                assert b"404" in raw.split(b"\r\n")[0]
            finally:
                await srv.stop()
        run(go(), timeout=60)


# ---------- per-path attribution oracle ----------

class TestPathAttribution:
    @pytest.fixture(scope="class")
    def attributed_run(self):
        node = _mk_node(supervise_threshold=8)
        _subscribe(node)

        async def go():
            await _warm(node)
            pb = node.publish_batcher
            eng = node.device_engine
            out = []
            # (a) trickle host: one lone message is below
            # device_min_batch and takes the inline host path
            out.append(await node.publish_async(
                _stamped("p", 1, "t/0/h")))
            # (b) device: pinned chooser, full windows
            pb._device_worth_it = lambda n: True
            out += await _drive(node, windows=3, warm=False, tag="d")
            assert node.metrics.val("pipeline.batches.device") \
                or node.metrics.val("pipeline.batches.device_cached"), \
                "device path never engaged"
            # (c) FORCED host-fallback: the device path is chosen but
            # prepare_window declines (mid-rebuild shape)
            real_prepare = eng.prepare_window
            eng.prepare_window = lambda lives: None
            out += await _drive(node, windows=1, warm=False, tag="f")
            eng.prepare_window = real_prepare
            # (d) journal replay: one injected dispatch exception
            sup = node.supervisor
            sup.injector = S.FaultInjector(S.parse_faults(
                "dispatch:exception:count=1"))
            for _w in range(6):
                out += await _drive(node, windows=1, warm=False,
                                    tag="r")
                if sup.injector.faults[0].fired:
                    break
            assert sup.injector.faults[0].fired, \
                "injected dispatch fault never fired"
            del pb.__dict__["_device_worth_it"]
            return out
        counts = run(go())
        return node, counts

    def test_each_rung_is_its_own_series(self, attributed_run):
        node, counts = attributed_run
        assert all(c == 1 for c in counts), "a rung lost deliveries"
        paths = {p for (leg, _q, p), h in
                 node.latency_observatory._hist.items()
                 if leg == "routed" and h.count}
        assert "host" in paths
        assert "device" in paths or "device_cached" in paths
        assert "host_fallback" in paths, \
            "forced prepare_window decline not attributed"
        assert "replay" in paths, "journal replay not attributed"

    def test_delivered_leg_mirrors_routed(self, attributed_run):
        node, _counts = attributed_run
        series = node.latency_observatory._hist
        for (leg, q, p), h in series.items():
            if leg != "routed" or not h.count:
                continue
            hd = series.get(("delivered", q, p))
            assert hd is not None and hd.count == h.count, \
                f"delivered leg missing for q{q}.{p}"

    def test_replay_breach_exemplar_names_injected_stage(
            self, attributed_run):
        """The acceptance drive's tier-1 twin: the slow (replayed)
        window's breach exemplar links the flight-recorder trace whose
        causal chain carries the replay event attributing the latency
        to the injected dispatch stage."""
        node, _counts = attributed_run
        obs = node.latency_observatory
        rec = node.flight_recorder
        assert obs.breaches > 0, \
            "replayed windows never breached the objective"
        tids = {ex["trace_id"] for ex in obs.exemplars
                if ex["trace_id"]}
        assert tids
        replayed = [s for s in rec.spans()
                    if s.name == "replay" and s.trace_id in tids]
        assert replayed, \
            "no breach exemplar links a trace with a replay event"
        assert replayed[0].meta["stage"] == "dispatch"

    def test_snapshot_and_exporters(self, attributed_run):
        node, _counts = attributed_run
        snap = node.pipeline_telemetry.snapshot()
        lat = snap["latency"]
        assert lat["schema"] == L.SCHEMA
        assert lat["slo"]["samples"] == \
            sum(r["count"] for r in lat["routed"].values())
        json.dumps(snap)
        # $SYS
        from emqx_tpu.apps.sys import SysBroker
        seen = {}

        class Spy(SysBroker):
            def _pub(self, suffix, payload):
                seen[suffix] = payload
        Spy(node).publish_pipeline()
        assert "pipeline/latency" in seen
        assert json.loads(seen["pipeline/latency"])["slo"]
        # Prometheus histogram families
        from emqx_tpu.apps.prometheus import collect
        text = collect(node)
        assert "emqx_pipeline_latency_routed_q1_" in text
        assert "emqx_pipeline_latency_delivered_q1_" in text

    def test_rest_endpoint(self, attributed_run):
        node, _counts = attributed_run
        from emqx_tpu.mgmt import make_api

        async def go():
            srv = make_api(node, port=0)
            await srv.start()
            try:
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", srv.port)
                writer.write(b"GET /api/v5/pipeline/latency HTTP/1.1"
                             b"\r\nhost: x\r\nconnection: close\r\n\r\n")
                await writer.drain()
                raw = await asyncio.wait_for(reader.read(-1), 10)
                writer.close()
                head, _, body = raw.partition(b"\r\n\r\n")
                assert b"200" in head.split(b"\r\n")[0]
                doc = json.loads(body)
                assert doc["schema"] == L.SCHEMA and doc["routed"]
            finally:
                await srv.stop()
        run(go(), timeout=60)

    def test_overhead_guard_under_3pct(self, attributed_run):
        """Deterministic, the PR 7/8 shape: measure the per-record
        cost of the observatory primitive, double it (two legs per
        message), and bound it against 3% of the MEASURED mean
        ingress→delivered latency of this live run. A hot-path
        regression (say, section() leaking into record) fails
        immediately; scheduler noise cannot."""
        node, _counts = attributed_run
        obs = node.latency_observatory
        probe = L.LatencyObservatory(Metrics(), objective_ms=1e9)
        m = Message(topic="t/overhead", qos=1)
        n = 4000
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _i in range(n):
                probe.record_routed(m, "device", 1e-4)
                probe.record_delivered(m, "device", 1e-4)
            best = min(best, (time.perf_counter() - t0) / n)
        hs = [h for (leg, _q, _p), h in obs._hist.items()
              if leg == "delivered" and h.count]
        mean_lat = sum(h.sum for h in hs) / sum(h.count for h in hs)
        assert best < 0.03 * mean_lat, (
            f"observatory records cost {best * 1e6:.2f}us/message vs "
            f"mean e2e latency {mean_lat * 1e3:.2f}ms — over the 3% "
            f"budget")


# ---------- host-only node (no batcher) still measures ----------

class TestHostOnlyNode:
    def test_host_path_records_both_legs(self):
        node = Node({"broker": {"device_route": False}},
                    use_device=False)
        assert node.publish_batcher is None
        assert node.latency_observatory is not None
        s = Sink()
        sid = node.broker.register(s, "c0")
        node.broker.subscribe(sid, "h/+", {"qos": 1})

        async def go():
            return [await node.publish_async(_stamped("p", 1, "h/a"))
                    for _ in range(16)]
        counts = run(go())
        assert all(c == 1 for c in counts)
        sec = node.latency_observatory.section()
        assert sec["routed"]["q1.host"]["count"] == 16
        assert sec["delivered"]["q1.host"]["count"] == 16


# ---------- offline report + CI gate ----------

class TestLatencyReport:
    def _section(self):
        obs = L.LatencyObservatory(Metrics(), objective_ms=2.0)
        m = Message(topic="a/b", qos=1)
        for _ in range(100):
            obs.record_routed(m, "device", 0.001)
            obs.record_delivered(m, "device", 0.0015)
        return obs.section()

    def test_report_renders_and_exits_0(self, tmp_path, capsys):
        import latency_report
        doc = {"phase0": {"metric": "x", "latency": self._section()},
               "e2e_host": {"latency": self._section()}}
        path = tmp_path / "bench.json"
        path.write_text(json.dumps(doc))
        assert latency_report.main([str(path)]) == 0
        out = capsys.readouterr().out
        assert "ingress→routed" in out and "q1.device" in out
        assert "SLO" in out and "MET" in out

    def test_exit_2_on_p99less_row(self, tmp_path, capsys):
        """The CI gate: a bench row WITHOUT a latency section cannot
        silently commit a p99-less headline."""
        import latency_report
        doc = {"phase0": {"metric": "x", "value": 123},
               "e2e_device": {"per_sec": 1}}
        path = tmp_path / "bench.json"
        path.write_text(json.dumps(doc))
        assert latency_report.main([str(path)]) == 2
        assert "NO latency section" in capsys.readouterr().err

    def test_checkpoint_shape_and_require(self, tmp_path, capsys):
        import latency_report
        ck = {"sig": {"subs": 1},
              "phases": {"phase0": {"latency": self._section()}}}
        path = tmp_path / "ckpt.json"
        path.write_text(json.dumps(ck))
        assert latency_report.main([str(path)]) == 0
        # --require pins a row the artifact lacks -> gate fires
        assert latency_report.main(
            ["--require", "phase0,e2e_device", str(path)]) == 2

    def test_exit_1_on_garbage(self, tmp_path):
        import latency_report
        path = tmp_path / "junk.json"
        path.write_text("not json")
        assert latency_report.main([str(path)]) == 1
        assert latency_report.main([]) == 1
