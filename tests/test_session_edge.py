"""Regression tests for session/channel edge cases found in review:
queue-full drop accounting, takeover pendings enrichment, v5 Receive
Maximum, round-robin phase, QoS2 publish-on-PUBLISH."""

import asyncio

import pytest

from emqx_tpu.broker.cm import ConnectionManager
from emqx_tpu.broker.connection import Listener
from emqx_tpu.broker.message import make
from emqx_tpu.broker.mqueue import MQueueOpts
from emqx_tpu.broker.node import Node
from emqx_tpu.broker.pubsub import Broker
from emqx_tpu.broker.router import Router
from emqx_tpu.broker.session import Session, SessionConf
from emqx_tpu.client import Client
from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt import packet as P


class TestQueueFullDrops:
    def test_on_dropped_callback(self):
        conf = SessionConf(max_inflight=1, mqueue=MQueueOpts(max_len=2))
        s = Session("c", conf)
        dropped = []
        s.on_dropped = lambda m, r: dropped.append((m.topic, r))
        msgs = [(make("p", 1, f"t/{i}", b"x"), {"qos": 1}) for i in range(5)]
        s.deliver(msgs)
        # 1 inflight + 2 queued + 2 evicted oldest-first
        assert [t for t, _ in dropped] == ["t/1", "t/2"]
        assert all(r == "queue_full" for _, r in dropped)


class TestTakeoverEnrich:
    def test_pendings_enriched(self):
        cm = ConnectionManager()
        loop = asyncio.new_event_loop()

        class OldChan:
            def __init__(self):
                self.session = Session("c", SessionConf())

            async def takeover_begin(self):
                return self.session

            async def takeover_end(self):
                m = make("other", 2, "t", b"x")
                m.headers["subopts"] = {"qos": 0}
                return [m]

        try:
            sess, present = loop.run_until_complete(
                cm.open_session(False, "c", SessionConf(), None))
            assert not present
            cm.register_channel("c", OldChan())
            sess2, present = loop.run_until_complete(
                cm.open_session(False, "c", SessionConf(), object()))
            assert present
            queued = sess2.mqueue.to_list()
            assert len(queued) == 1
            assert queued[0].qos == 0     # capped by subopts, not raw qos=2
        finally:
            loop.close()


class TestRoundRobinPhase:
    def test_first_member_first(self):
        b = Broker(router=Router(use_device=False),
                   shared_strategy="round_robin")

        class Col:
            def __init__(self):
                self.got = []

            def deliver(self, f, m):
                self.got.append(m)
                return True

        cols = [Col(), Col()]
        for c in cols:
            b.subscribe(b.register(c), "$share/g/t")
        b.publish(make("p", 0, "t", b""))
        assert len(cols[0].got) == 1 and len(cols[1].got) == 0


class TestNodeSweep:
    def test_sweep_expires_parked_sessions(self):
        node = Node()
        sess = Session("c", SessionConf(session_expiry_interval=0))
        node.cm.park_session("c", sess)
        node.cm._parked_at["c"] = -10_000   # long past expiry
        node.sweep()
        assert node.cm.parked_count() == 0


class TestReceiveMaximum:
    @pytest.fixture()
    def loop(self):
        loop = asyncio.new_event_loop()
        yield loop
        loop.close()

    def test_v5_receive_maximum_caps_inflight(self, loop):
        node = Node()
        lst = Listener(node, bind="127.0.0.1", port=0)
        loop.run_until_complete(lst.start())

        async def go():
            c = Client(port=lst.port, clientid="rm", proto_ver=C.MQTT_V5,
                       properties={"receive_maximum": 3})
            await c.connect()
            chan = node.cm.lookup_channel("rm")
            assert chan.session.inflight.max_size == 3
            await c.disconnect()
        try:
            loop.run_until_complete(asyncio.wait_for(go(), 15))
        finally:
            loop.run_until_complete(lst.stop())


class TestQos2PublishOnReceipt:
    @pytest.fixture()
    def loop(self):
        loop = asyncio.new_event_loop()
        yield loop
        loop.close()

    def test_duplicate_qos2_pid_not_republished(self, loop):
        node = Node()
        lst = Listener(node, bind="127.0.0.1", port=0)
        loop.run_until_complete(lst.start())

        async def go():
            sub = Client(port=lst.port, clientid="sub")
            await sub.connect()
            await sub.subscribe("q", qos=0)
            pub = Client(port=lst.port, clientid="pub")
            await pub.connect()
            # send two QoS2 PUBLISH with the same pid, no PUBREL between:
            # the broker must route only the first (dup suppression)
            pub._send(P.Publish(topic="q", payload=b"1", qos=2, packet_id=7))
            pub._send(P.Publish(topic="q", payload=b"1", qos=2, packet_id=7,
                                dup=True))
            m = await sub.recv()
            assert m.payload == b"1"
            with pytest.raises(asyncio.TimeoutError):
                await sub.recv(timeout=0.3)
            await pub.close()
            await sub.disconnect()
        try:
            loop.run_until_complete(asyncio.wait_for(go(), 15))
        finally:
            loop.run_until_complete(lst.stop())
