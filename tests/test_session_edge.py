"""Regression tests for session/channel edge cases found in review:
queue-full drop accounting, takeover pendings enrichment, v5 Receive
Maximum, round-robin phase, QoS2 publish-on-PUBLISH."""

import asyncio

import pytest

from emqx_tpu.broker.cm import ConnectionManager
from emqx_tpu.broker.connection import Listener
from emqx_tpu.broker.message import make
from emqx_tpu.broker.mqueue import MQueueOpts
from emqx_tpu.broker.node import Node
from emqx_tpu.broker.pubsub import Broker
from emqx_tpu.broker.router import Router
from emqx_tpu.broker.session import Session, SessionConf
from emqx_tpu.client import Client
from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt import packet as P


class TestQueueFullDrops:
    def test_on_dropped_callback(self):
        conf = SessionConf(max_inflight=1, mqueue=MQueueOpts(max_len=2))
        s = Session("c", conf)
        dropped = []
        s.on_dropped = lambda m, r: dropped.append((m.topic, r))
        msgs = [(make("p", 1, f"t/{i}", b"x"), {"qos": 1}) for i in range(5)]
        s.deliver(msgs)
        # 1 inflight + 2 queued + 2 evicted oldest-first
        assert [t for t, _ in dropped] == ["t/1", "t/2"]
        assert all(r == "queue_full" for _, r in dropped)


class TestTakeoverEnrich:
    def test_pendings_enriched(self):
        cm = ConnectionManager()
        loop = asyncio.new_event_loop()

        class OldChan:
            def __init__(self):
                self.session = Session("c", SessionConf())

            async def takeover_begin(self):
                return self.session

            async def takeover_end(self):
                m = make("other", 2, "t", b"x")
                m.headers["subopts"] = {"qos": 0}
                return [m]

        try:
            sess, present = loop.run_until_complete(
                cm.open_session(False, "c", SessionConf(), None))
            assert not present
            cm.register_channel("c", OldChan())
            sess2, present = loop.run_until_complete(
                cm.open_session(False, "c", SessionConf(), object()))
            assert present
            queued = sess2.mqueue.to_list()
            assert len(queued) == 1
            assert queued[0].qos == 0     # capped by subopts, not raw qos=2
        finally:
            loop.close()


class TestRoundRobinPhase:
    def test_first_member_first(self):
        b = Broker(router=Router(use_device=False),
                   shared_strategy="round_robin")

        class Col:
            def __init__(self):
                self.got = []

            def deliver(self, f, m):
                self.got.append(m)
                return True

        cols = [Col(), Col()]
        for c in cols:
            b.subscribe(b.register(c), "$share/g/t")
        b.publish(make("p", 0, "t", b""))
        assert len(cols[0].got) == 1 and len(cols[1].got) == 0


class TestNodeSweep:
    def test_sweep_expires_parked_sessions(self):
        node = Node()
        sess = Session("c", SessionConf(session_expiry_interval=0))
        node.cm.park_session("c", sess)
        node.cm._parked_at["c"] = -10_000   # long past expiry
        node.sweep()
        assert node.cm.parked_count() == 0


class TestReceiveMaximum:
    @pytest.fixture()
    def loop(self):
        loop = asyncio.new_event_loop()
        yield loop
        loop.close()

    def test_v5_receive_maximum_caps_inflight(self, loop):
        node = Node()
        lst = Listener(node, bind="127.0.0.1", port=0)
        loop.run_until_complete(lst.start())

        async def go():
            c = Client(port=lst.port, clientid="rm", proto_ver=C.MQTT_V5,
                       properties={"receive_maximum": 3})
            await c.connect()
            chan = node.cm.lookup_channel("rm")
            assert chan.session.inflight.max_size == 3
            await c.disconnect()
        try:
            loop.run_until_complete(asyncio.wait_for(go(), 15))
        finally:
            loop.run_until_complete(lst.stop())


class TestQos2PublishOnReceipt:
    @pytest.fixture()
    def loop(self):
        loop = asyncio.new_event_loop()
        yield loop
        loop.close()

    def test_duplicate_qos2_pid_not_republished(self, loop):
        node = Node()
        lst = Listener(node, bind="127.0.0.1", port=0)
        loop.run_until_complete(lst.start())

        async def go():
            sub = Client(port=lst.port, clientid="sub")
            await sub.connect()
            await sub.subscribe("q", qos=0)
            pub = Client(port=lst.port, clientid="pub")
            await pub.connect()
            # send two QoS2 PUBLISH with the same pid, no PUBREL between:
            # the broker must route only the first (dup suppression)
            pub._send(P.Publish(topic="q", payload=b"1", qos=2, packet_id=7))
            pub._send(P.Publish(topic="q", payload=b"1", qos=2, packet_id=7,
                                dup=True))
            m = await sub.recv()
            assert m.payload == b"1"
            with pytest.raises(asyncio.TimeoutError):
                await sub.recv(timeout=0.3)
            await pub.close()
            await sub.disconnect()
        try:
            loop.run_until_complete(asyncio.wait_for(go(), 15))
        finally:
            loop.run_until_complete(lst.stop())


class TestReplayRebalance:
    def test_shrunk_window_moves_excess_to_mqueue(self):
        s = Session("c", SessionConf(max_inflight=10))
        msgs = [(make("p", 1, f"t/{i}", b"x"), {"qos": 1}) for i in range(5)]
        s.deliver(msgs)
        assert len(s.inflight) == 5
        s.inflight.max_size = 2          # client reconnects with RM=2
        out = s.replay()
        pubs = [o for o in out if o[1] == "publish"]
        assert len(pubs) == 2            # never exceeds the new window
        assert [m.topic for _, _, m in pubs] == ["t/0", "t/1"]
        # the moved-back messages kept order at the queue head
        assert [m.topic for m in s.mqueue.to_list()] == ["t/2", "t/3", "t/4"]

    def test_pubrel_phase_not_counted(self):
        s = Session("c", SessionConf(max_inflight=5))
        s.deliver([(make("p", 2, f"q/{i}", b"x"), {"qos": 2})
                   for i in range(3)])
        for pid, _ in list(s.inflight.items()):
            s.pubrec(pid)                 # all move to pubrel phase
        s.inflight.max_size = 1
        out = s.replay()
        assert [phase for _, phase, _ in out].count("pubrel") == 3


class TestDenyDisconnect:
    @pytest.fixture()
    def loop(self):
        loop = asyncio.new_event_loop()
        yield loop
        loop.close()

    def test_no_packets_after_disconnect(self, loop):
        from emqx_tpu.apps.authz import Authz, FileSource
        node = Node({"authz": {"deny_action": "disconnect"}})
        Authz(node, [FileSource([
            {"permit": "deny", "topics": ["secret/#"]},
            {"permit": "allow"}])]).load()
        lst = Listener(node, bind="127.0.0.1", port=0)
        loop.run_until_complete(lst.start())

        async def go():
            c = Client(port=lst.port, clientid="dd", proto_ver=C.MQTT_V5)
            await c.connect()
            # SUBSCRIBE [denied, allowed]: server must DISCONNECT and send
            # nothing after; the allowed filter must not be installed
            c._send(P.Subscribe(packet_id=1, filters=[
                ("secret/x", P.SubOpts(qos=1)), ("open/x", P.SubOpts(qos=1))]))
            await c.closed.wait()
            assert c.disconnect_pkt is not None
            assert c.disconnect_pkt.reason_code == C.RC_NOT_AUTHORIZED
            assert not node.router.has_route("open/x")
            assert node.metrics.val("packets.suback.sent") == 0
        try:
            loop.run_until_complete(asyncio.wait_for(go(), 15))
        finally:
            loop.run_until_complete(lst.stop())
