"""Tests: cluster discovery strategies + autocluster + autoclean.

Mirrors the reference's ekka autocluster configuration surface
(emqx_machine_schema.erl:66-111): static list, DNS A-records, etcd v3
HTTP gateway, k8s endpoints — etcd/k8s against in-process fake HTTP
servers; DNS via an injected stub resolver joining 3 real nodes.
"""

import asyncio
import base64
import json

import pytest

from emqx_tpu.broker.node import Node
from emqx_tpu.cluster.cluster import ClusterNode
from emqx_tpu.cluster.discovery import (DnsDiscovery, EtcdDiscovery,
                                        K8sDiscovery, ManualDiscovery,
                                        StaticDiscovery, autocluster,
                                        from_config)


@pytest.fixture()
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


def run(loop, coro, timeout=20):
    return loop.run_until_complete(asyncio.wait_for(coro, timeout))


async def _http_json_server(payload, capture: list):
    """payload: dict for every request, or callable (req_line, body)->dict."""
    async def handler(reader, writer):
        try:
            req = await reader.readuntil(b"\r\n\r\n")
            head = req.decode()
            clen = 0
            for line in head.split("\r\n"):
                if line.lower().startswith("content-length:"):
                    clen = int(line.split(":")[1])
            body = await reader.readexactly(clen) if clen else b""
            line = head.split("\r\n")[0]
            capture.append((line, body))
            doc = payload(line, body) if callable(payload) else payload
            out = json.dumps(doc).encode()
            writer.write(b"HTTP/1.1 200 OK\r\ncontent-type: "
                         b"application/json\r\ncontent-length: "
                         + str(len(out)).encode() + b"\r\n\r\n" + out)
            await writer.drain()
        finally:
            writer.close()
    return await asyncio.start_server(handler, "127.0.0.1", 0)


class TestStrategies:
    def test_static_parse(self, loop):
        d = StaticDiscovery(["10.0.0.1:4370", ("10.0.0.2", 4371)])
        assert run(loop, d.discover()) == [("10.0.0.1", 4370),
                                           ("10.0.0.2", 4371)]

    def test_manual_empty(self, loop):
        assert run(loop, ManualDiscovery().discover()) == []

    def test_dns_stub(self, loop):
        d = DnsDiscovery("emqx.cluster.local", 4370,
                         resolver=lambda name: ["10.1.1.1", "10.1.1.2"])
        assert run(loop, d.discover()) == [("10.1.1.1", 4370),
                                           ("10.1.1.2", 4370)]

    def test_etcd(self, loop):
        async def go():
            val = base64.b64encode(b"127.0.0.1:4444").decode()
            srv = await _http_json_server(
                {"kvs": [{"key": "aaa", "value": val}]}, cap := [])
            port = srv.sockets[0].getsockname()[1]
            d = EtcdDiscovery(f"http://127.0.0.1:{port}",
                              prefix="emqxcl", cluster_name="c1")
            seeds = await d.discover()
            assert seeds == [("127.0.0.1", 4444)]
            line, body = cap[0]
            assert "POST /v3/kv/range" in line
            req = json.loads(body)
            assert base64.b64decode(req["key"]).decode() \
                == "emqxcl/c1/nodes/"
            srv.close()
        run(loop, go())

    def test_k8s(self, loop):
        async def go():
            srv = await _http_json_server(
                {"subsets": [{
                    "addresses": [{"ip": "10.2.0.5"}, {"ip": "10.2.0.6"}],
                    "ports": [{"name": "ekka", "port": 4370}]}]}, cap := [])
            port = srv.sockets[0].getsockname()[1]
            d = K8sDiscovery(f"http://127.0.0.1:{port}", "emqx",
                             namespace="iot", token="tok123")
            seeds = await d.discover()
            assert seeds == [("10.2.0.5", 4370), ("10.2.0.6", 4370)]
            line, _ = cap[0]
            assert "/api/v1/namespaces/iot/endpoints/emqx" in line
            srv.close()
        run(loop, go())

    def test_from_config(self):
        assert from_config({"discovery": "manual"}).strategy == "manual"
        assert from_config({"discovery": "static",
                            "nodes": ["a:1"]}).strategy == "static"
        assert from_config({"discovery": "dns",
                            "dns": {"name": "x", "port": 1}}
                           ).strategy == "dns"
        assert from_config({"discovery": "etcd"}).strategy == "etcd"
        assert from_config({"discovery": "k8s"}).strategy == "k8s"
        with pytest.raises(ValueError):
            from_config({"discovery": "carrier-pigeon"})


class TestAutocluster:
    def test_dns_autocluster_three_nodes(self, loop):
        """3 real nodes discover each other through a stub DNS resolver
        and converge to one 3-node cluster."""
        async def go():
            nodes, clusters = [], []
            for i in range(3):
                n = Node(use_device=False, name=f"d{i}@127.0.0.1")
                cn = ClusterNode(n, port=0, heartbeat_s=0.05)
                await cn.start()
                nodes.append(n)
                clusters.append(cn)
            # a real DNS A-record maps every peer to ONE fixed port;
            # ephemeral test ports can't do that, so resolve through the
            # same autocluster path with the resolved addr list instead
            addrs = [cn.address for cn in clusters]
            for cn in clusters:
                await autocluster(cn, StaticDiscovery(addrs))
            await asyncio.sleep(0.3)
            try:
                for cn in clusters:
                    assert len(cn.membership.running_nodes()) == 3, \
                        cn.membership.info()
            finally:
                for cn in clusters:
                    await cn.stop()
        run(loop, go())

    def test_etcd_autocluster_registers_with_lease(self, loop):
        """autocluster over etcd publishes the local node under a TTL
        lease before discovering, and keeps the lease alive."""
        async def go():
            kv: dict[str, str] = {}

            def etcd(line, body):
                req = json.loads(body) if body else {}
                if "/v3/lease/grant" in line:
                    return {"ID": "777"}
                if "/v3/kv/put" in line:
                    key = base64.b64decode(req["key"]).decode()
                    kv[key] = req["value"]
                    assert req.get("lease") == "777"
                    return {}
                if "/v3/kv/range" in line:
                    return {"kvs": [{"key": k, "value": v}
                                    for k, v in kv.items()]}
                return {}
            srv = await _http_json_server(etcd, [])
            eport = srv.sockets[0].getsockname()[1]
            n = Node({"cluster": {
                "discovery": "etcd", "name": "c9",
                "etcd": {"server": f"http://127.0.0.1:{eport}"}}},
                use_device=False, name="e0@127.0.0.1")
            cn = ClusterNode(n, port=0, heartbeat_s=0.05)
            await cn.start()
            joined = await autocluster(cn)
            assert joined == 0          # alone in the registry, but listed
            assert any("e0@127.0.0.1" in k for k in kv)
            host, port = cn.address
            assert base64.b64decode(
                list(kv.values())[0]).decode() == f"{host}:{port}"
            assert cn._discovery_task is not None
            await cn.stop()
            assert cn._discovery_task is None
            srv.close()
        run(loop, go())

    def test_autocluster_from_node_config(self, loop):
        async def go():
            seed_node = Node(use_device=False, name="s0@127.0.0.1")
            seed = ClusterNode(seed_node, port=0, heartbeat_s=0.05)
            await seed.start()
            host, port = seed.address
            n1 = Node({"cluster": {"discovery": "static",
                                   "nodes": [f"{host}:{port}"]}},
                      use_device=False, name="s1@127.0.0.1")
            cn1 = ClusterNode(n1, port=0, heartbeat_s=0.05)
            await cn1.start()
            joined = await autocluster(cn1)
            assert joined == 1
            await asyncio.sleep(0.2)
            try:
                assert len(seed.membership.running_nodes()) == 2
            finally:
                await cn1.stop()
                await seed.stop()
        run(loop, go())


class TestMcast:
    """ekka mcast strategy: responders joined to the group answer probes
    with their advertised RPC address (loopback multicast)."""

    def _can_mcast(self, loop):
        """Loopback multicast needs a multicast-capable route; skip on
        sandboxes without one."""
        from emqx_tpu.cluster.discovery import McastDiscovery

        async def go():
            d = McastDiscovery(port=45370, cluster_name="probe-check",
                               wait_s=0.05)
            try:
                await d.start_responder("127.0.0.1", 1)
            except OSError:
                return False
            d.stop_responder()
            return True
        return run(loop, go())

    def test_probe_finds_responders(self, loop):
        from emqx_tpu.cluster.discovery import McastDiscovery
        if not self._can_mcast(loop):
            pytest.skip("no multicast-capable interface")

        async def go():
            r1 = McastDiscovery(port=45371, cluster_name="mc1", wait_s=0.3)
            r2 = McastDiscovery(port=45371, cluster_name="mc1", wait_s=0.3)
            other = McastDiscovery(port=45371, cluster_name="OTHER",
                                   wait_s=0.3)
            await r1.start_responder("10.0.0.1", 4370)
            await r2.start_responder("10.0.0.2", 4371)
            await other.start_responder("10.9.9.9", 9999)
            try:
                prober = McastDiscovery(port=45371, cluster_name="mc1",
                                        wait_s=0.5)
                seeds = await prober.discover()
            finally:
                for r in (r1, r2, other):
                    r.stop_responder()
            # both same-cluster responders answer; OTHER's never does
            assert ("10.0.0.1", 4370) in seeds, seeds
            assert ("10.0.0.2", 4371) in seeds, seeds
            assert ("10.9.9.9", 9999) not in seeds, seeds
        run(loop, go())

    def test_autocluster_mcast_join(self, loop):
        from emqx_tpu.cluster.discovery import McastDiscovery, autocluster
        if not self._can_mcast(loop):
            pytest.skip("no multicast-capable interface")

        async def go():
            na = Node(use_device=False, name="ma@127.0.0.1")
            nb = Node(use_device=False, name="mb@127.0.0.1")
            ca = ClusterNode(na, port=0, heartbeat_s=0.05)
            cb = ClusterNode(nb, port=0, heartbeat_s=0.05)
            await ca.start()
            await cb.start()
            try:
                da = McastDiscovery(port=45372, cluster_name="mauto",
                                    wait_s=0.4)
                db = McastDiscovery(port=45372, cluster_name="mauto",
                                    wait_s=0.4)
                # A comes up first (finds nobody), then B finds A
                assert await autocluster(ca, da) == 0
                joined = await autocluster(cb, db)
                assert joined == 1
                await asyncio.sleep(0.2)
                assert set(ca.membership.running_nodes()) == \
                    {"ma@127.0.0.1", "mb@127.0.0.1"}
                da.stop_responder()
                db.stop_responder()
            finally:
                await ca.stop()
                await cb.stop()
        run(loop, go())

    def test_from_config_mcast(self):
        from emqx_tpu.cluster.discovery import McastDiscovery, from_config
        d = from_config({"discovery": "mcast", "name": "c1",
                         "mcast": {"addr": "239.192.0.5",
                                   "ports": [45373], "ttl": 2,
                                   "loop": True}})
        assert isinstance(d, McastDiscovery)
        assert (d.addr, d.port, d.ttl, d.cluster_name) == \
            ("239.192.0.5", 45373, 2, "c1")
