"""Two-OS-process cluster: the deployment shape the reference tests with
scripts/start-two-nodes-in-docker.sh (SURVEY §4 "Multi-node" row).

Each node is a separate python process (tools/run_node.py) with its own
event loop, RPC listener, and MQTT listener; the harness wires a cluster
join, then drives real MQTT clients cross-node: subscribe on A, publish
on B → delivery must cross the node boundary over the RPC channel.
"""

import asyncio
import os
import signal
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")


def _readline_deadline(p, timeout_s):
    """readline with a deadline: a node that boots but never prints READY
    must fail the test, not hang pytest with an orphaned broker."""
    import selectors
    sel = selectors.DefaultSelector()
    sel.register(p.stdout, selectors.EVENT_READ)
    buf = b""
    import time
    deadline = time.monotonic() + timeout_s
    fd = p.stdout.fileno()
    while time.monotonic() < deadline:
        if not sel.select(timeout=0.2):
            continue
        chunk = os.read(fd, 4096)
        if not chunk:
            break
        buf += chunk
        if b"\n" in buf:
            return buf.split(b"\n", 1)[0].decode()
    p.kill()
    raise AssertionError(f"no READY line within {timeout_s}s: {buf!r}")


def _spawn(name, join=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)   # never touch the TPU relay
    cmd = [sys.executable, os.path.join(REPO, "tools", "run_node.py"),
           "--name", name, "--no-device"]
    if join:
        cmd += ["--join", join]
    p = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=None, env=env)
    try:
        line = _readline_deadline(p, 60).strip()
        assert line.startswith("READY "), \
            f"node {name} failed to boot: {line}"
        _, mqtt_port, rpc_port = line.split()
        return p, int(mqtt_port), int(rpc_port)
    except BaseException:
        p.kill()        # never orphan a half-booted broker
        raise


@pytest.fixture()
def two_nodes():
    a = b = None
    try:
        a = _spawn("a@127.0.0.1")
        b = _spawn("b@127.0.0.1", join=f"127.0.0.1:{a[2]}")
        yield a, b
    finally:
        for p in (x[0] for x in (a, b) if x):
            p.send_signal(signal.SIGTERM)
        for p in (x[0] for x in (a, b) if x):
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_chaos_cycles():
    """Bounded chaos run (tools/chaos_cluster.py): 3-node OS-process
    cluster, SIGKILL a random node per cycle under QoS1 traffic, assert
    fast CONNECT on survivors, PUBACK continuity, delivery resumption,
    membership re-convergence, and reachability of the rejoined node at
    its new dynamic ports. The long-form drive is the same tool with
    more cycles."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", CHAOS_LAX="3")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_cluster.py"),
         "2"],
        capture_output=True, text=True, timeout=240, env=env)
    assert r.returncode == 0, f"chaos failed:\n{r.stdout}\n{r.stderr}"
    assert "CHAOS OK" in r.stdout


def test_cross_process_pubsub(two_nodes):
    (pa, mqtt_a, _), (pb, mqtt_b, _) = two_nodes

    async def go():
        from emqx_tpu.client import Client

        sub = Client(port=mqtt_a, clientid="sub-a")
        await sub.connect()
        from emqx_tpu.mqtt import packet as P
        await sub.subscribe([("x/cross/#", P.SubOpts(qos=0))])

        pub = Client(port=mqtt_b, clientid="pub-b")
        await pub.connect()
        # replication is async: wait for the route to reach node B by
        # publishing until delivery lands (bounded)
        got = None
        for i in range(100):
            await pub.publish(f"x/cross/{i}", b"hello", qos=0)
            try:
                got = await asyncio.wait_for(sub.messages.get(), 0.2)
                break
            except asyncio.TimeoutError:
                continue
        assert got is not None, "cross-node delivery never arrived"
        assert got.topic.startswith("x/cross/")
        assert got.payload == b"hello"

        # reverse direction: subscribe on B, publish on A
        sub2 = Client(port=mqtt_b, clientid="sub-b")
        await sub2.connect()
        await sub2.subscribe([("y/back", P.SubOpts(qos=0))])
        pub2 = Client(port=mqtt_a, clientid="pub-a")
        await pub2.connect()
        got2 = None
        for _ in range(100):
            await pub2.publish("y/back", b"rsvp", qos=0)
            try:
                got2 = await asyncio.wait_for(sub2.messages.get(), 0.2)
                break
            except asyncio.TimeoutError:
                continue
        assert got2 is not None and got2.payload == b"rsvp"

        for c in (sub, pub, sub2, pub2):
            await c.disconnect()

    asyncio.run(go())


def test_autocluster_static_discovery(tmp_path):
    """Two processes with `cluster { discovery = static }` config and no
    explicit --join must find each other (run_node drives autocluster);
    proven by cross-node delivery."""
    import time

    confs = {}
    for name, my_rpc, peer_rpc in (("a", 17771, 17772),
                                   ("b", 17772, 17771)):
        c = tmp_path / f"{name}.conf"
        c.write_text(f"""
        listeners {{ t {{ type = tcp, bind = "127.0.0.1", port = 0 }} }}
        cluster {{ discovery = static,
                   nodes = ["127.0.0.1:{peer_rpc}"] }}
        """)
        confs[name] = (str(c), my_rpc)

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    procs = []
    try:
        ports = {}
        for name, (conf, rpc) in confs.items():
            p = subprocess.Popen(
                [sys.executable, os.path.join(REPO, "tools",
                                              "run_node.py"),
                 "--name", f"{name}@127.0.0.1", "--no-device",
                 "--config", conf, "--rpc-port", str(rpc)],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                env=env)
            procs.append(p)
            line = _readline_deadline(p, 60).strip()
            assert line.startswith("READY "), line
            ports[name] = int(line.split()[1])

        async def go():
            from emqx_tpu.client import Client
            from emqx_tpu.mqtt import packet as P
            sub = Client(port=ports["a"], clientid="s")
            await sub.connect()
            await sub.subscribe([("auto/#", P.SubOpts(qos=0))])
            pub = Client(port=ports["b"], clientid="p")
            await pub.connect()
            got = None
            for i in range(150):
                await pub.publish(f"auto/{i}", b"x", qos=0)
                try:
                    got = await asyncio.wait_for(sub.messages.get(), 0.2)
                    break
                except asyncio.TimeoutError:
                    pass
            assert got is not None, "autocluster never joined"
        asyncio.run(go())
    finally:
        for p in procs:
            p.kill()
        for p in procs:
            try:
                p.wait(10)
            except subprocess.TimeoutExpired:
                pass


def test_gray_failure_frozen_peer(two_nodes):
    """SIGSTOP (gray failure: TCP open, node unresponsive) must not park
    CONNECT on the survivor: the clientid-lock RPC and the heartbeat
    probe both bound their connect/handshake phase, so failure detection
    proceeds and the lock skips the frozen target within ~detection +
    one RPC timeout. Pre-fix this parked 25s+ (unbounded handshake wedged
    the beat loop, so nodedown never fired)."""
    import time

    (pa, mqtt_a, _), (pb, _mqtt_b, _) = two_nodes

    async def go():
        from emqx_tpu.client import Client
        from emqx_tpu.mqtt import packet as P

        warm = Client(port=mqtt_a, clientid="warm")
        await warm.connect()
        await warm.disconnect()

        os.kill(pb.pid, signal.SIGSTOP)
        try:
            await asyncio.sleep(0.3)
            t0 = time.monotonic()
            c = Client(port=mqtt_a, clientid="during-freeze")
            await c.connect(timeout=20)
            dt = time.monotonic() - t0
            assert dt < 15, f"gray failure parked CONNECT {dt:.1f}s"
            # the survivor still serves end-to-end during the freeze
            await c.subscribe([("gray/t", P.SubOpts(qos=1))])
            await c.publish("gray/t", b"ping", qos=1)
            got = await asyncio.wait_for(c.messages.get(), 10)
            assert got.payload == b"ping"
            await c.disconnect()
        finally:
            os.kill(pb.pid, signal.SIGCONT)

        await asyncio.sleep(2)            # thaw: autoheal
        c2 = Client(port=mqtt_a, clientid="after-thaw")
        await c2.connect(timeout=10)
        await c2.disconnect()

    asyncio.run(go())


def test_node_death_is_survivable(two_nodes):
    """Killing B must leave A serving: its clients still pub/sub locally."""
    (pa, mqtt_a, _), (pb, _mqtt_b, _) = two_nodes

    async def go():
        from emqx_tpu.client import Client
        from emqx_tpu.mqtt import packet as P

        pb.kill()
        pb.wait(timeout=10)
        await asyncio.sleep(0.2)

        c = Client(port=mqtt_a, clientid="local-a")
        await c.connect()
        await c.subscribe([("alive/check", P.SubOpts(qos=1))])
        await c.publish("alive/check", b"ping", qos=1)
        got = await asyncio.wait_for(c.messages.get(), 5)
        assert got.payload == b"ping"
        await c.disconnect()

    asyncio.run(go())
