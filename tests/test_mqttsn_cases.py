"""MQTT-SN wire-level conformance: the reference's C client case matrix.

A 1:1 port of /root/reference/apps/emqx_gateway/test/intergration_test/
client/*.c — each test below maps onto the C program of the same name
(case1..case7: 12 case programs; the 13th file, int_test_result.c, is
the harness's result reporter, not a case). The C harness runs pub/sub
pairs as separate processes against a live gateway; here each leg is a
named test driving the same wire bytes over UDP (the acceptable language
swap SURVEY.md §2.3 records for this component).

Case matrix (from the C sources' headers):
  case1  qos0 publish with a SHORT topic name ("tt"), subscriber on the
         normal name auto-registered at SUBSCRIBE
  case2  qos0 publish with a PREDEFINED topic id
  case3  qos0 publish with a NORMAL topic id obtained via REGISTER
  case4  QoS -1 (qos bits 0b11) publish with a PREDEFINED id, no CONNECT
  case5  QoS -1 publish with a SHORT topic name, no CONNECT
  case6  sleeping client: DISCONNECT(duration) handshake, buffered
         delivery drained by PINGREQ(clientid)
  case7  double connect: same clientid reconnects, new clientid connects
"""

import asyncio
import struct

import pytest

import emqx_tpu.gateway.mqttsn as SN
from emqx_tpu.broker.message import make
from emqx_tpu.broker.node import Node


class Capture:
    def __init__(self):
        self.msgs = []

    def deliver(self, tf, msg):
        self.msgs.append(msg)
        return True


class SnWireClient(asyncio.DatagramProtocol):
    """Raw-UDP client, byte-for-byte what the C clients send."""

    def __init__(self):
        self.inbox = asyncio.Queue()

    def datagram_received(self, data, addr):
        self.inbox.put_nowait(SN.decode(data))

    @classmethod
    async def create(cls, port):
        loop = asyncio.get_running_loop()
        proto = cls()
        transport, _ = await loop.create_datagram_endpoint(
            lambda: proto, remote_addr=("127.0.0.1", port))
        proto.transport = transport
        return proto

    def send(self, msg_type, body=b""):
        self.transport.sendto(SN.encode(msg_type, body))

    async def recv(self, timeout=5):
        return await asyncio.wait_for(self.inbox.get(), timeout)

    async def connect(self, clientid=b"testclientid_case1", flags=0):
        # MQTTSNSerialize_connect: flags, protocol_id=1, duration, clientid
        self.send(SN.CONNECT, bytes([flags, 1]) +
                  struct.pack(">H", 60) + clientid)
        t, body = await self.recv()
        assert t == SN.CONNACK and body[0] == 0, (t, body)

    async def subscribe_name(self, topicname: bytes, qos=1, mid=2):
        """SUBSCRIBE by topic NAME (type 0b00); returns the assigned
        topic id from SUBACK (auto-registration at subscribe)."""
        self.send(SN.SUBSCRIBE, bytes([qos << 5]) +
                  struct.pack(">H", mid) + topicname)
        t, body = await self.recv()
        assert t == SN.SUBACK and body[-1] == 0, (t, body)
        return struct.unpack(">H", body[1:3])[0]

    async def subscribe_predef(self, tid: int, qos=1, mid=2):
        """SUBSCRIBE by PREDEFINED id (topic-type bits 0b01)."""
        self.send(SN.SUBSCRIBE, bytes([(qos << 5) | 0x01]) +
                  struct.pack(">H", mid) + struct.pack(">H", tid))
        t, body = await self.recv()
        assert t == SN.SUBACK and body[-1] == 0, (t, body)

    def publish_short(self, name: bytes, payload: bytes, qos=0, mid=0):
        """PUBLISH with a SHORT (2-char) topic, type bits 0b10."""
        q = 3 if qos == -1 else qos
        self.send(SN.PUBLISH, bytes([(q << 5) | 0x02]) + name +
                  struct.pack(">H", mid) + payload)

    def publish_predef(self, tid: int, payload: bytes, qos=0, mid=0):
        q = 3 if qos == -1 else qos
        self.send(SN.PUBLISH, bytes([(q << 5) | 0x01]) +
                  struct.pack(">H", tid) + struct.pack(">H", mid) + payload)

    def publish_normal(self, tid: int, payload: bytes, qos=0, mid=0):
        self.send(SN.PUBLISH, bytes([qos << 5]) +
                  struct.pack(">H", tid) + struct.pack(">H", mid) + payload)

    async def register(self, topicname: bytes, mid=1) -> int:
        self.send(SN.REGISTER, struct.pack(">HH", 0, mid) + topicname)
        t, body = await self.recv()
        assert t == SN.REGACK and body[4] == 0, (t, body)
        return struct.unpack(">H", body[:2])[0]

    async def expect_publish(self, timeout=5):
        """Collect the next PUBLISH, transparently REGACK-ing any
        gateway REGISTER (the C read_publish loop does the same)."""
        while True:
            t, body = await self.recv(timeout)
            if t == SN.REGISTER:
                tid, mid = struct.unpack(">HH", body[:4])
                self.send(SN.REGACK,
                          struct.pack(">HH", tid, mid) + b"\x00")
                continue
            if t == SN.PUBLISH:
                flags = body[0]
                qos = (flags >> 5) & 0x3
                mid = struct.unpack(">H", body[3:5])[0]
                if qos == 1:
                    self.send(SN.PUBACK, body[1:3] +
                              struct.pack(">H", mid) + b"\x00")
                return body[5:], flags
            # ignore anything else (ADVERTISE etc.)

    def close(self):
        self.transport.close()


@pytest.fixture()
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


@pytest.fixture()
def sn(loop):
    node = Node(use_device=False)
    # predef_topicid 1, exactly the C harness's PRE_DEF_TOPIC_ID
    gw = SN.MqttSnGateway(node, {"port": 0,
                                 "predefined": {1: "predef/topic1"}})
    loop.run_until_complete(gw.start())
    yield node, gw
    loop.run_until_complete(gw.stop())


def run(loop, coro):
    return loop.run_until_complete(asyncio.wait_for(coro, 15))


class TestCase1ShortTopic:
    def test_case1_qos0pub(self, loop, sn):
        """case1_qos0pub.c: qos0 publish with SHORT topic 'tt' routes."""
        node, gw = sn

        async def go():
            cap = Capture()
            node.broker.subscribe(node.broker.register(cap, "c"), "tt")
            c = await SnWireClient.create(gw.port)
            await c.connect(b"testclientid_case1pub")
            c.publish_short(b"tt", b"short-topic qos0")
            await asyncio.sleep(0.1)
            assert cap.msgs and cap.msgs[0].payload == b"short-topic qos0"
            assert cap.msgs[0].topic == "tt" and cap.msgs[0].qos == 0
            c.close()
        run(loop, go())

    def test_case1_qos0sub(self, loop, sn):
        """case1_qos0sub.c: subscribe the normal name 'tt' (registered at
        SUBSCRIBE), receive the short-topic publish."""
        node, gw = sn

        async def go():
            sub = await SnWireClient.create(gw.port)
            await sub.connect(b"testclientid_case1")
            await sub.subscribe_name(b"tt", qos=1)
            pub = await SnWireClient.create(gw.port)
            await pub.connect(b"testclientid_case1pub")
            pub.publish_short(b"tt", b"case1 payload")
            payload, _flags = await sub.expect_publish()
            assert payload == b"case1 payload"
            sub.close()
            pub.close()
        run(loop, go())


class TestCase2Predefined:
    def test_case2_qos0pub(self, loop, sn):
        """case2_qos0pub.c: qos0 publish with PREDEFINED topic id 1."""
        node, gw = sn

        async def go():
            cap = Capture()
            node.broker.subscribe(node.broker.register(cap, "c"),
                                  "predef/topic1")
            c = await SnWireClient.create(gw.port)
            await c.connect(b"testclientid_case2pub")
            c.publish_predef(1, b"predefined qos0")
            await asyncio.sleep(0.1)
            assert cap.msgs and cap.msgs[0].payload == b"predefined qos0"
            assert cap.msgs[0].topic == "predef/topic1"
            c.close()
        run(loop, go())

    def test_case2_qos0sub(self, loop, sn):
        """case2_qos0sub.c: subscribe by PREDEFINED id, receive."""
        node, gw = sn

        async def go():
            sub = await SnWireClient.create(gw.port)
            await sub.connect(b"testclientid_case2")
            await sub.subscribe_predef(1, qos=1)
            pub = await SnWireClient.create(gw.port)
            await pub.connect(b"testclientid_case2pub")
            pub.publish_predef(1, b"case2 payload")
            payload, _flags = await sub.expect_publish()
            assert payload == b"case2 payload"
            sub.close()
            pub.close()
        run(loop, go())


class TestCase3RegisteredTopic:
    def test_case3_qos0pub(self, loop, sn):
        """case3_qos0pub.c: REGISTER a normal topic name, publish qos0 by
        the returned NORMAL topic id."""
        node, gw = sn

        async def go():
            cap = Capture()
            node.broker.subscribe(node.broker.register(cap, "c"),
                                  "predef_topic1")
            c = await SnWireClient.create(gw.port)
            await c.connect(b"testclientid_case3pub")
            tid = await c.register(b"predef_topic1")
            c.publish_normal(tid, b"registered qos0")
            await asyncio.sleep(0.1)
            assert cap.msgs and cap.msgs[0].payload == b"registered qos0"
            assert cap.msgs[0].topic == "predef_topic1"
            c.close()
        run(loop, go())

    def test_case3_qos0sub(self, loop, sn):
        """case3_qos0sub.c: subscriber on the registered name receives
        the normal-topic-id publish."""
        node, gw = sn

        async def go():
            sub = await SnWireClient.create(gw.port)
            await sub.connect(b"testclientid_case3")
            await sub.subscribe_name(b"predef_topic1", qos=1)
            pub = await SnWireClient.create(gw.port)
            await pub.connect(b"testclientid_case3pub")
            tid = await pub.register(b"predef_topic1")
            pub.publish_normal(tid, b"case3 payload")
            payload, _flags = await sub.expect_publish()
            assert payload == b"case3 payload"
            sub.close()
            pub.close()
        run(loop, go())


class TestCase4QosMinus1Predefined:
    def test_case4_qos3pub(self, loop, sn):
        """case4_qos3pub.c: QoS -1 publish with PREDEFINED id 1, NO
        CONNECT at all — fire and forget."""
        node, gw = sn

        async def go():
            cap = Capture()
            node.broker.subscribe(node.broker.register(cap, "c"),
                                  "predef/topic1")
            c = await SnWireClient.create(gw.port)
            c.publish_predef(1, b"qos -1 predefined", qos=-1)
            await asyncio.sleep(0.1)
            assert cap.msgs and cap.msgs[0].payload == b"qos -1 predefined"
            c.close()
        run(loop, go())

    def test_case4_qos3sub(self, loop, sn):
        """case4_qos3sub.c: a connected subscriber on the predefined
        topic receives the connection-less QoS -1 publish."""
        node, gw = sn

        async def go():
            sub = await SnWireClient.create(gw.port)
            await sub.connect(b"testclientid_case4")
            await sub.subscribe_predef(1, qos=1)
            pub = await SnWireClient.create(gw.port)
            pub.publish_predef(1, b"case4 payload", qos=-1)
            payload, _flags = await sub.expect_publish()
            assert payload == b"case4 payload"
            sub.close()
            pub.close()
        run(loop, go())


class TestCase5QosMinus1Short:
    def test_case5_qos3pub(self, loop, sn):
        """case5_qos3pub.c: QoS -1 publish with SHORT topic, no CONNECT."""
        node, gw = sn

        async def go():
            cap = Capture()
            node.broker.subscribe(node.broker.register(cap, "c"), "tt")
            c = await SnWireClient.create(gw.port)
            c.publish_short(b"tt", b"qos -1 short", qos=-1)
            await asyncio.sleep(0.1)
            assert cap.msgs and cap.msgs[0].payload == b"qos -1 short"
            c.close()
        run(loop, go())

    def test_case5_qos3sub(self, loop, sn):
        """case5_qos3sub.c: subscriber on the short name receives the
        connection-less QoS -1 publish."""
        node, gw = sn

        async def go():
            sub = await SnWireClient.create(gw.port)
            await sub.connect(b"testclientid_case5")
            await sub.subscribe_name(b"tt", qos=0)
            pub = await SnWireClient.create(gw.port)
            pub.publish_short(b"tt", b"case5 payload", qos=-1)
            payload, _flags = await sub.expect_publish()
            assert payload == b"case5 payload"
            sub.close()
            pub.close()
        run(loop, go())


class TestCase6Sleep:
    def test_case6_sleep(self, loop, sn):
        """case6_sleep.c: DISCONNECT(duration=5) answered with
        DISCONNECT; messages buffer while asleep; PINGREQ(clientid)
        drains them and ends with PINGRESP."""
        node, gw = sn

        async def go():
            c = await SnWireClient.create(gw.port)
            await c.connect(b"testclientid_case1")
            await c.subscribe_name(b"tt", qos=1)
            # sleep handshake: DISCONNECT with a duration field
            c.send(SN.DISCONNECT, struct.pack(">H", 5))
            t, _body = await c.recv()
            assert t == SN.DISCONNECT
            # publish while asleep: must buffer, not deliver
            node.broker.publish(make("m", 1, "tt", b"while asleep"))
            await asyncio.sleep(0.2)
            assert c.inbox.empty()
            # wake: PINGREQ with clientid drains the buffer
            c.send(SN.PINGREQ, b"testclientid_case1")
            payload, _flags = await c.expect_publish()
            assert payload == b"while asleep"
            t, _body = await c.recv()
            assert t == SN.PINGRESP
            c.close()
        run(loop, go())


class TestCase7DoubleConnect:
    def test_case7_double_connect(self, loop, sn):
        """case7_double_connect.c: connect clientid A, connect a NEW
        clientid, reconnect the OLD clientid — each CONNACK accepted."""
        node, gw = sn

        async def go():
            c1 = await SnWireClient.create(gw.port)
            await c1.connect(b"testclientid_case7")
            c2 = await SnWireClient.create(gw.port)
            await c2.connect(b"testclientid_case7_new")
            c3 = await SnWireClient.create(gw.port)
            await c3.connect(b"testclientid_case7")   # old id again
            # the reconnected old id is live: it can subscribe + receive
            await c3.subscribe_name(b"tt", qos=1, mid=9)
            pub = await SnWireClient.create(gw.port)
            await pub.connect(b"pub7")
            pub.publish_short(b"tt", b"after reconnect")
            payload, _flags = await c3.expect_publish()
            assert payload == b"after reconnect"
            for c in (c1, c2, c3, pub):
                c.close()
        run(loop, go())
