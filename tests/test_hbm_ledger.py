"""Device-resource observatory (ISSUE 8).

Coverage, per the issue's tentpole + satellite list:

- knob resolution (`broker.hbm_ledger` / `EMQX_TPU_HBM_LEDGER` config
  beats env beats default-on; `EMQX_TPU_PIN_WARN_WINDOWS` validation)
- ledger unit lifecycle: hold/weakref-release, aliased-leaf dedup,
  peak watermarks, owner accounting, non-weakrefable leaf skip
- reconciliation: ledger-accounted bytes == summed `.nbytes` of the
  live held pytrees within 1% (live engine AND tools/hbm_report.py
  measure points)
- snapshot swap + overlay lifecycle: bytes return to baseline after a
  rebuild, no weakref leaks (live_leaves returns to the live set)
- the pin sentinel: counter + `pipeline.pin_stale` hook + `stale_pin`
  flight-recorder event after EMQX_TPU_PIN_WARN_WINDOWS windows,
  fired once per handle
- A/B: `EMQX_TPU_HBM_LEDGER=0` yields no ledger objects anywhere, an
  identical snapshot schema minus `memory`, and bit-identical
  delivery counts
- exporter exposition of the `memory` section: $SYS
  `pipeline/memory`, Prometheus gauge families, StatsD lines, REST
  `GET /api/v5/pipeline/memory`
- the jit-program cost registry: per-class compile rows recorded by
  the wrapped route programs, `snapshot()["program_costs"]`, lazy
  `cost_stats(analyze=True)` flop/byte fill, external rows via
  `record_program_cost`
- the untracked-allocation gate (tools/check_hbm_hygiene.py) as a
  tier-1 test over emqx_tpu/
- tools/hbm_report.py: the capacity forecast fits per-sub bytes and
  reports a >=10M-subscription ceiling for the 16GB budget
- the ledger-overhead guard: per-window ledger cost (<1% of a window)
"""

import gc
import json
import os
import sys
import time
import weakref

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))

from emqx_tpu.broker import hbm_ledger as H      # noqa: E402
from emqx_tpu.broker.message import make         # noqa: E402
from emqx_tpu.broker.metrics import Metrics      # noqa: E402
from emqx_tpu.broker.node import Node            # noqa: E402


class Sink:
    def __init__(self):
        self.got = []

    def deliver(self, topic_filter, msg):
        self.got.append((topic_filter, msg.topic))
        return True


def _mk_node(**over):
    conf = {"device_fanout_cap": 16, "device_slot_cap": 4,
            "device_min_batch": 1, "deliver_lanes": 0}
    conf.update(over)
    return Node({"broker": conf})


def _subscribe(node, n=8):
    sinks = []
    for i in range(n):
        s = Sink()
        sid = node.broker.register(s, f"c{i}")
        node.broker.subscribe(sid, f"t/{i}/+", {"qos": 1})
        sinks.append(s)
    return sinks


def _route(node, windows=3, n=8):
    """Synchronous route_batch windows (no loop needed)."""
    out = []
    for w in range(windows):
        out.append(node.device_engine.route_batch(
            [make("p", 0, f"t/{i}/x", b"m%d" % w) for i in range(n)]))
    return out


def _tree_nbytes(tree) -> int:
    return sum(int(x.nbytes) for x in H._leaves(tree))


@pytest.fixture(scope="module")
def ledger_run():
    """One routed node with the ledger on (default), shared by the
    read-only tests: (node, delivery counts)."""
    node = _mk_node()
    _subscribe(node)
    counts = _route(node, windows=4)
    return node, counts


# ---------- knob resolution ----------

class TestKnobs:
    def test_config_beats_env_beats_default(self, monkeypatch):
        assert H.resolve_hbm_ledger(None) is True
        monkeypatch.setenv("EMQX_TPU_HBM_LEDGER", "0")
        assert H.resolve_hbm_ledger(None) is False
        assert H.resolve_hbm_ledger(True) is True     # config wins
        monkeypatch.setenv("EMQX_TPU_HBM_LEDGER", "off")
        assert H.resolve_hbm_ledger(None) is False

    def test_pin_warn_windows_resolution(self, monkeypatch):
        assert H.resolve_pin_warn_windows(None) == 64
        monkeypatch.setenv("EMQX_TPU_PIN_WARN_WINDOWS", "7")
        assert H.resolve_pin_warn_windows(None) == 7
        assert H.resolve_pin_warn_windows(3) == 3     # config wins
        with pytest.raises(ValueError):
            H.resolve_pin_warn_windows(0)
        with pytest.raises(ValueError):
            H.resolve_pin_warn_windows(-4)
        monkeypatch.setenv("EMQX_TPU_PIN_WARN_WINDOWS", "banana")
        with pytest.raises(ValueError):
            H.resolve_pin_warn_windows(None)

    def test_host_only_node_has_no_ledger(self):
        node = Node(use_device=False)
        assert node.hbm_ledger is None

    def test_env_knob_off(self, monkeypatch):
        monkeypatch.setenv("EMQX_TPU_HBM_LEDGER", "0")
        node = _mk_node()
        assert node.hbm_ledger is None
        assert node.pipeline_telemetry.ledger is None


# ---------- ledger unit lifecycle ----------

class TestLedgerUnit:
    def test_hold_release_and_alias_dedup(self):
        m = Metrics()
        led = H.HbmLedger(m)
        a = np.zeros(1000, np.int32)          # 4000 B
        b = np.ones(10, np.float64)           # 80 B
        tree = {"x": a, "y": [a, b]}          # a aliased twice
        out = led.hold("snapshot_tables", tree, owner="sid1")
        assert out is tree                    # identity passthrough
        assert led.live_bytes() == 4080       # alias counted once
        assert led.live_bytes("snapshot_tables") == 4080
        assert led.live_leaves() == 2
        sec = led.section()
        cat = sec["categories"]["snapshot_tables"]
        assert cat["live_bytes"] == 4080 and cat["holds"] == 1
        assert cat["owners"] == {"sid1": 4080}
        # metric counts LEAVES (2), symmetric with per-finalizer
        # releases; the category row counts hold() calls (1)
        assert m.val("pipeline.memory.holds") == 2
        assert m.val("pipeline.memory.hold_bytes") == 4080
        # release is AUTOMATIC: drop the arrays, GC returns the bytes
        del tree, out, a, b
        gc.collect()
        assert led.live_bytes() == 0
        assert led.live_leaves() == 0
        assert m.val("pipeline.memory.releases") == 2
        assert m.val("pipeline.memory.release_bytes") == 4080
        # peak watermark + release count survive the release
        cat = led.section()["categories"]["snapshot_tables"]
        assert cat["peak_bytes"] == 4080
        assert cat["releases"] == 2

    def test_owner_accounting_clears_on_release(self):
        led = H.HbmLedger()
        a = np.zeros(100, np.int8)
        led.hold("delta_overlay", a, owner="v3")
        assert led.section()["categories"]["delta_overlay"][
            "owners"] == {"v3": 100}
        del a
        gc.collect()
        assert "owners" not in led.section()[
            "categories"]["delta_overlay"]

    def test_non_weakrefable_leaf_skipped(self):
        led = H.HbmLedger()
        # numpy scalars expose .nbytes but reject weakrefs — the
        # ledger must skip them rather than leak an unreleasable entry
        with pytest.raises(TypeError):
            weakref.finalize(np.int32(5), lambda: None)
        tree = [np.int32(5), np.zeros(4, np.int8)]
        led.hold("snapshot_tables", tree)
        assert led.live_bytes() == 4
        assert led.live_leaves() == 1
        del tree

    def test_rehold_same_leaf_is_idempotent(self):
        led = H.HbmLedger()
        a = np.zeros(64, np.int8)
        led.hold("snapshot_cursors", a)
        led.hold("snapshot_cursors", a)     # cursor re-adopt idiom
        assert led.live_bytes() == 64
        assert led.section()["categories"]["snapshot_cursors"][
            "holds"] == 2

    def test_global_peak_is_true_high_water_mark(self):
        """Top-level peak_bytes is the high-water mark of SUMMED live
        bytes — not the sum of per-category peaks, which can report a
        total that never occurred when categories peak at different
        times."""
        led = H.HbmLedger()
        a = led.hold("snapshot_tables", np.zeros(1000, np.int8))
        del a
        gc.collect()                       # tables gone: live back to 0
        b = led.hold("delta_overlay", np.zeros(600, np.int8))
        sec = led.section()
        assert sec["live_bytes"] == 600
        assert sec["peak_bytes"] == 1000   # not 1600 (sum of cat peaks)
        assert sec["categories"]["snapshot_tables"]["peak_bytes"] == 1000
        assert sec["categories"]["delta_overlay"]["peak_bytes"] == 600
        assert b is not None               # keep the hold live

    def test_section_is_json_clean(self):
        led = H.HbmLedger()
        held = led.hold("mesh_tables", np.zeros(8, np.int8), owner="s0")
        doc = json.loads(json.dumps(led.section()))
        assert held is not None     # keep the hold live for the read
        assert doc["schema"] == H.SCHEMA
        assert doc["live_bytes"] == 8
        assert doc["pins"]["outstanding"] == 0


# ---------- pin sentinel ----------

class TestPinSentinel:
    def test_warning_fires_once_past_threshold(self):
        from emqx_tpu.broker.hooks import Hooks
        from emqx_tpu.broker.trace import FlightRecorder
        m = Metrics()
        hooks = Hooks()
        fired = []
        hooks.add("pipeline.pin_stale", lambda info: fired.append(info))
        rec = FlightRecorder(cap=64, sample=0)
        led = H.HbmLedger(m, pin_warn_windows=3, hooks=hooks,
                          recorder=rec)

        class Handle:
            trace = 42
        h = Handle()     # alive-but-leaked: something still holds it
        led.pin(1, h)
        for _ in range(3):
            led.note_window()
        assert led.pin_warnings == 0          # age == threshold: OK
        led.note_window()                     # age 4 > 3: fires
        assert led.pin_warnings == 1
        assert m.val("pipeline.memory.pin_warnings") == 1
        assert fired and fired[0]["age_windows"] == 4
        assert fired[0]["warn_windows"] == 3
        assert fired[0]["trace"] == 42
        evs = [s for s in rec.spans() if s.name == "stale_pin"]
        assert evs and evs[0].trace_id == 42
        assert evs[0].meta["age_windows"] == 4
        # fires ONCE per handle, not once per window
        led.note_window()
        assert led.pin_warnings == 1
        st = led.pin_state()
        assert st["outstanding"] == 1 and st["warnings"] == 1
        assert st["max_age_windows"] == 5
        led.unpin(1)
        assert led.pin_state()["outstanding"] == 0

    def test_pin_holds_handle_by_weakref_only(self):
        # the ledger must never retain the handle it is instrumenting:
        # a truly dropped handle stays collectable (its snapshot HBM
        # frees) and the sentinel still fires, trace falling back to 0
        import gc
        led = H.HbmLedger(None, pin_warn_windows=1)

        class Handle:
            trace = 7
        led.pin(1, Handle())          # no other reference anywhere
        gc.collect()
        assert led._pins[1][1]() is None
        led.note_window()
        led.note_window()             # age 2 > 1: fires, trace=0
        assert led.pin_warnings == 1

    def test_live_engine_pins_ride_the_clock(self):
        node = _mk_node(pin_warn_windows=2)
        _subscribe(node)
        _route(node)                          # snapshot built + warm
        eng = node.device_engine
        led = node.hbm_ledger
        h = eng.prepare([make("p", 0, "t/0/z", b"")], gate_cold=False)
        assert h is not None
        assert led.pin_state()["outstanding"] == 1
        for _ in range(4):
            led.note_window()
        assert node.metrics.val("pipeline.memory.pin_warnings") >= 1
        eng.abandon(h)
        assert led.pin_state()["outstanding"] == 0


# ---------- reconciliation + swap/overlay lifecycle ----------

class TestLifecycle:
    def test_live_bytes_reconcile_with_held_trees(self, ledger_run):
        """The acceptance criterion: ledger-accounted bytes == summed
        .nbytes of the LIVE held pytrees, within 1%."""
        node, _counts = ledger_run
        eng = node.device_engine
        gc.collect()                 # superseded cursor chains release
        led = node.hbm_ledger
        expected = _tree_nbytes(eng._tables) + _tree_nbytes(
            eng._cursors)
        ov = getattr(eng, "_overlay", None)
        if ov is not None:
            expected += _tree_nbytes(ov.dev)
        live = led.live_bytes()
        assert expected > 0
        assert abs(live - expected) / expected < 0.01, (live, expected)

    def test_swap_returns_bytes_to_baseline(self):
        """A snapshot rebuild swaps new tables in; the old snapshot's
        bytes must come back through the weakref finalizers — the
        leak class the ledger exists to catch."""
        node = _mk_node()
        _subscribe(node)
        _route(node)
        led = node.hbm_ledger
        eng = node.device_engine
        gc.collect()
        base_bytes = led.live_bytes()
        base_leaves = led.live_leaves()
        holds0 = led.section()["categories"]["snapshot_tables"]["holds"]
        for i in range(3):
            eng.rebuild()            # full swap, same route set
            _route(node, windows=1)
        gc.collect()
        assert led.section()["categories"]["snapshot_tables"][
            "holds"] > holds0       # the swaps really re-held
        # same route set -> same table sizes: bytes return to baseline
        assert led.live_bytes() == pytest.approx(base_bytes, rel=0.01)
        # no weakref leaks: the live set tracks the live snapshot only
        assert led.live_leaves() <= base_leaves + 2

    def test_overlay_versions_release_on_compaction(self):
        """Delta-overlay versions are per-version ledger owners; a
        rebuild (compaction) folds them into the snapshot and their
        bytes must return."""
        node = _mk_node(delta_overlay=True)
        s = Sink()
        sid = node.broker.register(s, "seed")
        for i in range(8):
            node.broker.subscribe(sid, f"dev/{i}/+", {"qos": 1})
        node.device_engine.route_batch(
            [make("p", 0, f"dev/{i}/t", b"") for i in range(8)])
        # post-build churn -> overlay versions
        node.broker.subscribe(sid, "fresh/+/x", {"qos": 0})
        node.broker.subscribe(sid, "deep/#", {"qos": 1})
        node.device_engine.route_batch(
            [make("p", 0, "fresh/1/x", b""), make("p", 0, "deep/a/b", b"")])
        led = node.hbm_ledger
        if led.section()["categories"].get("delta_overlay") is None:
            pytest.skip("overlay did not engage on this backend")
        assert led.live_bytes("delta_overlay") > 0
        node.device_engine.rebuild()     # compaction folds the overlay
        node.device_engine.route_batch(
            [make("p", 0, "fresh/1/x", b"")])
        gc.collect()
        assert led.live_bytes("delta_overlay") == 0
        # ... but the category's history (peak/holds) remains readable
        assert led.section()["categories"]["delta_overlay"][
            "peak_bytes"] > 0


# ---------- A/B: EMQX_TPU_HBM_LEDGER=0 restores current behavior ----

class TestLedgerOffAB:
    def test_off_means_no_ledger_and_same_results(self):
        node_off = _mk_node(hbm_ledger=False)
        assert node_off.hbm_ledger is None
        assert node_off.pipeline_telemetry.ledger is None
        assert node_off.device_engine.ledger is None
        _subscribe(node_off)
        counts_off = _route(node_off, windows=4)
        node_on = _mk_node(hbm_ledger=True)
        assert node_on.hbm_ledger is not None
        _subscribe(node_on)
        counts_on = _route(node_on, windows=4)
        # delivery counts are bit-identical either way
        assert counts_off == counts_on
        # snapshot schema identical minus the memory section
        snap_off = node_off.pipeline_telemetry.snapshot()
        snap_on = node_on.pipeline_telemetry.snapshot()
        assert "memory" not in snap_off
        assert set(snap_off) == set(snap_on) - {"memory"}
        # no memory counters leak into the off registry
        assert node_off.metrics.val("pipeline.memory.holds") == 0
        assert "pipeline.memory.live_bytes" not in \
            node_off.stats.sample()


# ---------- exporter exposition of the memory section ----------

class TestExporters:
    def test_snapshot_memory_section(self, ledger_run):
        node, _counts = ledger_run
        snap = node.pipeline_telemetry.snapshot()
        mem = snap["memory"]
        assert mem["schema"] == H.SCHEMA
        assert mem["live_bytes"] > 0
        assert mem["categories"]["snapshot_tables"]["live_bytes"] > 0
        assert "pins" in mem
        json.dumps(snap)        # the whole document stays JSON-clean

    def test_sys_publishes_memory_topic(self, ledger_run):
        node, _counts = ledger_run
        from emqx_tpu.apps.sys import SysBroker
        seen = {}

        class Spy(SysBroker):
            def _pub(self, suffix, payload):
                seen[suffix] = payload
        Spy(node).publish_pipeline()
        assert "pipeline/memory" in seen
        doc = json.loads(seen["pipeline/memory"])
        assert doc["live_bytes"] > 0
        # the cost registry rides the same cadence
        assert "pipeline/program_costs" in seen
        assert json.loads(seen["pipeline/program_costs"])

    def test_prometheus_carries_memory_gauges(self, ledger_run):
        node, _counts = ledger_run
        from emqx_tpu.apps.prometheus import collect
        text = collect(node)
        assert "emqx_pipeline_memory_live_bytes" in text
        assert "emqx_pipeline_memory_holds" in text
        for line in text.splitlines():
            if line.startswith("emqx_pipeline_memory_live_bytes "):
                assert int(line.split()[1]) > 0
                break
        else:
            raise AssertionError("live_bytes gauge sample missing")
        # well-formedness: exactly one TYPE declaration per family
        fams = [ln for ln in text.splitlines()
                if ln.startswith("# TYPE emqx_pipeline_memory_")]
        assert len(fams) == len(set(fams)) and fams

    def test_statsd_renders_memory_lines(self, ledger_run):
        node, _counts = ledger_run
        from emqx_tpu.apps.statsd import StatsdApp
        app = StatsdApp(node)
        lines = app.render()
        gauges = [ln for ln in lines
                  if ln.startswith("emqx.pipeline.memory.live_bytes:")]
        assert gauges and gauges[0].endswith("|g")

    def test_api_endpoint(self, ledger_run):
        import asyncio
        node, _counts = ledger_run
        from emqx_tpu.mgmt import make_api

        async def _get(port, path, expect=b"200"):
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", port)
            writer.write(f"GET {path} HTTP/1.1\r\nhost: x\r\n"
                         "connection: close\r\n\r\n".encode())
            await writer.drain()
            raw = await asyncio.wait_for(reader.read(-1), 10)
            writer.close()
            head, _, body = raw.partition(b"\r\n\r\n")
            assert expect in head.split(b"\r\n")[0], head
            return json.loads(body) if expect == b"200" else None

        async def go():
            srv = make_api(node, port=0)
            await srv.start()
            try:
                doc = await _get(srv.port, "/api/v5/pipeline/memory")
                assert doc["schema"] == H.SCHEMA
                assert doc["live_bytes"] > 0
                assert doc["categories"]["snapshot_tables"][
                    "live_bytes"] > 0
            finally:
                await srv.stop()
        loop = asyncio.new_event_loop()
        try:
            loop.run_until_complete(asyncio.wait_for(go(), 60))
        finally:
            loop.close()

    def test_api_endpoint_404_when_off(self):
        import asyncio
        node = _mk_node(hbm_ledger=False)
        from emqx_tpu.mgmt import make_api

        async def go():
            srv = make_api(node, port=0)
            await srv.start()
            try:
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", srv.port)
                writer.write(b"GET /api/v5/pipeline/memory HTTP/1.1"
                             b"\r\nhost: x\r\nconnection: close\r\n\r\n")
                await writer.drain()
                raw = await asyncio.wait_for(reader.read(-1), 10)
                writer.close()
                assert b"404" in raw.split(b"\r\n")[0]
            finally:
                await srv.stop()
        loop = asyncio.new_event_loop()
        try:
            loop.run_until_complete(asyncio.wait_for(go(), 60))
        finally:
            loop.close()


# ---------- the jit-program cost registry ----------

class TestCostRegistry:
    def test_route_programs_record_compile_rows(self, ledger_run):
        import emqx_tpu.models.router_engine as R
        node, _counts = ledger_run
        cs = R.cost_stats()
        assert cs, "no cost rows after a routed run"
        prog, rows = next(iter(cs.items()))
        assert prog.startswith("route_")
        label, row = next(iter(rows.items()))
        assert row["compiles"] >= 1
        assert row["compile_ms"] > 0
        # keyed like compiles.by_shape ("dispatch W1xB64" / "warm ...")
        assert " W" in label or label.startswith("adhoc")
        # no private keys leak into the exported table
        assert not any(k.startswith("_")
                       for r in rows.values() for k in r)

    def test_snapshot_embeds_program_costs(self, ledger_run):
        node, _counts = ledger_run
        snap = node.pipeline_telemetry.snapshot()
        assert snap["program_costs"]
        json.dumps(snap["program_costs"])

    def test_analyze_fills_flops_and_drops_avals(self, ledger_run):
        import emqx_tpu.models.router_engine as R
        _node, _counts = ledger_run
        cs = R.cost_stats(analyze=True)
        rows = [row for prog in cs.values() for row in prog.values()]
        assert rows
        # the CPU backend provides cost_analysis: flops/bytes land
        assert any("flops" in r for r in rows)
        for r in rows:
            if "flops" in r:
                assert r["flops"] > 0
            if "bytes_accessed" in r:
                assert r["bytes_accessed"] > 0
        # analysis is idempotent and cheap the second time
        assert R.cost_stats(analyze=True) == R.cost_stats()

    def test_external_harness_rows(self):
        import emqx_tpu.models.router_engine as R
        R.record_program_cost("bench_kernel", "profile match_only",
                              compile_ms=12.5, flops=1e6,
                              bytes_accessed=2e6)
        row = R.cost_stats()["bench_kernel"]["profile match_only"]
        assert row == {"compiles": 1, "compile_ms": 12.5,
                       "flops": 1e6, "bytes_accessed": 2e6}

    def test_wrapper_is_transparent(self):
        import emqx_tpu.models.router_engine as R
        for fn in (R.route_step, R.route_window_full,
                   R.route_step_cached_compact):
            assert callable(fn.lower)
            assert isinstance(fn._cache_size(), int)
            assert fn.__name__.startswith("route_")

    def test_env_off_leaves_programs_unwrapped(self):
        """EMQX_TPU_HBM_LEDGER=0 restores pre-ISSUE-8 behavior for
        the registry leg too: programs bind unwrapped (zero per-call
        introspection) and snapshot(full=True) has no program_costs
        section. Subprocess: the binding happens at module import."""
        import subprocess
        env = dict(os.environ)
        env["EMQX_TPU_HBM_LEDGER"] = "0"
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        code = (
            "import types\n"
            "import emqx_tpu.models.router_engine as R\n"
            "assert not R.cost_registry_enabled()\n"
            "# unwrapped: the raw jit object, not a plain function\n"
            "assert not isinstance(R.route_step, types.FunctionType)\n"
            "assert not R._cost_programs, 'programs registered'\n"
            "from emqx_tpu.broker.telemetry import PipelineTelemetry\n"
            "snap = PipelineTelemetry().snapshot(full=True)\n"
            "assert 'program_costs' not in snap, sorted(snap)\n"
            "print('OFF_OK')\n")
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=120,
                           env=env, cwd=os.path.dirname(os.path.dirname(
                               os.path.abspath(__file__))))
        assert r.returncode == 0, r.stderr
        assert "OFF_OK" in r.stdout

    def test_foreign_thread_compile_not_attributed(self, ledger_run):
        """The per-thread jax.monitoring compile-seq confirmation: a
        compile on ANOTHER thread bumps that thread's seq, not ours —
        the signal the wrapper uses to reject cache growth it did not
        cause (cross-thread misattribution guard)."""
        import threading as T
        import jax
        import jax.numpy as jnp
        from emqx_tpu.broker import telemetry as tele
        node, _counts = ledger_run      # listener installed + warm
        seq_here = tele.thread_compile_seq()
        assert seq_here is not None     # listener is installed
        done = T.Event()
        other_seq = []

        @jax.jit
        def _fresh(x):
            return x * 2 + 1

        def compile_elsewhere():
            _fresh(jnp.arange(7))       # fresh program: compiles there
            other_seq.append(tele.thread_compile_seq())
            done.set()

        t = T.Thread(target=compile_elsewhere)
        t.start()
        assert done.wait(60)
        t.join()
        assert other_seq[0] >= 1        # the compiling thread saw it
        # our thread's seq did not move: the confirmation signal is
        # exactly per-thread
        assert tele.thread_compile_seq() == seq_here


# ---------- untracked-allocation gate (tier-1 satellite) ----------

class TestHygieneGate:
    def test_no_device_put_bypasses_the_ledger(self):
        import check_hbm_hygiene as hygiene
        root = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "emqx_tpu")
        findings = hygiene.check(root)
        assert not findings, "\n".join(map(repr, findings))

    def test_gate_catches_a_bypass(self):
        import check_hbm_hygiene as hygiene
        bad = "import jax\nx = jax.device_put(tables)\n"
        assert len(hygiene.check_source("x.py", bad)) == 1
        wrapped = "x = ledger.hold('c', jax.device_put(t))\n"
        assert not hygiene.check_source("x.py", wrapped)
        noted = "# hbm: transient — consumed by this dispatch\n" \
                "x = jax.device_put(t)\n"
        assert not hygiene.check_source("x.py", noted)


# ---------- the capacity forecaster ----------

class TestHbmReport:
    def test_forecast_fits_and_extrapolates(self):
        import hbm_report
        doc = hbm_report.report(sizes=(5_000, 10_000, 20_000))
        assert doc["schema"] == hbm_report.SCHEMA
        assert len(doc["points"]) == 3
        for p in doc["points"]:
            # the acceptance reconciliation: ledger vs .nbytes < 1%
            assert p["reconcile_err"] < 0.01
            assert p["released"], "ledger leaked a measure point"
        fit = doc["fit"]
        assert fit["per_sub_bytes"] > 0
        assert fit["r2"] is None or fit["r2"] > 0.9
        head = doc["headline"]
        # the 16GB v5e-1 budget holds the 10M-subscription target
        assert head["budget"] == "16GB"
        assert head["ceiling_subs"] >= 10_000_000
        assert head["target_10m_fits"] is True
        json.dumps(doc)

    def test_cli_writes_report(self, tmp_path):
        import hbm_report
        out = tmp_path / "hbm.json"
        rc = hbm_report.main(["5000", "8000", "--budget-gb", "16",
                              "--out", str(out)])
        assert rc == 0
        doc = json.loads(out.read_text())
        assert doc["budgets"]["16GB"]["ceiling_subs"] > 0


# ---------- ledger-overhead guard ----------

class TestOverheadGuard:
    def test_per_window_ledger_cost_under_1pct(self, ledger_run):
        """Deterministic bound, like the PR-7 tracing guard: the
        per-window ledger work is note_window + pin + unpin. Measure
        the primitive cost tight-loop and bound it against 1% of the
        mean dispatch stage span of the live run — a hot-path
        regression (section() leaking into note_window, a lock on the
        pin path) fails immediately; scheduler noise cannot."""
        node, _counts = ledger_run
        led = H.HbmLedger(pin_warn_windows=64)

        class Handle:
            trace = 1
        h = Handle()
        for i in range(4):              # realistic outstanding depth
            led.pin(1000 + i, h)
        n = 20_000
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for i in range(n):
                led.note_window()
                led.pin(i, h)
                led.unpin(i)
            best = min(best, (time.perf_counter() - t0) / n)
        hist = node.metrics.histograms().get("pipeline.stage.dispatch"
                                             ".seconds")
        if hist is None or not hist.count:
            pytest.skip("no dispatch spans in the shared run")
        mean_window = hist.sum / hist.count
        assert best < 0.01 * mean_window, (
            f"ledger per-window cost {best * 1e6:.2f}us vs mean "
            f"dispatch {mean_window * 1e3:.2f}ms — over the 1% budget")
