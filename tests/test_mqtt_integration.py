"""End-to-end broker tests over real TCP sockets.

Mirrors the reference's emqx_client_SUITE / emqx_mqtt_protocol_v5_SUITE:
a live broker (Node + Listener) driven by the bundled asyncio client.
"""

import asyncio

import pytest

from emqx_tpu.broker.connection import Listener
from emqx_tpu.broker.node import Node
from emqx_tpu.client import Client, MqttError
from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt import packet as P


@pytest.fixture()
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


@pytest.fixture()
def broker(loop):
    node = Node()
    listener = Listener(node, bind="127.0.0.1", port=0)
    loop.run_until_complete(listener.start())
    yield node, listener
    loop.run_until_complete(listener.stop())


def run(loop, coro):
    return loop.run_until_complete(asyncio.wait_for(coro, 15))


class TestConnect:
    def test_connect_v4(self, loop, broker):
        node, lst = broker

        async def go():
            c = Client(port=lst.port, clientid="c1")
            ack = await c.connect()
            assert ack.reason_code == 0 and not ack.session_present
            await c.disconnect()
        run(loop, go())
        assert node.metrics.val("client.connected") == 1
        assert node.metrics.val("client.disconnected") == 1

    def test_connect_v5_props(self, loop, broker):
        node, lst = broker

        async def go():
            c = Client(port=lst.port, clientid="c5", proto_ver=C.MQTT_V5)
            ack = await c.connect()
            assert ack.properties.get("shared_subscription_available") == 1
            assert "receive_maximum" in ack.properties
            await c.disconnect()
        run(loop, go())

    def test_v5_assigned_clientid(self, loop, broker):
        node, lst = broker

        async def go():
            c = Client(port=lst.port, clientid="", proto_ver=C.MQTT_V5)
            ack = await c.connect()
            assert ack.properties.get("assigned_client_identifier")
            await c.disconnect()
        run(loop, go())

    def test_v3_empty_clientid_no_cleanstart_rejected(self, loop, broker):
        node, lst = broker

        async def go():
            c = Client(port=lst.port, clientid="", clean_start=False)
            with pytest.raises(MqttError):
                await c.connect()
            await c.close()
        run(loop, go())


class TestPubSub:
    def test_qos0_roundtrip(self, loop, broker):
        node, lst = broker

        async def go():
            sub = Client(port=lst.port, clientid="sub")
            await sub.connect()
            ack = await sub.subscribe("t/+", qos=0)
            assert ack.reason_codes == [0]
            pub = Client(port=lst.port, clientid="pub")
            await pub.connect()
            await pub.publish("t/1", b"hello")
            m = await sub.recv()
            assert m.topic == "t/1" and m.payload == b"hello" and m.qos == 0
            await sub.disconnect()
            await pub.disconnect()
        run(loop, go())

    def test_qos1_roundtrip(self, loop, broker):
        node, lst = broker

        async def go():
            sub = Client(port=lst.port, clientid="sub")
            await sub.connect()
            await sub.subscribe("a/b", qos=1)
            pub = Client(port=lst.port, clientid="pub")
            await pub.connect()
            ack = await pub.publish("a/b", b"x", qos=1)
            assert isinstance(ack, P.Puback)
            m = await sub.recv()
            assert m.qos == 1 and m.packet_id
            await sub.disconnect()
            await pub.disconnect()
        run(loop, go())
        assert node.metrics.val("messages.qos1.received") == 1
        assert node.metrics.val("messages.acked") >= 1

    def test_qos2_roundtrip(self, loop, broker):
        node, lst = broker

        async def go():
            sub = Client(port=lst.port, clientid="sub")
            await sub.connect()
            await sub.subscribe("q2", qos=2)
            pub = Client(port=lst.port, clientid="pub")
            await pub.connect()
            comp = await pub.publish("q2", b"x", qos=2)
            assert isinstance(comp, P.Pubcomp)
            m = await sub.recv()
            assert m.qos == 2 and m.payload == b"x"
            await sub.disconnect()
            await pub.disconnect()
        run(loop, go())

    def test_qos_downgrade(self, loop, broker):
        node, lst = broker

        async def go():
            sub = Client(port=lst.port, clientid="sub")
            await sub.connect()
            await sub.subscribe("d", qos=0)
            pub = Client(port=lst.port, clientid="pub")
            await pub.connect()
            await pub.publish("d", b"x", qos=1)
            m = await sub.recv()
            assert m.qos == 0
            await sub.disconnect()
            await pub.disconnect()
        run(loop, go())

    def test_unsubscribe(self, loop, broker):
        node, lst = broker

        async def go():
            c = Client(port=lst.port, clientid="c")
            await c.connect()
            await c.subscribe("u/#", qos=0)
            un = await c.unsubscribe("u/#")
            assert un.reason_codes == [] or un.reason_codes == [0]
            pub = Client(port=lst.port, clientid="p")
            await pub.connect()
            await pub.publish("u/x", b"1")
            with pytest.raises(asyncio.TimeoutError):
                await c.recv(timeout=0.3)
            await c.disconnect()
            await pub.disconnect()
        run(loop, go())

    def test_shared_subscription_balances(self, loop, broker):
        node, lst = broker

        async def go():
            subs = []
            for i in range(2):
                s = Client(port=lst.port, clientid=f"m{i}")
                await s.connect()
                await s.subscribe("$share/g/work", qos=0)
                subs.append(s)
            pub = Client(port=lst.port, clientid="p")
            await pub.connect()
            for i in range(4):
                await pub.publish("work", str(i).encode())
            await asyncio.sleep(0.2)
            counts = [s.messages.qsize() for s in subs]
            assert sum(counts) == 4 and counts == [2, 2]
            for s in subs:
                await s.disconnect()
            await pub.disconnect()
        run(loop, go())

    def test_no_local_v5(self, loop, broker):
        node, lst = broker

        async def go():
            c = Client(port=lst.port, clientid="me", proto_ver=C.MQTT_V5)
            await c.connect()
            await c.subscribe("nl/t", qos=0, opts={"nl": 1})
            await c.publish("nl/t", b"self")
            with pytest.raises(asyncio.TimeoutError):
                await c.recv(timeout=0.3)
            await c.disconnect()
        run(loop, go())


class TestSessionLifecycle:
    def test_takeover_and_resume(self, loop, broker):
        node, lst = broker

        async def go():
            c1 = Client(port=lst.port, clientid="dev", clean_start=False,
                        proto_ver=C.MQTT_V5,
                        properties={"session_expiry_interval": 300})
            await c1.connect()
            await c1.subscribe("s/1", qos=1)
            # second connection with same clientid takes over the session
            c2 = Client(port=lst.port, clientid="dev", clean_start=False,
                        proto_ver=C.MQTT_V5,
                        properties={"session_expiry_interval": 300})
            ack = await c2.connect()
            assert ack.session_present
            # subscription survived
            pub = Client(port=lst.port, clientid="p")
            await pub.connect()
            await pub.publish("s/1", b"after", qos=1)
            m = await c2.recv()
            assert m.payload == b"after"
            await c1.close()
            await c2.disconnect()
            await pub.disconnect()
        run(loop, go())
        assert node.metrics.val("session.takenover") == 1

    def test_offline_queue_then_resume(self, loop, broker):
        node, lst = broker

        async def go():
            c1 = Client(port=lst.port, clientid="dev", clean_start=False,
                        proto_ver=C.MQTT_V5,
                        properties={"session_expiry_interval": 300})
            await c1.connect()
            await c1.subscribe("off/q", qos=1)
            await c1.close()        # abrupt close → session parked
            await asyncio.sleep(0.1)
            pub = Client(port=lst.port, clientid="p")
            await pub.connect()
            await pub.publish("off/q", b"queued", qos=1)
            await pub.disconnect()
            c2 = Client(port=lst.port, clientid="dev", clean_start=False,
                        proto_ver=C.MQTT_V5,
                        properties={"session_expiry_interval": 300})
            ack = await c2.connect()
            assert ack.session_present
            m = await c2.recv()
            assert m.payload == b"queued" and m.qos == 1
            await c2.disconnect()
        run(loop, go())

    def test_clean_start_discards(self, loop, broker):
        node, lst = broker

        async def go():
            c1 = Client(port=lst.port, clientid="dev", clean_start=False,
                        proto_ver=C.MQTT_V5,
                        properties={"session_expiry_interval": 300})
            await c1.connect()
            await c1.subscribe("cs", qos=1)
            await c1.close()
            c2 = Client(port=lst.port, clientid="dev", clean_start=True)
            ack = await c2.connect()
            assert not ack.session_present
            await c2.disconnect()
        run(loop, go())

    def test_will_message_on_abnormal_close(self, loop, broker):
        node, lst = broker

        async def go():
            w = Client(port=lst.port, clientid="watcher")
            await w.connect()
            await w.subscribe("will/t", qos=0)
            c = Client(port=lst.port, clientid="dying",
                       will=P.Will(topic="will/t", payload=b"bye", qos=0))
            await c.connect()
            await c.close()     # abrupt close (no DISCONNECT) → will fires
            m = await w.recv()
            assert m.topic == "will/t" and m.payload == b"bye"
            await w.disconnect()
        run(loop, go())

    def test_will_suppressed_on_clean_disconnect(self, loop, broker):
        node, lst = broker

        async def go():
            w = Client(port=lst.port, clientid="watcher")
            await w.connect()
            await w.subscribe("will/t2", qos=0)
            c = Client(port=lst.port, clientid="polite",
                       will=P.Will(topic="will/t2", payload=b"bye"))
            await c.connect()
            await c.disconnect()    # clean → will dropped
            with pytest.raises(asyncio.TimeoutError):
                await w.recv(timeout=0.3)
            await w.disconnect()
        run(loop, go())

    def test_kick_session(self, loop, broker):
        node, lst = broker

        async def go():
            c = Client(port=lst.port, clientid="victim")
            await c.connect()
            assert await node.cm.kick_session("victim")
            await asyncio.wait_for(c.closed.wait(), 5)
            await c.close()
        run(loop, go())


class TestProtocolEdges:
    def test_ping(self, loop, broker):
        node, lst = broker

        async def go():
            c = Client(port=lst.port, clientid="c")
            await c.connect()
            await c.ping()
            await asyncio.sleep(0.1)
            await c.disconnect()
        run(loop, go())
        assert node.metrics.val("packets.pingresp.sent") == 1

    def test_publish_before_connect_closes(self, loop, broker):
        node, lst = broker

        async def go():
            r, w = await asyncio.open_connection("127.0.0.1", lst.port)
            from emqx_tpu.mqtt.frame import serialize
            w.write(serialize(P.Publish(topic="x", payload=b"y")))
            data = await r.read(100)
            assert data == b""      # closed without CONNACK
            w.close()
        run(loop, go())

    def test_topic_alias_v5(self, loop, broker):
        node, lst = broker

        async def go():
            sub = Client(port=lst.port, clientid="s")
            await sub.connect()
            await sub.subscribe("alias/t", qos=0)
            pub = Client(port=lst.port, clientid="p", proto_ver=C.MQTT_V5)
            await pub.connect()
            await pub.publish("alias/t", b"one",
                              properties={"topic_alias": 3})
            await pub.publish("", b"two", properties={"topic_alias": 3})
            assert (await sub.recv()).payload == b"one"
            m = await sub.recv()
            assert m.topic == "alias/t" and m.payload == b"two"
            await sub.disconnect()
            await pub.disconnect()
        run(loop, go())

    def test_metrics_counters(self, loop, broker):
        node, lst = broker

        async def go():
            c = Client(port=lst.port, clientid="c")
            await c.connect()
            await c.publish("m/t", b"x")
            await c.disconnect()
            # QoS0 routing completes after the publish batch window
            await asyncio.sleep(0.01)
        run(loop, go())
        assert node.metrics.val("packets.connect.received") == 1
        assert node.metrics.val("messages.dropped.no_subscribers") == 1
        assert node.metrics.val("bytes.received") > 0
