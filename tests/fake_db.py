"""In-process fake database servers speaking just enough wire protocol.

The repo's test pattern (like the fake HTTP/exhook servers): boot a real
asyncio server on an ephemeral port and drive the production connector
clients against it — mirrors the reference's meck-per-driver approach but
exercises the actual codec bytes.
"""

from __future__ import annotations

import asyncio
import hashlib
import struct
from typing import Callable, Optional

from emqx_tpu.utils import bson
from emqx_tpu.utils.scram import ScramServer, make_credentials


class _FakeServer:
    def __init__(self, host: str = "127.0.0.1"):
        self.host = host
        self.port = 0
        self._server: Optional[asyncio.AbstractServer] = None
        self._writers: set = set()

    async def start(self) -> "_FakeServer":
        self._server = await asyncio.start_server(
            self._on_client, self.host, 0)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            for w in list(self._writers):
                w.close()
            try:
                # py3.12 wait_closed blocks until every handler returns
                await asyncio.wait_for(self._server.wait_closed(), 2)
            except asyncio.TimeoutError:
                pass

    async def _on_client(self, reader, writer):
        self._writers.add(writer)
        try:
            await self.session(reader, writer)
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError):
            pass
        finally:
            self._writers.discard(writer)
            writer.close()

    async def session(self, reader, writer):  # pragma: no cover
        raise NotImplementedError


class FakeRedis(_FakeServer):
    """RESP2 server: AUTH/SELECT/PING + hash commands over a dict store."""

    def __init__(self, password: Optional[str] = None,
                 role: str = "master",
                 masters: Optional[dict] = None):
        super().__init__()
        self.password = password
        self.role = role              # ROLE reply (master/replica)
        # sentinel mode: master_name -> (host, port) for
        # SENTINEL get-master-addr-by-name
        self.masters = masters
        self.hashes: dict[str, dict[str, str]] = {}
        self.kv: dict[str, str] = {}
        self.commands: list[list[bytes]] = []
        # cluster mode: list of (start, end, host, port) for CLUSTER SLOTS
        self.cluster_slots: Optional[list] = None
        # key -> ("MOVED"|"ASK", slot, host, port): forced redirects
        self.redirects: dict[str, tuple] = {}
        # keys mid-migration on THIS node: served only after ASKING
        self.ask_required: set[str] = set()

    async def _read_cmd(self, reader) -> Optional[list[bytes]]:
        line = (await reader.readuntil(b"\r\n"))[:-2]
        if not line.startswith(b"*"):
            return None
        n = int(line[1:])
        args = []
        for _ in range(n):
            head = (await reader.readuntil(b"\r\n"))[:-2]
            size = int(head[1:])
            data = await reader.readexactly(size + 2)
            args.append(data[:-2])
        return args

    @staticmethod
    def _bulk(v: Optional[str]) -> bytes:
        if v is None:
            return b"$-1\r\n"
        b = v.encode() if isinstance(v, str) else v
        return b"$%d\r\n%s\r\n" % (len(b), b)

    async def session(self, reader, writer):
        authed = self.password is None
        while True:
            args = await self._read_cmd(reader)
            if args is None:
                return
            self.commands.append(args)
            cmd = args[0].upper()
            if cmd == b"AUTH":
                if args[-1].decode() == (self.password or ""):
                    authed = True
                    writer.write(b"+OK\r\n")
                else:
                    writer.write(b"-ERR invalid password\r\n")
            elif not authed:
                writer.write(b"-NOAUTH Authentication required.\r\n")
            elif cmd == b"SELECT":
                writer.write(b"+OK\r\n")
            elif cmd == b"PING":
                writer.write(b"+PONG\r\n")
            elif cmd == b"ROLE":
                writer.write(b"*3\r\n" + self._bulk(self.role)
                             + b":0\r\n*0\r\n")
            elif cmd == b"SENTINEL":
                name = args[2].decode() if len(args) > 2 else ""
                m = (self.masters or {}).get(name)
                if m is None:
                    writer.write(b"*-1\r\n")
                else:
                    writer.write(b"*2\r\n" + self._bulk(str(m[0]))
                                 + self._bulk(str(m[1])))
            elif cmd in (b"HGETALL", b"HMGET") \
                    and self._redirect(args, writer):
                pass
            elif cmd == b"HGETALL":
                h = self.hashes.get(args[1].decode(), {})
                out = [b"*%d\r\n" % (len(h) * 2)]
                for k, v in h.items():
                    out.append(self._bulk(k))
                    out.append(self._bulk(v))
                writer.write(b"".join(out))
            elif cmd == b"HMGET":
                h = self.hashes.get(args[1].decode(), {})
                fields = [a.decode() for a in args[2:]]
                out = [b"*%d\r\n" % len(fields)]
                for f in fields:
                    out.append(self._bulk(h.get(f)))
                writer.write(b"".join(out))
            elif cmd == b"CLUSTER" and len(args) > 1 \
                    and args[1].upper() == b"SLOTS":
                entries = self.cluster_slots or []
                out = [b"*%d\r\n" % len(entries)]
                for start, end, host, port in entries:
                    out.append(b"*3\r\n:%d\r\n:%d\r\n" % (start, end))
                    out.append(b"*3\r\n" + self._bulk(host)
                               + b":%d\r\n" % port + self._bulk("nodeid"))
                writer.write(b"".join(out))
            elif cmd == b"ASKING":
                self._asking = True
                writer.write(b"+OK\r\n")
            elif cmd in (b"GET", b"SET") and self._redirect(args, writer):
                pass
            elif cmd == b"GET":
                writer.write(self._bulk(self.kv.get(args[1].decode())))
            elif cmd == b"SET":
                self.kv[args[1].decode()] = args[2].decode()
                writer.write(b"+OK\r\n")
            else:
                writer.write(b"-ERR unknown command\r\n")
            await writer.drain()

    def _redirect(self, args, writer) -> bool:
        """Write a forced MOVED/ASK redirect for this key (cluster tests);
        a key mid-import here is served only under a one-shot ASKING."""
        key = args[1].decode()
        if key in self.ask_required:
            if getattr(self, "_asking", False):
                self._asking = False
                return False
            writer.write(b"-TRYAGAIN key is being imported (no ASKING)\r\n")
            return True
        r = self.redirects.get(key)
        if r is None:
            return False
        kind, slot, host, port = r
        writer.write(b"-%s %d %s:%d\r\n" % (kind.encode(), slot,
                                            host.encode(), port))
        return True


def _mysql_scramble(password: bytes, nonce: bytes) -> bytes:
    if not password:
        return b""
    h1 = hashlib.sha1(password).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(nonce + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


def _sha2_scramble(password: bytes, nonce: bytes) -> bytes:
    if not password:
        return b""
    h1 = hashlib.sha256(password).digest()
    h2 = hashlib.sha256(hashlib.sha256(h1).digest() + nonce).digest()
    return bytes(a ^ b for a, b in zip(h1, h2))


class FakeMysql(_FakeServer):
    """Protocol-v10 server: native or caching_sha2 handshake (fast path
    when `sha2_cached`, else full auth via RSA public-key exchange),
    COM_QUERY text resultsets, and COM_STMT_PREPARE/EXECUTE binary
    resultsets, routed to `handler(sql) -> (columns, rows) | None`
    (None -> OK packet). Prepared executions are recorded in
    `self.prepared` as (sql, params) so tests can assert parameters never
    entered the SQL text."""

    def __init__(self, username: str = "root", password: str = "",
                 handler: Optional[Callable] = None,
                 plugin: str = "mysql_native_password",
                 sha2_cached: bool = False):
        super().__init__()
        self.username = username
        self.password = password
        self.handler = handler or (lambda sql: ([], []))
        self.plugin = plugin
        self.sha2_cached = sha2_cached
        self.queries: list[str] = []
        self.prepared: list[tuple] = []
        self._rsa_key = None

    @staticmethod
    def _lenenc_str(b: bytes) -> bytes:
        return bytes([len(b)]) + b

    def _rsa(self):
        if self._rsa_key is None:
            from cryptography.hazmat.primitives.asymmetric import rsa
            self._rsa_key = rsa.generate_private_key(
                public_exponent=65537, key_size=2048)
        return self._rsa_key

    async def session(self, reader, writer):
        seq = 0

        def send(payload: bytes) -> None:
            nonlocal seq
            writer.write(len(payload).to_bytes(3, "little")
                         + bytes([seq & 0xFF]) + payload)
            seq += 1

        async def recv() -> bytes:
            nonlocal seq
            head = await reader.readexactly(4)
            seq = head[3] + 1
            return await reader.readexactly(
                int.from_bytes(head[:3], "little"))

        nonce = b"abcdefgh12345678mnop"       # 20 bytes
        greet = (b"\x0a" + b"8.0.0-fake\x00"
                 + struct.pack("<I", 1)                       # thread id
                 + nonce[:8] + b"\x00"
                 + struct.pack("<H", 0xFFFF)                  # caps lo
                 + b"\x21" + struct.pack("<H", 2)             # charset,status
                 + struct.pack("<H", 0x000F)                  # caps hi
                 + bytes([21]) + b"\x00" * 10
                 + nonce[8:] + b"\x00"
                 + self.plugin.encode() + b"\x00")
        send(greet)
        await writer.drain()
        resp = await recv()
        # parse: caps(4) maxpkt(4) charset(1) 23 zeros, user\0, authlen+auth
        pos = 32
        end = resp.index(b"\x00", pos)
        user = resp[pos:end].decode()
        pos = end + 1
        alen = resp[pos]
        auth = resp[pos + 1:pos + 1 + alen]

        def deny():
            send(b"\xff" + struct.pack("<H", 1045) + b"#28000"
                 + b"Access denied")

        pw = self.password.encode()
        if user != self.username:
            deny()
            await writer.drain()
            return
        if self.plugin == "caching_sha2_password":
            if auth != _sha2_scramble(pw, nonce):
                deny()
                await writer.drain()
                return
            if self.sha2_cached:
                send(b"\x01\x03")                 # fast auth success
            else:
                send(b"\x01\x04")                 # full authentication
                await writer.drain()
                req = await recv()
                if req == b"\x02":                # public key request
                    from cryptography.hazmat.primitives import (
                        hashes, serialization)
                    from cryptography.hazmat.primitives.asymmetric import (
                        padding)
                    pem = self._rsa().public_key().public_bytes(
                        serialization.Encoding.PEM,
                        serialization.PublicFormat.SubjectPublicKeyInfo)
                    send(b"\x01" + pem)
                    await writer.drain()
                    enc = await recv()
                    xored = self._rsa().decrypt(enc, padding.OAEP(
                        mgf=padding.MGF1(hashes.SHA1()),
                        algorithm=hashes.SHA1(), label=None))
                    got = bytes(b ^ nonce[i % len(nonce)]
                                for i, b in enumerate(xored))
                    if got != pw + b"\x00":
                        deny()
                        await writer.drain()
                        return
                else:                             # cleartext (TLS channel)
                    if req.rstrip(b"\x00") != pw:
                        deny()
                        await writer.drain()
                        return
        else:
            if auth != _mysql_scramble(pw, nonce):
                deny()
                await writer.drain()
                return
        send(b"\x00\x00\x00\x02\x00\x00\x00")                 # OK
        await writer.drain()

        stmts: dict[int, tuple[str, int]] = {}
        next_stmt = [1]
        while True:
            seq = 0
            pkt = await recv()
            com = pkt[:1]
            if com == b"\x01":                                # COM_QUIT
                return
            if com == b"\x0e":                                # COM_PING
                send(b"\x00\x00\x00\x02\x00\x00\x00")
                await writer.drain()
                continue
            if com == b"\x16":                                # STMT_PREPARE
                sql = pkt[1:].decode()
                sid = next_stmt[0]
                next_stmt[0] += 1
                nparams = sql.count("?")
                stmts[sid] = (sql, nparams)
                send(b"\x00" + struct.pack("<IHHBH", sid, 0, nparams, 0, 0))
                for _ in range(nparams):
                    send(self._coldef(b"?"))
                if nparams:
                    send(b"\xfe\x00\x00\x02\x00")
                await writer.drain()
                continue
            if com == b"\x19":                                # STMT_CLOSE
                stmts.pop(struct.unpack_from("<I", pkt, 1)[0], None)
                continue
            if com == b"\x17":                                # STMT_EXECUTE
                sid = struct.unpack_from("<I", pkt, 1)[0]
                sql, nparams = stmts[sid]
                params = self._parse_exec_params(pkt, nparams)
                self.prepared.append((sql, params))
                result = self.handler(sql, params) \
                    if self.handler.__code__.co_argcount > 1 \
                    else self.handler(sql)
                if result is None:
                    send(b"\x00\x00\x00\x02\x00\x00\x00")
                    await writer.drain()
                    continue
                columns, rows = result
                send(bytes([len(columns)]))
                for name in columns:
                    send(self._coldef(name.encode()))
                send(b"\xfe\x00\x00\x02\x00")
                nbm = (len(columns) + 9) // 8
                for row in rows:
                    bitmap = bytearray(nbm)
                    vals = b""
                    for i, v in enumerate(row):
                        if v is None:
                            bitmap[(i + 2) // 8] |= 1 << ((i + 2) % 8)
                        else:
                            vb = str(v).encode()
                            vals += self._lenenc_str(vb) if len(vb) < 251 \
                                else b"\xfc" + struct.pack("<H", len(vb)) \
                                + vb
                    send(b"\x00" + bytes(bitmap) + vals)
                send(b"\xfe\x00\x00\x02\x00")
                await writer.drain()
                continue
            if com != b"\x03":
                send(b"\xff" + struct.pack("<H", 1047)
                     + b"#08S01" + b"unknown command")
                await writer.drain()
                continue
            sql = pkt[1:].decode()
            self.queries.append(sql)
            result = self.handler(sql)
            if result is None:
                send(b"\x00\x00\x00\x02\x00\x00\x00")
                await writer.drain()
                continue
            columns, rows = result
            send(bytes([len(columns)]))
            for name in columns:
                send(self._coldef(name.encode()))
            send(b"\xfe\x00\x00\x02\x00")                     # EOF
            for row in rows:
                out = b""
                for v in row:
                    if v is None:
                        out += b"\xfb"
                    else:
                        vb = str(v).encode()
                        out += self._lenenc_str(vb) if len(vb) < 251 \
                            else b"\xfc" + struct.pack("<H", len(vb)) + vb
                send(out)
            send(b"\xfe\x00\x00\x02\x00")                     # EOF
            await writer.drain()

    def _coldef(self, name: bytes) -> bytes:
        return (self._lenenc_str(b"def") + self._lenenc_str(b"db")
                + self._lenenc_str(b"t") + self._lenenc_str(b"t")
                + self._lenenc_str(name) + self._lenenc_str(name)
                + b"\x0c" + struct.pack("<H", 0x21)
                + struct.pack("<I", 255) + b"\xfd"
                + struct.pack("<H", 0) + b"\x00" + b"\x00\x00")

    @staticmethod
    def _parse_exec_params(pkt: bytes, nparams: int) -> list:
        """Decode COM_STMT_EXECUTE parameter values (subset of types the
        client sends: NULL/LONGLONG/DOUBLE/VAR_STRING)."""
        pos = 1 + 4 + 1 + 4
        nbm = (nparams + 7) // 8
        bitmap = pkt[pos:pos + nbm]
        pos += nbm
        if nparams == 0 or pkt[pos] != 1:
            return []
        pos += 1
        types = []
        for _ in range(nparams):
            types.append(struct.unpack_from("<H", pkt, pos)[0])
            pos += 2
        out = []
        for i, t in enumerate(types):
            if bitmap[i // 8] & (1 << (i % 8)):
                out.append(None)
                continue
            if t == 0x08:
                out.append(struct.unpack_from("<q", pkt, pos)[0])
                pos += 8
            elif t == 0x05:
                out.append(struct.unpack_from("<d", pkt, pos)[0])
                pos += 8
            else:
                first = pkt[pos]
                if first < 0xFB:
                    n, pos = first, pos + 1
                elif first == 0xFC:
                    n = struct.unpack_from("<H", pkt, pos + 1)[0]
                    pos += 3
                else:
                    n = int.from_bytes(pkt[pos + 1:pos + 4], "little")
                    pos += 4
                out.append(pkt[pos:pos + n].decode())
                pos += n
        return out


class FakePgsql(_FakeServer):
    """Protocol-v3 server: configurable auth (trust/cleartext/md5/scram) +
    simple Query routed to `handler(sql) -> (columns, rows)`."""

    def __init__(self, username: str = "postgres", password: str = "",
                 auth: str = "scram", handler: Optional[Callable] = None):
        super().__init__()
        self.username = username
        self.password = password
        self.auth = auth
        self.handler = handler or (lambda sql: ([], []))
        self.queries: list[str] = []

    async def session(self, reader, writer):
        head = await reader.readexactly(4)
        n = struct.unpack(">i", head)[0]
        body = await reader.readexactly(n - 4)
        proto = struct.unpack(">i", body[:4])[0]
        assert proto == 196608, f"unexpected protocol {proto}"

        def send(mtype: bytes, payload: bytes) -> None:
            writer.write(mtype + struct.pack(">i", len(payload) + 4)
                         + payload)

        async def recv() -> tuple[bytes, bytes]:
            h = await reader.readexactly(5)
            ln = struct.unpack(">i", h[1:])[0]
            return h[:1], await reader.readexactly(ln - 4)

        ok = False
        if self.auth == "trust":
            ok = True
        elif self.auth == "cleartext":
            send(b"R", struct.pack(">i", 3))
            await writer.drain()
            _, b = await recv()
            ok = b.rstrip(b"\x00").decode() == self.password
        elif self.auth == "md5":
            salt = b"SALT"
            send(b"R", struct.pack(">i", 5) + salt)
            await writer.drain()
            _, b = await recv()
            inner = hashlib.md5(self.password.encode()
                                + self.username.encode()).hexdigest()
            want = "md5" + hashlib.md5(inner.encode() + salt).hexdigest()
            ok = b.rstrip(b"\x00").decode() == want
        else:                                                # scram
            send(b"R", struct.pack(">i", 10) + b"SCRAM-SHA-256\x00\x00")
            await writer.drain()
            _, b = await recv()
            end = b.index(b"\x00")
            assert b[:end] == b"SCRAM-SHA-256"
            flen = struct.unpack(">i", b[end + 1:end + 5])[0]
            client_first = b[end + 5:end + 5 + flen].decode()
            cred = make_credentials(self.password, "sha256")
            srv = ScramServer(lambda u: cred, "sha256")
            try:
                sf = srv.challenge(client_first)
                send(b"R", struct.pack(">i", 11) + sf.encode())
                await writer.drain()
                _, b = await recv()
                final = srv.finish(b.decode())
                send(b"R", struct.pack(">i", 12) + final.encode())
                ok = True
            except Exception:  # noqa: BLE001
                ok = False
        if not ok:
            send(b"E", b"SFATAL\x00C28P01\x00"
                 b"Mpassword authentication failed\x00\x00")
            await writer.drain()
            return
        send(b"R", struct.pack(">i", 0))                     # AuthOk
        send(b"S", b"server_version\x0014.0-fake\x00")
        send(b"K", struct.pack(">ii", 1, 2))
        send(b"Z", b"I")
        await writer.drain()

        while True:
            mtype, body = await recv()
            if mtype == b"X":
                return
            if mtype != b"Q":
                continue
            sql = body.rstrip(b"\x00").decode()
            self.queries.append(sql)
            try:
                columns, rows = self.handler(sql)
            except Exception as e:  # noqa: BLE001
                send(b"E", b"SERROR\x00C42601\x00M"
                     + str(e).encode() + b"\x00\x00")
                send(b"Z", b"I")
                await writer.drain()
                continue
            if columns:
                desc = struct.pack(">h", len(columns))
                for c in columns:
                    desc += (c.encode() + b"\x00"
                             + struct.pack(">ihihih", 0, 0, 25, -1, -1, 0))
                send(b"T", desc)
            for row in rows:
                out = struct.pack(">h", len(row))
                for v in row:
                    if v is None:
                        out += struct.pack(">i", -1)
                    else:
                        vb = str(v).encode()
                        out += struct.pack(">i", len(vb)) + vb
                send(b"D", out)
            send(b"C", b"SELECT %d\x00" % len(rows))
            send(b"Z", b"I")
            await writer.drain()


class FakeMongo(_FakeServer):
    """OP_MSG server: ping/find/insert over an in-memory collection map +
    SCRAM saslStart/saslContinue when credentials are configured."""

    def __init__(self, username: Optional[str] = None,
                 password: str = "", algo: str = "sha256"):
        super().__init__()
        self.username = username
        self.password = password
        self.algo = algo
        self.collections: dict[str, list[dict]] = {}
        self.commands: list[dict] = []

    async def session(self, reader, writer):
        authed = self.username is None
        scram: Optional[ScramServer] = None
        while True:
            head = await reader.readexactly(16)
            total, req_id, _, opcode = struct.unpack("<iiii", head)
            data = await reader.readexactly(total - 16)
            assert opcode == 2013
            doc = bson.decode(data[5:])
            self.commands.append(doc)
            reply = self._dispatch(doc, authed)
            if "___scram" in reply:
                phase = reply.pop("___scram")
                try:
                    if phase == "start":
                        cred = make_credentials(self.password, self.algo)
                        cred_for = {self.username: cred}
                        scram = ScramServer(cred_for.get, self.algo)
                        challenge = scram.challenge(
                            bytes(doc["payload"]).decode())
                        reply.update({"ok": 1.0, "conversationId": 1,
                                      "done": False,
                                      "payload": challenge.encode()})
                    else:
                        final = scram.finish(bytes(doc["payload"]).decode())
                        authed = True
                        reply.update({"ok": 1.0, "conversationId": 1,
                                      "done": True,
                                      "payload": final.encode()})
                except Exception:  # noqa: BLE001
                    reply.update({"ok": 0.0, "code": 18,
                                  "errmsg": "Authentication failed."})
            body = bson.encode(reply)
            payload = struct.pack("<i", 0) + b"\x00" + body
            writer.write(struct.pack("<iiii", len(payload) + 16,
                                     1000 + req_id, req_id, 2013) + payload)
            await writer.drain()

    def _dispatch(self, doc: dict, authed: bool) -> dict:
        if "saslStart" in doc:
            return {"___scram": "start"}
        if "saslContinue" in doc:
            return {"___scram": "continue"}
        if not authed:
            return {"ok": 0.0, "code": 13,
                    "errmsg": "command requires authentication"}
        if "ping" in doc:
            return {"ok": 1.0}
        if "find" in doc:
            coll = self.collections.get(doc["find"], [])
            filt = doc.get("filter", {})
            rows = [d for d in coll
                    if all(d.get(k) == v for k, v in filt.items())]
            limit = doc.get("limit", 0)
            if limit:
                rows = rows[:limit]
            return {"ok": 1.0, "cursor": {
                "id": 0, "ns": f"db.{doc['find']}", "firstBatch": rows}}
        if "insert" in doc:
            self.collections.setdefault(doc["insert"], []).extend(
                doc.get("documents", []))
            return {"ok": 1.0, "n": len(doc.get("documents", []))}
        return {"ok": 0.0, "code": 59, "errmsg": "no such command"}


class FakeLdap(_FakeServer):
    """RFC 4511 subset: simple bind against a DN->password map, search
    over a flat entry list with equality/present/AND filters."""

    def __init__(self, binds: Optional[dict] = None,
                 entries: Optional[list] = None):
        super().__init__()
        self.binds = binds if binds is not None else {"": ""}
        self.entries = entries or []    # [{"dn": ..., attr: [vals]}]

    async def session(self, reader, writer):
        from emqx_tpu.connectors.ldap import (ber_int, ber_seq, ber_str,
                                              read_int, read_tlv, tlv)
        bound = False
        while True:
            head = await reader.readexactly(2)
            ln = head[1]
            if ln & 0x80:
                ext = await reader.readexactly(ln & 0x7F)
                ln = int.from_bytes(ext, "big")
            body = await reader.readexactly(ln)
            _t, mid_b, pos = read_tlv(body, 0)
            mid = read_int(mid_b)
            op_tag, op, _ = read_tlv(body, pos)

            def send(tag, rbody):
                writer.write(ber_seq(ber_int(mid), tlv(tag, rbody)))

            if op_tag == 0x60:                               # bind
                _t, _ver, p = read_tlv(op, 0)
                _t, dn, p = read_tlv(op, p)
                _t, pw, _ = read_tlv(op, p)
                dn_s = dn.decode()
                ok = dn_s in self.binds and \
                    self.binds[dn_s] == pw.decode()
                bound = ok
                code = 0 if ok else 49                      # invalidCreds
                send(0x61, ber_int(code, tag=0x0A) + ber_str("")
                     + ber_str("" if ok else "invalid credentials"))
            elif op_tag == 0x42:                             # unbind
                return
            elif op_tag == 0x63:                             # search
                if not bound:
                    send(0x65, ber_int(50, tag=0x0A) + ber_str("")
                         + ber_str("not bound"))
                else:
                    _t, base, p = read_tlv(op, 0)
                    for _ in range(5):                       # skip to filter
                        _t, _x, p = read_tlv(op, p)
                    ftag, fbody, p = read_tlv(op, p)
                    for e in self.entries:
                        if self._match(ftag, fbody, e):
                            attrs = b"".join(
                                ber_seq(ber_str(k), tlv(0x31, b"".join(
                                    ber_str(v) for v in vs)))
                                for k, vs in e.items() if k != "dn")
                            send(0x64, ber_str(e["dn"]) + ber_seq(attrs))
                    send(0x65, ber_int(0, tag=0x0A) + ber_str("")
                         + ber_str(""))
            await writer.drain()

    def _match(self, ftag, fbody, entry) -> bool:
        from emqx_tpu.connectors.ldap import read_tlv
        if ftag == 0x87:                                     # present
            return fbody.decode() in entry
        if ftag == 0xA3:                                     # equality
            _t, attr, p = read_tlv(fbody, 0)
            _t, val, _ = read_tlv(fbody, p)
            return val.decode() in entry.get(attr.decode(), [])
        if ftag == 0xA0:                                     # AND
            pos = 0
            while pos < len(fbody):
                t, b, pos = read_tlv(fbody, pos)
                if not self._match(t, b, entry):
                    return False
            return True
        return False
