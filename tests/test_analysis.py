"""ISSUE 12: the pipeline contract analyzer (tools/analysis/).

Three layers of coverage:

- **Seeded-violation corpus** (positive direction): every pass catches
  its defect class on in-memory sources — including the PR-7-style
  cross-thread counter race and a blocking-call-in-coroutine, the two
  acceptance seeds — and each seed's FIXED twin comes back clean, so a
  pass that rots into flagging everything (or nothing) fails here.
- **Repo-clean gates** (negative direction, tier-1): every pass runs
  over the real emqx_tpu/ tree with zero unannotated findings —
  mirroring the task-/hbm-hygiene gate pattern the two migrated
  checkers established.
- **Knob resolver regressions**: the knob-discipline pass surfaced
  every EMQX_TPU_* env read that bypassed the config-beats-env-beats-
  default resolver convention (device_engine's module globals,
  supervise's watchdog/breaker/fault-spec reads, ops/shapes' fold
  backend, ops/shared's rank block). Each refactored resolver gets a
  targeted test: env honored, explicit value beats env, malformed
  fails loudly.

Plus the annotation grammar, stable finding IDs, the context engine's
classification, CLI exit codes, and the whole-repo time budget guard
(`make analyze` must stay cheap enough for tier-1).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))

from analysis.core import (                               # noqa: E402
    Repo, PASS_NAMES, run_repo)

from emqx_tpu.broker import device_engine as DE           # noqa: E402
from emqx_tpu.broker import supervise as S                # noqa: E402
from emqx_tpu.ops import shapes as SHP                    # noqa: E402
from emqx_tpu.ops import shared as SHR                    # noqa: E402


def _run(files, passes, docs=None, tests=None, extra=None):
    repo = Repo.from_sources(files, docs=docs, tests=tests,
                             extra_code=extra)
    return run_repo(repo, passes=passes)


@pytest.fixture(scope="module")
def repo_real():
    return Repo.from_fs(REPO_ROOT)


# ---------- the annotation grammar ----------

class TestAnnotationGrammar:
    def test_ok_parses_and_suppresses(self):
        src = ("import time\n"
               "async def f():\n"
               "    # analysis: ok(loop-affinity) — bounded microsleep"
               " in a test shim\n"
               "    time.sleep(0)\n")
        findings, suppressed = _run({"pkg/m.py": src},
                                    ["loop-affinity"])
        assert findings == []
        assert len(suppressed) == 1

    def test_suppression_from_comment_block_above(self):
        src = ("import time\n"
               "async def f():\n"
               "    # analysis: ok(loop-affinity) — reason on the\n"
               "    # first line of a multi-line comment block\n"
               "    # whose later lines keep explaining\n"
               "    time.sleep(0)\n")
        findings, suppressed = _run({"pkg/m.py": src},
                                    ["loop-affinity"])
        assert findings == []
        assert len(suppressed) == 1

    def test_wrong_pass_does_not_suppress(self):
        src = ("import time\n"
               "async def f():\n"
               "    # analysis: ok(jit-purity) — wrong pass\n"
               "    time.sleep(0)\n")
        findings, _ = _run({"pkg/m.py": src}, ["loop-affinity"])
        assert [f.pass_name for f in findings] == ["loop-affinity"]

    def test_malformed_annotations_are_findings(self):
        src = ("# analysis: ok(loop-affinity)\n"          # no reason
               "# analysis: ok(nonsuch-pass) — reason\n"  # unknown
               "# analysis: sure why not\n"               # unparseable
               "x = 1\n")
        findings, _ = _run({"pkg/m.py": src}, ["task-hygiene"])
        kinds = [f.pass_name for f in findings]
        assert kinds == ["annotation"] * 3
        assert "no reason" in findings[0].detail
        assert "unknown pass" in findings[1].detail

    def test_finding_id_stable_across_line_drift(self):
        src = ("import time\n"
               "async def f():\n"
               "    time.sleep(0)\n")
        shifted = "# a new comment line\n# another\n" + src
        f1, _ = _run({"pkg/m.py": src}, ["loop-affinity"])
        f2, _ = _run({"pkg/m.py": shifted}, ["loop-affinity"])
        assert f1[0].fid == f2[0].fid
        assert f1[0].line != f2[0].line


# ---------- the context engine ----------

class TestContextEngine:
    CORPUS = {
        "pkg/a.py": (
            "import asyncio, threading\n"
            "async def coro():\n"
            "    helper()\n"
            "def helper():\n"
            "    leaf()\n"
            "def leaf():\n"
            "    pass\n"
            "def worker():\n"
            "    pass\n"
            "def boot(loop, pool):\n"
            "    t = threading.Thread(target=worker)\n"
            "    t.start()\n"
        ),
        "pkg/b.py": (
            "import jax\n"
            "@jax.jit\n"
            "def prog(x):\n"
            "    return stage(x)\n"
            "def stage(x):\n"
            "    return x\n"
        ),
    }

    def test_classification_and_propagation(self):
        repo = Repo.from_sources(self.CORPUS)
        g = repo.contexts
        ctx = {f.qualname: f.contexts for f in g.functions}
        assert "loop" in ctx["coro"]
        assert "loop" in ctx["helper"]          # propagated
        assert "loop" in ctx["leaf"]            # transitively
        assert "thread" in ctx["worker"]        # Thread(target=...)
        assert "loop" not in ctx["worker"]
        assert "jit" in ctx["prog"]
        assert "jit" in ctx["stage"]            # traced callee
        assert "jit" not in ctx["leaf"]

    def test_run_in_executor_is_a_thread_seed_not_loop(self):
        src = ("async def f(loop, pool, obj):\n"
               "    await loop.run_in_executor(pool, crunch)\n"
               "def crunch():\n"
               "    pass\n")
        repo = Repo.from_sources({"pkg/m.py": src})
        ctx = {f.qualname: f.contexts
               for f in repo.contexts.functions}
        assert "thread" in ctx["crunch"]
        assert "loop" not in ctx["crunch"]

    def test_chain_names_the_seed(self):
        repo = Repo.from_sources(self.CORPUS)
        g = repo.contexts
        leaf = next(f for f in g.functions if f.qualname == "leaf")
        chain = g.chain_str(leaf, "loop")
        assert "leaf" in chain and "coro" in chain \
            and "async def" in chain


# ---------- pass: loop-affinity ----------

class TestLoopAffinity:
    def test_seeded_blocking_call_in_coroutine(self):
        """The acceptance seed: a sleep reached THROUGH a sync helper
        from a coroutine — exactly what a reviewer misses."""
        src = ("import time\n"
               "async def handler():\n"
               "    slow()\n"
               "def slow():\n"
               "    time.sleep(0.1)\n")
        findings, _ = _run({"pkg/m.py": src}, ["loop-affinity"])
        assert len(findings) == 1
        assert "time.sleep" in findings[0].detail
        assert "handler" in findings[0].detail   # the chain is named

    def test_thread_only_sleep_is_fine(self):
        src = ("import time, threading\n"
               "def worker():\n"
               "    time.sleep(1.0)\n"
               "def boot():\n"
               "    threading.Thread(target=worker).start()\n")
        findings, _ = _run({"pkg/m.py": src}, ["loop-affinity"])
        assert findings == []

    def test_awaited_calls_are_fine(self):
        src = ("import asyncio\n"
               "async def f():\n"
               "    await asyncio.sleep(1)\n")
        findings, _ = _run({"pkg/m.py": src}, ["loop-affinity"])
        assert findings == []

    def test_bare_acquire_flagged_with_block_not(self):
        src = ("async def f(self):\n"
               "    self._lock.acquire()\n"
               "    with self._lock:\n"
               "        pass\n"
               "    self._lock.acquire(blocking=False)\n")
        findings, _ = _run({"pkg/m.py": src}, ["loop-affinity"])
        assert len(findings) == 1
        assert findings[0].line == 2

    def test_block_until_ready_and_subprocess(self):
        src = ("import subprocess\n"
               "async def f(r):\n"
               "    subprocess.run(['x'])\n"
               "    r.block_until_ready()\n")
        findings, _ = _run({"pkg/m.py": src}, ["loop-affinity"])
        assert len(findings) == 2

    def test_ctypes_native_call_flagged(self):
        src = ("async def f():\n"
               "    decode()\n"
               "def decode():\n"
               "    return _lib.mqtt_frame_scan(0)\n")
        findings, _ = _run({"pkg/m.py": src}, ["loop-affinity"])
        assert len(findings) == 1
        assert "ctypes" in findings[0].detail

    def test_repo_clean(self, repo_real):
        findings, _ = run_repo(repo_real, passes=["loop-affinity"])
        assert findings == [], "\n".join(map(repr, findings))


# ---------- pass: cross-thread-state ----------

# the pre-fix PR 7 flight-recorder pattern, distilled: a ring counter
# bumped from executor-thread writers while the loop reads it
RING_RACE = (
    "class Recorder:\n"
    "    def __init__(self):\n"
    "        self.written = 0\n"
    "        self.buf = [None] * 64\n"
    "    def record(self, span):\n"
    "        self.buf[self.written % 64] = span\n"
    "        self.written += 1\n"
    "    async def snapshot(self):\n"
    "        return self.written\n"
    "async def pipeline(rec, loop, pool):\n"
    "    await loop.run_in_executor(pool, rec.record, 1)\n"
)


class TestCrossThreadState:
    def test_seeded_ring_counter_race(self):
        """The acceptance seed: the PR-7 ring-counter RMW race must be
        caught."""
        findings, _ = _run({"pkg/m.py": RING_RACE},
                           ["cross-thread-state"])
        assert any("self.written" in f.detail and f.line == 7
                   for f in findings), findings

    def test_lock_at_both_sites_is_clean(self):
        src = RING_RACE.replace(
            "    def record(self, span):\n"
            "        self.buf[self.written % 64] = span\n"
            "        self.written += 1\n",
            "    def record(self, span):\n"
            "        with self._lock:\n"
            "            self.buf[self.written % 64] = span\n"
            "            self.written += 1\n")
        findings, _ = _run({"pkg/m.py": src}, ["cross-thread-state"])
        assert findings == [], findings

    def test_annotation_suppresses_with_reason(self):
        src = RING_RACE.replace(
            "        self.written += 1\n",
            "        # analysis: ok(cross-thread-state) — single "
            "writer by construction\n"
            "        self.written += 1\n")
        findings, suppressed = _run({"pkg/m.py": src},
                                    ["cross-thread-state"])
        assert findings == []
        assert len(suppressed) == 1

    def test_loop_only_rmw_is_fine(self):
        src = ("class C:\n"
               "    async def a(self):\n"
               "        self.n += 1\n"
               "    async def b(self):\n"
               "        return self.n\n")
        findings, _ = _run({"pkg/m.py": src}, ["cross-thread-state"])
        assert findings == []

    def test_lock_bypassing_rmw_flagged_even_unclassified(self):
        """Review hardening: the half-locked rule must cover RMW sites
        in methods the context engine could NOT classify — an
        unguarded += bypassing the class's lock is strictly worse than
        the plain store the rule already caught."""
        src = ("import threading\n"
               "class C:\n"
               "    def tick(self):\n"
               "        with self._lock:\n"
               "            self.n += 1\n"
               "    def bump(self):\n"        # unclassified context
               "        self.n += 1\n"
               "    async def boot(self, loop, pool):\n"
               "        await loop.run_in_executor(pool, self.tick)\n")
        findings, _ = _run({"pkg/m.py": src}, ["cross-thread-state"])
        assert any("bypasses the lock" in f.detail and f.line == 7
                   for f in findings), findings

    def test_lock_bypassing_write_flagged(self):
        src = ("import threading\n"
               "class C:\n"
               "    def tick(self):\n"
               "        with self._lock:\n"
               "            self.n += 1\n"
               "    def reset(self):\n"
               "        self.n = 0\n"
               "    async def boot(self, loop, pool):\n"
               "        await loop.run_in_executor(pool, self.tick)\n")
        findings, _ = _run({"pkg/m.py": src}, ["cross-thread-state"])
        assert any("bypasses the lock" in f.detail for f in findings)

    def test_repo_clean(self, repo_real):
        findings, _ = run_repo(repo_real,
                               passes=["cross-thread-state"])
        assert findings == [], "\n".join(map(repr, findings))


# ---------- pass: jit-purity ----------

class TestJitPurity:
    def test_seeded_impurities(self):
        src = ("import functools, time, jax\n"
               "CACHE = {}\n"
               "@jax.jit\n"
               "def prog(x):\n"
               "    return stage(x)\n"
               "def stage(x):\n"
               "    CACHE['k'] = x\n"
               "    t = time.time()\n"
               "    return x.item() + t\n")
        findings, _ = _run({"pkg/m.py": src}, ["jit-purity"])
        details = "\n".join(f.detail for f in findings)
        assert "CACHE" in details
        assert "time.time" in details
        assert ".item()" in details
        assert len(findings) == 3

    def test_global_decl_and_host_callback(self):
        src = ("import jax\n"
               "N = 0\n"
               "@jax.jit\n"
               "def prog(x):\n"
               "    global N\n"
               "    N = 1\n"
               "    return jax.pure_callback(abs, x, x)\n")
        findings, _ = _run({"pkg/m.py": src}, ["jit-purity"])
        details = "\n".join(f.detail for f in findings)
        assert "global" in details
        assert "callback" in details

    def test_pure_program_and_untraced_impurity_clean(self):
        src = ("import time, jax\n"
               "@jax.jit\n"
               "def prog(x):\n"
               "    return x * 2\n"
               "def wrapper(x):\n"
               "    t0 = time.perf_counter()\n"   # not traced: fine
               "    return prog(x), time.perf_counter() - t0\n")
        findings, _ = _run({"pkg/m.py": src}, ["jit-purity"])
        assert findings == []

    def test_partial_jit_decorator_recognized(self):
        src = ("import functools, jax, time\n"
               "@functools.partial(jax.jit, static_argnames=('n',))\n"
               "def prog(x, n):\n"
               "    return x + time.monotonic()\n")
        findings, _ = _run({"pkg/m.py": src}, ["jit-purity"])
        assert len(findings) == 1

    def test_repo_clean(self, repo_real):
        findings, _ = run_repo(repo_real, passes=["jit-purity"])
        assert findings == [], "\n".join(map(repr, findings))


# ---------- pass: knob-discipline ----------

class TestKnobDiscipline:
    DOCS = {"docs/X.md": "`EMQX_TPU_WIDGET` (default 1)\n"}
    TESTS = {"tests/t.py": "conf broker widget EMQX_TPU_WIDGET\n"}

    def test_clean_resolver_with_doc_and_test(self):
        src = ("import os\n"
               "def resolve_widget(configured=None):\n"
               "    if configured is not None:\n"
               "        return int(configured)\n"
               "    return int(os.environ.get('EMQX_TPU_WIDGET', "
               "'1'))\n")
        findings, _ = _run({"pkg/m.py": src}, ["knob-discipline"],
                           docs=self.DOCS, tests=self.TESTS)
        assert findings == [], findings

    def test_env_read_outside_resolver_flagged(self):
        src = ("import os\n"
               "_ROGUE = os.environ.get('EMQX_TPU_ROGUE', '0')\n")
        findings, _ = _run({"pkg/m.py": src}, ["knob-discipline"],
                           docs=self.DOCS, tests=self.TESTS)
        kinds = {f.anchor.split(":")[1] for f in findings}
        # outside a resolver + undocumented + untested: all three legs
        assert kinds == {"resolver", "docs", "tests"}

    def test_config_twin_test_reference_counts(self):
        src = ("import os\n"
               "def resolve_gadget(configured=None):\n"
               "    '''config (broker.gadget_depth) beats "
               "EMQX_TPU_GADGET beats 2.'''\n"
               "    if configured is not None:\n"
               "        return int(configured)\n"
               "    return int(os.environ.get('EMQX_TPU_GADGET', "
               "'2'))\n")
        docs = {"docs/X.md": "EMQX_TPU_GADGET\n"}
        tests = {"tests/t.py": "node({'broker': {'gadget_depth': 1}})"}
        findings, _ = _run({"pkg/m.py": src}, ["knob-discipline"],
                           docs=docs, tests=tests)
        assert findings == [], findings

    def test_dead_doc_knob_flagged(self):
        findings, _ = _run(
            {"pkg/m.py": "x = 1\n"}, ["knob-discipline"],
            docs={"docs/X.md": "set `EMQX_TPU_GHOST=1` to win\n"},
            tests={})
        assert len(findings) == 1
        assert "EMQX_TPU_GHOST" in findings[0].detail
        assert findings[0].path == "docs/X.md"

    def test_subscript_env_read_detected(self):
        src = ("import os\n"
               "def setup():\n"
               "    return os.environ['EMQX_TPU_HARD']\n")
        findings, _ = _run({"pkg/m.py": src}, ["knob-discipline"],
                           docs={}, tests={})
        assert any(f.anchor == "EMQX_TPU_HARD:resolver"
                   for f in findings)

    def test_repo_clean(self, repo_real):
        findings, _ = run_repo(repo_real, passes=["knob-discipline"])
        assert findings == [], "\n".join(map(repr, findings))


# ---------- passes: migrated task-/hbm-hygiene ----------

class TestMigratedHygiene:
    def test_task_hygiene_seeds(self):
        src = ("import asyncio\n"
               "async def f():\n"
               "    asyncio.create_task(g())\n"
               "    t = asyncio.create_task(g())\n"
               "try:\n"
               "    pass\n"
               "except Exception:\n"
               "    pass\n")
        findings, _ = _run({"pkg/m.py": src}, ["task-hygiene"])
        kinds = sorted(f.anchor.split(":")[0] for f in findings)
        assert kinds == ["except-pass", "fire-and-forget"]

    def test_hbm_hygiene_seeds(self):
        src = ("import jax\n"
               "x = jax.device_put(t)\n"
               "y = ledger.hold('c', jax.device_put(t))\n"
               "# hbm: transient — consumed by this dispatch\n"
               "z = jax.device_put(t)\n")
        findings, _ = _run({"pkg/m.py": src}, ["hbm-hygiene"])
        assert len(findings) == 1
        assert findings[0].line == 2

    def test_shims_keep_the_legacy_contract(self):
        """The old script entry points still answer exactly as before
        (tests/test_supervise.py + test_hbm_ledger.py pin the full
        behavior; this is the smoke check that the shims wire through
        the framework)."""
        import check_hbm_hygiene as hbm
        import check_task_hygiene as th
        got = th.check_source(
            "x.py", "import asyncio\nasyncio.create_task(f())\n")
        assert [f.kind for f in got] == ["fire-and-forget"]
        assert hbm.check_source(
            "x.py", "import jax\nx = jax.device_put(t)\n")
        assert th.check_source("x.py", "x = 1\n") == []

    def test_shims_honor_the_annotation_grammar(self):
        """Review hardening: the shim gates and `make analyze` must
        agree — an `# analysis: ok(...)` suppression the framework
        honors must suppress through the legacy entry points too."""
        import check_hbm_hygiene as hbm
        import check_task_hygiene as th
        assert th.check_source(
            "x.py",
            "import asyncio\n"
            "# analysis: ok(task-hygiene) — test-only stub loop\n"
            "asyncio.create_task(f())\n") == []
        assert hbm.check_source(
            "x.py",
            "import jax\n"
            "# analysis: ok(hbm-hygiene) — transient probe buffer\n"
            "x = jax.device_put(t)\n") == []

    def test_repo_clean(self, repo_real):
        findings, _ = run_repo(
            repo_real, passes=["task-hygiene", "hbm-hygiene"])
        assert findings == [], "\n".join(map(repr, findings))


# ---------- the knob-fix resolver regressions ----------

class TestKnobResolvers:
    """Every env read the knob-discipline pass surfaced now routes
    through a resolver: env honored, explicit value beats env,
    malformed fails loudly."""

    def test_dedup(self, monkeypatch):
        monkeypatch.setenv("EMQX_TPU_DEDUP", "0")
        assert DE.resolve_dedup() is False
        assert DE.resolve_dedup(True) is True      # config beats env
        monkeypatch.setenv("EMQX_TPU_DEDUP", "1")
        assert DE.resolve_dedup() is True

    def test_match_cache_size(self, monkeypatch):
        monkeypatch.delenv("EMQX_TPU_MATCH_CACHE", raising=False)
        from emqx_tpu.broker.match_cache import DEFAULT_CAPACITY
        assert DE.resolve_match_cache_size() == DEFAULT_CAPACITY
        monkeypatch.setenv("EMQX_TPU_MATCH_CACHE", "123")
        assert DE.resolve_match_cache_size() == 123
        assert DE.resolve_match_cache_size(7) == 7

    def test_compact_and_delta(self, monkeypatch):
        monkeypatch.setenv("EMQX_TPU_COMPACT_READBACK", "off")
        assert DE.resolve_compact_readback() is False
        assert DE.resolve_compact_readback(True) is True
        monkeypatch.setenv("EMQX_TPU_DELTA_OVERLAY", "0")
        assert DE.resolve_delta_overlay() is False
        monkeypatch.delenv("EMQX_TPU_DELTA_OVERLAY")
        assert DE.resolve_delta_overlay() is True

    def test_faults(self, monkeypatch):
        monkeypatch.setenv("EMQX_TPU_FAULTS",
                           "dispatch:exception:count=2")
        fl = S.resolve_faults()
        assert len(fl) == 1 and fl[0].point == "dispatch" \
            and fl[0].count == 2
        explicit = []
        assert S.resolve_faults(explicit) is explicit  # passthrough
        monkeypatch.setenv("EMQX_TPU_FAULTS", "garbage")
        with pytest.raises(ValueError):
            S.resolve_faults()

    def test_watchdog(self, monkeypatch):
        monkeypatch.setenv("EMQX_TPU_WATCHDOG_FLOOR_S", "2.5")
        monkeypatch.setenv("EMQX_TPU_WATCHDOG_CAP_S", "33")
        monkeypatch.setenv("EMQX_TPU_WATCHDOG_MULT", "5")
        assert S.resolve_watchdog_floor_s() == 2.5
        assert S.resolve_watchdog_cap_s() == 33.0
        assert S.resolve_watchdog_mult() == 5.0
        assert S.resolve_watchdog_floor_s(0.1) == 0.1
        assert S.resolve_watchdog_cap_s(9) == 9.0
        assert S.resolve_watchdog_mult(2) == 2.0

    def test_breaker(self, monkeypatch):
        monkeypatch.setenv("EMQX_TPU_BREAKER_THRESHOLD", "7")
        monkeypatch.setenv("EMQX_TPU_BREAKER_COOLDOWN_S", "0.25")
        assert S.resolve_breaker_threshold() == 7
        assert S.resolve_breaker_cooldown_s() == 0.25
        assert S.resolve_breaker_threshold(1) == 1
        assert S.resolve_breaker_cooldown_s(2.0) == 2.0

    def test_fold_backend(self, monkeypatch):
        monkeypatch.setenv("EMQX_TPU_FOLD", "pallas")
        assert SHP.resolve_fold_backend() == "pallas"
        assert SHP.resolve_fold_backend("xla") == "xla"
        monkeypatch.setenv("EMQX_TPU_FOLD", "cuda")
        with pytest.raises(ValueError):
            SHP.resolve_fold_backend()

    def test_rank_block(self, monkeypatch):
        monkeypatch.delenv("EMQX_TPU_RANK_BLOCK", raising=False)
        assert SHR.resolve_rank_block() == 512
        monkeypatch.setenv("EMQX_TPU_RANK_BLOCK", "64")
        assert SHR.resolve_rank_block() == 64
        assert SHR.resolve_rank_block(16) == 16
        with pytest.raises(ValueError):
            SHR.resolve_rank_block(4)
        monkeypatch.setenv("EMQX_TPU_RANK_BLOCK", "wide")
        with pytest.raises(ValueError):
            SHR.resolve_rank_block()


# ---------- the whole framework: gate + CLI + budget ----------

class TestFramework:
    def test_whole_repo_gate_all_passes(self, repo_real):
        """THE tier-1 gate: all six passes + the annotation check over
        all of emqx_tpu/, zero unannotated findings (no baseline
        file — every exception is an `# analysis: ok` with a reason,
        in the code, next to the site)."""
        findings, suppressed = run_repo(repo_real)
        assert findings == [], "\n".join(map(repr, findings))
        # the annotated exceptions are deliberate and bounded; growth
        # here should be a conscious choice, not drift
        assert len(suppressed) < 40

    def test_time_budget(self, repo_real):
        """tier-1 latency guard: one full framework run (fresh repo
        load + all passes) stays under 30s — the budget `make analyze`
        and this test file share."""
        t0 = time.perf_counter()
        repo = Repo.from_fs(REPO_ROOT)
        run_repo(repo)
        assert time.perf_counter() - t0 < 30.0

    def test_cli_exit_codes_and_json(self):
        env = dict(os.environ,
                   PYTHONPATH=os.path.join(REPO_ROOT, "tools"))
        r = subprocess.run(
            [sys.executable, "-m", "analysis", "--list"],
            capture_output=True, text=True, env=env, cwd=REPO_ROOT)
        assert r.returncode == 0
        assert set(r.stdout.split()) == set(PASS_NAMES)
        r = subprocess.run(
            [sys.executable, "-m", "analysis", "--pass", "nonsuch"],
            capture_output=True, text=True, env=env, cwd=REPO_ROOT)
        assert r.returncode == 2
        r = subprocess.run(
            [sys.executable, "-m", "analysis", "--json"],
            capture_output=True, text=True, env=env, cwd=REPO_ROOT)
        assert r.returncode == 0, r.stdout + r.stderr
        doc = json.loads(r.stdout)
        assert doc["findings"] == []

    def test_cli_path_filter(self):
        env = dict(os.environ,
                   PYTHONPATH=os.path.join(REPO_ROOT, "tools"))
        r = subprocess.run(
            [sys.executable, "-m", "analysis",
             "emqx_tpu/broker/batcher.py"],
            capture_output=True, text=True, env=env, cwd=REPO_ROOT)
        assert r.returncode == 0, r.stdout

    def test_pass_registry_matches_core_list(self):
        from analysis.core import ALL_PASSES
        assert tuple(ALL_PASSES()) == PASS_NAMES
        assert len(PASS_NAMES) >= 6

    def test_unknown_pass_raises(self, repo_real):
        with pytest.raises(KeyError):
            run_repo(repo_real, passes=["nonsuch"])

    def test_syntax_error_module_is_reported(self):
        findings, _ = _run({"pkg/bad.py": "def f(:\n"},
                           ["task-hygiene"])
        assert any("does not parse" in f.detail for f in findings)
