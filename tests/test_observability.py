"""Tests: alarms, $SYS broker, OS monitors, tracer, rate limiters.

Mirrors the reference suites emqx_alarm_SUITE, emqx_sys_SUITE,
emqx_os_mon_SUITE, emqx_tracer_SUITE and the limiter/force_shutdown
coverage in emqx_connection_SUITE.
"""

import asyncio
import time

import pytest

from emqx_tpu.apps.sys import SysBroker
from emqx_tpu.apps.tracer import Tracer
from emqx_tpu.broker.alarm import AlarmManager
from emqx_tpu.broker.connection import Listener
from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.broker.limiter import (ConnectionLimiter, ForceShutdownPolicy,
                                     QuotaLimiter, TokenBucket)
from emqx_tpu.broker.message import make
from emqx_tpu.broker.monitor import OsMon, cpu_load, proc_memory, sys_memory
from emqx_tpu.broker.node import Node
from emqx_tpu.client import Client
from emqx_tpu.mqtt import constants as C


class Sink:
    def __init__(self):
        self.got = []

    def deliver(self, f, m):
        self.got.append((f, m))
        return True


# ---------- alarms ----------

class TestAlarms:
    def test_lifecycle_and_hooks(self):
        h = Hooks()
        seen = []
        h.add("alarm.activated", lambda a: seen.append(("on", a["name"])))
        h.add("alarm.deactivated", lambda a: seen.append(("off", a["name"])))
        am = AlarmManager(h)
        assert am.activate("overload", {"v": 1}, "too hot")
        assert not am.activate("overload")       # already active
        assert am.is_active("overload")
        assert len(am.get_alarms("activated")) == 1
        assert am.deactivate("overload")
        assert not am.deactivate("overload")
        assert len(am.get_alarms("deactivated")) == 1
        assert seen == [("on", "overload"), ("off", "overload")]

    def test_history_cap_and_expiry(self):
        am = AlarmManager(None, size_limit=2, validity_period=0.05)
        for i in range(4):
            am.activate(f"a{i}")
            am.deactivate(f"a{i}")
        assert len(am.get_alarms("deactivated")) == 2
        time.sleep(0.06)
        am.tick()
        assert am.get_alarms("deactivated") == []

    def test_ensure_edge_trigger(self):
        am = AlarmManager(None)
        am.ensure("x", True)
        am.ensure("x", True)
        assert len(am.get_alarms("activated")) == 1
        am.ensure("x", False)
        assert not am.is_active("x")


# ---------- monitors ----------

class TestOsMon:
    def test_readings(self):
        used, total = sys_memory()
        assert total > 0 and 0 < used <= total
        assert proc_memory() > 0
        assert cpu_load() >= 0

    def test_watermark_alarm(self):
        am = AlarmManager(None)
        mon = OsMon(am, {"sysmem_high_watermark": 0.0000001,
                         "procmem_high_watermark": 2.0})
        mon.tick()
        assert am.is_active("high_system_memory_usage")
        assert not am.is_active("high_process_memory_usage")
        mon.sysmem_high = 2.0
        mon.tick()
        assert not am.is_active("high_system_memory_usage")


# ---------- $SYS ----------

class TestSysBroker:
    def test_heartbeat_and_stats_topics(self):
        node = Node({"broker": {"sys_heartbeat_interval": 0,
                                "sys_msg_interval": 0}})
        sys_app = node.register_app(SysBroker(node).load())
        sink = Sink()
        sid = node.broker.register(sink, "w")
        node.broker.subscribe(sid, "$SYS/#")
        sys_app.publish_heartbeat()
        topics = [m.topic for _, m in sink.got]
        assert "$SYS/brokers" in topics
        assert f"$SYS/brokers/{node.name}/version" in topics
        assert f"$SYS/brokers/{node.name}/uptime" in topics
        sink.got.clear()
        sys_app.publish_stats_metrics()
        topics = [m.topic for _, m in sink.got]
        assert any("/stats/connections.count" in t for t in topics)
        assert any("/metrics/messages.publish" in t for t in topics)

    def test_alarm_republish(self):
        node = Node()
        node.register_app(SysBroker(node).load())
        sink = Sink()
        sid = node.broker.register(sink, "w")
        node.broker.subscribe(sid, "$SYS/brokers/+/alarms/#")
        node.alarms.activate("boom", {}, "kapow")
        assert sink.got and sink.got[-1][1].topic.endswith("alarms/activate")
        node.alarms.deactivate("boom")
        assert sink.got[-1][1].topic.endswith("alarms/deactivate")


# ---------- tracer ----------

class TestTracer:
    def test_trace_clientid_and_topic(self, tmp_path):
        node = Node()
        tr = node.register_app(Tracer(node).load())
        f1 = tmp_path / "c1.log"
        f2 = tmp_path / "top.log"
        assert tr.start_trace("clientid", "c1", str(f1))
        assert not tr.start_trace("clientid", "c1", str(f1))
        assert tr.start_trace("topic", "t/#", str(f2))
        assert len(tr.lookup_traces()) == 2
        node.hooks.run("client.connected", ({"clientid": "c1"}, {}))
        node.broker.publish(make("c1", 1, "x/y", b"payload1"))
        node.broker.publish(make("other", 0, "t/1", b"payload2"))
        node.broker.publish(make("other", 0, "nope", b"payload3"))
        text1 = f1.read_text()
        assert "CONNECTED clientid=c1" in text1
        assert "topic=x/y" in text1
        text2 = f2.read_text()
        assert "topic=t/1" in text2 and "payload3" not in text2
        assert tr.stop_trace("topic", "t/#")
        assert not tr.stop_trace("topic", "t/#")
        assert len(tr.lookup_traces()) == 1


# ---------- limiters ----------

class TestTokenBucket:
    def test_burst_then_pace(self):
        tb = TokenBucket(rate=10, burst=5)
        now = time.monotonic()
        assert all(tb.consume(1, now) == 0 for _ in range(5))
        pause = tb.consume(1, now)
        assert pause > 0
        assert tb.consume(1, now + pause + 1e-6) == 0

    def test_quota(self):
        q = QuotaLimiter(rate=2, burst=2)
        assert q.check_publish() and q.check_publish()
        assert not q.check_publish()
        assert QuotaLimiter(None).check_publish()

    def test_conn_limiter(self):
        cl = ConnectionLimiter(msgs_rate=1, bytes_rate=None)
        assert cl.check(1, 100) == 0
        assert cl.check(1, 100) > 0
        assert ConnectionLimiter().check(1000, 10**9) == 0

    def test_force_shutdown(self):
        from emqx_tpu.broker.session import Session, SessionConf
        from emqx_tpu.broker.mqueue import MQueueOpts
        pol = ForceShutdownPolicy(max_mqueue_len=2)
        s = Session("c", SessionConf(max_inflight=1,
                                     mqueue=MQueueOpts(max_len=100)))
        assert pol.violated(s) is None
        s.deliver([(make("p", 1, "t", b"x"), {"qos": 1}) for _ in range(5)])
        assert pol.violated(s) == "mqueue_overflow"
        assert pol.violated(None) is None


class TestLimiterEndToEnd:
    @pytest.fixture()
    def loop(self):
        loop = asyncio.new_event_loop()
        yield loop
        loop.close()

    def test_quota_exceeded_rc(self, loop):
        node = Node({"rate_limit": {"quota_messages_routing": 2}})
        lst = Listener(node, bind="127.0.0.1", port=0)
        loop.run_until_complete(lst.start())

        async def go():
            c = Client(port=lst.port, clientid="q", proto_ver=C.MQTT_V5)
            await c.connect()
            rcs = []
            for i in range(4):
                ack = await c.publish("t", b"x", qos=1)
                rcs.append(ack.reason_code)
            assert C.RC_QUOTA_EXCEEDED in rcs
            assert rcs[0] != C.RC_QUOTA_EXCEEDED
            await c.disconnect()
        try:
            loop.run_until_complete(asyncio.wait_for(go(), 15))
        finally:
            loop.run_until_complete(lst.stop())

    def test_force_shutdown_kills_connection(self, loop):
        node = Node({"force_shutdown": {"max_mqueue_len": 3},
                     "mqtt": {"max_inflight": 1, "max_mqueue_len": 100}})
        lst = Listener(node, bind="127.0.0.1", port=0)
        loop.run_until_complete(lst.start())

        async def go():
            slow = Client(port=lst.port, clientid="slow")
            slow.auto_ack = False        # never acks → inflight stays full
            await slow.connect()
            await slow.subscribe("f/t", qos=1)
            pub = Client(port=lst.port, clientid="pub")
            await pub.connect()
            for i in range(8):
                await pub.publish("f/t", b"x", qos=1)
            # timer tick (1s) must detect the overflow and kill `slow`
            await asyncio.wait_for(slow.closed.wait(), 5)
            assert node.metrics.val("connection.force_shutdown") == 1
            await pub.disconnect()
        try:
            loop.run_until_complete(asyncio.wait_for(go(), 15))
        finally:
            loop.run_until_complete(lst.stop())


class TestCongestion:
    """emqx_congestion.erl analog: write-buffer congestion alarms with
    sustain-duration hysteresis."""

    def test_alarm_lifecycle(self):
        from emqx_tpu.broker.congestion import Congestion

        class FakeTransport:
            def __init__(self):
                self.size = 0

            def get_write_buffer_size(self):
                return self.size

        class FakeWriter:
            def __init__(self):
                self.transport = FakeTransport()

        node = Node(use_device=False)

        class Ch:
            clientid = "c1"
            clientinfo = {"username": "u1"}
            conninfo = {"peername": ("127.0.0.1", 1)}
            conn_state = "connected"

        w = FakeWriter()
        cg = Congestion(node, Ch(), w, enable_alarm=True,
                        min_alarm_sustain_duration=0.05)
        cg.check()
        assert not node.alarms.get_alarms("activated")  # not congested yet
        w.transport.size = 4096
        cg.check()
        acts = node.alarms.get_alarms("activated")
        assert any(a["name"] == "conn_congestion/c1/u1" for a in acts)
        # still congested: stays active
        cg.check()
        assert node.alarms.get_alarms("activated")
        # drained, but within sustain window: still active
        w.transport.size = 0
        cg.check()
        assert node.alarms.get_alarms("activated")
        time.sleep(0.06)
        cg.check()
        assert not node.alarms.get_alarms("activated")

    def test_disabled_noop(self):
        from emqx_tpu.broker.congestion import Congestion
        node = Node(use_device=False)

        class Ch:
            clientid = "c"
            clientinfo = {}
            conninfo = {}
            conn_state = "connected"

        class W:
            transport = None
        cg = Congestion(node, Ch(), W())
        cg.check()
        cg.cancel()
        assert not node.alarms.get_alarms("activated")


class TestLogFormatters:
    """emqx_logger_jsonfmt/textfmt + metadata scoping."""

    @pytest.fixture()
    def loop(self):
        loop = asyncio.new_event_loop()
        yield loop
        loop.close()

    def test_json_formatter_with_metadata(self):
        import json as _json
        import logging as _logging

        from emqx_tpu.utils import logger as L
        records = []

        class Cap(_logging.Handler):
            def emit(self, record):
                records.append(self.format(record))

        h = Cap()
        h.setFormatter(L.JsonFormatter())
        h.addFilter(L.MetadataFilter())
        lg = _logging.getLogger("emqx_tpu.testjson")
        lg.addHandler(h)
        lg.setLevel(_logging.INFO)
        try:
            L.set_metadata_clientid("cli-9")
            L.set_metadata_peername("10.0.0.9:1234")
            lg.info("client subscribed %s", "t/1")
            out = _json.loads(records[0])
            assert out["msg"] == "client subscribed t/1"
            assert out["level"] == "info"
            assert out["clientid"] == "cli-9"
            assert out["peername"] == "10.0.0.9:1234"
            assert isinstance(out["time"], int)
        finally:
            lg.removeHandler(h)
            L.clear_metadata()

    def test_json_formatter_unjsonable_extra(self):
        import json as _json
        import logging as _logging

        from emqx_tpu.utils import logger as L
        f = L.JsonFormatter()
        rec = _logging.makeLogRecord(
            {"msg": "x", "levelname": "INFO", "name": "n",
             "payload": b"\xff\xfe", "obj": object()})
        out = _json.loads(f.format(rec))
        assert "payload" in out and "obj" in out

    def test_text_formatter(self):
        import logging as _logging

        from emqx_tpu.utils import logger as L
        f = L.TextFormatter()
        rec = _logging.makeLogRecord(
            {"msg": "hello", "levelname": "WARNING", "name": "n"})
        rec.emqx_metadata = {"clientid": "c1", "peername": "1.2.3.4:5"}
        line = f.format(rec)
        assert "[warning]" in line and "c1@1.2.3.4:5:" in line \
            and "hello" in line

    def test_metadata_isolated_per_task(self, loop):
        from emqx_tpu.utils import logger as L

        async def task(cid, out):
            L.set_metadata_clientid(cid)
            await asyncio.sleep(0.01)
            out[cid] = dict(L._log_metadata.get())

        async def go():
            out = {}
            await asyncio.gather(task("a", out), task("b", out))
            assert out["a"]["clientid"] == "a"
            assert out["b"]["clientid"] == "b"
        loop.run_until_complete(go())
