"""Tests: alarms, $SYS broker, OS monitors, tracer, rate limiters.

Mirrors the reference suites emqx_alarm_SUITE, emqx_sys_SUITE,
emqx_os_mon_SUITE, emqx_tracer_SUITE and the limiter/force_shutdown
coverage in emqx_connection_SUITE.
"""

import asyncio
import time

import pytest

from emqx_tpu.apps.sys import SysBroker
from emqx_tpu.apps.tracer import Tracer
from emqx_tpu.broker.alarm import AlarmManager
from emqx_tpu.broker.connection import Listener
from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.broker.limiter import (ConnectionLimiter, ForceShutdownPolicy,
                                     QuotaLimiter, TokenBucket)
from emqx_tpu.broker.message import make
from emqx_tpu.broker.monitor import OsMon, cpu_load, proc_memory, sys_memory
from emqx_tpu.broker.node import Node
from emqx_tpu.client import Client
from emqx_tpu.mqtt import constants as C


class Sink:
    def __init__(self):
        self.got = []

    def deliver(self, f, m):
        self.got.append((f, m))
        return True


# ---------- alarms ----------

class TestAlarms:
    def test_lifecycle_and_hooks(self):
        h = Hooks()
        seen = []
        h.add("alarm.activated", lambda a: seen.append(("on", a["name"])))
        h.add("alarm.deactivated", lambda a: seen.append(("off", a["name"])))
        am = AlarmManager(h)
        assert am.activate("overload", {"v": 1}, "too hot")
        assert not am.activate("overload")       # already active
        assert am.is_active("overload")
        assert len(am.get_alarms("activated")) == 1
        assert am.deactivate("overload")
        assert not am.deactivate("overload")
        assert len(am.get_alarms("deactivated")) == 1
        assert seen == [("on", "overload"), ("off", "overload")]

    def test_history_cap_and_expiry(self):
        am = AlarmManager(None, size_limit=2, validity_period=0.05)
        for i in range(4):
            am.activate(f"a{i}")
            am.deactivate(f"a{i}")
        assert len(am.get_alarms("deactivated")) == 2
        time.sleep(0.06)
        am.tick()
        assert am.get_alarms("deactivated") == []

    def test_ensure_edge_trigger(self):
        am = AlarmManager(None)
        am.ensure("x", True)
        am.ensure("x", True)
        assert len(am.get_alarms("activated")) == 1
        am.ensure("x", False)
        assert not am.is_active("x")


# ---------- monitors ----------

class TestOsMon:
    def test_readings(self):
        used, total = sys_memory()
        assert total > 0 and 0 < used <= total
        assert proc_memory() > 0
        assert cpu_load() >= 0

    def test_watermark_alarm(self):
        am = AlarmManager(None)
        mon = OsMon(am, {"sysmem_high_watermark": 0.0000001,
                         "procmem_high_watermark": 2.0})
        mon.tick()
        assert am.is_active("high_system_memory_usage")
        assert not am.is_active("high_process_memory_usage")
        mon.sysmem_high = 2.0
        mon.tick()
        assert not am.is_active("high_system_memory_usage")


# ---------- $SYS ----------

class TestSysBroker:
    def test_heartbeat_and_stats_topics(self):
        node = Node({"broker": {"sys_heartbeat_interval": 0,
                                "sys_msg_interval": 0}})
        sys_app = node.register_app(SysBroker(node).load())
        sink = Sink()
        sid = node.broker.register(sink, "w")
        node.broker.subscribe(sid, "$SYS/#")
        sys_app.publish_heartbeat()
        topics = [m.topic for _, m in sink.got]
        assert "$SYS/brokers" in topics
        assert f"$SYS/brokers/{node.name}/version" in topics
        assert f"$SYS/brokers/{node.name}/uptime" in topics
        sink.got.clear()
        sys_app.publish_stats_metrics()
        topics = [m.topic for _, m in sink.got]
        assert any("/stats/connections.count" in t for t in topics)
        assert any("/metrics/messages.publish" in t for t in topics)

    def test_alarm_republish(self):
        node = Node()
        node.register_app(SysBroker(node).load())
        sink = Sink()
        sid = node.broker.register(sink, "w")
        node.broker.subscribe(sid, "$SYS/brokers/+/alarms/#")
        node.alarms.activate("boom", {}, "kapow")
        assert sink.got and sink.got[-1][1].topic.endswith("alarms/activate")
        node.alarms.deactivate("boom")
        assert sink.got[-1][1].topic.endswith("alarms/deactivate")


# ---------- tracer ----------

class TestTracer:
    def test_trace_clientid_and_topic(self, tmp_path):
        node = Node()
        tr = node.register_app(Tracer(node).load())
        f1 = tmp_path / "c1.log"
        f2 = tmp_path / "top.log"
        assert tr.start_trace("clientid", "c1", str(f1))
        assert not tr.start_trace("clientid", "c1", str(f1))
        assert tr.start_trace("topic", "t/#", str(f2))
        assert len(tr.lookup_traces()) == 2
        node.hooks.run("client.connected", ({"clientid": "c1"}, {}))
        node.broker.publish(make("c1", 1, "x/y", b"payload1"))
        node.broker.publish(make("other", 0, "t/1", b"payload2"))
        node.broker.publish(make("other", 0, "nope", b"payload3"))
        text1 = f1.read_text()
        assert "CONNECTED clientid=c1" in text1
        assert "topic=x/y" in text1
        text2 = f2.read_text()
        assert "topic=t/1" in text2 and "payload3" not in text2
        assert tr.stop_trace("topic", "t/#")
        assert not tr.stop_trace("topic", "t/#")
        assert len(tr.lookup_traces()) == 1


# ---------- limiters ----------

class TestTokenBucket:
    def test_burst_then_pace(self):
        tb = TokenBucket(rate=10, burst=5)
        now = time.monotonic()
        assert all(tb.consume(1, now) == 0 for _ in range(5))
        pause = tb.consume(1, now)
        assert pause > 0
        assert tb.consume(1, now + pause + 1e-6) == 0

    def test_quota(self):
        q = QuotaLimiter(rate=2, burst=2)
        assert q.check_publish() and q.check_publish()
        assert not q.check_publish()
        assert QuotaLimiter(None).check_publish()

    def test_conn_limiter(self):
        cl = ConnectionLimiter(msgs_rate=1, bytes_rate=None)
        assert cl.check(1, 100) == 0
        assert cl.check(1, 100) > 0
        assert ConnectionLimiter().check(1000, 10**9) == 0

    def test_force_shutdown(self):
        from emqx_tpu.broker.session import Session, SessionConf
        from emqx_tpu.broker.mqueue import MQueueOpts
        pol = ForceShutdownPolicy(max_mqueue_len=2)
        s = Session("c", SessionConf(max_inflight=1,
                                     mqueue=MQueueOpts(max_len=100)))
        assert pol.violated(s) is None
        s.deliver([(make("p", 1, "t", b"x"), {"qos": 1}) for _ in range(5)])
        assert pol.violated(s) == "mqueue_overflow"
        assert pol.violated(None) is None


class TestLimiterEndToEnd:
    @pytest.fixture()
    def loop(self):
        loop = asyncio.new_event_loop()
        yield loop
        loop.close()

    def test_quota_exceeded_rc(self, loop):
        node = Node({"rate_limit": {"quota_messages_routing": 2}})
        lst = Listener(node, bind="127.0.0.1", port=0)
        loop.run_until_complete(lst.start())

        async def go():
            c = Client(port=lst.port, clientid="q", proto_ver=C.MQTT_V5)
            await c.connect()
            rcs = []
            for i in range(4):
                ack = await c.publish("t", b"x", qos=1)
                rcs.append(ack.reason_code)
            assert C.RC_QUOTA_EXCEEDED in rcs
            assert rcs[0] != C.RC_QUOTA_EXCEEDED
            await c.disconnect()
        try:
            loop.run_until_complete(asyncio.wait_for(go(), 15))
        finally:
            loop.run_until_complete(lst.stop())

    def test_force_shutdown_kills_connection(self, loop):
        node = Node({"force_shutdown": {"max_mqueue_len": 3},
                     "mqtt": {"max_inflight": 1, "max_mqueue_len": 100}})
        lst = Listener(node, bind="127.0.0.1", port=0)
        loop.run_until_complete(lst.start())

        async def go():
            slow = Client(port=lst.port, clientid="slow")
            slow.auto_ack = False        # never acks → inflight stays full
            await slow.connect()
            await slow.subscribe("f/t", qos=1)
            pub = Client(port=lst.port, clientid="pub")
            await pub.connect()
            for i in range(8):
                await pub.publish("f/t", b"x", qos=1)
            # timer tick (1s) must detect the overflow and kill `slow`
            await asyncio.wait_for(slow.closed.wait(), 5)
            assert node.metrics.val("connection.force_shutdown") == 1
            await pub.disconnect()
        try:
            loop.run_until_complete(asyncio.wait_for(go(), 15))
        finally:
            loop.run_until_complete(lst.stop())


class TestCongestion:
    """emqx_congestion.erl analog: write-buffer congestion alarms with
    sustain-duration hysteresis."""

    def test_alarm_lifecycle(self):
        from emqx_tpu.broker.congestion import Congestion

        class FakeTransport:
            def __init__(self):
                self.size = 0

            def get_write_buffer_size(self):
                return self.size

        class FakeWriter:
            def __init__(self):
                self.transport = FakeTransport()

        node = Node(use_device=False)

        class Ch:
            clientid = "c1"
            clientinfo = {"username": "u1"}
            conninfo = {"peername": ("127.0.0.1", 1)}
            conn_state = "connected"

        w = FakeWriter()
        cg = Congestion(node, Ch(), w, enable_alarm=True,
                        min_alarm_sustain_duration=0.05)
        cg.check()
        assert not node.alarms.get_alarms("activated")  # not congested yet
        w.transport.size = 4096
        cg.check()
        acts = node.alarms.get_alarms("activated")
        assert any(a["name"] == "conn_congestion/c1/u1" for a in acts)
        # still congested: stays active
        cg.check()
        assert node.alarms.get_alarms("activated")
        # drained, but within sustain window: still active
        w.transport.size = 0
        cg.check()
        assert node.alarms.get_alarms("activated")
        time.sleep(0.06)
        cg.check()
        assert not node.alarms.get_alarms("activated")

    def test_disabled_noop(self):
        from emqx_tpu.broker.congestion import Congestion
        node = Node(use_device=False)

        class Ch:
            clientid = "c"
            clientinfo = {}
            conninfo = {}
            conn_state = "connected"

        class W:
            transport = None
        cg = Congestion(node, Ch(), W())
        cg.check()
        cg.cancel()
        assert not node.alarms.get_alarms("activated")


class TestLogFormatters:
    """emqx_logger_jsonfmt/textfmt + metadata scoping."""

    @pytest.fixture()
    def loop(self):
        loop = asyncio.new_event_loop()
        yield loop
        loop.close()

    def test_json_formatter_with_metadata(self):
        import json as _json
        import logging as _logging

        from emqx_tpu.utils import logger as L
        records = []

        class Cap(_logging.Handler):
            def emit(self, record):
                records.append(self.format(record))

        h = Cap()
        h.setFormatter(L.JsonFormatter())
        h.addFilter(L.MetadataFilter())
        lg = _logging.getLogger("emqx_tpu.testjson")
        lg.addHandler(h)
        lg.setLevel(_logging.INFO)
        try:
            L.set_metadata_clientid("cli-9")
            L.set_metadata_peername("10.0.0.9:1234")
            lg.info("client subscribed %s", "t/1")
            out = _json.loads(records[0])
            assert out["msg"] == "client subscribed t/1"
            assert out["level"] == "info"
            assert out["clientid"] == "cli-9"
            assert out["peername"] == "10.0.0.9:1234"
            assert isinstance(out["time"], int)
        finally:
            lg.removeHandler(h)
            L.clear_metadata()

    def test_json_formatter_unjsonable_extra(self):
        import json as _json
        import logging as _logging

        from emqx_tpu.utils import logger as L
        f = L.JsonFormatter()
        rec = _logging.makeLogRecord(
            {"msg": "x", "levelname": "INFO", "name": "n",
             "payload": b"\xff\xfe", "obj": object()})
        out = _json.loads(f.format(rec))
        assert "payload" in out and "obj" in out

    def test_text_formatter(self):
        import logging as _logging

        from emqx_tpu.utils import logger as L
        f = L.TextFormatter()
        rec = _logging.makeLogRecord(
            {"msg": "hello", "levelname": "WARNING", "name": "n"})
        rec.emqx_metadata = {"clientid": "c1", "peername": "1.2.3.4:5"}
        line = f.format(rec)
        assert "[warning]" in line and "c1@1.2.3.4:5:" in line \
            and "hello" in line

    def test_metadata_isolated_per_task(self, loop):
        from emqx_tpu.utils import logger as L

        async def task(cid, out):
            L.set_metadata_clientid(cid)
            await asyncio.sleep(0.01)
            out[cid] = dict(L._log_metadata.get())

        async def go():
            out = {}
            await asyncio.gather(task("a", out), task("b", out))
            assert out["a"]["clientid"] == "a"
            assert out["b"]["clientid"] == "b"
        loop.run_until_complete(go())


# ---------- pipeline telemetry: histograms ----------

class TestHistogram:
    """broker.metrics.Histogram — log2-bucket math edge cases."""

    def _h(self, **kw):
        from emqx_tpu.broker.metrics import Histogram
        return Histogram("t", **kw)

    def test_zero_and_min_land_in_first_bucket(self):
        h = self._h(lo=1e-6, n_buckets=4)
        h.observe(0.0)
        h.observe(1e-6)        # exactly the first bound: inclusive
        h.observe(-1.0)        # clamped, never a negative index
        assert h.counts[0] == 3 and h.count == 3

    def test_exact_bounds_are_inclusive(self):
        h = self._h(lo=1.0, n_buckets=4)       # bounds 1, 2, 4, 8
        for v in (1.0, 2.0, 4.0, 8.0):
            h.observe(v)
        assert h.counts[:4] == [1, 1, 1, 1]
        h2 = self._h(lo=1.0, n_buckets=4)
        h2.observe(2.0001)                     # just past a bound
        assert h2.counts[2] == 1

    def test_max_bound_and_overflow(self):
        h = self._h(lo=1.0, n_buckets=3)       # bounds 1, 2, 4
        h.observe(4.0)                         # last finite bucket
        h.observe(4.1)                         # overflow
        h.observe(1e12)                        # deep overflow
        assert h.counts[2] == 1
        assert h.counts[-1] == 2               # +Inf-only bucket
        cum = h.cumulative()
        assert cum[-1][0] == float("inf") and cum[-1][1] == 3
        assert cum[-2][1] == 1                 # finite cum excludes oflow

    def test_cumulative_monotone_and_count(self):
        h = self._h(lo=1e-6, n_buckets=10)
        import random
        rng = random.Random(5)
        for _ in range(500):
            h.observe(rng.uniform(0, 2e-4))
        cum = h.cumulative()
        vals = [c for _, c in cum]
        assert vals == sorted(vals)
        assert vals[-1] == h.count == 500

    def test_percentile(self):
        h = self._h(lo=1.0, n_buckets=8)
        assert h.percentile(0.99) == 0.0       # empty
        for _ in range(99):
            h.observe(1.5)                     # bucket le=2
        h.observe(100.0)                       # bucket le=128
        assert h.percentile(0.50) == 2.0
        assert h.percentile(0.99) == 2.0
        assert h.percentile(1.0) == 128.0

    def test_snapshot_fields(self):
        h = self._h(lo=1.0, n_buckets=4)
        h.observe(1.0)
        h.observe(3.0)
        s = h.snapshot()
        assert s["count"] == 2 and s["sum"] == 4.0 and s["mean"] == 2.0
        assert s["p50"] >= 1.0 and s["p99"] >= s["p50"]

    def test_metrics_registry(self):
        from emqx_tpu.broker.metrics import Metrics
        m = Metrics()
        h = m.hist("pipeline.stage.x.seconds")
        assert m.hist("pipeline.stage.x.seconds") is h
        h.observe(0.001)
        assert m.histograms()["pipeline.stage.x.seconds"].count == 1


class TestCompileAccounting:
    def test_jit_trace_attributed_to_context(self):
        import jax
        import jax.numpy as jnp

        from emqx_tpu.broker.telemetry import PipelineTelemetry
        tele = PipelineTelemetry()
        with tele.compile_context("W1xB17"):
            f = jax.jit(lambda x: x * 3 + 1)   # fresh fn: jit-cache miss
            f(jnp.zeros(17))
        snap = tele.snapshot()
        assert snap["compiles"]["count"] >= 1
        assert snap["compiles"]["total_s"] > 0
        assert "W1xB17" in snap["compiles"]["by_shape"]
        assert snap["compiles"]["by_shape"]["W1xB17"]["count"] >= 1
        # outside any context: not attributed to this instance
        before = tele.compiles
        g = jax.jit(lambda x: x - 2)
        g(jnp.zeros(13))
        assert tele.compiles == before

    def test_jit_cache_sizes_surface(self):
        from emqx_tpu.models.router_engine import compile_stats
        st = compile_stats()
        # ISSUE 15: mesh exchange programs (one per segment-capacity
        # class) fold into the same namespace under exchange_step_*
        named = {k for k in st if not k.startswith("exchange_step_")}
        assert named <= {"route_step", "route_step_shapes",
                           "route_window_shapes", "route_window_full",
                           "route_step_cached", "route_window_cached",
                           "route_step_compact",
                           "route_step_cached_compact",
                           "route_window_full_compact",
                           "route_window_cached_compact",
                           "route_step_delta", "route_window_delta",
                           "route_step_delta_cached",
                           "route_window_delta_cached",
                           "route_step_delta_compact",
                           "route_window_delta_compact",
                           "route_step_delta_cached_compact",
                           "route_window_delta_cached_compact"}
        assert all(isinstance(v, int) for v in st.values())


# ---------- pipeline telemetry: the publish-path smoke test ----------

def _http_get(loop, port, path):
    import json as _json

    async def go():
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(f"GET {path} HTTP/1.1\r\nhost: x\r\n"
                     "connection: close\r\n\r\n".encode())
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(-1), 10)
        writer.close()
        head, _, body = raw.partition(b"\r\n\r\n")
        return head.split(b"\r\n")[0], body
    status, body = loop.run_until_complete(asyncio.wait_for(go(), 15))
    assert b"200" in status, status
    try:
        return _json.loads(body)
    except ValueError:
        return body


class TestPipelineSpans:
    """The acceptance-criterion smoke test: a pytest-driven publish burst
    through PublishBatcher + DeviceRouteEngine, then the snapshot and
    the REST endpoint report per-stage p50/p95/p99 and occupancy."""

    @pytest.fixture()
    def loop(self):
        loop = asyncio.new_event_loop()
        yield loop
        loop.close()

    def _burst_node(self, loop):
        from emqx_tpu.broker.message import make
        node = Node()          # device path on (CPU jax backend)
        b = node.broker
        sink = Sink()
        sid = b.register(sink, "w")
        for i in range(32):
            b.subscribe(sid, f"pt/{i}/+")
        # sync device route: compiles inline, exercises prepare →
        # dispatch → materialize → finish (occupancy + device stages)
        msgs = [make("p", 0, f"pt/{i % 32}/x", b"d") for i in range(16)]
        assert node.device_engine.route_batch(msgs) is not None

        async def burst():
            for _ in range(4):
                await asyncio.gather(*[
                    node.publish_async(make("p", 0, f"pt/{i % 32}/y", b"h"))
                    for i in range(48)])
            await node.publish_batcher.stop()
        loop.run_until_complete(asyncio.wait_for(burst(), 60))
        return node

    def test_snapshot_and_api_after_burst(self, loop):
        from emqx_tpu.mgmt.api import make_api
        node = self._burst_node(loop)
        snap = node.pipeline_telemetry.snapshot()
        # batched path stages all saw traffic
        for stage in ("enqueue", "batch_form", "total",
                      "dispatch", "materialize", "deliver"):
            assert snap["stages"].get(stage, {}).get("count", 0) > 0, stage
        for row in snap["stages"].values():
            assert row["p50_ms"] <= row["p95_ms"] <= row["p99_ms"]
        # occupancy recorded for the b64 shape class (16/64 fill)
        occ = [k for k in snap["occupancy"] if k.startswith("b")]
        assert occ, snap["occupancy"]
        assert 0 < snap["occupancy"][occ[0]]["mean_fill"] <= 1.0
        assert snap["decisions"]  # device/host decisions accounted
        assert snap["compiles"]["count"] >= 1  # route_batch cold compile

        # the REST surface serves the same schema
        srv = make_api(node, port=0)
        loop.run_until_complete(srv.start())
        try:
            doc = _http_get(loop, srv.port, "/api/v5/pipeline/stats")
        finally:
            loop.run_until_complete(srv.stop())
        assert doc["schema"] == snap["schema"]
        for stage, row in doc["stages"].items():
            assert {"p50_ms", "p95_ms", "p99_ms"} <= set(row), stage
        assert doc["occupancy"]

    def test_slow_batch_hook_and_trace(self, loop, tmp_path):
        from emqx_tpu.broker.message import make
        node = Node({"broker": {"slow_batch_threshold_ms": 1e-9}},
                    use_device=False)
        # host-only node still runs the batched pipeline? no — without a
        # batcher publishes go straight through; drive telemetry direct
        node.pipeline_telemetry.record_total(0.5, batch=8, path="host")
        assert node.metrics.val("pipeline.slow_batches") == 1

        # through the tracer: hook fires into slow_batch trace files
        tr = node.register_app(Tracer(node).load())
        path = tmp_path / "slow.log"
        assert tr.start_trace("slow_batch", "*", str(path))
        node.pipeline_telemetry.record_total(0.5, batch=4, path="device")
        text = path.read_text()
        assert "SLOW_BATCH" in text and "path=device" in text
        # slow_batch traces never capture ordinary publishes
        node.broker.publish(make("c", 0, "x/y", b"p"))
        assert "PUBLISH" not in path.read_text()
        assert tr.stop_trace("slow_batch", "*")


# ---------- exporters: Prometheus exposition validity ----------

def _parse_exposition(text):
    """Strict-enough exposition parser: returns {family: {type, samples}}
    and asserts one TYPE per family + family-contiguous samples."""
    import re
    families = {}
    current = None
    for line in text.rstrip("\n").split("\n"):
        if line.startswith("# HELP"):
            continue
        if line.startswith("# TYPE"):
            _, _, name, kind = line.split(" ", 3)
            assert name not in families, f"duplicate TYPE for {name}"
            families[name] = {"type": kind, "samples": []}
            current = name
            continue
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})? (\S+)$", line)
        assert m, f"unparseable sample line: {line!r}"
        name, labels, value = m.groups()
        fam = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and \
                    name[: -len(suffix)] in families and \
                    families[name[: -len(suffix)]]["type"] == "histogram":
                fam = name[: -len(suffix)]
        assert fam in families, f"sample before TYPE: {line!r}"
        assert fam == current, \
            f"family {fam} not contiguous (current={current}): {line!r}"
        families[fam]["samples"].append((name, labels, value))
    return families


class TestPrometheusExposition:
    def test_valid_exposition_with_traffic(self):
        from emqx_tpu.apps.prometheus import collect
        node = Node(use_device=False)
        node.metrics.inc("messages.publish", 3)
        tele = node.pipeline_telemetry
        for v in (1e-5, 2e-4, 0.003, 0.04):
            tele.observe_stage("dispatch", v)
        tele.record_occupancy("b64", 0.25)
        fams = _parse_exposition(collect(node))

        fam = fams["emqx_pipeline_stage_dispatch_seconds"]
        assert fam["type"] == "histogram"
        les, cums = [], []
        saw_sum = saw_count = False
        for name, labels, value in fam["samples"]:
            if name.endswith("_bucket"):
                le = labels[len('{le="'):-2]
                les.append(float("inf") if le == "+Inf" else float(le))
                cums.append(int(value))
            elif name.endswith("_sum"):
                saw_sum = True
            elif name.endswith("_count"):
                saw_count = True
                assert int(value) == 4
        assert saw_sum and saw_count
        assert les == sorted(les) and les[-1] == float("inf")
        assert cums == sorted(cums) and cums[-1] == 4
        assert fams["emqx_pipeline_occupancy_b64"]["type"] == "histogram"

    def test_rule_families_one_type_and_escaped_labels(self):
        from emqx_tpu.apps.prometheus import collect
        from emqx_tpu.broker.message import make
        from emqx_tpu.rules import RuleEngine
        node = Node(use_device=False)
        eng = RuleEngine(node).load()
        eng.create_rule('SELECT * FROM "m/#"',
                        [{"name": "do_nothing", "params": {}}],
                        rule_id='r"quote\\slash')
        eng.create_rule('SELECT * FROM "m/#"',
                        [{"name": "do_nothing", "params": {}}],
                        rule_id="plain")
        node.broker.publish(make("p", 0, "m/1", b""))
        text = collect(node)
        fams = _parse_exposition(text)   # asserts single TYPE + grouping
        fam = fams["emqx_rule_sql_matched"]
        assert len(fam["samples"]) == 2  # both rules under ONE family
        assert '\\"' in text             # quote escaped in label value
        import re
        for _n, labels, _v in fam["samples"]:
            assert re.fullmatch(r'\{rule="(?:[^"\\\n]|\\.)*"\}', labels), \
                labels


# ---------- exporters: StatsD timers + final flush ----------

class TestStatsdPipeline:
    @pytest.fixture()
    def loop(self):
        loop = asyncio.new_event_loop()
        yield loop
        loop.close()

    def _recv_all(self, sock):
        out = ""
        while True:
            try:
                out += sock.recv(65536).decode()
            except BlockingIOError:
                return out

    def test_histogram_ms_timers(self, loop):
        import socket

        from emqx_tpu.apps.statsd import StatsdApp
        node = Node(use_device=False)

        async def go():
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            sock.bind(("127.0.0.1", 0))
            sock.setblocking(False)
            app = StatsdApp(node, {"host": "127.0.0.1",
                                   "port": sock.getsockname()[1],
                                   "interval": 60})
            app.load()
            h = node.metrics.hist("pipeline.stage.dispatch.seconds")
            h.observe(0.002)
            h.observe(0.004)
            app.flush()
            await asyncio.sleep(0.1)
            data = self._recv_all(sock)
            # interval mean 3ms, sample rate 1/2 observations
            assert "emqx.pipeline.stage.dispatch.seconds:3.000|ms|@0.5" \
                in data
            # second flush with no new observations: no timer line
            app.flush()
            await asyncio.sleep(0.1)
            data = self._recv_all(sock)
            assert "|ms" not in data
            app.unload()
            sock.close()
        loop.run_until_complete(asyncio.wait_for(go(), 15))

    def test_unload_flushes_final_interval(self, loop):
        import socket

        from emqx_tpu.apps.statsd import StatsdApp
        node = Node(use_device=False)

        async def go():
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            sock.bind(("127.0.0.1", 0))
            sock.setblocking(False)
            app = StatsdApp(node, {"host": "127.0.0.1",
                                   "port": sock.getsockname()[1],
                                   "interval": 3600})
            app.load()
            node.metrics.inc("messages.publish", 9)
            app.unload()             # NO explicit flush: unload must send
            await asyncio.sleep(0.1)
            data = self._recv_all(sock)
            assert "emqx.messages.publish:9|c" in data
            assert app._sock is None
            sock.close()
        loop.run_until_complete(asyncio.wait_for(go(), 15))


# ---------- $SYS pipeline topics ----------

class TestSysPipelineTopics:
    def test_pipeline_topics_published(self):
        import json as _json
        node = Node(use_device=False)
        tele = node.pipeline_telemetry
        tele.observe_stage("dispatch", 0.002)
        tele.record_occupancy("b64", 0.5)
        tele.record_decision("device", 3)
        sys_app = node.register_app(SysBroker(node).load())
        sink = Sink()
        sid = node.broker.register(sink, "w")
        node.broker.subscribe(sid, "$SYS/#")
        sys_app.publish_pipeline()
        by_topic = {m.topic: m.payload for _, m in sink.got}
        base = f"$SYS/brokers/{node.name}/pipeline"
        stage = _json.loads(by_topic[f"{base}/stages/dispatch"])
        assert stage["count"] == 1 and "p99_ms" in stage
        occ = _json.loads(by_topic[f"{base}/occupancy/b64"])
        assert occ["mean_fill"] == 0.5
        assert f"{base}/compiles" in by_topic
        dec = _json.loads(by_topic[f"{base}/decisions"])
        assert dec["device"] == 3
        # and the periodic stats/metrics publish carries them too
        sink.got.clear()
        sys_app.publish_stats_metrics()
        assert any(t.startswith(f"{base}/stages/")
                   for t, _ in ((m.topic, m) for _, m in sink.got))
