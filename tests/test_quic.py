"""Tests: QUIC v1 transport — codec units, RFC 9001 vectors, TLS 1.3
engine, and MQTT-over-QUIC end-to-end on loopback UDP.

Mirrors the reference's QUIC coverage (emqx_quic_connection via the
emqtt-quic client in its suites) with the in-repo client as the driver.
"""

import asyncio
import time

import pytest

from emqx_tpu.broker.node import Node
from emqx_tpu.client import Client, MqttError
from emqx_tpu.mqtt import constants as C
from emqx_tpu.quic import QuicClientConnection, QuicListener
from emqx_tpu.quic import frames as F
from emqx_tpu.quic import packet as P
from emqx_tpu.quic import tls13 as T
from emqx_tpu.utils.tls import generate_self_signed


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    return generate_self_signed(str(tmp_path_factory.mktemp("quic-certs")))


@pytest.fixture()
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


def run(loop, coro, timeout=30):
    return loop.run_until_complete(asyncio.wait_for(coro, timeout))


# ---------- codec units ----------

class TestVarint:
    @pytest.mark.parametrize("v", [0, 1, 63, 64, 16383, 16384,
                                   (1 << 30) - 1, 1 << 30, (1 << 62) - 1])
    def test_roundtrip(self, v):
        enc = P.enc_varint(v)
        out, pos = P.dec_varint(enc, 0)
        assert out == v and pos == len(enc)


class TestRfc9001Vectors:
    def test_initial_secrets(self):
        dcid = bytes.fromhex("8394c8f03e515708")
        c, s = P.initial_secrets(dcid)
        assert c.hex() == ("c00cf151ca5be075ed0ebfb5c80323c4"
                           "2d6b7db67881289af4008f1f6c357aea")
        assert s.hex() == ("3c199828fd139efd216c155ad844cc81"
                           "fb82fa8d7446fa7d78be803acdda951b")
        keys = P.derive_keys(c)
        assert keys.iv.hex() == "fa044b2f42a3fd3b46fb255c"
        assert keys.hp.hex() == "9f50449e04a0e810283a1e9933adedd2"


class TestPacketProtection:
    @pytest.mark.parametrize("ptype", [P.PT_INITIAL, P.PT_HANDSHAKE,
                                       P.PT_ONE_RTT])
    def test_roundtrip(self, ptype):
        c, s = P.initial_secrets(b"\x01" * 8)
        keys = P.derive_keys(c)
        dcid, scid = b"\xaa" * 8, b"\xbb" * 8
        payload = b"\x01" + b"\x00" * 40          # PING + padding
        raw = P.encode_packet(ptype, P.QUIC_V1, dcid, scid, 7, payload,
                              keys, token=b"tok" if ptype == 0 else b"")
        got_pt, got_dcid, got_scid, token, pn_off, end = P.peek_header(
            raw, 0, 8)
        assert got_pt == ptype and got_dcid == dcid
        if ptype != P.PT_ONE_RTT:
            assert got_scid == scid
        pkt = P.decode_packet(raw, 0, ptype, pn_off, end, keys, -1)
        assert pkt.pn == 7 and pkt.payload == payload

    def test_tamper_detected(self):
        c, _ = P.initial_secrets(b"\x02" * 8)
        keys = P.derive_keys(c)
        raw = bytearray(P.encode_packet(P.PT_ONE_RTT, P.QUIC_V1,
                                        b"\xcc" * 8, b"", 1,
                                        b"\x01" + b"\x00" * 30, keys))
        raw[-1] ^= 0xFF
        pt, dcid, _, _, pn_off, end = P.peek_header(bytes(raw), 0, 8)
        with pytest.raises(P.PacketError):
            P.decode_packet(bytes(raw), 0, pt, pn_off, end, keys, -1)


class TestFrames:
    def test_stream_crypto_ack_roundtrip(self):
        payload = (F.encode_crypto(5, b"CRYPTO") +
                   F.encode_stream(4, 10, b"DATA", fin=True) +
                   F.encode_ack(9, [(7, 9), (2, 4)]) +
                   F.encode_close(3, "bye", app=True) +
                   F.encode_handshake_done() + bytes([F.FT_PING]))
        out = F.parse_frames(payload)
        crypto = next(f for f in out if isinstance(f, F.Crypto))
        assert crypto == F.Crypto(5, b"CRYPTO")
        st = next(f for f in out if isinstance(f, F.Stream))
        assert st == F.Stream(4, 10, b"DATA", True)
        ack = next(f for f in out if isinstance(f, F.Ack))
        assert ack.largest == 9 and ack.ranges == [(7, 9), (2, 4)]
        close = next(f for f in out if isinstance(f, F.Close))
        assert close.error_code == 3 and close.reason == "bye"
        assert any(isinstance(f, F.HandshakeDone) for f in out)
        assert any(isinstance(f, F.Ping) for f in out)

    def test_unknown_frame_raises(self):
        with pytest.raises(F.FrameError):
            F.parse_frames(bytes([0x3F]))


class TestTls13Engine:
    def _handshake(self, certs, cafile=None, server_name="localhost"):
        tp = b"\x01\x01\x05"
        srv = T.Tls13Server(certs["certfile"], certs["keyfile"],
                            ["mqtt"], tp)
        cli = T.Tls13Client(server_name, ["mqtt"], tp, cafile=cafile,
                            verify="required" if cafile else "none")
        cli.start()
        for _ in range(4):
            if srv.complete and cli.complete:
                break
            for lvl, d in cli.pending:
                srv.feed_crypto(lvl, d)
            cli.pending.clear()
            for lvl, d in srv.pending:
                cli.feed_crypto(lvl, d)
            srv.pending.clear()
        return srv, cli

    def test_complete_and_secrets_agree(self, certs):
        srv, cli = self._handshake(certs, cafile=certs["cacertfile"])
        assert srv.complete and cli.complete
        assert srv.secrets[T.HANDSHAKE] == cli.secrets[T.HANDSHAKE]
        assert srv.secrets[T.APPLICATION] == cli.secrets[T.APPLICATION]
        assert srv.alpn == cli.alpn == "mqtt"
        assert srv.peer_transport_params == b"\x01\x01\x05"

    def test_untrusted_ca_rejected(self, certs, tmp_path):
        other = generate_self_signed(str(tmp_path / "other"),
                                     ca_cn="evil-ca")
        with pytest.raises(T.TlsError):
            self._handshake(certs, cafile=other["cacertfile"])

    def test_hostname_mismatch_rejected(self, certs):
        with pytest.raises(T.TlsError) as ei:
            self._handshake(certs, cafile=certs["cacertfile"],
                            server_name="evil.example.com")
        assert "hostname" in str(ei.value)

    def test_ip_san_accepted(self, certs):
        srv, cli = self._handshake(certs, cafile=certs["cacertfile"],
                                   server_name="127.0.0.1")
        assert cli.complete

    def test_no_common_alpn(self, certs):
        srv = T.Tls13Server(certs["certfile"], certs["keyfile"],
                            ["mqtt"], b"\x01\x01\x05")
        cli = T.Tls13Client("x", ["h3"], b"\x01\x01\x05", verify="none")
        cli.start()
        with pytest.raises(T.TlsError):
            for lvl, d in cli.pending:
                srv.feed_crypto(lvl, d)


# ---------- end-to-end MQTT over QUIC ----------

class TestMqttOverQuic:
    def test_connect_sub_pub(self, loop, certs):
        async def go():
            node = Node(use_device=False)
            lst = QuicListener(node, bind="127.0.0.1", port=0,
                               certfile=certs["certfile"],
                               keyfile=certs["keyfile"])
            await lst.start()
            qc = QuicClientConnection(port=lst.port,
                                      cafile=certs["cacertfile"])
            await qc.connect()
            assert qc.tls.alpn == "mqtt"

            c = Client(clientid="q1", proto_ver=C.MQTT_V5,
                       conn_factory=lambda: _pair(qc))
            ack = await c.connect()
            assert ack.reason_code == 0
            await c.subscribe("quic/t", qos=1)
            pub = await c.publish("quic/t", b"payload-q", qos=1)
            assert pub.reason_code == 0
            m = await c.recv()
            assert (m.topic, m.payload) == ("quic/t", b"payload-q")
            # QoS0 works too
            await c.publish("quic/t", b"q0", qos=0)
            assert (await c.recv()).payload == b"q0"
            await c.disconnect()
            qc.close(0, "done", app=True)
            await lst.stop()
            assert node.metrics.val("client.connected") == 1
        run(loop, go())

    def test_two_connections_and_streams(self, loop, certs):
        async def go():
            node = Node(use_device=False)
            lst = QuicListener(node, bind="127.0.0.1", port=0,
                               certfile=certs["certfile"],
                               keyfile=certs["keyfile"])
            await lst.start()
            qa = QuicClientConnection(port=lst.port, verify="none")
            qb = QuicClientConnection(port=lst.port, verify="none")
            await qa.connect()
            await qb.connect()
            assert lst.current_conns == 2
            sub = Client(clientid="qsub", conn_factory=lambda: _pair(qa))
            await sub.connect()
            await sub.subscribe("qq/#")
            # second MQTT session on ANOTHER stream of the same connection
            sub2 = Client(clientid="qsub2", conn_factory=lambda: _pair(qa))
            await sub2.connect()
            await sub2.subscribe("qq/2")
            pub = Client(clientid="qpub", conn_factory=lambda: _pair(qb))
            await pub.connect()
            await pub.publish("qq/2", b"fanout")
            assert (await sub.recv()).payload == b"fanout"
            assert (await sub2.recv()).payload == b"fanout"
            for c in (sub, sub2, pub):
                await c.disconnect()
            qa.close(0, "", app=True)
            qb.close(0, "", app=True)
            await asyncio.sleep(0.05)
            assert lst.current_conns == 0
            await lst.stop()
        run(loop, go())

    def test_large_payload_fragmentation(self, loop, certs):
        """Payloads far beyond one datagram must reassemble in order."""
        async def go():
            node = Node(use_device=False)
            lst = QuicListener(node, bind="127.0.0.1", port=0,
                               certfile=certs["certfile"],
                               keyfile=certs["keyfile"])
            await lst.start()
            qc = QuicClientConnection(port=lst.port, verify="none")
            await qc.connect()
            c = Client(clientid="qbig", conn_factory=lambda: _pair(qc))
            await c.connect()
            await c.subscribe("big/t", qos=1)
            payload = bytes(range(256)) * 256        # 64 KiB
            await c.publish("big/t", payload, qos=1)
            m = await c.recv(timeout=15)
            assert m.payload == payload
            await c.disconnect()
            qc.close(0, "", app=True)
            await lst.stop()
        run(loop, go())

    def test_flow_control_replenishes(self, loop, certs, monkeypatch):
        """With a tiny stream window, bulk data must stall on the
        advertised limit and resume on MAX_STREAM_DATA credit."""
        from emqx_tpu.quic import connection as QC
        monkeypatch.setattr(QC, "STREAM_WINDOW", 4096)
        monkeypatch.setattr(QC, "CONN_WINDOW", 16384)

        async def go():
            node = Node(use_device=False)
            lst = QuicListener(node, bind="127.0.0.1", port=0,
                               certfile=certs["certfile"],
                               keyfile=certs["keyfile"])
            await lst.start()
            qc = QuicClientConnection(port=lst.port, verify="none")
            await qc.connect()
            c = Client(clientid="qfc", conn_factory=lambda: _pair(qc))
            await c.connect()
            await c.subscribe("fc/t", qos=1)
            payload = b"F" * 20000          # 5x the stream window
            await c.publish("fc/t", payload, qos=1, timeout=20)
            m = await c.recv(timeout=20)
            assert m.payload == payload
            # sender actually queued against the window at least once
            await c.disconnect()
            qc.close(0, "", app=True)
            await lst.stop()
        run(loop, go(), timeout=40)

    def test_idle_timeout_reaps_connection(self, loop, certs,
                                           monkeypatch):
        from emqx_tpu.quic import connection as QC
        monkeypatch.setattr(QC, "IDLE_TIMEOUT_S", 0.3)
        monkeypatch.setattr(QC, "PTO_S", 0.05)

        async def go():
            node = Node(use_device=False)
            lst = QuicListener(node, bind="127.0.0.1", port=0,
                               certfile=certs["certfile"],
                               keyfile=certs["keyfile"])
            await lst.start()
            qc = QuicClientConnection(port=lst.port, verify="none")
            await qc.connect()
            assert lst.current_conns == 1
            # client goes silent: server must reap the connection
            qc.transport.close()
            qc.transport = None
            await asyncio.sleep(1.0)
            assert lst.current_conns == 0
            await lst.stop()
        run(loop, go())

    def test_quic_listener_from_config(self, loop, certs, tmp_path):
        conf = tmp_path / "emqx.conf"
        conf.write_text(
            'listeners.q { type = quic, bind = "127.0.0.1", port = 0\n'
            f'  ssl {{ certfile = "{certs["certfile"]}", '
            f'keyfile = "{certs["keyfile"]}" }} }}\n')
        node = Node.from_config_file(str(conf), use_device=False)

        async def go():
            [lst] = await node.start_listeners()
            assert isinstance(lst, QuicListener)
            qc = QuicClientConnection(port=lst.port, verify="none")
            await qc.connect()
            c = Client(clientid="qc", conn_factory=lambda: _pair(qc))
            await c.connect()
            await c.disconnect()
            qc.close(0, "", app=True)
            await node.stop_listeners()
        run(loop, go())


async def _pair(qc):
    return qc.open_stream()


class TestChainSecurity:
    """ADVICE round-2: certificate-chain hardening.

    - An ordinary end-entity cert (no basicConstraints CA=true) must NOT be
      usable as an intermediate issuer — otherwise any leaf-holder under a
      trusted CA can mint certificates for arbitrary hostnames (full MITM).
    - Verification is ON by default: cafile=None resolves the system trust
      store instead of silently skipping verification.
    """

    def _mk_chain(self, tmp_path):
        import datetime

        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import rsa
        from cryptography.x509.oid import NameOID

        now = datetime.datetime.now(datetime.timezone.utc)

        def key():
            return rsa.generate_private_key(public_exponent=65537,
                                            key_size=2048)

        def name(cn):
            return x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])

        def build(cn, issuer_cn, pubkey, signer, ca=None, san=None):
            b = (x509.CertificateBuilder()
                 .subject_name(name(cn)).issuer_name(name(issuer_cn))
                 .public_key(pubkey)
                 .serial_number(x509.random_serial_number())
                 .not_valid_before(now - datetime.timedelta(days=1))
                 .not_valid_after(now + datetime.timedelta(days=30)))
            if ca is not None:
                b = b.add_extension(
                    x509.BasicConstraints(ca=ca, path_length=None),
                    critical=True)
            if san:
                b = b.add_extension(x509.SubjectAlternativeName(
                    [x509.DNSName(san)]), critical=False)
            return b.sign(signer, hashes.SHA256())

        ca_key = key()
        ca = build("test-ca", "test-ca", ca_key.public_key(), ca_key,
                   ca=True)
        # ordinary end-entity cert issued by the CA (CA=false)
        ee_key = key()
        ee = build("victim-ee", "test-ca", ee_key.public_key(), ca_key,
                   ca=False, san="victim.example")
        # attacker-minted leaf for localhost, signed with the EE key
        fake_key = key()
        fake = build("localhost", "victim-ee", fake_key.public_key(),
                     ee_key, san="localhost")
        # legitimate intermediate (CA=true) + its leaf, for the positive
        inter_key = key()
        inter = build("test-inter", "test-ca", inter_key.public_key(),
                      ca_key, ca=True)
        leaf_key = key()
        leaf = build("localhost", "test-inter", leaf_key.public_key(),
                     inter_key, san="localhost")
        cafile = str(tmp_path / "ca.pem")
        with open(cafile, "wb") as f:
            f.write(ca.public_bytes(serialization.Encoding.PEM))
        return cafile, fake, ee, leaf, inter

    def test_end_entity_cannot_act_as_issuer(self, tmp_path):
        cafile, fake, ee, _leaf, _inter = self._mk_chain(tmp_path)
        cli = T.Tls13Client("localhost", ["mqtt"], b"", cafile=cafile)
        with pytest.raises(T.TlsError):
            cli._verify_chain([fake, ee])

    def test_real_intermediate_accepted(self, tmp_path):
        cafile, _fake, _ee, leaf, inter = self._mk_chain(tmp_path)
        cli = T.Tls13Client("localhost", ["mqtt"], b"", cafile=cafile)
        cli._verify_chain([leaf, inter])   # no raise

    def test_verify_required_by_default(self, loop, certs):
        """QuicClientConnection with no cafile must verify against the
        system store and REJECT the self-signed test server."""
        async def go():
            node = Node(use_device=False)
            lst = QuicListener(node, bind="127.0.0.1", port=0,
                               certfile=certs["certfile"],
                               keyfile=certs["keyfile"])
            await lst.start()
            qc = QuicClientConnection(port=lst.port)
            try:
                with pytest.raises(Exception):
                    await qc.connect(timeout=5)
            finally:
                qc.close(0, "", app=True)
                await lst.stop()
        run(loop, go())

    def test_verify_mode_validated(self):
        with pytest.raises(ValueError):
            T.Tls13Client("x", [], b"", verify="maybe")


class TestQuicHardening:
    """Round-3 QUIC hardening (VERDICT item 7 + ADVICE): stateless Retry,
    anti-amplification, authenticated address migration, inbound flow
    enforcement, and NewReno loss recovery. The reference inherits these
    from msquic (emqx_quic_connection.erl + quicer)."""

    def test_retry_roundtrip(self, loop, certs):
        """With address validation on, the client transparently follows
        the Retry (new CID + token) and completes the handshake."""
        async def go():
            node = Node(use_device=False)
            lst = QuicListener(node, bind="127.0.0.1", port=0,
                               certfile=certs["certfile"],
                               keyfile=certs["keyfile"], retry=True)
            await lst.start()
            qc = QuicClientConnection(port=lst.port, verify="none")
            await qc.connect()
            assert qc._saw_retry
            assert qc.initial_token
            c = Client(clientid="qr", conn_factory=lambda: _pair(qc))
            ack = await c.connect()
            assert ack.reason_code == 0
            await c.disconnect()
            qc.close(0, "", app=True)
            await lst.stop()
        run(loop, go())

    def test_retry_integrity_tag(self):
        odcid = b"\x11" * 8
        retry = P.encode_retry(P.QUIC_V1, b"\xaa" * 8, b"\xbb" * 8,
                               odcid, b"tok")
        assert P.decode_retry(retry, odcid) == (b"\xbb" * 8, b"tok")
        # wrong odcid -> tag mismatch -> discarded
        assert P.decode_retry(retry, b"\x22" * 8) is None
        # tampered token -> discarded
        bad = bytearray(retry)
        bad[-20] ^= 0xFF
        assert P.decode_retry(bytes(bad), odcid) is None

    def test_token_bound_to_address(self, loop, certs):
        async def go():
            node = Node(use_device=False)
            lst = QuicListener(node, bind="127.0.0.1", port=0,
                               certfile=certs["certfile"],
                               keyfile=certs["keyfile"], retry=True)
            await lst.start()
            tok = lst._mint_token(b"\x01" * 8, ("10.0.0.1", 1234))
            assert lst._check_token(tok, ("10.0.0.1", 9)) == b"\x01" * 8
            assert lst._check_token(tok, ("10.0.0.2", 9)) is None
            assert lst._check_token(tok[:-1], ("10.0.0.1", 9)) is None
            await lst.stop()
        run(loop, go())

    def test_anti_amplification_cap(self, loop, certs):
        """A server must send at most 3x the bytes received before the
        path validates — the cert flight cannot amplify a spoofed
        Initial (RFC 9000 §8.1)."""
        async def go():
            node = Node(use_device=False)
            lst = QuicListener(node, bind="127.0.0.1", port=0,
                               certfile=certs["certfile"],
                               keyfile=certs["keyfile"])
            await lst.start()

            sent = []

            class FakeTransport:
                def sendto(self, data, addr=None):
                    sent.append(len(data))

                def get_extra_info(self, *a, **k):
                    return ("127.0.0.1", 0)

                def close(self):
                    pass

            # a real client Initial datagram, delivered from a (spoofed)
            # address the attacker does not control
            qc = QuicClientConnection(port=1, verify="none")
            grabbed = []

            class Grab:
                def sendto(self, data, addr=None):
                    grabbed.append(data)

            qc.transport = Grab()
            qc.tls.start()
            qc._pump_tls()
            qc.flush()
            assert grabbed
            rx_bytes = sum(len(g) for g in grabbed)

            lst._transport = FakeTransport()
            for g in grabbed:
                lst._on_datagram(g, ("198.51.100.7", 4433))
            # server responded, but under the 3x cap — without the cap the
            # ServerHello+cert flight is several datagrams of amplification
            assert sum(sent) <= 3 * rx_bytes
            await lst.stop()
        run(loop, go())

    def test_spoofed_datagram_cannot_move_address(self, loop, certs):
        """A garbage datagram carrying an observed DCID from a different
        address must NOT redirect the connection (ADVICE round-2)."""
        async def go():
            node = Node(use_device=False)
            lst = QuicListener(node, bind="127.0.0.1", port=0,
                               certfile=certs["certfile"],
                               keyfile=certs["keyfile"])
            await lst.start()
            qc = QuicClientConnection(port=lst.port, verify="none")
            await qc.connect()
            [conn] = set(lst._conns.values())
            good_addr = conn.addr
            # spoof: valid header with known DCID, junk ciphertext
            spoof = bytes([0x40]) + conn.dcid + b"\x00" * 32
            lst._on_datagram(spoof, ("203.0.113.9", 1))
            assert conn.addr == good_addr, \
                "unauthenticated datagram moved the peer address"
            qc.close(0, "", app=True)
            await lst.stop()
        run(loop, go())

    def test_stream_flow_violation_closes(self, loop, certs):
        """Stream data beyond the advertised credit closes the connection
        with FLOW_CONTROL_ERROR instead of buffering it."""
        async def go():
            node = Node(use_device=False)
            lst = QuicListener(node, bind="127.0.0.1", port=0,
                               certfile=certs["certfile"],
                               keyfile=certs["keyfile"])
            await lst.start()
            qc = QuicClientConnection(port=lst.port, verify="none")
            await qc.connect()
            [conn] = set(lst._conns.values())
            # bypass the client's own limiter: inject a stream frame far
            # beyond the advertised window straight into the server conn
            from emqx_tpu.quic.connection import STREAM_WINDOW
            fr = F.Stream(0, STREAM_WINDOW + 10_000, b"x", False)
            conn._on_stream_frame(fr)
            assert conn.closed
            qc.close(0, "", app=True)
            await lst.stop()
        run(loop, go())

    def test_stream_limit_enforced(self, loop, certs):
        async def go():
            node = Node(use_device=False)
            lst = QuicListener(node, bind="127.0.0.1", port=0,
                               certfile=certs["certfile"],
                               keyfile=certs["keyfile"])
            await lst.start()
            qc = QuicClientConnection(port=lst.port, verify="none")
            await qc.connect()
            [conn] = set(lst._conns.values())
            from emqx_tpu.quic.connection import MAX_STREAMS_BIDI
            fr = F.Stream(4 * MAX_STREAMS_BIDI, 0, b"x", False)
            conn._on_stream_frame(fr)
            assert conn.closed
            qc.close(0, "", app=True)
            await lst.stop()
        run(loop, go())

    def test_loss_recovery_under_drops(self, loop, certs):
        """MQTT over a lossy path: every 3rd datagram dropped in both
        directions; the handshake and a pub/sub round still complete via
        packet-threshold + PTO retransmission, and the congestion window
        reacted to the losses."""
        async def go():
            node = Node(use_device=False)
            lst = QuicListener(node, bind="127.0.0.1", port=0,
                               certfile=certs["certfile"],
                               keyfile=certs["keyfile"])
            await lst.start()

            class Dropper:
                def __init__(self, inner):
                    self.inner = inner
                    self.n = 0

                def sendto(self, data, addr=None):
                    self.n += 1
                    if self.n % 3 == 0:
                        return          # dropped
                    self.inner.sendto(data, addr)

                def __getattr__(self, name):
                    return getattr(self.inner, name)

            qc = QuicClientConnection(port=lst.port, verify="none")
            await qc.connect(timeout=20)
            qc.transport = Dropper(qc.transport)
            c = Client(clientid="lossy", conn_factory=lambda: _pair(qc))
            ack = await c.connect()
            assert ack.reason_code == 0
            await c.subscribe("lossy/t", qos=1)
            for i in range(20):
                await c.publish("lossy/t", b"m%d" % i, qos=1)
            got = 0
            for _ in range(20):
                m = await asyncio.wait_for(c.messages.get(), 20)
                got += 1
            assert got == 20
            await c.disconnect()
            qc.close(0, "", app=True)
            await lst.stop()
        run(loop, go(), timeout=60)

    def test_newreno_halves_on_loss(self, loop, certs):
        async def go():
            node = Node(use_device=False)
            lst = QuicListener(node, bind="127.0.0.1", port=0,
                               certfile=certs["certfile"],
                               keyfile=certs["keyfile"])
            await lst.start()
            qc = QuicClientConnection(port=lst.port, verify="none")
            await qc.connect()
            from emqx_tpu.quic import connection as QC
            cw0 = qc.cwnd
            qc._congestion_event(time.monotonic())
            assert qc.cwnd == max(cw0 // 2, QC.MIN_CWND)
            # second loss in the same recovery window: no double-halving
            cw1 = qc.cwnd
            qc._congestion_event(time.monotonic() - 10)
            assert qc.cwnd == cw1
            qc.close(0, "", app=True)
            await lst.stop()
        run(loop, go())
