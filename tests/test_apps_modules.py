"""Tests for feature apps: retainer, delayed publish, rewrite, topic
metrics, event messages.

Mirrors the reference suites emqx_retainer_SUITE, emqx_delayed_SUITE,
emqx_rewrite_SUITE, emqx_topic_metrics_SUITE, emqx_event_message_SUITE.
"""

import asyncio
import json

import pytest

from emqx_tpu.apps.delayed import DelayedPublish
from emqx_tpu.apps.event_message import EventMessage
from emqx_tpu.apps.retainer import Retainer, TopicIndex
from emqx_tpu.apps.rewrite import TopicRewrite
from emqx_tpu.apps.topic_metrics import TopicMetrics
from emqx_tpu.broker.connection import Listener
from emqx_tpu.broker.message import make, now_ms
from emqx_tpu.broker.node import Node
from emqx_tpu.client import Client
from emqx_tpu.mqtt import constants as C


class Sink:
    def __init__(self):
        self.got = []

    def deliver(self, topic_filter, msg):
        self.got.append((topic_filter, msg))
        return True


# ---------- TopicIndex ----------

class TestTopicIndex:
    def test_insert_match_delete(self):
        ix = TopicIndex()
        for t in ("a/b/c", "a/b/d", "a/x", "b", "$SYS/uptime"):
            assert ix.insert(t)
        assert not ix.insert("a/b/c")       # duplicate
        assert sorted(ix.match("a/b/+")) == ["a/b/c", "a/b/d"]
        assert sorted(ix.match("a/#")) == ["a/b/c", "a/b/d", "a/x"]
        assert sorted(ix.match("#")) == ["a/b/c", "a/b/d", "a/x", "b"]
        assert ix.match("$SYS/#") is not None
        assert list(ix.match("$SYS/uptime")) == ["$SYS/uptime"]
        assert ix.delete("a/b/c")
        assert not ix.delete("a/b/c")
        assert sorted(ix.match("a/b/+")) == ["a/b/d"]
        assert len(ix) == 4

    def test_dollar_excluded_from_root_wildcards(self):
        ix = TopicIndex()
        ix.insert("$SYS/x")
        ix.insert("n/x")
        assert list(ix.match("#")) == ["n/x"]
        assert list(ix.match("+/x")) == ["n/x"]

    def test_hash_matches_parent(self):
        ix = TopicIndex()
        ix.insert("sport")
        ix.insert("sport/tennis")
        assert sorted(ix.match("sport/#")) == ["sport", "sport/tennis"]


# ---------- Retainer ----------

class TestRetainer:
    def setup_method(self):
        self.node = Node()
        self.ret = self.node.register_app(Retainer(self.node).load())

    def test_store_and_clear_via_publish(self):
        self.node.broker.publish(make("p", 0, "a/b", b"v1",
                                      flags={"retain": True}))
        assert self.ret.retained_count() == 1
        assert self.ret.lookup("a/b").payload == b"v1"
        # overwrite
        self.node.broker.publish(make("p", 0, "a/b", b"v2",
                                      flags={"retain": True}))
        assert self.ret.lookup("a/b").payload == b"v2"
        assert self.ret.retained_count() == 1
        # empty payload clears
        self.node.broker.publish(make("p", 0, "a/b", b"",
                                      flags={"retain": True}))
        assert self.ret.retained_count() == 0

    def test_non_retained_not_stored(self):
        self.node.broker.publish(make("p", 0, "a/b", b"v"))
        assert self.ret.retained_count() == 0

    def test_sys_not_stored(self):
        self.node.broker.publish(make("p", 0, "$SYS/x", b"v",
                                      flags={"retain": True}))
        assert self.ret.retained_count() == 0

    def test_wildcard_match(self):
        for t in ("a/1", "a/2", "b/1"):
            self.node.broker.publish(make("p", 0, t, b"x",
                                          flags={"retain": True}))
        assert len(self.ret.match("a/+")) == 2
        assert len(self.ret.match("#")) == 3

    def test_max_retained(self):
        node = Node({"retainer": {"max_retained_messages": 2}})
        ret = Retainer(node).load()
        for t in ("a", "b", "c"):
            node.broker.publish(make("p", 0, t, b"x", flags={"retain": True}))
        assert ret.retained_count() == 2
        # replacing an existing topic is allowed when full
        node.broker.publish(make("p", 0, "a", b"y", flags={"retain": True}))
        assert ret.lookup("a").payload == b"y"

    def test_max_payload(self):
        node = Node({"retainer": {"max_payload_size": 3}})
        ret = Retainer(node).load()
        node.broker.publish(make("p", 0, "a", b"xxxx", flags={"retain": True}))
        assert ret.retained_count() == 0

    def test_expiry(self):
        m = make("p", 0, "a", b"x", flags={"retain": True},
                 headers={"properties": {"message_expiry_interval": 100}})
        m.ts = now_ms() - 200_000           # already expired
        self.ret._insert(m)
        assert self.ret.lookup("a") is None
        assert self.ret.retained_count() == 0

    def test_clean(self):
        for t in ("a/1", "a/2", "b/1"):
            self.node.broker.publish(make("p", 0, t, b"x",
                                          flags={"retain": True}))
        assert self.ret.clean("a/#") == 2
        assert self.ret.retained_count() == 1
        assert self.ret.clean() == 1
        assert self.ret.retained_count() == 0


class TestRetainerEndToEnd:
    @pytest.fixture()
    def loop(self):
        loop = asyncio.new_event_loop()
        yield loop
        loop.close()

    @pytest.fixture()
    def broker(self, loop):
        node = Node()
        node.register_app(Retainer(node).load())
        lst = Listener(node, bind="127.0.0.1", port=0)
        loop.run_until_complete(lst.start())
        yield node, lst
        loop.run_until_complete(lst.stop())

    def test_retained_delivered_on_subscribe(self, loop, broker):
        node, lst = broker

        async def go():
            pub = Client(port=lst.port, clientid="pub")
            await pub.connect()
            await pub.publish("r/t", b"hello", qos=1, retain=True)
            sub = Client(port=lst.port, clientid="sub", proto_ver=C.MQTT_V5)
            await sub.connect()
            await sub.subscribe("r/+", qos=1)
            m = await sub.recv()
            assert m.topic == "r/t" and m.payload == b"hello"
            assert m.retain          # retained delivery keeps the flag
            await pub.disconnect()
            await sub.disconnect()
        loop.run_until_complete(asyncio.wait_for(go(), 15))

    def test_rh_never(self, loop, broker):
        node, lst = broker

        async def go():
            pub = Client(port=lst.port, clientid="pub")
            await pub.connect()
            await pub.publish("r/t", b"hello", retain=True)
            sub = Client(port=lst.port, clientid="sub", proto_ver=C.MQTT_V5)
            await sub.connect()
            await sub.subscribe("r/t", qos=0, opts={"rh": 2})
            with pytest.raises(asyncio.TimeoutError):
                await sub.recv(timeout=0.3)
            await pub.disconnect()
            await sub.disconnect()
        loop.run_until_complete(asyncio.wait_for(go(), 15))


# ---------- Delayed ----------

class TestDelayed:
    def setup_method(self):
        self.node = Node()
        self.d = self.node.register_app(DelayedPublish(self.node).load())

    def test_intercept_and_fire(self):
        sink = Sink()
        sid = self.node.broker.register(sink, "s")
        self.node.broker.subscribe(sid, "a/b")
        n = self.node.broker.publish(make("p", 0, "$delayed/5/a/b", b"x"))
        assert n == 0 and not sink.got
        assert self.d.count() == 1
        assert self.d.tick(now_ms()) == 0          # not due yet
        assert self.d.tick(now_ms() + 6000) == 1   # due
        assert sink.got and sink.got[0][1].topic == "a/b"

    def test_malformed_dropped(self):
        for bad in ("$delayed/x/a", "$delayed/5", "$delayed//a",
                    "$delayed/99999999999/a"):
            assert self.node.broker.publish(make("p", 0, bad, b"x")) == 0
        assert self.d.count() == 0
        assert self.node.metrics.val("messages.delayed.dropped") == 4

    def test_max_delayed(self):
        node = Node({"delayed": {"max_delayed_messages": 1}})
        d = DelayedPublish(node).load()
        node.broker.publish(make("p", 0, "$delayed/5/a", b"x"))
        node.broker.publish(make("p", 0, "$delayed/5/b", b"x"))
        assert d.count() == 1

    def test_list_delete(self):
        self.node.broker.publish(make("p", 0, "$delayed/5/a", b"x"))
        self.node.broker.publish(make("p", 0, "$delayed/9/b", b"x"))
        items = self.d.list()
        assert [i["topic"] for i in items] == ["a", "b"]
        assert self.d.delete(items[0]["seq"])
        assert self.d.count() == 1
        assert self.d.tick(now_ms() + 10_000) == 1   # only 'b' fires

    def test_ordering(self):
        sink = Sink()
        sid = self.node.broker.register(sink, "s")
        self.node.broker.subscribe(sid, "#")
        self.node.broker.publish(make("p", 0, "$delayed/9/late", b""))
        self.node.broker.publish(make("p", 0, "$delayed/1/early", b""))
        self.d.tick(now_ms() + 10_000)
        assert [m.topic for _, m in sink.got] == ["early", "late"]


# ---------- Rewrite ----------

class TestRewrite:
    def test_publish_rewrite(self):
        node = Node({"rewrite": [
            {"action": "publish", "source": "x/#",
             "re": r"^x/y/(\d+)$", "dest": "z/y/$1"}]})
        TopicRewrite(node).load()
        sink = Sink()
        sid = node.broker.register(sink, "s")
        node.broker.subscribe(sid, "z/y/1")
        node.broker.publish(make("p", 0, "x/y/1", b""))
        assert sink.got and sink.got[0][1].topic == "z/y/1"
        # non-matching regex passes through
        node.broker.subscribe(sid, "x/y/abc")
        node.broker.publish(make("p", 0, "x/y/abc", b""))
        assert sink.got[-1][1].topic == "x/y/abc"

    def test_chained_rules(self):
        node = Node({"rewrite": [
            {"action": "all", "source": "a", "re": "^a$", "dest": "b"},
            {"action": "all", "source": "b", "re": "^b$", "dest": "c"}]})
        rw = TopicRewrite(node).load()
        assert rw._rewrite("a", "publish") == "c"

    def test_subscribe_rewrite_preserves_share(self):
        node = Node({"rewrite": [
            {"action": "subscribe", "source": "old/#",
             "re": r"^old/(.+)$", "dest": "new/$1"}]})
        rw = TopicRewrite(node).load()
        out = node.hooks.run_fold(
            "client.subscribe", ({}, {}),
            [("$share/g/old/t", {"qos": 1}), ("old/t", {"qos": 0})])
        assert out[0][0] == "$share/g/new/t"
        assert out[1][0] == "new/t"

    def test_action_scoping(self):
        node = Node({"rewrite": [
            {"action": "subscribe", "source": "a", "re": "^a$",
             "dest": "b"}]})
        rw = TopicRewrite(node).load()
        assert rw._rewrite("a", "publish") == "a"
        assert rw._rewrite("a", "subscribe") == "b"


# ---------- Topic metrics ----------

class TestTopicMetrics:
    def test_counts(self):
        node = Node()
        tm = TopicMetrics(node).load()
        tm.register("t/#")
        sink = Sink()
        sid = node.broker.register(sink, "s")
        node.broker.subscribe(sid, "t/1")
        node.broker.publish(make("p", 1, "t/1", b""))
        node.broker.publish(make("p", 0, "other", b""))
        assert tm.val("t/#", "messages.in") == 1
        assert tm.val("t/#", "messages.qos1.in") == 1
        assert tm.val("t/#", "messages.out") == 1
        # dropped: no subscriber for t/2
        node.broker.publish(make("p", 0, "t/2", b""))
        assert tm.val("t/#", "messages.dropped") == 1

    def test_register_dedup_and_rates(self):
        node = Node()
        tm = TopicMetrics(node).load()
        assert tm.register("a")
        assert not tm.register("a")
        node.broker.publish(make("p", 0, "a", b""))
        tm.tick()
        assert tm.rate("a", "messages.in") > 0
        assert tm.deregister("a")
        assert not tm.deregister("a")


# ---------- Event message ----------

class TestEventMessage:
    def test_events_published(self):
        node = Node({"event_message": {e: True for e in (
            "client_connected", "session_subscribed", "message_dropped")}})
        EventMessage(node).load()
        sink = Sink()
        sid = node.broker.register(sink, "watcher")
        node.broker.subscribe(sid, "$event/#")
        node.hooks.run("client.connected",
                       ({"clientid": "c1", "username": "u"}, {}))
        assert sink.got[-1][1].topic == "$event/client_connected"
        body = json.loads(sink.got[-1][1].payload)
        assert body["clientid"] == "c1"
        node.hooks.run("session.subscribed",
                       ({"clientid": "c1"}, "t/1", {"qos": 1, "is_new": True}))
        body = json.loads(sink.got[-1][1].payload)
        assert body["topic"] == "t/1" and "is_new" not in body["subopts"]
        # message.dropped on a normal topic → event; event topics skipped
        node.broker.publish(make("p", 0, "nobody/home", b""))
        assert sink.got[-1][1].topic == "$event/message_dropped"

    def test_disabled_by_default(self):
        node = Node()
        EventMessage(node).load()
        sink = Sink()
        sid = node.broker.register(sink, "watcher")
        node.broker.subscribe(sid, "$event/#")
        node.hooks.run("client.connected", ({"clientid": "c1"}, {}))
        assert not sink.got


class TestRetainerStorageBackends:
    """Pluggable retained-message storage (round-2 VERDICT missing #4):
    the behaviour swap and the disc backend's restart durability.
    Parity: emqx_retainer_mnesia.erl ram/disc/disc_only copies."""

    def _msg(self, topic, payload=b"p"):
        from emqx_tpu.broker.message import make
        m = make("pub", 0, topic, payload)
        m.set_flag("retain", True)
        return m

    def test_backend_swap(self):
        from emqx_tpu.apps.retainer import (DiscStorage, RamStorage,
                                            Retainer)
        node = Node(use_device=False)
        for storage in (RamStorage(),):
            ret = Retainer(node, storage=storage)
            ret.on_message_publish(self._msg("r/a"))
            ret.on_message_publish(self._msg("r/b"))
            assert ret.retained_count() == 2
            assert {m.topic for m in ret.match("r/+")} == {"r/a", "r/b"}
            assert ret.storage is storage

    def test_disc_backend_survives_restart(self, tmp_path):
        from emqx_tpu.apps.retainer import DiscStorage, Retainer
        node = Node(use_device=False)
        ret = Retainer(node, conf={"storage": {"type": "disc",
                                               "dir": str(tmp_path)}})
        ret.on_message_publish(self._msg("d/one", b"v1"))
        ret.on_message_publish(self._msg("d/two", b"v2"))
        ret.delete("d/two")
        ret.storage.close()
        # "restart": a fresh backend over the same directory replays
        ret2 = Retainer(node, storage=DiscStorage(str(tmp_path)))
        assert ret2.retained_count() == 1
        [m] = ret2.match("d/#")
        assert (m.topic, m.payload) == ("d/one", b"v1")
        ret2.storage.close()

    def test_disc_journal_compaction(self, tmp_path):
        from emqx_tpu.apps.retainer import DiscStorage
        st = DiscStorage(str(tmp_path))
        for k in range(300):            # churn far past the live count
            st.insert("t/x", self._msg("t/x", b"%d" % k), None)
        assert st._journal_lines <= max(64, 4 * len(st)) + 1
        st.close()
        st2 = DiscStorage(str(tmp_path))
        m, _exp = st2.get("t/x")
        assert m.payload == b"299"
        st2.close()

    def test_storage_config_parsing(self):
        from emqx_tpu.apps.retainer import (DiscStorage, RamStorage,
                                            make_storage)
        assert isinstance(make_storage(None), RamStorage)
        assert isinstance(make_storage("ram"), RamStorage)
        with pytest.raises(ValueError):
            make_storage({"type": "martian"})
