"""Compacted device→host readback (ISSUE 3): CSR payload classes.

The CSR readback must be INVISIBLE except for bytes: a compacted window
produces the identical deliveries and per-message counts as the dense
readback of the same traffic — including overflow/host-fallback lanes,
the payload-class overflow fallback, under-filled fused windows, shared
slots, and the match cache populated from CSR views — and the byte
accounting the exporters carry must reflect the actual transfer.
"""

import numpy as np
import pytest

from emqx_tpu.broker.device_engine import _CsrRes
from emqx_tpu.broker.message import make
from emqx_tpu.broker.node import Node

DENSE_CONF = {"broker": {"compact_readback": False}}


class Sink:
    def __init__(self):
        self.got = []

    def deliver(self, topic_filter, msg):
        self.got.append((topic_filter, msg.topic))
        return True


def mkmsg(topic, payload=b"x"):
    return make("pub", 0, topic, payload)


def _twin_nodes(setup, **engine_over):
    """Two nodes with identical subscription state: `comp` reads back
    CSR (default), `dense` the padded planes — the delivery oracle.
    (Raw-plane comparison is meaningless across the two paths — the CSR
    readback replaces the planes — so the oracle is deliveries+counts,
    with the per-plane CSR decode pinned in TestCsrDecode.)"""
    comp = Node()
    dense = Node(DENSE_CONF)
    assert comp.device_engine.compact_readback
    assert not dense.device_engine.compact_readback
    for k, v in engine_over.items():
        setattr(comp.device_engine, k, v)
        setattr(dense.device_engine, k, v)
    return comp, setup(comp.broker), dense, setup(dense.broker)


def _setup_mixed(broker):
    sinks = [Sink() for _ in range(3)]
    sids = [broker.register(s, f"c{i}") for i, s in enumerate(sinks)]
    broker.subscribe(sids[0], "dev/+/temp", {"qos": 1})
    broker.subscribe(sids[1], "dev/7/temp", {"qos": 0})
    broker.subscribe(sids[2], "exact/topic", {"qos": 2})
    broker.subscribe(sids[0], "$share/g/job/q", {"qos": 0})
    broker.subscribe(sids[1], "$share/g/job/q", {"qos": 0})
    return sinks


def _mixed_msgs():
    return ([mkmsg("dev/7/temp")] * 30 + [mkmsg("job/q")] * 25
            + [mkmsg("exact/topic")] * 10 + [mkmsg("no/match")] * 5)


def _route_csr(node, msgs, *, window=None):
    """prepare/dispatch/materialize and return the handle (caller
    finishes); asserts the COMPACT path actually engaged."""
    eng = node.device_engine
    h = eng.prepare(msgs, gate_cold=False) if window is None \
        else eng.prepare_window(window, gate_cold=False)
    assert h is not None
    eng.dispatch(h)
    eng.materialize(h)
    return h


def _finish_all(node, h):
    out = []
    for k in range(len(h.subs)):
        out.extend(node.device_engine.finish_sub(h, k))
    return out


class TestCompactOracle:
    def test_mixed_batch_identical_three_rounds(self):
        """Shared slots + wildcard + exact + no-match traffic, repeated
        so round 2+ serves from the CSR-populated match cache: counts
        and deliveries equal the dense engine's every round, and the
        round-robin shared distribution threads identically."""
        comp, cs, dense, ds = _twin_nodes(_setup_mixed)
        for rnd in range(3):
            hc = _route_csr(comp, _mixed_msgs())
            hd = _route_csr(dense, _mixed_msgs())
            assert isinstance(hc.np_res, _CsrRes), "compact did not engage"
            assert not isinstance(hd.np_res, _CsrRes)
            assert _finish_all(comp, hc) == _finish_all(dense, hd), rnd
        assert [s.got for s in cs] == [s.got for s in ds]
        assert comp.metrics.val("pipeline.readback.windows.compact") == 3
        assert comp.device_engine.stats()["match_cache"]["hits"] > 0

    def test_trie_backend(self):
        """The trie-NFA fallback backend compacts through
        route_step_compact / route_step_cached_compact, bit-identically."""
        def setup(broker):
            s = Sink()
            sid = broker.register(s, "c")
            for f in ["a", "a/b", "a/+/c", "+/b/#", "x/y/z/w"]:
                broker.subscribe(sid, f, {"qos": 0})
            return [s]

        comp, cs, dense, ds = _twin_nodes(setup, shape_cap=2)
        msgs = [mkmsg("a/b")] * 50 + [mkmsg("x/y/z/w")] * 20
        for rnd in range(2):       # round 2: cached trie plan + compact
            hc = _route_csr(comp, [mkmsg(m.topic) for m in msgs])
            hd = _route_csr(dense, [mkmsg(m.topic) for m in msgs])
            assert comp.device_engine.stats()["backend"] == "trie"
            assert isinstance(hc.np_res, _CsrRes)
            assert _finish_all(comp, hc) == _finish_all(dense, hd), rnd
        assert [s.got for s in cs] == [s.got for s in ds]

    def test_underfilled_window(self):
        """Fused window with an under-filled sub-batch: padding lanes
        contribute zero payload entries and deliveries match."""
        comp, cs, dense, ds = _twin_nodes(_setup_mixed)
        win = [[mkmsg("dev/7/temp"), mkmsg("dev/9/temp")],
               [mkmsg("dev/7/temp")]]
        hc = _route_csr(comp, None, window=[[mkmsg(m.topic) for m in w]
                                            for w in win])
        hd = _route_csr(dense, None, window=[[mkmsg(m.topic) for m in w]
                                             for w in win])
        assert isinstance(hc.np_res, _CsrRes)
        assert _finish_all(comp, hc) == _finish_all(dense, hd)
        assert [s.got for s in cs] == [s.got for s in ds]

    def test_payload_overflow_falls_back_dense(self):
        """A window outgrowing its payload class reads the dense planes
        of the SAME dispatch: deliveries identical, counter fires, and
        the EWMA resizes the next window's class up."""
        comp, cs, dense, ds = _twin_nodes(_setup_mixed)
        eng = comp.device_engine
        real = eng._choose_payload_cap
        eng._choose_payload_cap = lambda Bp: 8   # absurdly small class
        hc = _route_csr(comp, _mixed_msgs())
        assert not isinstance(hc.np_res, _CsrRes), \
            "overflow must fall back to the dense readback"
        assert comp.metrics.val("routing.device.compact_overflow") == 1
        hd = _route_csr(dense, _mixed_msgs())
        assert _finish_all(comp, hc) == _finish_all(dense, hd)
        assert [s.got for s in cs] == [s.got for s in ds]
        # the overflow window's offsets seeded the EWMA: the un-mocked
        # chooser now picks a class that fits
        eng._choose_payload_cap = real
        assert eng._pay_ewma, "overflow fallback must still feed the EWMA"
        hc2 = _route_csr(comp, _mixed_msgs())
        assert isinstance(hc2.np_res, _CsrRes)
        hd2 = _route_csr(dense, _mixed_msgs())
        assert _finish_all(comp, hc2) == _finish_all(dense, hd2)

    def test_fanout_overflow_lanes_host_fallback(self):
        """Per-message capacity overflow (fan-out cap) survives
        compaction: the lane is flagged, host-fallback routes it, and
        counts match the dense engine."""
        def setup(broker):
            sinks = [Sink() for _ in range(8)]
            for i, s in enumerate(sinks):
                broker.subscribe(broker.register(s, f"o{i}"), "big/+",
                                 {"qos": 0})
            return sinks

        comp, cs, dense, ds = _twin_nodes(setup, fanout_cap=4)
        msgs = [mkmsg("big/t")] * 40 + [mkmsg("big/u")] * 30
        hc = _route_csr(comp, [mkmsg(m.topic) for m in msgs])
        hd = _route_csr(dense, [mkmsg(m.topic) for m in msgs])
        assert isinstance(hc.np_res, _CsrRes)
        assert hc.np_res.overflow.any(), "expected overflow lanes"
        assert _finish_all(comp, hc) == _finish_all(dense, hd)
        assert sorted(len(s.got) for s in cs) == \
            sorted(len(s.got) for s in ds)


class TestCsrDecode:
    def test_csr_slices_equal_dense_planes(self):
        """Per-plane decode oracle: every message's CSR slices carry
        exactly the dense planes' valid entries, in order (matches may
        drop interior holes — the shapes backend's slot layout — which
        is the documented hole-insensitivity contract)."""
        from emqx_tpu.ops.compact import csr_slices
        comp, _cs, dense, _ds = _twin_nodes(_setup_mixed)
        hc = _route_csr(comp, _mixed_msgs())
        hd = _route_csr(dense, _mixed_msgs())
        nr = hc.np_res
        assert isinstance(nr, _CsrRes)
        (m_d, r_d, o_d, ss_d, sr_d, so_d, ovf_d, occ_d) = hd.np_res
        np.testing.assert_array_equal(nr.overflow, ovf_d)
        np.testing.assert_array_equal(nr.occur, occ_d)
        W, B = ovf_d.shape
        for w in range(W):
            for i in range(B):
                m, r, o, ss, sr, so = csr_slices(nr.off[w], nr.c3[w],
                                                 nr.pay[w], i)
                md = m_d[w, i]
                np.testing.assert_array_equal(m, md[md >= 0])
                cf = len(r)
                np.testing.assert_array_equal(r, r_d[w, i][:cf])
                np.testing.assert_array_equal(o, o_d[w, i][:cf])
                sd = ss_d[w, i]
                cs_n = int((sd >= 0).sum())
                np.testing.assert_array_equal(ss, sd[sd >= 0])
                np.testing.assert_array_equal(sr, sr_d[w, i][:cs_n])
                np.testing.assert_array_equal(so, so_d[w, i][:cs_n])
        _finish_all(comp, hc)
        _finish_all(dense, hd)


class TestCachePopulationFromCsr:
    def test_rows_equivalent_to_dense_population(self):
        """A cache row built from the CSR view carries the same valid
        filter ids (in order), the same count, and the same overflow
        flag as the dense-populated row for the same topic."""
        comp, _cs, dense, _ds = _twin_nodes(_setup_mixed)
        _finish_all(comp, _route_csr(comp, _mixed_msgs()))
        _finish_all(dense, _route_csr(dense, _mixed_msgs()))
        cc = comp.device_engine._match_cache
        dc = dense.device_engine._match_cache
        assert len(cc) == len(dc) > 0
        with dc._lock:
            dense_rows = dict(dc._rows)
        with cc._lock:
            comp_rows = dict(cc._rows)
        assert set(comp_rows) == set(dense_rows)
        for key, row in comp_rows.items():
            m, c, o = row[:3]
            md, cd, od = dense_rows[key][:3]
            assert m.shape == md.shape      # full match width both ways
            np.testing.assert_array_equal(m[m >= 0], md[md >= 0])
            assert (c, o) == (cd, od)
            # the delta-overlay fields (ISSUE 4) ride the same rows:
            # topic encoding identical on both populate paths
            if len(row) > 3:
                np.testing.assert_array_equal(row[6],
                                              dense_rows[key][6])
                assert row[7:] == dense_rows[key][7:]
        assert comp.metrics.val("match_cache.inserts") > 0


class TestByteAccounting:
    def test_compact_bytes_exact_and_reduced(self):
        """pipeline.readback.bytes.* count the actual transferred host
        arrays, and at fan-out ~1 the compact transfer is >= 4x smaller
        per window (the ISSUE 3 acceptance regime)."""
        comp, _cs, dense, _ds = _twin_nodes(_setup_mixed)
        hc = _route_csr(comp, _mixed_msgs())
        nr = hc.np_res
        assert isinstance(nr, _CsrRes)
        expect = (nr.off.nbytes + nr.c3.nbytes + nr.pay.nbytes
                  + nr.overflow.nbytes + nr.occur.nbytes)
        assert comp.metrics.val("pipeline.readback.bytes.compact") \
            == expect
        _finish_all(comp, hc)

        hd = _route_csr(dense, _mixed_msgs())
        dense_expect = sum(a.nbytes for a in hd.np_res)
        if hd.np_counts is not None:
            dense_expect += hd.np_counts.nbytes
        assert dense.metrics.val("pipeline.readback.bytes.dense") \
            == dense_expect
        _finish_all(dense, hd)
        assert dense_expect >= 4 * expect, \
            f"compaction won only {dense_expect / expect:.1f}x"

    def test_snapshot_readback_section(self):
        """The telemetry snapshot (the schema all four exporters and
        bench.py embed) derives per-window bytes for each path."""
        comp, _cs, _dense, _ds = _twin_nodes(_setup_mixed)
        _finish_all(comp, _route_csr(comp, _mixed_msgs()))
        snap = comp.pipeline_telemetry.snapshot()
        rb = snap["readback"]
        assert rb["windows_compact"] == 1
        assert rb["bytes_per_window_compact"] == rb["bytes_compact"]
        # raw counters ride the shared Metrics registry — what the
        # Prometheus/StatsD exporters emit verbatim
        assert comp.metrics.val("pipeline.readback.bytes.compact") > 0
        from emqx_tpu.apps.prometheus import collect
        text = collect(comp)
        assert "emqx_pipeline_readback_bytes_compact" in text

    def test_disabled_knob(self):
        node = Node(DENSE_CONF)
        b = node.broker
        b.subscribe(b.register(Sink(), "c"), "t/+", {"qos": 0})
        eng = node.device_engine
        assert not eng.compact_readback
        assert eng.route_batch([mkmsg("t/1")] * 70) == [1] * 70
        assert node.metrics.val("pipeline.readback.windows.compact") == 0
        assert node.metrics.val("pipeline.readback.windows.dense") > 0


class TestMeshCompact:
    def test_mesh_compact_identical_and_guarded(self):
        """Mesh readback compaction: deliveries equal the dense mesh,
        and the per-slot staleness guard host-dispatches a pick whose
        member left the group mid-batch instead of delivering to the
        stale session."""
        MC = {"broker": {"multichip": {"enable": True, "devices": 4,
                                       "dp": 2, "max_batch": 16},
                         "device_min_batch": 1}}
        MCD = {"broker": {**MC["broker"], "compact_readback": False}}
        comp, dense = Node(MC), Node(MCD)

        def setup(node):
            b = node.broker
            sinks = [Sink() for _ in range(3)]
            sids = [b.register(s, f"c{i}") for i, s in enumerate(sinks)]
            for i in range(8):
                b.subscribe(sids[i % 3], f"dev/{i}/+", {"qos": 0})
            b.subscribe(sids[0], "$share/g/job/q", {"qos": 0})
            b.subscribe(sids[1], "$share/g/job/q", {"qos": 0})
            return sinks, sids

        cs, c_sids = setup(comp)
        ds, _d_sids = setup(dense)
        msgs = [mkmsg(f"dev/{i % 8}/x") for i in range(10)] \
            + [mkmsg("job/q"), mkmsg("no/match")]
        eng = comp.device_engine
        # pre-warm the payload class so the compact path engages on the
        # first batch (production: the background warm thread does this)
        eng.route_batch([mkmsg(m.topic) for m in msgs], wait=True)
        Bp = eng._batch_class(len(msgs))
        P = eng._choose_pcap(Bp)
        assert P is not None
        eng._compact_warm.add((Bp, P))
        for rnd in range(3):
            c1 = eng.route_batch([mkmsg(m.topic) for m in msgs],
                                 wait=True)
            c2 = dense.device_engine.route_batch(
                [mkmsg(m.topic) for m in msgs], wait=True)
            assert c1 == c2, rnd
        assert comp.metrics.val("pipeline.readback.windows.compact") > 0
        # equalize: run the dense node the extra warm batch the compact
        # node got, then compare distributions by count
        dense.device_engine.route_batch([mkmsg(m.topic) for m in msgs],
                                        wait=True)
        assert sorted(len(s.got) for s in cs) == \
            sorted(len(s.got) for s in ds)

        # staleness guard: single-member group, member leaves AFTER the
        # pick is materialized but before consume — without the guard
        # the stale session (still alive) would receive the delivery
        b = comp.broker
        lone = Sink()
        sid_l = b.register(lone, "lone")
        b.subscribe(sid_l, "$share/s/solo/q", {"qos": 0})
        eng.route_batch([mkmsg("solo/q")] * 4, wait=True)  # warm shard
        n_before = len(lone.got)
        h = eng.prepare([mkmsg("solo/q")] * 4)
        assert h is not None
        eng.dispatch(h)
        eng.materialize(h)
        b.unsubscribe(sid_l, "$share/s/solo/q")   # leaves mid-batch
        counts = eng.finish(h)
        assert len(lone.got) == n_before, \
            "stale pick delivered to a member that left the group"
        assert counts == [0] * 4
